"""dlint core: findings, rule registry, suppressions, baseline, runner.

Stdlib-only and network-free, like the tools/lint.py gate it grew out of —
the hermetic build image has no ruff/flake8. Ruff stays authoritative for
*style* wherever it is installed; dlint owns the repo-specific correctness
contracts (x64 config placement, trace-boundary host syncs, assert vs
raise, lazy-jax schema layers, seeded RNG, axon-guard routing) that no
off-the-shelf linter knows about.

Vocabulary:

- A **rule** is a callable object with a ``code`` (``DLP0xx``) registered in
  ``RULES``; given a :class:`FileContext` it yields :class:`Finding`\\ s.
- A ``# dlint: disable=CODE[,CODE]`` comment on the finding's line
  suppresses it; ``# dlint: disable-file=CODE`` anywhere in the file
  suppresses the code for the whole file.  ``all`` is accepted as a code.
- The **baseline** (``tools/dlint/baseline.json``) grandfathers known
  findings as ``{path, code, count, reason}`` entries so the gate can be
  adopted without fixing the world first.  Non-strict runs fail only on
  findings beyond the baseline; ``--strict`` additionally fails on stale
  entries (count no longer matched) and entries missing a ``reason`` — an
  empty-or-justified baseline is the steady state CI enforces.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

REPO = Path(__file__).resolve().parents[2]
SKIP_DIRS = {".git", "__pycache__", "build", "dist", ".venv", "node_modules"}
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

# Codes are bare identifiers separated by commas; anything after the code
# list (e.g. a prose justification) must NOT be swallowed into the last
# code token, so no \s inside the capture except around commas.
_CODES = r"[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*"
_DISABLE_RE = re.compile(rf"#\s*dlint:\s*disable=({_CODES})")
_DISABLE_FILE_RE = re.compile(rf"#\s*dlint:\s*disable-file=({_CODES})")


@dataclass(frozen=True)
class Finding:
    """One lint finding, printed as ``path:line[:col]: CODE message``.

    ``col``/``end_col`` are 1-based and present only where the AST node
    provided offsets — editor integrations jump to the exact span.
    Baseline matching stays on ``(path, code)`` only, so adding or
    refining columns never invalidates a committed baseline entry.
    """

    path: str  # repo-relative, forward slashes
    line: int
    code: str
    message: str
    col: Optional[int] = None
    end_col: Optional[int] = None

    def render(self) -> str:
        if self.col is not None:
            return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def finding_at(relpath: str, node: ast.AST, code: str, message: str) -> Finding:
    """Finding anchored at ``node``, carrying its column span when the
    node has one (ast gives 0-based ``col_offset``; editors are 1-based)."""
    col = getattr(node, "col_offset", None)
    end = getattr(node, "end_col_offset", None)
    return Finding(
        relpath,
        getattr(node, "lineno", 0),
        code,
        message,
        col=col + 1 if col is not None else None,
        end_col=end + 1 if end is not None else None,
    )


@dataclass
class FileContext:
    """Everything a rule may inspect about one file.

    Built either from disk (the repo walk) or from an in-memory snippet
    (the fixture tests): rules must only read this object, never the
    filesystem, so test fixtures exercise them without touching the repo.
    """

    relpath: str
    src: str
    tree: Optional[ast.AST] = None
    syntax_error: Optional[SyntaxError] = None
    lines: List[str] = field(default_factory=list)
    _file_disabled: Optional[set] = None
    _comments: Optional[Dict[int, str]] = None

    def comments(self) -> Dict[int, str]:
        """{lineno: comment text} from the tokenizer — NOT a line regex, so
        directive-looking text inside string literals (test fixtures, doc
        snippets) can never suppress anything."""
        if self._comments is None:
            out: Dict[int, str] = {}
            try:
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.src).readline
                ):
                    if tok.type == tokenize.COMMENT:
                        out[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass  # unparseable tail; DLP000 reports the file anyway
            self._comments = out
        return self._comments

    @classmethod
    def from_source(cls, relpath: str, src: str) -> "FileContext":
        ctx = cls(relpath=relpath.replace("\\", "/"), src=src)
        ctx.lines = src.splitlines()
        try:
            ctx.tree = ast.parse(src, filename=relpath)
        except SyntaxError as e:
            ctx.syntax_error = e
        return ctx

    @property
    def is_test(self) -> bool:
        parts = self.relpath.split("/")
        return parts[0] == "tests" or parts[-1].startswith("test_")

    @property
    def in_library(self) -> bool:
        return self.relpath.startswith("distilp_tpu/")


class Rule:
    """Base class; subclasses set ``code``/``name``/``rationale`` and
    implement :meth:`check`."""

    code: str = ""
    name: str = ""
    rationale: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register(cls: type) -> type:
    """Class decorator adding one instance of the rule to the registry."""
    rule = cls()
    if not rule.code or rule.code in RULES:
        raise ValueError(f"bad or duplicate rule code: {rule.code!r}")
    RULES[rule.code] = rule
    return cls


# --------------------------------------------------------------------------
# suppressions


def _parse_codes(blob: str) -> set:
    return {c.strip().upper() for c in blob.split(",") if c.strip()}


def file_disabled_codes(ctx: FileContext) -> set:
    # Computed once per file: is_suppressed runs per finding and must not
    # rescan every comment each time.
    if ctx._file_disabled is None:
        codes: set = set()
        for comment in ctx.comments().values():
            m = _DISABLE_FILE_RE.search(comment)
            if m:
                codes |= _parse_codes(m.group(1))
        ctx._file_disabled = codes
    return ctx._file_disabled


def line_disabled_codes(ctx: FileContext, lineno: int) -> set:
    comment = ctx.comments().get(lineno)
    if comment:
        m = _DISABLE_RE.search(comment)
        if m:
            return _parse_codes(m.group(1))
    return set()


def is_suppressed(ctx: FileContext, finding: Finding) -> bool:
    file_codes = file_disabled_codes(ctx)
    if "ALL" in file_codes or finding.code in file_codes:
        return True
    line_codes = line_disabled_codes(ctx, finding.line)
    return "ALL" in line_codes or finding.code in line_codes


# --------------------------------------------------------------------------
# baseline


@dataclass
class BaselineEntry:
    path: str
    code: str
    count: int = 1
    reason: str = ""


@dataclass
class Baseline:
    entries: List[BaselineEntry] = field(default_factory=list)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.is_file():
            return cls()
        data = json.loads(path.read_text())
        entries = [
            BaselineEntry(
                path=e["path"],
                code=e["code"],
                count=int(e.get("count", 1)),
                reason=e.get("reason", ""),
            )
            for e in data.get("entries", [])
        ]
        return cls(entries=entries)

    def dump(self, path: Path) -> None:
        data = {
            "version": 1,
            "entries": [
                {
                    "path": e.path,
                    "code": e.code,
                    "count": e.count,
                    "reason": e.reason or "TODO: justify or fix",
                }
                for e in self.entries
            ],
        }
        path.write_text(json.dumps(data, indent=2) + "\n")

    def partition(
        self, findings: Iterable[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
        """Split findings into (new, grandfathered) and report stale entries.

        An entry absorbs up to ``count`` findings matching its (path, code);
        entries that absorb fewer than ``count`` are stale (the violation
        was fixed but the baseline not trimmed) — strict mode fails on them
        so the baseline only ever shrinks.
        """
        budget: Dict[Tuple[str, str], int] = {}
        for e in self.entries:
            # Duplicate (path, code) entries accumulate, they don't overwrite.
            budget[(e.path, e.code)] = budget.get((e.path, e.code), 0) + e.count
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            key = (f.path, f.code)
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                old.append(f)
            else:
                new.append(f)
        stale = [
            e for e in self.entries if budget.get((e.path, e.code), 0) > 0
        ]
        return new, old, stale

    def unjustified(self) -> List[BaselineEntry]:
        # The --write-baseline placeholder ("TODO: ...") is by definition
        # not a justification; strict mode fails until a human replaces it.
        return [
            e
            for e in self.entries
            if not e.reason.strip() or e.reason.strip().upper().startswith("TODO")
        ]


# --------------------------------------------------------------------------
# runner


def iter_py_files(root: Path = REPO) -> Iterator[Path]:
    for p in sorted(root.rglob("*.py")):
        # Match skip dirs against REPO-RELATIVE parts only: a checkout that
        # happens to live under .../build/... must not skip everything and
        # report a vacuously clean gate.
        try:
            rel_parts = p.relative_to(root).parts
        except ValueError:
            rel_parts = p.parts
        if not any(part in SKIP_DIRS for part in rel_parts):
            yield p


def _sort_key(f: Finding):
    return (f.path, f.line, f.col or 0, f.code)


def lint_context(
    ctx: FileContext, select: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Run (selected) per-file rules over one already-parsed context."""
    findings: List[Finding] = []
    if ctx.syntax_error is not None:
        e = ctx.syntax_error
        findings.append(
            Finding(ctx.relpath, e.lineno or 0, "DLP000", f"syntax error: {e.msg}")
        )
        return findings
    codes = set(select) if select else set(RULES)
    for code in sorted(codes):
        rule = RULES.get(code)
        if rule is None:
            raise KeyError(f"unknown rule code {code!r}")
        findings.extend(rule.check(ctx))
    findings = [f for f in findings if not is_suppressed(ctx, f)]
    findings.sort(key=_sort_key)
    return findings


def lint_source(
    relpath: str,
    src: str,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run (selected) rules over one in-memory file. The fixture-test API."""
    return lint_context(FileContext.from_source(relpath, src), select=select)


def resolve_files(
    paths: Optional[List[Path]] = None, root: Path = REPO
) -> List[Path]:
    files: List[Path] = []
    # `paths=[]` is an explicit empty subset (e.g. --changed with a clean
    # tree) and must NOT fall back to the full walk — only None does.
    if paths is not None:
        for p in paths:
            if p.is_dir():
                files.extend(iter_py_files(p))
            else:
                files.append(p)
    else:
        files = list(iter_py_files(root))
    return files


def _relpath(f: Path, root: Path) -> str:
    try:
        return f.resolve().relative_to(root).as_posix()
    except ValueError:
        # Out-of-tree path (explicit argument or symlink): rules keyed on
        # repo-relative prefixes simply won't match; lint it as-is.
        return f.as_posix()


def build_contexts(
    files: List[Path], root: Path = REPO
) -> Dict[str, FileContext]:
    """Parse each file ONCE into a context keyed by repo-relative path.

    The single shared parse is the whole-program pass's cost contract:
    per-file rules and project rules both read these contexts, so adding
    the project pass must not re-parse the tree a second time.
    """
    out: Dict[str, FileContext] = {}
    for f in files:
        rel = _relpath(f, root)
        if rel not in out:
            out[rel] = FileContext.from_source(rel, f.read_text())
    return out


def lint_files(
    files: List[Path],
    select: Optional[Iterable[str]] = None,
    root: Path = REPO,
) -> List[Finding]:
    findings: List[Finding] = []
    for f in files:
        findings.extend(
            lint_source(_relpath(f, root), f.read_text(), select=select)
        )
    return findings


def lint_paths(
    paths: Optional[List[Path]] = None,
    select: Optional[Iterable[str]] = None,
    root: Path = REPO,
) -> List[Finding]:
    return lint_files(resolve_files(paths, root), select=select, root=root)


@dataclass
class RunResult:
    findings_new: List[Finding]
    findings_baselined: List[Finding]
    stale_entries: List[BaselineEntry]
    unjustified_entries: List[BaselineEntry]
    n_files: int

    def failed(self, strict: bool) -> bool:
        if self.findings_new:
            return True
        if strict and (self.stale_entries or self.unjustified_entries):
            return True
        return False


def _split_select(select: Optional[Iterable[str]]):
    """Partition a --select list into (per-file codes, project codes).

    Imports the project registry lazily: core must stay importable on its
    own (the fixture tests), and project.py imports core.
    """
    from .project import PROJECT_RULES

    if select is None:
        return None, None
    per_file = [c for c in select if c in RULES]
    project = [c for c in select if c in PROJECT_RULES]
    return per_file, project


def run(
    paths: Optional[List[Path]] = None,
    baseline: Optional[Baseline] = None,
    select: Optional[Iterable[str]] = None,
    root: Path = REPO,
    with_project: Optional[bool] = None,
) -> RunResult:
    """The gate: per-file rules over the requested files, plus the
    whole-program pass.

    ``with_project``: None = run the project pass exactly when this is a
    whole-repo run (or when --select names a DLP03x code); True forces it
    (the --changed dev loop: per-file rules on the touched files only,
    the whole-program pass once over everything — cross-file findings
    caused by a local edit surface wherever they land); False skips it.
    Project findings are whole-program facts and are never filtered to
    the path subset.
    """
    from .project import PROJECT_RULES, run_project

    if baseline is None:
        baseline = Baseline()
    select_file, select_project = _split_select(select)
    files = resolve_files(paths, root)
    contexts = build_contexts(files, root=root)
    findings: List[Finding] = []
    for rel in sorted(contexts):
        sel = select_file if select is not None else None
        if select is not None and not sel:
            break  # select named only project codes: no per-file pass
        findings.extend(lint_context(contexts[rel], select=sel))

    run_proj = with_project
    if run_proj is None:
        run_proj = paths is None or bool(select_project)
    if select is not None and not select_project:
        run_proj = False
    if run_proj:
        # The project pass reads the WHOLE library tree; reuse the parses
        # we already have and fill in whatever the path subset left out.
        proj_files = [
            p
            for p in iter_py_files(root)
            if _relpath(p, root).startswith("distilp_tpu/")
        ]
        missing = [
            p for p in proj_files if _relpath(p, root) not in contexts
        ]
        contexts.update(build_contexts(missing, root=root))
        proj_contexts = {
            rel: c
            for rel, c in contexts.items()
            if rel.startswith("distilp_tpu/")
        }
        findings.extend(
            run_project(proj_contexts, select=select_project or None)
        )
    findings.sort(key=_sort_key)

    new, old, stale = baseline.partition(findings)
    if paths is not None or select:
        # Staleness is only meaningful against a whole-repo, all-rules
        # scan: a subset run never sees the findings that keep entries for
        # other files/rules alive, and must not tell the user to trim them.
        stale = []
    return RunResult(
        findings_new=new,
        findings_baselined=old,
        stale_entries=stale,
        unjustified_entries=baseline.unjustified(),
        n_files=len(files) if paths is None else -1,
    )


# --------------------------------------------------------------------------
# shared AST helpers used by several rules


def dotted_name(node: ast.AST) -> str:
    """``jax.lax.while_loop`` -> "jax.lax.while_loop"; "" if not a pure
    Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def module_level_statements(tree: ast.AST) -> Iterator[ast.stmt]:
    """Module-level statements, descending into top-level If/Try blocks
    (``try: import jax`` patterns) but skipping ``if TYPE_CHECKING:`` —
    those imports never execute."""

    def walk(stmts: Iterable[ast.stmt]) -> Iterator[ast.stmt]:
        for s in stmts:
            if isinstance(s, ast.If):
                test = dotted_name(s.test)
                if test in ("TYPE_CHECKING", "typing.TYPE_CHECKING"):
                    continue
                yield from walk(s.body)
                yield from walk(s.orelse)
            elif isinstance(s, ast.Try):
                yield from walk(s.body)
                for h in s.handlers:
                    yield from walk(h.body)
                yield from walk(s.orelse)
                yield from walk(s.finalbody)
            else:
                yield s

    return walk(getattr(tree, "body", []))

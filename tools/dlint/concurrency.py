"""DLP03x: the concurrency rule family, checked whole-program.

These rules consume the :class:`~tools.dlint.project.ProjectContext`
model — symbol tables, the name-resolution call graph, the thread-entry
set and the static lock-acquisition graph — rather than a single file's
tree. They are the machine-checked form of the locking contracts the
gateway/scheduler/obs stack documents with ``# guarded-by:`` comments,
and the static half of the runtime lock sanitizer
(``distilp_tpu/utils/lockwatch.py``): DLP032's acquisition graph is the
reference the sanitizer's *observed* graph is validated against.

| code   | contract                                                    |
|--------|-------------------------------------------------------------|
| DLP030 | guarded-by discipline: annotated state only under its lock  |
| DLP031 | no blocking call (I/O, sleep, device sync) inside a lock    |
| DLP032 | the static lock-acquisition graph is acyclic                |
| DLP033 | asyncio hazards: sync locks / blocking / TLS across await   |
| DLP034 | mutable state must not escape into a thread unguarded       |
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import Finding, finding_at
from .project import (
    ClassInfo,
    FunctionInfo,
    ModuleInfo,
    ProjectContext,
    ProjectRule,
    register_project,
)


def _guard_lock_id(
    pc: ProjectContext, mod: ModuleInfo, ci: Optional[ClassInfo], guard: str
) -> Optional[str]:
    """Resolve annotation text (``self._lock`` or ``_MODULE_LOCK``) to a
    lock node id."""
    if guard.startswith("self.") and ci is not None:
        rec = pc._lookup_attr(ci, guard[len("self."):])
        return rec.lock_id if rec is not None else None
    g = mod.globals.get(guard)
    return g.lock_id if g is not None else None


def _class_functions(
    pc: ProjectContext, ci: ClassInfo
) -> Iterator[FunctionInfo]:
    """All functions whose ``self`` is an instance of ``ci``: methods and
    every closure nested inside them (closures are how this codebase
    ships work to other threads, so they are NOT exempt)."""
    for fn in pc.functions.values():
        if fn.klass is ci:
            yield fn


def _is_dunder_init(fn: FunctionInfo) -> bool:
    # Only __init__'s own body is single-threaded by construction; a
    # closure defined inside __init__ may run anywhere, so it keeps the
    # obligation (fn.parent is not None for closures).
    return fn.node.name == "__init__" and fn.parent is None


@register_project
class GuardedByDiscipline(ProjectRule):
    code = "DLP030"
    name = "guarded-by-discipline"
    rationale = (
        "A `# guarded-by: self._lock` annotation is a contract, not a "
        "comment: every read or write of the annotated attribute outside "
        "a region holding that lock is a data race the moment any thread "
        "entry reaches the method. The rule also SEEDS the annotations: "
        "an attribute written under a lock in one method and bare in "
        "another is flagged so the contract gets written down (or the "
        "bare write gets its guard). __init__ bodies are exempt — no "
        "other thread can hold a reference during construction."
    )

    def check(self, pc: ProjectContext) -> Iterator[Finding]:
        for mod in pc.modules.values():
            yield from self._check_module_globals(pc, mod)
            for ci in mod.classes.values():
                yield from self._check_class(pc, mod, ci)

    def _check_module_globals(self, pc, mod) -> Iterator[Finding]:
        guarded = {
            g.name: _guard_lock_id(pc, mod, None, g.guarded_by)
            for g in mod.globals.values()
            if g.guarded_by and not g.lock_id
        }
        guarded = {k: v for k, v in guarded.items() if v}
        if not guarded:
            return
        for fn in pc.functions.values():
            if fn.modname != mod.modname or fn.analysis is None:
                continue
            seen: Set[Tuple[int, str]] = set()
            entry_held = pc.entry_held.get(fn.qname, ())
            for name, _kind, held, node in fn.analysis.global_names:
                lock = guarded.get(name)
                if lock is None or lock in held or lock in entry_held:
                    continue
                key = (node.lineno, name)
                if key not in seen:
                    seen.add(key)
                    yield finding_at(
                        mod.relpath, node, self.code,
                        f"`{name}` is `# guarded-by:` `{lock}` but accessed "
                        f"without it in `{fn.node.name}`",
                    )

    def _check_class(self, pc, mod, ci) -> Iterator[Finding]:
        guards: Dict[str, str] = {}
        for attr in ci.attrs.values():
            if attr.guarded_by and not attr.lock_id:
                lock = _guard_lock_id(pc, mod, ci, attr.guarded_by)
                if lock:
                    guards[attr.name] = lock
        # Enforcement: annotated attributes, everywhere but __init__.
        writes_by_attr: Dict[str, List[Tuple[FunctionInfo, Tuple[str, ...], ast.AST]]] = {}
        for fn in _class_functions(pc, ci):
            if fn.analysis is None:
                continue
            init = _is_dunder_init(fn)
            seen: Set[Tuple[int, str]] = set()
            entry_held = pc.entry_held.get(fn.qname, ())
            for attr, kind, held, node in fn.analysis.self_attr:
                eff_held = tuple(held) + entry_held
                if kind == "store" and not init:
                    writes_by_attr.setdefault(attr, []).append(
                        (fn, eff_held, node)
                    )
                lock = guards.get(attr)
                if lock is None or init or lock in eff_held:
                    continue
                key = (node.lineno, attr)
                if key not in seen:
                    seen.add(key)
                    yield finding_at(
                        mod.relpath, node, self.code,
                        f"`self.{attr}` is `# guarded-by:` `{lock}` but "
                        f"accessed without it in `{ci.name}.{fn.node.name}`",
                    )
        # Inference seed: written under a lock in one method, bare in
        # another -> the bare write is either a race or a missing
        # annotation; surface it so the contract gets written down.
        for attr, writes in sorted(writes_by_attr.items()):
            if attr in guards or (
                ci.attrs.get(attr) and ci.attrs[attr].lock_id
            ):
                continue
            locked = [(f, h, n) for f, h, n in writes if h]
            bare = [(f, h, n) for f, h, n in writes if not h]
            if not locked or not bare:
                continue
            lock_names = sorted({h[-1] for _, h, _ in locked})
            for fn, _h, node in bare:
                if any(lf.qname != fn.qname for lf, _, _ in locked):
                    yield finding_at(
                        mod.relpath, node, self.code,
                        f"`self.{attr}` is written under `{lock_names[0]}` in "
                        f"another method but bare here — guard the write or "
                        f"annotate the attribute with `# guarded-by:`",
                    )
                    break  # one finding per (attr, function) is enough


@register_project
class BlockingUnderLock(ProjectRule):
    code = "DLP031"
    name = "blocking-under-lock"
    rationale = (
        "A lock held across `time.sleep`, file/socket I/O, a blocking "
        "`queue.get`, or a device sync (`block_until_ready`) convoys "
        "every thread that needs the lock behind the slowest external "
        "wait — the gateway's admission lock serializes ALL fleets, so "
        "one blocking call under it is a cross-tenant stall. Checked "
        "interprocedurally one call level deep: calling a function that "
        "blocks is blocking. `cond.wait()` on the innermost held lock is "
        "exempt (Condition.wait releases it)."
    )

    def check(self, pc: ProjectContext) -> Iterator[Finding]:
        for fn in pc.functions.values():
            a = fn.analysis
            if a is None:
                continue
            seen: Set[int] = set()
            for node, desc, held in a.blocking:
                if held and node.lineno not in seen:
                    seen.add(node.lineno)
                    yield finding_at(
                        fn.relpath, node, self.code,
                        f"{desc} while holding `{held[-1]}`",
                    )
            for call, held in a.calls:
                if not held:
                    continue
                for callee in pc.call_targets.get(id(call), []):
                    if callee == fn.qname:
                        continue
                    blocks = pc.blocks_direct.get(callee)
                    if blocks and call.lineno not in seen:
                        seen.add(call.lineno)
                        short = callee.split(".", 1)[-1]
                        yield finding_at(
                            fn.relpath, call, self.code,
                            f"call to `{short}` while holding "
                            f"`{held[-1]}` — it does {blocks[0][1]} at line "
                            f"{blocks[0][0]}",
                        )
                        break


@register_project
class LockOrderCycles(ProjectRule):
    code = "DLP032"
    name = "lock-order-cycle"
    rationale = (
        "Two threads taking the same pair of locks in opposite orders is "
        "a deadlock waiting for the right interleaving. The static "
        "acquisition graph (lock B acquired — lexically or through a "
        "call — while A is held) must stay acyclic; any strongly "
        "connected component is a potential deadlock, reported with one "
        "witness site per edge. The runtime sanitizer "
        "(DLP_LOCKWATCH=1) validates this same graph against observed "
        "executions."
    )

    def check(self, pc: ProjectContext) -> Iterator[Finding]:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in pc.lock_edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        for scc in _tarjan(adj):
            if len(scc) < 2:
                continue
            yield self._cycle_finding(pc, sorted(scc))
        # Direct re-acquire of a non-reentrant lock: lexically nested
        # acquisition of the same lock identity. (Type-granular, so two
        # distinct instances of one class CAN nest legitimately — the
        # message says so; suppress with a justification where intended.)
        for fn in pc.functions.values():
            a = fn.analysis
            if a is None:
                continue
            for lock, held, node, _via_with in a.acquisitions:
                if lock in held and pc.lock_kinds.get(lock) != "rlock":
                    yield finding_at(
                        fn.relpath, node, self.code,
                        f"`{lock}` acquired while already held — "
                        f"self-deadlock if both are the same instance "
                        f"(use an RLock or restructure)",
                    )

    def _cycle_finding(self, pc: ProjectContext, scc: List[str]) -> Finding:
        # Walk the SCC to present one concrete cycle with witness sites.
        members = set(scc)
        adj = {
            n: {b for (x, b) in pc.lock_edges if x == n and b in members}
            for n in scc
        }
        cycle = _cycle_path(adj, scc[0])
        hops = []
        for a, b in zip(cycle, cycle[1:]):
            sites = pc.lock_edges.get((a, b), [("?", 0, "?")])
            rel, line, how = sites[0]
            hops.append(f"{a} -> {b} ({how} at {rel}:{line})")
        first = pc.lock_edges.get((cycle[0], cycle[1]), [("?", 0, "?")])[0]
        return Finding(
            first[0], first[1], self.code,
            "lock-order cycle: " + "; ".join(hops),
        )


@register_project
class AsyncioHazards(ProjectRule):
    code = "DLP033"
    name = "asyncio-hazards"
    rationale = (
        "Inside `async def`, a synchronous `threading` lock acquire "
        "freezes the whole event loop if contended (no other coroutine "
        "can run to release it), a blocking call stalls every fleet's "
        "traffic at once, and thread-local state read after an `await` "
        "may belong to a different task entirely — the loop migrates "
        "coroutines across its internal machinery, and thread-locals "
        "key on threads, not tasks (use contextvars). The blocking-call "
        "half defers to DLP018 where that per-file rule already covers "
        "the tree (gateway/obs/traffic)."
    )

    # Kept in sync with DLP018._PATH_PREFIXES: one finding per hazard.
    _DLP018_PREFIXES = (
        "distilp_tpu/gateway/",
        "distilp_tpu/obs/",
        "distilp_tpu/traffic/",
    )

    def check(self, pc: ProjectContext) -> Iterator[Finding]:
        for fn in pc.functions.values():
            a = fn.analysis
            if not fn.is_async or a is None:
                continue
            mod = pc.modules[fn.modname]
            for lock, _held, node, _via_with in a.acquisitions:
                yield finding_at(
                    fn.relpath, node, self.code,
                    f"synchronous lock `{lock}` acquired inside "
                    f"`async def {fn.node.name}` — blocks the event loop "
                    f"if contended (take it in an executor, or use "
                    f"asyncio primitives)",
                )
            if not fn.relpath.startswith(self._DLP018_PREFIXES):
                for node, desc, _held in a.blocking:
                    yield finding_at(
                        fn.relpath, node, self.code,
                        f"{desc} inside `async def {fn.node.name}` stalls "
                        f"the event loop — run it in an executor",
                    )
            first_await = min(a.awaits) if a.awaits else None
            if first_await is None:
                continue
            seen: Set[int] = set()
            for name, _kind, _held, node in a.global_names:
                g = mod.globals.get(name)
                if (
                    g is not None
                    and g.thread_local
                    and node.lineno > first_await
                    and node.lineno not in seen
                ):
                    seen.add(node.lineno)
                    yield finding_at(
                        fn.relpath, node, self.code,
                        f"thread-local `{name}` read after `await` in "
                        f"`async def {fn.node.name}` — the value keys on "
                        f"the thread, not the task (use contextvars)",
                    )


@register_project
class ThreadEscape(ProjectRule):
    code = "DLP034"
    name = "thread-escape"
    rationale = (
        "Handing a thread target a mutable container the spawner keeps "
        "using is the unsynchronized-sharing pattern behind PR 8's "
        "cross-thread mis-parenting bug: two threads, one dict, no lock. "
        "Flagged when a spawn site (Thread/Timer/submit/run_in_executor) "
        "passes or closure-captures a mutable local that the spawner "
        "touches again after the spawn with no lock held, or a mutable "
        "module global with no `# guarded-by:` annotation. Hand-off "
        "objects the spawner never touches again are fine — that is the "
        "ownership-transfer idiom the worker queue is built on."
    )

    def check(self, pc: ProjectContext) -> Iterator[Finding]:
        for site in pc.entry_sites:
            if site.kind == "task":
                # asyncio tasks run on the SPAWNER's thread; coroutines
                # interleave only at awaits, so sharing a container with
                # one is not a data race (DLP033 owns the async hazards).
                continue
            fn = site.func
            a = fn.analysis
            if a is None:
                continue
            mod = pc.modules[fn.modname]
            flagged: Set[str] = set()
            # Payload arguments passed by name.
            for expr in site.data_args:
                if not isinstance(expr, ast.Name):
                    continue
                yield from self._check_name(
                    pc, mod, fn, site, expr.id, "passed to", flagged
                )
            # Closure captures of nested-def targets.
            for tq in site.targets:
                nested = pc.functions.get(tq)
                if (
                    nested is None
                    or nested.parent is not fn
                    or nested.analysis is None
                ):
                    continue
                captured = {
                    name
                    for name, _ln, _held in nested.analysis.local_uses
                    if name in a.local_mutables
                }
                for name in sorted(captured):
                    yield from self._check_name(
                        pc, mod, fn, site, name,
                        f"captured by `{nested.node.name}` handed to",
                        flagged,
                    )

    def _check_name(
        self, pc, mod, fn, site, name: str, how: str, flagged: Set[str]
    ) -> Iterator[Finding]:
        if name in flagged:
            return
        a = fn.analysis
        g = mod.globals.get(name)
        if g is not None and g.mutable_literal and not g.guarded_by:
            flagged.add(name)
            yield finding_at(
                mod.relpath, site.call, self.code,
                f"mutable module global `{name}` {how} a {site.kind} "
                f"target with no `# guarded-by:` annotation",
            )
            return
        if name not in a.local_mutables:
            return
        # Shared only if the spawner touches it again, unsynchronized,
        # after the spawn. (Post-spawn uses under a lock are the
        # synchronized-rendezvous idiom and stay quiet.)
        spawn_line = site.call.lineno
        for use_name, lineno, held in a.local_uses:
            if use_name == name and lineno > spawn_line and not held:
                flagged.add(name)
                yield finding_at(
                    mod.relpath, site.call, self.code,
                    f"mutable local `{name}` {how} a {site.kind} target "
                    f"and used again at line {lineno} with no lock held",
                )
                return


# --------------------------------------------------------------------------
# graph helpers


def _tarjan(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components, iterative (no recursion limit)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work: List[Tuple[str, Iterator[str]]] = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)
    return sccs


def _cycle_path(adj: Dict[str, Set[str]], start: str) -> List[str]:
    """A concrete cycle through ``start`` inside one SCC, as
    ``[start, ..., start]``."""
    path = [start]
    seen = {start}
    node = start
    while True:
        nxt = None
        for cand in sorted(adj.get(node, ())):
            if cand == start and len(path) > 1:
                path.append(start)
                return path
            if cand not in seen:
                nxt = cand
                break
        if nxt is None:
            # Dead end inside the SCC walk; fall back to closing directly.
            path.append(start)
            return path
        seen.add(nxt)
        path.append(nxt)
        node = nxt

"""dlint rules: the repo's correctness contracts, mechanically enforced.

Style rules (DLP001/DLP002) port the old tools/lint.py F401/F811 checks.
The JAX-aware rules (DLP010-DLP015) each encode one convention that until
now lived only in a docstring — every rationale below points at where the
contract is documented and why violating it corrupts results rather than
crashing.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from .core import (
    RULES,
    FileContext,
    Finding,
    Rule,
    dotted_name,
    finding_at,
    module_level_statements,
    register,
)

# ---- rule configuration: the repo's contract surface ---------------------

# The only modules allowed to flip jax_enable_x64 (ops/ipm.py:44-51 states
# the contract: set it before jax.numpy is imported, in the module that
# owns the f64 certificate math — both LP engines evaluate the f64
# Lagrangian certificate, so both kernels are sanctioned). Tests are exempt
# from the placement half (they pin their own interpreter-wide config) but
# not the ordering half.
SANCTIONED_X64_MODULES = {
    "distilp_tpu/ops/ipm.py",
    "distilp_tpu/ops/pdhg.py",
    "distilp_tpu/solver/backend_jax.py",
}

# Layers that must be importable without loading jax (pyproject extras
# split: "profile schemas are always importable"; tools/lint.py docstring:
# "jax must not load at schema-import time"). Function-scope imports are
# the idiom there.
LAZY_JAX_PREFIXES = (
    "distilp_tpu/common/",
    "distilp_tpu/profiler/",
    "distilp_tpu/cli/",
    "distilp_tpu/sched/",
    # The twin layer's report schemas must parse without a backend; the
    # engine lazy-imports jax inside its kernel builder.
    "distilp_tpu/twin/",
    # The gateway tier routes, snapshots and serves HTTP without touching
    # a backend itself — only the schedulers its workers build do; a
    # top-level jax import here would drag backend init into every
    # process that merely parses a snapshot or a multi-fleet trace.
    "distilp_tpu/gateway/",
    # The combiner's policy/bucket plumbing is pure stdlib; the flush
    # thread lazy-imports the batch layout at dispatch time, so building
    # (or unit-testing) a BucketPolicy never pays backend init.
    "distilp_tpu/combine/",
    # The observability layer is pure plumbing (spans, exporters, flight
    # rings): `solver spans` must convert a JSONL on a box with no
    # backend at all, and a top-level jax import here would leak into the
    # sched/gateway layers that import obs at module level.
    "distilp_tpu/obs/",
    # The autoscaler decides from a SignalsPayload and actuates through
    # gateway methods — pure policy/stdlib; offline replay (a tier-1
    # pin) must never pay backend init to judge a timeline.
    "distilp_tpu/control/",
    # The traffic engine generates schedules and fires them at the
    # gateway; generating (or byte-checking) a committed open-loop trace
    # must never pay backend init — jax only loads through the
    # schedulers the gateway builds.
    "distilp_tpu/traffic/",
)
LAZY_JAX_MODULES = {
    "distilp_tpu/__init__.py",
    "distilp_tpu/axon_guard.py",
    "distilp_tpu/solver/api.py",
    "distilp_tpu/solver/result.py",
    "distilp_tpu/solver/streaming.py",
    "distilp_tpu/solver/coeffs.py",
    "distilp_tpu/solver/routing.py",
}

# Entry points that may initialize a JAX backend. On this image a
# sitecustomize registers the tunneled-TPU ("axon") PJRT plugin in every
# interpreter, and a dead tunnel wedges ANY backend init forever
# (axon_guard.py docstring) — so every process entry that can touch a
# backend must route through distilp_tpu.axon_guard first.
ENTRY_POINT_PREFIXES = ("distilp_tpu/cli/", "tools/", "examples/")
ENTRY_POINT_FILES = {"bench.py", "__graft_entry__.py"}

# Library modules that dispatch backend work on behalf of plain library
# users (no CLI in between): they must arm the guard themselves, because
# `JAX_PLATFORMS=cpu halda_solve(backend='jax')` wedging on a dead tunnel
# is exactly the trap VERDICT round 5 (finding 2) documented. DLP015
# treats these like entry points.
GUARDED_LIBRARY_FILES = {
    "distilp_tpu/solver/api.py",
    "distilp_tpu/solver/streaming.py",
    "distilp_tpu/twin/api.py",
    # Gateway construction builds Schedulers (backend work) for plain
    # library users with no CLI shim in between.
    "distilp_tpu/gateway/gateway.py",
}

# Modules whose IMPORT eagerly loads jax (top-level `import jax` in the
# module or its package __init__); a lazy layer importing one of these at
# module level defeats its own laziness just as surely as `import jax`.
EAGER_JAX_MODULES = (
    "distilp_tpu.ops",
    "distilp_tpu.parallel",
    "distilp_tpu.solver.backend_jax",
)

# Imports of these layers pull (or can pull) jax backend init into the
# process; schema-only layers (common/, profiler.datatypes, ...) do not.
BACKEND_TOUCHING_PREFIXES = (
    "distilp_tpu.solver",
    "distilp_tpu.ops",
    "distilp_tpu.parallel",
    "distilp_tpu.sched",
    "distilp_tpu.twin",
    "distilp_tpu.gateway",
    "distilp_tpu.traffic",
    "distilp_tpu.utils",
    "distilp_tpu.profiler.device",
    "distilp_tpu.profiler.topology",
)

AXON_GUARD_NAMES = {
    "force_cpu_platform",
    "force_cpu_if_env_requested",
    "axon_guard",
}

HOST_SYNC_BUILTINS = {"float", "int", "bool"}
NUMPY_ALIASES = {"np", "numpy", "onp"}
TRACE_DECORATORS = {"jit", "vmap", "pmap"}
TRACE_BODY_CONSUMERS = {
    "while_loop",
    "scan",
    "fori_loop",
    "cond",
    "switch",
    "map",
    "jit",
    "vmap",
    "pmap",
    "checkpoint",
    "remat",
}


def _import_bindings(node: ast.AST):
    """Yield (local_name, alias_node) bound by an import statement.

    The alias carries the name's own source span (3.10+), so findings can
    point at the exact name inside a multi-name import, not just line 1
    of the statement."""
    if isinstance(node, ast.Import):
        for a in node.names:
            yield (a.asname or a.name.split(".")[0], a)
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name != "*":
                yield (a.asname or a.name, a)


@register
class UnusedImport(Rule):
    code = "DLP001"
    name = "unused-import"
    rationale = (
        "Module-level imports never referenced in the module (ruff F401). "
        "Dead imports in this codebase are not just noise: an accidental "
        "top-level `import jax` in a schema module drags backend init into "
        "every consumer."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        tree = ctx.tree
        used: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
        # Names re-exported via __all__ strings count as used.
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        for elt in ast.walk(node.value):
                            if isinstance(elt, ast.Constant) and isinstance(
                                elt.value, str
                            ):
                                used.add(elt.value)
        for node in tree.body:  # module level only
            for name, alias in _import_bindings(node):
                if name not in used and not name.startswith("_"):
                    yield finding_at(
                        ctx.relpath,
                        alias,
                        self.code,
                        f"`{name}` imported but unused (F401)",
                    )


@register
class ImportRedefinition(Rule):
    code = "DLP002"
    name = "import-redefinition"
    rationale = (
        "A second import rebinding a module-level name on a different line "
        "(ruff F811): the first binding is dead and usually a merge mistake."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        seen: Dict[str, int] = {}
        for node in ctx.tree.body:
            for name, alias in _import_bindings(node):
                if name in seen and seen[name] != alias.lineno:
                    yield finding_at(
                        ctx.relpath,
                        alias,
                        self.code,
                        f"redefinition of unused `{name}` (F811)",
                    )
                seen[name] = alias.lineno


def _module_level_jnp_import_line(tree: ast.AST) -> Optional[int]:
    """Line of the first module-level import that binds jax.numpy."""
    for node in module_level_statements(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy" or a.name.startswith("jax.numpy."):
                    return node.lineno
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax.numpy" or mod.startswith("jax.numpy."):
                return node.lineno
            if mod == "jax" and any(a.name == "numpy" for a in node.names):
                return node.lineno
    return None


@register
class X64ConfigPlacement(Rule):
    code = "DLP010"
    name = "x64-config-placement"
    rationale = (
        'jax.config.update("jax_enable_x64", ...) is only sound in the two '
        "modules that own the f64 certificate math, and only BEFORE "
        "jax.numpy is imported (ops/ipm.py:44-51): set anywhere else it "
        "either has no effect on already-traced programs or silently "
        "changes every other module's dtypes; set after the jnp import it "
        "races dtype canonicalization and bounds lose their f64 precision "
        "without any error."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        jnp_line = _module_level_jnp_import_line(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted_name(node.func)
            if not fn.endswith("config.update"):
                continue
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "jax_enable_x64"
            ):
                continue
            sanctioned = ctx.relpath in SANCTIONED_X64_MODULES
            if not sanctioned and not ctx.is_test:
                yield Finding(
                    ctx.relpath,
                    node.lineno,
                    self.code,
                    "jax_enable_x64 flipped outside the sanctioned modules "
                    f"({', '.join(sorted(SANCTIONED_X64_MODULES))}); the "
                    "x64 contract lives where the f64 certificate math "
                    "lives (see ops/ipm.py:44-51)",
                )
            elif jnp_line is not None and node.lineno > jnp_line:
                yield Finding(
                    ctx.relpath,
                    node.lineno,
                    self.code,
                    "jax_enable_x64 set AFTER jax.numpy was imported at "
                    f"line {jnp_line}; move the config.update above the "
                    "jnp import (ops/ipm.py:44-51)",
                )


def _decorator_is_tracing(dec: ast.AST) -> bool:
    """True for @jax.jit, @jit, @partial(jax.jit, ...), @jax.vmap, ..."""
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        if fn.split(".")[-1] == "partial" and dec.args:
            return _decorator_is_tracing(dec.args[0])
        # @jax.jit(...) / @jit(...) call-form decorators
        return fn.split(".")[-1] in TRACE_DECORATORS
    return dotted_name(dec).split(".")[-1] in TRACE_DECORATORS


class _TracedScopeCollector(ast.NodeVisitor):
    """Collect function nodes whose bodies execute under a JAX trace:
    jit/vmap/pmap-decorated defs, lambdas handed to lax control flow, and
    named functions handed to lax control flow / jit / vmap.

    Name references are resolved lexically: a consumed name only marks
    defs whose enclosing-scope chain is a prefix of the call site's (the
    innermost such def wins), so a host-side helper that merely shares a
    name with a traced function in another scope is not flagged."""

    # Callables sit in the leading positions of every lax/jit signature
    # (fori_loop's body is arg 2, the deepest); later args are data.
    _CALLABLE_POSITIONS = 3

    def __init__(self) -> None:
        # name -> [(def node, enclosing-scope chain of function-node ids)]
        self.defs_by_name: Dict[str, List] = {}
        self.traced: List[ast.AST] = []
        self._consumed: List = []  # (name, call-site scope chain)
        self._scope: List[int] = []

    def _remember_def(self, node) -> None:
        self.defs_by_name.setdefault(node.name, []).append(
            (node, tuple(self._scope))
        )

    def _visit_scope(self, node) -> None:
        self._scope.append(id(node))
        try:
            self.generic_visit(node)
        finally:
            self._scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._remember_def(node)
        if any(_decorator_is_tracing(d) for d in node.decorator_list):
            self.traced.append(node)
        self._visit_scope(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._remember_def(node)
        self._visit_scope(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_scope(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = dotted_name(node.func)
        tail = fn.split(".")[-1]
        is_consumer = tail in TRACE_BODY_CONSUMERS and (
            "lax" in fn or fn.startswith("jax.") or tail in TRACE_DECORATORS
        )
        # jax.tree.map (and friends) run their function eagerly on host —
        # only jax.lax.map traces its body.
        if tail == "map" and "lax" not in fn:
            is_consumer = False
        if is_consumer:
            for pos, arg in enumerate(node.args):
                if pos >= self._CALLABLE_POSITIONS:
                    break
                if isinstance(arg, ast.Lambda):
                    self.traced.append(arg)
                elif isinstance(arg, ast.Name):
                    self._consumed.append((arg.id, tuple(self._scope)))
        self.generic_visit(node)

    def finish(self) -> List[ast.AST]:
        for name, site_chain in self._consumed:
            candidates = [
                (node, chain)
                for node, chain in self.defs_by_name.get(name, [])
                if chain == site_chain[: len(chain)]  # lexically visible
            ]
            if candidates:
                innermost = max(len(c) for _, c in candidates)
                self.traced.extend(
                    n for n, c in candidates if len(c) == innermost
                )
        # Dedup by identity, preserving order.
        seen: Set[int] = set()
        out: List[ast.AST] = []
        for n in self.traced:
            if id(n) not in seen:
                seen.add(id(n))
                out.append(n)
        return out


@register
class HostSyncInTrace(Rule):
    code = "DLP011"
    name = "host-sync-in-trace"
    rationale = (
        "float()/int()/bool()/.item()/np.asarray() on a traced value forces "
        "a device->host sync; on a tunneled TPU each sync pays the full "
        "per-operation wire cost (~1000x a local dispatch, "
        "solver/backend_jax.py docstring), and under jit it throws a "
        "TracerConversionError only on the paths a test happens to trace."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        collector = _TracedScopeCollector()
        collector.visit(ctx.tree)
        # Traced scopes nest (a lambda handed to lax inside a @jit def):
        # dedup so one violation yields one finding, or a count=1 baseline
        # entry could never absorb it.
        emitted = set()
        for scope in collector.finish():
            body = scope.body if isinstance(scope.body, list) else [scope.body]
            for stmt in body:
                for f in self._scan(ctx, stmt):
                    key = (f.line, f.message)
                    if key not in emitted:
                        emitted.add(key)
                        yield f

    def _scan(self, ctx: FileContext, root: ast.AST) -> Iterator[Finding]:
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in HOST_SYNC_BUILTINS:
                if len(node.args) == 1 and not isinstance(
                    node.args[0], ast.Constant
                ):
                    yield Finding(
                        ctx.relpath,
                        node.lineno,
                        self.code,
                        f"`{fn.id}()` inside traced code is a host sync "
                        "(~1000x on a tunneled TPU); keep the value on "
                        "device (jnp ops) or hoist it out of the traced "
                        "scope",
                    )
            elif isinstance(fn, ast.Attribute):
                if fn.attr == "item" and not node.args:
                    yield Finding(
                        ctx.relpath,
                        node.lineno,
                        self.code,
                        "`.item()` inside traced code is a host sync; "
                        "return the array and read it outside the trace",
                    )
                elif (
                    fn.attr in ("asarray", "array")
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in NUMPY_ALIASES
                ):
                    yield Finding(
                        ctx.relpath,
                        node.lineno,
                        self.code,
                        f"`{fn.value.id}.{fn.attr}()` inside traced code "
                        "materializes on host; use jnp.asarray or move the "
                        "conversion outside the traced scope",
                    )


@register
class BareAssertInLibrary(Rule):
    code = "DLP012"
    name = "bare-assert"
    rationale = (
        "`assert` vanishes under `python -O`, so a runtime invariant "
        "guarded by one silently stops being checked in optimized "
        "deployments — in this solver that means a mis-aligned blob decode "
        "corrupts the certificate instead of raising (the class of bug PR 1 "
        "hand-fixed twice). Library invariants raise ValueError/RuntimeError; "
        "tests keep assert."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_library or ctx.is_test:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assert):
                yield Finding(
                    ctx.relpath,
                    node.lineno,
                    self.code,
                    "bare `assert` guards a runtime invariant in library "
                    "code; raise ValueError/RuntimeError so the check "
                    "survives `python -O`",
                )


@register
class EagerJaxImportInSchemaLayer(Rule):
    code = "DLP013"
    name = "eager-jax-import"
    rationale = (
        "Schema/profile/CLI layers must import without loading jax "
        "(pyproject extras split; tools/lint.py docstring): a top-level "
        "`import jax` there makes `import distilp_tpu.common` pull backend "
        "init into processes that only wanted to parse a profile JSON — on "
        "this image that can wedge on the axon plugin."
    )

    @staticmethod
    def _eager_jax(mod: str) -> bool:
        if mod == "jax" or mod.startswith("jax."):
            return True
        # An eager-jax distilp module dragged in at top level defeats the
        # laziness contract the same way a literal `import jax` does.
        return any(
            mod == p or mod.startswith(p + ".") for p in EAGER_JAX_MODULES
        )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        lazy = ctx.relpath in LAZY_JAX_MODULES or any(
            ctx.relpath.startswith(p) for p in LAZY_JAX_PREFIXES
        )
        if not lazy:
            return
        pkg_parts = tuple(ctx.relpath.split("/")[:-1])
        for node in module_level_statements(ctx.tree):
            bad_line = None
            if isinstance(node, ast.Import):
                if any(self._eager_jax(a.name) for a in node.names):
                    bad_line = node.lineno
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + tuple(mod.split("."))) if mod else ".".join(base)
                if self._eager_jax(mod) or any(
                    self._eager_jax(f"{mod}.{a.name}") for a in node.names
                ):
                    bad_line = node.lineno
            if bad_line is not None:
                yield Finding(
                    ctx.relpath,
                    bad_line,
                    self.code,
                    "top-level import loads jax into a lazy "
                    "(schema/profile/cli) module; import it inside the "
                    "function that needs it so the schema layer stays "
                    "importable without a backend",
                )


@register
class LegacyNumpyRandom(Rule):
    code = "DLP014"
    name = "legacy-np-random"
    rationale = (
        "The legacy `np.random.<fn>` API draws from (or mutates) the "
        "process-global RNG: probes and simulators become unreproducible, "
        "and even `np.random.seed(...)` only pins global state that any "
        "import can silently consume. The repo-wide idiom is an explicit "
        "`np.random.default_rng(seed)` generator (utils/synthetic.py, "
        "sched/sim.py, bench.py) — the whole legacy API is banned, not "
        "just the unseeded calls."
    )

    _OK = {"default_rng", "Generator", "SeedSequence", "PCG64", "Philox"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted_name(node.func)
            parts = fn.split(".")
            if (
                len(parts) == 3
                and parts[0] in NUMPY_ALIASES
                and parts[1] == "random"
                and parts[2] not in self._OK
            ):
                yield Finding(
                    ctx.relpath,
                    node.lineno,
                    self.code,
                    f"`{fn}()` uses the process-global legacy RNG; use an "
                    "explicit `np.random.default_rng(seed)` generator for "
                    "reproducible runs",
                )


@register
class FixedScanHeavyOpNeedsGate(Rule):
    code = "DLP016"
    name = "fixed-scan-heavy-op"
    rationale = (
        "A fixed-`length=` lax.scan whose body does per-step heavy linear "
        "algebra — a factorization (cho_factor) like the IPM's, or "
        "matrix-operator applications (`A @ x` / matmul / einsum / "
        "tensordot) like the matrix-free PDHG's — pays that cost for the "
        "WHOLE budget, converged or not: the pay-for-converged-work "
        "pattern the warm-started IPM rewrite removed and ops/pdhg.py was "
        "born without (both kernels spend their budget in chunks under a "
        "while_loop whose exit test is batch-wide convergence). New "
        "kernels in ops//solver/ must either gate the scan the same way "
        "or justify the fixed length with a nearby 'convergence' comment "
        "(or `# dlint: disable=DLP016`). Helper calls are followed one "
        "call-graph fixpoint deep, so hiding the matmul in a local "
        "step-function (the PDHG operator idiom) does not evade the rule."
    )

    _PATH_PREFIXES = ("distilp_tpu/ops/", "distilp_tpu/solver/")
    _GATE_WORD = "convergence"
    # Per-step costs worth gating: factorizations and matrix-operator
    # products. Vector-vector ops spelled jnp.vdot (or plain arithmetic)
    # stay exempt — a scan of cheap steps is not the pattern this rule
    # exists for. Operand ranks are invisible to the AST, so `@`/matmul
    # gates REGARDLESS of rank: a 1-D `w @ x` in a scan body trips it —
    # spell cheap dots as jnp.vdot (the kernel idiom anyway) or gate it.
    _HEAVY_CALLS = {"cho_factor", "matmul", "einsum", "tensordot"}
    # A justification comment counts when it sits on the scan call's line
    # or within this many lines above it (the idiom: a short gate comment
    # directly over the call, see ops/ipm.py's chunk body).
    _COMMENT_WINDOW = 3

    def _direct_heavy(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.MatMult):
                return True
            if isinstance(sub, ast.Call):
                if dotted_name(sub.func).split(".")[-1] in self._HEAVY_CALLS:
                    return True
        return False

    def _called_names(self, node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                out.add(sub.func.id)
        return out

    def _heavy_names(self, defs: Dict[str, List[ast.AST]]) -> Set[str]:
        """Function names whose body is heavy, directly or through calls to
        other named functions (fixpoint over the name-level call graph —
        scan bodies routinely delegate the operator application to a local
        helper, e.g. ops/pdhg.py's ``T``)."""
        heavy = {
            name
            for name, nodes in defs.items()
            if any(self._direct_heavy(d) for d in nodes)
        }
        changed = True
        while changed:
            changed = False
            for name, nodes in defs.items():
                if name in heavy:
                    continue
                calls = set().union(
                    *(self._called_names(d) for d in nodes)
                )
                if calls & heavy:
                    heavy.add(name)
                    changed = True
        return heavy

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not any(ctx.relpath.startswith(p) for p in self._PATH_PREFIXES):
            return
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        heavy_names = self._heavy_names(defs)
        comments = ctx.comments()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted_name(node.func)
            if fn.split(".")[-1] != "scan" or "lax" not in fn:
                continue
            if not any(kw.arg == "length" for kw in node.keywords):
                continue
            body_arg = node.args[0] if node.args else None
            if isinstance(body_arg, ast.Lambda):
                has_heavy = self._direct_heavy(body_arg) or bool(
                    self._called_names(body_arg) & heavy_names
                )
            elif isinstance(body_arg, ast.Name):
                has_heavy = body_arg.id in heavy_names
            else:
                has_heavy = False
            if not has_heavy:
                continue
            gated = any(
                self._GATE_WORD in comments.get(ln, "").lower()
                for ln in range(
                    node.lineno - self._COMMENT_WINDOW, node.lineno + 1
                )
            )
            if gated:
                continue
            yield Finding(
                ctx.relpath,
                node.lineno,
                self.code,
                "fixed-length lax.scan whose body does per-step heavy "
                "linear algebra (cho_factor / matmul / `@`) runs the full "
                "budget even after convergence; bound it with a "
                "convergence-gated while_loop (see ops/ipm.py and "
                "ops/pdhg.py) or justify the fixed length with a nearby "
                "'convergence' comment",
            )


@register
class UnguardedBackendEntryPoint(Rule):
    code = "DLP015"
    name = "unguarded-entry-point"
    rationale = (
        "Every process entry point that can initialize a JAX backend must "
        "route through distilp_tpu.axon_guard first: the sitecustomize on "
        "this image registers the tunneled-TPU PJRT plugin in every "
        "interpreter and a dead tunnel wedges backend init forever — "
        "JAX_PLATFORMS=cpu alone does NOT help (axon_guard.py docstring). "
        "The same applies to the guarded LIBRARY dispatch modules "
        "(GUARDED_LIBRARY_FILES: solver/api.py, solver/streaming.py, "
        "twin/api.py) — plain halda_solve/twin users get no CLI shim to "
        "arm the guard for them (VERDICT round-5 finding 2)."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.is_test:
            return
        is_entry = (
            ctx.relpath in ENTRY_POINT_FILES
            or ctx.relpath in GUARDED_LIBRARY_FILES
            or any(ctx.relpath.startswith(p) for p in ENTRY_POINT_PREFIXES)
        )
        if not is_entry:
            return
        touch_line = self._first_backend_touch(ctx)
        if touch_line is None:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Name, ast.Attribute)):
                tail = (
                    node.id
                    if isinstance(node, ast.Name)
                    else node.attr
                )
                if tail in AXON_GUARD_NAMES:
                    return
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for name, _ in _import_bindings(node):
                    if name in AXON_GUARD_NAMES:
                        return
        yield Finding(
            ctx.relpath,
            touch_line,
            self.code,
            "entry point touches a JAX backend layer without routing "
            "through distilp_tpu.axon_guard "
            "(force_cpu_platform/force_cpu_if_env_requested); a dead TPU "
            "tunnel will wedge this process at backend init",
        )

    @staticmethod
    def _touches_backend(mod: str) -> bool:
        # Prefix match on module boundaries only: distilp_tpu.scheduling
        # must not match the distilp_tpu.sched prefix.
        return any(
            mod == p or mod.startswith(p + ".")
            for p in BACKEND_TOUCHING_PREFIXES
        )

    def _first_backend_touch(self, ctx: FileContext) -> Optional[int]:
        # Package path of this file, for resolving relative imports:
        # distilp_tpu/cli/solver_cli.py -> ("distilp_tpu", "cli").
        pkg_parts = tuple(ctx.relpath.split("/")[:-1])
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        return node.lineno
                    if self._touches_backend(a.name):
                        return node.lineno
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:
                    # `from ..solver import x` with level=2 strips one
                    # trailing package component; level=1 strips none.
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + tuple(mod.split("."))) if mod else ".".join(base)
                if mod == "jax" or mod.startswith("jax."):
                    return node.lineno
                if self._touches_backend(mod):
                    return node.lineno
                # `from distilp_tpu import solver` style: the touched
                # module is named by the alias, not the module field.
                for a in node.names:
                    if self._touches_backend(f"{mod}.{a.name}"):
                        return node.lineno
        return None


@register
class SilentExceptInScheduler(Rule):
    code = "DLP017"
    name = "silent-except-in-sched"
    rationale = (
        "The serving layers are the ones that PROMISE observability "
        "under faults (README degraded-mode semantics: every fault is "
        "counted, health is derived from counters). A `try/except` in "
        "distilp_tpu/sched/ or distilp_tpu/gateway/ that neither "
        "re-raises nor records through the metrics sink swallows exactly "
        "the signal the chaos soak audits — a fault recovers "
        "'successfully' while the counters (and therefore HealthState "
        "and every dashboard) claim nothing happened."
    )

    _PATH_PREFIXES = (
        "distilp_tpu/sched/",
        "distilp_tpu/gateway/",
        # The obs layer makes the same promise one level up: a tracer or
        # flight recorder that silently ate a failure would be the one
        # component whose faults nothing else can observe.
        "distilp_tpu/obs/",
        # The traffic harness AUDITS the shed/coalesce accounting — a
        # swallowed exception there hides exactly the contract breaks it
        # exists to surface.
        "distilp_tpu/traffic/",
        # The combiner serves many shards from one dispatch: a swallowed
        # flush/delivery failure would strand every lane in the batch.
        "distilp_tpu/combine/",
        # The autoscaler RESHAPES the fleet: a swallowed spawn/migrate
        # failure would leave topology and accounting silently split.
        "distilp_tpu/control/",
    )
    # Attribute calls that count as recording through the metrics sink.
    # `_quarantine`/`_quarantine_note` are the scheduler's fault recorders
    # (they increment the quarantine counters and the health state);
    # delegating to either from a handler IS the accounting.
    _SINK_METHODS = {
        "inc", "observe", "record_tick", "_quarantine", "_quarantine_note",
    }

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.is_test or not any(
            ctx.relpath.startswith(p) for p in self._PATH_PREFIXES
        ):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._handler_accounts(node):
                continue
            yield Finding(
                ctx.relpath,
                node.lineno,
                self.code,
                "except handler in sched//gateway/ neither re-raises nor "
                "records through the metrics sink "
                "(.inc/.observe/.record_tick); silent recovery hides "
                "faults from HealthState and the chaos soak's accounting",
            )

    def _handler_accounts(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr in self._SINK_METHODS:
                    return True
        return False


@register
class BlockingCallInAsyncGateway(Rule):
    code = "DLP018"
    name = "blocking-call-in-async"
    rationale = (
        "The gateway's asyncio loop is the single ingest thread for EVERY "
        "fleet's HTTP traffic: one `time.sleep`, synchronous socket "
        "accept/recv, or `subprocess.run` inside an `async def` there "
        "stalls all of them at once — the exact cross-fleet isolation "
        "failure the sharded-worker design exists to rule out. Blocking "
        "work belongs on the shard workers (queue + thread) or behind "
        "`loop.run_in_executor`; the event loop only parses and routes. "
        "Nested synchronous defs inside an async body are exempt — they "
        "are the executor-closure idiom, judged where they run."
    )

    # obs/ has no event loop of its own today, but it is imported BY the
    # gateway's async tier — the same contract applies the day it grows
    # an async exporter. traffic/'s open-loop executor LIVES on the loop:
    # one blocking call in the dispatcher and every fleet's schedule
    # slips together, which would corrupt the very lateness numbers the
    # harness reports.
    _PATH_PREFIXES = (
        "distilp_tpu/gateway/",
        "distilp_tpu/obs/",
        "distilp_tpu/traffic/",
        # The control loop runs beside the gateway's asyncio tier; any
        # future async surface here inherits the same no-blocking rule.
        "distilp_tpu/control/",
    )
    # module -> function names that block the loop outright. Matched
    # through ALIASES too: `import time as t; t.sleep(...)` and
    # `from subprocess import run` block exactly as hard as the literal
    # dotted spellings, so the ban resolves both binding forms.
    _BANNED_FUNCS = {
        "time": {"sleep"},
        "subprocess": {"run", "call", "check_call", "check_output"},
    }
    # Attribute calls that are synchronous socket operations (the asyncio
    # equivalents are loop.sock_accept / StreamReader reads and never
    # spell these bare names).
    _BANNED_ATTRS = {"accept", "recv", "recvfrom", "recv_into"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.is_test or not any(
            ctx.relpath.startswith(p) for p in self._PATH_PREFIXES
        ):
            return
        # Resolve both import forms down to local names:
        #   module_aliases: local module name -> canonical ("t" -> "time")
        #   banned_names:   local bare name -> canonical dotted call
        module_aliases: Dict[str, str] = {}
        banned_names: Dict[str, str] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in self._BANNED_FUNCS:
                        module_aliases[a.asname or a.name] = a.name
            elif isinstance(node, ast.ImportFrom):
                funcs = self._BANNED_FUNCS.get(node.module or "")
                if funcs:
                    for a in node.names:
                        if a.name in funcs:
                            banned_names[a.asname or a.name] = (
                                f"{node.module}.{a.name}"
                            )
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                yield from self._scan_async_body(
                    ctx, node, module_aliases, banned_names
                )

    def _scan_async_body(
        self, ctx, func, module_aliases, banned_names
    ) -> Iterator[Finding]:
        stack: List[ast.AST] = list(func.body)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                # Nested scopes run elsewhere (executor closures, worker
                # callbacks); nested async defs get their own walk.
                continue
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                reason = None
                head, _, tail = fn.partition(".")
                module = module_aliases.get(head, head)
                if (
                    tail
                    and "." not in tail
                    and tail in self._BANNED_FUNCS.get(module, ())
                ):
                    reason = f"`{module}.{tail}()`"
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in banned_names
                ):
                    reason = (
                        f"`{node.func.id}()` ({banned_names[node.func.id]})"
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._BANNED_ATTRS
                ):
                    reason = f"synchronous socket `.{node.func.attr}()`"
                if reason is not None:
                    yield Finding(
                        ctx.relpath,
                        node.lineno,
                        self.code,
                        f"{reason} inside `async def {func.name}` blocks "
                        "the gateway event loop for every fleet; use "
                        "await asyncio.sleep / the shard-worker queue / "
                        "loop.run_in_executor",
                    )
            stack.extend(ast.iter_child_nodes(node))


@register
class UnregisteredJitEntryPoint(Rule):
    code = "DLP020"
    name = "unregistered-jit"
    rationale = (
        "Every `jax.jit` call site in the solver/serving layers must be "
        "MODULE-LEVEL and registered with the compile ledger's entry-point "
        "registry (`X = instrument(\"name\", jax.jit(impl, "
        "static_argnames=S), S)` — obs/compile_ledger.py): an inline jit "
        "inside a function or loop body mints a fresh executable per call "
        "— the exact recompile storm the ledger exists to catch — and an "
        "unregistered one compiles as '(unregistered)', invisible to the "
        "per-entry-point attribution, the cause taxonomy and the "
        "zero-recompile warm-serving gate. The one sanctioned "
        "function-scope shape is a lazily-built module-global kernel "
        "cache (twin/engine.py builds under a lock because jax must not "
        "import at module scope there), which carries a justified "
        "`# dlint: disable=DLP020`."
    )

    _PATH_PREFIXES = (
        "distilp_tpu/sched/",
        "distilp_tpu/gateway/",
        "distilp_tpu/solver/",
        "distilp_tpu/ops/",
        "distilp_tpu/twin/",
        "distilp_tpu/combine/",
        "distilp_tpu/control/",
    )

    @staticmethod
    def _is_jit_name(node: ast.AST) -> bool:
        """A Name/Attribute that denotes jax.jit (jit / jax.jit)."""
        fn = dotted_name(node)
        tail = fn.split(".")[-1]
        return tail == "jit" and (fn == "jit" or "jax" in fn)

    def _jit_call(self, node: ast.Call) -> bool:
        """True for `jax.jit(...)` and `partial(jax.jit, ...)` calls."""
        if self._is_jit_name(node.func):
            return True
        fn = dotted_name(node.func)
        if fn.split(".")[-1] == "partial" and node.args:
            return self._is_jit_name(node.args[0])
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.is_test or not any(
            ctx.relpath.startswith(p) for p in self._PATH_PREFIXES
        ):
            return
        # The sanctioned registration form: instrument("name", <jit>, ...)
        # — collect the node ids sitting in the wrapped-callable position.
        registered_ids: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and dotted_name(node.func).split(".")[-1] == "instrument"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                registered_ids.add(id(node.args[1]))
        # Walk with scope context: (inside a def?, inside a loop body?).
        # Decorator Calls are flagged at their def and skipped by the
        # general walk (one violation, one finding — a count=1 baseline
        # entry must be able to absorb it).
        yield from self._walk(
            ctx, ctx.tree, registered_ids, set(), False, False
        )

    def _walk(
        self, ctx, node, registered_ids, flagged, in_func, in_loop
    ) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_in_func = in_func or isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
            child_in_loop = in_loop or isinstance(
                child, (ast.For, ast.AsyncFor, ast.While)
            )
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                for dec in child.decorator_list:
                    if (
                        isinstance(dec, ast.Call) and self._jit_call(dec)
                    ) or (
                        not isinstance(dec, ast.Call)
                        and self._is_jit_name(dec)
                    ):
                        flagged.add(id(dec))
                        yield Finding(
                            ctx.relpath,
                            dec.lineno,
                            self.code,
                            "jit-decorated def cannot register with the "
                            "compile ledger; use the module-level "
                            '`X = instrument("layer.name", jax.jit(impl, '
                            "static_argnames=S), S)` idiom "
                            "(obs/compile_ledger.py) so its compiles are "
                            "attributed",
                        )
            if (
                isinstance(child, ast.Call)
                and id(child) not in flagged
                and self._jit_call(child)
            ):
                if in_loop:
                    yield Finding(
                        ctx.relpath,
                        child.lineno,
                        self.code,
                        "jax.jit inside a loop body mints a fresh "
                        "executable per iteration — the recompile storm "
                        "the compile ledger exists to catch; hoist it to "
                        "module level and register it with instrument()",
                    )
                elif in_func:
                    yield Finding(
                        ctx.relpath,
                        child.lineno,
                        self.code,
                        "jax.jit inside a function body mints a fresh "
                        "executable per call; hoist it to module level "
                        "and register it with instrument() — the one "
                        "sanctioned exception is a lazily-built "
                        "module-global kernel cache, which carries a "
                        "justified `# dlint: disable=DLP020` "
                        "(twin/engine.py)",
                    )
                elif id(child) not in registered_ids:
                    yield Finding(
                        ctx.relpath,
                        child.lineno,
                        self.code,
                        "module-level jax.jit not registered with the "
                        "compile ledger's entry-point registry; wrap it: "
                        '`X = instrument("layer.name", jax.jit(impl, '
                        "static_argnames=S), S)` (obs/compile_ledger.py) "
                        "so its compiles are attributed instead of "
                        "landing in '(unregistered)'",
                    )
            yield from self._walk(
                ctx, child, registered_ids, flagged, child_in_func,
                child_in_loop,
            )


@register
class UnregisteredMetricName(Rule):
    code = "DLP019"
    name = "unregistered-metric-name"
    rationale = (
        "sched.metrics.METRIC_REGISTRY is the ONE enumeration of every "
        "counter the serving layers emit: the Prometheus exposition takes "
        "its `# HELP` lines from it and dashboards enumerate from it. A "
        "string-literal `metrics.inc(\"...\")` in sched//gateway//obs/ "
        "whose name is not an exact registry entry is a counter that "
        "ships without help text — it renders as an unregistered sample, "
        "and the dashboards drift from the code silently. Dynamically "
        "composed names (f-strings over event kinds / tick modes / fault "
        "kinds / worker ids) are covered by METRIC_FAMILIES prefixes "
        "instead and are not checked here."
    )

    _PATH_PREFIXES = (
        "distilp_tpu/sched/",
        "distilp_tpu/gateway/",
        "distilp_tpu/obs/",
        "distilp_tpu/traffic/",
        "distilp_tpu/combine/",
        "distilp_tpu/control/",
    )

    _registry_cache: Optional[Dict[str, str]] = None

    @classmethod
    def _registry(cls) -> Dict[str, str]:
        # The registry lives in the metrics module so there is exactly one
        # copy. It is a PURE dict literal, so dlint lifts it out of the
        # AST with literal_eval instead of executing the module — no
        # import chain to drag in (the package __init__ pulls numpy), no
        # constraint that metrics.py stay free of relative imports, and a
        # broken edit elsewhere in the package cannot take the linter
        # down with it.
        if cls._registry_cache is None:
            from .core import REPO

            path = REPO / "distilp_tpu" / "sched" / "metrics.py"
            tree = ast.parse(path.read_text())
            for node in tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == "METRIC_REGISTRY"
                        for t in node.targets
                    )
                ):
                    cls._registry_cache = ast.literal_eval(node.value)
                    break
            else:
                raise RuntimeError(
                    "sched/metrics.py has no module-level METRIC_REGISTRY "
                    "literal; DLP019 cannot run"
                )
        return cls._registry_cache

    @staticmethod
    def _literal_names(arg: ast.AST) -> List[str]:
        """The candidate metric names a literal-ish first argument names:
        a plain string constant, or a conditional expression over string
        constants (the `"pool_hit" if hit else "pool_miss"` idiom — both
        branches must be registered)."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return [arg.value]
        if isinstance(arg, ast.IfExp):
            out: List[str] = []
            for branch in (arg.body, arg.orelse):
                if isinstance(branch, ast.Constant) and isinstance(
                    branch.value, str
                ):
                    out.append(branch.value)
            return out
        return []

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.is_test or not any(
            ctx.relpath.startswith(p) for p in self._PATH_PREFIXES
        ):
            return
        registry = None
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "inc"
            ):
                continue
            for name in self._literal_names(node.args[0]):
                if registry is None:
                    registry = self._registry()
                if name not in registry:
                    yield Finding(
                        ctx.relpath,
                        node.lineno,
                        self.code,
                        f"counter {name!r} is not in "
                        "sched.metrics.METRIC_REGISTRY; register it (with "
                        "help text) so the Prometheus exposition and "
                        "dashboards cannot drift from the code",
                    )


# --------------------------------------------------------------------------
# DLP021 — shard_map mesh-body hazards


class _MeshBodyCollector(_TracedScopeCollector):
    """Collect function nodes whose bodies run inside a shard_map mesh
    region: lambdas and named defs in the callable position of a
    ``shard_map(...)`` call under any spelling — ``jax.shard_map``,
    ``jax.experimental.shard_map.shard_map``, or the
    ``utils.shardcompat`` shim the kernels actually use. Inherits the
    traced-scope collector's lexical name resolution; decorators are
    ignored here — only being handed to shard_map marks a body."""

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._remember_def(node)
        self._visit_scope(node)

    def visit_Call(self, node: ast.Call) -> None:
        if (
            dotted_name(node.func).split(".")[-1] == "shard_map"
            and node.args
        ):
            body = node.args[0]
            if isinstance(body, ast.Lambda):
                self.traced.append(body)
            elif isinstance(body, ast.Name):
                self._consumed.append((body.id, tuple(self._scope)))
        self.generic_visit(node)


# Array constructors whose leading argument is an explicit shape, and
# broadcast ops whose second argument is one: a literal rank-3 shape in
# either position inside a mesh body is the full (B, m, n) operator.
MESH_DENSE_CONSTRUCTORS = {"zeros", "ones", "full", "empty"}
MESH_DENSE_BROADCASTERS = {"broadcast_to", "tile"}
# Per-element outer products: under the body's vmap these materialize the
# dense (m, n) operator per batch element — (B, m, n) in aggregate.
MESH_DENSE_OUTER = {"outer", "kron"}
ARRAY_NAMESPACES = NUMPY_ALIASES | {"jnp", "jax.numpy"}


@register
class MeshBodyHazard(Rule):
    code = "DLP021"
    name = "mesh-body-hazard"
    rationale = (
        "A shard_map body (ops/meshlp.py) exists to keep PER-SHARD state "
        "per-shard: each device holds a (B, m/shards, n) row block of A "
        "and meets the others only at psum/pmax/all_gather points. Two "
        "hazards silently void that contract from inside the body. "
        "(1) Host syncs — DLP011's call set — stall EVERY shard: the "
        "mesh program is SPMD, so one device pausing at a host round-trip "
        "parks all of them at the next collective. (2) Materializing a "
        "full (B, m, n) dense A inside the body recreates on every shard "
        "the exact allocation row-sharding exists to avoid — the "
        "fleet-scale memory model (ops/memmodel.py) prices per-shard "
        "blocks, so the predicted-vs-measured ledger band breaks and the "
        "M~10^4 fleet solves the sharding was built for OOM again. "
        "Scoped to ops//solver/, where the mesh kernels live."
    )

    _PATH_PREFIXES = ("distilp_tpu/ops/", "distilp_tpu/solver/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.is_test or not any(
            ctx.relpath.startswith(p) for p in self._PATH_PREFIXES
        ):
            return
        collector = _MeshBodyCollector()
        collector.visit(ctx.tree)
        emitted = set()
        for scope in collector.finish():
            body = scope.body if isinstance(scope.body, list) else [scope.body]
            for stmt in body:
                for f in self._scan(ctx, stmt):
                    key = (f.line, f.message)
                    if key not in emitted:
                        emitted.add(key)
                        yield f

    @staticmethod
    def _is_rank3_literal(arg: Optional[ast.AST]) -> bool:
        return (
            isinstance(arg, (ast.Tuple, ast.List)) and len(arg.elts) >= 3
        )

    def _shape_arg(self, node: ast.Call, tail: str) -> Optional[ast.AST]:
        """The shape-like argument of a constructor/broadcast call."""
        if tail in MESH_DENSE_CONSTRUCTORS:
            pos, kw_names = 0, ("shape",)
        else:  # broadcasters: broadcast_to(x, shape) / tile(x, reps)
            pos, kw_names = 1, ("shape", "reps")
        if len(node.args) > pos:
            return node.args[pos]
        for kw in node.keywords:
            if kw.arg in kw_names:
                return kw.value
        return None

    def _scan(self, ctx: FileContext, root: ast.AST) -> Iterator[Finding]:
        # Host syncs: the exact DLP011 call set (float/int/bool on a
        # traced value, .item(), np.asarray/np.array), re-tagged with the
        # mesh consequence — in SPMD code the sync stalls all shards.
        for f in RULES["DLP011"]._scan(ctx, root):
            yield Finding(
                ctx.relpath,
                f.line,
                self.code,
                f.message.split(";")[0].split(" (")[0]
                + "; inside a shard_map mesh body the sync stalls every "
                "shard at the next collective — return the value and "
                "read it outside the mesh",
            )
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted_name(node.func)
            head, _, tail = fn.rpartition(".")
            if head not in ARRAY_NAMESPACES:
                continue
            if tail in MESH_DENSE_CONSTRUCTORS | MESH_DENSE_BROADCASTERS:
                if self._is_rank3_literal(self._shape_arg(node, tail)):
                    yield Finding(
                        ctx.relpath,
                        node.lineno,
                        self.code,
                        f"`{fn}` with a rank-3 shape inside a shard_map "
                        "mesh body materializes the full (B, m, n) dense "
                        "operator on every shard — the allocation "
                        "row-sharding exists to avoid; build the "
                        "(B, m/shards, n) block outside and pass it "
                        "through in_specs (ops/meshlp.py)",
                    )
            elif tail in MESH_DENSE_OUTER:
                yield Finding(
                    ctx.relpath,
                    node.lineno,
                    self.code,
                    f"`{fn}` inside a shard_map mesh body builds the "
                    "dense operator per element — (B, m, n) in aggregate "
                    "under the body's vmap; keep A as the row-sharded "
                    "block passed through in_specs (ops/meshlp.py)",
                )

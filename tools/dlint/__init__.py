"""dlint: the repo's JAX-aware static-analysis gate.

Run as ``python -m tools.dlint`` from the repo root (what ``make lint``
does). Importing :mod:`tools.dlint.rules` populates the registry as a side
effect, so pulling anything from this package is enough to have every rule
available.
"""

from .core import (
    Baseline,
    BaselineEntry,
    DEFAULT_BASELINE,
    FileContext,
    Finding,
    REPO,
    RULES,
    Rule,
    lint_paths,
    lint_source,
    run,
)
from . import rules  # registers the rules (and is re-exported via __all__)

__all__ = [
    "Baseline",
    "BaselineEntry",
    "DEFAULT_BASELINE",
    "FileContext",
    "Finding",
    "REPO",
    "RULES",
    "Rule",
    "lint_paths",
    "lint_source",
    "run",
    "rules",
]

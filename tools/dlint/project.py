"""dlint project pass: whole-program facts for the DLP03x family.

The per-file rules in ``rules.py`` see one tree at a time; none of them
can see a lock acquired in ``gateway.py`` protecting state mutated from a
worker thread spawned in ``worker.py``. This module builds the shared
whole-program model the concurrency rules consume, from the SAME
``FileContext`` parses the per-file pass already paid for (the single
parse is the cost contract: the project pass must not double dlint's wall
time).

What gets built, in order:

1. **Symbol tables** — one :class:`ModuleInfo` per ``distilp_tpu/``
   module: imports resolved to dotted targets (relative forms included),
   top-level functions, classes with their methods AND every nested
   ``def`` (closures are how work crosses threads here), module globals.
2. **Attribute tables** — per class, every ``self.X = ...`` assignment:
   whether it creates a lock (``threading.Lock/RLock/Condition`` or the
   runtime sanitizer's ``make_lock``), its ``# guarded-by:`` annotation,
   whether the value is a mutable container literal, and the attribute's
   class type when it is statically evident (``self.x = ClassName(...)``
   or an annotated constructor parameter).
3. **A name-resolution call graph** — calls resolved lexically through
   imports, ``self``, annotated parameters, locally-constructed types and
   captured enclosing-scope names; when the receiver's type is unknown,
   a method name defined by exactly ONE project class still resolves
   (the conservative duck-typing fallback — ambiguous names resolve to
   every candidate so the static graph over- rather than
   under-approximates what runtime lock tracking can observe).
4. **The thread-entry set** — targets of ``threading.Thread``/``Timer``,
   anything function-valued handed to a ``.submit(...)`` or
   ``run_in_executor``, ``run`` methods of ``threading.Thread``
   subclasses, and every ``async def`` (the event loop is its own
   execution context, concurrent with every worker).
5. **The static lock-acquisition graph** — nodes are lock identities
   (``make_lock``'s literal name when present, else
   ``module.Class.attr``), edges are "B acquired while A held", found
   both lexically (nested ``with``) and interprocedurally (a call under
   a held lock contributes every lock the callee may transitively
   acquire). ``lock_graph()`` exports it; ``--check-lockwatch``
   cross-validates the runtime sanitizer's observed graph against it.

Lock identity is TYPE-granular (every instance of ``LatencyHist`` shares
one ``metrics.hist`` node): standard for lock-order analysis, and exactly
the granularity the runtime sanitizer records, so the two graphs compare
edge for edge.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import FileContext, Finding, is_suppressed

# ``# guarded-by: self._lock`` / ``# guarded-by: _MODULE_LOCK`` — the
# annotation grammar. Anything after the expression (prose) is ignored.
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")

_LOCK_FACTORIES = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# Receiver-method names too generic for the unique-class fallback: a
# ``.get()`` must never resolve to some project class's ``get`` just
# because only one class defines it — dicts and queues spell it too.
_FALLBACK_DENYLIST = {
    "get", "put", "pop", "append", "add", "update", "items", "keys",
    "values", "join", "wait", "notify", "notify_all", "acquire",
    "release", "set", "clear", "copy", "read", "write", "open",
}
_FALLBACK_MAX_CANDIDATES = 4

_MUTABLE_LITERALS = (
    ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp,
)

# Method calls that mutate their receiver in place — classified as stores
# for guarded-by inference (``self.X.append(v)`` races like ``self.X = v``).
_MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "appendleft",
}


def modname_of(relpath: str) -> str:
    return relpath[:-3].replace("/", ".") if relpath.endswith(".py") else relpath.replace("/", ".")


def _short_mod(modname: str) -> str:
    return modname[len("distilp_tpu."):] if modname.startswith("distilp_tpu.") else modname


@dataclass
class AttrRecord:
    """One ``self.X`` attribute of a class, as the analyzer sees it."""

    name: str
    lineno: int = 0
    lock_id: Optional[str] = None      # set when the attr IS a lock
    lock_kind: Optional[str] = None    # lock | rlock | condition
    guarded_by: Optional[str] = None   # annotation text, e.g. "self._lock"
    mutable_literal: bool = False
    type_qname: Optional[str] = None   # resolved class qname of the value


@dataclass
class GlobalRecord:
    """One module-level binding: lock / mutable / threading.local."""

    name: str
    lineno: int = 0
    lock_id: Optional[str] = None
    lock_kind: Optional[str] = None
    guarded_by: Optional[str] = None
    mutable_literal: bool = False
    thread_local: bool = False


@dataclass
class FunctionInfo:
    qname: str
    modname: str
    relpath: str
    node: ast.AST
    is_async: bool = False
    klass: Optional["ClassInfo"] = None
    parent: Optional["FunctionInfo"] = None  # enclosing function, if nested
    nested: Dict[str, "FunctionInfo"] = field(default_factory=dict)
    local_types: Dict[str, str] = field(default_factory=dict)
    analysis: Optional["FuncAnalysis"] = None


@dataclass
class ClassInfo:
    qname: str
    name: str
    modname: str
    relpath: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)  # resolved dotted names
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    attrs: Dict[str, AttrRecord] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    relpath: str
    modname: str
    ctx: FileContext
    imports: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    globals: Dict[str, GlobalRecord] = field(default_factory=dict)


@dataclass
class EntrySite:
    """One place a callable is handed to another execution context."""

    call: ast.Call
    func: FunctionInfo            # the function containing the site
    targets: List[str]            # resolved entry qnames
    target_exprs: List[ast.AST]   # the function-valued argument exprs
    data_args: List[ast.AST]      # non-callable payload argument exprs
    kind: str                     # thread | submit | executor | timer | task


@dataclass
class FuncAnalysis:
    """Everything the concurrency rules need about one function body,
    computed in ONE walk: lock acquisitions, calls, attribute/name
    accesses and direct blocking calls, each with the lexically-held
    lock stack at that point."""

    acquisitions: List[Tuple[str, Tuple[str, ...], ast.AST, bool]] = field(
        default_factory=list
    )  # (lock_id, held-before, node, via_with)
    calls: List[Tuple[ast.Call, Tuple[str, ...]]] = field(default_factory=list)
    self_attr: List[Tuple[str, str, Tuple[str, ...], ast.AST]] = field(
        default_factory=list
    )  # (attr, "load"|"store", held, node)
    global_names: List[Tuple[str, str, Tuple[str, ...], ast.AST]] = field(
        default_factory=list
    )
    local_stores: Dict[str, ast.AST] = field(default_factory=dict)
    local_mutables: Dict[str, int] = field(default_factory=dict)  # name -> assign line
    local_uses: List[Tuple[str, int, Tuple[str, ...]]] = field(
        default_factory=list
    )  # (name, lineno, held)
    blocking: List[Tuple[ast.AST, str, Tuple[str, ...]]] = field(
        default_factory=list
    )
    awaits: List[int] = field(default_factory=list)
    direct_locks: Set[str] = field(default_factory=set)


class ProjectContext:
    """The whole-program model. Build once per run with :meth:`build`."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_relpath: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.class_by_name: Dict[str, List[ClassInfo]] = {}
        self.methods_by_name: Dict[str, List[FunctionInfo]] = {}
        self.callees: Dict[str, Set[str]] = {}
        self.call_targets: Dict[int, List[str]] = {}  # id(Call) -> qnames
        self.entry_sites: List[EntrySite] = []
        self.thread_entries: Set[str] = set()
        self.thread_reachable: Set[str] = set()
        self.acquires_star: Dict[str, Set[str]] = {}
        self.blocks_direct: Dict[str, List[Tuple[int, str]]] = {}
        # (a, b) -> [(relpath, line, description)]
        self.lock_edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        self.lock_kinds: Dict[str, str] = {}
        self.lock_sites: Dict[str, Tuple[str, int]] = {}
        self.entry_held: Dict[str, Tuple[str, ...]] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, contexts: Dict[str, FileContext]) -> "ProjectContext":
        pc = cls()
        for relpath in sorted(contexts):
            ctx = contexts[relpath]
            if ctx.syntax_error is not None:
                continue  # DLP000 already reported it
            pc._collect_module(ctx)
        pc._index()
        for mod in pc.modules.values():
            for fn in _iter_functions(mod):
                pc._resolve_function(mod, fn)
        pc._find_entries()
        pc._fixpoint_acquires()
        pc._build_lock_graph()
        pc._entry_held_pass()
        pc._reach()
        return pc

    # -- pass 1: symbols ---------------------------------------------------

    def _collect_module(self, ctx: FileContext) -> None:
        modname = modname_of(ctx.relpath)
        mod = ModuleInfo(relpath=ctx.relpath, modname=modname, ctx=ctx)
        pkg_parts = tuple(ctx.relpath.split("/")[:-1])
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    head = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    base = ".".join(head + tuple(base.split("."))) if base else ".".join(head)
                for a in node.names:
                    if a.name != "*":
                        mod.imports[a.asname or a.name] = f"{base}.{a.name}"
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self._make_function(mod, stmt, None, None, f"{modname}.{stmt.name}")
                mod.functions[stmt.name] = fn
            elif isinstance(stmt, ast.ClassDef):
                self._collect_class(mod, stmt)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._collect_global(mod, stmt)
        self.modules[modname] = mod
        self.by_relpath[ctx.relpath] = mod

    def _make_function(
        self,
        mod: ModuleInfo,
        node,
        klass: Optional[ClassInfo],
        parent: Optional[FunctionInfo],
        qname: str,
    ) -> FunctionInfo:
        fn = FunctionInfo(
            qname=qname,
            modname=mod.modname,
            relpath=mod.relpath,
            node=node,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            klass=klass,
            parent=parent,
        )
        self.functions[qname] = fn
        # Register nested defs (closures are the repo's unit of
        # cross-thread work), one level of qname per nesting.
        for sub in ast.walk(node):
            if sub is node:
                continue
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                owner = _innermost_owner(node, sub)
                if owner is node and sub.name not in fn.nested:
                    fn.nested[sub.name] = self._make_function(
                        mod, sub, klass, fn, f"{qname}.<locals>.{sub.name}"
                    )
        return fn

    def _collect_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        qname = f"{mod.modname}.{node.name}"
        ci = ClassInfo(
            qname=qname,
            name=node.name,
            modname=mod.modname,
            relpath=mod.relpath,
            node=node,
        )
        for b in node.bases:
            dotted = _dotted(b)
            if dotted:
                ci.bases.append(_resolve_dotted(mod, dotted))
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[stmt.name] = self._make_function(
                    mod, stmt, ci, None, f"{qname}.{stmt.name}"
                )
        # Attribute table: every `self.X = ...` in every method.
        for m in ci.methods.values():
            for sub in ast.walk(m.node):
                targets: List[ast.expr] = []
                value = None
                annotation = None
                if isinstance(sub, ast.Assign):
                    targets, value = sub.targets, sub.value
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    targets, value, annotation = [sub.target], sub.value, sub.annotation
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self._note_attr(
                            mod, ci, m, t.attr, value, sub.lineno, annotation
                        )
        mod.classes[node.name] = ci
        self.classes[qname] = ci

    def _note_attr(
        self, mod, ci, method, name, value, lineno, annotation
    ) -> None:
        rec = ci.attrs.get(name)
        if rec is None:
            rec = ci.attrs[name] = AttrRecord(name=name, lineno=lineno)
        guard = _guard_comment(mod.ctx, lineno)
        if guard and not rec.guarded_by:
            rec.guarded_by = guard
        kind, lock_name = _lock_factory(value)
        if kind and rec.lock_id is None:
            rec.lock_kind = kind
            rec.lock_id = lock_name or f"{_short_mod(mod.modname)}.{ci.name}.{name}"
        if isinstance(value, _MUTABLE_LITERALS) or (
            isinstance(value, ast.Call)
            and _dotted(value.func).split(".")[-1] in ("dict", "list", "set", "defaultdict", "deque")
        ):
            rec.mutable_literal = True
        if rec.type_qname is None:
            rec.type_qname = self._value_type(mod, method, value, annotation)

    def _value_type(self, mod, method, value, annotation) -> Optional[str]:
        """Class qname of an assigned value, when statically evident."""
        ann_t = _annotation_class(mod, annotation)
        if ann_t:
            return ann_t
        if isinstance(value, ast.IfExp):
            # `x if x is not None else Default()`: either arm may name
            # the type (both arms agreeing is the common idiom).
            return self._value_type(
                mod, method, value.body, None
            ) or self._value_type(mod, method, value.orelse, None)
        if isinstance(value, ast.Call):
            dotted = _dotted(value.func)
            if dotted:
                resolved = _resolve_dotted(mod, dotted)
                if resolved in self.classes or resolved.split(".")[-1][:1].isupper():
                    return resolved
        if isinstance(value, ast.Name):
            # `self.x = param` where the constructor annotates param.
            args = getattr(method.node, "args", None)
            if args is not None:
                for a in list(args.args) + list(args.kwonlyargs):
                    if a.arg == value.id and a.annotation is not None:
                        return _annotation_class(mod, a.annotation)
        return None

    def _collect_global(self, mod: ModuleInfo, stmt) -> None:
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        else:
            targets, value = [stmt.target], stmt.value
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            rec = mod.globals.get(t.id)
            if rec is None:
                rec = mod.globals[t.id] = GlobalRecord(name=t.id, lineno=stmt.lineno)
            rec.guarded_by = rec.guarded_by or _guard_comment(mod.ctx, stmt.lineno)
            kind, lock_name = _lock_factory(value)
            if kind:
                rec.lock_kind = kind
                rec.lock_id = lock_name or f"{_short_mod(mod.modname)}.{t.id}"
            if isinstance(value, _MUTABLE_LITERALS):
                rec.mutable_literal = True
            if (
                isinstance(value, ast.Call)
                and _dotted(value.func).split(".")[-1] == "local"
                and "threading" in _dotted(value.func)
            ):
                rec.thread_local = True

    def _index(self) -> None:
        for ci in self.classes.values():
            self.class_by_name.setdefault(ci.name, []).append(ci)
            for m in ci.methods.values():
                self.methods_by_name.setdefault(
                    m.node.name, []
                ).append(m)
        for lock_id, kind, site in self._iter_locks():
            self.lock_kinds[lock_id] = kind
            self.lock_sites.setdefault(lock_id, site)

    def _iter_locks(self):
        for mod in self.modules.values():
            for g in mod.globals.values():
                if g.lock_id:
                    yield g.lock_id, g.lock_kind, (mod.relpath, g.lineno)
            for ci in mod.classes.values():
                for a in ci.attrs.values():
                    if a.lock_id:
                        yield a.lock_id, a.lock_kind, (mod.relpath, a.lineno)

    # -- pass 2: per-function resolution -----------------------------------

    def _resolve_function(self, mod: ModuleInfo, fn: FunctionInfo) -> None:
        fn.local_types = dict(fn.parent.local_types) if fn.parent else {}
        args = getattr(fn.node, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs) + list(
                filter(None, [args.vararg, args.kwarg])
            ):
                if a.arg == "self" and fn.klass is not None:
                    fn.local_types["self"] = fn.klass.qname
                elif a.annotation is not None:
                    t = _annotation_class(mod, a.annotation)
                    if t:
                        fn.local_types[a.arg] = t
        for sub in _own_nodes(fn.node):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                t = self._value_type(mod, fn, sub.value, None)
                if t:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            fn.local_types[tgt.id] = t
        analysis = FuncAnalysis()
        fn.analysis = analysis
        self._walk_body(mod, fn, list(_body_of(fn.node)), (), analysis)
        self.callees[fn.qname] = {
            q for call, _ in analysis.calls
            for q in self.call_targets.get(id(call), [])
        }
        self.blocks_direct[fn.qname] = [
            (node.lineno, desc) for node, desc, _ in analysis.blocking
        ]

    def _walk_body(self, mod, fn, stmts, held, analysis: FuncAnalysis) -> None:
        for stmt in stmts:
            self._walk_node(mod, fn, stmt, held, analysis)

    def _walk_node(self, mod, fn, node, held, analysis: FuncAnalysis) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested scopes run elsewhere; analyzed on their own
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                self._walk_node(mod, fn, item.context_expr, held, analysis)
                lock = self._lock_of_expr(mod, fn, item.context_expr)
                if lock is not None:
                    analysis.acquisitions.append(
                        (lock, tuple(held) + tuple(acquired), item.context_expr, True)
                    )
                    analysis.direct_locks.add(lock)
                    acquired.append(lock)
            inner = tuple(held) + tuple(acquired)
            self._walk_body(mod, fn, node.body, inner, analysis)
            return
        if isinstance(node, ast.Await):
            analysis.awaits.append(node.lineno)
        if isinstance(node, ast.Call):
            self._note_call(mod, fn, node, held, analysis)
            # `self.X.append(...)` / `self.X.update(...)` mutate X just as
            # surely as `self.X[k] = v`: classify as stores so guarded-by
            # inference sees dict/list mutations, not only rebinds.
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATOR_METHODS
                and isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"
            ):
                analysis.self_attr.append(
                    (f.value.attr, "store", tuple(held), node)
                )
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            v = node.value
            if (
                isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"
            ):
                analysis.self_attr.append((v.attr, "store", tuple(held), node))
            elif isinstance(v, ast.Name) and v.id in mod.globals:
                analysis.global_names.append((v.id, "store", tuple(held), node))
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                kind = "store" if isinstance(node.ctx, (ast.Store, ast.Del)) else "load"
                analysis.self_attr.append((node.attr, kind, tuple(held), node))
        elif isinstance(node, ast.Name):
            kind = "store" if isinstance(node.ctx, (ast.Store, ast.Del)) else "load"
            if node.id in mod.globals:
                analysis.global_names.append((node.id, kind, tuple(held), node))
            analysis.local_uses.append((node.id, node.lineno, tuple(held)))
            if isinstance(node.ctx, ast.Store):
                analysis.local_stores.setdefault(node.id, node)
        if isinstance(node, ast.Assign) and isinstance(
            node.value, _MUTABLE_LITERALS
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    analysis.local_mutables.setdefault(t.id, node.lineno)
        if isinstance(node, ast.AnnAssign) and isinstance(
            node.value, _MUTABLE_LITERALS
        ) and isinstance(node.target, ast.Name):
            analysis.local_mutables.setdefault(node.target.id, node.lineno)
        for child in ast.iter_child_nodes(node):
            self._walk_node(mod, fn, child, held, analysis)

    def _note_call(self, mod, fn, node: ast.Call, held, analysis) -> None:
        targets = self._resolve_call(mod, fn, node)
        if targets:
            self.call_targets[id(node)] = targets
        analysis.calls.append((node, tuple(held)))
        # `.acquire()` on a resolvable lock counts as an acquisition even
        # outside a `with` (the manual-protocol form).
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            lock = self._lock_of_expr(mod, fn, node.func.value)
            if lock is not None:
                analysis.acquisitions.append((lock, tuple(held), node, False))
                analysis.direct_locks.add(lock)
        desc = self._blocking_desc(mod, fn, node, held)
        if desc is not None:
            # A (desc, effective_held) pair narrows the held set: the
            # cv-wait case releases its own lock, leaving only the outer
            # ones blocked for the wait's duration.
            eff = tuple(held)
            if isinstance(desc, tuple):
                desc, eff = desc
            analysis.blocking.append((node, desc, eff))

    # -- resolution helpers ------------------------------------------------

    def _resolve_call(self, mod, fn, node: ast.Call) -> List[str]:
        f = node.func
        if isinstance(f, ast.Name):
            scope: Optional[FunctionInfo] = fn
            while scope is not None:
                if f.id in scope.nested:
                    return [scope.nested[f.id].qname]
                scope = scope.parent
            if fn.klass is not None and f.id in fn.klass.methods:
                pass  # bare method names don't resolve without self
            if f.id in mod.functions:
                return [mod.functions[f.id].qname]
            dotted = _resolve_dotted(mod, f.id)
            return self._qnames_for(dotted)
        if isinstance(f, ast.Attribute):
            recv_type = self._expr_type(mod, fn, f.value)
            if recv_type is not None:
                m = self._lookup_method(recv_type, f.attr)
                if m is not None:
                    return [m.qname]
            dotted = _dotted(f)
            if dotted:
                head = dotted.split(".")[0]
                if head in mod.imports:
                    return self._qnames_for(
                        _resolve_dotted(mod, dotted)
                    )
            # Duck-typing fallback: a method name defined by few-enough
            # project classes resolves to every candidate (conservative
            # over-approximation; see module docstring).
            if f.attr not in _FALLBACK_DENYLIST:
                cands = self.methods_by_name.get(f.attr, [])
                if 1 <= len(cands) <= _FALLBACK_MAX_CANDIDATES:
                    return [m.qname for m in cands]
        return []

    def _qnames_for(self, dotted: str) -> List[str]:
        if dotted in self.functions:
            return [dotted]
        if dotted in self.classes:
            init = self.classes[dotted].methods.get("__init__")
            return [init.qname] if init is not None else []
        return []

    def _lookup_method(self, class_qname: str, name: str) -> Optional[FunctionInfo]:
        seen: Set[str] = set()
        queue = [class_qname]
        while queue:
            q = queue.pop(0)
            if q in seen:
                continue
            seen.add(q)
            ci = self.classes.get(q)
            if ci is None:
                continue
            if name in ci.methods:
                return ci.methods[name]
            queue.extend(ci.bases)
        return None

    def _expr_type(self, mod, fn, expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return fn.local_types.get(expr.id)
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
        ):
            base_t = fn.local_types.get(expr.value.id)
            if base_t is not None:
                ci = self.classes.get(base_t)
                if ci is not None:
                    rec = self._lookup_attr(ci, expr.attr)
                    if rec is not None:
                        return rec.type_qname
        return None

    def _lookup_attr(self, ci: ClassInfo, name: str) -> Optional[AttrRecord]:
        seen: Set[str] = set()
        queue = [ci.qname]
        while queue:
            q = queue.pop(0)
            if q in seen:
                continue
            seen.add(q)
            c = self.classes.get(q)
            if c is None:
                continue
            if name in c.attrs:
                return c.attrs[name]
            queue.extend(c.bases)
        return None

    def _lock_of_expr(self, mod, fn, expr) -> Optional[str]:
        """Resolve an expression to a lock node id, or None."""
        if isinstance(expr, ast.Name):
            g = mod.globals.get(expr.id)
            return g.lock_id if g is not None else None
        if isinstance(expr, ast.Attribute):
            base_t = self._expr_type(mod, fn, expr.value)
            if base_t is None and isinstance(expr.value, ast.Name):
                base_t = fn.local_types.get(expr.value.id)
            if base_t is not None:
                ci = self.classes.get(base_t)
                if ci is not None:
                    rec = self._lookup_attr(ci, expr.attr)
                    if rec is not None and rec.lock_id:
                        return rec.lock_id
        return None

    # -- blocking-call classification (shared by DLP031/DLP033) ------------

    _QUEUEISH = re.compile(r"(^|_)q(ueue)?$")
    _THREADISH = re.compile(r"thread")

    def _blocking_desc(self, mod, fn, node: ast.Call, held):
        """A human description when ``node`` blocks, else None. Returns a
        ``(desc, effective_held)`` pair instead when the call narrows the
        held set (cv.wait releases its own lock for the duration)."""
        f = node.func
        dotted = _dotted(f)
        tail = dotted.split(".")[-1] if dotted else ""
        head = dotted.split(".")[0] if dotted else ""
        head_mod = mod.imports.get(head, head)
        if head_mod == "time" and tail == "sleep" and "." in dotted:
            return f"`{dotted}()` (time.sleep)"
        if head_mod == "subprocess" and tail in (
            "run", "call", "check_call", "check_output"
        ) and "." in dotted:
            return f"`{dotted}()` (subprocess)"
        if isinstance(f, ast.Name):
            target = mod.imports.get(f.id, "")
            if target in ("time.sleep",) or target.startswith("subprocess."):
                return f"`{f.id}()` ({target})"
            if f.id == "open":
                return "`open()` (file I/O)"
        if isinstance(f, ast.Attribute):
            if f.attr in ("block_until_ready", "device_get"):
                return f"`.{f.attr}()` (device sync)"
            if f.attr in ("read_text", "write_text", "read_bytes", "write_bytes"):
                return f"`.{f.attr}()` (file I/O)"
            if f.attr in ("accept", "recv", "recvfrom", "recv_into"):
                return f"synchronous socket `.{f.attr}()`"
            recv_name = (
                f.value.attr if isinstance(f.value, ast.Attribute)
                else f.value.id if isinstance(f.value, ast.Name) else ""
            )
            if f.attr in ("write", "flush") and re.search(
                r"writ|sink|_fh$|file", recv_name or ""
            ):
                return f"`{recv_name}.{f.attr}()` (file I/O)"
            if f.attr == "get" and self._QUEUEISH.search(recv_name or ""):
                if not any(
                    kw.arg == "block"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                    for kw in node.keywords
                ):
                    return f"blocking `{recv_name}.get()`"
            if f.attr == "join" and self._THREADISH.search(recv_name or ""):
                return f"`{recv_name}.join()`"
            if f.attr == "wait":
                # Condition.wait RELEASES the lock it waits on: exempt
                # when the receiver is the innermost held lock AND nothing
                # else is held — an OUTER lock stays held for the whole
                # wait, which is exactly the convoy this rule exists for.
                lock = self._lock_of_expr(mod, fn, f.value)
                if lock is not None and held and held[-1] == lock:
                    outer = tuple(held[:-1])
                    if not outer:
                        return None
                    return (
                        f"`{recv_name or '<expr>'}.wait()` (releases "
                        f"`{lock}`, but not the outer lock)",
                        outer,
                    )
                if lock is not None or _looks_waitable(recv_name):
                    return f"`{recv_name or '<expr>'}.wait()`"
        return None

    # -- thread entries ----------------------------------------------------

    def _find_entries(self) -> None:
        for fn in self.functions.values():
            if fn.is_async:
                # The event loop is its own execution context, concurrent
                # with every worker thread.
                self.thread_entries.add(fn.qname)
            if fn.analysis is None:
                continue
            mod = self.modules[fn.modname]
            for call, _held in fn.analysis.calls:
                site = self._entry_site(mod, fn, call)
                if site is not None:
                    self.entry_sites.append(site)
                    self.thread_entries.update(site.targets)
        # run() of threading.Thread subclasses.
        for ci in self.classes.values():
            if any(b.split(".")[-1] == "Thread" for b in ci.bases):
                run = ci.methods.get("run")
                if run is not None:
                    self.thread_entries.add(run.qname)

    def _entry_site(self, mod, fn, call: ast.Call) -> Optional[EntrySite]:
        dotted = _dotted(call.func)
        tail = dotted.split(".")[-1] if dotted else (
            call.func.attr if isinstance(call.func, ast.Attribute) else ""
        )
        fn_args: List[ast.AST] = []
        data_args: List[ast.AST] = []
        kind = None
        if tail == "Thread" and "threading" in _resolve_dotted(mod, dotted):
            kind = "thread"
            for kw in call.keywords:
                if kw.arg == "target":
                    fn_args.append(kw.value)
                elif kw.arg == "args" and isinstance(kw.value, (ast.Tuple, ast.List)):
                    data_args.extend(kw.value.elts)
                elif kw.arg == "kwargs" and isinstance(kw.value, ast.Dict):
                    data_args.extend(kw.value.values)
        elif tail == "Timer" and "threading" in _resolve_dotted(mod, dotted):
            kind = "timer"
            if len(call.args) >= 2:
                fn_args.append(call.args[1])
            for kw in call.keywords:
                if kw.arg == "function":
                    fn_args.append(kw.value)
                elif kw.arg == "args" and isinstance(kw.value, (ast.Tuple, ast.List)):
                    data_args.extend(kw.value.elts)
        elif tail == "submit" and isinstance(call.func, ast.Attribute):
            kind = "submit"
            for i, a in enumerate(call.args):
                (fn_args if self._is_callable_expr(mod, fn, a) or i == 0
                 else data_args).append(a)
            for kw in call.keywords:
                (fn_args if self._is_callable_expr(mod, fn, kw.value)
                 else data_args).append(kw.value)
        elif tail == "run_in_executor":
            kind = "executor"
            if len(call.args) >= 2:
                fn_args.append(call.args[1])
                data_args.extend(call.args[2:])
        elif tail in ("create_task", "ensure_future") and (
            "asyncio" in _resolve_dotted(mod, dotted) or dotted.startswith("asyncio")
        ):
            kind = "task"
            for a in call.args:
                if isinstance(a, ast.Call):
                    fn_args.append(a.func)
        if kind is None:
            return None
        targets: List[str] = []
        for e in fn_args:
            targets.extend(self._resolve_callable_expr(mod, fn, e))
        if not targets and kind == "submit":
            # `.submit` on a non-worker object (e.g. a plain pool we can't
            # see): still an entry site for escape checking, with no
            # resolvable target.
            pass
        return EntrySite(
            call=call, func=fn, targets=targets,
            target_exprs=fn_args, data_args=data_args, kind=kind,
        )

    def _is_callable_expr(self, mod, fn, expr) -> bool:
        return bool(self._resolve_callable_expr(mod, fn, expr))

    def _resolve_callable_expr(self, mod, fn, expr) -> List[str]:
        if isinstance(expr, ast.Name):
            scope = fn
            while scope is not None:
                if expr.id in scope.nested:
                    return [scope.nested[expr.id].qname]
                scope = scope.parent
            if expr.id in mod.functions:
                return [mod.functions[expr.id].qname]
            dotted = _resolve_dotted(mod, expr.id)
            if dotted in self.functions:
                return [dotted]
            return []
        if isinstance(expr, ast.Attribute):
            recv_type = self._expr_type(mod, fn, expr.value)
            if recv_type is not None:
                m = self._lookup_method(recv_type, expr.attr)
                if m is not None:
                    return [m.qname]
            if expr.attr not in _FALLBACK_DENYLIST:
                cands = self.methods_by_name.get(expr.attr, [])
                if 1 <= len(cands) <= _FALLBACK_MAX_CANDIDATES:
                    return [m.qname for m in cands]
        return []

    # -- lock graph --------------------------------------------------------

    def _fixpoint_acquires(self) -> None:
        for q, fn in self.functions.items():
            self.acquires_star[q] = set(
                fn.analysis.direct_locks if fn.analysis else ()
            )
        changed = True
        while changed:
            changed = False
            for q in self.functions:
                acc = self.acquires_star[q]
                before = len(acc)
                for callee in self.callees.get(q, ()):
                    acc |= self.acquires_star.get(callee, set())
                if len(acc) != before:
                    changed = True

    def _build_lock_graph(self) -> None:
        for fn in self.functions.values():
            a = fn.analysis
            if a is None:
                continue
            for lock, held, node, _via_with in a.acquisitions:
                for h in held:
                    if h != lock:
                        self._add_edge(h, lock, fn.relpath, node.lineno, "direct")
            for call, held in a.calls:
                if not held:
                    continue
                for callee in self.call_targets.get(id(call), []):
                    for m in self.acquires_star.get(callee, ()):
                        for h in held:
                            if h != m:
                                self._add_edge(
                                    h, m, fn.relpath, call.lineno,
                                    f"via {callee.split('.<locals>.')[-1]}",
                                )

    def _add_edge(self, a: str, b: str, relpath: str, line: int, how: str) -> None:
        self.lock_edges.setdefault((a, b), []).append((relpath, line, how))

    def _entry_held_pass(self) -> None:
        """Locks provably held on ENTRY to a function: the intersection of
        the lexically-held sets over every resolved call site (one level —
        callers' own inherited context is not chased). This is how the
        ``_take_ready``-style "helper called only under the lock" idiom
        type-checks against guarded-by annotations without lexically
        re-acquiring in the helper. Thread entries get nothing: their
        callers hand them to another thread, not a held region."""
        sites: Dict[str, List[Tuple[str, ...]]] = {}
        for fn in self.functions.values():
            if fn.analysis is None:
                continue
            for call, held in fn.analysis.calls:
                for callee in self.call_targets.get(id(call), []):
                    sites.setdefault(callee, []).append(held)
        for q, helds in sites.items():
            if q in self.thread_entries or not helds:
                continue
            common = set(helds[0])
            for h in helds[1:]:
                common &= set(h)
            if common:
                self.entry_held[q] = tuple(sorted(common))

    def _reach(self) -> None:
        seen: Set[str] = set()
        queue = list(self.thread_entries)
        while queue:
            q = queue.pop()
            if q in seen:
                continue
            seen.add(q)
            queue.extend(self.callees.get(q, ()))
            # A thread entry drags its nested closures along.
            fn = self.functions.get(q)
            if fn is not None:
                queue.extend(n.qname for n in fn.nested.values())
        self.thread_reachable = seen

    def lock_graph(self) -> dict:
        """The static acquisition graph, JSON-able — the reference the
        runtime sanitizer's observed graph is validated against."""
        return {
            "nodes": {
                lock: {
                    "kind": self.lock_kinds.get(lock, "lock"),
                    "site": list(self.lock_sites.get(lock, ("?", 0))),
                }
                for lock in sorted(self.lock_kinds)
            },
            "edges": [
                {
                    "from": a,
                    "to": b,
                    "sites": [list(s) for s in sorted(set(sites))[:4]],
                }
                for (a, b), sites in sorted(self.lock_edges.items())
            ],
        }


# --------------------------------------------------------------------------
# small AST helpers


def _iter_functions(mod: ModuleInfo) -> Iterator[FunctionInfo]:
    """Every function of a module, parents before their nested defs (a
    closure's resolution inherits the enclosing type environment)."""

    def rec(fn: FunctionInfo) -> Iterator[FunctionInfo]:
        yield fn
        for sub in fn.nested.values():
            yield from rec(sub)

    for fn in mod.functions.values():
        yield from rec(fn)
    for ci in mod.classes.values():
        for m in ci.methods.values():
            yield from rec(m)


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _resolve_dotted(mod: ModuleInfo, dotted: str) -> str:
    if not dotted:
        return ""
    head, _, rest = dotted.partition(".")
    target = mod.imports.get(head)
    if target is None:
        if head in mod.classes:
            target = f"{mod.modname}.{head}"
        elif head in mod.functions:
            target = f"{mod.modname}.{head}"
        else:
            target = head
    return f"{target}.{rest}" if rest else target


def _annotation_class(mod: ModuleInfo, ann) -> Optional[str]:
    """Class qname named by an annotation: Name, dotted, Optional[X],
    or the quoted-string form."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.strip().split("[")[-1].rstrip("]").strip("\"'")
        return _resolve_dotted(mod, name) if name else None
    if isinstance(ann, ast.Subscript):
        base = _dotted(ann.value).split(".")[-1]
        if base in ("Optional", "Union"):
            inner = ann.slice
            if isinstance(inner, ast.Tuple):
                for e in inner.elts:
                    t = _annotation_class(mod, e)
                    if t:
                        return t
                return None
            return _annotation_class(mod, inner)
        return None
    dotted = _dotted(ann)
    if not dotted or dotted in ("None",):
        return None
    return _resolve_dotted(mod, dotted)


def _lock_factory(value) -> Tuple[Optional[str], Optional[str]]:
    """(kind, explicit name) when ``value`` constructs a lock."""
    if not isinstance(value, ast.Call):
        return None, None
    dotted = _dotted(value.func)
    tail = dotted.split(".")[-1]
    if tail in _LOCK_FACTORIES and ("threading" in dotted or dotted == tail):
        return _LOCK_FACTORIES[tail], None
    if tail == "make_lock":
        name = None
        if value.args and isinstance(value.args[0], ast.Constant) and isinstance(
            value.args[0].value, str
        ):
            name = value.args[0].value
        kind = "lock"
        for kw in value.keywords:
            if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                kind = str(kw.value.value)
        return kind, name
    return None, None


def _guard_comment(
    ctx: FileContext, lineno: int
) -> Optional[str]:
    """The ``# guarded-by:`` annotation for an assignment at ``lineno``:
    its own inline comment, or a PURE comment line directly above it. An
    inline comment on the *previous statement's* line must never leak
    onto this one, so the line-above form requires the line to hold
    nothing but the comment."""
    comments = ctx.comments()
    m = GUARDED_BY_RE.search(comments.get(lineno, ""))
    if m:
        return m.group(1)
    above = comments.get(lineno - 1)
    if above and 0 < lineno - 1 <= len(ctx.lines):
        if ctx.lines[lineno - 2].strip().startswith("#"):
            m = GUARDED_BY_RE.search(above)
            if m:
                return m.group(1)
    return None


def _looks_waitable(name: str) -> bool:
    return bool(name) and bool(
        re.search(r"(event|done|_cv|cond|stop)", name, re.IGNORECASE)
    )


def _body_of(node) -> List[ast.stmt]:
    body = getattr(node, "body", [])
    return body if isinstance(body, list) else [ast.Expr(body)]


def _own_nodes(func_node) -> Iterator[ast.AST]:
    """Nodes of a function body, NOT descending into nested defs."""
    stack = list(_body_of(func_node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _innermost_owner(root, target):
    """The function node whose body (not a nested def's) contains target."""
    owner = root
    stack = [(root, root)]
    while stack:
        node, own = stack.pop()
        for child in ast.iter_child_nodes(node):
            if child is target:
                return own
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.append((child, child))
            else:
                stack.append((child, own))
    return owner


# --------------------------------------------------------------------------
# project rule registry + runner


class ProjectRule:
    """Like :class:`core.Rule`, but checked against the whole program."""

    code: str = ""
    name: str = ""
    rationale: str = ""

    def check(self, pc: ProjectContext) -> Iterator[Finding]:
        raise NotImplementedError


PROJECT_RULES: Dict[str, ProjectRule] = {}


def register_project(cls: type) -> type:
    rule = cls()
    if not rule.code or rule.code in PROJECT_RULES:
        raise ValueError(f"bad or duplicate project rule code: {rule.code!r}")
    PROJECT_RULES[rule.code] = rule
    return cls


def run_project(
    contexts: Dict[str, FileContext],
    select: Optional[List[str]] = None,
) -> List[Finding]:
    """Run the (selected) project rules over already-parsed contexts.

    Suppression comments work exactly as for per-file rules: a finding's
    line is looked up in its OWN file's comments.
    """
    pc = ProjectContext.build(contexts)
    codes = list(select) if select else sorted(PROJECT_RULES)
    findings: List[Finding] = []
    for code in codes:
        rule = PROJECT_RULES.get(code)
        if rule is None:
            raise KeyError(f"unknown project rule code {code!r}")
        findings.extend(rule.check(pc))
    out: List[Finding] = []
    for f in findings:
        ctx = contexts.get(f.path)
        if ctx is not None and is_suppressed(ctx, f):
            continue
        out.append(f)
    return out


def project_lint_sources(
    sources: Dict[str, str], select: Optional[List[str]] = None
) -> List[Finding]:
    """Fixture-test API: run project rules over in-memory modules."""
    contexts = {
        rel: FileContext.from_source(rel, src) for rel, src in sources.items()
    }
    return run_project(contexts, select=select)


# Importing this module must leave PROJECT_RULES fully populated — the CLI
# validates --select against it and --list-rules walks it. The import sits
# at the BOTTOM because concurrency.py imports names defined above; by the
# time it runs, they all exist, so the cycle is benign.
from . import concurrency  # noqa: E402,F401  # dlint: disable=DLP001 imported for its register_project side effect

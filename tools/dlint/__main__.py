"""CLI for the dlint gate.

    python -m tools.dlint [paths ...]        # baseline-aware gate
    python -m tools.dlint --strict           # + baseline hygiene (CI)
    python -m tools.dlint --list-rules       # rule codes + rationale
    python -m tools.dlint --select DLP012    # run a subset
    python -m tools.dlint --write-baseline   # grandfather current findings

Exit status: 0 clean, 1 findings (or, under --strict, stale/unjustified
baseline entries), 2 usage errors.
"""

from __future__ import annotations

import argparse
import sys
import textwrap
from pathlib import Path

from .core import DEFAULT_BASELINE, RULES, Baseline, BaselineEntry, run


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dlint",
        description="JAX-aware static-analysis gate (stdlib-only)",
    )
    p.add_argument("paths", nargs="*", help="files/dirs (default: whole repo)")
    p.add_argument(
        "--strict",
        action="store_true",
        help="also fail on stale or unjustified baseline entries",
    )
    p.add_argument(
        "--baseline",
        default=str(DEFAULT_BASELINE),
        help="baseline JSON path (default: tools/dlint/baseline.json)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: every finding fails",
    )
    p.add_argument(
        "--select",
        default=None,
        help="comma-separated rule codes to run (default: all)",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print rules and exit"
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0 "
        "(reasons start as TODO; --strict fails until they are justified "
        "or the findings fixed)",
    )
    p.add_argument("--quiet", action="store_true", help="findings only")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for code in sorted(RULES):
            rule = RULES[code]
            print(f"{code} {rule.name}")
            print(textwrap.indent(textwrap.fill(rule.rationale, 74), "    "))
        return 0

    select = None
    if args.select:
        select = [c.strip().upper() for c in args.select.split(",") if c.strip()]
        unknown = [c for c in select if c not in RULES]
        if unknown:
            print(f"error: unknown rule code(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    paths = [Path(p) for p in args.paths] or None
    if paths:
        for p in paths:
            if not p.exists():
                print(f"error: no such path: {p}", file=sys.stderr)
                return 2

    baseline_path = Path(args.baseline)
    baseline = (
        Baseline() if args.no_baseline else Baseline.load(baseline_path)
    )

    if args.write_baseline and args.no_baseline:
        # The rewrite path carries existing reasons forward; --no-baseline
        # hides them, so the combination would discard every justification.
        print(
            "error: --write-baseline cannot be combined with --no-baseline "
            "(existing entry reasons would be discarded)",
            file=sys.stderr,
        )
        return 2

    if args.write_baseline and (paths or select):
        # A subset run sees only a subset of findings; rewriting the
        # baseline from it would silently drop every entry outside the
        # subset (and its human-written reason).
        print(
            "error: --write-baseline requires a whole-repo, all-rules run "
            "(no paths, no --select)",
            file=sys.stderr,
        )
        return 2

    result = run(paths=paths, baseline=baseline, select=select)

    if args.write_baseline:
        entries = {}
        for f in result.findings_new + result.findings_baselined:
            key = (f.path, f.code)
            if key in entries:
                entries[key].count += 1
            else:
                old_reason = next(
                    (
                        e.reason
                        for e in baseline.entries
                        if (e.path, e.code) == key and e.reason.strip()
                    ),
                    "",
                )
                entries[key] = BaselineEntry(
                    path=f.path, code=f.code, count=1, reason=old_reason
                )
        Baseline(entries=list(entries.values())).dump(baseline_path)
        print(
            f"baseline written: {len(entries)} entr(y/ies) covering "
            f"{len(result.findings_new) + len(result.findings_baselined)} "
            f"finding(s) -> {baseline_path}"
        )
        return 0

    for f in result.findings_new:
        print(f.render())
    failed = result.failed(strict=args.strict)
    if args.strict:
        for e in result.stale_entries:
            print(
                f"{e.path}: STALE baseline entry {e.code} x{e.count} "
                "no longer matches any finding; trim the baseline"
            )
        for e in result.unjustified_entries:
            print(
                f"{e.path}: baseline entry {e.code} has no reason; "
                "justify it or fix the finding"
            )
    if not args.quiet:
        n_new = len(result.findings_new)
        n_old = len(result.findings_baselined)
        scope = (
            f"{result.n_files} files" if result.n_files >= 0 else "given paths"
        )
        if failed:
            print(
                f"dlint: {n_new} finding(s)"
                + (f", {n_old} baselined" if n_old else "")
                + (
                    f", {len(result.stale_entries)} stale / "
                    f"{len(result.unjustified_entries)} unjustified "
                    "baseline entr(y/ies)"
                    if args.strict
                    and (result.stale_entries or result.unjustified_entries)
                    else ""
                )
            )
        else:
            print(
                f"dlint clean ({scope}, {len(RULES)} rules"
                + (f", {n_old} baselined finding(s)" if n_old else "")
                + ")"
            )
    return 1 if failed else 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # `dlint ... | head` closed the pipe before we finished printing.
        # Findings were being printed, so the run must NOT read as clean —
        # exit 141 (the conventional 128+SIGPIPE), never 0.
        raise SystemExit(141)

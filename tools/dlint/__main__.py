"""CLI for the dlint gate.

    python -m tools.dlint [paths ...]        # baseline-aware gate
    python -m tools.dlint --strict           # + baseline hygiene (CI)
    python -m tools.dlint --changed          # per-file rules on the git
                                             # diff only (project pass
                                             # still runs whole-program)
    python -m tools.dlint --list-rules       # rule codes + rationale
    python -m tools.dlint --select DLP012    # run a subset
    python -m tools.dlint --write-baseline   # grandfather current findings
    python -m tools.dlint --lock-graph       # dump the static DLP032
                                             # acquisition graph as JSON
    python -m tools.dlint --check-lockwatch OUT.json
                                             # validate a DLP_LOCKWATCH
                                             # runtime report against it

Exit status: 0 clean, 1 findings (or, under --strict, stale/unjustified
baseline entries; or a failed lockwatch check), 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import textwrap
from pathlib import Path
from typing import List, Optional

from .core import DEFAULT_BASELINE, REPO, RULES, Baseline, BaselineEntry, run

LOCK_GRAPH_ALLOW = Path(__file__).resolve().parent / "lock_graph_allow.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dlint",
        description="JAX-aware static-analysis gate (stdlib-only)",
    )
    p.add_argument("paths", nargs="*", help="files/dirs (default: whole repo)")
    p.add_argument(
        "--strict",
        action="store_true",
        help="also fail on stale or unjustified baseline entries",
    )
    p.add_argument(
        "--changed",
        action="store_true",
        help="lint only files touched per git (diff vs HEAD + untracked); "
        "the whole-program pass still runs over the full library tree. "
        "Falls back to a full scan outside a git repo.",
    )
    p.add_argument(
        "--baseline",
        default=str(DEFAULT_BASELINE),
        help="baseline JSON path (default: tools/dlint/baseline.json)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: every finding fails",
    )
    p.add_argument(
        "--select",
        default=None,
        help="comma-separated rule codes to run (default: all; DLP03x "
        "codes select the whole-program pass)",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print rules and exit"
    )
    p.add_argument(
        "--lock-graph",
        action="store_true",
        help="print the static lock-acquisition graph (DLP032's model) "
        "as JSON and exit",
    )
    p.add_argument(
        "--check-lockwatch",
        metavar="REPORT",
        default=None,
        help="validate a DLP_LOCKWATCH_OUT runtime report: observed "
        "acquisition edges must be non-empty and a subset of the static "
        "graph (plus tools/dlint/lock_graph_allow.json), with zero "
        "cycle witnesses",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0 "
        "(reasons start as TODO; --strict fails until they are justified "
        "or the findings fixed)",
    )
    p.add_argument("--quiet", action="store_true", help="findings only")
    return p


def changed_files(root: Path = REPO) -> Optional[List[Path]]:
    """Python files touched per git: diff vs HEAD plus untracked. None
    when git is unavailable (caller falls back to the full scan)."""
    out: List[Path] = []
    try:
        for args in (
            ["git", "diff", "--name-only", "HEAD"],
            ["git", "ls-files", "--others", "--exclude-standard"],
        ):
            res = subprocess.run(
                args, cwd=root, capture_output=True, text=True, timeout=30
            )
            if res.returncode != 0:
                return None
            out.extend(
                root / line
                for line in res.stdout.splitlines()
                if line.endswith(".py")
            )
    except (OSError, subprocess.SubprocessError):
        return None
    # Deleted-but-not-committed files still appear in the diff.
    return sorted({p for p in out if p.exists()})


def _static_graph() -> dict:
    from .core import build_contexts, iter_py_files
    from .project import ProjectContext

    files = [
        p
        for p in iter_py_files(REPO)
        if p.resolve().relative_to(REPO).as_posix().startswith("distilp_tpu/")
    ]
    return ProjectContext.build(build_contexts(files)).lock_graph()


def check_lockwatch(report_path: Path) -> int:
    """The runtime half of DLP032's contract (see utils/lockwatch.py):
    the observed graph must be non-empty (the smoke actually exercised
    lock nesting), every observed edge must be one the static analyzer
    predicted (or an allowlisted, justified exception), and no cycle
    witness may have fired."""
    try:
        observed = json.loads(report_path.read_text())
    except (OSError, ValueError) as e:
        print(f"error: cannot read lockwatch report {report_path}: {e}",
              file=sys.stderr)
        return 2
    static = _static_graph()
    static_edges = {(e["from"], e["to"]) for e in static["edges"]}
    allowed = set()
    if LOCK_GRAPH_ALLOW.exists():
        blob = json.loads(LOCK_GRAPH_ALLOW.read_text())
        allowed = {(e["from"], e["to"]) for e in blob.get("edges", [])}

    failures = []
    obs_edges = [(e["from"], e["to"]) for e in observed.get("edges", [])]
    if not obs_edges:
        failures.append(
            "observed acquisition graph is EMPTY — the run under "
            "DLP_LOCKWATCH=1 never nested two locks, so it validated "
            "nothing (wrong smoke arm?)"
        )
    unexplained = [
        e for e in obs_edges if e not in static_edges and e not in allowed
    ]
    for a, b in unexplained:
        failures.append(
            f"observed edge {a} -> {b} is missing from the static graph "
            "(dlint's call-graph model did not predict this nesting: fix "
            "the model or allowlist it with a justification)"
        )
    witnesses = observed.get("witnesses", [])
    for w in witnesses:
        failures.append(
            f"lock-order cycle witness: {' -> '.join(w.get('cycle', []))} "
            f"on thread {w.get('thread')}"
        )
    for line in failures:
        print(f"lockwatch: {line}")
    if not failures:
        print(
            f"lockwatch ok: {len(obs_edges)} observed edge(s), all in the "
            f"static graph ({len(static_edges)} edges), 0 witnesses"
        )
    return 1 if failures else 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from .project import PROJECT_RULES  # registers DLP03x

    if args.list_rules:
        for code in sorted(RULES):
            rule = RULES[code]
            print(f"{code} {rule.name}")
            print(textwrap.indent(textwrap.fill(rule.rationale, 74), "    "))
        for code in sorted(PROJECT_RULES):
            rule = PROJECT_RULES[code]
            print(f"{code} {rule.name} [whole-program]")
            print(textwrap.indent(textwrap.fill(rule.rationale, 74), "    "))
        return 0

    if args.lock_graph:
        json.dump(_static_graph(), sys.stdout, indent=2, sort_keys=True)
        print()
        return 0

    if args.check_lockwatch:
        return check_lockwatch(Path(args.check_lockwatch))

    select = None
    if args.select:
        select = [c.strip().upper() for c in args.select.split(",") if c.strip()]
        unknown = [
            c for c in select if c not in RULES and c not in PROJECT_RULES
        ]
        if unknown:
            print(f"error: unknown rule code(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    paths: Optional[List[Path]] = [Path(p) for p in args.paths] or None
    if paths:
        for p in paths:
            if not p.exists():
                print(f"error: no such path: {p}", file=sys.stderr)
                return 2

    with_project = None
    if args.changed:
        if paths:
            print("error: --changed cannot be combined with explicit paths",
                  file=sys.stderr)
            return 2
        changed = changed_files()
        if changed is None:
            # Not a git repo (or git broke): full scan is the safe answer.
            paths = None
        else:
            paths = changed  # may be [] — then only the project pass runs
            with_project = True

    baseline_path = Path(args.baseline)
    baseline = (
        Baseline() if args.no_baseline else Baseline.load(baseline_path)
    )

    if args.write_baseline and args.no_baseline:
        # The rewrite path carries existing reasons forward; --no-baseline
        # hides them, so the combination would discard every justification.
        print(
            "error: --write-baseline cannot be combined with --no-baseline "
            "(existing entry reasons would be discarded)",
            file=sys.stderr,
        )
        return 2

    if args.write_baseline and (paths is not None or select or args.changed):
        # A subset run sees only a subset of findings; rewriting the
        # baseline from it would silently drop every entry outside the
        # subset (and its human-written reason).
        print(
            "error: --write-baseline requires a whole-repo, all-rules run "
            "(no paths, no --select, no --changed)",
            file=sys.stderr,
        )
        return 2

    result = run(
        paths=paths, baseline=baseline, select=select,
        with_project=with_project,
    )

    if args.write_baseline:
        entries = {}
        for f in result.findings_new + result.findings_baselined:
            key = (f.path, f.code)
            if key in entries:
                entries[key].count += 1
            else:
                old_reason = next(
                    (
                        e.reason
                        for e in baseline.entries
                        if (e.path, e.code) == key and e.reason.strip()
                    ),
                    "",
                )
                entries[key] = BaselineEntry(
                    path=f.path, code=f.code, count=1, reason=old_reason
                )
        Baseline(entries=list(entries.values())).dump(baseline_path)
        print(
            f"baseline written: {len(entries)} entr(y/ies) covering "
            f"{len(result.findings_new) + len(result.findings_baselined)} "
            f"finding(s) -> {baseline_path}"
        )
        return 0

    for f in result.findings_new:
        print(f.render())
    failed = result.failed(strict=args.strict)
    if args.strict:
        for e in result.stale_entries:
            print(
                f"{e.path}: STALE baseline entry {e.code} x{e.count} "
                "no longer matches any finding; trim the baseline"
            )
        for e in result.unjustified_entries:
            print(
                f"{e.path}: baseline entry {e.code} has no reason; "
                "justify it or fix the finding"
            )
    if not args.quiet:
        n_new = len(result.findings_new)
        n_old = len(result.findings_baselined)
        n_rules = len(RULES) + len(PROJECT_RULES)
        scope = (
            f"{result.n_files} files" if result.n_files >= 0 else "given paths"
        )
        if args.changed and paths is not None:
            scope = f"{len(paths)} changed file(s) + project pass"
        if failed:
            print(
                f"dlint: {n_new} finding(s)"
                + (f", {n_old} baselined" if n_old else "")
                + (
                    f", {len(result.stale_entries)} stale / "
                    f"{len(result.unjustified_entries)} unjustified "
                    "baseline entr(y/ies)"
                    if args.strict
                    and (result.stale_entries or result.unjustified_entries)
                    else ""
                )
            )
        else:
            print(
                f"dlint clean ({scope}, {n_rules} rules"
                + (f", {n_old} baselined finding(s)" if n_old else "")
                + ")"
            )
    return 1 if failed else 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # `dlint ... | head` closed the pipe before we finished printing.
        # Findings were being printed, so the run must NOT read as clean —
        # exit 141 (the conventional 128+SIGPIPE), never 0.
        raise SystemExit(141)

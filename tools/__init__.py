# Makes `python -m tools.dlint` resolvable from the repo root. The tools
# package is never imported by library code.

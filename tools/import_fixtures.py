#!/usr/bin/env python3
"""Import the reference's golden profile fixtures as conformance data.

The fixture JSONs under the reference's ``test/profiles/`` are *data* — measured
device profiles and analytic model profiles — and serve as the
cross-implementation conformance suite: both solvers must produce the same
objective on the same profiles. This script validates each fixture through our
pydantic schemas and re-serializes it into ``tests/profiles/`` (normalized key
order/formatting). Values are intentionally identical; that is the point of a
conformance fixture.

Usage: python tools/import_fixtures.py [reference_root] [dest_root]
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from distilp_tpu.common import DeviceProfile
from distilp_tpu.common.loaders import parse_model_profile


def normalize(path: Path) -> dict:
    data = json.loads(path.read_text())
    if path.name == "model_profile.json":
        return parse_model_profile(data).model_dump(mode="json")
    return DeviceProfile.model_validate(data).model_dump(mode="json")


def main() -> int:
    ref = Path(sys.argv[1]) if len(sys.argv) > 1 else Path("/root/reference")
    dest = (
        Path(sys.argv[2])
        if len(sys.argv) > 2
        else Path(__file__).resolve().parents[1] / "tests" / "profiles"
    )
    src = ref / "test" / "profiles"

    # The legacy orphan fixture (flat f_q lists, f_by_quant keys) is not loadable
    # by the current schema in either implementation; skip it.
    skip = {"model_profile_qwen3_4b_8bit.json"}

    count = 0
    for path in sorted(src.rglob("*.json")):
        if path.name in skip:
            continue
        rel = path.relative_to(src)
        out = dest / rel
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(normalize(path), indent=1, sort_keys=True) + "\n")
        count += 1
        print(f"imported {rel}")
    print(f"{count} fixtures -> {dest}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python3
"""Opportunistic TPU capture loop.

The tunneled TPU ("axon" PJRT plugin) flaps for hours at a time; a round
lasts hours. Instead of attempting one end-of-round capture, this tool
probes the backend every few minutes in a throwaway subprocess (the probe
from bench.py — hard timeout, process-group kill, so a wedged tunnel costs
one child, never this process) and, the moment a live window opens:

1. runs ``python bench.py`` and saves the JSON line to
   ``BENCH_tpu_capture.json`` if it reports a real TPU platform;
2. runs ``profiler device --raw-out`` to capture the measured device
   fixtures ``tests/profiles/tpu_v5e/{tpu_v5e.json,tpu_v5e_raw.json}``
   (the analogue of the reference's measured device profiles, e.g.
   /root/reference/test/profiles/llama_3_70b/online/m1.json);
3. re-runs the skip-gated regression pins
   (tests/test_device_profiler.py::TestTpuV5eGoldenArtifacts) against the
   fresh fixtures and discards them if they fail;
4. commits whatever passed.

Exits 0 once both captures are committed; a partial window (bench captured
but the tunnel dropped before the fixtures finished) commits the part that
succeeded and keeps watching for the rest.

Run from round start:  ``python tools/tpu_watch.py >> tools/tpu_watch.log``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402  - reuse the wedge-proof probe

FIXDIR = REPO / "tests" / "profiles" / "tpu_v5e"
BENCH_OUT = REPO / "BENCH_tpu_capture.json"


_JSON_MODE = False


def _log(msg: str) -> None:
    # In --json mode the log stream moves to stderr so stdout carries
    # exactly one machine-readable object.
    out = sys.stderr if _JSON_MODE else sys.stdout
    print(f"[tpu_watch {time.strftime('%H:%M:%S')}] {msg}", file=out, flush=True)


def _run(cmd: list[str], timeout_s: float, env: dict | None = None) -> tuple[int | None, str, str]:
    """bench.run_contained pinned to the repo root (single shared
    implementation of the session/temp-file/killpg wedge containment)."""
    return bench.run_contained(cmd, timeout_s, env=env, cwd=str(REPO))


def probe_attempt(timeout_s: float, attempt: int = 0) -> tuple[str | None, dict]:
    """One live-backend probe; (platform-or-None, structured record).

    The record is SHAPED LIKE bench.py's ``tpu_error.attempts`` entries
    (outcome, elapsed, the probe child's phase trail and its compile
    ledger counters), so a watcher log and a bench capture describe a
    wedged init in the same vocabulary: ``wedged_after`` names the last
    phase the killed child flushed (``backend_init`` = the axon-tunnel
    wedge class; ``jax_import`` = environment, not tunnel), and
    ``ledger`` says whether the backend ever compiled anything.
    """
    t0 = time.monotonic()
    rc, stdout, _stderr = bench._run_probe_once(timeout_s)
    rec: dict = {
        "attempt": attempt,
        "elapsed_s": round(time.monotonic() - t0, 2),
    }
    phases = bench.parse_probe_phases(stdout)
    if phases:
        rec["phases"] = [p["phase"] for p in phases]
        ledger = next((p["ledger"] for p in phases if "ledger" in p), None)
        if ledger is not None:
            rec["ledger"] = ledger
    platform = bench.parse_probe_output(rc, stdout)
    if rc is None:
        rec["outcome"] = "timeout"
        rec["wedged_after"] = phases[-1]["phase"] if phases else "spawn"
    elif platform is None:
        rec["outcome"] = f"failed rc={rc}"
    else:
        rec["outcome"] = "ok"
        rec["platform"] = platform
    return platform, rec


# Device-memory probe child: runs in the SAME wedge-contained subprocess
# discipline as every other backend touch in this tool (this process must
# never init a backend — a dead tunnel wedges it forever). Emits one
# machine line with per-device memory_stats; devices that report none
# (the CPU backend) are recorded without a memory_stats key.
_MEM_PROBE_SRC = r"""
import json
import jax
devs = []
for d in jax.local_devices():
    rec = {"id": d.id, "platform": d.platform,
           "kind": getattr(d, "device_kind", None)}
    stats = d.memory_stats()
    if stats:
        rec["memory_stats"] = {
            k: int(v) for k, v in stats.items()
            if isinstance(v, (int, float))
        }
    devs.append(rec)
print("DPERF_MEM", json.dumps({"devices": devs}))
"""


def probe_device_memory(timeout_s: float) -> dict | None:
    """Per-device HBM totals from ``jax.local_devices()`` memory stats,
    via a contained child. None when no device reports memory stats —
    the CPU backend's ``memory_stats()`` is None, so a cpu-only probe
    yields an ABSENT memory block (absent, not zeroed: a fabricated
    0-byte HBM row would read as an empty accelerator, which is a much
    worse lie than no row)."""
    rc, stdout, _stderr = _run(
        [sys.executable, "-c", _MEM_PROBE_SRC], timeout_s
    )
    if rc != 0:
        return None
    line = next(
        (ln for ln in stdout.splitlines() if ln.startswith("DPERF_MEM ")),
        None,
    )
    if line is None:
        return None
    try:
        got = json.loads(line[len("DPERF_MEM "):])
    except json.JSONDecodeError:
        return None
    devices = [
        d for d in got.get("devices", []) if d.get("memory_stats")
    ]
    if not devices:
        return None
    limit = sum(
        d["memory_stats"].get("bytes_limit", 0) for d in devices
    )
    in_use = sum(
        d["memory_stats"].get("bytes_in_use", 0) for d in devices
    )
    peak = sum(
        d["memory_stats"].get("peak_bytes_in_use", 0) for d in devices
    )
    return {
        "devices": devices,
        "hbm_limit_bytes_total": limit or None,
        "hbm_in_use_bytes_total": in_use or None,
        "hbm_peak_bytes_total": peak or None,
    }


def _capture_bench(timeout_s: float) -> bool:
    """Run bench.py; persist the JSON line iff it ran on the TPU."""
    # Single attempt, no retries: the window is open NOW; if the tunnel
    # drops mid-bench the outer loop re-probes rather than stacking waits.
    env = dict(os.environ)
    env["DPERF_BENCH_PROBE_RETRIES"] = "1"
    rc, stdout, stderr = _run([sys.executable, "bench.py"], timeout_s, env=env)
    if rc is None:
        _log("bench.py timed out (tunnel dropped mid-bench?)")
        return False
    line = next(
        (ln for ln in reversed(stdout.strip().splitlines())
         if ln.startswith("{")), None,
    )
    if line is None:
        _log(f"bench.py rc={rc} with no JSON line; stderr tail: "
             f"{stderr.strip().splitlines()[-1:] or ''}")
        return False
    try:
        payload = json.loads(line)
    except json.JSONDecodeError:
        _log(f"bench.py emitted unparseable line: {line[:200]}")
        return False
    platform = str(payload.get("platform", ""))
    if platform.startswith("cpu") or payload.get("value") is None:
        _log(f"bench ran but not on TPU (platform={platform!r}, "
             f"value={payload.get('value')!r}); discarding")
        return False
    BENCH_OUT.write_text(line + "\n")
    _log(f"captured on-TPU bench: value={payload['value']} ms, "
         f"warm={payload.get('warm_tick_ms')} ms, "
         f"moe={payload.get('moe_warm_tick_ms')} ms, "
         f"tiny_put={payload.get('tiny_put_ms')} ms")
    return True


def _capture_fixtures(timeout_s: float) -> bool:
    """profiler device --raw-out → tpu_v5e fixtures, verified by the pins."""
    FIXDIR.mkdir(parents=True, exist_ok=True)
    prof_path = FIXDIR / "tpu_v5e.json"
    raw_path = FIXDIR / "tpu_v5e_raw.json"
    rc, _, stderr = _run(
        [
            sys.executable, "-m", "distilp_tpu.cli.profiler_cli", "device",
            "-r", "tests/configs/llama3_70b_4bit.json",
            "-o", str(prof_path), "--raw-out", str(raw_path),
        ],
        timeout_s,
    )
    if rc != 0:
        _log(f"profiler device failed (rc={rc}); stderr tail: "
             f"{stderr.strip().splitlines()[-1:] or ''}")
        return False
    if not (prof_path.exists() and raw_path.exists()):
        _log("profiler device rc=0 but fixtures missing")
        return False
    # A probe->capture race can leave the profiler on a CPU fallback that
    # exits 0; CPU-measured fixtures committed as tpu_v5e would poison the
    # regression pins, so require hard TPU evidence in the raw DeviceInfo.
    try:
        raw = json.loads(raw_path.read_text())
        gpu_name = str(raw.get("gpu", {}).get("name", ""))
    except (json.JSONDecodeError, AttributeError):
        gpu_name = ""
    if gpu_name != "tpu":
        _log(f"capture ran without a TPU accelerator (gpu.name={gpu_name!r}) "
             "— discarding")
        prof_path.unlink(missing_ok=True)
        raw_path.unlink(missing_ok=True)
        return False
    # Verify against the committed regression pins before trusting the
    # capture; the pin suite runs on the guarded CPU platform.
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    rc, out, err = _run(
        [sys.executable, "-m", "pytest", "-x", "-q",
         "tests/test_device_profiler.py", "-k", "TpuV5eGoldenArtifacts"],
        600, env=env,
    )
    if rc != 0:
        _log(f"fixture pins FAILED — discarding capture; tail: "
             f"{(out + err).strip().splitlines()[-3:]}")
        prof_path.unlink(missing_ok=True)
        raw_path.unlink(missing_ok=True)
        return False
    _log("captured tpu_v5e device fixtures (pins pass)")
    return True


def _commit(paths: list[str], msg: str) -> bool:
    """Stage paths and commit; True iff the artifacts are durably in git.

    On commit failure the paths are UNSTAGED again so a later commit of the
    other artifact cannot sweep them in under the wrong message, and the
    caller keeps retrying on the next live window.
    """
    # Everything pathspec-scoped: unrelated content the operator may have
    # staged must neither trigger nor ride along with an artifact commit.
    subprocess.run(["git", "add", "--"] + paths, cwd=str(REPO), check=False)
    staged = subprocess.run(
        ["git", "diff", "--cached", "--quiet", "--"] + paths, cwd=str(REPO)
    )
    if staged.returncode == 0:
        return True  # nothing new to record — already committed
    full = msg + "\n\nNo-Verification-Needed: benchmark/fixture artifact capture\n"
    r = subprocess.run(
        ["git", "commit", "-m", full, "--"] + paths, cwd=str(REPO),
        capture_output=True, text=True,
    )
    _log(f"git commit rc={r.returncode}: {r.stdout.strip().splitlines()[-1:] or r.stderr.strip().splitlines()[-1:]}")
    if r.returncode != 0:
        subprocess.run(["git", "reset", "--"] + paths, cwd=str(REPO), check=False)
        return False
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--interval", type=float, default=180.0,
                    help="seconds between probes (default 180)")
    ap.add_argument("--probe-timeout", type=float, default=60.0)
    ap.add_argument("--bench-timeout", type=float, default=2400.0)
    ap.add_argument("--fixture-timeout", type=float, default=1800.0)
    ap.add_argument("--max-hours", type=float, default=11.0,
                    help="give up after this long (default 11h)")
    ap.add_argument("--once", action="store_true",
                    help="single probe+capture attempt, then exit")
    ap.add_argument("--json", action="store_true",
                    help="emit ONE machine-readable JSON object on stdout "
                    "at exit (probe attempts with phase trails + compile-"
                    "ledger counters, capture status, and — when no live "
                    "window ever opened — a bench-shaped tpu_error block); "
                    "human logs move to stderr")
    args = ap.parse_args(argv)
    global _JSON_MODE
    _JSON_MODE = bool(args.json)
    attempts: list[dict] = []
    # Per-device HBM stats, captured once per run on the first live
    # window. A cpu-only run leaves this None and the --json payload's
    # memory block ABSENT (not zeroed) — same contract as the memory
    # ledger's watermark gauges.
    mem_state: dict = {"memory": None}

    def _finish(rc: int, have_bench: bool, have_fixtures: bool) -> int:
        if args.json:
            payload: dict = {
                "exit": rc,
                "attempts": attempts,
                "bench_captured": have_bench,
                "fixtures_captured": have_fixtures,
            }
            if mem_state["memory"] is not None:
                payload["memory"] = mem_state["memory"]
            live = any(
                a.get("platform") and not a["platform"].startswith("cpu")
                for a in attempts
            )
            if not live:
                # Fold the trail into the bench's structured tpu_error
                # shape: a watcher that never saw a live TPU window
                # reports the same block a fallback bench capture would.
                # A cpu-only probe is NOT a live window (the watcher
                # never captures on it), so it gets the block too.
                last = attempts[-1] if attempts else {}
                if last.get("outcome") == "timeout":
                    error = (
                        "probe timed out (backend init wedged after "
                        f"{last.get('wedged_after', 'spawn')})"
                    )
                elif last.get("platform", "").startswith("cpu"):
                    error = (
                        "probe found only the cpu fallback "
                        "(no live TPU window)"
                    )
                else:
                    error = (
                        "probe never found a live backend "
                        f"({last.get('outcome', 'no attempt')})"
                    )
                payload["tpu_error"] = {
                    "error": error,
                    "timeout_s": args.probe_timeout,
                    "retries": len(attempts),
                    "attempts": attempts,
                }
            print(json.dumps(payload))
        return rc

    deadline = time.monotonic() + args.max_hours * 3600.0
    # Restart-safe: a relaunched watcher must not burn a live window redoing
    # a capture that is already on disk — but on-disk is not durable, so
    # pre-existing artifacts are re-committed here (a no-op when they
    # already are; retries a capture→crash→relaunch gap when they aren't).
    have_bench = BENCH_OUT.exists() and _commit(
        [str(BENCH_OUT.relative_to(REPO))],
        "Capture on-TPU benchmark artifact (live tunnel window)")
    have_fixtures = (FIXDIR / "tpu_v5e.json").exists() and (
        FIXDIR / "tpu_v5e_raw.json").exists() and _commit(
        ["tests/profiles/tpu_v5e"],
        "Capture measured tpu_v5e device fixtures on live TPU")
    if have_bench:
        _log("on-TPU bench artifact already captured; not re-running it")
    if have_fixtures:
        _log("tpu_v5e fixtures already captured; watching for bench only")
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        platform, rec = probe_attempt(args.probe_timeout, attempt=attempt)
        attempts.append(rec)
        if platform is None or platform.startswith("cpu"):
            where = (
                f" (wedged after {rec['wedged_after']})"
                if rec.get("outcome") == "timeout"
                else ""
            )
            _log(f"probe #{attempt}: backend={platform or 'wedged/down'}"
                 f"{where}; sleeping {args.interval:.0f}s")
        else:
            _log(f"probe #{attempt}: LIVE backend platform={platform!r} — capturing")
            if mem_state["memory"] is None:
                # A live window is the one moment HBM stats exist to
                # read; the probe is its own contained child, so a
                # tunnel drop here costs one child, never the captures.
                mem_state["memory"] = probe_device_memory(args.probe_timeout)
                if mem_state["memory"] is not None:
                    _log(
                        "captured per-device HBM stats "
                        f"({len(mem_state['memory']['devices'])} device(s))"
                    )
            if not have_bench and _capture_bench(args.bench_timeout):
                have_bench = _commit(
                    [str(BENCH_OUT.relative_to(REPO))],
                    "Capture on-TPU benchmark artifact (live tunnel window)")
            if not have_fixtures and _capture_fixtures(args.fixture_timeout):
                have_fixtures = _commit(
                    ["tests/profiles/tpu_v5e"],
                    "Capture measured tpu_v5e device fixtures on live TPU")
            if have_bench and have_fixtures:
                _log("all captures committed; done")
                return _finish(0, have_bench, have_fixtures)
        if args.once:
            return _finish(
                0 if (have_bench and have_fixtures) else 2,
                have_bench, have_fixtures,
            )
        time.sleep(args.interval)
    _log("deadline reached without a full capture")
    return _finish(3, have_bench, have_fixtures)


if __name__ == "__main__":
    raise SystemExit(main())

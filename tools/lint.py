#!/usr/bin/env python3
"""Minimal stdlib lint gate: a subset of ruff's F-class checks.

The image this framework builds in has no ruff/flake8 and no network, so
`make lint` runs this instead; the `[tool.ruff]` config in pyproject.toml is
authoritative wherever ruff is available. Checks:

- every file parses (syntax gate);
- F401: module-level imports never referenced in the module;
- F811: module-level names redefined by a second import on a different line.

Function-scope imports are left alone (lazy imports are idiomatic here: jax
must not load at schema-import time).

Exit status 1 on any finding, printing ``path:line: code message`` lines.
"""

from __future__ import annotations

import ast
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SKIP_DIRS = {".git", "__pycache__", "build", "dist", ".venv"}


def iter_py_files():
    for p in sorted(REPO.rglob("*.py")):
        if not any(part in SKIP_DIRS for part in p.parts):
            yield p


def _import_bindings(node: ast.AST):
    """Yield (local_name, lineno) bound by an import statement."""
    if isinstance(node, ast.Import):
        for a in node.names:
            yield (a.asname or a.name.split(".")[0], node.lineno)
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name != "*":
                yield (a.asname or a.name, node.lineno)


def check_file(path: Path) -> list:
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [(e.lineno or 0, "E999", f"syntax error: {e.msg}")]

    problems = []
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # "import a.b" is used via the root name; ast.Name covers it.
            pass

    # Names re-exported via __all__ strings count as used.
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for elt in ast.walk(node.value):
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            used.add(elt.value)

    seen = {}
    for node in tree.body:  # module level only
        for name, lineno in _import_bindings(node):
            if name in seen and seen[name] != lineno:
                problems.append(
                    (lineno, "F811", f"redefinition of unused `{name}`")
                )
            seen[name] = lineno
            if name not in used and not name.startswith("_"):
                problems.append((lineno, "F401", f"`{name}` imported but unused"))
    return problems


def main() -> int:
    n = 0
    for path in iter_py_files():
        for lineno, code, msg in check_file(path):
            print(f"{path.relative_to(REPO)}:{lineno}: {code} {msg}")
            n += 1
    if n:
        print(f"{n} problem(s)")
        return 1
    print(f"lint clean ({len(list(iter_py_files()))} files)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python3
"""Compatibility shim: the lint gate grew into the ``tools/dlint`` package.

``python tools/lint.py`` keeps working (older scripts and muscle memory
call it) but simply delegates to ``python -m tools.dlint`` with the same
arguments. The old F401/F811 checks live on as rules DLP001/DLP002; the
JAX-aware contract rules are documented in README "Static analysis gate"
and ``python -m tools.dlint --list-rules``.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def main() -> int:
    sys.path.insert(0, str(REPO))
    from tools.dlint.__main__ import main as dlint_main

    return dlint_main(sys.argv[1:])


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python3
"""The machine-readable bench trajectory: BENCH_HISTORY.jsonl + tables.

Two jobs:

1. **History file** (``BENCH_HISTORY.jsonl``): one JSON line per bench
   run with the headline keys (``HISTORY_KEYS``) — ``make bench`` appends
   via ``python bench.py --history BENCH_HISTORY.jsonl``, and
   ``solver slo --history`` evaluates trend rules against it
   (``obs.slo.evaluate_history``), so the bench trajectory is a dataset
   instead of N loose BENCH_r*.json artifacts. ``--rebuild`` seeds (or
   re-derives) the file from the committed BENCH_r*.json captures.

2. **Table** (default): the committed rounds side by side with the
   wire-condition diagnostic (``tiny_put_ms``, recorded since round 4) —
   the headline numbers ride a tunneled TPU whose per-operation wire cost
   swings run to run, so a regression in the ENGINE must stay
   distinguishable from a slow tunnel day.

    python tools/bench_history.py [repo_root]            # table
    python tools/bench_history.py --rebuild [repo_root]  # reseed the JSONL
"""

from __future__ import annotations

import json
import re
import sys
import time
from pathlib import Path

# The committed-format history line: one value per key per bench run
# (missing keys simply absent). Trend rules over these live in
# obs.slo.HISTORY_TREND_RULES; adding a key here is additive and never
# breaks old lines.
HISTORY_KEYS = (
    "platform",
    "value",
    "warm_tick_ms",
    "moe_warm_tick_ms",
    "vs_baseline",
    "placements_per_sec",
    "pipelined_placements_per_sec",
    "scenario_batch_placements_per_sec",
    "tiny_put_ms",
    "scheduler_events_per_sec",
    "scheduler_p99_ms",
    "gateway_events_per_sec_100f_4w",
    "gateway_scaling_100f_4w",
    "combine_events_per_sec_100f",
    "combine_vs_per_shard_100f",
    "combine_p99_ms_100f",
    "combine_warm_phase_compiles",
    "combine_bucket_occupancy",
    "combine_padding_waste",
    "overload_max_sustainable_eps",
    "overload_plateau_ratio",
    "spec_hit_rate",
    "spec_p99_on_ms",
    "obs_overhead_pct",
    "conv_ipm_iters_to_certify",
    "conv_pdhg_iters_to_certify",
    "slo_overhead_pct",
    "slo_alerts_fired",
    "cold_process_ms",
    "cold_process_cached_ms",
    "fleet_scale_certified_m_max",
    "compile_warm_phase_count",
    "compile_cache_hit_rate",
    "compile_overhead_pct",
    "memory_overhead_pct",
    "memory_leak_bytes",
    "mem_calibration_ratio_ipm",
    "mem_calibration_ratio_pdhg",
)


def history_record(payload: dict, round_no=None, captured_at=None) -> dict:
    """One committed-format history line from a bench payload."""
    rec: dict = {}
    if round_no is not None:
        rec["round"] = round_no
    rec["captured_at"] = (
        captured_at
        if captured_at is not None
        else time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    )
    for key in HISTORY_KEYS:
        v = payload.get(key)
        if isinstance(v, (int, float, str, bool)) and v is not None:
            rec[key] = v
    return rec


def append_history(payload: dict, path, round_no=None) -> dict:
    """Append one history line for this run (``bench.py --history``)."""
    rec = history_record(payload, round_no=round_no)
    path = Path(path)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec


def load_history(path) -> list:
    """History rows, oldest first (the order lines were appended)."""
    rows = []
    for ln in Path(path).read_text(encoding="utf-8").splitlines():
        ln = ln.strip()
        if ln:
            rows.append(json.loads(ln))
    return rows


def rebuild_history(root: Path, out_path) -> int:
    """Re-derive BENCH_HISTORY.jsonl from the committed BENCH_r*.json
    artifacts (deterministic: captured_at comes from the artifact when
    present, else is omitted — a rebuild never invents timestamps)."""
    rows = []
    for r, payload in load_rounds(root):
        if "error" in payload and "metric" not in payload:
            continue
        rec = history_record(
            payload, round_no=r, captured_at=payload.get("captured_at", "")
        )
        if not rec.get("captured_at"):
            rec.pop("captured_at", None)
        rows.append(rec)
    Path(out_path).write_text(
        "".join(json.dumps(r, sort_keys=True) + "\n" for r in rows),
        encoding="utf-8",
    )
    return len(rows)


def load_rounds(root: Path):
    rounds = []
    for p in sorted(root.glob("BENCH_r*.json")):
        m = re.match(r"BENCH_r(\d+)\.json", p.name)
        if not m:
            continue
        try:
            rec = json.loads(p.read_text())
        except json.JSONDecodeError:
            rounds.append((int(m.group(1)), {"error": "unparseable artifact"}))
            continue
        # Driver artifacts wrap the bench line: find the parsed payload.
        payload = rec.get("parsed") if isinstance(rec, dict) else None
        if payload is None and isinstance(rec, dict) and "metric" in rec:
            payload = rec
        if not isinstance(payload, dict):
            tail = (
                payload
                or (rec.get("tail") if isinstance(rec, dict) else None)
                or "no payload"
            )
            payload = {"error": " ".join(str(tail).split())[:80]}
        rounds.append((int(m.group(1)), payload))
    return sorted(rounds, key=lambda t: t[0])


def fmt(v, suffix=""):
    if v is None:
        return "—"
    if isinstance(v, float):
        # %g keeps sub-millisecond values (tiny_put_ms — the wire-condition
        # diagnostic this tool exists to surface) distinguishable instead of
        # collapsing every run to "0.0", while big numbers stay compact.
        return f"{v:.4g}{suffix}"
    return f"{v}{suffix}"


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == "--rebuild":
        root = (
            Path(argv[1]) if len(argv) > 1
            else Path(__file__).resolve().parents[1]
        )
        n = rebuild_history(root, root / "BENCH_HISTORY.jsonl")
        print(f"rebuilt {root / 'BENCH_HISTORY.jsonl'}: {n} round(s)")
        return 0
    root = Path(sys.argv[1]) if len(sys.argv) > 1 else Path(__file__).resolve().parents[1]
    rounds = load_rounds(root)
    if not rounds:
        print("no BENCH_r*.json artifacts found")
        return 1

    cols = [
        ("value", "cold ms"),
        ("warm_tick_ms", "warm ms"),
        ("moe_warm_tick_ms", "moe warm ms"),
        ("placements_per_sec", "plc/s"),
        ("pipelined_placements_per_sec", "pipe/s"),
        ("scenario_batch_placements_per_sec", "scen/s"),
        ("vs_baseline", "x HiGHS"),
        ("tiny_put_ms", "wire ms/op"),
    ]
    header = f"{'round':>5s} {'platform':>14s} " + " ".join(
        f"{label:>11s}" for _, label in cols
    )
    print(header)
    print("-" * len(header))
    for r, payload in rounds:
        if "error" in payload and "metric" not in payload:
            excerpt = " ".join(str(payload["error"]).split())[:70]
            print(f"{r:5d} {'FAILED':>14s}  {excerpt}")
            continue
        platform = payload.get("platform", "?")
        row = f"{r:5d} {platform:>14s} " + " ".join(
            f"{fmt(payload.get(key)):>11s}" for key, _ in cols
        )
        print(row)
        if payload.get("error") or payload.get("tpu_error"):
            print(f"      note: {payload.get('error') or payload.get('tpu_error')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python3
"""Tabulate the committed BENCH_r*.json driver artifacts across rounds.

The headline numbers ride a tunneled TPU whose per-operation wire cost
swings run to run, so raw wall-clocks across rounds are not comparable.
This prints them side by side with the wire-condition diagnostic
(``tiny_put_ms``, recorded since round 4) so a regression in the ENGINE is
distinguishable from a slow tunnel day.

    python tools/bench_history.py [repo_root]
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path


def load_rounds(root: Path):
    rounds = []
    for p in sorted(root.glob("BENCH_r*.json")):
        m = re.match(r"BENCH_r(\d+)\.json", p.name)
        if not m:
            continue
        try:
            rec = json.loads(p.read_text())
        except json.JSONDecodeError:
            rounds.append((int(m.group(1)), {"error": "unparseable artifact"}))
            continue
        # Driver artifacts wrap the bench line: find the parsed payload.
        payload = rec.get("parsed") if isinstance(rec, dict) else None
        if payload is None and isinstance(rec, dict) and "metric" in rec:
            payload = rec
        if not isinstance(payload, dict):
            tail = (
                payload
                or (rec.get("tail") if isinstance(rec, dict) else None)
                or "no payload"
            )
            payload = {"error": " ".join(str(tail).split())[:80]}
        rounds.append((int(m.group(1)), payload))
    return sorted(rounds, key=lambda t: t[0])


def fmt(v, suffix=""):
    if v is None:
        return "—"
    if isinstance(v, float):
        # %g keeps sub-millisecond values (tiny_put_ms — the wire-condition
        # diagnostic this tool exists to surface) distinguishable instead of
        # collapsing every run to "0.0", while big numbers stay compact.
        return f"{v:.4g}{suffix}"
    return f"{v}{suffix}"


def main() -> int:
    root = Path(sys.argv[1]) if len(sys.argv) > 1 else Path(__file__).resolve().parents[1]
    rounds = load_rounds(root)
    if not rounds:
        print("no BENCH_r*.json artifacts found")
        return 1

    cols = [
        ("value", "cold ms"),
        ("warm_tick_ms", "warm ms"),
        ("moe_warm_tick_ms", "moe warm ms"),
        ("placements_per_sec", "plc/s"),
        ("pipelined_placements_per_sec", "pipe/s"),
        ("scenario_batch_placements_per_sec", "scen/s"),
        ("vs_baseline", "x HiGHS"),
        ("tiny_put_ms", "wire ms/op"),
    ]
    header = f"{'round':>5s} {'platform':>14s} " + " ".join(
        f"{label:>11s}" for _, label in cols
    )
    print(header)
    print("-" * len(header))
    for r, payload in rounds:
        if "error" in payload and "metric" not in payload:
            excerpt = " ".join(str(payload["error"]).split())[:70]
            print(f"{r:5d} {'FAILED':>14s}  {excerpt}")
            continue
        platform = payload.get("platform", "?")
        row = f"{r:5d} {platform:>14s} " + " ".join(
            f"{fmt(payload.get(key)):>11s}" for key, _ in cols
        )
        print(row)
        if payload.get("error") or payload.get("tpu_error"):
            print(f"      note: {payload.get('error') or payload.get('tpu_error')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

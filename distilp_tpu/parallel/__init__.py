"""Mesh distribution and the ICI/DCN communication cost model."""

from .mesh import (
    NODE_AXIS,
    make_mesh,
    pad_cap_to_mesh,
    shard_state,
    solve_sweep_sharded,
    state_shardings,
)

__all__ = [
    "NODE_AXIS",
    "make_mesh",
    "shard_state",
    "state_shardings",
    "pad_cap_to_mesh",
    "solve_sweep_sharded",
]

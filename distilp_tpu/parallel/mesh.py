"""Device-mesh utilities for distributing the HALDA search.

The parallel axis of this framework is the branch-and-bound frontier: every
node's LP relaxation is independent, so the batched IPM shards cleanly along
the node dimension of the ``SearchState`` arrays. The round function itself is
an ordinary jitted program — GSPMD partitions the vmapped Cholesky solves
across the mesh and inserts the collectives (argmin/argsort reductions for
incumbent and compaction) over ICI.

This replaces, TPU-natively, what a host-cluster MILP sweep would do with a
work queue: the "queue" is a sharded array, the "workers" are mesh devices,
and the synchronization is XLA collectives instead of RPC.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis: str = NODE_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` available devices."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices but only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def state_shardings(mesh: Mesh, state) -> "jax.tree_util.PyTreeDef":
    """NamedShardings for a SearchState: frontier arrays split along the node
    axis, incumbent scalars and per-k reporting replicated."""
    node_sharded = NamedSharding(mesh, P(NODE_AXIS))
    replicated = NamedSharding(mesh, P())

    def spec(path_leaf):
        name, leaf = path_leaf
        if name in {
            "node_lo", "node_hi", "node_kidx", "node_bound", "active",
            "node_v", "node_y", "node_z", "node_f", "node_warm",
        }:
            return node_sharded
        return replicated

    fields = state._fields
    return type(state)(*[spec((name, getattr(state, name))) for name in fields])


def shard_state(state, mesh: Mesh):
    """Place a SearchState onto the mesh with frontier arrays node-sharded."""
    shardings = state_shardings(mesh, state)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s), state, shardings,
        is_leaf=lambda x: isinstance(x, jax.Array),
    )


def pad_cap_to_mesh(cap: int, mesh: Mesh) -> int:
    """Round the frontier capacity up to a multiple of the mesh size."""
    n = mesh.devices.size
    return int(-(-cap // n) * n)


def solve_sweep_sharded(
    arrays,
    kWs: Sequence,
    coeffs,
    mesh: Mesh,
    mip_gap: float = 1e-3,
    ipm_iters: Optional[int] = None,
    max_rounds: int = 48,
    beam: Optional[int] = None,
    node_cap: Optional[int] = None,
    per_k: bool = False,
    ipm_warm_iters: Optional[int] = None,
    lp_backend: Optional[str] = None,
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
):
    """Run the fused B&B sweep with the frontier sharded across ``mesh``.

    Same single-dispatch program as ``solver.backend_jax.solve_sweep_jax``;
    the only difference is input placement — the frontier arrays enter
    node-sharded and GSPMD partitions the batched IPM along the node axis,
    turning the incumbent/compaction reductions into ICI collectives.

    ``beam``/``ipm_iters``/``ipm_warm_iters``/``node_cap`` default like the
    unsharded backend (``default_search_params``/``_resolve_search_params``),
    except the beam — and the root round's n_k-row batch — are rounded up
    to a multiple of the mesh size so every device solves the same number
    of frontier rows (GSPMD shards the IPM batch along the node axis), and
    the cap to a multiple likewise.

    ``per_k`` switches to the per-k pruning regime (every feasible k closes
    its own certificate; read the per-k assignments off the returned
    state's ``per_k_w/n/y`` and bounds via ``backend_jax._per_k_bound``) —
    the sharded counterpart of ``halda_solve_per_k``.
    """
    import jax.numpy as jnp

    from ..solver.backend_jax import (
        BDTYPE,
        DECOMP_STEPS_COLD,
        _init_state,
        _seed_root_bounds,
        _solve_fused,
        _sweep_data,
        _resolve_search_params,
        build_standard_form,
        rounding_data,
    )

    M = arrays.layout.M
    feasible = [(k, W) for (k, W) in kWs if W >= M]
    if not feasible:
        raise RuntimeError("No feasible MILP found for any k.")

    sf = build_standard_form(arrays, coeffs, feasible)
    # The shared resolution rule (incl. the per-k cap/beam scaling — a
    # frontier sized for one winner spills under per-k pressure and a
    # spilled node floors its k's certificate), then mesh-align: cap and
    # beam round up to a multiple of the mesh size so every device solves
    # the same number of frontier rows.
    # mesh_shards/pdhg_dtype stay default here: this path already owns the
    # device mesh along the NODE axis (GSPMD over the frontier); the row
    # mesh of ops/meshlp.py is the orthogonal, single-instance engine.
    (
        cap, d_beam, d_iters, d_warm_iters, _, engine, _shards, _dt,
    ) = _resolve_search_params(
        sf.moe, len(sf.ks), node_cap, beam, ipm_iters, max_rounds,
        per_k=per_k, ipm_warm_iters=ipm_warm_iters,
        lp_backend=lp_backend, pdhg_iters=pdhg_iters, M=M,
    )
    cap = pad_cap_to_mesh(max(cap, 2 * len(sf.ks)), mesh)
    beam = min(pad_cap_to_mesh(d_beam, mesh), cap)
    # The root round solves exactly the n_k roots; pad its batch to the
    # mesh size so it keeps the even-rows-per-device sharding too.
    root_beam = min(pad_cap_to_mesh(len(sf.ks), mesh), cap)
    ipm_iters = d_iters

    rd = rounding_data(coeffs, arrays.moe)
    data = _sweep_data(sf, rd)
    gap = jnp.asarray(mip_gap, BDTYPE)

    state = _init_state(sf, cap=cap)
    if sf.moe:
        # Same Lagrangian decomposition root bounds + primal seeding as the
        # single-chip packed path: without them, wide-expert MoE instances
        # cannot close the structural LP root gap and the sharded sweep
        # would silently miss the certificate the single-chip path earns.
        state, _, _, _ = _seed_root_bounds(
            state,
            rd,
            jnp.asarray(sf.ks, BDTYPE),
            jnp.asarray(sf.Ws, BDTYPE),
            jnp.asarray(sf.obj_const, BDTYPE),
            sf.A.shape[2],
            M,
            True,
            max(W for _, W in feasible),
            int(arrays.moe.E),
            DECOMP_STEPS_COLD,
        )
    state = shard_state(state, mesh)
    replicated = NamedSharding(mesh, P())
    data = jax.tree.map(lambda x: jax.device_put(x, replicated), data)

    with mesh:
        fused_kw = {}
        if pdhg_restart_tol is not None:
            fused_kw["pdhg_restart_tol"] = pdhg_restart_tol
        state = _solve_fused(
            data,
            state,
            gap,
            ipm_iters=ipm_iters,
            max_rounds=max_rounds,
            beam=beam,
            moe=sf.moe,
            per_k=per_k,
            ipm_warm_iters=d_warm_iters,
            root_beam=root_beam,
            lp_backend=engine,
            **fused_kw,
        )
    return state, sf

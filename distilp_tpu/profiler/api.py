"""High-level profiler API (reference /root/reference/src/distilp/profiler/api.py).

Both entry points accept what the reference accepts (a HF repo id) plus
offline-first sources: a local ``config.json`` path, a directory containing
one, a raw config dict, or an :class:`HFConfig`.
"""

from __future__ import annotations

from typing import List, Optional

from ..common import DeviceProfile, ModelProfileSplit
from .analytic import profile_model_split
from .hfconfig import ConfigSource, load_config


def profile_model(
    source: ConfigSource,
    batch_sizes: Optional[List[int]] = None,
    sequence_length: int = 512,
) -> ModelProfileSplit:
    """Analytically profile a model (reference api.py:12-51).

    Args:
        source: HF repo id, config.json path/dir, config dict, or HFConfig.
        batch_sizes: batch sizes to tabulate (default [1, 2, 4, 8]).
        sequence_length: profiling sequence length (default 512).
    """
    batches = batch_sizes or [1, 2, 4, 8]
    cfg = load_config(source)
    return profile_model_split(
        cfg,
        B=batches[0],
        L=sequence_length,
        bs_list=batches,
    )


def profile_device(
    source: ConfigSource,
    max_batch_exp: int = 6,
    is_head: bool = True,
    raw_info=None,
) -> DeviceProfile:
    """Microbenchmark this host/accelerator for the given model's shapes
    (reference api.py:54-82). ``raw_info``: see ``device.profile_device``."""
    from .device import profile_device as _profile_device

    cfg = load_config(source)
    return _profile_device(
        cfg, max_batch_exp=max_batch_exp, is_head=is_head, raw_info=raw_info
    )

"""HF ``config.json`` adapter: architecture registry + normalized accessors.

Replaces the reference's mlx_lm-backed adapter
(/root/reference/src/distilp/profiler/models.py) with a pure-metadata design:
instead of validating the config through per-arch ``mlx_lm ModelArgs`` classes
and later instantiating a module tree to walk, each supported architecture is
described by a small :class:`ArchSpec` record stating how its decoder blocks
are laid out (attention kind, MLP projection set, MoE structure). The analytic
profiler consumes the spec directly, so no model framework and no macOS/Metal
dependency is needed.

Accessor semantics match the reference adapter exactly (models.py:182-364):
e.g. ``head_dim()`` returns ``hidden_size // num_attention_heads`` for the
llama/phi3/mistral/qwen2/qwen2_moe/deepseek_v3/olmo3 families and the config's
explicit ``head_dim`` otherwise (models.py:210-222), and
``max_position_embeddings(default)`` falls back to the profiling sequence
length for families whose ModelArgs lacks the field (models.py:194-207).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, Literal, Optional, Sequence, Union

AttentionKind = Literal["standard", "mla"]  # standard = MHA/GQA chosen by head counts
MoERoutedLayout = Literal["switch_glu", "fused_gate_up"]


@dataclass(frozen=True)
class MoESpec:
    """How an architecture's sparse-MoE blocks are shaped.

    ``routed_layout`` mirrors what the reference's module-tree walk would have
    found: ``switch_glu`` = separate gate/up/down expert projections (3 GEMMs,
    no explicit activation FLOPs term — reference profiler/model.py:195-256);
    ``fused_gate_up`` = fused gate_up + down projections discovered via the
    fallback pattern detector, which adds an activation term
    (profiler/model.py:319-355, the gpt-oss shape).
    """

    experts_key: str  # config key holding the routed-expert count
    topk_key: str = "num_experts_per_tok"
    routed_layout: MoERoutedLayout = "switch_glu"
    moe_intermediate_key: Optional[str] = "moe_intermediate_size"
    shared_experts_key: Optional[str] = None  # deepseek: "n_shared_experts"
    # Families whose shared expert is structural rather than configured:
    # qwen2_moe always has exactly one (the HF config publishes only its
    # size, shared_expert_intermediate_size, never a count).
    implicit_shared: int = 0
    layer_freq_key: Optional[str] = None  # qwen3_moe: decoder_sparse_step
    mlp_only_layers_key: Optional[str] = None
    first_k_dense_key: Optional[str] = None


@dataclass(frozen=True)
class ArchSpec:
    """Decoder-block layout facts for one model family."""

    name: str
    head_dim_from_config: bool  # False => hidden_size // num_attention_heads
    has_max_position_embeddings: bool
    attention: AttentionKind = "standard"
    # Dense-MLP projection names; totals match the reference walker whether
    # the family uses 3 separate GLU projections or a fused gate_up
    # (profiler/model.py:461-492).
    mlp_projections: Sequence[str] = ("gate_proj", "up_proj", "down_proj")
    moe: Optional[MoESpec] = None


_GLU3 = ("gate_proj", "up_proj", "down_proj")
_FUSED = ("gate_up_proj", "down_proj")

ARCHS: Dict[str, ArchSpec] = {
    "llama": ArchSpec("llama", False, False),
    # "mistral" is the Mixtral family in the reference registry
    # (models.py:9,93-95): MoE with routed experts sized by intermediate_size.
    # Divergence from reference (documented): the reference walker never
    # descends into Mixtral's `block_sparse_moe` module (its name is not in
    # the MLP-name list, profiler/model.py:136), silently producing
    # attention-only profiles; we profile the experts properly.
    "mistral": ArchSpec(
        "mistral",
        False,
        False,
        moe=MoESpec(experts_key="num_local_experts", moe_intermediate_key=None),
    ),
    "qwen2": ArchSpec("qwen2", False, True),
    "qwen2_moe": ArchSpec(
        "qwen2_moe",
        False,
        False,
        moe=MoESpec(experts_key="num_experts", implicit_shared=1),
    ),
    "qwen3": ArchSpec("qwen3", True, True),
    "qwen3_moe": ArchSpec(
        "qwen3_moe",
        True,
        True,
        moe=MoESpec(
            experts_key="num_experts",
            layer_freq_key="decoder_sparse_step",
            mlp_only_layers_key="mlp_only_layers",
        ),
    ),
    "gemma2": ArchSpec("gemma2", True, False),
    "phi3": ArchSpec("phi3", False, True, mlp_projections=_FUSED),
    "gpt_oss": ArchSpec(
        "gpt_oss",
        True,
        False,
        moe=MoESpec(
            experts_key="num_local_experts",
            routed_layout="fused_gate_up",
            moe_intermediate_key=None,
        ),
    ),
    "deepseek_v3": ArchSpec(
        "deepseek_v3",
        False,
        True,
        attention="mla",
        moe=MoESpec(
            experts_key="n_routed_experts",
            shared_experts_key="n_shared_experts",
            layer_freq_key="moe_layer_freq",
            first_k_dense_key="first_k_dense_replace",
        ),
    ),
    "olmo3": ArchSpec("olmo3", False, True),
    "glm4": ArchSpec("glm4", True, True, mlp_projections=_FUSED),
}

# HF model_type -> arch name (reference models.py:41-75).
MODEL_TYPE_ALIASES: Dict[str, str] = {
    "llama": "llama",
    "llama2": "llama",
    "llama-2": "llama",
    "llama3": "llama",
    "llama-3": "llama",
    "mistral": "mistral",
    "mixtral": "mistral",
    "qwen2": "qwen2",
    "qwen-2": "qwen2",
    "qwen2_moe": "qwen2_moe",
    "qwen2-moe": "qwen2_moe",
    "qwen3": "qwen3",
    "qwen-3": "qwen3",
    "qwen3_moe": "qwen3_moe",
    "qwen3-moe": "qwen3_moe",
    "gemma": "gemma2",
    "gemma2": "gemma2",
    "phi3": "phi3",
    "gpt_oss": "gpt_oss",
    "deepseek_v3": "deepseek_v3",
    "deepseek-v3": "deepseek_v3",
    "olmo3": "olmo3",
    "olmo-3": "olmo3",
    "glm4": "glm4",
    "glm-4": "glm4",
}


class HFConfig:
    """A parsed HF config with arch-normalized accessors.

    ``raw`` is the verbatim ``config.json`` dict (the reference keeps the same
    attribute for quantization parsing and MLA field probing,
    models.py:151-152).
    """

    def __init__(self, raw: Dict[str, Any], arch: Optional[str] = None):
        self.raw = dict(raw)
        name = arch or resolve_arch(raw)
        if name not in ARCHS:
            raise ValueError(f"Unsupported architecture {name!r}")
        self.spec: ArchSpec = ARCHS[name]

    # -- helpers ----------------------------------------------------------
    def _get(self, key: str, default: Any = None) -> Any:
        value = self.raw.get(key)
        return default if value is None else value

    def _require(self, key: str) -> Any:
        if self.raw.get(key) is None:
            raise KeyError(
                f"config.json for {self.spec.name!r} is missing required key {key!r}"
            )
        return self.raw[key]

    # -- core accessors (reference models.py:182-235) ---------------------
    def model_type(self) -> str:
        return str(self._get("model_type", self.spec.name))

    def hidden_size(self) -> int:
        return int(self._require("hidden_size"))

    def num_hidden_layers(self) -> int:
        return int(self._require("num_hidden_layers"))

    def intermediate_size(self) -> int:
        return int(self._require("intermediate_size"))

    def num_attention_heads(self) -> int:
        return int(self._require("num_attention_heads"))

    def num_key_value_heads(self) -> int:
        # Fall back to num_attention_heads (reference models.py:224-229).
        value = self.raw.get("num_key_value_heads")
        return int(value) if value is not None else self.num_attention_heads()

    def vocab_size(self) -> int:
        return int(self._require("vocab_size"))

    def head_dim(self) -> int:
        if self.spec.head_dim_from_config:
            return int(self._require("head_dim"))
        return self.hidden_size() // self.num_attention_heads()

    def max_position_embeddings(self, default: int) -> int:
        if self.spec.has_max_position_embeddings:
            return int(self._get("max_position_embeddings", default))
        return int(default)

    # -- MoE accessors (reference models.py:237-300) -----------------------
    def n_routed_experts(self) -> int:
        if self.spec.moe is None:
            return 0
        return int(self._get(self.spec.moe.experts_key, 0))

    def num_experts_tok(self) -> int:
        if self.spec.moe is None:
            raise ValueError(
                f"num_experts_tok is not applicable for {self.spec.name}"
            )
        return int(self._get(self.spec.moe.topk_key, 0))

    def moe_layer_freq(self) -> int:
        moe = self.spec.moe
        if moe is not None and moe.layer_freq_key is not None:
            return int(self._get(moe.layer_freq_key, 1))
        return 1

    def mlp_only_layers(self) -> list:
        moe = self.spec.moe
        if moe is not None and moe.mlp_only_layers_key is not None:
            return list(self._get(moe.mlp_only_layers_key, []))
        return []

    def moe_intermediate(self) -> int:
        moe = self.spec.moe
        if moe is not None and moe.moe_intermediate_key is not None:
            return int(self._get(moe.moe_intermediate_key, 0))
        # Families without a dedicated MoE size use the dense FFN size
        # (reference models.py:263-273).
        return self.intermediate_size()

    def shared_intermediate(self) -> int:
        # qwen2_moe publishes shared_expert_intermediate_size
        # (reference models.py:275-280); everyone else reuses the MoE size.
        if self.spec.name == "qwen2_moe":
            return int(self._get("shared_expert_intermediate_size", 0))
        return self.moe_intermediate()

    def n_shared(self) -> int:
        moe = self.spec.moe
        if moe is not None and moe.shared_experts_key is not None:
            return int(self._get(moe.shared_experts_key, 0))
        return moe.implicit_shared if moe is not None else 0

    def first_k_dense_replace(self) -> int:
        moe = self.spec.moe
        if moe is not None and moe.first_k_dense_key is not None:
            return int(self._get(moe.first_k_dense_key, 0))
        return 0

    # -- MLA accessors (reference models.py:306-324) -----------------------
    def is_mla(self) -> bool:
        # Same probe as the reference walker (profiler/model.py:503):
        # presence of the low-rank attention fields in the raw config.
        return all(
            self.raw.get(k) is not None
            for k in ("q_lora_rank", "qk_nope_head_dim", "qk_rope_head_dim")
        )

    def q_lora_rank(self) -> int:
        return int(self._get("q_lora_rank", 0))

    def kv_lora_rank(self) -> int:
        return int(self._get("kv_lora_rank", 0))

    def qk_rope_head_dim(self) -> int:
        return int(self._get("qk_rope_head_dim", 0))

    def qk_nope_head_dim(self) -> int:
        return int(self._get("qk_nope_head_dim", 0))

    def v_head_dim(self) -> int:
        return int(self._get("v_head_dim", 0))


def resolve_arch(config: Dict[str, Any]) -> str:
    """Map ``config.model_type`` to an arch name (reference models.py:402-422)."""
    model_type = config.get("model_type")
    if not model_type:
        raise ValueError("config.json is missing 'model_type'")
    key = str(model_type).strip().replace(" ", "").lower()
    arch = MODEL_TYPE_ALIASES.get(key)
    if arch is None:
        raise ValueError(f"Unsupported or unknown model_type {model_type!r}")
    return arch


ConfigSource = Union[str, os.PathLike, Dict[str, Any], HFConfig]


def load_config(source: ConfigSource) -> HFConfig:
    """Load a model config from a dict, a config.json path, a directory
    containing one, or a HuggingFace repo id (network path, optional).

    The offline-first ordering means tests and air-gapped deployments never
    touch the network; the hub download mirrors the reference's
    ``load_config_from_repo`` (models.py:367-399).
    """
    if isinstance(source, HFConfig):
        return source
    if isinstance(source, dict):
        return HFConfig(source)

    path = Path(source)
    if path.is_dir():
        path = path / "config.json"
    if path.is_file():
        with open(path, "r") as f:
            return HFConfig(json.load(f))

    # Not a local path: treat as a HF repo id.
    try:
        from huggingface_hub import hf_hub_download  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            f"{source!r} is not a local config path and huggingface_hub is "
            "not installed; pass a config dict or a path to config.json"
        ) from e
    try:
        config_path = hf_hub_download(repo_id=str(source), filename="config.json")
    except Exception as e:
        raise RuntimeError(
            f"Unable to download config from HuggingFace Hub for {source!r}: {e}"
        ) from e
    with open(config_path, "r") as f:
        return HFConfig(json.load(f))


def load_config_from_repo(repo_id: str) -> HFConfig:
    """Reference-parity alias (models.py:367) — also accepts local paths."""
    return load_config(repo_id)

"""Raw device-info schema produced by the microbenchmarks.

Wire-compatible superset of the reference's ``DeviceInfo`` tree
(/root/reference/src/distilp/profiler/datatypes.py:5-123), with two changes:

- ``GPUInfo.name`` admits ``"tpu"`` — the accelerator this framework targets.
- ``CPUFeatures`` actually has the ``AVX2``/``NEON`` fields the reference's
  x86 probe tries to set (its schema lacks them, so the probe raises on
  pydantic v2 — reference profiler/device.py:53,58 vs datatypes.py:16-21;
  fixed here).
- ``InterconnectInfo`` is new: measured/derived ICI-DCN characteristics that
  replace the reference's hand-edited per-device ``t_comm`` scalar
  (common/device.py:50).
"""

from __future__ import annotations

from typing import Dict, Literal

from pydantic import BaseModel, Field


class CPUTopology(BaseModel):
    packages: int = 1
    cores: int = 0
    threads: int = 0


class CPUClock(BaseModel):
    base: float = 0.0  # MHz
    max: float = 0.0  # MHz


class CPUFeatures(BaseModel):
    AVX: bool = False
    AVX2: bool = False
    FMA: bool = False
    BF16: bool = False
    SSE: bool = False
    NEON: bool = False


class CPUCache(BaseModel):
    l1d: int = 0
    l1i: int = 0
    l2: int = 0
    l3: int = 0


class Stat(BaseModel):
    """Distribution of one microbenchmark's timed samples (seconds).

    The reference prints p50/p95/p99 at debug>=1 and then discards them
    (/root/reference/src/distilp/profiler/profiler/device.py:188-197); here
    the spread is carried on the profile so a consumer can judge whether a
    throughput entry is trustworthy. ``valid=False`` marks a measurement
    whose net time was within the dispatch round-trip noise — its derived
    throughput is NOT stored (the table keeps the 0.0 "no table" sentinel
    instead of an absurd number).
    """

    samples: int = 0
    min: float = 0.0
    p50: float = 0.0
    p95: float = 0.0
    p99: float = 0.0
    max: float = 0.0
    mean: float = 0.0
    stddev: float = 0.0
    baseline: float = 0.0  # subtracted dispatch/fetch round-trip floor
    valid: bool = True


class Batches(BaseModel):
    b_1: float = 0.0
    b_2: float = 0.0
    b_4: float = 0.0
    b_8: float = 0.0
    b_16: float = 0.0
    b_32: float = 0.0
    b_64: float = 0.0
    b_128: float = 0.0
    b_256: float = 0.0
    b_512: float = 0.0


class Benchmarks(BaseModel):
    f64: Batches = Field(default_factory=Batches)
    f32: Batches = Field(default_factory=Batches)
    tf32: Batches = Field(default_factory=Batches)
    fp16: Batches = Field(default_factory=Batches)
    bf16: Batches = Field(default_factory=Batches)
    u32: Batches = Field(default_factory=Batches)
    u16: Batches = Field(default_factory=Batches)
    u8: Batches = Field(default_factory=Batches)
    i32: Batches = Field(default_factory=Batches)
    i16: Batches = Field(default_factory=Batches)
    i8: Batches = Field(default_factory=Batches)


class SystemMemory(BaseModel):
    can_swap: int = 0
    total: float = 0.0
    available: float = 0.0
    total_swap: float = 0.0
    available_swap: float = 0.0
    cpu_read_cold_bw: float = 0.0
    cpu_read_warm_bw: float = 0.0
    cpu_write_cold_bw: float = 0.0
    cpu_write_warm_bw: float = 0.0
    memcpy_delay: float = 0.0  # ms


class DiskInfo(BaseModel):
    read: float = 0.0  # bytes/s
    write: float = 0.0  # bytes/s
    random: float = 0.0  # bytes/s


class CPUInfo(BaseModel):
    vendor: str = ""
    model: str = ""
    arch: str = ""
    topology: CPUTopology = Field(default_factory=CPUTopology)
    clock: CPUClock = Field(default_factory=CPUClock)
    cache: CPUCache = Field(default_factory=CPUCache)
    features: CPUFeatures = Field(default_factory=CPUFeatures)
    benchmarks: Benchmarks = Field(default_factory=Benchmarks)
    memcpy_hot: float = 0.0
    memcpy_cold: float = 0.0


class GPUMemory(BaseModel):
    name: str = ""
    free: float = 0
    total: float = 0
    read_bw: float = 0.0  # host->device bytes/s
    write_bw: float = 0.0  # device->host bytes/s
    read_write_bw: float = 0.0
    two_read_one_write_bw: float = 0.0
    vram_to_compute: float = 0.0  # device-memory streaming bytes/s
    unified_memory: bool = False
    # Where ``total``/``free`` came from: "memory_stats" (runtime-reported),
    # "table:<device kind>" (static per-chip HBM table), "env:DPERF_HBM_BYTES"
    # (operator override), or "unknown" (unlisted kind — capacity is 0 and
    # must not be trusted).
    capacity_source: str = ""


class GPUInfo(BaseModel):
    name: Literal["cuda", "metal", "tpu", ""] = ""
    memory: GPUMemory = Field(default_factory=GPUMemory)
    benchmarks: Benchmarks = Field(default_factory=Benchmarks)
    device_kind: str = ""  # e.g. "TPU v5e"
    num_devices: int = 0  # local devices visible to this host


class InterconnectInfo(BaseModel):
    """Measured/derived inter-device link characteristics (new vs reference).

    On a multi-device mesh these come from timed collectives over ICI; on a
    single-device host they stay 0 and ``t_comm`` falls back to the profile
    scalar, exactly as the reference behaves (profiler/device.py:719).
    """

    num_devices: int = 0
    num_slices: int = 1
    ici_allreduce_latency_s: float = 0.0  # small-message all-reduce time
    ici_bandwidth: float = 0.0  # bytes/s per link, large-message all-gather
    dcn_latency_s: float = 0.0  # cross-slice small-message latency (0 = unknown)
    dcn_bandwidth: float = 0.0  # bytes/s across slices (0 = unknown)
    topology: str = ""  # e.g. "2x4" when coords are available
    # Where the numbers came from (VERDICT r5 item 8): collectives timed on
    # a VIRTUAL host-platform mesh (xla_force_host_platform_device_count)
    # are fiction relative to any real link, and must not masquerade as
    # measured ICI/DCN characteristics once a profile is saved to disk.
    # "unmeasured" = never probed (the <2-device fallback), "virtual" =
    # probed over host-platform virtual devices, "measured" = probed over
    # real accelerator devices, "config" = hand-written fixture values.
    provenance: Literal["unmeasured", "virtual", "measured", "config"] = (
        "unmeasured"
    )


class DeviceInfo(BaseModel):
    os: str = ""  # platform.system().lower() or "" (unknown)
    cpu: CPUInfo = Field(default_factory=CPUInfo)
    gpu: GPUInfo = Field(default_factory=GPUInfo)
    disk: DiskInfo = Field(default_factory=DiskInfo)
    memory: SystemMemory = Field(default_factory=SystemMemory)
    interconnect: InterconnectInfo = Field(default_factory=InterconnectInfo)
    # Timing spread of each microbenchmark, keyed "<area>.<detail>" (e.g.
    # "gemm.tpu.bf16.b_8", "mem.cpu_read_warm"): the raw-measurement
    # observability the reference prints at debug>=1 and throws away.
    stats: Dict[str, Stat] = Field(default_factory=dict)

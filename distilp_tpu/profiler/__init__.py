"""TPU-native profiler: device microbenchmarks + analytic model profiling.

Capability parity with the reference profiler package
(/root/reference/src/distilp/profiler/), redesigned for this stack:

- Model profiling is **config-driven**: per-layer FLOPs/bytes are derived
  from the HF ``config.json`` alone via a per-architecture layout registry
  (``archs.py``), instead of instantiating an ``mlx_lm`` module tree and
  pattern-matching module names (reference profiler/model.py:69-781). Same
  numbers, no macOS/Metal dependency, no network requirement.
- Device profiling runs **JAX** microbenchmarks (jitted GEMM sweeps, HBM and
  host-memory bandwidth probes, host<->device transfer timing) instead of
  MLX/CuPy (reference profiler/profiler/device.py), and adds an ICI/DCN
  topology model for inter-device communication cost (the reference has only
  a hand-measured ``t_comm`` scalar, common/device.py:50).
"""

from .api import profile_device, profile_model
from .analytic import (
    parse_quantization_info,
    profile_model_phased,
    profile_model_split,
    profile_moe_model,
)
from .datatypes import DeviceInfo
from .hfconfig import HFConfig, load_config, load_config_from_repo

__all__ = [
    "profile_device",
    "profile_model",
    "profile_model_split",
    "profile_model_phased",
    "profile_moe_model",
    "parse_quantization_info",
    "DeviceInfo",
    "HFConfig",
    "load_config",
    "load_config_from_repo",
]

"""Analytic model profiler: per-layer FLOPs/bytes from config metadata alone.

Numeric parity with the reference walker
(/root/reference/src/distilp/profiler/profiler/model.py:50-781) is pinned by
golden-value tests (reference test/test_models.py:54-121). The reference
instantiates an ``mlx_lm`` module tree and pattern-matches module names; this
implementation computes the same quantities directly from the
:class:`~distilp_tpu.profiler.hfconfig.ArchSpec` layout registry — pure
arithmetic, no model framework, no network.

Conventions shared with the reference:
- FMA counts as 2 FLOPs; norms/RoPE count as 0.
- Activations are 16-bit; layer input/output bytes are ``B*L*H*2``.
- The per-layer arrays have length ``L+1``: index 0 is a synthetic all-zero
  "prefill" row so array index == decoder layer index
  (profiler/model.py:98-101).
- GQA/MHA projection sizes use ``head_size = hidden // heads`` even for
  families whose real ``head_dim`` differs (profiler/model.py:630) — the
  golden byte counts depend on this.
- Quantized tensors carry group metadata: 2 scale bytes per group, zero
  bytes for offsets (profiler/model.py:84-86).
- MoE router weights are recorded in ``router_bytes`` but NOT added to the
  layer's ``weight_bytes`` (profiler/model.py:176-192 mutates only
  ``moe_router_bytes``); replicated for fixture parity.
"""

from __future__ import annotations

from fnmatch import fnmatch
from math import ceil
from typing import Dict, List, NamedTuple, Optional, Sequence

from pydantic import BaseModel

from ..common import ModelProfile, ModelProfilePhased, ModelProfileSplit
from ..common.types import ModelPhase, QuantizationLevel
from .hfconfig import HFConfig

_SCALE_BYTES = 2
_ZERO_BYTES = 0
_A_BITS = 16  # activation width


class LayerCosts(BaseModel):
    """Per-layer profiling record (reference LayerMetadata,
    profiler/model.py:14-47, minus the module-tree bookkeeping fields)."""

    name: str = ""
    flops: float = 0.0
    weight_bytes: int = 0
    input_bytes: int = 0
    output_bytes: int = 0
    kv_cache_r: float = 0.0
    kv_cache_w: float = 0.0

    # Component breakdowns for the MoE co-assignment solver
    attn_flops: float = 0.0
    attn_bytes: int = 0
    moe_router_flops: float = 0.0
    moe_router_bytes: int = 0
    moe_expert_flops: float = 0.0
    moe_expert_bytes: int = 0
    moe_expert_flops_per_token: float = 0.0
    moe_shared_flops: float = 0.0
    moe_shared_bytes: int = 0
    is_moe_layer: bool = False


class QuantInfo(NamedTuple):
    bits: int
    group_size: int
    exclude_patterns: List[str]
    fp_bits: int
    label: QuantizationLevel


def parse_quantization_info(cfg: HFConfig) -> QuantInfo:
    """Read quantization metadata from the raw config
    (reference profiler/model.py:862-935)."""
    raw = cfg.raw
    bits = 0
    group_size = 0
    quant_method: Optional[str] = None
    exclude_patterns: List[str] = []

    if isinstance(raw.get("quantization"), dict):
        q = raw["quantization"]
        bits = int(q.get("bits", 0) or 0)
        group_size = int(q.get("group_size", 0) or 0)
    elif isinstance(raw.get("quantization_config"), dict):
        q = raw["quantization_config"]
        bits = int(q.get("bits", 0) or 0)
        group_size = int(q.get("group_size", 0) or 0)
        quant_method = q.get("quant_method")
        exclude_patterns = list(q.get("modules_to_not_convert", []) or [])

    dtype = raw.get("torch_dtype") or raw.get("dtype")
    if bits == 0:
        if quant_method in ("mxfp4", "MXFP4", "mx_fp4"):
            bits = 4
            if group_size == 0:
                group_size = 128
        if bits == 0 and dtype:
            if dtype in ("bfloat16", "bf16", "float16", "fp16"):
                bits = 16
            elif dtype in ("float32", "f32"):
                bits = 32
        if bits == 0:
            bits = 16

    fp_bits = 32 if dtype in ("float32", "f32") else 16

    label: QuantizationLevel
    mapping: Dict[int, QuantizationLevel] = {
        4: "Q4_K",
        5: "Q5_K",
        6: "Q6_K",
        8: "Q8_0",
        32: "F32",
    }
    if bits in mapping:
        label = mapping[bits]
    elif bits == 16:
        label = "BF16" if dtype in ("bfloat16", "bf16") else "F16"
    else:
        label = "F16"

    return QuantInfo(bits, group_size, exclude_patterns, fp_bits, label)


def _quantized_bytes(n: int, bits: int, group_size: int) -> int:
    """Packed code bytes + per-group scale/zero metadata
    (reference profiler/model.py:58-66)."""
    code_bytes = ceil(n * bits / 8)
    if group_size and group_size > 0:
        groups = (n + group_size - 1) // group_size
        meta_bytes = groups * (_SCALE_BYTES + _ZERO_BYTES)
    else:
        meta_bytes = 0
    return code_bytes + meta_bytes


def _tensor_bytes(n: int, bits: int, group_size: Optional[int]) -> int:
    if bits < 16 and group_size is not None:
        return _quantized_bytes(n, bits, group_size)
    return ceil(n * bits / 8)


def _is_excluded(path: str, patterns: Sequence[str]) -> bool:
    for pat in patterns:
        try:
            if fnmatch(path, pat):
                return True
        except Exception:
            pass
    return False


def _phase_tokens(phase: ModelPhase, B: int, L: int) -> int:
    """Tokens pushed through the weights per phase
    (reference profiler/model.py:121-129)."""
    if phase == "prefill":
        return B * L
    if phase == "decode":
        return B
    return B * L + B  # merged: full prefill + one decode step


def _phase_pick(phase: ModelPhase, prefill_val: float, decode_val: float) -> float:
    if phase == "prefill":
        return prefill_val
    if phase == "decode":
        return decode_val
    return prefill_val + decode_val


def _attention_costs(
    cfg: HFConfig,
    lm: LayerCosts,
    idx: int,
    tokens: int,
    B: int,
    L: int,
    phase: ModelPhase,
    q: QuantInfo,
) -> None:
    """Attention projections + attention core + KV-cache traffic.

    Branch selection and formulas match the reference walker: MLA
    (profiler/model.py:506-622), GQA (:629-724), MHA (:727-777).
    """
    H = cfg.hidden_size()
    A = cfg.num_attention_heads()
    kv_heads = cfg.num_key_value_heads()
    is_gqa = kv_heads != A

    attn_path = f"model.layers.{idx}.self_attn"
    w_bits = q.fp_bits if _is_excluded(attn_path, q.exclude_patterns) else q.bits

    if cfg.is_mla():
        if not any(cfg.raw.get(k) is not None for k in ("kv_lora_rank", "v_head_dim")):
            # Low-rank replace without latent KV: unimplemented in the
            # reference too (profiler/model.py:624-627).
            return
        if is_gqa:
            raise NotImplementedError(
                "MLA with grouped KV heads is not modeled (the reference "
                "walker crashes on this path, profiler/model.py:517-518)"
            )
        q_head_dim = cfg.qk_nope_head_dim() + cfg.qk_rope_head_dim()
        q_lora = cfg.q_lora_rank()
        kv_lora = cfg.kv_lora_rank()
        v_head = cfg.v_head_dim()

        q_a_f = 2 * tokens * H * q_lora
        q_b_f = 2 * tokens * A * q_head_dim * q_lora
        kv_a_f = 2 * tokens * (kv_lora + cfg.qk_rope_head_dim()) * H
        kv_b_f = 2 * tokens * kv_lora * A * (cfg.qk_nope_head_dim() + v_head)
        o_f = 2 * tokens * A * v_head * H

        out_features = kv_lora + cfg.qk_rope_head_dim()
        param_counts = (
            q_lora * H,  # q_a
            A * q_head_dim * q_lora,  # q_b
            out_features * H,  # kv_a_with_mqa
            out_features * kv_lora,  # kv_b (reference sizing, model.py:535)
            H * A * v_head,  # o
        )

        kv_elems = kv_lora + cfg.qk_rope_head_dim()
        lm.kv_cache_w = (
            B * L * kv_elems * _A_BITS / 8
            if phase == "prefill"
            else B * 1 * kv_elems * _A_BITS / 8
            if phase == "decode"
            else B * (L + 1) * kv_elems * _A_BITS / 8
        )
        lm.kv_cache_r = 0.0 if phase == "prefill" else B * L * kv_elems * _A_BITS / 8

        attn_core = _phase_pick(
            phase,
            4 * B * A * (L * L) * q_head_dim,
            4 * B * A * L * q_head_dim,
        )
        attn_flops = q_a_f + q_b_f + kv_a_f + kv_b_f + o_f + attn_core
        attn_bytes = sum(
            _tensor_bytes(n, w_bits, q.group_size or None) for n in param_counts
        )
    else:
        head_size = H // A  # NOT cfg.head_dim(): golden parity, model.py:630
        if is_gqa:
            kv_out = kv_heads * head_size
            param_counts = (H * H, H * kv_out, H * kv_out, H * H)
            proj_flops = sum(2 * tokens * n for n in param_counts)
            attn_bytes = sum(
                _tensor_bytes(n, w_bits, q.group_size or None) for n in param_counts
            )
            kv_elems = 2 * kv_heads * head_size
        else:
            proj_flops = 4 * (2 * tokens * H * H)
            # MHA quantizes Q,K,V,O as one 4*H^2 blob (model.py:759-766).
            attn_bytes = _tensor_bytes(4 * H * H, w_bits, q.group_size or None)
            kv_elems = 2 * H

        attn_core = _phase_pick(
            phase,
            4 * B * A * (L * L) * head_size,
            4 * B * A * L * head_size,
        )
        attn_flops = proj_flops + attn_core
        lm.kv_cache_w = float(
            (B * L * kv_elems * _A_BITS) // 8
            if phase == "prefill"
            else (B * 1 * kv_elems * _A_BITS) // 8
            if phase == "decode"
            else (B * (L + 1) * kv_elems * _A_BITS) // 8
        )
        lm.kv_cache_r = (
            0.0 if phase == "prefill" else float((B * L * kv_elems * _A_BITS) // 8)
        )

    lm.flops += attn_flops
    lm.attn_flops = attn_flops
    lm.weight_bytes += attn_bytes
    lm.attn_bytes = attn_bytes


def _dense_mlp_costs(
    cfg: HFConfig, lm: LayerCosts, idx: int, tokens: int, q: QuantInfo
) -> None:
    """Dense GLU MLP: 3 effective projections whether the family stores them
    separately or fused (reference profiler/model.py:461-492)."""
    H = cfg.hidden_size()
    inter = cfg.intermediate_size()
    w_bits = q.bits  # dense MLP path applies no exclusion (model.py:472)
    for proj in cfg.spec.mlp_projections:
        width = 2 * inter if proj == "gate_up_proj" else inter
        lm.flops += 2 * tokens * H * width
        lm.weight_bytes += _tensor_bytes(H * width, w_bits, q.group_size or None)


def _moe_costs(
    cfg: HFConfig, lm: LayerCosts, idx: int, tokens: int, q: QuantInfo
) -> None:
    """Sparse-MoE block: router + routed experts + optional shared experts
    (reference profiler/model.py:144-459)."""
    H = cfg.hidden_size()
    E = cfg.n_routed_experts()
    topk = cfg.num_experts_tok()
    moe_inter = cfg.moe_intermediate()
    if moe_inter == 0:
        raise ValueError(
            "MoE layer detected but no valid intermediate size found in config"
        )
    lm.is_moe_layer = True
    mlp_path = f"model.layers.{idx}.mlp"
    router_path = f"model.layers.{idx}.mlp.router"

    # Router / gate
    gate_f = 2 * tokens * H * E
    lm.flops += gate_f
    lm.moe_router_flops = gate_f
    router_bits = q.fp_bits if _is_excluded(router_path, q.exclude_patterns) else q.bits
    lm.moe_router_bytes = _tensor_bytes(H * E, router_bits, q.group_size or None)

    # Routed experts. Layers the config marks as dense-replaced still get
    # expert costs in the reference (its tier-3 fallback fires because the
    # config says E>0, profiler/model.py:386-424) — with an activation term
    # and without shared experts.
    dense_replaced = idx <= cfg.first_k_dense_replace()
    layout = cfg.spec.moe.routed_layout if cfg.spec.moe else "switch_glu"
    with_activation = layout == "fused_gate_up" or dense_replaced

    num_proj = 3
    DS = H * moe_inter
    smlp_f = num_proj * (2 * tokens * topk * DS)
    if with_activation:
        smlp_f += tokens * topk * moe_inter

    w_bits = q.fp_bits if _is_excluded(mlp_path, q.exclude_patterns) else q.bits
    if w_bits < 16 and (q.group_size or None) is not None:
        smlp_b = E * num_proj * _quantized_bytes(H * moe_inter, w_bits, q.group_size)
    else:
        smlp_b = ceil(E * num_proj * H * moe_inter * w_bits / 8)
    lm.weight_bytes += smlp_b
    lm.flops += smlp_f
    lm.moe_expert_flops = smlp_f / E if E > 0 else 0.0
    lm.moe_expert_bytes = smlp_b // E if E > 0 else 0
    lm.moe_expert_flops_per_token = 2 * num_proj * H * moe_inter + moe_inter

    # Shared experts (deepseek-style)
    n_shared = cfg.n_shared()
    if n_shared > 0 and not dense_replaced:
        shared_inter = cfg.shared_intermediate()
        se_f = num_proj * (2 * tokens * H * n_shared * shared_inter)
        if w_bits < 16 and (q.group_size or None) is not None:
            se_b = n_shared * num_proj * _quantized_bytes(
                H * shared_inter, w_bits, q.group_size
            )
        else:
            se_b = (n_shared * num_proj * H * shared_inter * w_bits) // 8
        lm.weight_bytes += se_b
        lm.flops += se_f
        lm.moe_shared_flops = se_f
        lm.moe_shared_bytes = se_b


def profile_layers(
    cfg: HFConfig,
    B: int = 1,
    L: int = 4096,
    phase: ModelPhase = "merged",
    quant: Optional[QuantInfo] = None,
) -> List[LayerCosts]:
    """Per-layer cost records, length ``num_hidden_layers + 1``
    (index 0 is the synthetic zero row)."""
    q = quant or parse_quantization_info(cfg)
    H = cfg.hidden_size()
    tokens = _phase_tokens(phase, B, L)
    io_bytes = ceil(B * L * H * _A_BITS / 8)

    has_moe = cfg.spec.moe is not None and cfg.n_routed_experts() != 0
    layer_freq = cfg.moe_layer_freq()
    mlp_only = set(cfg.mlp_only_layers())

    layers: List[LayerCosts] = [LayerCosts(name="prefill")]
    for idx in range(1, cfg.num_hidden_layers() + 1):
        lm = LayerCosts(name=f"decoder_{idx}")
        lm.input_bytes = io_bytes
        lm.output_bytes = io_bytes
        _attention_costs(cfg, lm, idx, tokens, B, L, phase, q)
        if has_moe and idx % layer_freq == 0 and idx not in mlp_only:
            _moe_costs(cfg, lm, idx, tokens, q)
        else:
            _dense_mlp_costs(cfg, lm, idx, tokens, q)
        layers.append(lm)
    return layers


def _fill_common(
    ret: ModelProfile, cfg: HFConfig, layers: List[LayerCosts], B: int, L: int
) -> None:
    ret.b_layers = [int(x.weight_bytes) for x in layers]
    ret.b_i_layers = [int(x.input_bytes) for x in layers]
    ret.b_o_layers = [int(x.output_bytes) for x in layers]
    if ret.f_q_layers is None:
        ret.f_q_layers = {}
    tag = f"b_{B}"
    ret.f_q_layers[tag] = [float(x.flops) for x in layers]
    ret.f_out[tag] = ret.f_q_layers[tag][-1] if ret.f_q_layers[tag] else 0.0
    ret.seq_len = int(L)

    ret.L = cfg.num_hidden_layers()
    ret.e_embed = cfg.hidden_size()
    ret.V = cfg.vocab_size()
    ret.hk = cfg.num_key_value_heads()
    ret.hv = cfg.num_key_value_heads()
    head_dim = cfg.head_dim()
    if head_dim == 0 and ret.e_embed > 0 and cfg.num_attention_heads() > 0:
        head_dim = ret.e_embed // cfg.num_attention_heads()
    ret.ek = head_dim
    ret.ev = head_dim
    ret.n_kv = cfg.max_position_embeddings(L)


def profile_model(
    cfg: HFConfig,
    B: int = 1,
    L: int = 4096,
    bs_list: Optional[List[int]] = None,
    phase: ModelPhase = "merged",
) -> ModelProfile:
    """Dense-model profile (reference profiler/model.py:785-858)."""
    q = parse_quantization_info(cfg)
    layers = profile_layers(cfg, B, L, phase, q)
    ret = ModelProfile()
    _fill_common(ret, cfg, layers, B, L)
    ret.quantization = q.label
    ret.Q = q.label

    for Bx in bs_list or []:
        tag = f"b_{Bx}"
        layers_bx = profile_layers(cfg, Bx, L, phase, q)
        ret.f_q_layers[tag] = [float(x.flops) for x in layers_bx]
        ret.f_out[tag] = ret.f_q_layers[tag][-1] if ret.f_q_layers[tag] else 0.0
    return ret


def profile_moe_model(
    cfg: HFConfig,
    B: int = 1,
    L: int = 4096,
    bs_list: Optional[List[int]] = None,
    phase: ModelPhase = "merged",
) -> ModelProfile:
    """MoE-aware profile with component metrics for expert co-assignment
    (reference profiler/model.py:938-1098). Delegates to
    :func:`profile_model` for dense models."""
    if cfg.spec.moe is None or cfg.n_routed_experts() == 0:
        return profile_model(cfg, B, L, bs_list, phase)

    q = parse_quantization_info(cfg)
    layers = profile_layers(cfg, B, L, phase, q)
    ret = ModelProfile()
    ret.is_moe = True
    _fill_common(ret, cfg, layers, B, L)
    ret.quantization = q.label
    ret.Q = q.label

    ret.n_routed_experts = cfg.n_routed_experts()
    ret.n_shared_experts = (
        cfg.n_shared() if cfg.n_shared() > 0 else (1 if cfg.shared_intermediate() > 0 else 0)
    )
    ret.experts_per_token = cfg.num_experts_tok()
    ret.moe_intermediate_size = cfg.moe_intermediate()
    if ret.moe_intermediate_size == 0:
        raise ValueError(
            "MoE model detected but no valid intermediate/FFN size found"
        )
    ret.moe_layer_freq = cfg.moe_layer_freq()
    # The reference hard-codes 0 here regardless of config
    # (profiler/model.py:1029-1031); we report the config value, which the
    # co-assignment solver needs.
    ret.first_k_dense_replace = cfg.first_k_dense_replace()

    moe_indices = [i for i, lyr in enumerate(layers[1:], 1) if lyr.is_moe_layer]
    ret.moe_layer_indices = moe_indices
    ret.total_moe_layers = len(moe_indices)

    ret.attn_bytes = []
    ret.attn_flops = {f"b_{B}": []}
    ret.bytes_per_expert = {}
    ret.bytes_shared_experts = {}
    ret.flops_per_expert = {}
    ret.flops_shared_experts = {}
    ret.router_flops = {}
    ret.router_bytes = {}
    ret.flops_per_active_expert_per_token = {}

    for idx, lyr in enumerate(layers[1:], 1):
        ret.attn_bytes.append(lyr.attn_bytes)
        ret.attn_flops[f"b_{B}"].append(lyr.attn_flops)
        if lyr.is_moe_layer:
            ret.bytes_per_expert[idx] = lyr.moe_expert_bytes
            ret.bytes_shared_experts[idx] = lyr.moe_shared_bytes
            ret.flops_per_expert[idx] = lyr.moe_expert_flops
            ret.flops_shared_experts[idx] = lyr.moe_shared_flops
            ret.router_flops[idx] = lyr.moe_router_flops
            ret.router_bytes[idx] = lyr.moe_router_bytes
            ret.flops_per_active_expert_per_token[idx] = lyr.moe_expert_flops_per_token

    for Bx in bs_list or []:
        tag = f"b_{Bx}"
        layers_bx = profile_layers(cfg, Bx, L, phase, q)
        ret.f_q_layers[tag] = [float(x.flops) for x in layers_bx]
        ret.f_out[tag] = ret.f_q_layers[tag][-1] if ret.f_q_layers[tag] else 0.0
        ret.attn_flops[tag] = [float(x.attn_flops) for x in layers_bx[1:]]
    return ret


def profile_model_phased(
    cfg: HFConfig,
    B: int,
    L: int,
    bs_list: Optional[List[int]] = None,
) -> ModelProfilePhased:
    """Prefill + decode profiles in one run (reference profiler/model.py:1101-1125)."""
    return ModelProfilePhased(
        prefill=profile_moe_model(cfg, B, L, bs_list, "prefill"),
        decode=profile_moe_model(cfg, B, L, bs_list, "decode"),
    )


def profile_model_split(
    cfg: HFConfig,
    B: int,
    L: int,
    bs_list: Optional[List[int]] = None,
) -> ModelProfileSplit:
    """Merge phased profiles into the wire format
    (reference profiler/model.py:1128-1193)."""
    phased = profile_model_phased(cfg, B, L, bs_list)
    pre, dec = phased.prefill, phased.decode

    result = ModelProfileSplit(
        b=pre.b_layers or [],
        b_i=pre.b_i_layers or [],
        b_o=pre.b_o_layers or [],
        L=pre.L,
        hk=pre.hk,
        hv=pre.hv,
        ek=pre.ek,
        ev=pre.ev,
        n_kv=pre.n_kv,
        e_embed=pre.e_embed,
        V=pre.V,
        seq_len=pre.seq_len,
        f_q={
            "prefill": pre.f_q_layers or {},
            "decode": dec.f_q_layers or {},
        },
        f_out={
            "prefill": pre.f_out,
            "decode": dec.f_out,
        },
        quantization=pre.quantization,
    )

    if pre.is_moe:
        result.is_moe = True
        result.n_routed_experts = pre.n_routed_experts
        result.n_shared_experts = pre.n_shared_experts
        result.experts_per_token = pre.experts_per_token
        result.moe_intermediate_size = pre.moe_intermediate_size
        result.moe_layer_freq = pre.moe_layer_freq
        result.first_k_dense_replace = pre.first_k_dense_replace
        result.total_moe_layers = pre.total_moe_layers
        result.moe_layer_indices = pre.moe_layer_indices or []
        result.attn_bytes = pre.attn_bytes or []
        result.attn_flops = {
            "prefill": pre.attn_flops or {},
            "decode": (dec.attn_flops or {}) if dec.is_moe else {},
        }
        result.bytes_per_expert = pre.bytes_per_expert or {}
        result.bytes_shared_experts = pre.bytes_shared_experts or {}
        result.flops_per_expert = pre.flops_per_expert or {}
        result.flops_shared_experts = pre.flops_shared_experts or {}
        result.router_flops = pre.router_flops or {}
        result.router_bytes = pre.router_bytes or {}
        result.flops_per_active_expert_per_token = (
            pre.flops_per_active_expert_per_token or {}
        )

    return result

"""TPU-native device profiler: JAX microbenchmarks -> DeviceInfo -> DeviceProfile.

Capability parity with the reference device profiler
(/root/reference/src/distilp/profiler/profiler/device.py), rebuilt on JAX:

- GEMM throughput sweeps are jitted ``jnp.matmul`` calls per dtype and batch
  on the host (CPU backend) and the accelerator (default backend), replacing
  the MLX sweeps (reference :79-172). Same table shape, same sizes
  (host: hidden/8 min 512; accelerator: hidden min 4096).
- Memory probes run jitted reductions/fills on the CPU backend (reference
  :423-487 used MLX CPU streams).
- Host<->accelerator transfer timing uses ``jax.device_put`` / host fetch,
  replacing the CuPy pinned-memory streams (reference :202-261).
- Accelerator memory comes from ``Device.memory_stats()`` (reference used
  Metal/cudaMemGetInfo, :491-512).
- Disk benchmark keeps the reference's file-sized-like-one-layer design and
  its ``DPERF_*`` env knobs (reference :264-420).

DeviceProfile mapping parity (reference :551-744): quantized throughput is
synthesized from measured F32 by the same fixed factors (Q4_K=0.25,
Q5_K=0.31, Q6_K=0.37, Q8_0=0.5); T_cpu is warm read bandwidth; KV-copy uses
the 2*head_dim*kv_heads*2-byte payload. Two deliberate divergences, both
documented reference bugs: the CUDA-branch ``*1e3`` unit error on
``t_kvcpy_gpu`` (reference :706) is not replicated, and the x86 CPU-feature
probe populates fields that actually exist on the schema (reference :53).
"""

from __future__ import annotations

import gc
import math
import os
import platform
import statistics as stats
import time

from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from ..common import DeviceProfile
from ..common.types import QuantizationLevel
from .datatypes import Batches, DeviceInfo
from .hfconfig import HFConfig

_BATCH_TAGS = [f"b_{2**n}" for n in range(9)]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _quantile(sorted_times: List[float], q: float) -> float:
    idx = min(len(sorted_times) - 1, int(round(q * (len(sorted_times) - 1))))
    return sorted_times[idx]


def bench(
    fn: Callable[[], Any],
    warmup: int = 3,
    iters: int = 10,
    baseline: float = 0.0,
    label: str = "",
    sink: Optional[Dict[str, Any]] = None,
) -> float:
    """Median wall-clock seconds of ``fn`` (reference profiler/device.py:
    175-199), minus ``baseline`` (the round-trip floor on remote devices).

    Completion is forced by FETCHING one element of the output, not by
    ``block_until_ready``: on tunneled accelerator runtimes the latter
    acknowledges before the computation finishes (measured: a 137-GFLOP
    matmul "completed" in 0.05 ms), while a value fetch cannot lie.

    Returns ``nan`` when the baseline-subtracted median is inside the
    measurement noise (non-positive, or smaller than the interquartile
    sample spread while a baseline is being subtracted): a kernel
    indistinguishable from the round-trip floor has NO measurable time, and
    the old behavior of clamping to 1e-9 s turned exactly those cases into
    absurd throughputs.

    ``sink[label]`` (when given) records the raw sample distribution as a
    ``Stat`` — including ``valid`` — so profiles carry the spread instead of
    discarding it; ``DPERF_DEBUG>=1`` prints it, like the reference's debug
    output (/root/reference/src/distilp/profiler/profiler/device.py:188-197).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .datatypes import Stat

    def run() -> None:
        out = fn()
        leaf = jax.tree.leaves(out)[0]
        if isinstance(leaf, jax.Array):
            np.asarray(jnp.ravel(leaf)[0])
        else:
            # Plain numpy output (e.g. a device->host fetch already done by
            # fn): touching it through jnp would re-UPLOAD it to the default
            # backend inside the timed region. It is already synchronous.
            np.ravel(leaf)[:1]

    for _ in range(warmup):
        run()
    times: List[float] = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)

    srt = sorted(times)
    st = Stat(
        samples=len(times),
        min=srt[0],
        p50=stats.median(times),
        p95=_quantile(srt, 0.95),
        p99=_quantile(srt, 0.99),
        max=srt[-1],
        mean=stats.fmean(times),
        stddev=stats.pstdev(times) if len(times) > 1 else 0.0,
        baseline=baseline,
    )
    net = st.p50 - baseline
    # Robust jitter estimate: the interquartile spread. NOT p95-p50 — at the
    # default iters=10 the p95 index IS the max sample, so one GC pause or
    # network hiccup would invalidate an otherwise tightly-clustered
    # measurement.
    noise = _quantile(srt, 0.75) - _quantile(srt, 0.25)
    if net <= 0 or (baseline > 0 and net < noise):
        st.valid = False
    if sink is not None and label:
        sink[label] = st
    if _env_int("DPERF_DEBUG", 0) >= 1:
        import sys

        flag = "" if st.valid else "  [SUB-NOISE: discarded]"
        print(
            f"[dperf] {label or 'bench'}: n={st.samples} "
            f"min={st.min * 1e3:.3f}ms p50={st.p50 * 1e3:.3f}ms "
            f"p95={st.p95 * 1e3:.3f}ms p99={st.p99 * 1e3:.3f}ms "
            f"max={st.max * 1e3:.3f}ms baseline={baseline * 1e3:.3f}ms{flag}",
            file=sys.stderr,
        )
    return net if st.valid else float("nan")


def _rate(nbytes: float, seconds: float) -> float:
    """bytes/sec with sub-noise measurements mapped to 0.0, never NaN.

    ``bench()`` returns NaN for a sub-noise net time. The direct-division
    call sites all pass ``baseline=0`` today — an implicit invariant that
    makes NaN unreachable there; this helper makes the contract explicit so
    adding a baseline at one of those sites writes "no measured bandwidth"
    (0.0) into the profile instead of silently persisting NaN into JSON.
    """
    if math.isnan(seconds) or seconds <= 0.0:
        return 0.0
    return nbytes / seconds


def _ms(seconds: float) -> float:
    """Milliseconds with sub-noise (NaN) measurements mapped to 0.0."""
    return 0.0 if math.isnan(seconds) else 1000.0 * seconds


def _fetch_baseline(backend: str) -> float:
    """Round-trip floor of a dispatch + one-element fetch on ``backend``."""
    import jax
    import jax.numpy as jnp

    try:
        dev = jax.devices(backend)[0]
        x = jax.device_put(jnp.ones((8,), jnp.float32), dev)
        probe = jax.jit(lambda v: v * 1.0)
        v = bench(lambda: probe(x), warmup=3, iters=10)
        return v if v == v else 0.0  # NaN-guard (baseline=0 never triggers)
    except Exception:
        return 0.0


def _chained_rate(
    fn: Callable[[Any], Any],
    chain: int,
    units_per_iter: float,
    warmup: int,
    iters: int,
    baseline: float,
    label: str = "",
    sink: Optional[Dict[str, Any]] = None,
) -> float:
    """Units/second of a chained kernel ``fn(chain_length)`` measured at two
    chain lengths; the slope cancels the dispatch round-trip and per-call
    overheads. Falls back to single-point (baseline-subtracted) timing when
    jitter swamps the slope; returns 0.0 ("no table") when even that is
    inside the round-trip noise — never an absurd clamped throughput."""
    import jax.numpy as jnp

    c_lo = max(1, chain // 4)
    t_hi = bench(
        lambda: fn(jnp.asarray(chain, jnp.int32)), warmup, iters,
        label=f"{label}.hi" if label else "", sink=sink,
    )
    t_lo = bench(
        lambda: fn(jnp.asarray(c_lo, jnp.int32)), warmup, iters,
        label=f"{label}.lo" if label else "", sink=sink,
    )
    dt = t_hi - t_lo
    if dt > 0:
        return units_per_iter * (chain - c_lo) / dt
    net = t_hi - baseline
    if net > 0:
        return units_per_iter * chain / net
    return 0.0


def _gemm_flops(
    backend: str,
    B: int,
    N: int,
    M: int,
    K: int,
    dtype_name: str,
    warmup: int,
    iters: int,
    baseline: float = 0.0,
    label: str = "",
    sink: Optional[Dict[str, Any]] = None,
) -> float:
    """FLOPS of a jitted batched GEMM ``(B,M,K) @ (K,N)`` on ``backend``.

    Returns 0.0 on failure, like the reference (:134-137) — e.g. integer
    matmul on accelerators that lack it — and 0.0 (the "no table" sentinel)
    when the measurement is sub-noise (see ``bench``).
    """
    import jax
    import jax.numpy as jnp

    try:
        dev = jax.devices(backend)[0]
        dtype = jnp.dtype(dtype_name)
        if jnp.issubdtype(dtype, jnp.integer):
            key = None
            a = jnp.ones((B, M, K), dtype=dtype)
            b = jnp.ones((K, N), dtype=dtype)
        else:
            key = jax.random.PRNGKey(0)
            ka, kb = jax.random.split(key)
            a = jax.random.normal(ka, (B, M, K), dtype=dtype)
            b = jax.random.normal(kb, (K, N), dtype=dtype)
        a = jax.device_put(a, dev)
        b = jax.device_put(b, dev)
        flop = 2.0 * B * N * M * K

        if key is None:
            # Integer matmul: single call (no float feedback trick exists
            # that XLA cannot constant-fold); RTT subtracted via baseline.
            # Reduce via max|.| — a plain [0] slice lets XLA rewrite
            # slice-of-dot into a one-element dot.
            mm = jax.jit(lambda a, b: jnp.max(jnp.abs(jnp.matmul(a, b))))
            median = bench(
                lambda: mm(a, b), warmup, iters, baseline=baseline,
                label=label, sink=sink,
            )
            result = flop / median if median == median else 0.0
        else:
            # Chain matmuls inside ONE jitted call with FULL matrix feedback
            # (the output, normalized, is the next input). Anything weaker is
            # defeated: scalar feedback perturbations are either distributed
            # out of the linear matmul and hoisted, flushed to zero
            # (subnormal constants on TPU), or rounded into a fixed point —
            # all observed to collapse the loop. Throughput comes from the
            # SLOPE between two chain lengths, which cancels the dispatch
            # round-trip (tens of ms on a tunneled TPU) and per-call
            # overheads entirely.
            # Local backends have ~us dispatch overhead: a short chain
            # suffices and keeps the (slow, upcast) CPU fp16 sweep bounded.
            if backend == "cpu":
                chain = _env_int("DPERF_CHAIN_CPU", 2)
            else:
                chain = max(4, _env_int("DPERF_CHAIN", 64) // B)
            eps = jnp.asarray(1e-6, dtype)

            # Chain length is a DYNAMIC argument (fori_loop lowers a traced
            # bound to while_loop): one compile covers both slope points —
            # with remote compile times in seconds, recompiling per chain
            # length dominated the whole profiling run.
            @jax.jit
            def chained(x, b, c):
                def body(_, x):
                    y = jnp.matmul(x, b)
                    return y / (jnp.max(jnp.abs(y)) + eps)

                return jax.lax.fori_loop(0, c, body, x).ravel()[0]

            result = _chained_rate(
                lambda c: chained(a, b, c), chain, flop, warmup, iters,
                baseline, label=label, sink=sink,
            )
        del a, b
        gc.collect()
        return result
    except Exception:
        return 0.0


def run_host_benchmarks(di: DeviceInfo, n_embd: int, max_batch_exp: int) -> None:
    """CPU GEMM sweep (reference run_cpu_benchmarks, :142-155)."""
    size = int(n_embd / 8 if n_embd >= 4096 else 4096 / 8)
    warmup = _env_int("DPERF_GEMM_WARMUP", 1)
    iters = _env_int("DPERF_GEMM_ITERS", 4)
    base = _fetch_baseline("cpu")
    for tag, dtype in [("f32", "float32"), ("fp16", "float16"), ("bf16", "bfloat16"), ("u32", "uint32")]:
        table: Batches = getattr(di.cpu.benchmarks, tag)
        for exp in range(min(max_batch_exp, len(_BATCH_TAGS))):
            setattr(
                table,
                _BATCH_TAGS[exp],
                _gemm_flops(
                    "cpu", 2**exp, size, size, size, dtype, warmup, iters,
                    base, label=f"gemm.cpu.{tag}.{_BATCH_TAGS[exp]}",
                    sink=di.stats,
                ),
            )


def run_accel_benchmarks(di: DeviceInfo, n_embd: int, max_batch_exp: int) -> None:
    """Accelerator GEMM sweep (reference run_gpu_benchmarks, :159-172)."""
    import jax

    backend = jax.default_backend()
    if backend == "cpu":
        return
    size = n_embd if n_embd >= 4096 else 4096
    warmup = _env_int("DPERF_GEMM_WARMUP", 1)
    iters = _env_int("DPERF_GEMM_ITERS", 4)
    base = _fetch_baseline(backend)
    for tag, dtype in [("f32", "float32"), ("fp16", "float16"), ("bf16", "bfloat16"), ("u32", "uint32")]:
        table = getattr(di.gpu.benchmarks, tag)
        for exp in range(min(max_batch_exp, len(_BATCH_TAGS))):
            setattr(
                table,
                _BATCH_TAGS[exp],
                _gemm_flops(
                    backend, 2**exp, size, size, size, dtype, warmup, iters,
                    base, label=f"gemm.{backend}.{tag}.{_BATCH_TAGS[exp]}",
                    sink=di.stats,
                ),
            )


def get_sysmem_info(di: DeviceInfo) -> None:
    """Host memory capacities and bandwidth probes (reference :423-487)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import psutil

    vm = psutil.virtual_memory()
    sm = psutil.swap_memory()
    di.memory.total = vm.total
    di.memory.available = vm.available
    di.memory.total_swap = sm.total
    di.memory.available_swap = sm.free
    di.memory.can_swap = 1 if sm.total > 0 else 0

    cpu = jax.devices("cpu")[0]
    _fetch_baseline("cpu")  # warm the trace/compile of the sync path before
    # the one-shot cold probes below, so they time memory, not tracing
    mb = _env_int("DPERF_MEM_MB", 128)
    n = (mb * 1024 * 1024) // 4
    A = jax.device_put(jnp.ones((n,), dtype=jnp.float32), cpu)
    nbytes = n * 4

    read = jax.jit(jnp.max)  # runs on the CPU: A is CPU-resident
    di.memory.cpu_read_cold_bw = _rate(
        nbytes,
        bench(lambda: read(A), 0, 1, label="mem.cpu_read_cold", sink=di.stats),
    )
    warm_read = jax.jit(jnp.sum)  # scalar output: bench() fetches it to sync
    di.memory.cpu_read_warm_bw = _rate(
        nbytes,
        bench(lambda: warm_read(A), 5, 10, label="mem.cpu_read_warm", sink=di.stats),
    )

    # No input to anchor placement: pin the fill's output to the CPU device.
    fill = jax.jit(
        lambda: jnp.full((n,), 23.4, dtype=jnp.float32),
        out_shardings=jax.sharding.SingleDeviceSharding(cpu),
    )
    di.memory.cpu_write_cold_bw = _rate(
        nbytes, bench(fill, 0, 1, label="mem.cpu_write_cold", sink=di.stats)
    )
    di.memory.cpu_write_warm_bw = _rate(
        nbytes, bench(fill, 5, 10, label="mem.cpu_write_warm", sink=di.stats)
    )

    # Seeded: the probe buffer's contents must not vary run to run, or the
    # memcpy timing picks up data-dependent (denormal) effects.
    host_buf = np.random.default_rng(0).standard_normal(n // 8).astype(np.float32)
    di.memory.memcpy_delay = _ms(
        bench(
            lambda: jax.device_put(host_buf, cpu), 1, 5,
            label="mem.memcpy", sink=di.stats,
        )
    )
    del A, host_buf
    gc.collect()


def fill_cpu_info(di: DeviceInfo) -> None:
    """CPU identity via py-cpuinfo/psutil (reference :32-74, with the
    schema-mismatch crash fixed — see datatypes.CPUFeatures)."""
    import psutil

    di.cpu.topology.cores = psutil.cpu_count(logical=False) or 0
    di.cpu.topology.threads = psutil.cpu_count(logical=True) or 0
    freq = psutil.cpu_freq()
    if freq:
        di.cpu.clock.base = freq.min or freq.current or 0.0
        di.cpu.clock.max = freq.max or freq.current or 0.0

    try:
        import cpuinfo  # py-cpuinfo

        info = cpuinfo.get_cpu_info()
        di.cpu.vendor = info.get("vendor_id_raw", "")
        di.cpu.model = info.get("brand_raw", "")
        di.cpu.arch = info.get("arch_string_raw", platform.machine())
        flags = set(info.get("flags", []))
        di.cpu.features.AVX = "avx" in flags
        di.cpu.features.AVX2 = "avx2" in flags
        di.cpu.features.FMA = "fma" in flags
        di.cpu.features.SSE = "sse" in flags
        di.cpu.features.BF16 = "avx512_bf16" in flags or "amx_bf16" in flags
        di.cpu.features.NEON = "neon" in flags or platform.machine() in ("arm64", "aarch64")
        di.cpu.cache.l2 = int(info.get("l2_cache_size", 0) or 0)
        di.cpu.cache.l3 = int(info.get("l3_cache_size", 0) or 0)
    except Exception:
        di.cpu.model = platform.processor() or platform.machine()
        di.cpu.arch = platform.machine()


def accel_get_memory_info(di: DeviceInfo) -> None:
    """Accelerator memory capacity from ``Device.memory_stats()``
    (replaces Metal/cudaMemGetInfo, reference :491-512)."""
    import jax

    backend = jax.default_backend()
    if backend == "cpu":
        return
    dev = jax.devices()[0]
    di.gpu.memory.name = dev.device_kind
    di.gpu.device_kind = dev.device_kind
    di.gpu.num_devices = jax.local_device_count()
    try:
        ms = dev.memory_stats() or {}
        total = ms.get("bytes_limit", 0)
        in_use = ms.get("bytes_in_use", 0)
    except Exception:
        total = in_use = 0
    source = "memory_stats"
    if total <= 0:
        # Some runtimes (remote/tunneled TPUs) expose no memory_stats; fall
        # back to the DPERF_HBM_BYTES override, then the known per-chip HBM
        # of the device kind. An unparsable override falls through to the
        # table rather than silently zeroing the capacity.
        in_use = 0
        if _env_int("DPERF_HBM_BYTES", 0) > 0:
            total = _env_int("DPERF_HBM_BYTES", 0)
            source = "env:DPERF_HBM_BYTES"
        else:
            total = _hbm_by_kind(dev.device_kind)
            source = f"table:{dev.device_kind}" if total > 0 else "unknown"
        if total <= 0:
            import sys

            source = "unknown"
            print(
                f"[dperf] WARNING: no memory_stats and unlisted device kind "
                f"{dev.device_kind!r}: HBM capacity recorded as 0 "
                f"(capacity_source='unknown'); set DPERF_HBM_BYTES to the "
                f"per-chip HBM bytes.",
                file=sys.stderr,
            )
    di.gpu.memory.capacity_source = source
    di.gpu.memory.total = float(total)
    di.gpu.memory.free = float(max(total - in_use, 0))


# Known HBM per chip, bytes. Keys are matched as lowercase substrings of
# ``Device.device_kind`` (e.g. "TPU v5 lite" -> v5e, 16 GiB).
_HBM_TABLE = (
    ("v5 lite", 16 << 30),
    ("v5e", 16 << 30),
    ("v5p", 95 << 30),
    ("v6 lite", 32 << 30),
    ("v6e", 32 << 30),
    ("v4", 32 << 30),
    ("v3", 32 << 30),
    ("v2", 16 << 30),
)


def _hbm_by_kind(kind: str) -> int:
    k = (kind or "").lower()
    for pat, size in _HBM_TABLE:
        if pat in k:
            return size
    return 0


def accel_bench_mem_to_compute(di: DeviceInfo) -> None:
    """HBM streaming bandwidth: jitted reduction over a large resident array
    (replaces metal_bench_mem_to_compute, reference :524-548)."""
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    if backend == "cpu":
        return
    dev = jax.devices()[0]
    mb = _env_int("DPERF_HBM_MB", 256)
    n = (mb * 1024 * 1024) // 4
    try:
        A = jax.device_put(jnp.ones((n,), dtype=jnp.float32), dev)
        # Chained full-feedback data movement (roll carries the array through
        # the loop, so nothing can be hoisted or folded), timed at two chain
        # lengths; the slope cancels the dispatch round-trip. Each iteration
        # reads and writes the array once -> 2 passes of n*4 bytes.
        chain = 8 * _env_int("DPERF_CHAIN", 8)

        @jax.jit
        def rolled(x, c):
            def body(_, x):
                return jnp.roll(x, 1)

            return jax.lax.fori_loop(0, c, body, x)[0]

        di.gpu.memory.vram_to_compute = _chained_rate(
            lambda c: rolled(A, c), chain, 2 * n * 4, 2, 6,
            _fetch_baseline(backend), label="hbm.stream", sink=di.stats,
        )
        del A
        gc.collect()
    except Exception:
        pass


def bench_host_accel_transfers(di: DeviceInfo, n_embd: int) -> None:
    """Host->HBM and HBM->host bandwidth via device_put / host fetch
    (replaces the CuPy pinned-memory streams, reference :202-261)."""
    import jax
    import numpy as np

    backend = jax.default_backend()
    if backend == "cpu":
        return
    dev = jax.devices()[0]
    mb = _env_int("DPERF_XFER_MB", 64)
    n = (mb * 1024 * 1024) // 4
    try:
        host = np.ones((n,), dtype=np.float32)
        nbytes = n * 4
        di.gpu.memory.read_bw = _rate(
            nbytes,
            bench(
                lambda: jax.device_put(host, dev), 1, 5,
                label="xfer.host_to_accel", sink=di.stats,
            ),
        )  # host -> device
        resident = jax.device_put(host, dev)
        di.gpu.memory.write_bw = _rate(
            nbytes,
            bench(
                lambda: np.asarray(resident), 1, 5,
                label="xfer.accel_to_host", sink=di.stats,
            ),
        )  # device -> host
        if di.gpu.memory.read_bw > 0 and di.gpu.memory.write_bw > 0:
            di.gpu.memory.read_write_bw = 2.0 / (
                1.0 / di.gpu.memory.read_bw + 1.0 / di.gpu.memory.write_bw
            )
        del host, resident
        gc.collect()
    except Exception:
        pass

# -- Disk benchmark (reference :264-420) -----------------------------------


def _bytes_per_weight_from_config(config: Optional[Dict[str, Any]]) -> float:
    override = os.environ.get("DPERF_BYTES_PER_WEIGHT")
    if override:
        try:
            return float(override)
        except ValueError:
            pass
    if not config:
        return 2.0
    q = config.get("quantization") or config.get("quantization_config") or {}
    bits = 0
    if isinstance(q, dict):
        bits = int(q.get("bits", 0) or 0)
        if bits == 0 and q.get("quant_method") in ("mxfp4", "MXFP4", "mx_fp4"):
            bits = 4
    if bits == 0:
        dtype = config.get("torch_dtype") or config.get("dtype")
        bits = 32 if dtype in ("float32", "f32") else 16
    group = int(q.get("group_size", 0) or 0) if isinstance(q, dict) else 0
    per_weight = bits / 8.0
    if bits < 16 and group > 0:
        per_weight += 2.0 / group  # group scale metadata
    return per_weight


def _estimate_layer_file_bytes(config: Optional[Dict[str, Any]]) -> int:
    """~One decoder layer on disk: (4d^2 + 3di) params * bytes/weight
    (reference :302-337)."""
    overhead = _env_float("DPERF_LAYER_OVERHEAD", 1.05)
    min_mb = _env_int("DPERF_LAYER_MIN_MB", 16)
    max_mb = _env_int("DPERF_LAYER_MAX_MB", 1024)
    d = int((config or {}).get("hidden_size", 4096) or 4096)
    i = int((config or {}).get("intermediate_size", 4 * d) or 4 * d)
    params = 4 * d * d + 3 * d * i
    size = int(params * _bytes_per_weight_from_config(config) * overhead)
    return max(min_mb * 1024 * 1024, min(size, max_mb * 1024 * 1024))


def bench_disk_mainfs(di: DeviceInfo, config: Optional[Dict[str, Any]] = None) -> None:
    """Sequential write+read of a layer-sized file on the main filesystem.

    ``random`` is aliased to ``read`` as in the reference (:417-420). Page
    cache is dropped with posix_fadvise(DONTNEED) where available (the
    reference used F_NOCACHE on macOS).
    """
    file_mb = os.environ.get("DPERF_DISK_FILE_MB")
    if file_mb:
        size = int(float(file_mb) * 1024 * 1024)
    else:
        size = _estimate_layer_file_bytes(config)
    chunk = _env_int("DPERF_DISK_CHUNK_MB", 8) * 1024 * 1024
    chunk = max(min(chunk, size), 1024 * 1024)

    path = Path(os.environ.get("TMPDIR", "/tmp")) / f"dperf_disk_{os.getpid()}.bin"
    data = os.urandom(min(chunk, size))
    try:
        t0 = time.perf_counter()
        written = 0
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            while written < size:
                written += os.write(fd, data[: min(chunk, size - written)])
            os.fsync(fd)
        finally:
            os.close(fd)
        di.disk.write = written / (time.perf_counter() - t0)

        fd = os.open(path, os.O_RDONLY)
        try:
            if hasattr(os, "posix_fadvise"):
                os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
            t0 = time.perf_counter()
            read_total = 0
            while True:
                buf = os.read(fd, chunk)
                if not buf:
                    break
                read_total += len(buf)
        finally:
            os.close(fd)
        di.disk.read = read_total / (time.perf_counter() - t0)
        di.disk.random = di.disk.read
    except OSError:
        pass
    finally:
        try:
            path.unlink(missing_ok=True)
        except OSError:
            pass

# -- Orchestration + DeviceProfile mapping (reference :551-744) -------------


def profile(config: HFConfig, max_batch_exp: int = 6) -> DeviceInfo:
    """Run all microbenchmarks and aggregate a DeviceInfo (reference :555-573)."""
    from .topology import measure_interconnect

    di = DeviceInfo()
    di.os = platform.system().lower()
    get_sysmem_info(di)
    fill_cpu_info(di)

    hidden = config.hidden_size()
    run_host_benchmarks(di, hidden, max_batch_exp)
    run_accel_benchmarks(di, hidden, max_batch_exp)
    accel_bench_mem_to_compute(di)
    accel_get_memory_info(di)
    bench_host_accel_transfers(di, hidden)
    bench_disk_mainfs(di, config=config.raw)

    import jax

    backend = jax.default_backend().lower()
    if backend == "tpu":
        di.gpu.name = "tpu"
    elif backend in ("gpu", "cuda", "rocm"):
        di.gpu.name = "cuda"
    elif backend == "metal":
        di.gpu.name = "metal"
        di.gpu.memory.unified_memory = True
    di.interconnect = measure_interconnect()
    return di


def _quant_table(
    benchmarks, batch_keys: List[str]
) -> Dict[QuantizationLevel, Dict[str, float]]:
    """Synthesize the per-quant throughput table from measured F32/F16/BF16
    by the reference's fixed factors (:641-653)."""
    table: Dict[QuantizationLevel, Dict[str, float]] = {
        "Q4_K": {},
        "Q5_K": {},
        "Q6_K": {},
        "Q8_0": {},
        "F16": {},
        "BF16": {},
        "F32": {},
    }
    for key in batch_keys:
        f32 = getattr(benchmarks.f32, key)
        fp16 = getattr(benchmarks.fp16, key)
        bf16 = getattr(benchmarks.bf16, key)
        table["Q4_K"][key] = f32 * 0.25
        table["Q5_K"][key] = f32 * 0.31
        table["Q6_K"][key] = f32 * 0.37
        table["Q8_0"][key] = f32 * 0.5
        table["F16"][key] = fp16
        table["BF16"][key] = bf16
        table["F32"][key] = f32
    return table


def profile_device(
    config: HFConfig,
    max_batch_exp: int = 6,
    is_head: bool = True,
    raw_info: Optional[List[DeviceInfo]] = None,
) -> DeviceProfile:
    """Microbenchmark this host and map to the solver's DeviceProfile
    (reference :577-744).

    ``raw_info`` (a list, appended to) receives the raw ``DeviceInfo`` —
    per-measurement timing spreads (``stats``), HBM capacity provenance,
    interconnect probe — which the solver-facing DeviceProfile mapping
    does not carry. The CLI's ``--raw-out`` persists it.
    """
    di = profile(config, max_batch_exp)
    if raw_info is not None:
        raw_info.append(di)
    ret = DeviceProfile()
    ret.name = platform.node() or "device"

    ret.has_metal = di.gpu.name == "metal"
    ret.has_cuda = di.gpu.name == "cuda"
    ret.has_tpu = di.gpu.name == "tpu"
    ret.is_unified_mem = ret.has_metal

    system = platform.system()
    if system == "Darwin":
        ret.os_type = "mac_metal" if ret.has_metal else "mac_no_metal"
    elif system == "Linux":
        ret.os_type = "linux"
        try:
            with open("/proc/version", "r") as f:
                if "android" in f.read().lower():
                    ret.os_type = "android"
        except OSError:
            pass
    else:
        ret.os_type = "linux"

    ret.is_head = is_head

    batch_keys = [f"b_{2**n}" for n in range(max_batch_exp)]
    ret.scpu = _quant_table(di.cpu.benchmarks, batch_keys)
    # T_cpu divides the solver's memory terms (coeffs.py: bprime / T_cpu) and
    # must stay positive; a sub-noise warm-read measurement is now 0.0 (see
    # _rate), so fall back to the cold-read probe, then to a deliberately
    # pessimistic 1 GB/s floor rather than persist a divide-by-zero.
    ret.T_cpu = (
        di.memory.cpu_read_warm_bw or di.memory.cpu_read_cold_bw or 1e9
    )

    if di.gpu.name:
        sgpu = _quant_table(di.gpu.benchmarks, batch_keys)
        if ret.has_tpu:
            ret.sgpu_tpu = sgpu
            ret.T_tpu = di.gpu.memory.vram_to_compute
        elif ret.has_cuda:
            ret.sgpu_cuda = sgpu
            ret.T_cuda = di.gpu.memory.vram_to_compute
        elif ret.has_metal:
            ret.sgpu_metal = sgpu
            ret.T_metal = di.gpu.memory.vram_to_compute

    # KV-copy payload: 2 * head_dim * kv_heads * 2 bytes (reference :700).
    kv_payload = 2 * config.head_dim() * config.num_key_value_heads() * 2
    if di.memory.cpu_write_cold_bw > 0:
        ret.t_kvcpy_cpu = kv_payload / di.memory.cpu_write_cold_bw
    if di.gpu.name and di.gpu.memory.vram_to_compute > 0:
        # Reference CUDA branch multiplies by 1e3 (unit bug, :706); we keep
        # seconds for every accelerator.
        ret.t_kvcpy_gpu = kv_payload / di.gpu.memory.vram_to_compute
    elif ret.has_metal and di.memory.cpu_write_cold_bw > 0:
        ret.t_kvcpy_gpu = kv_payload / di.memory.cpu_write_cold_bw

    transfer = 1024 * 1024
    if not ret.is_unified_mem:
        if di.gpu.memory.read_bw > 0:
            ret.t_ram2vram = transfer / di.gpu.memory.read_bw
        if di.gpu.memory.write_bw > 0:
            ret.t_vram2ram = transfer / di.gpu.memory.write_bw

    # Inter-device communication: payload-aware latency + bytes/bandwidth
    # from the timed collectives, with the payload sized to the activation
    # handoff a pipeline round actually ships (one token's hidden state in
    # bf16). 0 on a single device like the reference (:719, where it is
    # always 0 because nothing measures it). The link terms ride along so
    # the solver can price other payloads (MoE all-to-all) on the same link.
    if di.interconnect.num_devices > 1:
        from .topology import estimate_t_comm

        act_payload = config.hidden_size() * 2  # bf16 activations
        ret.t_comm = estimate_t_comm(act_payload, info=di.interconnect)
        # Same link-selection rule as estimate_t_comm, so t_comm and the
        # carried link terms always describe the SAME link.
        ic = di.interconnect
        if ic.num_slices > 1 and (ic.dcn_latency_s > 0 or ic.dcn_bandwidth > 0):
            ret.comm_latency = ic.dcn_latency_s
            ret.comm_bandwidth = ic.dcn_bandwidth
        else:
            ret.comm_latency = ic.ici_allreduce_latency_s
            ret.comm_bandwidth = ic.ici_bandwidth

    ret.s_disk = di.disk.read
    ret.d_avail_ram = int(di.memory.available)
    if ret.has_tpu:
        ret.d_avail_tpu = int(di.gpu.memory.free)
    elif ret.has_cuda:
        ret.d_avail_cuda = int(di.gpu.memory.free)
    elif ret.has_metal:
        ret.d_avail_metal = int(di.memory.available)

    ret.c_cpu = 0
    ret.c_gpu = 0
    ret.d_bytes_can_swap = int(di.memory.total_swap)
    ret.d_swap_avail = int(di.memory.available_swap)
    return ret

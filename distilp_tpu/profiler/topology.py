"""ICI/DCN interconnect probing: a measured communication-cost model.

The reference has no communication backend at all — inter-device cost is a
single hand-edited scalar ``t_comm`` per device profile
(/root/reference/src/distilp/common/device.py:50, set to 0 by its profiler at
profiler/device.py:719). Here ``t_comm``-class coefficients are *measured*
from the visible JAX mesh: a small psum across all local devices gives the
per-round collective latency (ICI rides this on TPU), and a large all-gather
gives sustained link bandwidth. On a single-device host everything stays 0
and the solver behaves exactly like the reference.

Works unchanged on the CPU-backend virtual mesh used in tests
(``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import time
from typing import List, Optional

from .datatypes import InterconnectInfo


def _topology_string(devices) -> str:
    coords = [getattr(d, "coords", None) for d in devices]
    if not coords or any(c is None for c in coords):
        return ""
    dims = len(coords[0])
    extents = [len({c[i] for c in coords}) for i in range(dims)]
    return "x".join(str(e) for e in extents)


def measure_interconnect(
    latency_iters: int = 10,
    bandwidth_mb: int = 32,
    devices: Optional[List] = None,
) -> InterconnectInfo:
    """Time collectives over all local devices (shard_map psum/all_gather)."""
    import jax

    devs = devices if devices is not None else jax.devices()
    info = InterconnectInfo(num_devices=len(devs))
    info.topology = _topology_string(devs)
    try:
        info.num_slices = len({getattr(d, "slice_index", 0) for d in devs})
    except Exception:
        info.num_slices = 1
    if len(devs) < 2:
        return info

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    shard_map = jax.shard_map

    n = len(devs)
    mesh = Mesh(np.array(devs), ("d",))

    try:
        # Small-message all-reduce latency.
        tiny = jax.device_put(
            jnp.ones((n, 8), dtype=jnp.float32),
            NamedSharding(mesh, P("d", None)),
        )
        f = jax.jit(
            shard_map(
                lambda x: jax.lax.psum(x, "d"),
                mesh=mesh,
                in_specs=P("d", None),
                out_specs=P(None),
            )
        )
        # Sync by FETCHING one element, not block_until_ready: tunneled
        # runtimes acknowledge before completion (see profiler.device.bench).
        def sync(out):
            np.asarray(jnp.ravel(out)[0])

        sync(f(tiny))  # compile
        times = []
        for _ in range(latency_iters):
            t0 = time.perf_counter()
            sync(f(tiny))
            times.append(time.perf_counter() - t0)
        info.ici_allreduce_latency_s = sorted(times)[len(times) // 2]

        # Large-message all-gather bandwidth.
        per_dev = (bandwidth_mb * 1024 * 1024) // 4
        big = jax.device_put(
            jnp.ones((n, per_dev), dtype=jnp.float32),
            NamedSharding(mesh, P("d", None)),
        )
        g = jax.jit(
            shard_map(
                lambda x: jax.lax.all_gather(x, "d"),
                mesh=mesh,
                in_specs=P("d", None),
                out_specs=P(None),
                check_vma=False,  # output is replicated; inference can't prove it
            )
        )
        sync(g(big))  # compile
        t0 = time.perf_counter()
        sync(g(big))
        dt = time.perf_counter() - t0
        # Each device receives (n-1) remote shards of per_dev floats.
        info.ici_bandwidth = (n - 1) * per_dev * 4 / dt if dt > 0 else 0.0
    except Exception:
        pass
    return info


def estimate_t_comm(payload_bytes: int = 1024 * 1024) -> float:
    """Per-round inter-device time for a payload: latency + payload/bandwidth.

    The TPU-native replacement for the reference's hand-measured ``t_comm``
    fixture scalar (test/profiles/llama_3_70b/online/m1.json).
    """
    info = measure_interconnect()
    if info.num_devices < 2:
        return 0.0
    bw = info.ici_bandwidth or float("inf")
    return info.ici_allreduce_latency_s + payload_bytes / bw

"""ICI/DCN interconnect probing: a measured communication-cost model.

The reference has no communication backend at all — inter-device cost is a
single hand-edited scalar ``t_comm`` per device profile
(/root/reference/src/distilp/common/device.py:50, set to 0 by its profiler at
profiler/device.py:719). Here ``t_comm``-class coefficients are *measured*
from the visible JAX mesh: a small psum across all local devices gives the
per-round collective latency (ICI rides this on TPU), and a large all-gather
gives sustained link bandwidth. On a single-device host everything stays 0
and the solver behaves exactly like the reference.

Works unchanged on the CPU-backend virtual mesh used in tests
(``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import time
from typing import List, Optional

from .datatypes import InterconnectInfo


def _topology_string(devices) -> str:
    coords = [getattr(d, "coords", None) for d in devices]
    if not coords or any(c is None for c in coords):
        return ""
    dims = len(coords[0])
    extents = [len({c[i] for c in coords}) for i in range(dims)]
    return "x".join(str(e) for e in extents)


def _slice_of(dev) -> int:
    """Slice id of a device: which ICI island it belongs to. Devices in
    different slices only reach each other over DCN."""
    return int(getattr(dev, "slice_index", 0) or 0)


def measure_interconnect(
    latency_iters: int = 10,
    bandwidth_mb: int = 32,
    devices: Optional[List] = None,
    slice_of=None,
) -> InterconnectInfo:
    """Time collectives over all local devices (shard_map psum/all_gather,
    via utils.shardcompat — works on jax 0.4.37's experimental spelling).

    When the device set spans more than one slice (multi-slice TPU pods:
    ICI inside a slice, DCN between slices), a second pair of collectives
    over one-device-per-slice measures the DCN latency and bandwidth
    separately — the cross-slice numbers the solver needs to price
    pipeline hops that leave the slice. ``slice_of`` overrides the slice
    keying (tests use it to split a virtual CPU mesh into fake slices).
    """
    import jax

    devs = devices if devices is not None else jax.devices()
    slice_of = slice_of if slice_of is not None else _slice_of
    info = InterconnectInfo(num_devices=len(devs))
    info.topology = _topology_string(devs)
    try:
        slices: dict = {}
        for d in devs:
            slices.setdefault(slice_of(d), []).append(d)
        info.num_slices = len(slices)
    except Exception:
        slices = {0: list(devs)}
        info.num_slices = 1
    if len(devs) < 2:
        return info  # provenance stays "unmeasured": nothing was probed

    # Provenance: collectives over host-platform virtual devices time the
    # host's memory system, not any interconnect — mark them so the saved
    # profile can never pass virtual numbers off as a measured link.
    platform = str(getattr(devs[0], "platform", "") or "")
    info.provenance = "virtual" if platform == "cpu" else "measured"

    # ICI: collectives inside ONE slice (the largest with >=2 devices);
    # with a single slice that is simply all devices.
    ici_devs = max(slices.values(), key=len)
    if len(ici_devs) >= 2:
        lat, bw = _collective_times(ici_devs, latency_iters, bandwidth_mb)
        info.ici_allreduce_latency_s = lat
        info.ici_bandwidth = bw

    # DCN: collectives across slices, one device per slice, so every hop
    # of the measured ring leaves its ICI island.
    if info.num_slices > 1:
        dcn_devs = [group[0] for group in slices.values()]
        lat, bw = _collective_times(dcn_devs, latency_iters, bandwidth_mb)
        info.dcn_latency_s = lat
        info.dcn_bandwidth = bw
    return info


def _collective_times(devs: List, latency_iters: int, bandwidth_mb: int):
    """(small-psum latency s, large-all-gather bytes/s) over ``devs``."""
    import jax
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..utils.shardcompat import shard_map
    n = len(devs)
    mesh = Mesh(np.array(devs), ("d",))
    latency = bandwidth = 0.0
    try:
        # Small-message all-reduce latency.
        tiny = jax.device_put(
            jnp.ones((n, 8), dtype=jnp.float32),
            NamedSharding(mesh, P("d", None)),
        )
        f = jax.jit(
            shard_map(
                lambda x: jax.lax.psum(x, "d"),
                mesh=mesh,
                in_specs=P("d", None),
                out_specs=P(None),
            )
        )
        # Sync by FETCHING one element, not block_until_ready: tunneled
        # runtimes acknowledge before completion (see profiler.device.bench).
        def sync(out):
            np.asarray(jnp.ravel(out)[0])

        sync(f(tiny))  # compile
        times = []
        for _ in range(latency_iters):
            t0 = time.perf_counter()
            sync(f(tiny))
            times.append(time.perf_counter() - t0)
        latency = sorted(times)[len(times) // 2]

        # Large-message all-gather bandwidth.
        per_dev = (bandwidth_mb * 1024 * 1024) // 4
        big = jax.device_put(
            jnp.ones((n, per_dev), dtype=jnp.float32),
            NamedSharding(mesh, P("d", None)),
        )
        g = jax.jit(
            shard_map(
                lambda x: jax.lax.all_gather(x, "d"),
                mesh=mesh,
                in_specs=P("d", None),
                out_specs=P(None),
                check_vma=False,  # output is replicated; inference can't prove it
            )
        )
        sync(g(big))  # compile
        t0 = time.perf_counter()
        sync(g(big))
        dt = time.perf_counter() - t0
        # Each device receives (n-1) remote shards of per_dev floats.
        bandwidth = (n - 1) * per_dev * 4 / dt if dt > 0 else 0.0
    except Exception:
        pass
    return latency, bandwidth


def estimate_t_comm(
    payload_bytes: int = 1024 * 1024,
    info: Optional[InterconnectInfo] = None,
) -> float:
    """Per-round inter-device time for a payload: latency + payload/bandwidth.

    The TPU-native replacement for the reference's hand-measured ``t_comm``
    fixture scalar (test/profiles/llama_3_70b/online/m1.json, 0.06355 s for
    a home-network fleet): the same latency + size/bandwidth shape, derived
    from timed collectives instead of a hand edit. Uses the slowest link the
    fleet spans — DCN when the mesh crosses slices, ICI otherwise — because
    a pipeline round is paced by its slowest hop. Pass a pre-measured
    ``info`` to avoid re-running the collectives.
    """
    if info is None:
        info = measure_interconnect()
    if info.num_devices < 2:
        return 0.0
    if info.num_slices > 1 and (info.dcn_latency_s > 0 or info.dcn_bandwidth > 0):
        lat, bw = info.dcn_latency_s, info.dcn_bandwidth or float("inf")
    else:
        lat, bw = info.ici_allreduce_latency_s, info.ici_bandwidth or float("inf")
    return lat + payload_bytes / bw

"""Model profile schemas: analytic per-layer cost data as a JSON contract.

Three forms, wire-compatible with the reference
(/root/reference/src/distilp/common/model.py:12-251):

- ``ModelProfile``      — solver-facing scalars for a "typical" layer.
- ``ModelProfilePhased`` — {prefill, decode} pair of ``ModelProfile``.
- ``ModelProfileSplit``  — raw profiler output: per-layer arrays split by phase.

The Split→scalar conversion picks layer index 1 (the first real decoder layer;
index 0 is a synthetic placeholder) and the decode phase by default, exactly as
the reference loader does (common/model.py:193-251), because the golden solver
objectives are pinned to that choice.
"""

from __future__ import annotations

from typing import Dict, List, Literal, Optional

from pydantic import BaseModel, Field

from .types import ModelPhase, QuantizationLevel


class ModelProfile(BaseModel):
    """Solver input: architecture scalars + typical-layer cost scalars.

    Optionally carries the per-layer arrays and MoE component breakdowns the
    profiler produced, for detailed analysis and the MoE co-assignment solver.
    """

    # Architecture (paper symbols in comments)
    L: int = 0  # decoder layer count
    hk: int = 0  # KV heads (keys), h_k
    ek: int = 0  # head dim (keys), e_k
    hv: int = 0  # KV heads (values), h_v
    ev: int = 0  # head dim (values), e_v
    n_kv: int = 0  # KV-cache token capacity, n_kv
    e_embed: int = 0  # hidden size, e
    V: int = 0  # vocab size

    # Typical-layer scalars consumed by the solver
    b_layer: int = 0  # weight bytes per typical layer, b
    b_in: int = 0  # input-layer bytes, b_i
    b_out: int = 0  # output-layer bytes, b_o
    f_q: Dict[str, float] = Field(default_factory=dict)  # {"b_<B>": FLOPs} typical layer
    f_out: Dict[str, float] = Field(default_factory=dict)  # {"b_<B>": FLOPs} output layer
    Q: QuantizationLevel = "F16"  # quant level used for throughput lookup

    # Optional per-layer arrays (length L+1; index 0 is the synthetic layer)
    b_layers: Optional[List[int]] = None
    b_i_layers: Optional[List[int]] = None
    b_o_layers: Optional[List[int]] = None
    f_q_layers: Optional[Dict[str, List[float]]] = None

    # Profiler metadata
    seq_len: int = 0
    quantization: QuantizationLevel = "F16"

    # MoE configuration
    is_moe: bool = False
    n_routed_experts: int = 0
    n_shared_experts: int = 0
    experts_per_token: int = 0
    moe_intermediate_size: int = 0
    moe_layer_freq: int = 1
    first_k_dense_replace: int = 0
    total_moe_layers: int = 0
    moe_layer_indices: Optional[List[int]] = None

    # MoE per-layer component metrics (keys are layer indices)
    attn_bytes: Optional[List[int]] = None
    attn_flops: Optional[Dict[str, List[float]]] = None
    bytes_per_expert: Optional[Dict[int, int]] = None
    bytes_shared_experts: Optional[Dict[int, int]] = None
    flops_per_expert: Optional[Dict[int, float]] = None
    flops_shared_experts: Optional[Dict[int, float]] = None
    router_flops: Optional[Dict[int, float]] = None
    router_bytes: Optional[Dict[int, int]] = None
    flops_per_active_expert_per_token: Optional[Dict[int, float]] = None

    # Measured expert popularity (extension; the natural carrier the
    # reference's per-expert metric dicts suggest but never fill,
    # /root/reference/src/distilp/common/model.py:79-85): entry e is the
    # relative token load routed to expert e, mean-1 normalized by the
    # solver. None = uniform routing. A streaming deployment refreshes this
    # from router statistics and re-solves; see ``solver.routing``.
    expert_loads: Optional[List[float]] = None

    def summary(self) -> str:
        mib = 1024.0**2
        lines = [
            "=" * 60,
            "Model Profile:",
            "=" * 60,
            f"  Layers (L): {self.L}",
        ]
        if self.b_layer > 0:
            lines.append(f"  Bytes per layer: {self.b_layer / mib:.1f} MB")
        if self.b_in > 0:
            lines.append(f"  Input bytes: {self.b_in / mib:.1f} MB")
        if self.b_out > 0:
            lines.append(f"  Output bytes: {self.b_out / mib:.1f} MB")
        lines += [
            f"  Attention heads (k/v): {self.hk}/{self.hv}",
            f"  Head dimensions (k/v): {self.ek}/{self.ev}",
            f"  KV cache tokens: {self.n_kv}",
            f"  Embedding dimension: {self.e_embed}",
            f"  Vocabulary size: {self.V}",
            f"  Quantization: {self.Q}",
        ]
        return "\n".join(lines)

    def print_summary(self) -> None:
        print(self.summary())


class ModelProfilePhased(BaseModel):
    """Prefill + decode profiles produced in one profiling run."""

    prefill: ModelProfile
    decode: ModelProfile

    def to_model_profile(
        self, phase: Literal["decode", "prefill"] = "decode"
    ) -> ModelProfile:
        if phase == "decode":
            return self.decode
        if phase == "prefill":
            return self.prefill
        raise ValueError(f"Invalid phase: {phase!r}. Must be 'decode' or 'prefill'.")


class ModelProfileSplit(BaseModel):
    """Raw profiler output: per-layer arrays, phase-split FLOPs, MoE components.

    Arrays have length L+1; index 0 is a synthetic placeholder row so that
    array index == decoder layer index for the real layers.
    """

    # Per-layer arrays
    b: List[int]  # weight bytes per layer
    b_i: List[int]  # input activation bytes per layer
    b_o: List[int]  # output activation bytes per layer

    # Architecture
    L: int
    hk: int
    hv: int
    ek: int
    ev: int
    n_kv: int
    e_embed: int
    V: int
    seq_len: int

    # {phase: {"b_<B>": [FLOPs per layer]}} and {phase: {"b_<B>": output FLOPs}}
    f_q: Dict[ModelPhase, Dict[str, List[float]]]
    f_out: Dict[ModelPhase, Dict[str, float]]
    quantization: QuantizationLevel

    # MoE
    is_moe: bool = False
    n_routed_experts: int = 0
    n_shared_experts: int = 0
    experts_per_token: int = 0
    moe_intermediate_size: int = 0
    moe_layer_freq: int = 0
    first_k_dense_replace: int = 0
    total_moe_layers: int = 0
    moe_layer_indices: List[int] = Field(default_factory=list)

    # Component metrics for the expert co-assignment solver
    attn_bytes: List[int] = Field(default_factory=list)
    attn_flops: Dict[ModelPhase, Dict[str, List[float]]] = Field(default_factory=dict)
    bytes_per_expert: Dict[int, int] = Field(default_factory=dict)
    bytes_shared_experts: Dict[int, int] = Field(default_factory=dict)
    flops_per_expert: Dict[int, float] = Field(default_factory=dict)
    flops_shared_experts: Dict[int, float] = Field(default_factory=dict)
    router_flops: Dict[int, float] = Field(default_factory=dict)
    router_bytes: Dict[int, int] = Field(default_factory=dict)
    flops_per_active_expert_per_token: Dict[int, float] = Field(default_factory=dict)

    def to_model_profile(
        self, phase: Literal["decode", "prefill"] = "decode"
    ) -> ModelProfile:
        """Collapse per-layer arrays into the solver's typical-layer scalars.

        Layer index 1 is the representative layer; per-batch FLOPs come from
        the requested phase. Parity with the reference loader is required for
        the golden solver objectives (common/model.py:193-251).
        """
        typical = 1

        def pick(arr: List[int]) -> int:
            return arr[typical] if len(arr) > typical else 0

        f_q_scalars = {
            batch_key: values[typical]
            for batch_key, values in self.f_q[phase].items()
            if isinstance(values, list) and len(values) > typical
        }

        return ModelProfile(
            L=self.L,
            b_layer=pick(self.b),
            b_in=pick(self.b_i),
            b_out=pick(self.b_o),
            hk=self.hk,
            ek=self.ek,
            hv=self.hv,
            ev=self.ev,
            n_kv=self.n_kv,
            e_embed=self.e_embed,
            V=self.V,
            f_q=f_q_scalars,
            f_out=dict(self.f_out[phase]),
            Q=self.quantization,
            quantization=self.quantization,
            is_moe=self.is_moe,
            n_routed_experts=self.n_routed_experts,
            n_shared_experts=self.n_shared_experts,
            experts_per_token=self.experts_per_token,
            moe_intermediate_size=self.moe_intermediate_size,
            moe_layer_freq=self.moe_layer_freq,
            first_k_dense_replace=self.first_k_dense_replace,
            total_moe_layers=self.total_moe_layers,
            moe_layer_indices=self.moe_layer_indices,
            attn_bytes=self.attn_bytes,
            attn_flops=self.attn_flops.get(phase, {}),
            bytes_per_expert=self.bytes_per_expert,
            bytes_shared_experts=self.bytes_shared_experts,
            flops_per_expert=self.flops_per_expert,
            flops_shared_experts=self.flops_shared_experts,
            router_flops=self.router_flops,
            router_bytes=self.router_bytes,
            flops_per_active_expert_per_token=self.flops_per_active_expert_per_token,
        )

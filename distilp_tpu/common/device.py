"""Device profile schema: the JSON contract between device profiling and the solver.

Field names, types and defaults are wire-compatible with the reference schema
(/root/reference/src/distilp/common/device.py:12-93) — golden fixture JSONs
must validate unchanged. Comments keep the paper-symbol mapping so the solver
math stays auditable against prima.cpp (arXiv:2504.08791) notation.
"""

from __future__ import annotations

from typing import Dict, Optional

from pydantic import BaseModel, Field

from .types import QuantizationLevel

# {quant level -> {"b_<batch>": FLOPS}} throughput table.
ThroughputTable = Dict[QuantizationLevel, Dict[str, float]]


class DeviceProfile(BaseModel):
    """One device's measured characteristics, as consumed by the HALDA solver.

    Produced by ``distilp_tpu.profiler.device`` (or hand-written for fleets),
    consumed by ``distilp_tpu.solver``. All fields default so the profiler can
    build the record incrementally; the solver expects a fully populated one.
    """

    # Identity
    name: str = ""
    os_type: str = ""  # 'mac_no_metal' | 'mac_metal' | 'linux' | 'android' | 'tpu'
    is_head: bool = True  # I_{m=1}: head device owns the input/output layers
    is_unified_mem: bool = False  # I_UMA: unified host/accelerator memory
    has_cuda: bool = False
    has_metal: bool = False
    has_tpu: bool = False  # extension: TPU accelerator attached to this host

    # CPU compute: s^{cpu}_{m,q} FLOPS table per quant level and batch,
    # and T^{cpu}_m register-load throughput in bytes/s.
    scpu: ThroughputTable = Field(default_factory=dict)
    T_cpu: float = 0.0

    # KV-cache copy time (seconds) for the fixed probe payload.
    t_kvcpy_cpu: float = 0.0
    t_kvcpy_gpu: float = 0.0

    # Host<->accelerator and inter-device transfer times (seconds).
    t_ram2vram: float = 0.0
    t_vram2ram: float = 0.0
    t_comm: float = 0.0  # t^{comm}_m: per-round inter-device communication time
    # Interconnect link shape behind t_comm (extension; 0 = unmeasured).
    # t_comm above is latency + activation_payload/bandwidth at profile time;
    # carrying the two terms lets the solver price OTHER payloads (e.g. the
    # MoE all-to-all token dispatch) on the same measured link instead of
    # reusing the scalar for every message size.
    comm_latency: float = 0.0  # seconds, small-message collective latency
    comm_bandwidth: float = 0.0  # bytes/s, sustained large-message link rate

    # Disk read throughput s^{disk}_m (bytes/s).
    s_disk: float = 0.0

    # Capacities (bytes).
    d_avail_ram: int = 0

    # Accelerator compute tables and capacities (None when absent).
    sgpu_cuda: Optional[ThroughputTable] = None
    sgpu_metal: Optional[ThroughputTable] = None
    sgpu_tpu: Optional[ThroughputTable] = None
    T_cuda: Optional[float] = None
    T_metal: Optional[float] = None
    T_tpu: Optional[float] = None
    d_avail_cuda: Optional[int] = None
    d_avail_metal: Optional[int] = None
    d_avail_tpu: Optional[int] = None

    # Compute scratch buffers (bytes), reserved out of the memory caps.
    c_cpu: int = 0
    c_gpu: int = 0

    # Swap headroom (Android only in practice).
    d_bytes_can_swap: int = 0
    d_swap_avail: int = 0

    def gpu_table(self) -> Optional[ThroughputTable]:
        """The accelerator FLOPS table the solver should use.

        TPU (this framework's extension) wins, then Metal over CUDA as in the
        reference (/root/reference/src/distilp/solver/components/dense_common.py:78-86).
        """
        if self.has_tpu and self.sgpu_tpu:
            return self.sgpu_tpu
        if self.has_metal and self.sgpu_metal:
            return self.sgpu_metal
        if self.has_cuda and self.sgpu_cuda:
            return self.sgpu_cuda
        return None

    def gpu_T(self) -> Optional[float]:
        """Accelerator register-load throughput, with the same preference order.

        Parity: /root/reference/src/distilp/solver/components/dense_common.py:89-97.
        """
        if self.has_tpu and self.T_tpu:
            return self.T_tpu
        if self.has_metal and self.T_metal:
            return self.T_metal
        if self.has_cuda and self.T_cuda:
            return self.T_cuda
        return None

    def has_gpu_backend(self) -> bool:
        """Whether any accelerator layers can be placed on this device (n_i > 0)."""
        return bool(
            (self.has_tpu and self.d_avail_tpu is not None)
            or (self.has_cuda and self.d_avail_cuda is not None)
            or (self.has_metal and self.d_avail_metal is not None)
        )

    def summary(self) -> str:
        """Human-readable one-device summary."""
        gib = 1024.0**3
        lines = [
            f"   OS Type: {self.os_type}",
            f"   RAM: {self.d_avail_ram / gib:.1f} GB",
            f"   Is Head: {self.is_head}",
            f"   Unified Memory: {self.is_unified_mem}",
        ]
        if self.has_cuda and self.d_avail_cuda:
            lines.append(f"   CUDA: {self.d_avail_cuda / gib:.1f} GB")
        if self.has_metal and self.d_avail_metal:
            lines.append(f"   Metal: {self.d_avail_metal / gib:.1f} GB")
        lines.append(f"   Disk Speed: {self.s_disk / 1024**2:.1f} MB/s")
        return "\n".join(lines)

    def print_summary(self) -> None:
        print(self.summary())

"""Profile schemas: the JSON contracts shared by profiler and solver.

Always importable with only pydantic installed; the heavy deps (JAX, scipy)
live behind the solver/profiler subpackages, mirroring the reference's
load-bearing extras split (reference pyproject.toml:17-26).
"""

from .device import DeviceProfile, ThroughputTable
from .loaders import (
    load_device_profile,
    load_devices_and_model,
    load_from_profile_folder,
    load_model_profile,
)
from .model import ModelProfile, ModelProfilePhased, ModelProfileSplit
from .types import (
    ALL_QUANT_LEVELS,
    KV_BITS_FACTORS,
    ModelPhase,
    QuantizationLevel,
    kv_bits_to_factor,
)

__all__ = [
    "DeviceProfile",
    "ThroughputTable",
    "ModelProfile",
    "ModelProfilePhased",
    "ModelProfileSplit",
    "ModelPhase",
    "QuantizationLevel",
    "ALL_QUANT_LEVELS",
    "KV_BITS_FACTORS",
    "kv_bits_to_factor",
    "load_device_profile",
    "load_model_profile",
    "load_devices_and_model",
    "load_from_profile_folder",
]

"""Shared type vocabulary for profiles.

JSON-contract parity with the reference type vocabulary
(/root/reference/src/distilp/common/types.py:3-4): the set of quantization
labels and model phases is the wire format shared by profiler output and
solver input, so it must match exactly.
"""

from typing import Literal

ModelPhase = Literal["merged", "prefill", "decode"]

QuantizationLevel = Literal["Q4_K", "Q5_K", "Q6_K", "Q8_0", "BF16", "F16", "F32"]

# All quantization levels, in canonical order (useful for building throughput tables).
ALL_QUANT_LEVELS: tuple[QuantizationLevel, ...] = (
    "Q4_K",
    "Q5_K",
    "Q6_K",
    "Q8_0",
    "BF16",
    "F16",
    "F32",
)

# Bytes per element stored in the KV cache, by kv-cache quantization label.
# Parity: /root/reference/src/distilp/solver/halda_p_solver.py:39-56.
KV_BITS_FACTORS: dict[str, float] = {
    "4bit": 0.5,
    "8bit": 1.0,
    "fp16": 2.0,
    "bf16": 2.0,
}


def kv_bits_to_factor(kv_bits: str) -> float:
    """Map a kv-cache quantization label to bytes/element."""
    key = kv_bits.strip().lower()
    try:
        return KV_BITS_FACTORS[key]
    except KeyError:
        raise ValueError(
            f"Unsupported kv_bits {kv_bits!r}; expected one of {sorted(KV_BITS_FACTORS)}"
        ) from None

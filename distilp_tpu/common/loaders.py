"""Profile JSON loaders.

Loader semantics match the reference CLI loaders
(/root/reference/src/cli/solver.py:15-86) so that fixture folders are
interchangeable between implementations:

- a profile folder holds ``model_profile.json`` plus any number of device
  JSONs (every other ``*.json``), sorted by filename;
- the first device is forced to be the head;
- a model JSON whose ``f_q`` has ``prefill``/``decode`` keys is the Split
  format and is collapsed to solver scalars from the decode phase.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Tuple

from .device import DeviceProfile
from .model import ModelProfile, ModelProfileSplit


def load_device_profile(path: str | Path) -> DeviceProfile:
    with open(path, "r") as f:
        return DeviceProfile.model_validate(json.load(f))


def parse_model_profile(data: dict) -> ModelProfile | ModelProfileSplit:
    """Validate a model-profile dict, sniffing scalar vs Split wire format.

    A ``f_q`` dict with ``prefill``/``decode`` keys marks the Split format.
    """
    f_q = data.get("f_q")
    if isinstance(f_q, dict) and "prefill" in f_q and "decode" in f_q:
        return ModelProfileSplit.model_validate(data)
    return ModelProfile.model_validate(data)


def load_model_profile(path: str | Path) -> ModelProfile:
    """Load either scalar (ModelProfile) or array (ModelProfileSplit) format."""
    with open(path, "r") as f:
        data = json.load(f)

    model = parse_model_profile(data)
    if isinstance(model, ModelProfileSplit):
        return model.to_model_profile()
    return model


def load_devices_and_model(
    device_paths: List[str | Path], model_path: str | Path
) -> Tuple[List[DeviceProfile], ModelProfile]:
    devices = []
    for i, p in enumerate(device_paths):
        device = load_device_profile(p)
        if i == 0:
            device.is_head = True
        devices.append(device)
    return devices, load_model_profile(model_path)


def load_from_profile_folder(
    folder: str | Path,
) -> Tuple[List[DeviceProfile], ModelProfile]:
    folder = Path(folder)
    if not folder.exists():
        fallback = Path("tests/profiles") / folder
        if not fallback.exists():
            raise FileNotFoundError(f"Profile folder not found: {folder}")
        folder = fallback

    model_file = folder / "model_profile.json"
    if not model_file.exists():
        raise FileNotFoundError(f"model_profile.json not found in {folder}")

    device_files = sorted(
        str(p) for p in folder.glob("*.json") if p.name != "model_profile.json"
    )
    if not device_files:
        raise ValueError(f"No device profiles found in {folder}")

    return load_devices_and_model(device_files, model_file)

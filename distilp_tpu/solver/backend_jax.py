"""JAX/TPU backend: the whole HALDA k-sweep as one batched computation.

Where the reference hands each fixed-k MILP to HiGHS branch-and-cut on the
host (/root/reference/src/distilp/solver/halda_p_solver.py:340-346, one
sequential call per k), this backend turns the *entire sweep* into accelerator
work:

- every k-candidate's LP relaxation and every branch-and-bound node is one
  element of a single batched Mehrotra IPM call (``distilp_tpu.ops.ipm``);
- integer incumbents come from an exact, vectorized rounding heuristic (the
  continuous (z, C) block of the MILP has a closed form given integers);
- pruning uses the kernel's rigorous Lagrangian bounds, so the mip-gap
  certificate does not depend on IPM convergence;
- one global incumbent prunes across all k trees simultaneously (the final
  answer is the min over k, so cross-k pruning is sound).

The search state lives in fixed-capacity arrays (no data-dependent shapes);
the host loop only inspects two scalars per round (gap, live-node count).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax

# The gap certificate needs ~1e-9 LP accuracy; f32 tops out around 1e-4.
# On TPU f64 is emulated but these problems are tiny.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from ..ops.ipm import LPBatch, ipm_solve_batch  # noqa: E402
from .assemble import INACTIVE_RHS, MilpArrays  # noqa: E402
from .coeffs import HaldaCoeffs  # noqa: E402
from .result import ILPResult  # noqa: E402

DTYPE = jnp.float64

# Fixed frontier capacity. HALDA trees are shallow (the LP optimum is
# near-integral), so this is generous; overflow is tracked honestly via
# ``dropped_bound`` rather than silently ignored.
NODE_CAP = 128
MAX_ROUNDS = 64
FRAC_TOL = 1e-6


class RoundingData(NamedTuple):
    """Exact per-device MILP data for the integer rounding heuristic."""

    a: jax.Array  # (M,)
    b_gpu: jax.Array
    pen_set: jax.Array  # (M,) penalty of the device's own RAM slack
    pen_vram: jax.Array
    busy_const: jax.Array
    s_disk: jax.Array
    ram_rhs: jax.Array
    ram_minus_n: jax.Array  # float 0/1
    cuda_rhs: jax.Array  # +inf when row inactive
    metal_rhs: jax.Array  # +inf when row inactive
    has_gpu: jax.Array  # float 0/1
    bprime: jax.Array  # scalar


@dataclass
class StandardForm:
    """Host-assembled arrays of the boxed-standard-form LP family.

    Variables: [x_struct (7M+1) | row slacks (6M)]; rows: 6M scaled
    inequality rows turned equalities + the sum(w)=W equality.
    """

    A: np.ndarray  # (m, nf) row-scaled
    b_k: np.ndarray  # (n_k, m)
    c_k: np.ndarray  # (n_k, nf)
    lo_k: np.ndarray  # (n_k, nf) root boxes
    hi_k: np.ndarray  # (n_k, nf)
    int_mask: np.ndarray  # (nf,) bool — branchable columns
    ks: List[int]
    Ws: List[int]
    M: int
    obj_const: float


def _root_boxes(arrays: MilpArrays, coeffs_like: RoundingData, W: int) -> Tuple[np.ndarray, np.ndarray]:
    """Finite boxes for every variable at one k.

    z and C are nominally free above, but any *optimal* solution satisfies
    z_i <= F_i^max and C <= max_i(B_i^max + F_i^max), so these bounds are
    valid for branch-and-bound. Boxing everything is what makes the
    Lagrangian bound rigorous for any dual vector.
    """
    M = arrays.layout.M
    lo, hi = arrays.bounds_for_k(W)

    a = np.asarray(coeffs_like.a)
    b_gpu = np.asarray(coeffs_like.b_gpu)
    pen_set = np.asarray(coeffs_like.pen_set)
    pen_vram = np.asarray(coeffs_like.pen_vram)
    busy_const = np.asarray(coeffs_like.busy_const)
    s_disk = np.asarray(coeffs_like.s_disk)
    has_gpu = np.asarray(coeffs_like.has_gpu)
    bp = float(coeffs_like.bprime)

    F_max = W * bp / s_disk
    B_max = (
        a * W
        + np.maximum(b_gpu, 0.0) * W
        + pen_set * W
        + pen_vram * W * has_gpu
        + busy_const
    )
    z_ub = F_max
    C_ub = float(np.max(B_max + F_max)) if M else 1.0

    hi = hi.copy()
    hi[6 * M : 7 * M] = z_ub
    hi[7 * M] = C_ub
    return lo, hi


def build_standard_form(
    arrays: MilpArrays, coeffs: HaldaCoeffs, kWs: Sequence[Tuple[int, int]]
) -> StandardForm:
    """Row-scale the MILP and emit the per-k (b, c, box) family."""
    M = arrays.layout.M
    N = arrays.layout.n_vars
    m_ub = arrays.A_ub.shape[0]
    nf = N + m_ub
    m = m_ub + 1

    rdata = rounding_data(coeffs)

    # Row scaling: each inequality row (incl. its huge inactive RHS) is
    # normalized by its own magnitude; the slack column keeps coefficient 1
    # (slacks live in scaled units, boxed below).
    row_mag = np.maximum(np.abs(arrays.A_ub).max(axis=1), np.abs(arrays.b_ub))
    row_scale = 1.0 / np.maximum(row_mag, 1.0)

    A = np.zeros((m, nf))
    A[:m_ub, :N] = arrays.A_ub * row_scale[:, None]
    A[:m_ub, N:] = np.eye(m_ub)
    A[m_ub, :N] = arrays.A_eq[0]
    b_ub_scaled = arrays.b_ub * row_scale

    n_k = len(kWs)
    b_k = np.zeros((n_k, m))
    c_k = np.zeros((n_k, nf))
    lo_k = np.zeros((n_k, nf))
    hi_k = np.zeros((n_k, nf))

    for j, (k, W) in enumerate(kWs):
        b_k[j, :m_ub] = b_ub_scaled
        b_k[j, m_ub] = float(W)
        c_k[j, :N] = arrays.c_for_k(k)

        lo_s, hi_s = _root_boxes(arrays, rdata, W)
        lo_k[j, :N] = lo_s
        hi_k[j, :N] = hi_s
        # Slack boxes: s_row = b_row - min_v(A_row v) over the structural box.
        Arow = A[:m_ub, :N]
        smin = np.minimum(Arow * lo_s[None, :], Arow * hi_s[None, :]).sum(axis=1)
        hi_k[j, N:] = np.maximum(b_ub_scaled - smin, 0.0)

    int_mask = np.zeros(nf, dtype=bool)
    int_mask[:N] = arrays.integrality.astype(bool)

    return StandardForm(
        A=A,
        b_k=b_k,
        c_k=c_k,
        lo_k=lo_k,
        hi_k=hi_k,
        int_mask=int_mask,
        ks=[k for k, _ in kWs],
        Ws=[W for _, W in kWs],
        M=M,
        obj_const=arrays.obj_const,
    )


def rounding_data(coeffs: HaldaCoeffs) -> RoundingData:
    pen_by_set = np.where(
        coeffs.set_id == 1,
        coeffs.pen_m1,
        np.where(coeffs.set_id == 2, coeffs.pen_m2, coeffs.pen_m3),
    )
    return RoundingData(
        a=jnp.asarray(coeffs.a, DTYPE),
        b_gpu=jnp.asarray(coeffs.b_gpu, DTYPE),
        pen_set=jnp.asarray(pen_by_set, DTYPE),
        pen_vram=jnp.asarray(coeffs.pen_vram, DTYPE),
        busy_const=jnp.asarray(coeffs.busy_const, DTYPE),
        s_disk=jnp.asarray(coeffs.s_disk, DTYPE),
        ram_rhs=jnp.asarray(
            np.where(np.isfinite(coeffs.ram_rhs), coeffs.ram_rhs, INACTIVE_RHS), DTYPE
        ),
        ram_minus_n=jnp.asarray(coeffs.ram_minus_n.astype(float), DTYPE),
        cuda_rhs=jnp.asarray(
            np.where(coeffs.cuda_row, coeffs.cuda_rhs, np.inf), DTYPE
        ),
        metal_rhs=jnp.asarray(
            np.where(coeffs.metal_row, coeffs.metal_rhs, np.inf), DTYPE
        ),
        has_gpu=jnp.asarray(coeffs.has_gpu.astype(float), DTYPE),
        bprime=jnp.asarray(coeffs.bprime, DTYPE),
    )


def _round_to_incumbent(v, M, W, k, rd: RoundingData):
    """Exact MILP objective of the best integer point near the LP solution v.

    Given integer (w, n), the minimal feasible slacks are closed-form, and the
    optimal continuous block is z_i = max(0, B_i + F_i - C), C = max_i(B_i +
    F_i/2); so the heuristic's objective is exact, not an LP approximation.

    Returns (obj_linear, w, n) with obj = +inf when rounding failed.
    """
    Wf = jnp.asarray(W, DTYPE)
    w_frac = v[:M]
    n_frac = v[M : 2 * M]

    rem = w_frac - jnp.floor(w_frac)
    w = jnp.clip(jnp.floor(w_frac), 1.0, Wf)

    # Distribute the residual sum(w) - W one unit at a time (|d| <= M for a
    # near-feasible LP point; the final validity check catches the rest).
    def body(state, _):
        w, d = state
        add_score = jnp.where(w < Wf, rem, -jnp.inf)
        sub_score = jnp.where(w > 1.0, -rem, -jnp.inf)
        i_add = jnp.argmax(add_score)
        i_sub = jnp.argmax(sub_score)
        w = jax.lax.cond(
            d > 0,
            lambda w: w.at[i_add].add(1.0),
            lambda w: jax.lax.cond(
                d < 0, lambda w: w.at[i_sub].add(-1.0), lambda w: w, w
            ),
            w,
        )
        return (w, d - jnp.sign(d)), None

    d0 = Wf - w.sum()
    (w, _), _ = jax.lax.scan(body, (w, d0), None, length=M + 4)
    valid = w.sum() == Wf

    n = jnp.clip(jnp.round(n_frac), 0.0, w) * rd.has_gpu

    bp = rd.bprime
    # RAM slack for the device's own set
    resident = bp * w - bp * n * rd.ram_minus_n
    viol_ram = jnp.maximum(resident - rd.ram_rhs, 0.0)
    s_ram = jnp.ceil(viol_ram / bp - 1e-9)
    valid &= jnp.all(s_ram <= Wf)

    # VRAM slack: one t_i covers both CUDA and Metal rows
    viol_vram = jnp.maximum(
        jnp.maximum(bp * n - rd.cuda_rhs, bp * n - rd.metal_rhs), 0.0
    )
    viol_vram = jnp.where(jnp.isfinite(viol_vram), viol_vram, 0.0)
    t = jnp.ceil(viol_vram / bp - 1e-9)
    valid &= jnp.all(t <= Wf * rd.has_gpu + 1e-9)

    pen_cost = rd.pen_set * s_ram + rd.pen_vram * t
    busy = rd.a * w + rd.b_gpu * n + pen_cost + rd.busy_const
    fetch = bp / rd.s_disk * w
    C = jnp.max(busy + 0.5 * fetch)

    k_f = jnp.asarray(k, DTYPE)
    obj = (k_f - 1.0) * C + jnp.sum(rd.a * w + rd.b_gpu * n + pen_cost)
    obj = jnp.where(valid, obj, jnp.inf)
    return obj, w, n


class SearchState(NamedTuple):
    node_lo: jax.Array  # (CAP, nf)
    node_hi: jax.Array  # (CAP, nf)
    node_kidx: jax.Array  # (CAP,) int32
    node_bound: jax.Array  # (CAP,) parent bound (full-objective space)
    active: jax.Array  # (CAP,) bool
    incumbent: jax.Array  # () full-objective incumbent
    inc_w: jax.Array  # (M,)
    inc_n: jax.Array  # (M,)
    inc_kidx: jax.Array  # () int32
    dropped_bound: jax.Array  # () min bound among nodes dropped on overflow
    per_k_best: jax.Array  # (n_k,) best incumbent per k (reporting only)


class SweepData(NamedTuple):
    """Device-resident arrays of one sweep, shared by every B&B round.

    A plain pytree argument (not a closure) so the jitted round function is a
    single module-level callable whose compile cache is reused across
    ``halda_solve`` calls of the same shape.
    """

    A: jax.Array  # (m, nf)
    b_k: jax.Array  # (n_k, m)
    c_k: jax.Array  # (n_k, nf)
    int_mask: jax.Array  # (nf,) bool
    ks: jax.Array  # (n_k,)
    Ws: jax.Array  # (n_k,)
    obj_const: jax.Array  # ()
    rd: RoundingData


def _sweep_data(sf: StandardForm, rd: RoundingData) -> SweepData:
    return SweepData(
        A=jnp.asarray(sf.A, DTYPE),
        b_k=jnp.asarray(sf.b_k, DTYPE),
        c_k=jnp.asarray(sf.c_k, DTYPE),
        int_mask=jnp.asarray(sf.int_mask),
        ks=jnp.asarray(sf.ks, DTYPE),
        Ws=jnp.asarray(sf.Ws, DTYPE),
        obj_const=jnp.asarray(sf.obj_const, DTYPE),
        rd=rd,
    )


def _init_state(sf: StandardForm, cap: Optional[int] = None) -> SearchState:
    """Root frontier: one node per k. An explicit ``cap`` is honored exactly
    (mesh callers pre-pad it to their device count); it must fit the roots."""
    n_k = len(sf.ks)
    nf = sf.A.shape[1]
    if cap is None:
        cap = max(NODE_CAP, 2 * n_k)
    elif cap < n_k:
        raise ValueError(f"frontier cap {cap} cannot hold {n_k} root nodes")
    node_lo = jnp.zeros((cap, nf), DTYPE).at[:n_k].set(jnp.asarray(sf.lo_k, DTYPE))
    node_hi = jnp.zeros((cap, nf), DTYPE).at[:n_k].set(jnp.asarray(sf.hi_k, DTYPE))
    node_kidx = jnp.zeros(cap, jnp.int32).at[:n_k].set(
        jnp.arange(n_k, dtype=jnp.int32)
    )
    active = jnp.zeros(cap, bool).at[:n_k].set(True)
    return SearchState(
        node_lo=node_lo,
        node_hi=node_hi,
        node_kidx=node_kidx,
        node_bound=jnp.full(cap, -jnp.inf, DTYPE),
        active=active,
        incumbent=jnp.asarray(jnp.inf, DTYPE),
        inc_w=jnp.zeros(sf.M, DTYPE),
        inc_n=jnp.zeros(sf.M, DTYPE),
        inc_kidx=jnp.asarray(0, jnp.int32),
        dropped_bound=jnp.asarray(jnp.inf, DTYPE),
        per_k_best=jnp.full(n_k, jnp.inf, DTYPE),
    )


@partial(jax.jit, static_argnames=("ipm_iters", "tier"))
def _bnb_round(
    data: SweepData,
    state: SearchState,
    mip_gap: jax.Array,
    ipm_iters: int = 50,
    tier: Optional[int] = None,
) -> SearchState:
    """One batched branch-and-bound round over the frontier.

    ``tier`` solves only the first ``tier`` slots — valid because compaction
    sorts live nodes to the front — so small trees don't pay for the full
    frontier capacity. The host picks the smallest tier >= live count.
    """
    A, int_mask, ks, Ws, rd = data.A, data.int_mask, data.ks, data.Ws, data.rd
    obj_const = data.obj_const
    M = state.inc_w.shape[0]

    full = state
    if tier is not None and tier < state.node_lo.shape[0]:
        state = state._replace(
            node_lo=state.node_lo[:tier],
            node_hi=state.node_hi[:tier],
            node_kidx=state.node_kidx[:tier],
            node_bound=state.node_bound[:tier],
            active=state.active[:tier],
        )

    b = data.b_k[state.node_kidx]
    c = data.c_k[state.node_kidx]
    res = ipm_solve_batch(
        LPBatch(A=A, b=b, c=c, l=state.node_lo, u=state.node_hi),
        iters=ipm_iters,
    )
    bound = res.bound + obj_const
    # A diverged IPM instance reports -inf (see ops/ipm.py); fall back to the
    # inherited parent bound so the node keeps exploring instead of being
    # NaN-pruned (observed: platform-dependent divergence on the root LP).
    bound = jnp.where(jnp.isfinite(bound), bound, -jnp.inf)
    bound = jnp.where(state.active, jnp.maximum(bound, state.node_bound), jnp.inf)

    # Exact integer incumbents from every active node's LP point.
    obj_lin, w_int, n_int = jax.vmap(
        lambda v, kidx: _round_to_incumbent(v, M, Ws[kidx], ks[kidx], rd)
    )(res.v, state.node_kidx)
    obj_full = jnp.where(state.active, obj_lin + obj_const, jnp.inf)

    best_i = jnp.argmin(obj_full)
    best_obj = obj_full[best_i]
    better = best_obj < state.incumbent
    incumbent = jnp.where(better, best_obj, state.incumbent)
    inc_w = jnp.where(better, w_int[best_i], state.inc_w)
    inc_n = jnp.where(better, n_int[best_i], state.inc_n)
    inc_kidx = jnp.where(better, state.node_kidx[best_i], state.inc_kidx)

    # Per-k reporting incumbents
    per_k_best = state.per_k_best
    per_k_best = jnp.minimum(
        per_k_best,
        jnp.full_like(per_k_best, jnp.inf).at[state.node_kidx].min(obj_full),
    )

    # Prune: a node survives only if its bound can still beat the
    # incumbent by more than the requested relative gap. (With no
    # incumbent yet the threshold must stay +inf, not inf-inf=NaN.)
    threshold = jnp.where(
        jnp.isfinite(incumbent),
        incumbent - mip_gap * jnp.abs(incumbent),
        jnp.inf,
    )
    survive = state.active & (bound < threshold)

    # Close nodes that are provably done: either the box is a single
    # point, or this round's rounded incumbent already achieves the
    # node's lower bound (so nothing better hides in the subtree). An
    # integral-*looking* LP point alone is NOT proof — the IPM may not
    # have converged — so such nodes keep splitting on the widest box.
    width = jnp.where(
        int_mask[None, :], state.node_hi - state.node_lo, 0.0
    )
    fully_fixed = jnp.max(width, axis=1) < 0.5
    achieved = obj_full <= bound + 1e-6 * jnp.maximum(1.0, jnp.abs(bound))
    survive &= ~(fully_fixed | achieved)

    # Branch variable: most fractional if any, else the widest box.
    frac = jnp.abs(res.v - jnp.round(res.v))
    branchable = int_mask[None, :] & (width > 0.5)
    frac_m = jnp.where(branchable, frac, -1.0)
    j_frac = jnp.argmax(frac_m, axis=1)
    max_frac = jnp.take_along_axis(frac_m, j_frac[:, None], axis=1)[:, 0]
    j_wide = jnp.argmax(width, axis=1)
    has_frac = max_frac > FRAC_TOL
    j_star = jnp.where(has_frac, j_frac, j_wide)

    lo_j = jnp.take_along_axis(state.node_lo, j_star[:, None], axis=1)[:, 0]
    hi_j = jnp.take_along_axis(state.node_hi, j_star[:, None], axis=1)[:, 0]
    vj = jnp.take_along_axis(res.v, j_star[:, None], axis=1)[:, 0]
    split = jnp.where(has_frac, vj, 0.5 * (lo_j + hi_j))
    dn = jnp.clip(jnp.floor(split), lo_j, jnp.maximum(hi_j - 1.0, lo_j))
    up = dn + 1.0

    cap = state.node_lo.shape[0]
    rows = jnp.arange(cap)
    # child A: hi_j -> floor(v_j); child B: lo_j -> ceil(v_j)
    hi_a = state.node_hi.at[rows, j_star].set(dn)
    lo_b = state.node_lo.at[rows, j_star].set(up)

    # Children of the solved prefix plus the untouched tail of the frontier.
    child_lo = jnp.concatenate([state.node_lo, lo_b, full.node_lo[cap:]], axis=0)
    child_hi = jnp.concatenate([hi_a, state.node_hi, full.node_hi[cap:]], axis=0)
    child_kidx = jnp.concatenate(
        [state.node_kidx, state.node_kidx, full.node_kidx[cap:]]
    )
    child_bound = jnp.concatenate([bound, bound, full.node_bound[cap:]])
    child_active = jnp.concatenate([survive, survive, full.active[cap:]])

    # Compact best-bound-first back into the full capacity; track what falls off.
    full_cap = full.node_lo.shape[0]
    sort_key = jnp.where(child_active, child_bound, jnp.inf)
    order = jnp.argsort(sort_key)
    keep = order[:full_cap]
    spill = order[full_cap:]
    spill_bound = jnp.min(jnp.where(child_active[spill], child_bound[spill], jnp.inf))
    dropped_bound = jnp.minimum(state.dropped_bound, spill_bound)

    return SearchState(
        node_lo=child_lo[keep],
        node_hi=child_hi[keep],
        node_kidx=child_kidx[keep],
        node_bound=child_bound[keep],
        active=child_active[keep],
        incumbent=incumbent,
        inc_w=inc_w,
        inc_n=inc_n,
        inc_kidx=inc_kidx,
        dropped_bound=dropped_bound,
        per_k_best=per_k_best,
    )



def solve_sweep_jax(
    arrays: MilpArrays,
    kWs: Sequence[Tuple[int, int]],
    mip_gap: float = 1e-4,
    coeffs: Optional[HaldaCoeffs] = None,
    ipm_iters: int = 50,
    debug: bool = False,
) -> Tuple[List[Optional[ILPResult]], Optional[ILPResult]]:
    """Solve the whole k-sweep on the accelerator.

    Returns ``(per_k_results, best)``: one entry per (k, W) pair carrying that
    k's best found incumbent objective (reporting), and the global optimum
    with its integer assignment and the mip-gap certificate. Ks whose
    subproblem is structurally infeasible (W < M: fewer layers per segment
    than devices) come back as None.
    """
    if coeffs is None:
        raise ValueError("solve_sweep_jax requires the HaldaCoeffs used for assembly")
    M = arrays.layout.M

    feasible = [(k, W) for (k, W) in kWs if W >= M]
    results: List[Optional[ILPResult]] = [None] * len(kWs)
    if not feasible:
        return results, None

    sf = build_standard_form(arrays, coeffs, feasible)
    data = _sweep_data(sf, rounding_data(coeffs))
    gap = jnp.asarray(mip_gap, DTYPE)

    state = _init_state(sf)
    cap = int(state.node_lo.shape[0])
    tiers = sorted({t for t in (16, 64, cap) if t <= cap})
    live = len(feasible)
    for _ in range(MAX_ROUNDS):
        tier = next((t for t in tiers if t >= live), cap)
        state = _bnb_round(data, state, gap, ipm_iters=ipm_iters, tier=tier)
        incumbent = float(state.incumbent)
        live_bounds = np.asarray(
            jnp.where(state.active, state.node_bound, jnp.inf)
        )
        best_bound = min(float(live_bounds.min()), float(state.dropped_bound))
        live = int(np.asarray(state.active).sum())
        if debug:
            print(
                f"    [jax] incumbent={incumbent:.6f} bound={best_bound:.6f} "
                f"live={live} tier={tier}"
            )
        if live == 0:
            break
        if np.isfinite(incumbent) and (
            incumbent - best_bound <= mip_gap * abs(incumbent)
        ):
            break

    if not np.isfinite(float(state.incumbent)):
        return results, None

    per_k_best = np.asarray(state.per_k_best)
    inc_k_idx = int(state.inc_kidx)
    inc_w = [int(x) for x in np.asarray(state.inc_w)]
    inc_n = [int(x) for x in np.asarray(state.inc_n)]

    best: Optional[ILPResult] = None
    pos_of = {kW: i for i, kW in enumerate(kWs)}
    for j, (k, W) in enumerate(feasible):
        obj_j = float(per_k_best[j])
        if not np.isfinite(obj_j):
            continue
        if j == inc_k_idx:
            w, n = inc_w, inc_n
            best = ILPResult(k=k, w=w, n=n, obj_value=obj_j)
        else:
            # Reporting-only entry: the k didn't win; re-deriving its exact
            # integer vector would cost another solve, so carry the objective
            # with the assignment left empty.
            w, n = [0] * M, [0] * M
        results[pos_of[(k, W)]] = ILPResult(k=k, w=w, n=n, obj_value=obj_j)
    return results, best

"""JAX/TPU backend: the whole HALDA k-sweep as one fused device program.

Where the reference hands each fixed-k MILP to HiGHS branch-and-cut on the
host (/root/reference/src/distilp/solver/halda_p_solver.py:340-346, one
sequential call per k), this backend turns the *entire sweep* into a single
accelerator dispatch:

- every k-candidate's LP relaxation and every branch-and-bound node is one
  element of a single batched Mehrotra IPM call (``distilp_tpu.ops.ipm``);
- integer incumbents come from an exact, vectorized rounding heuristic (the
  continuous (z, C) block of the MILP has a closed form given integers);
- pruning uses the kernel's rigorous Lagrangian bounds, so the mip-gap
  certificate does not depend on IPM convergence;
- one global incumbent prunes across all k trees simultaneously (the final
  answer is the min over k, so cross-k pruning is sound);
- the branch-and-bound *loop itself* runs on the device as a
  ``lax.while_loop`` with an on-device gap test — the host dispatches once
  and fetches the final state once. No per-round host round-trips: on a
  remote-tunnel TPU a host sync costs ~1000x the compute of a round.

Precision: search arrays and IPM iterations are float32 (TPU-native; float64
is software-emulated and ~40x slower), while everything the mip-gap
certificate touches — Lagrangian bounds, incumbent objectives, pruning
thresholds — is evaluated in float64. The bound is valid for ANY dual vector,
so float32 iterates cost tightness, never soundness.

The search state lives in fixed-capacity arrays (no data-dependent shapes);
frontier overflow is tracked honestly via ``dropped_bound``.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax

# Certificates (bounds, incumbents, thresholds) are float64; the search and
# IPM iterations are float32. x64 must be enabled for the f64 half.
jax.config.update("jax_enable_x64", True)


def _configure_compile_cache() -> None:
    """Env-gated persistent compilation cache (VERDICT r5 item 3).

    A fresh process pays seconds of jit compilation per static layout; for
    a "real-time re-placement" service that must survive restarts, that is
    the restart cost. ``DISTILP_COMPILE_CACHE=<dir>`` points JAX's
    persistent compilation cache at a directory so a restarted process
    reloads compiled programs in milliseconds instead. Opt-in (the cache
    trades disk + a hash lookup per compile), configured here because this
    module is the first backend contact of every solve path and the config
    must land before the first trace. Failures degrade to uncached
    compiles, never to a broken solver.
    """
    cache_dir = os.environ.get("DISTILP_COMPILE_CACHE")
    if not cache_dir:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every program: the solver's jit'd programs are small but
        # slow to build, exactly the shape the default thresholds skip.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - jax-version dependent
        import warnings

        warnings.warn(
            f"DISTILP_COMPILE_CACHE={cache_dir!r} could not be applied "
            f"({type(e).__name__}: {e}); continuing without a persistent "
            "compilation cache",
            RuntimeWarning,
            stacklevel=2,
        )


_configure_compile_cache()

import jax.numpy as jnp  # noqa: E402

from ..obs.compile_ledger import instrument  # noqa: E402  - stdlib-only
from ..ops.ipm import (  # noqa: E402
    IPM_DEFAULT_CHUNK,
    TRACE_COLS,
    IPMWarmState,
    LPBatch,
    ipm_solve_batch,
    n_trace_rows,
)
from ..ops.meshlp import sharded_pdhg  # noqa: E402
from ..ops.pdhg import (  # noqa: E402
    DEFAULT_RESTART_TOL,
    PDHG_DEFAULT_CHUNK,
    _default_tol_pdhg,
    pdhg_solve_batch,
    resolve_pdhg_dtype,
)
from .assemble import INACTIVE_RHS, MilpArrays, VarLayout  # noqa: E402
from .coeffs import HaldaCoeffs  # noqa: E402
from .result import ILPResult  # noqa: E402

DTYPE = jnp.float32  # search arrays + IPM iteration dtype
BDTYPE = jnp.float64  # certificate dtype

# Fixed frontier capacity. Dense HALDA trees are shallow (the LP optimum is
# near-integral); MoE trees with large E go wider, and an overflow floors the
# certificate at ``dropped_bound``, so capacity is generous — the beam keeps
# per-round compute independent of it (capacity only costs sort/memory).
NODE_CAP = 256
MAX_ROUNDS = 48
IPM_ITERS = 26
FRAC_TOL = 1e-4
# Rows of the (best-bound-sorted) frontier that get an IPM solve per round;
# the rest pass through with their parent bound (see ``_bnb_round``).
BEAM = 16
# Greedy single-expert-move refinement steps on rounded MoE incumbents
# (cold solves / Lagrangian-primal repairs); warm ticks keep a SHORT
# budget — the incumbent is already last tick's optimum, so moves only
# track per-tick drift, and each step prices a (quanta, M, M) transfer
# tensor (measured on the E=256/32-device flagship: 8 -> 2 steps cuts the
# margin tick 20.8 -> 12.5 ms at an unchanged certificate gap; a gap that
# ever drifts past mip_gap is caught by the round-0 settled test, and the
# B&B rounds then repair the incumbent on-device).
MOE_LOCAL_MOVES = 8
MOE_LOCAL_MOVES_WARM = 2
# Lagrangian root-ascent budgets: a cold MoE solve pays the full ascent; a
# warm streaming tick re-EVALUATES the bound at the previous tick's best
# multipliers with zero ascent steps — the bound is valid at ANY multiplier
# vector, so staleness only costs tightness, never soundness. Measured on
# the DeepSeek-V3 32-device flagship under ±5% t_comm drift: 0 steps still
# certifies at gap ~1e-6 and the tick drops ~12x (each ascent step is a
# softmax+argmin over the full (k,M,w,y) enumeration tensor, so steps
# dominate the warm program). If drift ever grows the gap past mip_gap the
# result comes back certified=False and StreamingReplanner re-solves cold,
# refreshing the duals.
DECOMP_STEPS_COLD = 300
DECOMP_STEPS_WARM = 0

# -- LP relaxation engines (see ops/ipm.py and ops/pdhg.py) ----------------
# 'ipm'  — batched Mehrotra predictor-corrector: dense (m, m) normal-matrix
#          Cholesky per iteration. Fastest to a high-accuracy dual on the
#          small fleets (M up to ~tens) it was built for; memory O(B·m²).
# 'pdhg' — matrix-free restarted Halpern PDHG: two operator applications
#          per iteration, no factorization. Memory O(m·n) shared + per-node
#          vectors, which is what admits M=512-4096 fleets the IPM cannot
#          touch. Needs more (cheap) iterations per LP.
# 'auto' — pdhg at or above PDHG_AUTO_M devices, ipm below. The threshold
#          is a conservative build-time default; `bench.py`'s fleet_scale
#          section measures the actual crossover on a given box.
LP_BACKENDS = ("ipm", "pdhg", "auto")
PDHG_AUTO_M = 128
# First-order iteration budgets. A PDHG iteration costs two matvecs (vs the
# IPM's factorization), so budgets are ~2 orders of magnitude larger for
# comparable dual quality; truncation only LOOSENS bounds, exactly like a
# truncated IPM (the f64 Lagrangian bound is valid for any dual). Warm
# rounds start from the parent's iterate and keep a quarter of the budget.
PDHG_ITERS = 2000
PDHG_WARM_FLOOR = 200

# -- branch-and-bound round log (the `diag` static flag) --------------------
# One row per B&B round when diagnostics are on, riding the packed output
# right after the root-iterate block (and BEFORE the m_y margin tail, which
# stays last). Decoded by obs/convergence.py into SearchTrace.
RL_COLS = 6  # [expanded, live_after, incumbent, best_bound, lp_iters, executed]


def _round_row(before: "SearchState", after: "SearchState", nbeam: int):
    """One round-log row from the states bracketing a B&B round. Pure
    bookkeeping over values the search already carries — the round itself
    is untouched, so the logged program's search trajectory is the
    unlogged program's."""
    return jnp.stack(
        [
            jnp.sum(before.active[:nbeam].astype(BDTYPE)),
            jnp.sum(after.active.astype(BDTYPE)),
            after.incumbent,
            _best_bound(after),
            after.stat_ipm_iters - before.stat_ipm_iters,
            jnp.ones((), BDTYPE),
        ]
    )


def _root_trace_rows(lp_backend: str, lp_iters: int, root_warm_chunk: bool) -> int:
    """Trace rows of the ROOT round's LP solve — mirrors exactly the chunk
    the root `_bnb_round` hands the kernel (PDHG always uses the kernel
    default; a cold IPM root runs one full-length chunk, a warm one the
    kernel default), so the packed-output decode and the while-loop buffer
    allocation can never disagree."""
    if lp_backend == "pdhg":
        chunk = PDHG_DEFAULT_CHUNK
    else:
        chunk = IPM_DEFAULT_CHUNK if root_warm_chunk else lp_iters
    return n_trace_rows(lp_iters, chunk)


def default_pdhg_iters(M: int) -> int:
    """Size-aware cold first-order budget — the ONE copy of the scaling
    rule (the escalation ladder in api.py multiplies it, so an inline
    re-derivation there could silently drift from this resolution)."""
    return PDHG_ITERS * max(1, M // 128)


def _resolve_lp_backend(lp_backend: Optional[str], M: int) -> str:
    """'ipm' or 'pdhg' from the public selector (None = 'auto')."""
    lb = "auto" if lp_backend is None else lp_backend
    if lb not in LP_BACKENDS:
        raise ValueError(
            f"unknown lp_backend {lp_backend!r}; expected one of {LP_BACKENDS}"
        )
    if lb == "auto":
        return "pdhg" if M >= PDHG_AUTO_M else "ipm"
    return lb


def default_search_params(moe: bool, n_k: int) -> Tuple[int, int, int]:
    """(node_cap, beam, ipm_iters) defaults by problem class.

    Dense HALDA trees certify in a couple of rounds with a handful of live
    nodes, so a small frontier and a short IPM keep the one-dispatch program
    lean. Measured across the four golden fixtures plus 16 perturbed
    synthetic fleets at M in {3..16} (every dense fuzz instance in the
    suite): beam 6 / 8 iters certifies + matches the HiGHS oracle
    everywhere and halves the M=16 north-star device program (30 -> 15 ms
    on a single host core). The edges are real: beam 4 starves one hard
    perturbed M=6 fleet's frontier (gap stalls at 0.019) and 6 iters'
    duals are too weak for one M=5 fleet (0.0101) — both failures are
    honest (certified=False), since fewer iters/rows can only LOOSEN
    bounds, never invalidate them: the bound is evaluated in f64 from
    whatever dual the iteration reached (see ops/ipm.py). Wide-expert MoE
    instances (E up to 256) need the full budget. Callers override any of
    these through ``halda_solve``.
    """
    if moe:
        return NODE_CAP, BEAM, IPM_ITERS
    return max(64, 2 * n_k), 6, 8


def _resolve_search_params(
    moe: bool,
    n_k: int,
    node_cap: Optional[int],
    beam: Optional[int],
    ipm_iters: Optional[int],
    max_rounds: Optional[int],
    per_k: bool = False,
    ipm_warm_iters: Optional[int] = None,
    lp_backend: Optional[str] = None,
    pdhg_iters: Optional[int] = None,
    M: int = 0,
    mesh_shards: Optional[int] = None,
    pdhg_dtype: Optional[str] = None,
) -> Tuple[int, int, int, int, int, str, int, Optional[str]]:
    """(cap, beam, lp_iters, lp_warm_iters, max_rounds, lp_backend,
    mesh_shards, pdhg_dtype): caller overrides applied over the
    problem-class defaults — the one resolution rule for every solve path
    (single-dispatch, async, scenario-batched).

    ``mesh_shards`` (None = 1) row-partitions each PDHG relaxation across
    a device mesh (ops/meshlp.py) and ``pdhg_dtype`` sets the first-order
    iterate precision ('f32'/'f64'; None keeps the search dtype — the f64
    certificate is unconditional either way). Both are pdhg-engine knobs:
    a resolution that lands on the IPM with either set is a caller error,
    raised here rather than silently ignored downstream.

    ``lp_backend`` (None = 'auto') selects the LP relaxation engine; the
    returned element is the CONCRETE engine ('ipm' or 'pdhg' — 'auto'
    resolves by fleet size ``M`` against ``PDHG_AUTO_M``). Under 'pdhg' the
    iteration slots carry the first-order budgets (``pdhg_iters`` override,
    else ``PDHG_ITERS``; warm rounds a quarter of it) — downstream plumbing
    treats them as the generic per-LP budget of whichever engine runs.

    Per-k mode keeps EVERY k's subtree alive to its own certificate, so the
    frontier carries ~n_k concurrent searches: capacity and beam scale with
    n_k (a frontier sized for one winner spills, and a spilled node floors
    its k's certificate forever).

    ``ipm_warm_iters`` is the iteration budget of every round AFTER the
    root round: children warm-start from their parent's iterate, so they
    need far fewer Mehrotra steps to recover a useful dual, and a truncated
    budget only LOOSENS bounds (the f64 Lagrangian bound is valid for any
    dual), never invalidates them. Default: half the cold budget, floored
    where the dual would get too weak to prune at all.
    """
    d_cap, d_beam, d_iters = default_search_params(moe, n_k)
    if per_k:
        d_cap = max(d_cap, 32 * n_k)
        d_beam = max(d_beam, 4 * n_k)
    engine = _resolve_lp_backend(lp_backend, M)
    if engine == "pdhg":
        # First-order budgets: ipm_iters AND ipm_warm_iters are IPM knobs
        # and deliberately do NOT rescale or truncate a PDHG solve (26 —
        # or 12 warm — first-order steps is never what a caller meant; a
        # replanner carrying IPM-era warm truncation across an 'auto'
        # flip to pdhg would cripple every warm round); pdhg_iters is the
        # explicit knob and the warm budget is derived from it alone. The
        # default scales with fleet size: bound tightness at a fixed
        # first-order iteration count degrades as the LP grows, and a too
        # loose root bound is paid back MANY times over in extra B&B
        # rounds (measured at M=256/gap 1e-3: 2000-iter roots grind 34
        # rounds + an escalation, 391s; 4000-iter roots certify in 3
        # rounds, 98s). Linear in M/PDHG_AUTO_M·... keeps the M<=128
        # behaviour identical to the flat default.
        it = pdhg_iters if pdhg_iters is not None else default_pdhg_iters(M)
        warm_it = min(it, max(PDHG_WARM_FLOOR, it // 4))
    else:
        it = ipm_iters if ipm_iters is not None else d_iters
        warm_it = (
            ipm_warm_iters if ipm_warm_iters is not None else max(6, it // 2)
        )
        warm_it = min(warm_it, it) if ipm_warm_iters is None else warm_it
    shards = 1 if mesh_shards is None else int(mesh_shards)
    if shards < 1:
        raise ValueError(f"mesh_shards must be >= 1 (got {mesh_shards})")
    resolve_pdhg_dtype(pdhg_dtype)  # validate the spelling early
    if engine != "pdhg":
        if shards > 1:
            raise ValueError(
                f"mesh_shards={shards} requires the matrix-free pdhg "
                f"engine, but lp_backend resolved to {engine!r} (pass "
                f"lp_backend='pdhg', or 'auto' at fleet scale)"
            )
        if pdhg_dtype is not None:
            raise ValueError(
                f"pdhg_dtype={pdhg_dtype!r} is a pdhg-engine knob, but "
                f"lp_backend resolved to {engine!r}"
            )
    return (
        max(node_cap, n_k) if node_cap is not None else d_cap,
        beam if beam is not None else d_beam,
        it,
        warm_it,
        max_rounds if max_rounds is not None else MAX_ROUNDS,
        engine,
        shards,
        pdhg_dtype,
    )


class RoundingData(NamedTuple):
    """Exact per-device MILP data for the integer rounding heuristic.

    Held in float64: the incumbent objective must be exact so the mip-gap
    certificate means what it says. The MoE fields are zeros in dense mode.
    """

    a: jax.Array  # (M,)
    b_gpu: jax.Array
    pen_set: jax.Array  # (M,) penalty of the device's own RAM slack
    pen_vram: jax.Array
    busy_const: jax.Array
    s_disk: jax.Array
    ram_rhs: jax.Array
    ram_minus_n: jax.Array  # float 0/1
    cuda_rhs: jax.Array  # +inf when row inactive
    metal_rhs: jax.Array  # +inf when row inactive
    has_gpu: jax.Array  # float 0/1
    g_raw: jax.Array  # (M,) MoE expert busy seconds per y-unit, times k
    eb_ram: jax.Array  # (M,) MoE bytes per y-unit charged to the primary pool
    eb_vram: jax.Array  # (M,) MoE bytes per y-unit charged to discrete VRAM
    eb_metal: jax.Array  # (M,) MoE bytes per y-unit on the Metal wired row
    w_active: jax.Array  # (M,) float 0/1 — 0 marks a phantom pad device
    #                      whose w is pinned to [0,0] (batch layout padding);
    #                      real devices keep the classic w >= 1 floor
    bprime: jax.Array  # scalar
    E: jax.Array  # scalar: routed experts per MoE layer (0 = dense)


def _rounding_arrays_np(coeffs: HaldaCoeffs, moe=None) -> dict:
    """Host-side (numpy) rounding-heuristic arrays; no device traffic."""
    M = coeffs.M
    pen_by_set = np.where(
        coeffs.set_id == 1,
        coeffs.pen_m1,
        np.where(coeffs.set_id == 2, coeffs.pen_m2, coeffs.pen_m3),
    )
    return dict(
        a=np.asarray(coeffs.a, np.float64),
        b_gpu=np.asarray(coeffs.b_gpu, np.float64),
        pen_set=np.asarray(pen_by_set, np.float64),
        pen_vram=np.asarray(coeffs.pen_vram, np.float64),
        busy_const=np.asarray(coeffs.busy_const, np.float64),
        s_disk=np.asarray(coeffs.s_disk, np.float64),
        ram_rhs=np.where(np.isfinite(coeffs.ram_rhs), coeffs.ram_rhs, INACTIVE_RHS),
        ram_minus_n=coeffs.ram_minus_n.astype(np.float64),
        cuda_rhs=np.where(coeffs.cuda_row, coeffs.cuda_rhs, np.inf),
        metal_rhs=np.where(coeffs.metal_row, coeffs.metal_rhs, np.inf),
        has_gpu=coeffs.has_gpu.astype(np.float64),
        g_raw=np.asarray(moe.g_raw if moe is not None else np.zeros(M), np.float64),
        eb_ram=np.asarray(
            moe.eb_ram if moe is not None else np.zeros(M), np.float64
        ),
        eb_vram=np.asarray(
            moe.eb_vram if moe is not None else np.zeros(M), np.float64
        ),
        eb_metal=np.asarray(
            moe.eb_metal if moe is not None else np.zeros(M), np.float64
        ),
        w_active=np.asarray(
            getattr(coeffs, "w_active", None)
            if getattr(coeffs, "w_active", None) is not None
            else np.ones(M),
            np.float64,
        ),
        bprime=np.float64(coeffs.bprime),
        E=np.float64(moe.E if moe is not None else 0.0),
    )


def rounding_data(coeffs: HaldaCoeffs, moe=None) -> RoundingData:
    return RoundingData(
        **{
            k: jnp.asarray(v, BDTYPE)
            for k, v in _rounding_arrays_np(coeffs, moe).items()
        }
    )


@dataclass
class StandardForm:
    """Host-assembled arrays of the boxed-standard-form LP family.

    Variables: [x_struct (N) | row slacks (6M)]; rows: 6M scaled inequality
    rows turned equalities + the sum(w)=W (and, MoE mode, sum(y)=E)
    equalities. A is per-k in MoE mode because the expert busy coefficients
    scale with 1/k; in dense mode A is k-independent, so exactly ONE copy is
    built (leading axis length 1). Row scaling is k-independent in BOTH
    modes (computed from the g-zeroed base matrix), which is what lets the
    packed single-dispatch path ship one base A and scatter the 2M per-k
    expert-busy entries in-trace.

    The split fields (``A_base``..``gscale``) carve the family into a
    DRIFT-INVARIANT part and a per-tick part. Under streaming profile drift
    (t_comm, expert load factors) only ``b_k`` rows 4M:6M, ``C_ub_k``, the
    rounding vectors, and the MoE g-values change; A, c-structural, the
    boxes, and the slack minima are byte-identical tick to tick. The packed
    path ships the static part once (content-addressed device cache) and a
    few-KB dynamic blob per tick — on a tunneled TPU the static upload is
    the bulk of the wire time, so warm ticks drop to solve+RTT.
    """

    A: np.ndarray  # (n_k, m, nf) row-scaled; (1, m, nf) in dense mode
    b_k: np.ndarray  # (n_k, m)
    c_k: np.ndarray  # (n_k, nf)
    lo_k: np.ndarray  # (n_k, nf) root boxes
    hi_k: np.ndarray  # (n_k, nf)
    int_mask: np.ndarray  # (nf,) bool — branchable columns
    ks: List[int]
    Ws: List[int]
    M: int
    obj_const: float
    moe: bool = False
    # --- drift-invariant / per-tick split (packed single-dispatch path) ---
    A_base: Optional[np.ndarray] = None  # (m, nf) scaled, g entries zero
    smin_k: Optional[np.ndarray] = None  # (n_k, m_ub) slack-box row minima
    C_ub_k: Optional[np.ndarray] = None  # (n_k,) cycle-time upper bound
    gscale: Optional[np.ndarray] = None  # (2, M) row_scale at cycle/prefetch
    #                                      rows (MoE g-scatter), else None


def _root_boxes(
    arrays: MilpArrays, rd: dict, k: int, W: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Finite boxes for every variable at one k (pure numpy).

    z and C are nominally free above, but any *optimal* solution satisfies
    z_i <= F_i^max and C <= max_i(B_i^max + F_i^max), so these bounds are
    valid for branch-and-bound. Boxing everything is what makes the
    Lagrangian bound rigorous for any dual vector.
    """
    lay = arrays.layout
    M = lay.M
    lo, hi = arrays.bounds_for_k(W)

    F_max = W * rd["bprime"] / rd["s_disk"]
    s_cap = float(W)  # slack counts streamable LAYERS; experts get no slack
    B_max = (
        rd["a"] * W
        + np.maximum(rd["b_gpu"], 0.0) * W
        + rd["pen_set"] * s_cap
        + rd["pen_vram"] * W * rd["has_gpu"]
        + (rd["g_raw"] / float(k)) * rd["E"]
        + rd["busy_const"]
    )
    z_ub = F_max
    C_ub = float(np.max(B_max + F_max)) if M else 1.0

    hi = hi.copy()
    hi[lay.z0 : lay.C] = z_ub
    hi[lay.C] = C_ub
    return lo, hi


def build_standard_form(
    arrays: MilpArrays, coeffs: HaldaCoeffs, kWs: Sequence[Tuple[int, int]]
) -> StandardForm:
    """Row-scale the MILP and emit the per-k (A, b, c, box) family. Pure
    numpy — no device traffic until ``_sweep_data`` uploads the result once.

    Row scaling is computed from the g-ZEROED base matrix (``arrays.A_ub``),
    so it is k-independent even in MoE mode. The per-k MoE busy entries
    g_raw/k at (cycle row, y col) and (prefetch row, y col) then ride on top
    of one shared scaled base — scattered host-side here (the materialized
    ``A`` legacy consumers read) and in-trace by ``_solve_packed`` (which
    ships only the base). Dropping g from the row magnitude changes the MoE
    scaling slightly; scaling is an internal equivalence transform, so only
    IPM conditioning (covered by the parity tests), not the solution, moves.
    """
    lay = arrays.layout
    M = lay.M
    N = lay.n_vars
    n_eq = lay.n_eq
    m_ub = arrays.A_ub.shape[0]
    nf = N + m_ub
    m = m_ub + n_eq

    rd = _rounding_arrays_np(coeffs, arrays.moe)

    # Row scaling: each inequality row (incl. its huge inactive RHS) is
    # normalized by its own magnitude; the slack column keeps coefficient 1
    # (slacks live in scaled units, boxed below). Drift note: |b_ub| on the
    # cycle/prefetch rows is xi+t_comm (well under their |C|=1 entry), so
    # streaming t_comm drift never moves the scale — the scaled base stays
    # byte-identical and the static device cache keeps hitting.
    row_mag = np.maximum(np.abs(arrays.A_ub).max(axis=1), np.abs(arrays.b_ub))
    row_scale = 1.0 / np.maximum(row_mag, 1.0)

    A_base = np.zeros((m, nf))
    A_base[:m_ub, :N] = arrays.A_ub * row_scale[:, None]
    A_base[:m_ub, N:] = np.eye(m_ub)
    A_base[m_ub:, :N] = arrays.A_eq
    b_ub_scaled = arrays.b_ub * row_scale

    n_k = len(kWs)
    A = np.zeros((n_k if lay.moe else 1, m, nf))
    b_k = np.zeros((n_k, m))
    c_k = np.zeros((n_k, nf))
    lo_k = np.zeros((n_k, nf))
    hi_k = np.zeros((n_k, nf))
    smin_k = np.zeros((n_k, m_ub))
    C_ub_k = np.zeros(n_k)

    g_raw = rd["g_raw"]
    for j, (k, W) in enumerate(kWs):
        ja = j if lay.moe else 0
        if lay.moe:
            A[ja] = A_base
            for i in range(M):
                g_k = g_raw[i] / float(k)
                A[ja, 4 * M + i, lay.y(i)] = g_k * row_scale[4 * M + i]
                A[ja, 5 * M + i, lay.y(i)] = g_k * row_scale[5 * M + i]
        elif j == 0:
            A[0] = A_base

        b_k[j, :m_ub] = b_ub_scaled
        b_k[j, m_ub:] = arrays.b_eq_for_k(W)
        c_k[j, :N] = arrays.c_for_k(k)

        lo_s, hi_s = _root_boxes(arrays, rd, k, W)
        lo_k[j, :N] = lo_s
        hi_k[j, :N] = hi_s
        C_ub_k[j] = hi_s[lay.C]
        # Slack boxes: s_row = b_row - min_v(A_row v) over the structural
        # box. Computed from the g-ZEROED base: the g entries sit at a
        # lo=0 column with g >= 0, so min(g*lo, g*hi) = 0 — base and full
        # matrix give identical minima. The C column is the one structural
        # column whose box (C_ub = max busy + prefetch) drifts with
        # t_comm, so its term is EXCLUDED from the shipped smin_k and
        # re-added in-trace from the dynamic C_ub_k — that is what keeps
        # smin_k (and the whole static blob) byte-stable across streaming
        # drift.
        Arow = A_base[:m_ub, :N]
        smin = np.minimum(Arow * lo_s[None, :], Arow * hi_s[None, :]).sum(axis=1)
        aC = A_base[:m_ub, lay.C]
        cmin = np.minimum(aC * lo_s[lay.C], aC * hi_s[lay.C])
        smin_k[j] = smin - cmin
        hi_k[j, N:] = np.maximum(b_ub_scaled - smin, 0.0)

    int_mask = np.zeros(nf, dtype=bool)
    int_mask[:N] = arrays.integrality.astype(bool)

    gscale = None
    if lay.moe:
        gscale = np.stack([row_scale[4 * M : 5 * M], row_scale[5 * M : 6 * M]])

    return StandardForm(
        A=A,
        b_k=b_k,
        c_k=c_k,
        lo_k=lo_k,
        hi_k=hi_k,
        int_mask=int_mask,
        ks=[k for k, _ in kWs],
        Ws=[W for _, W in kWs],
        M=M,
        obj_const=arrays.obj_const,
        moe=lay.moe,
        A_base=A_base,
        smin_k=smin_k,
        C_ub_k=C_ub_k,
        gscale=gscale,
    )


def _int_redistribute(vals, rem, lo, hi, target, M):
    """Scan that moves ``vals`` (integers in [lo, hi]) one unit at a time
    toward ``sum(vals) == target``, preferring large fractional remainders on
    the way up and small ones on the way down. Returns the adjusted vector;
    the caller re-checks the sum (|residual| <= M for near-feasible LP
    points; the scan length covers that — Lagrangian-primal y hints with
    larger residuals go through the exact-priced greedy repair in
    ``_round_to_incumbent`` instead)."""

    def body(state, _):
        v, d = state
        add_score = jnp.where(v < hi, rem, -jnp.inf)
        sub_score = jnp.where(v > lo, -rem, -jnp.inf)
        i_add = jnp.argmax(add_score)
        i_sub = jnp.argmax(sub_score)
        v = jax.lax.cond(
            d > 0,
            lambda v: v.at[i_add].add(1.0),
            lambda v: jax.lax.cond(
                d < 0, lambda v: v.at[i_sub].add(-1.0), lambda v: v, v
            ),
            v,
        )
        return (v, d - jnp.sign(d)), None

    d0 = target - vals.sum()
    (vals, _), _ = jax.lax.scan(body, (vals, d0), None, length=M + 4)
    return vals


def _round_to_incumbent(
    v, M, W, k, rd: RoundingData, moe: bool = False,
    y_steps: Optional[int] = None,
    moves: int = MOE_LOCAL_MOVES,
):
    """Exact MILP objective of the best integer point near the LP solution v.

    Given integer (w, n, y), the minimal feasible slacks are closed-form, and
    the optimal continuous block is z_i = max(0, B_i + F_i - C), C = max_i(B_i
    + F_i/2); so the heuristic's objective is exact (float64), not an LP
    approximation.

    Returns (obj_linear, w, n, y) with obj = +inf when rounding failed; y is
    zeros in dense mode.
    """
    Wf = W.astype(BDTYPE)
    k_f = k.astype(BDTYPE)
    v = v.astype(BDTYPE)
    w_frac = v[:M]
    n_frac = v[M : 2 * M]

    rem = w_frac - jnp.floor(w_frac)
    # Per-device box: real devices keep the classic [1, W] floor/cap;
    # phantom pad devices (w_active == 0, batch-layout padding) are pinned
    # to [0, 0] so rounding can never place a layer on them.
    w_lo = rd.w_active
    w_hi = Wf * rd.w_active
    w = jnp.clip(jnp.floor(w_frac), w_lo, w_hi)
    w = _int_redistribute(w, rem, w_lo, w_hi, Wf, M)
    valid = w.sum() == Wf

    n = jnp.clip(jnp.round(n_frac), 0.0, w) * rd.has_gpu

    bp = rd.bprime
    s_cap = Wf  # slack counts streamable LAYERS; expert bytes get no slack

    fetch = bp / rd.s_disk * w

    if moe:
        g_k = rd.g_raw / k_f
    else:
        g_k = jnp.zeros(M, BDTYPE)

    def price(y_t):
        """Exact objective of (w, n, y_t) with closed-form optimal slacks and
        continuous block; +inf when a slack cap is exceeded (RAM-overflowing
        expert residency is infeasible, not penalized — experts can't be
        disk-streamed)."""
        resident = bp * w - bp * n * rd.ram_minus_n + rd.eb_ram * y_t
        viol_ram = jnp.maximum(resident - rd.ram_rhs, 0.0)
        s_ram = jnp.ceil(viol_ram / bp - 1e-9)
        # Hard caps: a device cannot stream more layers than it hosts
        # (s <= w) — dense mode satisfies this automatically, MoE mode needs
        # it so expert bytes never ride the layer slack. s_cap (= W) stays
        # as the structural bound.
        ok = jnp.all(s_ram <= jnp.minimum(w, s_cap))
        # VRAM slack: one t_i covers both CUDA and Metal rows; pool-resident
        # experts (eb_vram / eb_metal) make it y-dependent.
        viol_vram = jnp.maximum(
            jnp.maximum(
                bp * n + rd.eb_vram * y_t - rd.cuda_rhs,
                bp * n + rd.eb_metal * y_t - rd.metal_rhs,
            ),
            0.0,
        )
        viol_vram = jnp.where(jnp.isfinite(viol_vram), viol_vram, 0.0)
        t = jnp.ceil(viol_vram / bp - 1e-9)
        if moe:
            # t <= n mirrors the MoE-only MILP row (rows 7M..8M): expert
            # bytes must fit VRAM, they cannot ride the offload slack.
            ok &= jnp.all(t <= n + 1e-9)
        else:
            # Dense MILP bounds t only by W*has_gpu — a device with negative
            # VRAM headroom (c_gpu > d_avail) legitimately pays the disk
            # penalty at n = 0, exactly like the CPU/HiGHS oracle.
            ok &= jnp.all(t <= Wf * rd.has_gpu + 1e-9)
        pen_cost = rd.pen_set * s_ram + rd.pen_vram * t
        lin = rd.a * w + rd.b_gpu * n + pen_cost + g_k * y_t
        busy = lin + rd.busy_const
        C = jnp.max(busy + 0.5 * fetch)
        return jnp.where(ok, (k_f - 1.0) * C + jnp.sum(lin), jnp.inf)

    # MoE expert counts. LP points (y_steps=None): floor + largest-remainder
    # redistribution, residual <= M by near-feasibility. Lagrangian-primal
    # hints (y_steps=k) can be short/long by up to E experts, and their
    # remainders carry no information — repair those with an exact-priced
    # greedy scan instead (each step adds the unit where the true objective
    # grows least / removes where it shrinks most). Either way a greedy
    # single-expert-move local search polishes the result: the rounding is
    # rarely the best lattice point when E is large (DeepSeek: E=256).
    if moe:
        y_frac = v[2 * M : 3 * M]
        if y_steps is None:
            y_rem = y_frac - jnp.floor(y_frac)
            y = jnp.clip(jnp.floor(y_frac), 0.0, rd.E)
            y = _int_redistribute(y, y_rem, 0.0, rd.E, rd.E, M)
        else:
            y0 = jnp.clip(jnp.round(y_frac), 0.0, rd.E)
            eyeM_r = jnp.eye(M, dtype=BDTYPE)

            def repair(y_t, _):
                d = rd.E - y_t.sum()
                add_cost = jnp.where(
                    y_t < rd.E, jax.vmap(price)(y_t[None, :] + eyeM_r), jnp.inf
                )
                sub_cost = jnp.where(
                    y_t > 0, jax.vmap(price)(y_t[None, :] - eyeM_r), jnp.inf
                )
                i_add = jnp.argmin(add_cost)
                i_sub = jnp.argmin(sub_cost)
                y_t = jnp.where(
                    d > 0,
                    y_t.at[i_add].add(1.0),
                    jnp.where(d < 0, y_t.at[i_sub].add(-1.0), y_t),
                )
                return y_t, None

            y, _ = jax.lax.scan(repair, y0, None, length=y_steps)
        valid &= y.sum() == rd.E

        eyeM = jnp.eye(M, dtype=BDTYPE)
        not_diag = ~jnp.eye(M, dtype=bool)
        # Move quanta: single-expert moves alone stall on the ceil staircase
        # of the RAM-slack penalty (moving 1 of 2 needed experts can be
        # neutral while moving both wins), so each step also prices coarser
        # i -> j transfers.
        qs = jnp.asarray([1.0, 2.0, 4.0, 8.0, 16.0], BDTYPE)

        def move(y_t, _):
            diff = eyeM[None, :, :] - eyeM[:, None, :]  # (i, j, M)
            cand = y_t[None, None, None, :] + qs[:, None, None, None] * diff[None]
            feas = (
                (y_t[None, :, None] >= qs[:, None, None])
                & (y_t[None, None, :] + qs[:, None, None] <= rd.E)
                & not_diag[None]
            )
            objs = jnp.where(
                feas, jax.vmap(jax.vmap(jax.vmap(price)))(cand), jnp.inf
            )
            flat = jnp.argmin(objs)
            q, i, j = flat // (M * M), (flat // M) % M, flat % M
            better = objs[q, i, j] < price(y_t) - 1e-12
            return jnp.where(better, cand[q, i, j], y_t), None

        y, _ = jax.lax.scan(move, y, None, length=moves)
    else:
        y = jnp.zeros(M, BDTYPE)

    obj = jnp.where(valid, price(y), jnp.inf)
    return obj, w, n, y


def price_fixed_assignment(rd: RoundingData, k, W, w, n, y) -> jax.Array:
    """Exact MILP objective (linear part, float64) of a FIXED integer MoE
    assignment — no rounding, no repair, no local moves.

    Same closed-form slack/continuous-block math as ``_round_to_incumbent``'s
    inner pricer; +inf when the assignment is infeasible. Total objective =
    returned value + ``MilpArrays.obj_const``. Host-callable (a few scalar
    device ops); used by ``routing.solve_load_aware`` to compare iterates at
    their REALIZED expert loads.
    """
    Wf = jnp.asarray(W, BDTYPE)
    k_f = jnp.asarray(k, BDTYPE)
    w = jnp.asarray(w, BDTYPE)
    n = jnp.asarray(n, BDTYPE)
    y = jnp.asarray(y, BDTYPE)
    bp = rd.bprime
    g_k = rd.g_raw / k_f
    fetch = bp / rd.s_disk * w

    valid = (w.sum() == Wf) & jnp.all(w >= 1.0) & (y.sum() == rd.E)
    resident = bp * w - bp * n * rd.ram_minus_n + rd.eb_ram * y
    viol_ram = jnp.maximum(resident - rd.ram_rhs, 0.0)
    s_ram = jnp.ceil(viol_ram / bp - 1e-9)
    valid &= jnp.all(s_ram <= jnp.minimum(w, Wf))
    viol_vram = jnp.maximum(
        jnp.maximum(
            bp * n + rd.eb_vram * y - rd.cuda_rhs,
            bp * n + rd.eb_metal * y - rd.metal_rhs,
        ),
        0.0,
    )
    viol_vram = jnp.where(jnp.isfinite(viol_vram), viol_vram, 0.0)
    t = jnp.ceil(viol_vram / bp - 1e-9)
    valid &= jnp.all(t <= n + 1e-9)
    pen_cost = rd.pen_set * s_ram + rd.pen_vram * t
    lin = rd.a * w + rd.b_gpu * n + pen_cost + g_k * y
    busy = lin + rd.busy_const
    C = jnp.max(busy + 0.5 * fetch)
    return jnp.where(valid, (k_f - 1.0) * C + jnp.sum(lin), jnp.inf)


def _decomp_terms(
    rd: RoundingData, ks, Ws, w_max: int, e_max: int, dtype, moe: bool = True
):
    """Enumeration tensors of the Lagrangian decomposition bound.

    For each k-candidate j, device i, integer w in [1, w_max], y in
    [0, e_max], and the complete n-candidate set {0, w, the VRAM boundary
    floor(V), the RAM-slack kink ceil(K), the s<=w feasibility endpoint},
    price the device EXACTLY as the MILP does (integer ceil slacks,
    penalties, busy constant). The candidate set is exact, not heuristic:
    over integer n the cost is piecewise linear with slope b_gpu - pen_set
    while the RAM slack is positive, b_gpu between the kinks, and b_gpu +
    pen_vram past the VRAM boundary — a convex slope sequence over the
    contiguous feasible interval [n_smin, w], so the integer minimum sits
    at an endpoint or a breakpoint. (Omitting ceil(K) would overstate the
    per-device minimum whenever 0 < b_gpu < pen_set — a slower-than-CPU
    accelerator — and an overstated minimum makes the Lagrangian BOUND
    unsound.)

        lin  = a w + b_gpu n + pen_ram ceil + pen_vram ceil + (g/k) y
        cyc  = lin + busy_const + (b'/s_disk) w / 2

    Returns (lin, cyc, ok) each shaped (5, n_k, M, w_max, e_max+1); ``ok``
    masks infeasible cells (slack caps exceeded, w > W_j).
    """
    w_vals = jnp.arange(1, w_max + 1, dtype=dtype)  # (W,)
    lin, cyc, ok, y_vals = _decomp_terms_for_w(
        rd, ks, Ws, w_vals, e_max, dtype, moe=moe
    )
    return lin, cyc, ok, w_vals, y_vals


def _decomp_terms_for_w(
    rd: RoundingData, ks, Ws, w_vals, e_max: int, dtype, moe: bool = True
):
    """The cell pricing of ``_decomp_terms`` over an ARBITRARY w slice.

    One definition of the enumeration math, two consumers: the monolithic
    full-(W, Y) tensors of the f32 ascent (``_decomp_terms``) and the
    memory-lean f64 final evaluation that streams one w value per
    ``lax.scan`` step (the full f64 tensor blows last-level cache on the
    E=256 flagship — ~20 MB per array and a dozen arrays — so streaming it
    is ~3.5x faster on a single host core and strictly fewer bytes live on
    a TPU core). Returns (lin, cyc, ok, y_vals) shaped
    (5, n_k, M, len(w_vals), e_max+1).
    """
    M = rd.a.shape[0]
    bp = rd.bprime
    y_vals = jnp.arange(0, e_max + 1, dtype=dtype)  # (Y,)
    Wg = w_vals[None, None, :, None]  # (1, 1, W, 1)
    Yg = y_vals[None, None, None, :]  # (1, 1, 1, Y)
    Wj = Ws.astype(dtype)[:, None, None, None]  # (n_k, 1, 1, 1)
    kj = ks.astype(dtype)[:, None, None, None]

    def dev(x):
        return x.astype(dtype)[None, :, None, None]  # (1, M, 1, 1)

    a = dev(rd.a)
    b_gpu = dev(rd.b_gpu)
    pen_set = dev(rd.pen_set)
    pen_vram = dev(rd.pen_vram)
    busy_const = dev(rd.busy_const)
    s_disk = dev(rd.s_disk)
    ram_rhs = dev(rd.ram_rhs)
    rm = dev(rd.ram_minus_n)
    cuda = dev(rd.cuda_rhs)
    metal = dev(rd.metal_rhs)
    hg = dev(rd.has_gpu)
    ebr = dev(rd.eb_ram)
    ebv = dev(rd.eb_vram)
    ebm = dev(rd.eb_metal)
    g_k = dev(rd.g_raw) / kj
    bp_d = bp.astype(dtype)
    E_d = rd.E.astype(dtype)
    s_cap = Wj  # hard cap: slack streams layers, never expert bytes

    # VRAM headroom left for n after the pool-resident expert slice (the
    # CUDA row carries eb_vram*y; the Metal row eb_metal*y).
    cuda_head = cuda - ebv * Yg
    metal_head = metal - ebm * Yg
    vram_rhs = jnp.minimum(cuda_head, metal_head)
    n_boundary = jnp.clip(jnp.floor(vram_rhs / bp_d), 0.0, Wg) * hg
    n_boundary = jnp.where(jnp.isfinite(n_boundary), n_boundary, Wg * hg)
    # RAM-slack kink: smallest n with zero RAM slack, ceil(K) for
    # K = (bp w + eb_ram y - rhs)/bp. Only meaningful when n relieves the
    # RAM row (ram_minus_n=1); elsewhere it's a harmless duplicate.
    ram_kink = jnp.clip(
        jnp.ceil((bp_d * Wg + ebr * Yg - ram_rhs) / bp_d - 1e-9), 0.0, Wg
    ) * hg * rm
    ram_kink = jnp.where(jnp.isfinite(ram_kink), ram_kink, 0.0)
    # Smallest n satisfying the s <= w hard cap (rm=1): the feasible-interval
    # endpoint the convex argmin lands on when expert bytes force offload.
    n_smin = jnp.clip(
        jnp.ceil((ebr * Yg - ram_rhs) / bp_d - 1e-9), 0.0, Wg
    ) * hg * rm
    n_smin = jnp.where(jnp.isfinite(n_smin), n_smin, 0.0)
    n_cands = jnp.stack(
        [
            jnp.zeros_like(Wg * hg * jnp.ones_like(Yg)),
            Wg * hg * jnp.ones_like(Yg),
            n_boundary * jnp.ones_like(Wg),
            ram_kink * jnp.ones_like(Wg),
            n_smin * jnp.ones_like(Wg),
        ]
    )  # (5, n_k, M, W, Y)

    resident = bp_d * Wg - bp_d * n_cands * rm + ebr * Yg
    s_ram = jnp.ceil(jnp.maximum(resident - ram_rhs, 0.0) / bp_d - 1e-9)
    # Hard caps mirroring the MILP rows: s <= min(w, W) and t <= n (a device
    # cannot stream more layers than it hosts, so expert bytes never ride
    # the slack; vacuous in dense mode where viol <= b'*w anyway).
    ok = s_ram <= jnp.minimum(Wg, s_cap)
    viol_v = jnp.maximum(
        jnp.maximum(
            bp_d * n_cands + ebv * Yg - cuda, bp_d * n_cands + ebm * Yg - metal
        ),
        0.0,
    )
    viol_v = jnp.where(jnp.isfinite(viol_v), viol_v, 0.0)
    t = jnp.ceil(viol_v / bp_d - 1e-9)
    if moe:
        ok &= t <= n_cands + 1e-9  # MoE rows 7M..8M: t <= n
    else:
        ok &= t <= Wj * hg + 1e-9  # dense: t only bounded by W*has_gpu
    ok &= (Wg <= Wj) & (Yg <= E_d)

    lin = a * Wg + b_gpu * n_cands + pen_set * s_ram + pen_vram * t + g_k * Yg
    cyc = lin + busy_const + 0.5 * (bp_d / s_disk) * Wg
    return lin, cyc, ok, y_vals


def _decomp_bound_roots(
    rd: RoundingData,
    ks,
    Ws,
    w_max: int,
    e_max: int,
    steps: int = 300,
    moe: bool = True,
    init_params: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, Tuple[jax.Array, ...]]:
    """Per-k Lagrangian decomposition lower bounds on the fixed-k MILP.

    Dualize the two coupling constraints (sum w = W, sum y = E) and split the
    cycle-time weight (k-1) over devices as theta_i = (k-1) softmax(tau_i)
    (valid because C >= B_i + F_i/2 for every feasible point — add the cycle
    and prefetch rows). For ANY (lambda, mu, tau) the per-device subproblems
    decouple and are solved EXACTLY over the integer lattice by the
    enumeration tensors, so

        bound(l, m, tau) = sum_i min_{w,n,y} [lin_i + theta_i cyc_i
                           - l w - m y] + l W + m E

    is a rigorous lower bound accounting for per-device integrality the LP
    relaxation cannot see (the MoE root integrality gap that box branching
    cannot close — cf. HiGHS closing it with cutting planes). Multipliers are
    optimized by momentum ascent in f32 (gradients through the min pick the
    argmin cell); the returned bound is ONE final f64 evaluation at the best
    multipliers, so f32 only costs tightness, never soundness.

    ``init_params`` warm-starts the ascent from a previous solve's best
    (lambda, mu, tau) — the bound is valid for ANY multipliers, so a
    streaming tick can run a short (or zero-step) ascent from the stored
    duals and still certify. The initial point is always evaluated and kept
    in the best-of tracking, and the chosen multipliers are returned so the
    caller can persist them for the next tick.
    """
    n_k = ks.shape[0]
    M = rd.a.shape[0]
    if init_params is not None:
        params0 = tuple(p.astype(DTYPE) for p in init_params)
    else:
        params0 = (
            jnp.zeros(n_k, DTYPE),
            jnp.zeros(n_k, DTYPE),
            jnp.zeros((n_k, M), DTYPE),
        )

    if steps > 0:
        lin32, cyc32, ok, w_vals, y_vals = _decomp_terms(
            rd, ks, Ws, w_max, e_max, DTYPE, moe=moe
        )
        big = jnp.asarray(3.4e37, DTYPE)
        wv = w_vals[None, None, :, None]
        yv = y_vals[None, None, None, :]

        def neg_bound32(params):
            lam, mu, tau = params  # (n_k,), (n_k,), (n_k, M)
            theta = (ks.astype(DTYPE) - 1.0)[:, None] * jax.nn.softmax(
                tau, axis=1
            )
            term = (
                lin32
                + theta[None, :, :, None, None] * cyc32
                - lam[None, :, None, None, None] * wv[None]
                - mu[None, :, None, None, None] * yv[None]
            )
            term = jnp.where(ok, term, big)
            per_dev = jnp.min(term, axis=(0, 3, 4))  # (n_k, M)
            b = (
                per_dev.sum(axis=1)
                + lam * Ws.astype(DTYPE)
                + mu * rd.E.astype(DTYPE)
            )
            return -jnp.sum(b), b

        grad_fn = jax.grad(lambda p: neg_bound32(p)[0])

        # Adam ascent on the bounds. The dual function is piecewise linear
        # and badly scaled across instances (dual-optimal multipliers range
        # from ~0.03 on the DeepSeek fleet to ~3 on Mixtral), so the step
        # size sweeps three decades in phases; any visited multiplier yields
        # a valid bound and ``best_b``/``best_params`` keep the tightest
        # one, so an overshooting phase can only waste steps, never weaken
        # the result.
        b1, b2, eps = 0.9, 0.999, 1e-12
        phase_len = max(1, steps // 3)

        def step(carry, i):
            params, m_st, v_st, best_b, best_params = carry
            g = grad_fn(params)
            t = i.astype(DTYPE) + 1.0
            lr = 0.01 * 10.0 ** jnp.minimum(i // phase_len, 2).astype(DTYPE)
            m_st = jax.tree.map(lambda m, gg: b1 * m + (1 - b1) * gg, m_st, g)
            v_st = jax.tree.map(
                lambda v, gg: b2 * v + (1 - b2) * gg * gg, v_st, g
            )
            params = jax.tree.map(
                lambda p, m, v: p
                - lr * (m / (1 - b1**t)) / (jnp.sqrt(v / (1 - b2**t)) + eps),
                params,
                m_st,
                v_st,
            )
            b = neg_bound32(params)[1]  # (n_k,)
            better = b > best_b
            best_params = jax.tree.map(
                lambda bp_, p: jnp.where(
                    better.reshape((n_k,) + (1,) * (p.ndim - 1)), p, bp_
                ),
                best_params,
                params,
            )
            best_b = jnp.maximum(best_b, b)
            return (params, m_st, v_st, best_b, best_params), None

        zeros = jax.tree.map(jnp.zeros_like, params0)
        # The initial point (stored duals on a warm tick, zeros cold) is a
        # valid multiplier vector: evaluate it and let the ascent only
        # improve on it.
        init = (params0, zeros, zeros, neg_bound32(params0)[1], params0)
        (_, _, _, _, best_params), _ = jax.lax.scan(
            step, init, jnp.arange(steps), length=steps
        )
    else:
        # Zero-step (warm tick) path: the stored duals ARE the chosen
        # multipliers, so skip the whole f32 enumeration tensor and ascent
        # machinery — only the rigorous f64 evaluation below runs.
        best_params = params0

    # Rigorous final evaluation: f64 pricing at the chosen multipliers,
    # STREAMED one w value per scan step. The monolithic (5, n_k, M, W, Y)
    # f64 tensors blow last-level cache at flagship scale (E=256: ~20 MB
    # per array, a dozen arrays live at once); per-w slices stay resident,
    # and the min folds associatively so the streamed bound is the same
    # f64 value bit for bit. The primal-hint argmin folds through the scan
    # on cold solves; warm ticks (steps == 0) skip it entirely — their
    # incumbent comes from the previous optimum re-priced, so tracking
    # argmin indices would only re-buy the transpose/argmin traffic this
    # streaming removes (hint ties may resolve to a different cell than
    # the monolithic argmin did; the hint is a repair-and-reprice seed, so
    # only the seed quality, never correctness, could differ).
    track_hint = steps > 0
    lam, mu, tau = jax.tree.map(lambda p: p.astype(BDTYPE), best_params)
    theta = (ks - 1.0)[:, None] * jax.nn.softmax(tau, axis=1)
    Y = e_max + 1
    y64 = jnp.arange(0, Y, dtype=BDTYPE)

    def w_step(carry, w_scalar):
        m_y, any_ok = carry[0], carry[1]
        w_slice = jnp.reshape(w_scalar, (1,))
        lin64, cyc64, ok64, _ = _decomp_terms_for_w(
            rd, ks, Ws, w_slice, e_max, BDTYPE, moe=moe
        )
        term = (
            lin64
            + theta[None, :, :, None, None] * cyc64
            - lam[None, :, None, None, None] * w_scalar
            - mu[None, :, None, None, None] * y64[None, None, None, None, :]
        )
        term = jnp.where(ok64, term, jnp.inf)
        # (5, n_k, M, 1, Y) -> per-(k, i, y) min over the n-candidate dim:
        # folding the y-PROFILE (not just the scalar min) is what the
        # margin fast path reuses — the g-term is linear in y, so a later
        # tick can shift the profile by (1+theta)*dg*y/k and re-min
        # EXACTLY, host-side (see ``margin_bounds_from_state``).
        c_min = term[:, :, :, 0, :].min(axis=0)  # (n_k, M, Y)
        m_y = jnp.minimum(m_y, c_min)
        any_ok = any_ok | jnp.any(ok64, axis=(0, 3, 4))
        if not track_hint:
            return (m_y, any_ok), None
        best, best_flat, best_w = carry[2], carry[3], carry[4]
        t2 = jnp.transpose(term[:, :, :, 0, :], (1, 2, 0, 3)).reshape(
            n_k, M, -1
        )
        slice_min = t2.min(axis=2)
        better = slice_min < best
        best_flat = jnp.where(
            better, t2.argmin(axis=2).astype(jnp.int32), best_flat
        )
        best_w = jnp.where(better, w_scalar, best_w)
        return (m_y, any_ok, jnp.minimum(best, slice_min), best_flat,
                best_w), None

    carry0 = [
        jnp.full((n_k, M, Y), jnp.inf, BDTYPE),
        jnp.zeros((n_k, M), bool),
    ]
    if track_hint:
        carry0 += [
            jnp.full((n_k, M), jnp.inf, BDTYPE),
            jnp.zeros((n_k, M), jnp.int32),
            jnp.ones((n_k, M), BDTYPE),
        ]
    carry, _ = jax.lax.scan(
        w_step, tuple(carry0), jnp.arange(1, w_max + 1, dtype=BDTYPE)
    )
    m_y = carry[0]  # (n_k, M, Y)
    per_dev = m_y.min(axis=2)  # (n_k, M)
    bound = per_dev.sum(axis=1) + lam * Ws + mu * rd.E
    # A device with NO feasible cell proves the whole k infeasible (+inf is
    # the honest bound); a non-finite optimization artifact must degrade to
    # -inf (vacuous) instead.
    any_feasible = carry[1].all(axis=1)
    bound = jnp.where(jnp.isnan(bound), -jnp.inf, bound)
    bound = jnp.where(any_feasible, bound, jnp.inf)

    if not track_hint:
        zeros = jnp.zeros((n_k, M), BDTYPE)
        return bound, zeros, zeros, zeros, (lam, mu, tau), m_y

    # Lagrangian primal hint: each device's argmin cell at the chosen
    # multipliers, INCLUDING its optimal n-candidate (leaving n at zero
    # would hand the pricer a GPU-less placement). sum(w*) is usually
    # exactly W near the dual optimum and sum(y*) within ~E/2 of E; the
    # caller repairs and exact-prices it as an incumbent candidate (LP
    # rounding alone lands far from the optimum on wide-expert instances).
    flat = carry[3]
    c_star = flat // Y
    w_star = carry[4]
    y_star = (flat % Y).astype(BDTYPE)
    # Reconstruct the n value of the chosen candidate: 0, w, the VRAM
    # boundary, or the RAM-slack kink (mirrors the n_cands construction in
    # _decomp_terms).
    hg = rd.has_gpu[None, :]
    rm = rd.ram_minus_n[None, :]
    vram_rhs = jnp.minimum(
        rd.cuda_rhs[None, :] - rd.eb_vram[None, :] * y_star,
        rd.metal_rhs[None, :] - rd.eb_metal[None, :] * y_star,
    )
    n_bnd = jnp.clip(jnp.floor(vram_rhs / rd.bprime), 0.0, w_star) * hg
    n_bnd = jnp.where(jnp.isfinite(n_bnd), n_bnd, w_star * hg)
    n_kink = (
        jnp.clip(
            jnp.ceil(
                (
                    rd.bprime * w_star
                    + rd.eb_ram[None, :] * y_star
                    - rd.ram_rhs[None, :]
                )
                / rd.bprime
                - 1e-9
            ),
            0.0,
            w_star,
        )
        * hg
        * rm
    )
    n_kink = jnp.where(jnp.isfinite(n_kink), n_kink, 0.0)
    n_smin = (
        jnp.clip(
            jnp.ceil(
                (rd.eb_ram[None, :] * y_star - rd.ram_rhs[None, :]) / rd.bprime
                - 1e-9
            ),
            0.0,
            w_star,
        )
        * hg
        * rm
    )
    n_smin = jnp.where(jnp.isfinite(n_smin), n_smin, 0.0)
    n_star = jnp.where(
        c_star == 0,
        0.0,
        jnp.where(
            c_star == 1,
            w_star * hg,
            jnp.where(
                c_star == 2, n_bnd, jnp.where(c_star == 3, n_kink, n_smin)
            ),
        ),
    )
    return bound, w_star, n_star, y_star, (lam, mu, tau), m_y


class SearchState(NamedTuple):
    node_lo: jax.Array  # (CAP, nf) float32
    node_hi: jax.Array  # (CAP, nf) float32
    node_kidx: jax.Array  # (CAP,) int32
    node_bound: jax.Array  # (CAP,) float64 parent bound (full-objective space)
    active: jax.Array  # (CAP,) bool
    incumbent: jax.Array  # () float64 full-objective incumbent
    inc_w: jax.Array  # (M,) float64
    inc_n: jax.Array  # (M,) float64
    inc_y: jax.Array  # (M,) float64 expert counts (zeros in dense mode)
    inc_kidx: jax.Array  # () int32
    dropped_bound: jax.Array  # () float64 min bound among overflow-dropped nodes
    per_k_best: jax.Array  # (n_k,) float64 best incumbent per k
    # Per-k incumbent assignments + per-k overflow bound. Reporting-only in
    # the default (global-incumbent) sweep; load-bearing in per-k mode
    # (``_bnb_round(per_k=True)``), where each k prunes only against its
    # own incumbent and certifies its own optimum.
    per_k_w: jax.Array  # (n_k, M) float64
    per_k_n: jax.Array  # (n_k, M) float64
    per_k_y: jax.Array  # (n_k, M) float64
    per_k_dropped: jax.Array  # (n_k,) float64
    # Per-node IPM iterates (original coordinates, see ops.ipm.IPMWarmState):
    # children seed their LP solve from the parent's point projected into
    # the tightened box, duals verbatim — the HALDA child differs from its
    # parent by one collapsed box, so the warm solve recovers a pruning
    # dual in a fraction of the cold budget. ``node_warm`` gates rows that
    # actually carry one (roots start cold unless the previous streaming
    # tick's root iterates were shipped in).
    node_v: jax.Array  # (CAP, nf) float32
    node_y: jax.Array  # (CAP, m) float32
    node_z: jax.Array  # (CAP, nf) float32
    node_f: jax.Array  # (CAP, nf) float32
    node_warm: jax.Array  # (CAP,) bool
    # Observability accumulators (ride the packed output header): useful
    # IPM iterations executed across every round, and rounds executed.
    stat_ipm_iters: jax.Array  # () float64
    stat_rounds: jax.Array  # () float64


class SweepData(NamedTuple):
    """Device-resident arrays of one sweep, shared by every B&B round.

    A plain pytree argument (not a closure) so the jitted solve is a single
    module-level callable whose compile cache is reused across
    ``halda_solve`` calls of the same shape.
    """

    A: jax.Array  # (m, nf) float32 shared base (dense AND hoisted MoE)
    b_k: jax.Array  # (n_k, m) float32
    c_k: jax.Array  # (n_k, nf) float32
    int_mask: jax.Array  # (nf,) bool
    ks: jax.Array  # (n_k,) float64
    Ws: jax.Array  # (n_k,) float64
    obj_const: jax.Array  # () float64
    rd: RoundingData
    # MoE A-gather hoist: the per-k matrices differ from the shared base in
    # exactly 2M entries (the expert-busy g/k values on the cycle/prefetch
    # rows, already row-scaled). Carrying the base once plus this
    # (n_k, 2, M) table lets ``_bnb_round`` scatter the per-NODE entries
    # in-trace instead of gathering B full (m, nf) matrices every round —
    # the only part of the A select that branching can change is which k's
    # 2M values land on each beam row. None in dense mode (A is k-free).
    gky: Optional[jax.Array] = None  # (n_k, 2, M) float32


def _gky_scatter_table(g_raw, ks, gscale):
    """(gky, table) for the MoE expert-busy entries: ``gky`` = g_raw/k
    (n_k, M) — the objective's y-column values — and ``table`` (n_k, 2, M)
    — the same values times the cycle/prefetch row scales, i.e. exactly
    what ``build_standard_form`` scatters into A host-side. ONE definition
    shared by the eager (``_sweep_data``) and packed
    (``_solve_packed_impl``) paths, so the two A constructions cannot
    drift apart. Works traced or eager; both outputs are DTYPE.
    """
    gky = (
        jnp.asarray(g_raw, BDTYPE)[None, :] / jnp.asarray(ks, BDTYPE)[:, None]
    ).astype(DTYPE)
    gs = jnp.asarray(gscale)
    table = jnp.stack(
        [
            gky * gs[0][None, :].astype(DTYPE),
            gky * gs[1][None, :].astype(DTYPE),
        ],
        axis=1,
    )
    return gky, table


def _sweep_data(sf: StandardForm, rd: RoundingData) -> SweepData:
    gky = None
    if sf.moe:
        _, gky = _gky_scatter_table(rd.g_raw, sf.ks, sf.gscale)
    return SweepData(
        A=jnp.asarray(sf.A_base if sf.moe else sf.A[0], DTYPE),
        b_k=jnp.asarray(sf.b_k, DTYPE),
        c_k=jnp.asarray(sf.c_k, DTYPE),
        int_mask=jnp.asarray(sf.int_mask),
        ks=jnp.asarray(sf.ks, BDTYPE),
        Ws=jnp.asarray(sf.Ws, BDTYPE),
        obj_const=jnp.asarray(sf.obj_const, BDTYPE),
        rd=rd,
        gky=gky,
    )


def _default_cap(n_k: int) -> int:
    return max(NODE_CAP, 2 * n_k)


def _root_state(
    lo_k, hi_k, M: int, cap: int, m: int, root_warm=None
) -> SearchState:
    """Root frontier (one node per k) built from box arrays; jnp throughout,
    so it works both eagerly and traced inside ``_solve_packed``.

    ``root_warm`` = (ok (n_k,), v (n_k, nf), y (n_k, m), z (n_k, nf),
    f (n_k, nf)) seeds the roots' IPM iterates from a previous tick's root
    solve (original coordinates; per-k ``ok`` gates stale entries), so a
    streaming re-solve starts its root round hot instead of from mid-box.
    """
    n_k, nf = lo_k.shape
    node_v = jnp.zeros((cap, nf), DTYPE)
    node_y = jnp.zeros((cap, m), DTYPE)
    node_z = jnp.zeros((cap, nf), DTYPE)
    node_f = jnp.zeros((cap, nf), DTYPE)
    node_warm = jnp.zeros(cap, bool)
    if root_warm is not None:
        ok_w, v_w, y_w, z_w, f_w = root_warm
        node_v = node_v.at[:n_k].set(v_w.astype(DTYPE))
        node_y = node_y.at[:n_k].set(y_w.astype(DTYPE))
        node_z = node_z.at[:n_k].set(z_w.astype(DTYPE))
        node_f = node_f.at[:n_k].set(f_w.astype(DTYPE))
        node_warm = node_warm.at[:n_k].set(ok_w)
    return SearchState(
        node_lo=jnp.zeros((cap, nf), DTYPE).at[:n_k].set(lo_k.astype(DTYPE)),
        node_hi=jnp.zeros((cap, nf), DTYPE).at[:n_k].set(hi_k.astype(DTYPE)),
        node_kidx=jnp.zeros(cap, jnp.int32).at[:n_k].set(
            jnp.arange(n_k, dtype=jnp.int32)
        ),
        node_bound=jnp.full(cap, -jnp.inf, BDTYPE),
        active=jnp.zeros(cap, bool).at[:n_k].set(True),
        incumbent=jnp.asarray(jnp.inf, BDTYPE),
        inc_w=jnp.zeros(M, BDTYPE),
        inc_n=jnp.zeros(M, BDTYPE),
        inc_y=jnp.zeros(M, BDTYPE),
        inc_kidx=jnp.asarray(0, jnp.int32),
        dropped_bound=jnp.asarray(jnp.inf, BDTYPE),
        per_k_best=jnp.full(n_k, jnp.inf, BDTYPE),
        per_k_w=jnp.zeros((n_k, M), BDTYPE),
        per_k_n=jnp.zeros((n_k, M), BDTYPE),
        per_k_y=jnp.zeros((n_k, M), BDTYPE),
        per_k_dropped=jnp.full(n_k, jnp.inf, BDTYPE),
        node_v=node_v,
        node_y=node_y,
        node_z=node_z,
        node_f=node_f,
        node_warm=node_warm,
        stat_ipm_iters=jnp.zeros((), BDTYPE),
        stat_rounds=jnp.zeros((), BDTYPE),
    )


def _init_state(sf: StandardForm, cap: Optional[int] = None) -> SearchState:
    """Root frontier: one node per k. An explicit ``cap`` is honored exactly
    (mesh callers pre-pad it to their device count); it must fit the roots."""
    n_k = len(sf.ks)
    if cap is None:
        cap = _default_cap(n_k)
    elif cap < n_k:
        raise ValueError(f"frontier cap {cap} cannot hold {n_k} root nodes")
    return _root_state(
        jnp.asarray(sf.lo_k), jnp.asarray(sf.hi_k), sf.M, cap, sf.A.shape[1]
    )


def _cast_lp_result(res, tgt):
    """Cast an LP result's iteration-dtype leaves back to the search dtype
    so a pdhg_dtype-escalated (f64) solve re-enters the f32 carry without
    changing any loop-carry signature. ``bound`` is ALREADY the f64
    certificate and ``converged`` is boolean — both pass through; every
    other leaf is iteration dtype by the IPMResult contract."""
    if res.v.dtype == tgt:
        return res
    cast = {
        f: getattr(res, f).astype(tgt)
        for f in (
            "v", "obj", "rp_norm", "rd_norm", "mu", "reduced",
            "y_dual", "z_dual", "f_dual", "iters_run",
        )
    }
    if res.trace_buf is not None:
        cast["trace_buf"] = res.trace_buf.astype(tgt)
    return res._replace(**cast)


def _bnb_round(
    data: SweepData,
    state: SearchState,
    mip_gap,
    ipm_iters: int = IPM_ITERS,
    beam: Optional[int] = None,
    moe: bool = False,
    per_k: bool = False,
    return_res: bool = False,
    ipm_chunk: Optional[int] = None,
    lp_backend: str = "ipm",
    pdhg_restart_tol: float = DEFAULT_RESTART_TOL,
    mesh_shards: int = 1,
    pdhg_dtype: Optional[str] = None,
    lp_trace: bool = False,
):
    """One batched branch-and-bound round over the frontier (pure function;
    traced inside the fused solve loop or jitted standalone by callers).
    Returns the new state; with ``return_res=True`` also the beam rows' raw
    ``IPMResult`` (the root round reads its iterates for persistence).
    ``ipm_chunk`` sets the kernel's convergence-test granularity (None =
    kernel default; pass ``ipm_iters`` to disable the early exit when the
    rows are known to need the whole budget, e.g. a cold root).

    ``beam`` (static) caps how many frontier rows get an IPM solve this round.
    Compaction keeps the frontier sorted best-bound-first, so the prefix holds
    the most promising nodes; rows past the beam pass through untouched
    (parent bound kept, no branching) and bubble forward as the prefix drains.
    Measured frontiers stay tiny (<=4 active on the 16-device north star), so
    a small beam removes ~90% of the round's FLOPs without weakening the
    certificate — an unprocessed node keeps its valid parent bound.

    ``per_k`` (static) switches the pruning regime: by default every node
    prunes against the single global incumbent (fastest route to THE
    optimum — losing k's die early, their entries are reporting-only). In
    per-k mode a node prunes only against ITS k's incumbent and the per-k
    incumbent assignments/overflow bounds are maintained, so the sweep
    terminates with a certified optimum for EVERY feasible k — the
    reference's per-k-MILP output contract
    (/root/reference/src/distilp/solver/halda_p_solver.py:392-412) in one
    dispatch.
    """
    A, int_mask, ks, Ws, rd = data.A, data.int_mask, data.ks, data.Ws, data.rd
    obj_const = data.obj_const
    M = state.inc_w.shape[0]
    cap = state.node_lo.shape[0]
    B = cap if beam is None else min(beam, cap)

    lo_p = state.node_lo[:B]
    hi_p = state.node_hi[:B]
    kidx_p = state.node_kidx[:B]
    active_p = state.active[:B]

    # Dense mode shares one (m, nf) A across every k (the IPM broadcasts a
    # 2-D A). MoE mode scatters each node's 2M per-k expert-busy entries
    # onto the shared base (``SweepData.gky``): branching only ever changes
    # WHICH k's entries land on a row, so the round gathers B*(2M) scalars
    # instead of B full matrices. A legacy (n_k, m, nf) A still gathers.
    if data.gky is not None:
        m_rows = A.shape[0]
        nf_cols = A.shape[1]
        A_p = jnp.broadcast_to(A, (B, m_rows, nf_cols))
        g_p = data.gky[kidx_p]  # (B, 2, M)
        y_cols = 2 * M + jnp.arange(M)
        rows_cyc = 4 * M + jnp.arange(M)
        rows_pre = 5 * M + jnp.arange(M)
        A_p = A_p.at[:, rows_cyc, y_cols].set(g_p[:, 0, :])
        A_p = A_p.at[:, rows_pre, y_cols].set(g_p[:, 1, :])
    else:
        A_p = A if A.ndim == 2 else A[kidx_p]
    b = data.b_k[kidx_p]
    c = data.c_k[kidx_p]
    # Warm-start each node from the iterate it carries (the parent's point
    # projected into this node's box — the projection happens inside the
    # kernel — with duals reused verbatim); inactive rows are skipped so
    # they stop gating the kernel's batch-wide early exit. Budget truncation
    # and warm quality only move bound TIGHTNESS: the f64 Lagrangian bound
    # is valid for whatever dual the solve reaches.
    warm = IPMWarmState(
        v=state.node_v[:B],
        y=state.node_y[:B],
        z=state.node_z[:B],
        f=state.node_f[:B],
        ok=state.node_warm[:B],
    )
    lp_batch = LPBatch(A=A_p, b=b, c=c, l=lo_p, u=hi_p)
    if lp_backend == "pdhg":
        # Matrix-free engine, same warm-state and result contract (see
        # ops/pdhg.py). The IPM's full-length-chunk cold-root optimization
        # (ipm_chunk=iters) is deliberately NOT forwarded: a first-order
        # budget is 2 orders of magnitude larger and where inside it an
        # element converges is unknown even cold, so the kernel-default
        # chunking (batch-wide early exit every few dozen matvecs) is
        # always the right granularity.
        if mesh_shards > 1:
            # Row-partitioned engine (ops/meshlp.py): same warm-state and
            # result contract, the mesh is built at trace time (mesh_shards
            # is static here). The iterate dtype follows pdhg_dtype; the
            # result's iteration-dtype leaves are cast back at this
            # boundary so the loop carry never changes signature.
            dt = resolve_pdhg_dtype(pdhg_dtype)
            mesh_batch = lp_batch
            if dt is not None and dt != lp_batch.A.dtype:
                mesh_batch = LPBatch(*(x.astype(dt) for x in lp_batch))
            res = sharded_pdhg(
                mesh_batch,
                mesh_shards,
                ipm_iters,
                _default_tol_pdhg(mesh_batch.A.dtype),
                pdhg_restart_tol,
                warm=warm,
                skip=~active_p,
                trace=lp_trace,
            )
        else:
            res = pdhg_solve_batch(
                lp_batch,
                iters=ipm_iters,
                restart_tol=pdhg_restart_tol,
                warm=warm,
                skip=~active_p,
                trace=lp_trace,
                dtype=pdhg_dtype,
            )
        res = _cast_lp_result(res, lp_batch.A.dtype)
    else:
        chunk_kw = {} if ipm_chunk is None else {"chunk": ipm_chunk}
        res = ipm_solve_batch(
            lp_batch,
            iters=ipm_iters,
            warm=warm,
            skip=~active_p,
            trace=lp_trace,
            **chunk_kw,
        )
    bound = res.bound + obj_const
    # A diverged IPM instance reports -inf (see ops/ipm.py); fall back to the
    # inherited parent bound so the node keeps exploring instead of being
    # NaN-pruned (observed: platform-dependent divergence on the root LP).
    bound = jnp.where(jnp.isfinite(bound), bound, -jnp.inf)
    bound = jnp.where(active_p, jnp.maximum(bound, state.node_bound[:B]), jnp.inf)

    # Exact integer incumbents from every active processed node's LP point.
    obj_lin, w_int, n_int, y_int = jax.vmap(
        lambda v, kidx: _round_to_incumbent(v, M, Ws[kidx], ks[kidx], rd, moe=moe)
    )(res.v, kidx_p)
    obj_full = jnp.where(active_p, obj_lin + obj_const, jnp.inf)

    best_i = jnp.argmin(obj_full)
    best_obj = obj_full[best_i]
    better = best_obj < state.incumbent
    incumbent = jnp.where(better, best_obj, state.incumbent)
    inc_w = jnp.where(better, w_int[best_i], state.inc_w)
    inc_n = jnp.where(better, n_int[best_i], state.inc_n)
    inc_y = jnp.where(better, y_int[best_i], state.inc_y)
    inc_kidx = jnp.where(better, kidx_p[best_i], state.inc_kidx)

    # Per-k incumbent objectives (always: the sweep reports them); the
    # assignment vectors only in per-k mode — they are dead weight in the
    # global regime and XLA cannot eliminate loop-carried state.
    n_k = state.per_k_best.shape[0]
    round_best_k = jnp.full(n_k, jnp.inf, BDTYPE).at[kidx_p].min(obj_full)
    per_k_best = jnp.minimum(state.per_k_best, round_best_k)
    if per_k:
        improved_k = round_best_k < state.per_k_best
        k_mask = kidx_p[:, None] == jnp.arange(n_k)[None, :]  # (B, n_k)
        r_star = jnp.argmin(
            jnp.where(k_mask, obj_full[:, None], jnp.inf), axis=0
        )  # (n_k,) row that achieved each k's round best
        per_k_w = jnp.where(improved_k[:, None], w_int[r_star], state.per_k_w)
        per_k_n = jnp.where(improved_k[:, None], n_int[r_star], state.per_k_n)
        per_k_y = jnp.where(improved_k[:, None], y_int[r_star], state.per_k_y)
    else:
        per_k_w, per_k_n, per_k_y = state.per_k_w, state.per_k_n, state.per_k_y

    # Prune: a node survives only if its bound can still beat the
    # incumbent by more than the requested relative gap. (With no
    # incumbent yet the threshold must stay +inf, not inf-inf=NaN.)
    # Per-k mode: the comparator is the node's OWN k's incumbent — a
    # losing k must still close its own gap, so the global optimum may
    # not prune it.
    threshold_k = jnp.where(
        jnp.isfinite(per_k_best),
        per_k_best - mip_gap * jnp.abs(per_k_best),
        jnp.inf,
    )
    if per_k:
        threshold = threshold_k[kidx_p]  # (B,) per-node
    else:
        threshold = jnp.where(
            jnp.isfinite(incumbent),
            incumbent - mip_gap * jnp.abs(incumbent),
            jnp.inf,
        )
    survive = active_p & (bound < threshold)

    # Reduced-cost box tightening. The Lagrangian bound prices a unit move of
    # variable j away from its bound-active side at |red_j|:
    #     obj >= bound_raw + red_j (x_j - lo_j)     when red_j > 0
    #     obj >= bound_raw + |red_j| (hi_j - x_j)   when red_j < 0
    # so any x_j further than (threshold - bound_raw)/|red_j| from that side
    # provably cannot beat the incumbent. This collapses the wide MoE y
    # boxes ([0, E], E up to 256) orders of magnitude faster than bisection
    # branching alone. Sound for any dual vector, like the bound itself.
    bound_raw = res.bound + obj_const  # the bound the reduced costs certify
    budget = threshold - bound_raw
    budget = jnp.where(jnp.isfinite(budget) & (budget >= 0), budget, jnp.inf)[
        :, None
    ]
    lo64 = lo_p.astype(BDTYPE)
    hi64 = hi_p.astype(BDTYPE)
    red = res.reduced
    tight_hi = jnp.where(
        int_mask[None, :] & (red > 1e-12),
        jnp.floor(lo64 + budget / jnp.maximum(red, 1e-12) + 1e-9),
        hi64,
    )
    tight_lo = jnp.where(
        int_mask[None, :] & (red < -1e-12),
        jnp.ceil(hi64 - budget / jnp.maximum(-red, 1e-12) - 1e-9),
        lo64,
    )
    hi_p = jnp.minimum(hi_p, tight_hi.astype(DTYPE))
    lo_p = jnp.maximum(lo_p, tight_lo.astype(DTYPE))
    # An emptied box proves the node cannot beat the incumbent.
    survive &= jnp.all(lo_p <= hi_p, axis=1)

    # Close nodes that are provably done: either the box is a single
    # point, or this round's rounded incumbent already achieves the
    # node's lower bound (so nothing better hides in the subtree). An
    # integral-*looking* LP point alone is NOT proof — the IPM may not
    # have converged — so such nodes keep splitting on the widest box.
    width = jnp.where(int_mask[None, :], hi_p - lo_p, 0.0)
    fully_fixed = jnp.max(width, axis=1) < 0.5
    achieved = obj_full <= bound + 1e-6 * jnp.maximum(1.0, jnp.abs(bound))
    survive &= ~(fully_fixed | achieved)

    # Branch variable: most fractional if any, else the widest box.
    # (Reduced-cost-weighted fractionality was tried and measured WORSE on
    # the DeepSeek E=256 instance — degenerate LPs put near-zero reduced
    # costs on exactly the variables that matter.)
    frac = jnp.abs(res.v - jnp.round(res.v))
    branchable = int_mask[None, :] & (width > 0.5)
    frac_m = jnp.where(branchable, frac, -1.0)
    j_frac = jnp.argmax(frac_m, axis=1)
    max_frac = jnp.take_along_axis(frac_m, j_frac[:, None], axis=1)[:, 0]
    j_wide = jnp.argmax(width, axis=1)
    has_frac = max_frac > FRAC_TOL
    j_star = jnp.where(has_frac, j_frac, j_wide)

    lo_j = jnp.take_along_axis(lo_p, j_star[:, None], axis=1)[:, 0]
    hi_j = jnp.take_along_axis(hi_p, j_star[:, None], axis=1)[:, 0]
    vj = jnp.take_along_axis(res.v, j_star[:, None], axis=1)[:, 0]
    split = jnp.where(has_frac, vj, 0.5 * (lo_j + hi_j))
    dn = jnp.clip(jnp.floor(split), lo_j, jnp.maximum(hi_j - 1.0, lo_j))
    up = dn + 1.0

    rows = jnp.arange(B)
    # child A: hi_j -> floor(v_j); child B: lo_j -> ceil(v_j)
    hi_a = hi_p.at[rows, j_star].set(dn)
    lo_b = lo_p.at[rows, j_star].set(up)

    # Unprocessed rows pass through once, with their parent bound still
    # subject to this round's (possibly improved) pruning threshold.
    rest_bound = state.node_bound[B:]
    rest_threshold = (
        threshold_k[state.node_kidx[B:]] if per_k else threshold
    )
    rest_active = state.active[B:] & (rest_bound < rest_threshold)

    child_lo = jnp.concatenate([lo_p, lo_b, state.node_lo[B:]], axis=0)
    child_hi = jnp.concatenate([hi_a, hi_p, state.node_hi[B:]], axis=0)
    child_kidx = jnp.concatenate([kidx_p, kidx_p, state.node_kidx[B:]])
    child_bound = jnp.concatenate([bound, bound, rest_bound])
    child_active = jnp.concatenate([survive, survive, rest_active])
    # Both children inherit the node's final iterate (their boxes differ
    # from it by one split; the kernel projects on entry); pass-through
    # rows keep what they carried. Rows that were solved this round carry
    # a usable iterate whether or not they survive pruning.
    solved = active_p[:, None]
    v_new = jnp.where(solved, res.v.astype(DTYPE), state.node_v[:B])
    y_new = jnp.where(solved, res.y_dual.astype(DTYPE), state.node_y[:B])
    z_new = jnp.where(solved, res.z_dual.astype(DTYPE), state.node_z[:B])
    f_new = jnp.where(solved, res.f_dual.astype(DTYPE), state.node_f[:B])
    warm_new = active_p | state.node_warm[:B]
    child_v = jnp.concatenate([v_new, v_new, state.node_v[B:]], axis=0)
    child_y = jnp.concatenate([y_new, y_new, state.node_y[B:]], axis=0)
    child_z = jnp.concatenate([z_new, z_new, state.node_z[B:]], axis=0)
    child_f = jnp.concatenate([f_new, f_new, state.node_f[B:]], axis=0)
    child_warm = jnp.concatenate([warm_new, warm_new, state.node_warm[B:]])

    # Compact best-bound-first back into the full capacity; track what falls off.
    sort_key = jnp.where(child_active, child_bound, jnp.inf)
    order = jnp.argsort(sort_key)
    if per_k:
        # K-FAIR compaction: under capacity pressure the global best-first
        # order lets one k's deep subtree crowd every other k out, and a
        # spilled node permanently floors its k's certificate. Re-rank so
        # each k keeps its best nodes first (primary key: within-k rank,
        # tie-broken by global bound order) — capacity is shared
        # round-robin by quality instead of winner-take-all.
        kidx_sorted = child_kidx[order]
        active_sorted = child_active[order]
        total = order.shape[0]
        onehot = (
            kidx_sorted[:, None] == jnp.arange(n_k, dtype=kidx_sorted.dtype)
        ) & active_sorted[:, None]
        # int64 KEYS: rank*(total+1) overflows int32 once the frontier
        # passes ~46k rows (node_cap is an unclamped public override), and
        # a wrapped key would scramble exactly the order this exists for.
        # The cumsum itself stays int32 (its values max out at `total`) —
        # only the extracted 1-D rank widens, not the (total, n_k) matrix.
        rank_in_k = (
            jnp.take_along_axis(
                jnp.cumsum(onehot.astype(jnp.int32), axis=0),
                jnp.clip(kidx_sorted, 0, n_k - 1)[:, None],
                axis=1,
            )[:, 0].astype(jnp.int64)
            - 1
        )
        fair_key = (
            jnp.where(active_sorted, rank_in_k, total) * (total + 1)
            + jnp.arange(total)
        )
        order = order[jnp.argsort(fair_key)]
    keep = order[:cap]
    spill = order[cap:]
    spill_live = jnp.where(child_active[spill], child_bound[spill], jnp.inf)
    dropped_bound = jnp.minimum(state.dropped_bound, jnp.min(spill_live))
    # Per-k overflow accounting: a spilled node floors ITS k's certificate,
    # not every k's (the global dropped_bound stays the conservative floor
    # for the global certificate). Per-k mode only — dead state otherwise.
    if per_k:
        per_k_dropped = jnp.minimum(
            state.per_k_dropped,
            jnp.full(n_k, jnp.inf, BDTYPE)
            .at[child_kidx[spill]]
            .min(spill_live),
        )
    else:
        per_k_dropped = state.per_k_dropped

    out = SearchState(
        node_lo=child_lo[keep],
        node_hi=child_hi[keep],
        node_kidx=child_kidx[keep],
        node_bound=child_bound[keep],
        active=child_active[keep],
        incumbent=incumbent,
        inc_w=inc_w,
        inc_n=inc_n,
        inc_y=inc_y,
        inc_kidx=inc_kidx,
        dropped_bound=dropped_bound,
        per_k_best=per_k_best,
        per_k_w=per_k_w,
        per_k_n=per_k_n,
        per_k_y=per_k_y,
        per_k_dropped=per_k_dropped,
        node_v=child_v[keep],
        node_y=child_y[keep],
        node_z=child_z[keep],
        node_f=child_f[keep],
        node_warm=child_warm[keep],
        stat_ipm_iters=state.stat_ipm_iters
        + jnp.sum(res.iters_run).astype(BDTYPE),
        stat_rounds=state.stat_rounds + 1.0,
    )
    return (out, res) if return_res else out


def _seed_root_bounds(
    state: SearchState,
    rd: RoundingData,
    ks: jax.Array,
    Ws: jax.Array,
    obj_const,
    nf: int,
    M: int,
    moe: bool,
    w_max: int,
    e_max: int,
    decomp_steps: int,
    init_duals: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
) -> Tuple[SearchState, Tuple[jax.Array, ...], jax.Array, jax.Array]:
    """Root Lagrangian decomposition bounds + primal incumbent seeding;
    returns ``(state, duals, raw_bounds, m_y)`` — the raw
    (pre-``obj_const``) per-k bounds and the per-device y-profile
    ``m_y[k, i, y] = min over (candidate, w) of the dual term`` ride the
    solve output so streaming ticks can reuse them (the margin fast path
    in ``solve_sweep_jax``).

    Per-device integrality the LP relaxation cannot express: children
    inherit the bounds through the max(ipm, parent) in ``_bnb_round``, and
    losing k's whose decomposition bound already exceeds the incumbent prune
    without a single IPM solve. This is what closes wide-expert MoE root
    gaps (see ``_decomp_bound_roots``). Shared by the packed single-dispatch
    path (``_solve_packed``) and the mesh-sharded path
    (``parallel.mesh.solve_sweep_sharded``), so certified MoE is not a
    single-chip-only property.
    """
    n_k = ks.shape[0]
    raw_bounds, w_star, n_star, y_star, duals, m_y = _decomp_bound_roots(
        rd, ks, Ws, w_max, e_max, steps=decomp_steps, moe=moe,
        init_params=init_duals,
    )
    root_bounds = raw_bounds + obj_const
    state = state._replace(
        node_bound=state.node_bound.at[:n_k].set(root_bounds)
    )

    if decomp_steps == 0:
        # Warm tick: the bound evaluation at the stored duals is all the
        # certificate needs — the incumbent is the previous tick's optimum,
        # re-priced exactly by the ``has_warm`` block, which beats a fresh
        # Lagrangian-primal repair essentially always. Skipping the repair
        # removes an (e_max + 4)-step sequential scan (260 steps at E=256,
        # each pricing 2M candidate vectors) from the warm device program.
        return state, duals, raw_bounds, m_y

    # Seed the incumbent from the Lagrangian primal: repair each k's
    # per-device argmin cells to a feasible placement (greedy exact-priced
    # y repair, scan budget E) and keep the best. On wide-expert instances
    # this lands within the certificate window on round 0 where LP-point
    # rounding lands ~0.5% off.
    def price_root(j):
        v_hint = jnp.zeros(nf, BDTYPE)
        v_hint = v_hint.at[:M].set(w_star[j])
        v_hint = v_hint.at[M : 2 * M].set(n_star[j])
        if moe:
            v_hint = v_hint.at[2 * M : 3 * M].set(y_star[j])
        return _round_to_incumbent(
            v_hint, M, Ws[j], ks[j], rd, moe=moe, y_steps=e_max + 4
        )

    lag_obj, lag_w, lag_n, lag_y = jax.vmap(price_root)(jnp.arange(n_k))
    lag_obj = lag_obj + obj_const
    jbest = jnp.argmin(lag_obj)
    lag_better = lag_obj[jbest] < state.incumbent
    lag_obj_clean = jnp.where(jnp.isfinite(lag_obj), lag_obj, jnp.inf)
    seeded_k = lag_obj_clean < state.per_k_best
    state = state._replace(
        incumbent=jnp.where(lag_better, lag_obj[jbest], state.incumbent),
        inc_w=jnp.where(lag_better, lag_w[jbest], state.inc_w),
        inc_n=jnp.where(lag_better, lag_n[jbest], state.inc_n),
        inc_y=jnp.where(lag_better, lag_y[jbest], state.inc_y),
        inc_kidx=jnp.where(
            lag_better, jbest.astype(jnp.int32), state.inc_kidx
        ),
        per_k_best=jnp.minimum(state.per_k_best, lag_obj_clean),
        per_k_w=jnp.where(seeded_k[:, None], lag_w, state.per_k_w),
        per_k_n=jnp.where(seeded_k[:, None], lag_n, state.per_k_n),
        per_k_y=jnp.where(seeded_k[:, None], lag_y, state.per_k_y),
    )
    return state, duals, raw_bounds, m_y


def _pack_static(sf: StandardForm) -> np.ndarray:
    """Flatten the DRIFT-INVARIANT half of a sweep into one float32 vector.

    On a remote-tunnel TPU the transfer (not FLOPs) is what a solve is
    billed for. The big blocks — the scaled base A (ONE copy even in MoE
    mode: the per-k g entries are scattered in-trace), the structural
    objective, the root boxes, and the slack-box minima — do not change
    when profiles drift (t_comm, expert loads), so they ship once and then
    live on-device behind ``_static_to_device``'s content-addressed cache.
    Warm streaming ticks re-upload only ``_pack_dynamic``'s few KB.

    Zeroed-in-static, filled-in-trace slots: the MoE y columns of c, the
    slack columns and the C entry of hi (b-dependent), and A's 2M expert
    busy entries.
    """
    N = VarLayout(sf.M, sf.moe).n_vars
    C_idx = VarLayout(sf.M, sf.moe).C
    c_struct = np.asarray(sf.c_k, np.float64).copy()
    hi_struct = np.asarray(sf.hi_k, np.float64).copy()
    hi_struct[:, N:] = 0.0
    hi_struct[:, C_idx] = 0.0
    if sf.moe:
        M = sf.M
        c_struct[:, 2 * M : 3 * M] = 0.0
    return np.concatenate(
        [
            np.asarray(sf.A_base, np.float32).ravel(),
            c_struct.astype(np.float32).ravel(),
            np.asarray(sf.lo_k, np.float32).ravel(),
            hi_struct.astype(np.float32).ravel(),
            np.asarray(sf.smin_k, np.float32).ravel(),
            sf.int_mask.astype(np.float32),
        ],
        dtype=np.float32,
    )


def _pack_dynamic(
    sf: StandardForm,
    rd: dict,
    mip_gap: float,
    warm: Optional[Tuple[int, Sequence[int], Sequence[int], Sequence[int]]] = None,
    duals: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    margin: Optional[np.ndarray] = None,
    root_warm: Optional[Tuple[np.ndarray, ...]] = None,
) -> np.ndarray:
    """Flatten the PER-TICK half of a sweep into one float32 vector.

    Everything profile drift can touch: the scaled RHS b (t_comm rides on
    the cycle/prefetch rows), the cycle-time box C_ub, the MoE g-scatter
    scales, the float64 rounding/certificate inputs, and the warm hint.
    A few hundred floats — the whole warm-tick upload.

    The certificate inputs (rounding data, obj_const, ks/Ws, warm hint)
    must stay float64: they ride along as raw f64 *bit pairs* in the f32
    vector and are bitcast back in-trace. (On this TPU runtime f64 is
    stored double-double anyway, so the bit-pair trip loses nothing the
    direct f64 upload wouldn't.)

    ``warm`` = (k_index, w, n, y) seeds the incumbent: the previous round's
    integer assignment, re-priced EXACTLY under this sweep's coefficients
    on-device (a stale objective would break the mip-gap certificate). The
    slot is packed only when present; ``has_warm`` is a static jit arg so
    each layout compiles once.

    ``duals`` = (lam (n_k,), mu (n_k,), tau (n_k, M)) warm-starts the
    Lagrangian root ascent from a previous tick's best multipliers (see
    ``_decomp_bound_roots``); gated by the static ``has_duals``.

    ``margin`` = (n_k,) pre-slackened raw decomp bounds from the previous
    tick (the margin fast path: host-side drift accounting replaces the
    on-device bound evaluation entirely); gated by the static
    ``has_margin``.

    ``root_warm`` = (ok, v, y, z, f) per-k root IPM iterates from the
    previous tick (see ``_solve_packed_impl``'s output tail); f32 — they
    are search state, not certificate inputs — and gated by the static
    ``has_root_warm``.
    """
    M = sf.M
    f32_parts = [np.asarray(sf.b_k, np.float32).ravel()]
    if root_warm is not None:
        ok_w, v_w, y_w, z_w, f_w = root_warm
        f32_parts += [
            np.asarray(ok_w, np.float32).ravel(),
            np.asarray(v_w, np.float32).ravel(),
            np.asarray(y_w, np.float32).ravel(),
            np.asarray(z_w, np.float32).ravel(),
            np.asarray(f_w, np.float32).ravel(),
        ]
    f64_parts = [
        np.asarray(sf.ks, np.float64),
        np.asarray(sf.Ws, np.float64),
        np.asarray([sf.obj_const, mip_gap], np.float64),
        np.asarray(sf.C_ub_k, np.float64),
    ]
    if sf.moe:
        f64_parts.append(np.asarray(sf.gscale, np.float64).ravel())
    for name in _RD_VEC_FIELDS:
        f64_parts.append(np.broadcast_to(np.asarray(rd[name], np.float64), (M,)))
    f64_parts.append(np.asarray([rd["bprime"], rd["E"]], np.float64))
    if warm is not None:
        kidx, w, n, y = warm
        f64_parts.append(
            np.concatenate(
                [[float(kidx)], np.asarray(w, np.float64),
                 np.asarray(n, np.float64), np.asarray(y, np.float64)]
            )
        )
    if duals is not None:
        lam, mu, tau = duals
        f64_parts.append(
            np.concatenate(
                [np.asarray(lam, np.float64).ravel(),
                 np.asarray(mu, np.float64).ravel(),
                 np.asarray(tau, np.float64).ravel()]
            )
        )
    if margin is not None:
        f64_parts.append(np.asarray(margin, np.float64).ravel())
    f64_bits = np.ascontiguousarray(
        np.concatenate(f64_parts, dtype=np.float64)
    ).view(np.float32)
    return np.concatenate(
        [np.concatenate(f32_parts, dtype=np.float32), f64_bits]
    )


# Content-addressed device cache for the static half. Keyed by the packed
# bytes themselves (no hashing subtleties: np.array_equal over ~100 KB is
# tens of microseconds), bounded to the last few distinct instances. Cache
# misses are always CORRECT — they just pay the full upload — so drift that
# does perturb the static half (e.g. a t_comm spike crossing a row-scale
# boundary) degrades to round-2 behavior, never to a wrong solve. The lock
# covers host-thread races on the list (concurrent solves from multiple
# threads would otherwise lose entries or double-upload — correctness-
# neutral but contradicting the warm-tick wire-cost contract).
_STATIC_CACHE: List[Tuple[np.ndarray, jax.Array]] = []
_STATIC_CACHE_CAP = 4
_STATIC_CACHE_LOCK = threading.Lock()


def _entry_alive(dev: jax.Array) -> bool:
    """A cached device buffer is reusable only while its backend lives: a
    torn-down backend (or a reconnected tunnel) deletes buffers, and
    dispatching against one fails with an opaque runtime error — treat it
    as a miss and re-upload instead."""
    try:
        if dev.is_deleted():
            return False
        return all(d in jax.devices() for d in dev.devices())
    except Exception:  # noqa: BLE001 - any probe failure means "dead"
        return False


def _static_to_device(vec: np.ndarray) -> Tuple[jax.Array, bool]:
    """(device array, uploaded-this-call). Reuses a cached device copy when
    the packed static bytes match a recent instance AND its buffer is still
    alive on a current device."""
    with _STATIC_CACHE_LOCK:
        for i, (host, dev) in enumerate(_STATIC_CACHE):
            if host.shape == vec.shape and np.array_equal(host, vec):
                if not _entry_alive(dev):
                    del _STATIC_CACHE[i]
                    break
                if i != len(_STATIC_CACHE) - 1:  # LRU bump
                    _STATIC_CACHE.append(_STATIC_CACHE.pop(i))
                return dev, False
    dev = jnp.asarray(vec)
    with _STATIC_CACHE_LOCK:
        _STATIC_CACHE.append((vec, dev))
        del _STATIC_CACHE[:-_STATIC_CACHE_CAP]
    return dev, True


def clear_static_cache() -> None:
    """Drop cached device-resident static blobs (tests; device teardown)."""
    with _STATIC_CACHE_LOCK:
        _STATIC_CACHE.clear()


_RD_VEC_FIELDS = (
    "a",
    "b_gpu",
    "pen_set",
    "pen_vram",
    "busy_const",
    "s_disk",
    "ram_rhs",
    "ram_minus_n",
    "cuda_rhs",
    "metal_rhs",
    "has_gpu",
    "g_raw",
    "eb_ram",
    "eb_vram",
    "eb_metal",
    "w_active",
)


_PACKED_STATIC_ARGS = (
    "M", "n_k", "m", "nf", "cap", "ipm_iters", "max_rounds", "beam", "moe",
    "has_warm", "w_max", "e_max", "decomp_steps", "has_duals", "per_k",
    "has_margin", "ipm_warm_iters", "has_root_warm", "lp_backend",
    "pdhg_restart_tol", "mesh_shards", "pdhg_dtype", "diag",
)


def _solve_packed_impl(
    static_blob: jax.Array,
    dyn_blob: jax.Array,
    M: int,
    n_k: int,
    m: int,
    nf: int,
    cap: int,
    ipm_iters: int = IPM_ITERS,
    max_rounds: int = MAX_ROUNDS,
    beam: Optional[int] = BEAM,
    moe: bool = False,
    has_warm: bool = False,
    w_max: int = 0,
    e_max: int = 0,
    decomp_steps: int = 0,
    has_duals: bool = False,
    per_k: bool = False,
    has_margin: bool = False,
    ipm_warm_iters: Optional[int] = None,
    has_root_warm: bool = False,
    lp_backend: str = "ipm",
    pdhg_restart_tol: float = DEFAULT_RESTART_TOL,
    mesh_shards: int = 1,
    pdhg_dtype: Optional[str] = None,
    diag: bool = False,
) -> jax.Array:
    """One-dispatch sweep: unpack the two blobs (``_pack_static`` stays
    device-resident across streaming ticks; ``_pack_dynamic`` is the per-tick
    upload), materialize the b-dependent pieces in-trace (slack-box his, the
    C bound, the MoE g scatter into A and c), build the root state, run the
    fused B&B loop, and pack the answer into one float64 vector:

        [incumbent, best_bound, inc_kidx, dropped_bound,
         ipm_iters_executed, bnb_rounds,
         inc_w (M), inc_n (M), inc_y (M), per_k_best (n_k)]

    When the root decomposition runs (``decomp_steps >= 0 and w_max > 0``)
    the chosen Lagrangian multipliers and the raw per-k bounds are appended
    as ``[lam (n_k), mu (n_k), tau (n_k*M), root_bounds (n_k)]`` so the
    caller can persist them and warm-start the next streaming tick's ascent
    (``has_duals``) or reuse the bounds via the margin fast path
    (``has_margin``: the per-k root bounds come pre-slackened from the host
    in the dynamic blob and NO decomposition program is traced at all —
    the duals pass through unchanged).

    ``per_k`` appends the per-k certified output —
    ``[per_k_w (n_k*M), per_k_n (n_k*M), per_k_y (n_k*M),
    per_k_bound (n_k)]`` — and switches the search to per-k pruning (every
    feasible k terminates with its own optimum and certificate).

    The ROOT-ROUND IPM iterates — ``[ok (n_k), v (n_k*nf), y (n_k*m),
    z (n_k*nf), f (n_k*nf)]`` — always follow (before the m_y tail): the
    caller persists them and ships them back through ``has_root_warm``'s
    dynamic-blob slot so the next streaming tick's root round starts from
    this tick's iterates instead of mid-box.

    ``diag`` (static, the convergence-diagnostics path) appends the B&B
    round log ``(max_rounds, RL_COLS)`` and the root round's per-chunk LP
    trace ``(n_k, _root_trace_rows(...), TRACE_COLS)`` right after the
    root-iterate block — BEFORE the m_y tail, which stays last so the
    margin anchor's negative-index read in ``collect_sweep`` is unmoved.
    With ``diag=False`` the output vector is byte-identical to the
    pre-diagnostics program (pinned in tests/test_convergence.py).
    """
    if has_margin and not (has_duals and has_warm):
        # Static-arg invariant, so it must survive `python -O` (an assert
        # would not): tracing with has_margin but no duals block would
        # build a program whose output decode is silently mis-aligned.
        raise ValueError(
            "margin fast path requires stored duals AND a warm incumbent "
            f"(has_margin={has_margin}, has_duals={has_duals}, "
            f"has_warm={has_warm})"
        )
    lay = VarLayout(M, moe)
    N = lay.n_vars
    m_ub = m - lay.n_eq
    C_idx = lay.C

    off = 0

    def take_s(n):
        nonlocal off
        s = static_blob[off : off + n]
        off += n
        return s

    A_base = take_s(m * nf).reshape(m, nf)
    c_k = take_s(n_k * nf).reshape(n_k, nf)
    lo_k = take_s(n_k * nf).reshape(n_k, nf)
    hi_k = take_s(n_k * nf).reshape(n_k, nf)
    smin_k = take_s(n_k * m_ub).reshape(n_k, m_ub)
    int_mask = take_s(nf) > 0.5
    if off != static_blob.shape[0]:
        # Trace-time static invariant (shapes are Python ints here); it must
        # survive `python -O` — a layout drift would decode the blob
        # mis-aligned and corrupt the certificate, not crash.
        raise ValueError(
            f"_pack_static/_solve_packed layout drift: "
            f"consumed {off} of {static_blob.shape[0]}"
        )

    offd = 0

    def take32(n):
        nonlocal offd
        s = dyn_blob[offd : offd + n]
        offd += n
        return s

    b_k = take32(n_k * m).reshape(n_k, m)
    root_warm = None
    if has_root_warm:
        # Previous tick's per-k root iterates (f32: they are search state,
        # not certificate inputs — a corrupted iterate can only cost
        # iterations, the kernel falls back to cold per element).
        rw_ok = take32(n_k) > 0.5
        rw_v = take32(n_k * nf).reshape(n_k, nf)
        rw_y = take32(n_k * m).reshape(n_k, m)
        rw_z = take32(n_k * nf).reshape(n_k, nf)
        rw_f = take32(n_k * nf).reshape(n_k, nf)
        root_warm = (rw_ok, rw_v, rw_y, rw_z, rw_f)

    # Everything certificate-critical rides as f64 bit pairs (_pack_dynamic).
    f64v = jax.lax.bitcast_convert_type(
        dyn_blob[offd:].reshape(-1, 2), jnp.float64
    )
    off64 = 0

    def take(n):
        nonlocal off64
        s = f64v[off64 : off64 + n]
        off64 += n
        return s

    ks = take(n_k)
    Ws = take(n_k)
    obj_const, mip_gap = take(2)
    C_ub_k = take(n_k)
    if moe:
        gscale = take(2 * M).reshape(2, M)
    rd_vecs = {name: take(M) for name in _RD_VEC_FIELDS}
    bprime, E = take(2)
    if has_warm:
        warm_kidx_f = take(1)[0]
        warm_w = take(M)
        warm_n = take(M)
        warm_y = take(M)
    init_duals = None
    if has_duals:
        d_lam = take(n_k)
        d_mu = take(n_k)
        d_tau = take(n_k * M).reshape(n_k, M)
        init_duals = (d_lam, d_mu, d_tau)
    margin_bounds = take(n_k) if has_margin else None
    if off64 != f64v.shape[0]:
        # Same class as the static-blob check above: must survive -O.
        raise ValueError(
            f"_pack_dynamic/_solve_packed layout drift: "
            f"consumed {off64} of {f64v.shape[0]}"
        )

    # --- in-trace materialization of the b-dependent / per-k pieces ---
    # Slack boxes: hi_slack = max(b_scaled - smin, 0), mirroring the host
    # computation in build_standard_form. smin_k ships WITHOUT the C
    # column's term (its box drifts with t_comm); re-add it here from the
    # dynamic C_ub_k.
    aC = A_base[:m_ub, C_idx]
    loC = lo_k[:, C_idx]
    cmin = jnp.minimum(
        aC[None, :] * loC[:, None],
        aC[None, :] * C_ub_k[:, None].astype(DTYPE),
    )
    hi_k = hi_k.at[:, N:].set(
        jnp.maximum(b_k[:, :m_ub] - (smin_k + cmin), 0.0)
    )
    hi_k = hi_k.at[:, C_idx].set(C_ub_k.astype(DTYPE))
    gky_tab = None
    if moe:
        # The per-k matrices differ from the base in only the 2M expert-busy
        # entries: keep the base SHARED and hand ``_bnb_round`` the per-k
        # scatter table — each round scatters the beam's 2M-entry rows
        # in-trace instead of this program materializing (and the round
        # gathering) n_k full matrices. c's y block still fills here.
        y_cols = 2 * M + jnp.arange(M)
        gky, gky_tab = _gky_scatter_table(rd_vecs["g_raw"], ks, gscale)
        c_k = c_k.at[:, y_cols].set(gky)
    A = A_base  # shared across k; MoE rides the gky scatter

    rd = RoundingData(bprime=bprime, E=E, **rd_vecs)
    data = SweepData(
        A=A,
        b_k=b_k,
        c_k=c_k,
        int_mask=int_mask,
        ks=ks,
        Ws=Ws,
        obj_const=obj_const,
        rd=rd,
        gky=gky_tab,
    )

    state = _root_state(lo_k, hi_k, M, cap, m, root_warm=root_warm)

    out_duals = None
    out_root_bounds = None
    out_m_y = None
    if has_margin:
        # Margin fast path: the previous full evaluation's per-k bounds,
        # re-derived HOST-side under the drift (exact in the g/busy
        # channels — see ``margin_bounds_from_state``), replace the
        # on-device bound evaluation entirely — no decomposition program
        # is traced. The stored duals pass through so the chain keeps
        # flowing.
        state = state._replace(
            node_bound=state.node_bound.at[:n_k].set(margin_bounds + obj_const)
        )
        out_duals = init_duals
        out_root_bounds = margin_bounds
    elif decomp_steps >= 0 and w_max > 0:
        state, out_duals, out_root_bounds, out_m_y = _seed_root_bounds(
            state, rd, ks, Ws, obj_const, nf, M, moe, w_max, e_max,
            decomp_steps, init_duals=init_duals,
        )

    if has_warm:
        # Warm start: re-price the previous assignment under THESE
        # coefficients (exact closed form, float64) and seed the incumbent
        # with it. Invalid or stale-infeasible assignments price to +inf and
        # leave the state cold.
        warm_kidx = jnp.clip(warm_kidx_f.astype(jnp.int32), 0, n_k - 1)
        v_warm = jnp.zeros(nf, BDTYPE)
        v_warm = v_warm.at[:M].set(warm_w).at[M : 2 * M].set(warm_n)
        if moe:
            v_warm = v_warm.at[2 * M : 3 * M].set(warm_y)
        # Seed with the vectors the pricer actually evaluated (it may have
        # repaired the hint, e.g. redistributed y to sum E or zeroed n on a
        # device that lost its GPU) — seeding the raw hint could return an
        # assignment inconsistent with the certified objective.
        warm_obj, w_rep, n_rep, y_rep = _round_to_incumbent(
            v_warm, M, Ws[warm_kidx], ks[warm_kidx], rd, moe=moe,
            moves=MOE_LOCAL_MOVES_WARM,
        )
        warm_obj = warm_obj + obj_const
        # Adopt the warm point only when it beats whatever already seeded the
        # state (the Lagrangian primal may be strictly better on a MoE tick;
        # a stale-infeasible hint prices to +inf and changes nothing).
        seeded = jnp.isfinite(warm_obj) & (warm_obj < state.incumbent)
        warm_obj_clean = jnp.where(jnp.isfinite(warm_obj), warm_obj, jnp.inf)
        seeded_k = warm_obj_clean < state.per_k_best[warm_kidx]
        state = state._replace(
            incumbent=jnp.where(seeded, warm_obj, state.incumbent),
            inc_w=jnp.where(seeded, w_rep, state.inc_w),
            inc_n=jnp.where(seeded, n_rep, state.inc_n),
            inc_y=jnp.where(seeded, y_rep, state.inc_y),
            inc_kidx=jnp.where(seeded, warm_kidx, state.inc_kidx),
            per_k_best=state.per_k_best.at[warm_kidx].min(warm_obj_clean),
            # Keep the per-k assignment vectors consistent with every
            # per_k_best improvement (the per-k decode trusts them).
            per_k_w=state.per_k_w.at[warm_kidx].set(
                jnp.where(seeded_k, w_rep, state.per_k_w[warm_kidx])
            ),
            per_k_n=state.per_k_n.at[warm_kidx].set(
                jnp.where(seeded_k, n_rep, state.per_k_n[warm_kidx])
            ),
            per_k_y=state.per_k_y.at[warm_kidx].set(
                jnp.where(seeded_k, y_rep, state.per_k_y[warm_kidx])
            ),
        )

    loop_out = _run_bnb_loop(
        data,
        state,
        mip_gap,
        ipm_iters=ipm_iters,
        max_rounds=max_rounds,
        beam=beam,
        moe=moe,
        per_k=per_k,
        ipm_warm_iters=ipm_warm_iters,
        collect_root=True,
        root_warm_chunk=has_root_warm,
        lp_backend=lp_backend,
        pdhg_restart_tol=pdhg_restart_tol,
        mesh_shards=mesh_shards,
        pdhg_dtype=pdhg_dtype,
        collect_rounds=diag,
    )
    if diag:
        state, root_iters, (round_log, root_trace) = loop_out
    else:
        state, root_iters = loop_out

    parts = [
        jnp.stack(
            [
                state.incumbent,
                _best_bound(state),
                state.inc_kidx.astype(BDTYPE),
                state.dropped_bound,
                state.stat_ipm_iters,
                state.stat_rounds,
            ]
        ),
        state.inc_w,
        state.inc_n,
        state.inc_y,
        state.per_k_best,
    ]
    if out_duals is not None:
        lam, mu, tau = out_duals
        parts += [
            lam.astype(BDTYPE).ravel(),
            mu.astype(BDTYPE).ravel(),
            tau.astype(BDTYPE).ravel(),
            out_root_bounds.astype(BDTYPE).ravel(),
        ]
    if per_k:
        parts += [
            state.per_k_w.ravel(),
            state.per_k_n.ravel(),
            state.per_k_y.ravel(),
            _per_k_bound(state),
        ]
    # Root-round iterates (rows 0..n_k-1 of the root beam) for cross-tick
    # persistence — a skipped root round (settled warm tick) re-emits the
    # carried-in iterates, so the chain never decays to cold.
    ok_r, v_r, y_r, z_r, f_r = root_iters
    parts += [
        ok_r[:n_k].astype(BDTYPE),
        v_r[:n_k].astype(BDTYPE).ravel(),
        y_r[:n_k].astype(BDTYPE).ravel(),
        z_r[:n_k].astype(BDTYPE).ravel(),
        f_r[:n_k].astype(BDTYPE).ravel(),
    ]
    if diag:
        # Diagnostics tail (round log + root LP trace) sits BEFORE the m_y
        # anchor so the margin tail's negative-index read stays valid.
        parts += [
            round_log.ravel(),
            root_trace[:n_k].astype(BDTYPE).ravel(),
        ]
    if out_m_y is not None:
        # y-profile tail (n_k*M*(e_max+1)), LAST so no earlier offset moves:
        # read back by solve_sweep_jax for the margin fast path; absent on
        # margin ticks (statics: moe & w_max>0 & not has_margin).
        parts += [out_m_y.astype(BDTYPE).ravel()]
    return jnp.concatenate(parts)


# The jitted single-instance entry (one sweep per dispatch) and its
# scenario-batched sibling: S dynamic blobs against ONE shared static blob,
# vmapped into a single dispatch. On a tunneled TPU every operation bills a
# fixed wire cost, so S what-if placements per dispatch multiply
# placements/sec by ~S — the TPU-idiomatic answer to planning under
# uncertainty (candidate t_comm futures, load scenarios) that a host MILP
# loop would serialize.
# Registered compile-ledger entry point (obs.compile_ledger; dlint DLP020).
# Every name in _PACKED_STATIC_ARGS mints a distinct executable — the
# `lp_backend`/`trace`/`diag`/`ipm_iters` flips the ledger's
# static-arg-flip cause exists to attribute all route through here.
_solve_packed = instrument(
    "solver._solve_packed",
    jax.jit(_solve_packed_impl, static_argnames=_PACKED_STATIC_ARGS),
    static_argnames=_PACKED_STATIC_ARGS,
)


# rd fields the margin evaluator can absorb as drift vs fields that must
# match EXACTLY between the anchor full evaluation and this tick (they
# shape the ceil staircases, the ok mask, or enter cells with
# cell-internal coefficients — a single changed byte there invalidates
# the reuse, so the gate falls back to the full on-device evaluation;
# a fallback is always CORRECT, just slower).
_MARGIN_DRIFT_FIELDS = ("a", "busy_const", "g_raw")
_MARGIN_EXACT_FIELDS = tuple(
    f for f in _RD_VEC_FIELDS if f not in _MARGIN_DRIFT_FIELDS
)


def margin_bounds_from_state(
    margin_state: dict, rd: dict, sf: StandardForm,
    duals: Tuple[np.ndarray, np.ndarray, np.ndarray],
) -> Optional[np.ndarray]:
    """Per-k Lagrangian bounds for THIS tick, re-derived host-side from the
    last full evaluation's y-profile — or None when reuse is unsound.

    At FIXED multipliers the bound is ``sum_i min_cells term_i + lam W +
    mu E`` with ``term = (1+theta)·lin + theta·(busy_const + fetch) -
    lam·w - mu·y``. Under drift confined to the linear channels the new
    term decomposes EXACTLY over the old one:

        term_new(cell) = term_old(cell)
                         + (1+theta)*(dg_i/k)*y        (g_raw: coeff y)
                         + (1+theta)*da_i*w            (a: coeff w)
                         + theta*dbusy_i               (cell-independent)

    The anchor stores ``m_y[k,i,y] = min over (candidate, w) term_old``,
    so the g and busy channels correct EXACTLY (shift the y-profile, re-min
    over 8K host floats — microseconds); the a channel decouples from the
    y-min as a separate ``min over w`` of its linear part (a valid lower
    bound via ``min(f+g) >= min f + min g``; exact when ``da = 0``, the
    streaming t_comm/load case). Because every correction is computed
    against the FIXED anchor, margin ticks do not decay the chain — under
    pure t_comm / expert-load drift the reused bound equals the full
    evaluation's bit for bit, indefinitely.

    Reuse requires (else None): same fleet/k-grid shapes, byte-identical
    exact-match fields (b', eb_*, rhs vectors, has_gpu, penalties, s_disk,
    E — they shape the ceil staircases and the ok mask), and the SAME
    multipliers the anchor was evaluated at. +inf profile slots (infeasible
    (i, y) pairs) stay +inf: feasibility is frozen by the exact-match gate.
    """
    prev_rd = margin_state.get("rd")
    m_y = margin_state.get("m_y")
    if prev_rd is None or m_y is None:
        return None
    ks = np.asarray(sf.ks, np.float64)
    Ws = np.asarray(sf.Ws, np.float64)
    if not (
        np.array_equal(margin_state.get("ks"), ks)
        and np.array_equal(margin_state.get("Ws"), Ws)
    ):
        return None
    M = rd["a"].shape[0]
    E = float(rd["E"])
    if m_y.shape != (ks.shape[0], M, int(E) + 1):
        return None
    for f in _MARGIN_EXACT_FIELDS:
        if not np.array_equal(prev_rd[f], rd[f]):
            return None
    if not (
        np.array_equal(prev_rd["bprime"], rd["bprime"])
        and np.array_equal(prev_rd["E"], rd["E"])
    ):
        return None
    # The anchor profile is only valid AT the multipliers it was evaluated
    # at — reject a caller mixing duals and profiles from different results.
    prev_duals = margin_state.get("duals")
    if prev_duals is None or not all(
        np.array_equal(np.asarray(p, np.float64), np.asarray(q, np.float64))
        for p, q in zip(prev_duals, duals)
    ):
        return None

    lam, mu, tau = (np.asarray(p, np.float64) for p in duals)
    t = np.exp(tau - tau.max(axis=1, keepdims=True))
    theta = (ks - 1.0)[:, None] * (t / t.sum(axis=1, keepdims=True))

    dG = np.asarray(rd["g_raw"] - prev_rd["g_raw"], np.float64)
    dA = np.asarray(rd["a"] - prev_rd["a"], np.float64)
    dB = np.asarray(rd["busy_const"] - prev_rd["busy_const"], np.float64)

    y_vals = np.arange(0, int(E) + 1, dtype=np.float64)
    kappa = (1.0 + theta) * dG[None, :] / ks[:, None]  # (n_k, M)
    shifted = m_y + kappa[:, :, None] * y_vals[None, None, :]
    per_dev = shifted.min(axis=2)  # (n_k, M) — exact g correction
    # a channel: linear in w over [1, W_k], decoupled endpoint minimum.
    a_coef = (1.0 + theta) * dA[None, :]
    per_dev = per_dev + np.minimum(a_coef, a_coef * Ws[:, None])
    # busy channel: cell-independent, exact.
    per_dev = per_dev + theta * dB[None, :]

    bound = per_dev.sum(axis=1) + lam * Ws + mu * E
    # Host numpy and the device program may round theta differently by an
    # ulp; a hair of slack keeps the reused bound strictly on the sound
    # side without denting the 1e-3-scale certificate.
    bound = bound - 1e-9 * (1.0 + np.abs(bound))
    # A device whose whole profile is +inf proves the k infeasible (+inf
    # honest); NaN (e.g. inf - inf artifacts) degrades to reuse refusal.
    infeasible = np.isposinf(per_dev).any(axis=1)
    bound = np.where(infeasible, np.inf, bound)
    if np.isnan(bound).any():
        return None
    return bound


def _solve_scenarios_packed(
    static_blob: jax.Array,
    dyn_blobs: jax.Array,  # (S, dyn_len)
    M: int,
    n_k: int,
    m: int,
    nf: int,
    cap: int,
    ipm_iters: int = IPM_ITERS,
    max_rounds: int = MAX_ROUNDS,
    beam: Optional[int] = BEAM,
    moe: bool = False,
    has_warm: bool = False,
    w_max: int = 0,
    e_max: int = 0,
    decomp_steps: int = 0,
    has_duals: bool = False,
    per_k: bool = False,
    has_margin: bool = False,
    ipm_warm_iters: Optional[int] = None,
    has_root_warm: bool = False,
    lp_backend: str = "ipm",
    pdhg_restart_tol: float = DEFAULT_RESTART_TOL,
    mesh_shards: int = 1,
    pdhg_dtype: Optional[str] = None,
    diag: bool = False,
) -> jax.Array:
    # mesh_shards is accepted for static-surface symmetry but clamped:
    # the scenario axis already composes by vmap, and vmap-of-shard_map
    # does not lower on the jax this image ships. pdhg_dtype composes
    # fine and threads for real.
    return jax.vmap(
        lambda dyn: _solve_packed_impl(
            static_blob, dyn, M=M, n_k=n_k, m=m, nf=nf, cap=cap,
            ipm_iters=ipm_iters, max_rounds=max_rounds, beam=beam, moe=moe,
            has_warm=has_warm, w_max=w_max, e_max=e_max,
            decomp_steps=decomp_steps, has_duals=has_duals, per_k=per_k,
            has_margin=has_margin, ipm_warm_iters=ipm_warm_iters,
            has_root_warm=has_root_warm, lp_backend=lp_backend,
            pdhg_restart_tol=pdhg_restart_tol, mesh_shards=1,
            pdhg_dtype=pdhg_dtype, diag=diag,
        )
    )(dyn_blobs)


# Registered compile-ledger entry point (obs.compile_ledger; dlint DLP020):
# the scenario batch shares _solve_packed's static surface but is its own
# executable — speculation's first presolve pays this compile, and the
# ledger shows it as this entry's cold, not a _solve_packed recompile.
_solve_scenarios_packed = instrument(
    "solver._solve_scenarios_packed",
    jax.jit(_solve_scenarios_packed, static_argnames=_PACKED_STATIC_ARGS),
    static_argnames=_PACKED_STATIC_ARGS,
)


def _solve_batched(
    static_blobs: jax.Array,  # (B, static_len)
    dyn_blobs: jax.Array,  # (B, dyn_len)
    M: int,
    n_k: int,
    m: int,
    nf: int,
    cap: int,
    ipm_iters: int = IPM_ITERS,
    max_rounds: int = MAX_ROUNDS,
    beam: Optional[int] = BEAM,
    moe: bool = False,
    has_warm: bool = False,
    w_max: int = 0,
    e_max: int = 0,
    decomp_steps: int = 0,
    has_duals: bool = False,
    per_k: bool = False,
    has_margin: bool = False,
    ipm_warm_iters: Optional[int] = None,
    has_root_warm: bool = False,
    lp_backend: str = "ipm",
    pdhg_restart_tol: float = DEFAULT_RESTART_TOL,
    mesh_shards: int = 1,
    pdhg_dtype: Optional[str] = None,
    diag: bool = False,
) -> jax.Array:
    """Cross-instance batch: N heterogeneous HALDA instances, ONE dispatch.

    Where ``_solve_scenarios_packed`` vmaps over dynamic blobs of a single
    instance family (one static half shared by every scenario), this entry
    vmaps over BOTH halves — each batch lane carries its own static blob
    (its own A matrix, boxes, row scaling, integer mask), so instances from
    unrelated fleets solve side by side as long as their static-shape
    signature (this function's static argnames plus the two blob lengths)
    matches. Mixed device counts within a bucket ride phantom padding
    (``solver.batchlayout``): every lane is a complete, exactly-priced MILP,
    so per-lane certificates decode independently.
    """
    # Same mesh_shards clamp as _solve_scenarios_packed: the lane axis is
    # the vmap, so the row mesh cannot nest under it on this jax.
    return jax.vmap(
        lambda stat, dyn: _solve_packed_impl(
            stat, dyn, M=M, n_k=n_k, m=m, nf=nf, cap=cap,
            ipm_iters=ipm_iters, max_rounds=max_rounds, beam=beam, moe=moe,
            has_warm=has_warm, w_max=w_max, e_max=e_max,
            decomp_steps=decomp_steps, has_duals=has_duals, per_k=per_k,
            has_margin=has_margin, ipm_warm_iters=ipm_warm_iters,
            has_root_warm=has_root_warm, lp_backend=lp_backend,
            pdhg_restart_tol=pdhg_restart_tol, mesh_shards=1,
            pdhg_dtype=pdhg_dtype, diag=diag,
        )
    )(static_blobs, dyn_blobs)


# Registered entry for the cross-shard combiner (distilp_tpu.combine): one
# executable per bucket signature. Bucket boundaries come from a COMMITTED
# policy (combine.BucketPolicy), so warm bucket traffic re-dispatches this
# same executable — the PR 14 zero-recompile gate holds across it.
_solve_batched = instrument(
    "solver._solve_batched",
    jax.jit(_solve_batched, static_argnames=_PACKED_STATIC_ARGS),
    static_argnames=_PACKED_STATIC_ARGS,
)


def _best_bound(state: SearchState) -> jax.Array:
    live = jnp.min(jnp.where(state.active, state.node_bound, jnp.inf))
    return jnp.minimum(live, state.dropped_bound)


def _certified(state: SearchState, mip_gap) -> jax.Array:
    inc = state.incumbent
    return jnp.isfinite(inc) & (inc - _best_bound(state) <= mip_gap * jnp.abs(inc))


def _per_k_bound(state: SearchState) -> jax.Array:
    """(n_k,) proven lower bound per k: min over that k's live nodes and its
    own overflow floor."""
    n_k = state.per_k_best.shape[0]
    live = (
        jnp.full(n_k, jnp.inf, BDTYPE)
        .at[state.node_kidx]
        .min(jnp.where(state.active, state.node_bound, jnp.inf))
    )
    return jnp.minimum(live, state.per_k_dropped)


def _certified_per_k(state: SearchState, mip_gap) -> jax.Array:
    """True when EVERY k is settled: its own gap closed, or its subtree
    exhausted (bound +inf: nothing live, nothing dropped — the incumbent,
    or infeasibility, is exact)."""
    bound_k = _per_k_bound(state)
    inc_k = state.per_k_best
    done = jnp.isfinite(inc_k) & (inc_k - bound_k <= mip_gap * jnp.abs(inc_k))
    exhausted = jnp.isposinf(bound_k)  # NOT -inf (unexplored roots)
    return jnp.all(done | exhausted)


def _run_bnb_loop(
    data: SweepData,
    state: SearchState,
    mip_gap,
    ipm_iters: int = IPM_ITERS,
    max_rounds: int = MAX_ROUNDS,
    beam: Optional[int] = None,
    moe: bool = False,
    per_k: bool = False,
    ipm_warm_iters: Optional[int] = None,
    collect_root: bool = False,
    root_warm_chunk: bool = False,
    root_beam: Optional[int] = None,
    lp_backend: str = "ipm",
    pdhg_restart_tol: float = DEFAULT_RESTART_TOL,
    mesh_shards: int = 1,
    pdhg_dtype: Optional[str] = None,
    collect_rounds: bool = False,
):
    """B&B rounds with the mip-gap test on-device. The single shared
    definition of the search loop (traced by both the packed single-dispatch
    path and the mesh-sharded path). ``per_k`` switches both the pruning
    regime and the termination test (every k settled vs the global gap
    closed).

    Two-phase structure: a ROOT round first — full ``ipm_iters`` budget and
    a beam widened to cover every root, since roots either start cold or
    from last tick's iterates — then a ``lax.while_loop`` of warm rounds at
    the (smaller) ``ipm_warm_iters`` budget, sound because every loop node
    carries its parent's iterate and a truncated solve only loosens the f64
    bound. The root round itself sits under ``lax.cond``: a streaming tick
    whose seeded bounds + warm incumbent already certify (the settled test)
    pays ZERO IPM work, exactly like the old loop's round-0 exit.

    ``collect_root=True`` additionally returns the root round's iterates
    ``(ok, v, y, z, f)`` (beam-row arrays; roots are rows ``0..n_k-1``) for
    cross-tick persistence — on a skipped root round the carried-in warm
    iterates pass through unchanged.

    ``root_warm_chunk=True`` keeps the kernel's small convergence-test
    chunks for the root round (the roots carry last tick's iterates and
    exit after a few steps); a cold root needs its whole budget, so by
    default the root runs one full-length chunk and skips the while-loop
    overhead entirely.

    ``collect_rounds=True`` (the diagnostics path) additionally threads a
    fixed-size per-round log through the loop carry (one `_round_row` per
    executed round, root at row 0) and runs the ROOT round's LP solve with
    the kernel convergence trace on; the return grows a trailing
    ``(round_log, root_trace)`` pair. Off (the default), the carry, the
    cond and the body are byte-for-byte the pre-diagnostics program.
    """
    warm_iters = ipm_iters if ipm_warm_iters is None else ipm_warm_iters
    n_k = state.per_k_best.shape[0]
    cap = state.node_lo.shape[0]
    # The root frontier is exactly the n_k root nodes (rows 0..n_k-1), so
    # the root round's batch is sized to them — a wider beam would only add
    # skip-masked lanes that still pay their share of each batched
    # factorization. ``root_beam`` overrides upward (never below n_k): the
    # mesh-sharded path pads it to a multiple of the mesh size so the root
    # round keeps its even-rows-per-device sharding.
    B0 = min(cap, max(n_k, root_beam or 0))

    def settled_of(st):
        return (
            _certified_per_k(st, mip_gap)
            if per_k
            else _certified(st, mip_gap)
        )

    def passthrough(st):
        return st, (
            st.node_warm[:B0],
            st.node_v[:B0],
            st.node_y[:B0],
            st.node_z[:B0],
            st.node_f[:B0],
        )

    if collect_rounds:
        rlog0 = jnp.zeros((max_rounds, RL_COLS), BDTYPE)
        rtrace0 = jnp.zeros(
            (B0, _root_trace_rows(lp_backend, ipm_iters, root_warm_chunk),
             TRACE_COLS),
            DTYPE,
        )

    def root_solve(st, lp_trace):
        ok = st.active[:B0]
        st2, res = _bnb_round(
            data, st, mip_gap, ipm_iters=ipm_iters, beam=B0,
            moe=moe, per_k=per_k, return_res=True,
            ipm_chunk=None if root_warm_chunk else ipm_iters,
            lp_backend=lp_backend, pdhg_restart_tol=pdhg_restart_tol,
            mesh_shards=mesh_shards, pdhg_dtype=pdhg_dtype,
            lp_trace=lp_trace,
        )
        return st2, (
            ok,
            res.v.astype(DTYPE),
            res.y_dual.astype(DTYPE),
            res.z_dual.astype(DTYPE),
            res.f_dual.astype(DTYPE),
        ), res

    if max_rounds >= 1 and collect_rounds:
        def root_fn_d(args):
            st, rlog = args
            st2, iters_t, res = root_solve(st, True)
            rlog = rlog.at[0].set(_round_row(st, st2, B0))
            return st2, iters_t, rlog, res.trace_buf.astype(DTYPE)

        def pass_fn_d(args):
            st, rlog = args
            st2, iters_t = passthrough(st)
            return st2, iters_t, rlog, rtrace0

        state, root_iters, rlog, root_trace = jax.lax.cond(
            jnp.any(state.active) & ~settled_of(state),
            root_fn_d,
            pass_fn_d,
            (state, rlog0),
        )
    elif max_rounds >= 1:
        def root_fn(st):
            st2, iters_t, _res = root_solve(st, False)
            return st2, iters_t

        state, root_iters = jax.lax.cond(
            jnp.any(state.active) & ~settled_of(state),
            root_fn,
            passthrough,
            state,
        )
    else:
        state, root_iters = passthrough(state)
        if collect_rounds:
            rlog, root_trace = rlog0, rtrace0

    Bw = cap if beam is None else min(beam, cap)

    if collect_rounds:
        def cond_d(carry):
            state, i, _rlog = carry
            return (
                (i < max_rounds) & jnp.any(state.active) & ~settled_of(state)
            )

        def body_d(carry):
            state, i, rlog = carry
            st2 = _bnb_round(
                data, state, mip_gap, ipm_iters=warm_iters, beam=beam,
                moe=moe, per_k=per_k,
                lp_backend=lp_backend, pdhg_restart_tol=pdhg_restart_tol,
                mesh_shards=mesh_shards, pdhg_dtype=pdhg_dtype,
            )
            rlog = rlog.at[i].set(_round_row(state, st2, Bw))
            return (st2, i + 1, rlog)

        state, _, rlog = jax.lax.while_loop(
            cond_d, body_d, (state, jnp.asarray(1, jnp.int32), rlog)
        )
    else:
        def cond(carry):
            state, i = carry
            return (
                (i < max_rounds) & jnp.any(state.active) & ~settled_of(state)
            )

        def body(carry):
            state, i = carry
            return (
                _bnb_round(
                    data, state, mip_gap, ipm_iters=warm_iters, beam=beam,
                    moe=moe, per_k=per_k,
                    lp_backend=lp_backend, pdhg_restart_tol=pdhg_restart_tol,
                    mesh_shards=mesh_shards, pdhg_dtype=pdhg_dtype,
                ),
                i + 1,
            )

        state, _ = jax.lax.while_loop(
            cond, body, (state, jnp.asarray(1, jnp.int32))
        )

    if collect_root and collect_rounds:
        return state, root_iters, (rlog, root_trace)
    if collect_rounds:
        return state, (rlog, root_trace)
    if collect_root:
        return state, root_iters
    return state


_FUSED_STATIC_ARGS = (
    "ipm_iters", "max_rounds", "beam", "moe", "per_k", "ipm_warm_iters",
    "root_beam", "lp_backend", "pdhg_restart_tol", "mesh_shards",
    "pdhg_dtype",
)


def _solve_fused(
    data: SweepData,
    state: SearchState,
    mip_gap: jax.Array,
    ipm_iters: int = IPM_ITERS,
    max_rounds: int = MAX_ROUNDS,
    beam: Optional[int] = None,
    moe: bool = False,
    per_k: bool = False,
    ipm_warm_iters: Optional[int] = None,
    root_beam: Optional[int] = None,
    lp_backend: str = "ipm",
    pdhg_restart_tol: float = DEFAULT_RESTART_TOL,
    mesh_shards: int = 1,
    pdhg_dtype: Optional[str] = None,
) -> SearchState:
    """The full branch-and-bound sweep as one device program; the host does
    one dispatch and one fetch per HALDA solve."""
    return _run_bnb_loop(
        data,
        state,
        mip_gap,
        ipm_iters=ipm_iters,
        max_rounds=max_rounds,
        beam=beam,
        moe=moe,
        per_k=per_k,
        ipm_warm_iters=ipm_warm_iters,
        root_beam=root_beam,
        lp_backend=lp_backend,
        pdhg_restart_tol=pdhg_restart_tol,
        mesh_shards=mesh_shards,
        pdhg_dtype=pdhg_dtype,
    )


# Registered compile-ledger entry point (obs.compile_ledger; dlint DLP020):
# the mesh-sharded sweep (parallel/mesh.py) dispatches through this one.
_solve_fused = instrument(
    "solver._solve_fused",
    jax.jit(_solve_fused, static_argnames=_FUSED_STATIC_ARGS),
    static_argnames=_FUSED_STATIC_ARGS,
)


def _warm_and_duals(
    sf: StandardForm,
    arrays: MilpArrays,
    warm: Optional[ILPResult],
    feasible: Sequence[Tuple[int, int]],
):
    """(warm_tuple, duals_tuple, root_warm_tuple) for one sweep — the
    host-side preparation of a previous solve's assignment, Lagrangian
    multipliers, and root IPM iterates, shared by the single-dispatch and
    scenario-batched paths."""
    M = sf.M
    n_k = len(sf.ks)
    warm_tuple = None
    if warm is not None and warm.w is not None and len(warm.w) == M:
        k_index = {k: j for j, (k, _) in enumerate(feasible)}
        if warm.k in k_index:
            if sf.moe:
                E = arrays.moe.E
                if warm.y is not None and sum(warm.y) == E:
                    warm_y = warm.y
                else:
                    # Hint lacks a usable expert split (dense->MoE tick):
                    # spread evenly HOST-side — the in-trace repair scan only
                    # covers deficits up to ~M, far less than E can be.
                    warm_y = [E // M + (1 if i < E % M else 0) for i in range(M)]
            else:
                warm_y = [0] * M
            warm_tuple = (k_index[warm.k], warm.w, warm.n, warm_y)

    # Stored root multipliers from the previous tick, when their shape still
    # matches this sweep (same k grid, same fleet size).
    duals_tuple = None
    if warm is not None and warm.duals is not None and sf.moe:
        try:
            lam = np.asarray(warm.duals["lam"], np.float64)
            mu = np.asarray(warm.duals["mu"], np.float64)
            tau = np.asarray(warm.duals["tau"], np.float64)
        except (KeyError, TypeError, ValueError):
            lam = mu = tau = None
        if (
            lam is not None
            and lam.shape == (n_k,)
            and mu.shape == (n_k,)
            and tau.shape == (n_k, M)
            and np.all(np.isfinite(lam))
            and np.all(np.isfinite(mu))
            and np.all(np.isfinite(tau))
        ):
            duals_tuple = (lam, mu, tau)

    # Previous tick's root IPM iterates, when their shapes still match this
    # sweep (same k grid, same LP family shape). Finite-ness is NOT gated
    # here: the kernel falls back to a cold start per element on any
    # non-finite component, so a partially-stale state still helps.
    root_warm_tuple = None
    ipm_state = getattr(warm, "ipm_state", None) if warm is not None else None
    if ipm_state is not None:
        m = sf.A.shape[1]
        nf = sf.A.shape[2]
        try:
            ok = np.asarray(ipm_state["ok"], np.float32)
            v = np.asarray(ipm_state["v"], np.float32)
            y = np.asarray(ipm_state["y"], np.float32)
            z = np.asarray(ipm_state["z"], np.float32)
            f = np.asarray(ipm_state["f"], np.float32)
        except (KeyError, TypeError, ValueError):
            ok = None
        if (
            ok is not None
            and ok.shape == (n_k,)
            and v.shape == (n_k, nf)
            and y.shape == (n_k, m)
            and z.shape == (n_k, nf)
            and f.shape == (n_k, nf)
        ):
            root_warm_tuple = (ok, v, y, z, f)
    return warm_tuple, duals_tuple, root_warm_tuple


def solve_sweep_jax(
    arrays: MilpArrays,
    kWs: Sequence[Tuple[int, int]],
    mip_gap: float = 1e-4,
    coeffs: Optional[HaldaCoeffs] = None,
    ipm_iters: Optional[int] = None,
    max_rounds: Optional[int] = None,
    beam: Optional[int] = None,
    node_cap: Optional[int] = None,
    debug: bool = False,
    warm: Optional[ILPResult] = None,
    timings: Optional[dict] = None,
    collect: bool = True,
    per_k_optima: bool = False,
    margin_state: Optional[dict] = None,
    ipm_warm_iters: Optional[int] = None,
    lp_backend: Optional[str] = None,
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
    mesh_shards: Optional[int] = None,
    pdhg_dtype: Optional[str] = None,
    convergence: Optional[dict] = None,
):
    """Solve the whole k-sweep on the accelerator.

    ``convergence`` (pass a dict) turns on solver-interior telemetry: the
    fused program additionally records a per-B&B-round log and the root
    round's per-chunk LP convergence trace (see ops/ipm.py TRACE_COLS),
    decoded into the dict at collect time — ``round_log`` (one
    ``[round, expanded, live_after, incumbent, bound, lp_iters]`` entry
    per executed round), ``root_trace`` (n_k × rows × TRACE_COLS nested
    lists), plus the engine/header facts ``obs.convergence`` builds its
    ``SearchTrace`` report from. A convergence digest (``conv_*`` keys)
    also lands in ``timings``. Default None = the exact untraced program
    (outputs byte-identical, pinned in tests/test_convergence.py).

    ``lp_backend`` picks the LP relaxation engine ('ipm' | 'pdhg' | 'auto',
    None = 'auto': pdhg at or above ``PDHG_AUTO_M`` devices). Both engines
    share the warm-start plumbing and the f64 Lagrangian certificate, so
    everything downstream — pruning, reduced-cost tightening, per-k
    certification — is engine-agnostic. ``pdhg_iters``/``pdhg_restart_tol``
    are the first-order budget/restart knobs (ignored under 'ipm'); the
    chosen engine is echoed as ``timings['lp_backend']``.

    ``per_k_optima=True`` switches the search to per-k pruning: every
    feasible k terminates with its OWN certified optimum and full integer
    assignment (the reference's per-k-MILP output contract), instead of the
    default regime where losing k's prune early against the global
    incumbent and report objectives only. Costs more rounds (each k closes
    its own gap) but still one dispatch.

    ``collect=False`` returns a ``PendingSweep`` right after the dispatch
    instead of blocking on the result fetch: the caller overlaps its own
    work (typically the NEXT tick's coefficient build + upload) and redeems
    the handle with ``collect_sweep``. A structurally infeasible sweep
    (no k with W >= M) still returns the plain ``(results, None)`` tuple.

    ``timings`` (when a dict is passed) receives the wall-clock breakdown of
    the solve in milliseconds: ``pack_ms`` (host-side blob assembly),
    ``upload_ms`` (host->device transfer of the packed blob), ``solve_ms``
    (device program: dispatch + execution + result fetch, indivisible on an
    async runtime — the fetch is what blocks). ``debug=True`` prints it.
    This is what locates the latency floor: on a tunneled TPU the
    upload+fetch round-trip is the irreducible part.

    ``warm`` seeds the search with a previous solve's integer assignment
    (re-priced exactly on-device under the current coefficients), so a
    streaming re-solve prunes against a strong incumbent from round one.

    ``margin_state`` (a dict the caller threads across ticks, sync path
    only) enables the MoE margin fast path: when consecutive ticks drift
    only the drift-class coefficients, the previous tick's decomposition
    bounds are slackened host-side (``margin_bounds_from_state``) and the
    on-device bound evaluation is skipped. The dict's ``"used"`` key
    reports whether the path engaged; clear ``"m_y"`` (the anchor profile)
    to force a full evaluation (done by StreamingReplanner when a margin
    tick misses its certificate).

    ``ipm_iters`` / ``beam`` / ``node_cap`` default by problem class (see
    ``default_search_params``); ``max_rounds`` caps the B&B rounds. All four
    are reachable from the public API (``halda_solve``).

    Returns ``(per_k_results, best)``: one entry per (k, W) pair carrying that
    k's best found incumbent objective (reporting-only — ``w``/``n`` are
    ``None`` for non-winning k's, see ``ILPResult``), and the global optimum
    with its integer assignment and the mip-gap certificate (``certified`` /
    ``gap``). Ks whose subproblem is structurally infeasible (W < M: fewer
    layers per segment than devices) come back as None.
    """
    if coeffs is None:
        raise ValueError("solve_sweep_jax requires the HaldaCoeffs used for assembly")
    M = arrays.layout.M

    feasible = [(k, W) for (k, W) in kWs if W >= M]
    results: List[Optional[ILPResult]] = [None] * len(kWs)
    if not feasible:
        return results, None

    sf = build_standard_form(arrays, coeffs, feasible)
    n_k = len(sf.ks)
    (
        cap, beam, ipm_iters, ipm_warm_iters, max_rounds, engine,
        mesh_shards, pdhg_dtype,
    ) = _resolve_search_params(
        sf.moe, n_k, node_cap, beam, ipm_iters, max_rounds,
        per_k=per_k_optima, ipm_warm_iters=ipm_warm_iters,
        lp_backend=lp_backend, pdhg_iters=pdhg_iters, M=M,
        mesh_shards=mesh_shards, pdhg_dtype=pdhg_dtype,
    )
    restart_tol = (
        DEFAULT_RESTART_TOL if pdhg_restart_tol is None else pdhg_restart_tol
    )
    if timings is not None:
        timings["lp_backend"] = engine
        timings["mesh_shards"] = mesh_shards
    diag = convergence is not None
    if diag:
        # One solve, one report: an escalated retry re-fills from scratch.
        convergence.clear()
    warm_tuple, duals_tuple, root_warm_tuple = _warm_and_duals(
        sf, arrays, warm, feasible
    )

    # Root decomposition bounds are what certify wide-expert MoE instances
    # (the LP root gap there is structural); dense sweeps certify from the
    # IPM bounds alone, so they skip the extra program — with w_max/e_max
    # zeroed so the unused statics don't key extra jit cache entries. A warm
    # tick that carries the previous multipliers only needs a short polish
    # ascent (the bound is valid at any multiplier vector), which is what
    # makes streaming MoE re-placement real-time.
    if sf.moe:
        w_max = max(W for _, W in feasible)
        e_max = int(arrays.moe.E)
        # Zero-step (warm) mode needs BOTH: the stored duals to evaluate
        # the bound at, and a warm incumbent to seed the search — steps=0
        # also skips the Lagrangian primal repair, so a duals-without-hint
        # call (e.g. a k-grid change that invalidates the hint but not the
        # multiplier shapes) must pay the full ascent or it would start
        # with no incumbent at all.
        decomp_steps = (
            DECOMP_STEPS_WARM
            if duals_tuple is not None and warm_tuple is not None
            else DECOMP_STEPS_COLD
        )
    else:
        w_max = e_max = decomp_steps = 0

    # One dispatch, one fetch, and at most one SMALL upload — transfer
    # bytes, not FLOPs, are what a remote-tunnel TPU bills for. The static
    # half (A, c-structural, boxes, slack minima — the bulk of the wire
    # time) lives on-device behind a content-addressed cache; re-solves of
    # the same fleet shape ship only the per-tick dynamic blob.
    import time as _time

    t0 = _time.perf_counter()
    rd_np = _rounding_arrays_np(coeffs, arrays.moe)
    # Margin fast path: when the caller threads a margin_state dict across
    # streaming ticks and the drift stayed inside the reusable class, the
    # previous tick's decomp bounds (slackened host-side, microseconds)
    # replace the on-device bound evaluation entirely.
    margin_np = None
    if (
        margin_state is not None
        and sf.moe
        and warm_tuple is not None
        and duals_tuple is not None
        and not per_k_optima
    ):
        margin_np = margin_bounds_from_state(
            margin_state, rd_np, sf, duals_tuple
        )
    has_margin = margin_np is not None
    static_np = _pack_static(sf)
    dyn_np = _pack_dynamic(
        sf, rd_np, mip_gap, warm_tuple, duals=duals_tuple, margin=margin_np,
        root_warm=root_warm_tuple,
    )
    t1 = _time.perf_counter()
    static_dev, static_uploaded = _static_to_device(static_np)
    dyn = jnp.asarray(dyn_np)
    if timings is not None or debug:
        # Splitting upload from solve+fetch needs a sync the async dispatch
        # would otherwise overlap — only pay it when someone asked.
        if static_uploaded:
            static_dev.block_until_ready()
        dyn.block_until_ready()
    t2 = _time.perf_counter()
    out_dev = _solve_packed(
        static_dev,
        dyn,
        M=M,
        n_k=n_k,
        m=sf.A.shape[1],
        nf=sf.A.shape[2],
        cap=cap,
        ipm_iters=ipm_iters,
        max_rounds=max_rounds,
        beam=beam,
        moe=sf.moe,
        has_warm=warm_tuple is not None,
        w_max=w_max,
        e_max=e_max,
        decomp_steps=decomp_steps,
        has_duals=duals_tuple is not None,
        per_k=per_k_optima,
        has_margin=has_margin,
        ipm_warm_iters=ipm_warm_iters,
        has_root_warm=root_warm_tuple is not None,
        lp_backend=engine,
        pdhg_restart_tol=restart_tol,
        mesh_shards=mesh_shards,
        pdhg_dtype=pdhg_dtype,
        diag=diag,
    )
    n_rows_root = (
        _root_trace_rows(engine, ipm_iters, root_warm_tuple is not None)
        if diag
        else 0
    )
    diag_len = (
        max_rounds * RL_COLS + n_k * n_rows_root * TRACE_COLS if diag else 0
    )
    pending = PendingSweep(
        out=out_dev,
        results=results,
        feasible=feasible,
        kWs=list(kWs),
        M=M,
        n_k=n_k,
        moe=sf.moe,
        w_max=w_max,
        mip_gap=mip_gap,
        debug=debug,
        per_k=per_k_optima,
        nf=sf.A.shape[2],
        m=sf.A.shape[1],
        stats=timings,
        margin_ctx=(
            (
                margin_state, has_margin, rd_np,
                np.asarray(sf.ks, np.float64),
                np.asarray(sf.Ws, np.float64),
            )
            if margin_state is not None and sf.moe
            else None
        ),
        diag_len=diag_len,
        conv_ctx=(
            {
                "dict": convergence,
                "rounds": max_rounds,
                "rows": n_rows_root,
                "engine": engine,
                "mip_gap": mip_gap,
                "ks": [k for k, _ in feasible],
            }
            if diag
            else None
        ),
    )
    if collect is False:
        # Async mode: the device is (or will be) computing; the caller
        # overlaps its own work and calls collect_sweep later. jax's async
        # dispatch means no host thread blocks here; the margin-chain
        # refresh rides the eventual collect_sweep.
        return pending

    results, best = collect_sweep(pending)
    t3 = _time.perf_counter()
    if timings is not None or debug:
        tm = {
            "pack_ms": (t1 - t0) * 1e3,
            "upload_ms": (t2 - t1) * 1e3,
            "solve_ms": (t3 - t2) * 1e3,
            "static_hit": 0.0 if static_uploaded else 1.0,
        }
        if timings is not None:
            timings.update(tm)
        if debug:
            print(
                f"    [jax] pack={tm['pack_ms']:.2f}ms "
                f"upload={tm['upload_ms']:.2f}ms solve+fetch={tm['solve_ms']:.2f}ms "
                f"static={'hit' if not static_uploaded else 'uploaded'}"
            )
    return results, best


class PendingSweep(NamedTuple):
    """An in-flight sweep: the un-fetched device result + decode context.

    Produced by ``solve_sweep_jax(collect=False)``; redeemed by
    ``collect_sweep``. The device program is already dispatched — holding a
    PendingSweep costs nothing and lets the host overlap the next tick's
    coefficient build and upload with this solve's execution and result
    transfer (on a tunneled TPU the transfer IS the latency floor, so the
    overlap is what pushes streaming throughput past 1/RTT).
    """

    out: jax.Array
    results: List[Optional[ILPResult]]
    feasible: List[Tuple[int, int]]
    kWs: List[Tuple[int, int]]
    M: int
    n_k: int
    moe: bool
    w_max: int
    mip_gap: float
    debug: bool
    per_k: bool = False
    # (margin_state, has_margin, rd_np, ks, Ws) when the caller threads a
    # margin chain — the anchor refresh happens at COLLECT time (it needs
    # the fetched y-profile tail), which is what lets pipelined
    # submit/collect ticks ride the margin fast path too.
    margin_ctx: Optional[tuple] = None
    # LP family shape (root-iterate block decode) and an optional dict that
    # receives the solve's device-side stats (ipm_iters_executed, rounds).
    nf: int = 0
    m: int = 0
    stats: Optional[dict] = None
    # Convergence-diagnostics context (`diag` runs only): diag_len floats
    # of round log + root LP trace sit between the root-iterate block and
    # the m_y tail; conv_ctx carries the decode shapes and the caller's
    # convergence dict to fill at collect time.
    diag_len: int = 0
    conv_ctx: Optional[dict] = None


def _pre_diag_len(
    M: int, n_k: int, moe: bool, w_max: int, per_k: bool, nf: int, m: int,
) -> int:
    """Output length UP TO the diagnostics tail: header + incumbent vectors
    + per-k bests + (optional) duals block + (optional) per-k block + the
    root-iterate block. The diag tail (round log + root LP trace) starts
    here; the m_y margin anchor, when present, stays last."""
    n = 6 + 3 * M + n_k
    if moe and w_max > 0:
        n += 3 * n_k + n_k * M  # lam, mu, tau, root_bounds
    if per_k:
        n += 3 * n_k * M + n_k  # per_k_w/n/y, per_k_bound
    n += n_k * (1 + 3 * nf + m)  # root-iterate block (ok, v, y, z, f)
    return n


def _expected_out_len(
    M: int, n_k: int, moe: bool, w_max: int, per_k: bool,
    has_margin: bool, Yn: int, nf: int, m: int, diag_len: int = 0,
) -> int:
    """Total ``_solve_packed`` output length implied by the static flags.

    Mirrors the pack order at the end of ``_solve_packed_impl``: header +
    incumbent vectors + per-k bests, then (when the decomposition context
    exists) the duals block, then the per-k assignment block, then the
    root-iterate block, then the ``diag_len``-float diagnostics tail
    (round log + root LP trace, ``diag`` runs only), then — LAST, and only
    on full-evaluation ticks — the margin anchor's y-profile. The input
    side has the off64 layout-drift assert; this is its output twin,
    guarding the negative tail slice the margin anchor is read with.
    """
    n = _pre_diag_len(M, n_k, moe, w_max, per_k, nf, m) + diag_len
    if moe and w_max > 0 and not has_margin:
        n += n_k * M * Yn  # m_y anchor profile
    return n


def collect_sweep(
    pending: PendingSweep,
) -> Tuple[List[Optional[ILPResult]], Optional[ILPResult]]:
    """Fetch + decode an in-flight sweep (the blocking half of the async
    split). Same output contract as ``solve_sweep_jax``."""
    out = np.asarray(jax.device_get(pending.out))
    results, best = _decode_sweep_out(
        out, pending.results, pending.feasible, pending.kWs, pending.M,
        pending.n_k, pending.moe, pending.w_max, pending.mip_gap,
        pending.debug, per_k=pending.per_k, nf=pending.nf, m=pending.m,
        stats=pending.stats,
    )
    if pending.conv_ctx is not None:
        _decode_convergence(out, pending)
    if pending.margin_ctx is not None:
        margin_state, has_margin, rd_np, ks_arr, Ws_arr = pending.margin_ctx
        # Tail reads below depend on 'm_y appended LAST'; verify the whole
        # layout from the static flags before trusting a negative slice
        # (margin-tick certificates depend on the anchor being exact).
        Yn = int(np.asarray(rd_np["E"])) + 1
        expected = _expected_out_len(
            pending.M, pending.n_k, pending.moe, pending.w_max,
            pending.per_k, has_margin, Yn, pending.nf, pending.m,
            diag_len=pending.diag_len,
        )
        if out.shape[0] != expected:
            # Explicit raise (not `assert`) so the guard survives
            # `python -O` — same rationale as the has_margin invariant in
            # _solve_packed_impl; a mis-aligned tail silently corrupts
            # the margin anchor and every certificate derived from it.
            raise AssertionError(
                f"_solve_packed/collect_sweep output layout drift: got "
                f"{out.shape[0]} values, static flags imply {expected}"
            )
        margin_state["used"] = has_margin
        if has_margin:
            # Margin tick: the stored full-eval anchor stays FIXED — every
            # margin tick re-derives its bounds from that anchor under the
            # cumulative drift (exact in the linear channels), so the
            # chain does not decay tick over tick.
            pass
        elif (
            best is not None
            and best.duals is not None
            and "root_bounds" in best.duals
        ):
            # Full evaluation: refresh the anchor — rd vectors, duals, and
            # the per-device y-profile read from the output tail.
            m_y_flat = out[-pending.n_k * pending.M * Yn:]
            margin_state.update(
                rd=rd_np,
                ks=ks_arr,
                Ws=Ws_arr,
                m_y=m_y_flat.reshape(pending.n_k, pending.M, Yn),
                duals=tuple(
                    np.asarray(best.duals[f], np.float64)
                    for f in ("lam", "mu", "tau")
                ),
            )
        else:
            margin_state.pop("m_y", None)
            margin_state.pop("duals", None)
    return results, best


def _decode_convergence(out: np.ndarray, pending: PendingSweep) -> None:
    """Decode the diagnostics tail (round log + root LP trace) into the
    caller's convergence dict and put the digest keys into ``stats``.

    The dict carries PLAIN nested lists, not arrays — ``obs.convergence``
    (the pydantic report layer) stays importable without numpy or jax.
    """
    cc = pending.conv_ctx
    n_k = pending.n_k
    rounds, rows = cc["rounds"], cc["rows"]
    pre = _pre_diag_len(
        pending.M, n_k, pending.moe, pending.w_max, pending.per_k,
        pending.nf, pending.m,
    )
    need = pre + rounds * RL_COLS + n_k * rows * TRACE_COLS
    if out.shape[0] < need:
        # Explicit raise (not assert) for the same -O reason as the other
        # layout guards: a short tail means the pack and this decode
        # disagree about the diag layout, and a silent mis-slice would
        # fabricate a convergence report.
        raise AssertionError(
            f"_solve_packed diagnostics tail layout drift: need {need} "
            f"values, got {out.shape[0]}"
        )
    rl = out[pre : pre + rounds * RL_COLS].reshape(rounds, RL_COLS)
    rt0 = pre + rounds * RL_COLS
    rtr = out[rt0 : rt0 + n_k * rows * TRACE_COLS].reshape(
        n_k, rows, TRACE_COLS
    )
    conv = cc["dict"]
    conv.update(
        lp_backend=cc["engine"],
        mip_gap=float(cc["mip_gap"]),
        ks=list(cc["ks"]),
        incumbent=float(out[0]),
        best_bound=float(out[1]),
        ipm_iters_executed=float(out[4]),
        bnb_rounds=float(out[5]),
        # Executed rounds only, each prefixed with its round index (row 0
        # is the root round; holes are legal — a settled warm tick skips
        # the root but the while loop may still run).
        round_log=[
            [int(i)] + [float(v) for v in rl[i, : RL_COLS - 1]]
            for i in range(rounds)
            if rl[i, RL_COLS - 1] > 0.5
        ],
        root_trace=[[list(map(float, r)) for r in el] for el in rtr],
    )
    if pending.stats is not None:
        from ..obs.convergence import build_search_trace

        pending.stats.update(build_search_trace(conv).digest())


def _decode_sweep_out(
    out: np.ndarray,
    results: List[Optional[ILPResult]],
    feasible: List[Tuple[int, int]],
    kWs: List[Tuple[int, int]],
    M: int,
    n_k: int,
    moe: bool,
    w_max: int,
    mip_gap: float,
    debug: bool,
    per_k: bool = False,
    nf: int = 0,
    m: int = 0,
    stats: Optional[dict] = None,
) -> Tuple[List[Optional[ILPResult]], Optional[ILPResult]]:
    """Decode one fetched ``_solve_packed`` output vector (shared by the
    single-dispatch, async, and scenario-batched paths). ``stats`` (when a
    dict is passed) receives the device program's execution counters:
    ``ipm_iters_executed`` (useful Mehrotra iterations summed over every
    element of every round) and ``bnb_rounds``."""
    incumbent = float(out[0])
    best_bound = float(out[1])
    if stats is not None:
        stats["ipm_iters_executed"] = float(out[4])
        stats["bnb_rounds"] = float(out[5])
    if debug:
        print(
            f"    [jax] incumbent={incumbent:.6f} bound={best_bound:.6f} "
            f"ipm_iters={out[4]:.0f} rounds={out[5]:.0f}"
        )
    if not np.isfinite(incumbent):
        if per_k:
            # No k found an incumbent. Distinguish budget starvation
            # (some bound still below +inf: subtrees remain) from proven
            # infeasibility (every subtree exhausted) — silence here would
            # make max_rounds=small look like "infeasible for every k".
            p0 = 6 + 3 * M + n_k
            if moe and w_max > 0:
                p0 += 3 * n_k + n_k * M  # lam, mu, tau, root_bounds
            pk_bound0 = out[p0 + 3 * n_k * M : p0 + 3 * n_k * M + n_k]
            if not np.all(np.isposinf(pk_bound0)):
                import warnings

                warnings.warn(
                    "HALDA per-k sweep: NO k found an incumbent within the "
                    "round budget (all entries omitted — budget starvation, "
                    "not proven infeasibility); raise max_rounds.",
                    RuntimeWarning,
                    stacklevel=2,
                )
        return results, None
    achieved_gap = (
        (incumbent - best_bound) / abs(incumbent) if incumbent != 0.0
        else incumbent - best_bound
    )
    achieved_gap = max(0.0, achieved_gap)
    certified = incumbent - best_bound <= mip_gap * abs(incumbent) + 1e-12
    if not certified:
        # Search exhausted max_rounds (or overflowed the frontier) without
        # closing the gap; the incumbent is still the best found integer
        # point, but the certificate failed — say so instead of implying it.
        import warnings

        warnings.warn(
            f"HALDA jax backend: mip-gap certificate NOT met "
            f"(incumbent={incumbent:.6g}, bound={best_bound:.6g}, achieved "
            f"gap={achieved_gap:.3g}, requested {mip_gap:g}); raise "
            f"halda_solve(max_rounds=..., node_cap=...) or relax mip_gap. "
            f"The result carries certified=False and the achieved gap.",
            RuntimeWarning,
            stacklevel=2,
        )

    inc_k_idx = int(out[2])
    inc_w = [int(round(x)) for x in out[6 : 6 + M]]
    inc_n = [int(round(x)) for x in out[6 + M : 6 + 2 * M]]
    inc_y = [int(round(x)) for x in out[6 + 2 * M : 6 + 3 * M]]
    per_k_best = out[6 + 3 * M : 6 + 3 * M + n_k]

    # Root multipliers chosen by this solve (MoE only): persist on the
    # winning result so the next streaming tick warm-starts the ascent.
    out_duals = None
    if moe and w_max > 0:
        d0 = 6 + 3 * M + n_k
        lam_out = out[d0 : d0 + n_k]
        mu_out = out[d0 + n_k : d0 + 2 * n_k]
        tau_out = out[d0 + 2 * n_k : d0 + 2 * n_k + n_k * M].reshape(n_k, M)
        rb0 = d0 + 2 * n_k + n_k * M
        # Raw (pre-obj_const) per-k decomp bounds: persisted so the next
        # streaming tick can reuse them through the margin fast path.
        root_bounds_out = out[rb0 : rb0 + n_k]
        out_duals = {
            "lam": lam_out.tolist(),
            "mu": mu_out.tolist(),
            "tau": tau_out.tolist(),
            "root_bounds": root_bounds_out.tolist(),
        }

    # Per-k mode: the tail carries full per-k assignments + per-k bounds,
    # right after the (optional) duals block.
    pk_w = pk_n = pk_y = pk_bound = None
    p0 = 6 + 3 * M + n_k
    if moe and w_max > 0:
        p0 += 3 * n_k + n_k * M  # duals block incl. root_bounds
    if per_k:
        pk_w = out[p0 : p0 + n_k * M].reshape(n_k, M)
        pk_n = out[p0 + n_k * M : p0 + 2 * n_k * M].reshape(n_k, M)
        pk_y = out[p0 + 2 * n_k * M : p0 + 3 * n_k * M].reshape(n_k, M)
        pk_bound = out[p0 + 3 * n_k * M : p0 + 3 * n_k * M + n_k]
        p0 += 3 * n_k * M + n_k

    # Root-round IPM iterates (always emitted, right after the per-k
    # block): persisted on the winning result so the next streaming tick's
    # root round starts from them (f32 on the wire; f64 here is just the
    # output vector's dtype).
    out_ipm_state = None
    if nf and m:
        r_ok = out[p0 : p0 + n_k] > 0.5
        r_v = out[p0 + n_k : p0 + n_k + n_k * nf].reshape(n_k, nf)
        ry0 = p0 + n_k + n_k * nf
        r_y = out[ry0 : ry0 + n_k * m].reshape(n_k, m)
        rz0 = ry0 + n_k * m
        r_z = out[rz0 : rz0 + n_k * nf].reshape(n_k, nf)
        rf0 = rz0 + n_k * nf
        r_f = out[rf0 : rf0 + n_k * nf].reshape(n_k, nf)
        if np.any(r_ok):
            out_ipm_state = {
                "ok": r_ok, "v": r_v, "y": r_y, "z": r_z, "f": r_f,
            }

    best: Optional[ILPResult] = None
    pos_of = {kW: i for i, kW in enumerate(kWs)}
    for j, (k, W) in enumerate(feasible):
        obj_j = float(per_k_best[j])
        if not np.isfinite(obj_j):
            continue
        if per_k:
            # Full certified entry for EVERY k (per-k pruning regime).
            # bound == +inf means every node was exhausted or pruned at the
            # mip-gap threshold — certified, but the surviving guarantee is
            # <= mip_gap, so report THAT, not a fabricated 0.0 (threshold
            # pruning kills nodes whose subtree can improve by up to
            # mip_gap*|incumbent|). bound == -inf means the subtree was
            # never explored (round budget ran out first) — no certificate.
            bound_j = float(pk_bound[j])
            if np.isposinf(bound_j):
                cert_j, gap_j = True, mip_gap
            elif not np.isfinite(bound_j):
                cert_j, gap_j = False, None
            else:
                gap_j = (
                    max(0.0, (obj_j - bound_j) / abs(obj_j))
                    if obj_j != 0.0
                    else max(0.0, obj_j - bound_j)
                )
                cert_j = obj_j - bound_j <= mip_gap * abs(obj_j) + 1e-12
            entry = ILPResult(
                k=k,
                w=[int(round(x)) for x in pk_w[j]],
                n=[int(round(x)) for x in pk_n[j]],
                y=[int(round(x)) for x in pk_y[j]] if moe else None,
                obj_value=obj_j,
                certified=cert_j,
                gap=gap_j,
                duals=out_duals if j == inc_k_idx else None,
                ipm_state=out_ipm_state if j == inc_k_idx else None,
            )
            results[pos_of[(k, W)]] = entry
            if j == inc_k_idx:
                best = entry
        elif j == inc_k_idx:
            y = inc_y if moe else None
            best = ILPResult(
                k=k, w=inc_w, n=inc_n, y=y, obj_value=obj_j,
                certified=certified, gap=achieved_gap, duals=out_duals,
                ipm_state=out_ipm_state,
            )
            results[pos_of[(k, W)]] = best
        else:
            # Reporting-only entry: the k didn't win; re-deriving its exact
            # integer vector would cost another solve, so carry the objective
            # with the assignment explicitly absent (w=n=None, uncertified).
            results[pos_of[(k, W)]] = ILPResult(
                k=k, obj_value=obj_j, certified=False
            )

    if per_k:
        # The global warning above only covers the winner; per-k mode
        # promises a certificate PER k, so name the ones that missed —
        # including k's the round budget never reached at all (no
        # incumbent, bound still -inf): silence there would make them
        # indistinguishable from proven-infeasible k's.
        missed = [
            r.k for r in results
            if r is not None and r.w is not None and not r.certified
        ]
        unexplored = [
            k
            for j, (k, W) in enumerate(feasible)
            if not np.isfinite(float(per_k_best[j]))
            and not np.isposinf(float(pk_bound[j]))
        ]
        if missed or unexplored:
            import warnings

            parts = []
            if missed:
                parts.append(
                    f"certificate NOT met for k={missed} (budget exhausted "
                    f"before those k's closed their own gap)"
                )
            if unexplored:
                parts.append(
                    f"k={unexplored} never explored (no incumbent found "
                    f"before the round budget ran out; these are OMITTED, "
                    f"not infeasible)"
                )
            warnings.warn(
                f"HALDA per-k sweep: {'; '.join(parts)}; raise max_rounds.",
                RuntimeWarning,
                stacklevel=2,
            )
    return results, best


def solve_sweep_scenarios(
    arrays_list: Sequence[MilpArrays],
    kWs: Sequence[Tuple[int, int]],
    coeffs_list: Sequence[HaldaCoeffs],
    mip_gap: float = 1e-4,
    warms: Optional[Sequence[Optional[ILPResult]]] = None,
    ipm_iters: Optional[int] = None,
    max_rounds: Optional[int] = None,
    beam: Optional[int] = None,
    node_cap: Optional[int] = None,
    timings: Optional[dict] = None,
    ipm_warm_iters: Optional[int] = None,
    lp_backend: Optional[str] = None,
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
    mesh_shards: Optional[int] = None,
    pdhg_dtype: Optional[str] = None,
) -> List[Tuple[List[Optional[ILPResult]], Optional[ILPResult]]]:
    """Solve S what-if scenarios of ONE fleet in a single device dispatch.

    Scenarios are profile-drift variants of the same instance — candidate
    t_comm futures, load redistributions, busy-constant shifts — exactly
    the variation class whose packed STATIC half (base A, structural
    objective, boxes, slack minima) is byte-identical. The S dynamic blobs
    stack into one upload, ``_solve_scenarios_packed`` vmaps the fused
    B&B program over them, and one fetch returns every placement: on a
    tunneled TPU, where each operation bills a fixed wire cost, this prices
    S placements at ~one placement's wire time (a host MILP loop would
    serialize S full solves).

    Scenarios whose static half DIFFERS (device speed/memory/topology
    changes — anything touching A or the boxes) raise ValueError: solve
    those as separate ``solve_sweep_jax`` calls.

    ``warms`` (optional, one entry per scenario) seeds each scenario's
    incumbent independently; warm hints and MoE duals engage only when
    EVERY scenario carries a usable one (the static jit layout is shared),
    else all run cold.

    Returns one ``(per_k_results, best)`` pair per scenario, same contract
    as ``solve_sweep_jax``.
    """
    S = len(arrays_list)
    if S == 0:
        return []
    if len(coeffs_list) != S or (warms is not None and len(warms) != S):
        raise ValueError("arrays_list/coeffs_list/warms lengths must match")
    M = arrays_list[0].layout.M

    feasible = [(k, W) for (k, W) in kWs if W >= M]
    if not feasible:
        return [([None] * len(kWs), None) for _ in range(S)]

    sfs = [
        build_standard_form(a, c, feasible)
        for a, c in zip(arrays_list, coeffs_list)
    ]
    static0 = _pack_static(sfs[0])
    for i, sf_i in enumerate(sfs[1:], start=1):
        if not np.array_equal(_pack_static(sf_i), static0):
            raise ValueError(
                f"scenario {i}'s packed static half differs from scenario "
                f"0's, so they cannot share one batched dispatch. Causes: "
                f"device speed/memory/fleet/model changes (out-of-class "
                f"drift), or a t_comm/load excursion large enough to move "
                f"a row's scaling (rare: the drifting RHS entries are "
                f"normally well under the row's |C|=1 coefficient). Solve "
                f"the scenarios as separate sweeps instead"
            )

    sf = sfs[0]
    n_k = len(sf.ks)
    (
        cap, beam, ipm_iters, ipm_warm_iters, max_rounds, engine,
        _shards, pdhg_dtype,
    ) = _resolve_search_params(
        sf.moe, n_k, node_cap, beam, ipm_iters, max_rounds,
        ipm_warm_iters=ipm_warm_iters,
        lp_backend=lp_backend, pdhg_iters=pdhg_iters, M=M,
        mesh_shards=mesh_shards, pdhg_dtype=pdhg_dtype,
    )
    restart_tol = (
        DEFAULT_RESTART_TOL if pdhg_restart_tol is None else pdhg_restart_tol
    )
    if timings is not None:
        timings["lp_backend"] = engine

    pairs = [
        _warm_and_duals(
            sf_i, a_i, warms[i] if warms is not None else None, feasible
        )
        for i, (sf_i, a_i) in enumerate(zip(sfs, arrays_list))
    ]
    # The jit layout (has_warm/has_duals/has_root_warm statics) is shared
    # across the vmap axis: engage each slot only when every scenario can
    # fill it.
    use_warm = all(w is not None for w, _, _ in pairs)
    use_duals = all(d is not None for _, d, _ in pairs)
    use_root_warm = all(r is not None for _, _, r in pairs)
    if sf.moe:
        w_max = max(W for _, W in feasible)
        e_max = int(arrays_list[0].moe.E)
        # Same both-or-cold rule as the single-dispatch path: steps=0 skips
        # the primal repair, which is only sound with a warm incumbent.
        decomp_steps = (
            DECOMP_STEPS_WARM if use_duals and use_warm else DECOMP_STEPS_COLD
        )
    else:
        w_max = e_max = decomp_steps = 0

    import time as _time

    t0 = _time.perf_counter()
    dyn_stack = np.stack(
        [
            _pack_dynamic(
                sf_i,
                _rounding_arrays_np(c_i, a_i.moe),
                mip_gap,
                pairs[i][0] if use_warm else None,
                duals=pairs[i][1] if use_duals else None,
                root_warm=pairs[i][2] if use_root_warm else None,
            )
            for i, (sf_i, a_i, c_i) in enumerate(
                zip(sfs, arrays_list, coeffs_list)
            )
        ]
    )
    t1 = _time.perf_counter()
    static_dev, static_uploaded = _static_to_device(static0)
    dyn = jnp.asarray(dyn_stack)
    if timings is not None:
        if static_uploaded:
            static_dev.block_until_ready()
        dyn.block_until_ready()
    t2 = _time.perf_counter()
    out_dev = _solve_scenarios_packed(
        static_dev,
        dyn,
        M=M,
        n_k=n_k,
        m=sf.A.shape[1],
        nf=sf.A.shape[2],
        cap=cap,
        ipm_iters=ipm_iters,
        max_rounds=max_rounds,
        beam=beam,
        moe=sf.moe,
        has_warm=use_warm,
        w_max=w_max,
        e_max=e_max,
        decomp_steps=decomp_steps,
        has_duals=use_duals,
        ipm_warm_iters=ipm_warm_iters,
        has_root_warm=use_root_warm,
        lp_backend=engine,
        pdhg_restart_tol=restart_tol,
        pdhg_dtype=pdhg_dtype,
    )
    out_np = np.asarray(jax.device_get(out_dev))
    t3 = _time.perf_counter()
    if timings is not None:
        timings.update(
            {
                "pack_ms": (t1 - t0) * 1e3,
                "upload_ms": (t2 - t1) * 1e3,
                "solve_ms": (t3 - t2) * 1e3,
                "static_hit": 0.0 if static_uploaded else 1.0,
                "scenarios": float(S),
            }
        )

    return [
        _decode_sweep_out(
            out_np[i], [None] * len(kWs), feasible, list(kWs), M, n_k,
            sf.moe, w_max, mip_gap, False,
            nf=sf.A.shape[2], m=sf.A.shape[1],
        )
        for i in range(S)
    ]

"""HALDA coefficient model, vectorized as struct-of-arrays.

Turns ``(devices, model, kv_factor)`` into the dense numeric ingredients of the
per-k MILP: per-device latency coefficients, memory caps, disk penalties and
the additive constants. All downstream backends (scipy CPU oracle, JAX IPM +
branch-and-bound) consume the same :class:`HaldaCoeffs`, so numeric parity with
the reference lives in exactly one place.

Numeric parity targets (verified by golden-objective tests):
- resident-bytes model   /root/reference/src/distilp/solver/components/dense_common.py:25-46
- latency coefficients   dense_common.py:49-126
- device-set partition   dense_common.py:129-167
- objective vectors / κ  dense_common.py:170-230

Everything here is host-side numpy: the arrays are tiny (O(M)) and are
``device_put`` once by the JAX backend; the hot loops live on the accelerator.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..common import DeviceProfile, ModelProfile, QuantizationLevel, ThroughputTable

# Weight-residency overhead and KV-cache per-group metadata defaults.
# (rho_w ~ runtime overhead on weights; kv_group=64 -> +2 bytes scale per group.)
RHO_W = 0.15
KV_GROUP = 64


def valid_factors_of_L(L: int) -> List[int]:
    """All positive factors of L except L itself — the candidate segment counts k."""
    fs = set()
    for k in range(1, int(math.isqrt(L)) + 1):
        if L % k == 0:
            fs.add(k)
            fs.add(L // k)
    fs.discard(L)
    return sorted(fs)


def b_prime(
    model: ModelProfile,
    kv_bits_k: float = 1.0,
    kv_bits_v: Optional[float] = None,
    *,
    rho_w: float = RHO_W,
    kv_group: int = KV_GROUP,
) -> int:
    """Resident bytes of one layer: weights (with runtime overhead) + KV cache.

        b' = (1+rho_w)·b_layer + (1 + 2/kv_group)·(h_k·e_k·kv_k + h_v·e_v·kv_v)·n_kv

    kv_bits_* are bytes/element (0.5 = 4-bit, 1.0 = 8-bit, 2.0 = fp16/bf16).
    """
    if kv_bits_v is None:
        kv_bits_v = kv_bits_k
    kv_elems_k = model.hk * model.ek * model.n_kv
    kv_elems_v = model.hv * model.ev * model.n_kv
    kv_nominal = kv_bits_k * kv_elems_k + kv_bits_v * kv_elems_v
    group_scale = 1.0 + 2.0 / float(max(1, kv_group))
    weights = (1.0 + float(rho_w)) * float(model.b_layer)
    return int(weights + group_scale * kv_nominal)


def flops_over_flops_per_s(
    f_by_batch: Dict[str, float],
    table: Optional[ThroughputTable],
    q: QuantizationLevel,
    batch_size: int = 1,
) -> float:
    """Seconds of compute: f_q / s_q at one batch size.

    Missing quant level or missing f entry yields 0.0 (device can't be charged
    for work it has no table for); a table that has the level but not the
    batch column is a malformed profile and raises.
    """
    batch_key = f"b_{batch_size}"
    if table is None or batch_key not in f_by_batch or q not in table:
        return 0.0
    level = table[q]
    if batch_key not in level:
        raise ValueError(f"Batch column {batch_key!r} missing from throughput table for {q}")
    s = level[batch_key]
    if s <= 0:
        return 0.0
    return f_by_batch[batch_key] / s


def alpha_beta_xi(
    dev: DeviceProfile, model: ModelProfile, kv_factor: float = 1.0,
    batch_size: int = 1,
) -> tuple[float, float, float]:
    """Per-layer latency coefficients for one device.

    alpha = CPU seconds/layer: compute + KV copy + register loads.
    beta  = accelerator minus CPU delta (negative when the GPU is faster); 0
            without an accelerator table.
    xi    = host<->accelerator round-trip, charged only on split-memory devices.

    ``batch_size`` selects the ``b_N`` column of both the model's FLOPs
    tables and the device's throughput tables (default 1 — reference
    parity, which hard-wires ``b_1``; SURVEY §8 quirk 10).
    """
    bprime = b_prime(model, kv_bits_k=kv_factor)
    comp_cpu = flops_over_flops_per_s(
        model.f_q, dev.scpu, model.Q, batch_size=batch_size
    )
    alpha = comp_cpu + dev.t_kvcpy_cpu + bprime / dev.T_cpu

    gpu_table = dev.gpu_table()
    gpu_T = dev.gpu_T()
    if gpu_table is not None and gpu_T is not None:
        comp_gpu = flops_over_flops_per_s(
            model.f_q, gpu_table, model.Q, batch_size=batch_size
        )
        beta = (
            (comp_gpu - comp_cpu)
            + (dev.t_kvcpy_gpu - dev.t_kvcpy_cpu)
            + (bprime / gpu_T - bprime / dev.T_cpu)
        )
    else:
        beta = 0.0

    xi = (dev.t_ram2vram + dev.t_vram2ram) * (0.0 if dev.is_unified_mem else 1.0)
    return alpha, beta, xi


def b_cio(dev: DeviceProfile, model: ModelProfile) -> float:
    """Non-layer resident bytes: head's input/output layers + CPU scratch."""
    head = 1.0 if dev.is_head else 0.0
    return (model.b_in / model.V + model.b_out) * head + dev.c_cpu


def classify_device(dev: DeviceProfile) -> int:
    """Memory-pressure case 1..3 by OS/backend.

    1: macOS without Metal (weights stream through RAM only)
    2: macOS with Metal (unified memory budget)
    3: everything else (Linux/Android/TPU hosts: RAM + optional swap)
    A "case 4 / fits in RAM" set exists in the paper but is never produced by
    the reference partitioner; we match that behavior.
    """
    if dev.os_type == "mac_no_metal":
        return 1
    if dev.os_type == "mac_metal":
        return 2
    return 3


def assign_sets(devs: Sequence[DeviceProfile]) -> Dict[str, List[int]]:
    """Partition device indices into the M1/M2/M3 cases."""
    sets: Dict[str, List[int]] = {"M1": [], "M2": [], "M3": []}
    for i, d in enumerate(devs):
        sets[f"M{classify_device(d)}"].append(i)
    return sets


def _swap_bytes(dev: DeviceProfile) -> int:
    """Swap headroom counted toward RAM capacity (Android only)."""
    if dev.os_type == "android":
        return min(dev.d_bytes_can_swap, dev.d_swap_avail)
    return 0


@dataclass
class HaldaCoeffs:
    """Everything the per-k MILP needs, as dense per-device arrays.

    k enters only through W = L/k: the Σw equality RHS and the [1, W] /
    [0, W] variable bounds. All arrays below are k-independent, which is what
    makes the k-sweep a pure vmap on the JAX backend.
    """

    M: int
    L: int
    bprime: float
    # Objective / busy-time coefficients (seconds per layer)
    a: np.ndarray  # CPU path sec/layer
    b_gpu: np.ndarray  # GPU-minus-CPU delta sec/layer (0 without GPU)
    xi: np.ndarray  # host<->accelerator round-trip constant
    t_comm: np.ndarray  # per-device inter-device comm seconds
    # Disk
    s_disk: np.ndarray  # clamped >= 1 byte/s for penalty math
    pen_m1: np.ndarray  # b'/s_disk
    pen_m2: np.ndarray  # b_layer/s_disk
    pen_m3: np.ndarray  # b'/s_disk
    pen_vram: np.ndarray  # set-2 devices pay pen_m2, others pen_m3
    # Set membership and accelerator structure
    set_id: np.ndarray  # 1 | 2 | 3
    has_gpu: np.ndarray  # bool: any accelerator layers allowed (n_i can be > 0)
    # Memory caps (RHS of the capacity rows)
    ram_rhs: np.ndarray  # per-device RAM/unified cap minus resident overheads
    ram_minus_n: np.ndarray  # bool: subtract b'·n_i from RAM residency (set 3)
    cuda_row: np.ndarray  # bool: CUDA VRAM row active
    cuda_rhs: np.ndarray
    metal_row: np.ndarray  # bool: Metal shared-memory row active
    metal_rhs: np.ndarray
    # Constants
    kappa: float
    sets: Dict[str, List[int]] = field(default_factory=dict)

    @property
    def busy_const(self) -> np.ndarray:
        """Per-device constant inside the busy time B_i: xi_i + t_comm_i."""
        return self.xi + self.t_comm

    @property
    def obj_const(self) -> float:
        """Additive objective constant: Σ t_comm + Σ xi + κ."""
        return float(self.t_comm.sum() + self.xi.sum() + self.kappa)


def kappa_constant(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    sets: Dict[str, List[int]],
    batch_size: int = 1,
) -> float:
    """Constant objective terms: head-device I/O-layer costs + tail RAM deficits."""
    head_idx = next((i for i, d in enumerate(devs) if d.is_head), 0)
    head = devs[head_idx]

    head_compute = flops_over_flops_per_s(
        model.f_out, head.scpu, model.Q, batch_size=batch_size
    )
    head_load_regs = (model.b_in / model.V + model.b_out) / head.T_cpu
    head_disk_in = model.b_in / (model.V * head.s_disk)
    head_disk_out = model.b_out / head.s_disk

    tail = 0.0
    for i in sets.get("M1", []) + sets.get("M3", []):
        d = devs[i]
        tail += (d.c_cpu - d.d_avail_ram - _swap_bytes(d)) / d.s_disk

    return head_compute + head_load_regs + head_disk_in + head_disk_out + tail


def build_coeffs(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    kv_factor: float,
    sets: Optional[Dict[str, List[int]]] = None,
    batch_size: int = 1,
) -> HaldaCoeffs:
    """Assemble the full coefficient struct for one (devices, model) instance.

    ``batch_size`` (opt-in, default 1 = reference parity) prices the dense
    compute at the model's and devices' ``b_N`` throughput columns, for
    prefill-heavy deployments whose real batch is not 1. The model profile
    must carry the requested column (profile with ``batch_sizes=[..., N]``).
    """
    M = len(devs)
    if batch_size != 1:
        # Validate BOTH FLOPs tables the batch column is read from: a
        # missing key silently prices that compute term at 0.0
        # (flops_over_flops_per_s), which must never happen on an
        # explicitly requested batch.
        for fname, fdict in (("f_q", model.f_q), ("f_out", model.f_out)):
            if f"b_{batch_size}" not in fdict:
                raise ValueError(
                    f"batch_size={batch_size} requested but the model "
                    f"profile's {fname} has no 'b_{batch_size}' FLOPs column "
                    f"(has: {sorted(fdict)}); re-profile the model with "
                    f"batch_sizes=[{batch_size}, ...]"
                )
    if sets is None:
        sets = assign_sets(devs)
    bprime = float(b_prime(model, kv_bits_k=kv_factor))

    a = np.zeros(M)
    b_gpu = np.zeros(M)
    xi = np.zeros(M)
    t_comm = np.zeros(M)
    s_disk = np.zeros(M)
    set_id = np.zeros(M, dtype=np.int32)
    has_gpu = np.zeros(M, dtype=bool)
    ram_rhs = np.zeros(M)
    ram_minus_n = np.zeros(M, dtype=bool)
    cuda_row = np.zeros(M, dtype=bool)
    cuda_rhs = np.zeros(M)
    metal_row = np.zeros(M, dtype=bool)
    metal_rhs = np.zeros(M)

    set_of = {}
    for name, idxs in sets.items():
        for i in idxs:
            set_of[i] = int(name[1])

    for i, d in enumerate(devs):
        alpha, beta, xi_i = alpha_beta_xi(d, model, kv_factor, batch_size)
        sid = set_of.get(i, 3)
        set_id[i] = sid
        # The set partition zeroes the GPU delta for set-1 devices (no Metal on
        # a mac without Metal) and keeps it elsewhere.
        a[i] = alpha
        b_gpu[i] = 0.0 if sid == 1 else beta
        xi[i] = xi_i
        t_comm[i] = d.t_comm
        s_disk[i] = max(1.0, float(d.s_disk))
        has_gpu[i] = d.has_gpu_backend()

        bcio_i = b_cio(d, model)
        if sid == 1:
            ram_rhs[i] = float(d.d_avail_ram) - bcio_i
        elif sid == 2:
            if d.d_avail_metal is None:
                # No usable cap row; keep it trivially inactive.
                ram_rhs[i] = np.inf
            else:
                ram_rhs[i] = float(d.d_avail_metal) - bcio_i - float(d.c_gpu)
        else:
            ram_rhs[i] = float(d.d_avail_ram + _swap_bytes(d)) - bcio_i
            ram_minus_n[i] = True

        # Discrete accelerator memory cap (CUDA in the reference; TPU HBM
        # fills the same role here — separate memory, so the same row shape).
        if d.has_tpu and d.d_avail_tpu is not None:
            cuda_row[i] = True
            cuda_rhs[i] = float(d.d_avail_tpu) - float(d.c_gpu)
        elif d.has_cuda and d.d_avail_cuda is not None:
            cuda_row[i] = True
            cuda_rhs[i] = float(d.d_avail_cuda) - float(d.c_gpu)
        if d.has_metal and d.d_avail_metal is not None:
            metal_row[i] = True
            head = 1.0 if d.is_head else 0.0
            metal_rhs[i] = (
                float(d.d_avail_metal) - float(d.c_gpu) - float(model.b_out) * head
            )

    pen_m1 = bprime / s_disk
    pen_m2 = float(model.b_layer) / s_disk
    pen_m3 = bprime / s_disk
    pen_vram = np.where(set_id == 2, pen_m2, pen_m3)

    return HaldaCoeffs(
        M=M,
        L=model.L,
        bprime=bprime,
        a=a,
        b_gpu=b_gpu,
        xi=xi,
        t_comm=t_comm,
        s_disk=s_disk,
        pen_m1=pen_m1,
        pen_m2=pen_m2,
        pen_m3=pen_m3,
        pen_vram=pen_vram,
        set_id=set_id,
        has_gpu=has_gpu,
        ram_rhs=ram_rhs,
        ram_minus_n=ram_minus_n,
        cuda_row=cuda_row,
        cuda_rhs=cuda_rhs,
        metal_row=metal_row,
        metal_rhs=metal_rhs,
        kappa=kappa_constant(devs, model, sets, batch_size),
        sets={k: list(v) for k, v in sets.items()},
    )

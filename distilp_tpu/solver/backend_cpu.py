"""CPU oracle backend: the assembled MILP handed to scipy.optimize.milp (HiGHS).

This is the conformance reference for the JAX backend — same
:mod:`distilp_tpu.solver.assemble` arrays, solved by branch-and-cut on the
host. Golden fixture objectives must match the upstream solver
(/root/reference/src/distilp/solver/halda_p_solver.py:340-366) to full
precision because the formulation is identical.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .assemble import MilpArrays
from .result import ILPResult


class Infeasible(RuntimeError):
    """The fixed-k subproblem has no feasible assignment."""


def solve_fixed_k_cpu(
    arrays: MilpArrays,
    k: int,
    W: int,
    time_limit: Optional[float] = None,
    mip_gap: Optional[float] = 1e-4,
) -> ILPResult:
    """Solve one fixed-k subproblem with scipy's MILP (HiGHS branch-and-cut)."""
    from scipy.optimize import Bounds, LinearConstraint, milp

    lay = arrays.layout
    lb, ub = arrays.bounds_for_k(W)
    c = arrays.c_for_k(k)
    b_eq = arrays.b_eq_for_k(W)

    constraints = [
        LinearConstraint(arrays.A_ub_for_k(k), -np.inf, arrays.b_ub),
        LinearConstraint(arrays.A_eq, b_eq, b_eq),
    ]

    options = {}
    if time_limit is not None:
        options["time_limit"] = float(time_limit)
    if mip_gap is not None:
        options["mip_rel_gap"] = float(mip_gap)

    res = milp(
        c=c,
        integrality=arrays.integrality,
        bounds=Bounds(lb, ub),
        constraints=constraints,
        options=options,
    )
    if not res.success:
        raise Infeasible(f"No feasible MILP found for k={k}.")

    x = res.x
    M = lay.M
    w = [int(round(x[lay.w(i)])) for i in range(M)]
    n = [int(round(x[lay.n(i)])) for i in range(M)]
    y = [int(round(x[lay.y(i)])) for i in range(M)] if lay.moe else None
    obj = float(c @ x) + arrays.obj_const
    return ILPResult(k=k, w=w, n=n, y=y, obj_value=obj)

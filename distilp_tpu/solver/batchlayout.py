"""Multi-instance batch layout: N heterogeneous HALDA instances, one dispatch.

``solve_sweep_jax`` packs ONE instance into a (static, dynamic) blob pair and
dispatches ``_solve_packed``; ``solve_sweep_scenarios`` batches K futures of
one fleet but requires every scenario to share scenario 0's static half (same
A matrix, same row scaling — the PR 9 ``ValueError`` on row-scale-crossing
excursions is exactly that constraint biting). This module factors the packing
prelude out into a form the cross-shard combiner (``distilp_tpu.combine``) can
batch: each instance keeps its OWN static half, and ``_solve_batched`` vmaps
over both blob stacks, so unrelated fleets — different profiles, different
row scaling, different warm state — solve side by side in one executable as
long as their shape signature matches.

Mixed device counts inside a bucket ride *phantom padding* (``pad_instance``):
a dense instance with ``M_real`` devices is extended to the bucket's ``M_pad``
with zero-cost phantom devices whose layer count is pinned to the ``[0, 0]``
box (the assembly already pins out-of-set slack/t/n variables the same way).
Every phantom coefficient is zero and every phantom capacity row is inactive,
so the padded MILP's feasible set is the real MILP's feasible set × {0}^pad:
objective values, certificates, and duals carry over EXACTLY — the pad buys
shape uniformity, not an approximation. The rounding heuristic learns about
phantoms through one new ``w_active`` vector in the dynamic blob (0 marks a
phantom; the per-device rounding box becomes ``[w_active, W·w_active]``).

Padding is dense-only by policy: MoE sweeps run the Lagrangian decomposition,
whose per-device cell enumeration over ``w ∈ [1, w_max]`` has no zero-width
notion of a device — MoE instances bucket by exact M instead (dense sweeps
zero ``w_max``/``e_max``/``decomp_steps``, so the decomposition never runs
over a phantom).

Decode reuses ``collect_sweep`` verbatim — each batch lane is decoded as its
own ``PendingSweep`` (same layout guards, same margin-anchor refresh), then
``unpad_result`` slices the assignment vectors back to ``M_real`` (phantom
entries are provably zero: their box is ``[0, 0]``).

The wire-cost contract carries over per LANE: ``_pack_static``'s
drift-invariant half ships once per shard and then lives on-device behind
``lane_static_to_device``'s content-addressed cache; a flush assembles the
batch's static stack from the cached device copies (``jnp.stack`` of
device arrays — a device-side op, not a host re-upload), so a warm bucket
re-ships only the per-tick dynamic blobs. The whole-stack cache in
``backend_jax`` can't do this job: bucket membership and lane order change
flush to flush, so the STACKED bytes almost never repeat even when every
individual lane is cache-hot.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, replace
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .assemble import INACTIVE_RHS, MilpArrays, assemble
from .coeffs import HaldaCoeffs
from .result import ILPResult

__all__ = [
    "PackedInstance",
    "clear_lane_static_cache",
    "lane_static_to_device",
    "pad_instance",
    "pack_instance",
    "solve_batch",
    "unpad_result",
]

# Per-lane device cache for combined static halves, keyed by the packed
# bytes (1-D float32, so the byte string pins shape and content alike).
# Sized for a full gateway of combinable shards — ``warm_combine`` primes
# one entry per shard BEFORE the openloop warm boundary, so the measured
# phase neither uploads static bytes nor grows live-array bytes (the PR 15
# leak gate sees a size-stable cache). Eviction at the cap is always
# correct — the evicted shard just pays one re-upload on its next flush.
_LANE_STATIC_CACHE: "OrderedDict[bytes, object]" = OrderedDict()
_LANE_STATIC_CAP = 512
_LANE_STATIC_LOCK = threading.Lock()


def lane_static_to_device(vec: np.ndarray):
    """(device array, uploaded-this-call) for ONE lane's static half.

    The combine analogue of ``backend_jax._static_to_device``: content
    addressed, LRU-bounded, and alive-checked (a torn-down backend's
    buffers read as misses, never as dispatch errors). ``warm_combine``
    calls this for every combinable shard so steady-state flushes find
    every lane device-resident.
    """
    from .backend_jax import _entry_alive

    import jax.numpy as jnp

    key = vec.tobytes()
    with _LANE_STATIC_LOCK:
        dev = _LANE_STATIC_CACHE.get(key)
        if dev is not None:
            if _entry_alive(dev):
                _LANE_STATIC_CACHE.move_to_end(key)
                return dev, False
            del _LANE_STATIC_CACHE[key]
    dev = jnp.asarray(vec)
    with _LANE_STATIC_LOCK:
        _LANE_STATIC_CACHE[key] = dev
        while len(_LANE_STATIC_CACHE) > _LANE_STATIC_CAP:
            _LANE_STATIC_CACHE.popitem(last=False)
    return dev, True


def clear_lane_static_cache() -> None:
    """Drop cached per-lane static device blobs (tests; device teardown)."""
    with _LANE_STATIC_LOCK:
        _LANE_STATIC_CACHE.clear()


def _ext(vec: np.ndarray, pad: int, fill: float = 0.0) -> np.ndarray:
    """``vec`` extended by ``pad`` trailing ``fill`` entries (dtype kept)."""
    out = np.full(len(vec) + pad, fill, dtype=np.asarray(vec).dtype)
    out[: len(vec)] = vec
    return out


def pad_instance(
    coeffs: HaldaCoeffs, arrays: MilpArrays, M_pad: int
) -> Tuple[HaldaCoeffs, MilpArrays]:
    """Extend a dense instance to ``M_pad`` devices with zero-cost phantoms.

    The phantom profile: no compute (``a``/``b_gpu``/``xi``/``t_comm`` zero,
    so ``busy_const`` and ``obj_const`` are unchanged), no memory (every
    capacity row inactive), no accelerator, no disk penalty. Post-assembly
    the phantom boxes are pinned: ``w ∈ [0, 0]`` (dropping the global
    ``w >= 1`` floor for phantoms only), the phantom's set-3 slack and its
    ``z`` overflow to ``[0, 0]`` as well. The Σw equality then forces every
    layer onto real devices, and each phantom's rows are identically slack —
    the optimum, its certificate, and the per-k objectives are EXACTLY those
    of the unpadded instance.

    The returned coeffs carry ``w_active`` (1 real / 0 phantom), which
    ``_rounding_arrays_np`` picks up and ships to the on-device rounding
    heuristic via the dynamic blob.
    """
    M = coeffs.M
    if M_pad < M:
        raise ValueError(f"cannot pad M={M} down to {M_pad}")
    if arrays.moe is not None:
        raise ValueError(
            "phantom padding is dense-only: the MoE Lagrangian decomposition "
            "enumerates w in [1, w_max] per device and has no zero-width "
            "device; bucket MoE instances by exact M instead"
        )
    pad = M_pad - M
    if pad == 0:
        return coeffs, arrays

    false_pad = np.zeros(pad, dtype=bool)
    coeffs_p = replace(
        coeffs,
        M=M_pad,
        a=_ext(coeffs.a, pad),
        b_gpu=_ext(coeffs.b_gpu, pad),
        xi=_ext(coeffs.xi, pad),
        t_comm=_ext(coeffs.t_comm, pad),
        # A phantom never streams: its disk is "infinitely fast" so the
        # prefetch row's bp/s_disk term vanishes instead of dividing by 0.
        s_disk=_ext(coeffs.s_disk, pad, INACTIVE_RHS),
        pen_m1=_ext(coeffs.pen_m1, pad),
        pen_m2=_ext(coeffs.pen_m2, pad),
        pen_m3=_ext(coeffs.pen_m3, pad),
        pen_vram=_ext(coeffs.pen_vram, pad),
        set_id=_ext(coeffs.set_id, pad, 3),
        has_gpu=np.concatenate([coeffs.has_gpu, false_pad]),
        ram_rhs=_ext(coeffs.ram_rhs, pad, INACTIVE_RHS),
        ram_minus_n=np.concatenate([coeffs.ram_minus_n, false_pad]),
        cuda_row=np.concatenate([coeffs.cuda_row, false_pad]),
        cuda_rhs=_ext(coeffs.cuda_rhs, pad),
        metal_row=np.concatenate([coeffs.metal_row, false_pad]),
        metal_rhs=_ext(coeffs.metal_rhs, pad),
    )
    arrays_p = assemble(coeffs_p)
    lay = arrays_p.layout
    for i in range(M, M_pad):
        arrays_p.lb[lay.w(i)] = 0.0  # drop the w >= 1 floor
        arrays_p.ub_scale[lay.w(i)] = 0.0  # w <= 0: pinned box
        arrays_p.ub_scale[lay.s3(i)] = 0.0  # no free zero-cost slack
        arrays_p.ub_const[lay.z(i)] = 0.0  # no degenerate overflow column
        arrays_p.ub_scale[lay.z(i)] = 0.0
    coeffs_p.w_active = np.concatenate([np.ones(M), np.zeros(pad)])
    return coeffs_p, arrays_p


@dataclass
class PackedInstance:
    """One instance packed for a cross-shard batch: the two blobs, the jit
    static-argument set, and everything decode needs to rebuild a
    ``PendingSweep`` for its lane of the batched output."""

    static_np: np.ndarray  # f32 drift-invariant half (per-lane in a batch)
    dyn_np: np.ndarray  # f32 per-tick half (f64 certificate bits inside)
    statics: dict  # _PACKED_STATIC_ARGS name -> value for _solve_batched
    M_real: int
    M_pad: int
    feasible: List[Tuple[int, int]]
    kWs: List[Tuple[int, int]]
    mip_gap: float
    nf: int
    m: int
    margin_ctx: Optional[tuple] = None
    stats: Optional[dict] = None

    @property
    def signature(self) -> tuple:
        """Bucket identity: instances solve in one ``_solve_batched``
        executable iff their signatures are equal — the jit static args plus
        the two blob lengths (together they pin the traced program and every
        argument shape)."""
        from .backend_jax import _PACKED_STATIC_ARGS

        return tuple(self.statics[a] for a in _PACKED_STATIC_ARGS) + (
            int(self.static_np.size),
            int(self.dyn_np.size),
        )


def _pad_warm(warm: Optional[ILPResult], pad: int) -> Optional[ILPResult]:
    """Zero-extend a warm hint's assignment vectors to the padded width.

    Phantom entries get w = n = y = 0 — exactly the padded optimum's shape —
    so the hint re-prices on-device to the same objective it had unpadded.
    ``duals``/``ipm_state`` ride along untouched; their shape gates in
    ``_warm_and_duals`` refuse them when the padded family's shapes differ
    (a refusal costs pruning speed, never correctness).
    """
    if warm is None or pad == 0 or warm.w is None:
        return warm
    zeros = [0] * pad
    return warm.model_copy(
        update={
            "w": list(warm.w) + zeros,
            "n": list(warm.n) + zeros if warm.n is not None else None,
            "y": list(warm.y) + zeros if warm.y is not None else None,
        }
    )


def pack_instance(
    arrays: MilpArrays,
    kWs: Sequence[Tuple[int, int]],
    mip_gap: float = 1e-4,
    coeffs: Optional[HaldaCoeffs] = None,
    warm: Optional[ILPResult] = None,
    M_pad: Optional[int] = None,
    ipm_iters: Optional[int] = None,
    max_rounds: Optional[int] = None,
    beam: Optional[int] = None,
    node_cap: Optional[int] = None,
    ipm_warm_iters: Optional[int] = None,
    lp_backend: Optional[str] = None,
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
    pdhg_dtype: Optional[str] = None,
    margin_state: Optional[dict] = None,
    per_k_optima: bool = False,
    stats: Optional[dict] = None,
) -> Optional[PackedInstance]:
    """Pack one instance for batched solving — ``solve_sweep_jax``'s prelude
    (feasibility filter, standard form, search-parameter resolution, warm and
    dual preparation, blob packing) without the dispatch.

    ``M_pad`` pads a dense instance to a bucket boundary (``pad_instance``).
    Feasibility is judged against the REAL device count — a k with
    ``W < M_real`` can't give every real device a layer, while phantoms take
    none — and the padded family zeroes the decomposition statics exactly as
    the per-shard dense path does.

    Returns None when no k is structurally feasible (mirrors
    ``solve_sweep_jax``'s ``(results, None)`` early-out).
    """
    from .backend_jax import (
        DEFAULT_RESTART_TOL,
        _pack_dynamic,
        _pack_static,
        _resolve_search_params,
        _rounding_arrays_np,
        _warm_and_duals,
        build_standard_form,
        margin_bounds_from_state,
    )

    if coeffs is None:
        raise ValueError("pack_instance requires the HaldaCoeffs used for assembly")
    M_real = arrays.layout.M
    feasible = [(k, W) for (k, W) in kWs if W >= M_real]
    if not feasible:
        return None

    M_pad = M_real if M_pad is None else int(M_pad)
    if M_pad != M_real:
        coeffs, arrays = pad_instance(coeffs, arrays, M_pad)
        warm = _pad_warm(warm, M_pad - M_real)

    sf = build_standard_form(arrays, coeffs, feasible)
    n_k = len(sf.ks)
    # Batched lanes compose by vmap, so mesh_shards stays 1 in the packed
    # statics (see _solve_batched); pdhg_dtype threads for real.
    (
        cap, beam, ipm_iters, ipm_warm_iters, max_rounds, engine,
        _shards, pdhg_dtype,
    ) = _resolve_search_params(
        sf.moe, n_k, node_cap, beam, ipm_iters, max_rounds,
        per_k=per_k_optima, ipm_warm_iters=ipm_warm_iters,
        lp_backend=lp_backend, pdhg_iters=pdhg_iters, M=M_pad,
        pdhg_dtype=pdhg_dtype,
    )
    restart_tol = (
        DEFAULT_RESTART_TOL if pdhg_restart_tol is None else pdhg_restart_tol
    )
    warm_tuple, duals_tuple, root_warm_tuple = _warm_and_duals(
        sf, arrays, warm, feasible
    )
    if sf.moe:
        from .backend_jax import DECOMP_STEPS_COLD, DECOMP_STEPS_WARM

        w_max = max(W for _, W in feasible)
        e_max = int(arrays.moe.E)
        decomp_steps = (
            DECOMP_STEPS_WARM
            if duals_tuple is not None and warm_tuple is not None
            else DECOMP_STEPS_COLD
        )
    else:
        w_max = e_max = decomp_steps = 0

    rd_np = _rounding_arrays_np(coeffs, arrays.moe)
    margin_np = None
    if (
        margin_state is not None
        and sf.moe
        and warm_tuple is not None
        and duals_tuple is not None
        and not per_k_optima
    ):
        margin_np = margin_bounds_from_state(margin_state, rd_np, sf, duals_tuple)
    has_margin = margin_np is not None

    static_np = _pack_static(sf)
    dyn_np = _pack_dynamic(
        sf, rd_np, mip_gap, warm_tuple, duals=duals_tuple, margin=margin_np,
        root_warm=root_warm_tuple,
    )
    statics = dict(
        M=M_pad,
        n_k=n_k,
        m=sf.A.shape[1],
        nf=sf.A.shape[2],
        cap=cap,
        ipm_iters=ipm_iters,
        max_rounds=max_rounds,
        beam=beam,
        moe=sf.moe,
        has_warm=warm_tuple is not None,
        w_max=w_max,
        e_max=e_max,
        decomp_steps=decomp_steps,
        has_duals=duals_tuple is not None,
        per_k=per_k_optima,
        has_margin=has_margin,
        ipm_warm_iters=ipm_warm_iters,
        has_root_warm=root_warm_tuple is not None,
        lp_backend=engine,
        pdhg_restart_tol=restart_tol,
        mesh_shards=1,
        pdhg_dtype=pdhg_dtype,
        diag=False,
    )
    return PackedInstance(
        static_np=static_np,
        dyn_np=dyn_np,
        statics=statics,
        M_real=M_real,
        M_pad=M_pad,
        feasible=feasible,
        kWs=list(kWs),
        mip_gap=mip_gap,
        nf=sf.A.shape[2],
        m=sf.A.shape[1],
        margin_ctx=(
            (
                margin_state, has_margin, rd_np,
                np.asarray(sf.ks, np.float64),
                np.asarray(sf.Ws, np.float64),
            )
            if margin_state is not None and sf.moe
            else None
        ),
        stats=stats,
    )


def unpad_result(res: Optional[ILPResult], M_real: int) -> Optional[ILPResult]:
    """Slice a padded lane's assignment vectors back to the real fleet.

    Phantom entries are provably zero (their ``[0, 0]`` box), so the slice
    discards nothing; ``duals`` (MoE — never padded) and ``ipm_state`` (the
    padded family's root iterates, valid verbatim on the lane's next padded
    solve and shape-refused by an unpadded one) pass through untouched.
    """
    if res is None or res.w is None or len(res.w) <= M_real:
        return res
    return res.model_copy(
        update={
            "w": list(res.w[:M_real]),
            "n": list(res.n[:M_real]) if res.n is not None else None,
            "y": list(res.y[:M_real]) if res.y is not None else None,
        }
    )


def solve_batch(
    instances: Sequence[PackedInstance],
    timings: Optional[dict] = None,
    lane_pad: Optional[int] = None,
) -> List[Tuple[List[Optional[ILPResult]], Optional[ILPResult]]]:
    """Solve N same-signature instances in ONE ``_solve_batched`` dispatch.

    Returns ``solve_sweep_jax``'s ``(per_k_results, best)`` contract per
    instance, in order, with padded lanes already sliced back to their real
    fleet. Each lane decodes through ``collect_sweep`` — the same layout
    guards, certificate math, and margin-anchor refresh as the per-shard
    path, which is what makes a combined tick indistinguishable downstream.

    ``lane_pad`` (>= N) pads the batch to a fixed lane COUNT by repeating
    the last instance's blobs: the lane axis is a compile-shape dimension
    of the vmapped executable, so quantizing it (``BucketPolicy.
    quantize_lanes``) keeps the reachable executable set finite — the
    zero-recompile contract. Duplicate lanes are solved but never decoded.
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    from .backend_jax import (
        PendingSweep,
        _solve_batched,
        collect_sweep,
    )

    if not instances:
        return []
    sig0 = instances[0].signature
    for i, inst in enumerate(instances[1:], 1):
        if inst.signature != sig0:
            raise ValueError(
                f"solve_batch requires one bucket signature: instance {i} "
                f"has {inst.signature}, instance 0 has {sig0} — group "
                f"instances with combine.BucketPolicy first"
            )

    t0 = _time.perf_counter()
    n_real = len(instances)
    if lane_pad is not None and lane_pad < n_real:
        raise ValueError(f"lane_pad {lane_pad} < batch size {n_real}")
    n_lanes = lane_pad if lane_pad is not None else n_real
    statics_np = [inst.static_np for inst in instances]
    dyns_np = [inst.dyn_np for inst in instances]
    if n_lanes > n_real:
        statics_np += [statics_np[-1]] * (n_lanes - n_real)
        dyns_np += [dyns_np[-1]] * (n_lanes - n_real)
    dyn_stack = np.stack(dyns_np)
    t1 = _time.perf_counter()
    # Per-LANE content addressing: each shard's static half is fetched from
    # (or installed into) the device cache individually, and the batch's
    # static stack is assembled device-side — bucket membership churn costs
    # zero static re-uploads as long as the lanes themselves are cache-hot.
    lane_pairs = [lane_static_to_device(s) for s in statics_np]
    static_dev = jnp.stack([dev for dev, _ in lane_pairs])
    lane_uploads = sum(1 for _, up in lane_pairs if up)
    dyn_dev = jnp.asarray(dyn_stack)
    out_dev = _solve_batched(static_dev, dyn_dev, **instances[0].statics)
    out_np = np.asarray(jax.device_get(out_dev))
    t2 = _time.perf_counter()

    decoded = []
    for b, inst in enumerate(instances):
        st = inst.statics
        pending = PendingSweep(
            out=out_np[b],
            results=[None] * len(inst.kWs),
            feasible=inst.feasible,
            kWs=inst.kWs,
            M=inst.M_pad,
            n_k=st["n_k"],
            moe=st["moe"],
            w_max=st["w_max"],
            mip_gap=inst.mip_gap,
            debug=False,
            per_k=st["per_k"],
            margin_ctx=inst.margin_ctx,
            nf=inst.nf,
            m=inst.m,
            stats=inst.stats,
        )
        results, best = collect_sweep(pending)
        decoded.append(
            (
                [unpad_result(r, inst.M_real) for r in results],
                unpad_result(best, inst.M_real),
            )
        )
    if timings is not None:
        timings["batch_size"] = n_real
        timings["lanes"] = n_lanes
        timings["pack_ms"] = (t1 - t0) * 1e3
        timings["solve_ms"] = (t2 - t1) * 1e3
        # Fraction of lanes served from the device cache (per-lane hit
        # rate, not the per-shard path's 0/1 whole-blob verdict).
        timings["static_hit"] = 1.0 - lane_uploads / n_lanes
        timings["decode_ms"] = (_time.perf_counter() - t2) * 1e3
    return decoded

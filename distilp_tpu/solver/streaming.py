"""Streaming re-placement: the north-star end state of BASELINE.json.

The reference is an offline planner — profiles in, one solve, placement out.
On an accelerator the solve is cheap enough to sit in a loop: profiles
stream in (device load changes, nodes join/leave, t_comm drifts), each tick
re-solves warm-started from the previous placement, and the new assignment
streams out. BASELINE.json's "DeepSeek-V3 MoE real-time re-placement
(streaming profiles, 32 devices)" is this loop.

Warm start semantics: the previous integer assignment is re-PRICED exactly
under the new coefficients on-device (never trusted at its stale objective),
then used as the initial incumbent, so branch-and-bound prunes from round
one. When the fleet barely changed, the first certificate check usually
passes within a round or two; when it changed shape (device count, L), the
replanner falls back to a cold solve automatically.

Three kinds of warm state ride ``self.last`` across ticks: the integer
assignment (incumbent seed), the Lagrangian root multipliers (MoE bound
re-certification), and — since the warm-started IPM — the root LP
iterates (``HALDAResult.ipm_state``), so each tick's root interior-point
solves start from the previous tick's points instead of mid-box and
early-exit after a handful of Mehrotra steps. All three are validity-gated
on-device; staleness costs iterations, never soundness. ``cold_start=True``
disables every one of them for A/B measurement.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..common import DeviceProfile, ModelProfile
from .api import halda_solve
from .result import HALDAResult

# Wire format of the warm-state blob (dump_warm_state/load_warm_state).
# Bump on any layout change; load refuses versions it does not know —
# a snapshot is warm STATE, staleness costs iterations but a misdecoded
# array would cost soundness.
WARM_BLOB_VERSION = 1


def _encode_state(obj):
    """JSON-able encoding of a warm-state payload, bit-exact for arrays.

    numpy arrays ride as base64 of their raw bytes plus dtype/shape (the
    round trip is bit-identical — a restored replanner's next tick must
    equal the uninterrupted one's, and f32 iterates re-encoded through
    decimal text would not be). Tuples decode as lists; every consumer of
    the warm state (margin gate, IPM/PDHG warm entry) only iterates or
    ``np.array_equal``s them, so the distinction is not load-bearing.
    """
    import base64

    import numpy as np

    if isinstance(obj, np.ndarray):
        return {
            "__nd__": 1,
            "dtype": str(obj.dtype),
            "shape": list(obj.shape),
            "data": base64.b64encode(
                np.ascontiguousarray(obj).tobytes()
            ).decode("ascii"),
        }
    if isinstance(obj, np.generic):
        # A lone numpy scalar (e.g. rd["E"]): re-materialize at the same
        # dtype so exact-match gates keep comparing equal types.
        return {"__npscalar__": 1, "dtype": str(obj.dtype), "value": obj.item()}
    if isinstance(obj, dict):
        return {str(k): _encode_state(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_encode_state(v) for v in obj]
    return obj


def _decode_state(obj):
    import base64

    import numpy as np

    if isinstance(obj, dict):
        if obj.get("__nd__") == 1:
            arr = np.frombuffer(
                base64.b64decode(obj["data"]), dtype=np.dtype(obj["dtype"])
            )
            return arr.reshape(obj["shape"]).copy()
        if obj.get("__npscalar__") == 1:
            return np.dtype(obj["dtype"]).type(obj["value"])
        return {k: _decode_state(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode_state(v) for v in obj]
    return obj


class CombinePrep:
    """One prepared combined tick: the packed instance plus everything
    ``adopt()`` needs to redeem the lane (sets for result decode, the warm
    flag for mode accounting, fleet/model snapshots for the escalation
    re-solve). Produced by ``StreamingReplanner.prepare``."""

    __slots__ = (
        "instance", "sets", "shape", "warm_used", "devs", "model",
        "k_candidates",
    )

    def __init__(
        self, instance, sets, shape, warm_used, devs, model, k_candidates
    ):
        self.instance = instance
        self.sets = sets
        self.shape = shape
        self.warm_used = warm_used
        self.devs = devs
        self.model = model
        self.k_candidates = k_candidates


class StreamingReplanner:
    """Holds the previous placement and re-solves warm on every tick.

    >>> planner = StreamingReplanner(kv_bits="8bit", mip_gap=1e-3)
    >>> placement = planner.step(devs, model)       # cold solve
    >>> devs[3].t_comm *= 2.0                        # profile update streams in
    >>> placement = planner.step(devs, model)       # warm re-solve
    """

    # JAX-backend search-budget overrides a replanner may carry across its
    # ticks (None entries fall back to backend_jax.default_search_params).
    # lp_backend/pdhg_* select and tune the LP relaxation engine per tick
    # ('auto' picks matrix-free PDHG at fleet scale; see README "LP
    # backends") — streaming warm state carries over unchanged either way,
    # because both engines share the iterate contract.
    _SEARCH_KEYS = (
        "max_rounds", "beam", "ipm_iters", "ipm_warm_iters", "node_cap",
        "lp_backend", "pdhg_iters", "pdhg_restart_tol", "mesh_shards",
        "pdhg_dtype",
    )

    def __init__(
        self,
        mip_gap: float = 1e-3,
        kv_bits: str = "8bit",
        backend: str = "jax",
        moe: Optional[bool] = None,
        cold_start: bool = False,
        search: Optional[dict] = None,
        diagnostics: bool = False,
    ) -> None:
        # Library users build a replanner and call step() in a loop; arm the
        # axon-wedge guard here too so the FIRST tick's backend init cannot
        # wedge under JAX_PLATFORMS=cpu (same contract as halda_solve*).
        from ..axon_guard import force_cpu_if_env_requested

        force_cpu_if_env_requested()
        self.mip_gap = mip_gap
        self.kv_bits = kv_bits
        self.backend = backend
        self.moe = moe
        # A/B debugging switch (`solver serve --cold-start`): every tick
        # solves from scratch — no warm incumbent, no stored duals, no root
        # IPM iterates, no margin chain. Results must agree with warm ticks
        # within mip_gap; the wall-clock delta is the warm-start win.
        self.cold_start = cold_start
        # Search-budget overrides (`beam`, `ipm_iters`, `ipm_warm_iters`,
        # `max_rounds`, `node_cap`) applied to EVERY tick — the streaming
        # analogue of passing the knobs to halda_solve directly. A tick
        # near the default budget's certification edge (README
        # "Search-budget knobs") raises them here once instead of on each
        # call site.
        self.search = dict(search or {})
        bad = set(self.search) - set(self._SEARCH_KEYS)
        if bad:
            raise ValueError(
                f"unknown search override(s) {sorted(bad)}; "
                f"valid keys: {list(self._SEARCH_KEYS)}"
            )
        # Solver-interior telemetry (`serve --solver-diagnostics`): every
        # sync tick solves with a convergence dict attached, the raw trace
        # lands on ``last_convergence`` (obs.convergence decodes it) and
        # the flat conv_* digest rides the tick's timings dict onto the
        # sched.solve span / flight records. Off (default) = the exact
        # untraced device program, byte-identical outputs.
        self.diagnostics = diagnostics
        self.last_convergence: dict = {}
        self.last: Optional[HALDAResult] = None
        self.last_mapping = None  # ExpertMapping of the last load-aware tick
        # Observability (see distilp_tpu.sched.metrics): an optional sink
        # with record_tick(mode, certified, escalations) — duck-typed so
        # the solver package stays import-free of the scheduler service —
        # plus the same facts as plain attributes for direct callers.
        self.metrics = None
        self.last_tick_mode: Optional[str] = None  # 'cold'|'warm'|'margin'
        self.last_tick_escalations: int = 0
        # The last tick's solver timings dict (build_ms/solve_ms/
        # lp_backend/bnb_rounds/ipm_iters_executed/escalated...), kept as
        # an attribute for DIRECT library users who drive step() in a loop
        # and want the breakdown after the fact without threading a dict
        # through every call site (same pattern as last_tick_mode /
        # last_tick_escalations above; the scheduler reads its own tick_tm
        # instead). Empty when the caller passed no timings dict — the
        # solve never slows down to record one it wasn't asked for.
        self.last_tick_timings: dict = {}
        self._last_shape: Optional[tuple] = None
        self._load_factors = None  # realized per-device load multipliers
        self._in_flight: list = []  # (PendingHalda, shape, devs, model, loads)
        # MoE margin fast path: previous tick's decomp bounds + rd vectors
        # (see backend_jax.margin_bounds_from_state). Sync step() only.
        self._margin_state: dict = {}

    def step(
        self,
        devs: Sequence[DeviceProfile],
        model: ModelProfile,
        k_candidates: Optional[Sequence[int]] = None,
        timings: Optional[dict] = None,
    ) -> HALDAResult:
        """One tick: re-solve under the current profiles, warm when possible.

        ``timings`` (a dict, JAX backend) receives the tick's wall-clock
        breakdown: ``build_ms``/``pack_ms``/``upload_ms``/``solve_ms``/
        ``static_hit`` (see ``halda_solve``) — a stale-dual cold fallback
        overwrites the dict with the fallback solve's numbers, which ARE
        that tick's cost.

        When the profile carries skewed ``expert_loads`` (refreshed per tick
        from router statistics), the tick prices each device's y-units at
        the PREVIOUS tick's realized load factors and maps concrete expert
        ids afterwards (``solver.routing``) — the fixed-point iteration of
        ``solve_load_aware`` unrolled across the stream, one mapping per
        tick. The mapping lands on ``self.last_mapping``.
        """
        import numpy as np

        from .moe import model_has_moe_components

        use_moe = (
            model_has_moe_components(model) if self.moe is None else bool(self.moe)
        )
        shape = (len(devs), model.L, use_moe)
        warm = self.last if shape == self._last_shape else None
        if self.cold_start:
            warm = None  # A/B mode: no cross-tick state of any kind

        loads = None
        if use_moe and model.expert_loads is not None:
            from .routing import normalize_loads

            loads = normalize_loads(model.expert_loads, model.n_routed_experts)
            if np.allclose(loads, 1.0):
                loads = None
        factors = self._load_factors if loads is not None else None
        if factors is not None and len(factors) != len(devs):
            factors = None  # fleet changed shape; restart the fixed point

        conv = {} if (self.diagnostics and self.backend == "jax") else None
        result = halda_solve(
            devs,
            model,
            k_candidates=k_candidates,
            mip_gap=self.mip_gap,
            kv_bits=self.kv_bits,
            backend=self.backend,
            moe=self.moe,
            warm=warm,
            load_factors=factors,
            timings=timings,
            margin_state=None if self.cold_start else self._margin_state,
            convergence=conv,
            **self.search,
        )
        result = self._certify_or_fallback(
            result, devs, model, k_candidates, factors, warm, timings,
            convergence=conv,
        )
        self.last_convergence = conv if conv is not None else {}

        if loads is not None and result.y is not None:
            from .moe import build_moe_arrays
            from .routing import map_experts

            g_base = build_moe_arrays(devs, model).g_raw
            mapping = map_experts(result.y, g_base, loads)
            self.last_mapping = mapping
            self._load_factors = mapping.factors
        else:
            self.last_mapping = None
            self._load_factors = None

        self.last = result
        self._last_shape = shape
        self.last_tick_timings = dict(timings) if timings is not None else {}
        return result

    def _certify_or_fallback(
        self,
        result: HALDAResult,
        devs: Sequence[DeviceProfile],
        model: ModelProfile,
        k_candidates,
        factors,
        warm: Optional[HALDAResult],
        timings: Optional[dict],
        convergence: Optional[dict] = None,
    ) -> HALDAResult:
        """The certification escalation ladder, shared by ``step()`` and
        ``collect()``.

        Rung 1 — a MARGIN tick that missed its certificate drops the
        anchor profile and retries with ONE full bound evaluation, still
        warm: far cheaper than a cold ascent, and it refreshes the anchor
        for subsequent ticks.

        Rung 2 — a warm tick whose STORED DUALS went stale (the zero-step
        bound at the previous multipliers no longer certifies) re-solves
        cold: full ascent, fresh duals. MoE-only, gated on those duals —
        a dense solve that misses its certificate does so for search-
        budget reasons a cold re-solve would not fix.
        """
        # Consume the margin-path report unconditionally: 'used' describes
        # THIS tick only, and a stale True surviving a short-circuit (e.g.
        # a later dense or shape-change tick that never rewrites the key)
        # would misreport that tick as a margin tick.
        margin_used = self._margin_state.pop("used", False)
        escalations = 0
        if not result.certified and margin_used and warm is not None:
            escalations += 1
            self._margin_state.pop("m_y", None)
            result = halda_solve(
                devs,
                model,
                k_candidates=k_candidates,
                mip_gap=self.mip_gap,
                kv_bits=self.kv_bits,
                backend=self.backend,
                moe=self.moe,
                warm=warm,
                load_factors=factors,
                timings=timings,
                margin_state=self._margin_state,
                convergence=convergence,
                **self.search,
            )
            # The retry's own report is irrelevant here (the anchor was
            # dropped, so it cannot be a margin tick); keep the key clean.
            self._margin_state.pop("used", None)
        if warm is not None and warm.duals is not None and not result.certified:
            escalations += 1
            result = halda_solve(
                devs,
                model,
                k_candidates=k_candidates,
                mip_gap=self.mip_gap,
                kv_bits=self.kv_bits,
                backend=self.backend,
                moe=self.moe,
                load_factors=factors,
                timings=timings,
                margin_state=self._margin_state,
                convergence=convergence,
                **self.search,
            )
            self._margin_state.pop("used", None)
        self.last_tick_mode = (
            "margin" if margin_used else ("warm" if warm is not None else "cold")
        )
        self.last_tick_escalations = escalations
        if self.metrics is not None:
            self.metrics.record_tick(
                mode=self.last_tick_mode,
                certified=result.certified,
                escalations=escalations,
            )
        return result

    def submit(
        self,
        devs: Sequence[DeviceProfile],
        model: ModelProfile,
        k_candidates: Optional[Sequence[int]] = None,
    ):
        """Pipelined tick, dispatch half: start a solve and return at once.

        Pair with ``collect()``. Keeping ONE tick in flight while preparing
        the next overlaps host-side instance assembly and the upload with
        the previous solve's execution and result transfer — on a tunneled
        TPU that transfer is the latency floor, so a submit/collect loop
        sustains more placements/sec than back-to-back ``step()`` calls.

        Warm seeding uses the most recently COLLECTED result (one tick
        stale in a full pipeline). That is sound — warm hints are re-priced
        exactly on-device, staleness only costs pruning speed — and the
        same goes for the stored Lagrangian duals and load factors riding
        on it. JAX backend only.
        """
        from .api import halda_solve_async
        from .moe import model_has_moe_components

        if self.backend != "jax":
            raise RuntimeError("pipelined ticks need backend='jax'")
        use_moe = (
            model_has_moe_components(model) if self.moe is None else bool(self.moe)
        )
        shape = (len(devs), model.L, use_moe)
        warm = self.last if shape == self._last_shape else None
        if self.cold_start:
            warm = None

        loads = None
        if use_moe and model.expert_loads is not None:
            import numpy as np

            from .routing import normalize_loads

            loads = normalize_loads(model.expert_loads, model.n_routed_experts)
            if np.allclose(loads, 1.0):
                loads = None
        factors = self._load_factors if loads is not None else None
        if factors is not None and len(factors) != len(devs):
            factors = None

        conv = {} if self.diagnostics else None
        pending = halda_solve_async(
            devs,
            model,
            k_candidates=k_candidates,
            mip_gap=self.mip_gap,
            kv_bits=self.kv_bits,
            moe=self.moe,
            warm=warm,
            load_factors=factors,
            margin_state=None if self.cold_start else self._margin_state,
            convergence=conv,
            **self.search,
        )
        # Snapshot the fleet AND the model: streaming callers mutate both in
        # place between ticks (t_comm drifts, expert_loads refresh), and
        # collect()'s fallback re-solve plus the MoE mapping must price THIS
        # tick's state, not whatever the profiles have drifted to by redeem
        # time. SHALLOW copies (VERDICT r5 item 5): a pydantic model_copy()
        # re-binds every top-level field, which freezes exactly what the
        # streaming drift idiom touches — scalar fields are mutated in
        # place (t_comm *= ...), container fields are REPLACED (expert_loads
        # = [...]) — without duplicating the model's per-layer arrays and
        # throughput tables every tick (that deep copy was most of the
        # off-tunnel pipelined-vs-sync regression). A caller that mutates a
        # nested container in place between submit and collect leaks into
        # the snapshot; no solver or sched path does.
        devs_snap = [d.model_copy() for d in devs]
        model_snap = model.model_copy()
        self._in_flight.append(
            (pending, shape, devs_snap, model_snap, loads, k_candidates,
             factors, warm, conv)
        )
        return pending

    def collect(self) -> HALDAResult:
        """Pipelined tick, blocking half: redeem the oldest in-flight solve."""
        if not self._in_flight:
            raise RuntimeError("no in-flight tick; call submit() first")
        (pending, shape, devs, model, loads, k_candidates, factors,
         warm, conv) = self._in_flight.pop(0)
        result = pending.collect()
        # Pipelined misses escalate synchronously — the pipeline hiccups,
        # correctness does not. The telemetry dict (diagnostics mode) is
        # decoded by the collect above and refilled by any escalation, so
        # last_convergence always describes the tick just redeemed.
        result = self._certify_or_fallback(
            result, devs, model, k_candidates, factors, warm, None,
            convergence=conv,
        )
        self.last_convergence = conv if conv is not None else {}
        if loads is not None and result.y is not None:
            from .moe import build_moe_arrays
            from .routing import map_experts

            g_base = build_moe_arrays(devs, model).g_raw
            mapping = map_experts(result.y, g_base, loads)
            self.last_mapping = mapping
            self._load_factors = mapping.factors
        else:
            self.last_mapping = None
            self._load_factors = None
        self.last = result
        self._last_shape = shape
        return result

    def prepare(
        self,
        devs: Sequence[DeviceProfile],
        model: ModelProfile,
        k_candidates: Optional[Sequence[int]] = None,
        M_pad: Optional[int] = None,
        warm_override=None,
    ):
        """Combined tick, pack half: this tick as a ``PackedInstance`` for a
        cross-shard batched solve (``solver.batchlayout`` / the gateway's
        ``combine`` path). Pair with ``adopt()``.

        Returns None when the tick cannot ride a batch — MoE profiles (the
        load-factor fixed point and the margin ladder are iterative multi-
        solve loops; those shards stay on the per-shard path) or a non-jax
        backend. Raises RuntimeError when no k is structurally feasible,
        same as ``step()`` would.

        Warm seeding is identical to ``step()``: the previous result when
        the fleet shape matched, re-priced exactly on-device. ``M_pad``
        extends the instance to a bucket boundary with phantom devices
        (see ``batchlayout.pad_instance`` — exact, not approximate).
        ``warm_override`` (an ``ILPResult``) substitutes the warm hint
        without touching planner state — ``Gateway.warm_combine`` uses it
        to trace the steady-state signature (root-warm iterates from a
        prior BATCHED solve carry padded-family shapes, which flips
        ``has_root_warm`` relative to a per-shard-seeded pack).
        """
        from .api import _build_instance, _warm_to_ilp
        from .batchlayout import pack_instance
        from .moe import model_has_moe_components

        if self.backend != "jax":
            return None
        use_moe = (
            model_has_moe_components(model) if self.moe is None else bool(self.moe)
        )
        if use_moe:
            return None
        shape = (len(devs), model.L, use_moe)
        warm = self.last if shape == self._last_shape else None
        if self.cold_start:
            warm = None

        Ks, sets, coeffs, arrays = _build_instance(
            devs, model, k_candidates, self.kv_bits, False, None, 1
        )
        # mesh_shards deliberately absent: combine lanes compose by vmap
        # (see backend_jax._solve_batched), so a replanner's row-mesh knob
        # applies to its own-dispatch ticks, not to batched prep.
        knobs = {
            key: self.search.get(key)
            for key in (
                "ipm_iters", "max_rounds", "beam", "node_cap",
                "ipm_warm_iters", "lp_backend", "pdhg_iters",
                "pdhg_restart_tol", "pdhg_dtype",
            )
        }
        inst = pack_instance(
            arrays,
            [(k, model.L // k) for k in Ks],
            mip_gap=self.mip_gap,
            coeffs=coeffs,
            warm=(
                warm_override if warm_override is not None
                else _warm_to_ilp(warm)
            ),
            M_pad=M_pad,
            **knobs,
        )
        if inst is None:
            raise RuntimeError("No feasible MILP found for any k.")
        # Snapshot fleet + model exactly like submit(): adopt()'s
        # escalation re-solve must price THIS tick's profiles, not whatever
        # they drifted to by the time the batch lands.
        return CombinePrep(
            instance=inst,
            sets=sets,
            shape=shape,
            warm_used=warm is not None or warm_override is not None,
            devs=[d.model_copy() for d in devs],
            model=model.model_copy(),
            k_candidates=list(k_candidates) if k_candidates else None,
        )

    def adopt(self, prep, decoded, timings: Optional[dict] = None) -> HALDAResult:
        """Combined tick, redeem half: fold one lane of a batched solve back
        into this replanner's warm state, exactly as if ``step()`` had
        produced it.

        ``decoded`` is this instance's ``(per_k_results, best)`` pair from
        ``batchlayout.solve_batch``. An uncertified lane escalates
        per-shard — a full ``halda_solve`` warm-seeded from the batch
        incumbent, which runs the solver's own escalation ladder — so a
        combined tick's certificate contract equals the per-shard path's.
        """
        from .api import _best_to_result, halda_solve

        results, best = decoded
        if best is None:
            raise RuntimeError("No feasible MILP found for any k.")
        result = _best_to_result(best, prep.sets)
        escalations = 0
        if not result.certified:
            escalations = 1
            result = halda_solve(
                prep.devs,
                prep.model,
                k_candidates=prep.k_candidates,
                mip_gap=self.mip_gap,
                kv_bits=self.kv_bits,
                backend="jax",
                moe=False,
                warm=result,
                timings=timings,
                **self.search,
            )
        self.last_tick_mode = "warm" if prep.warm_used else "cold"
        self.last_tick_escalations = escalations
        if self.metrics is not None:
            self.metrics.record_tick(
                mode=self.last_tick_mode,
                certified=result.certified,
                escalations=escalations,
            )
        self.last = result
        self._last_shape = prep.shape
        self.last_mapping = None
        self._load_factors = None
        self.last_tick_timings = dict(timings) if timings is not None else {}
        return result

    def reset(self) -> None:
        self.last = None
        self.last_mapping = None
        self.last_tick_mode = None
        self.last_tick_escalations = 0
        self.last_tick_timings = {}
        self.last_convergence = {}
        self._last_shape = None
        self._load_factors = None
        self._in_flight = []
        self._margin_state = {}

    # -- warm-state snapshot/restore --------------------------------------
    #
    # Three kinds of warm state ride across ticks (module docstring): the
    # integer incumbent + Lagrangian duals (on ``self.last``), the root LP
    # iterates (``last.ipm_state`` — IPM and PDHG share the field layout,
    # so one blob serves both engines), and the MoE margin anchor
    # (``self._margin_state``). All of it is validity-gated on-device at
    # the next tick, so a snapshot that goes stale between dump and load
    # costs iterations, never soundness. ``HALDAResult.ipm_state`` is
    # ``exclude=True`` in pydantic serialization on purpose (a casually
    # reloaded *solution* should re-solve its roots cold); these two
    # methods are the one sanctioned round trip for the full blob.

    def dump_warm_state(self) -> dict:
        """Every cross-tick warm artifact as one JSON-able blob.

        The inverse is ``load_warm_state``; the round trip is bit-exact
        (arrays travel as raw bytes), so a restored replanner's next tick
        is identical to the uninterrupted replanner's. Refuses to snapshot
        with pipelined ticks in flight — collect() them first; their warm
        state exists only on the device until redeemed.
        """
        if self._in_flight:
            raise RuntimeError(
                "cannot dump warm state with pipelined ticks in flight; "
                "collect() them first"
            )
        blob: dict = {
            "version": WARM_BLOB_VERSION,
            "shape": list(self._last_shape) if self._last_shape else None,
            "last": None,
            "ipm_state": None,
            "margin_state": None,
            "load_factors": _encode_state(self._load_factors),
        }
        if self.last is not None:
            blob["last"] = self.last.model_dump()
            blob["ipm_state"] = _encode_state(self.last.ipm_state)
        if self._margin_state:
            ms = {k: v for k, v in self._margin_state.items() if k != "used"}
            blob["margin_state"] = _encode_state(ms)
        return blob

    def load_warm_state(self, blob: dict) -> None:
        """Restore a ``dump_warm_state`` blob into this replanner.

        Replaces every piece of cross-tick state (an implicit ``reset()``
        first); the replanner's configuration (gap, backend, search knobs)
        stays its own — warm state interchanges across engines by design,
        so a blob dumped under one ``lp_backend`` warm-starts the other.
        """
        version = blob.get("version")
        if version != WARM_BLOB_VERSION:
            raise ValueError(
                f"unknown warm-state blob version {version!r} "
                f"(this build reads {WARM_BLOB_VERSION})"
            )
        self.reset()
        if blob.get("last") is not None:
            result = HALDAResult.model_validate(blob["last"])
            result.ipm_state = _decode_state(blob.get("ipm_state"))
            self.last = result
        shape = blob.get("shape")
        self._last_shape = tuple(shape) if shape else None
        ms = _decode_state(blob.get("margin_state"))
        self._margin_state = ms if isinstance(ms, dict) else {}
        lf = _decode_state(blob.get("load_factors"))
        self._load_factors = lf

"""HALDA placement solver: CPU oracle + JAX/TPU batched backend."""

from .api import (
    PendingHalda,
    halda_solve,
    halda_solve_async,
    halda_solve_per_k,
    halda_solve_scenarios,
)
from .coeffs import (
    HaldaCoeffs,
    alpha_beta_xi,
    assign_sets,
    b_cio,
    b_prime,
    build_coeffs,
    kappa_constant,
    valid_factors_of_L,
)
from .moe import MoEArrays, adjust_model, build_moe_arrays, model_has_moe_components
from .result import HALDAResult, ILPResult
from .routing import (
    ExpertMapping,
    expert_makespan,
    map_experts,
    normalize_loads,
    realized_objective,
    solve_load_aware,
)
from .streaming import StreamingReplanner

__all__ = [
    "halda_solve",
    "halda_solve_async",
    "halda_solve_per_k",
    "halda_solve_scenarios",
    "PendingHalda",
    "StreamingReplanner",
    "ExpertMapping",
    "expert_makespan",
    "map_experts",
    "normalize_loads",
    "realized_objective",
    "solve_load_aware",
    "MoEArrays",
    "adjust_model",
    "build_moe_arrays",
    "model_has_moe_components",
    "HALDAResult",
    "ILPResult",
    "HaldaCoeffs",
    "build_coeffs",
    "b_prime",
    "alpha_beta_xi",
    "b_cio",
    "assign_sets",
    "kappa_constant",
    "valid_factors_of_L",
]

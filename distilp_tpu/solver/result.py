"""Solver result types."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from pydantic import BaseModel, Field

from ..common import DeviceProfile


class ILPResult(BaseModel):
    """Solution of one fixed-k subproblem.

    The JAX backend's k-sweep returns one winning entry with the full integer
    assignment plus reporting-only entries for the other k's: those carry the
    best *found* incumbent objective for that k with ``w``/``n`` left as
    ``None`` (re-deriving the losing assignments would cost another solve) and
    ``certified=False``. The reference returns certified per-k optima
    (/root/reference/src/distilp/solver/halda_p_solver.py:392-412); consumers
    that need a losing k's assignment should re-solve with
    ``k_candidates=[k]``.
    """

    k: int
    w: Optional[List[int]] = None
    n: Optional[List[int]] = None
    obj_value: float
    # MoE co-assignment: routed experts hosted per device (None in dense mode)
    y: Optional[List[int]] = None
    # Optimality certificate: achieved relative gap (incumbent - best bound)
    # / |incumbent| when the backend computed one, and whether it met the
    # requested mip_gap. The CPU/HiGHS backend certifies by construction.
    certified: bool = True
    gap: Optional[float] = None
    # Best Lagrangian root multipliers of the solve ({"lam": (n_k,), "mu":
    # (n_k,), "tau": (n_k, M)} as nested lists; JAX MoE solves only). A
    # streaming tick feeds them back as the ascent's starting point, so the
    # warm re-certification needs a short polish instead of the full cold
    # ascent — the bound is valid at ANY multiplier vector.
    duals: Optional[Dict[str, List]] = None
    # Root-round IPM iterates ({"ok", "v", "y", "z", "f"} numpy arrays, one
    # row per k; JAX solves only): the next streaming tick ships them back
    # so its root LP solves start from this tick's iterates instead of the
    # mid-box cold point. Search state, not part of the certificate —
    # excluded from serialization (a reloaded result simply re-solves its
    # roots cold).
    ipm_state: Optional[dict] = Field(default=None, exclude=True, repr=False)


class HALDAResult(BaseModel):
    """Best placement over the k-sweep."""

    w: List[int]
    n: List[int]
    k: int
    obj_value: float
    sets: Dict[str, List[int]]
    # MoE co-assignment: routed experts hosted per device (None in dense mode)
    y: Optional[List[int]] = None
    # Optimality certificate of the winning solve (see ILPResult.certified).
    certified: bool = True
    gap: Optional[float] = None
    # Lagrangian root multipliers for warm-starting the next streaming tick
    # (see ILPResult.duals).
    duals: Optional[Dict[str, List]] = None
    # Root IPM iterates for cross-tick warm starts (see ILPResult.ipm_state;
    # excluded from serialization).
    ipm_state: Optional[dict] = Field(default=None, exclude=True, repr=False)

    def solution_text(self, devices: Sequence[DeviceProfile]) -> str:
        lines = [
            "",
            "=" * 60,
            "HALDA Solution",
            "=" * 60,
            "",
            f"Optimal k: {self.k}",
            f"Objective value: {self.obj_value:.6f}",
            "",
            "Layer distribution (w):",
        ]
        total = sum(self.w) or 1
        for dev, wi in zip(devices, self.w):
            lines.append(f"  {dev.name:40s}: {wi:3d} layers ({wi / total * 100:5.1f}%)")
        lines.append("")
        lines.append("GPU assignments (n):")
        for dev, ni in zip(devices, self.n):
            if ni > 0:
                lines.append(f"  {dev.name:40s}: {ni:3d} layers on GPU")
            else:
                lines.append(f"  {dev.name:40s}: CPU only")
        if self.y is not None:
            lines.append("")
            lines.append("Expert placement (y, routed experts per MoE layer):")
            for dev, yi in zip(devices, self.y):
                lines.append(f"  {dev.name:40s}: {yi:3d} experts")
        lines.append("")
        lines.append("Device sets:")
        for set_name in ("M1", "M2", "M3"):
            members = self.sets.get(set_name, [])
            if members:
                names = ", ".join(devices[i].name for i in members)
                lines.append(f"  {set_name}: {names}")
        return "\n".join(lines)

    def print_solution(self, devices: Sequence[DeviceProfile]) -> None:
        print(self.solution_text(devices))

"""Solver result types."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from pydantic import BaseModel

from ..common import DeviceProfile


class ILPResult(BaseModel):
    """Solution of one fixed-k subproblem."""

    k: int
    w: List[int]
    n: List[int]
    obj_value: float
    # MoE co-assignment: routed experts hosted per device (None in dense mode)
    y: Optional[List[int]] = None


class HALDAResult(BaseModel):
    """Best placement over the k-sweep."""

    w: List[int]
    n: List[int]
    k: int
    obj_value: float
    sets: Dict[str, List[int]]
    # MoE co-assignment: routed experts hosted per device (None in dense mode)
    y: Optional[List[int]] = None

    def solution_text(self, devices: Sequence[DeviceProfile]) -> str:
        lines = [
            "",
            "=" * 60,
            "HALDA Solution",
            "=" * 60,
            "",
            f"Optimal k: {self.k}",
            f"Objective value: {self.obj_value:.6f}",
            "",
            "Layer distribution (w):",
        ]
        total = sum(self.w) or 1
        for dev, wi in zip(devices, self.w):
            lines.append(f"  {dev.name:40s}: {wi:3d} layers ({wi / total * 100:5.1f}%)")
        lines.append("")
        lines.append("GPU assignments (n):")
        for dev, ni in zip(devices, self.n):
            if ni > 0:
                lines.append(f"  {dev.name:40s}: {ni:3d} layers on GPU")
            else:
                lines.append(f"  {dev.name:40s}: CPU only")
        if self.y is not None:
            lines.append("")
            lines.append("Expert placement (y, routed experts per MoE layer):")
            for dev, yi in zip(devices, self.y):
                lines.append(f"  {dev.name:40s}: {yi:3d} experts")
        lines.append("")
        lines.append("Device sets:")
        for set_name in ("M1", "M2", "M3"):
            members = self.sets.get(set_name, [])
            if members:
                names = ", ".join(devices[i].name for i in members)
                lines.append(f"  {set_name}: {names}")
        return "\n".join(lines)

    def print_solution(self, devices: Sequence[DeviceProfile]) -> None:
        print(self.solution_text(devices))

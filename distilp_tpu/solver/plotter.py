"""Optional k-vs-objective curve plot (matplotlib is an optional extra)."""

from __future__ import annotations

from typing import List, Optional, Tuple


def plot_k_curve(
    per_k_objs: List[Tuple[int, Optional[float]]],
    k_star: Optional[int] = None,
    title: str = "HALDA: k vs objective",
    save_path: Optional[str] = None,
) -> None:
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib not installed; skipping k-curve plot")
        return

    ks = [k for k, obj in per_k_objs if obj is not None]
    objs = [obj for _, obj in per_k_objs if obj is not None]
    infeasible = [k for k, obj in per_k_objs if obj is None]

    fig, ax = plt.subplots(figsize=(7, 4))
    ax.plot(ks, objs, marker="o", label="objective")
    if k_star is not None:
        ax.axvline(k_star, linestyle="--", alpha=0.6, label=f"k* = {k_star}")
    for k in infeasible:
        ax.axvline(k, color="red", alpha=0.2)
    ax.set_xlabel("k (pipeline segments)")
    ax.set_ylabel("objective (s)")
    ax.set_title(title)
    ax.legend()
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    else:
        plt.show()
    plt.close(fig)

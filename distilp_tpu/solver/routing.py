"""Load-weighted expert routing: skewed expert popularity shapes placement.

The base MoE formulation (``solver.moe``) assumes uniform routing: a device
hosting ``y_i`` of the ``E`` routed experts serves the load share ``y_i/E``.
Real MoE fleets see skewed expert popularity, and a per-request router makes
the skew observable (``ModelProfile.expert_loads``). Counts alone cannot see
skew — WHICH experts a device hosts decides how much load it serves — so
this module adds the missing pieces, keeping the MILP linear:

1. ``map_experts``: given solved counts ``y`` and a load vector, assign
   concrete expert ids to devices — hottest experts first, each placed on
   the open device where it finishes earliest (LPT list scheduling on the
   per-unit busy coefficient ``g_i``, capacity ``y_i`` slots). This is the
   classic 2-approximation for makespan on uniform-capacity machines,
   restricted by the solver's residency-feasible counts. The realized
   per-device load multipliers ``l_i = (served load share) / (y_i/E)``
   ride on the returned ``ExpertMapping.factors``.
2. ``build_moe_arrays(load_factors=...)`` (in ``solver.moe``) re-prices
   each y-unit on device i at its realized load, so the next solve shifts
   COUNTS in response to the skew (a fast device absorbing hot experts
   carries the same load with fewer slots; a slow device is priced for
   the cold tail it actually serves).
3. ``solve_load_aware``: the fixed-point loop — solve (uniform), map,
   re-price, re-solve — keeping the iterate whose REALIZED end-to-end
   objective (``realized_objective``: the full model objective with expert
   busy priced at the loads the mapped experts actually carry, dense costs
   and cycle term included) is best. Each inner solve carries the normal
   mip-gap certificate for its own linearized instance — the realized
   objective is reported alongside so the linearization is never mistaken
   for an end-to-end optimality claim. (On installs without the JAX
   backend the loop falls back to comparing the expert-busy makespan and
   reports no realized number.)

Both backends consume the same reweighted ``g`` coefficients (built once in
``build_moe_arrays``), so CPU/HiGHS and JAX agree on every linearized
instance by construction.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..common import DeviceProfile, ModelProfile


def normalize_loads(loads: Sequence[float], E: int) -> np.ndarray:
    """Validated mean-1 load vector of length E (uniform when ``loads`` is
    None-ish or degenerate)."""
    if loads is None:
        return np.ones(E)
    q = np.asarray(list(loads), dtype=np.float64)
    if q.shape != (E,) or not np.all(np.isfinite(q)) or np.any(q < 0):
        raise ValueError(
            f"expert_loads must be {E} finite non-negative entries, got "
            f"shape {q.shape}"
        )
    total = q.sum()
    if total <= 0:
        return np.ones(E)
    return q * (E / total)


@dataclass
class ExpertMapping:
    """Concrete expert->device assignment for one placement."""

    expert_of_device: List[List[int]]  # device i -> sorted expert ids hosted
    load_share: np.ndarray  # (M,) fraction of total routed load served
    factors: np.ndarray  # (M,) realized per-y-unit load multipliers


def map_experts(
    y: Sequence[int],
    g_per_unit: Sequence[float],
    loads: np.ndarray,
) -> ExpertMapping:
    """Assign expert ids to devices: LPT list scheduling under slot caps.

    ``g_per_unit[i]`` is device i's busy seconds per uniform y-unit (the
    ``MoEArrays.g_raw`` column, any common scale): the finish-time metric is
    ``g_i * (load already assigned + this expert's load)``. Experts are
    placed hottest-first on the device minimizing that metric among devices
    with free slots, so hot experts land on fast devices and the cold tail
    fills the slow ones.
    """
    y = [int(v) for v in y]
    M = len(y)
    E = int(loads.shape[0])
    if sum(y) != E:
        raise ValueError(f"sum(y)={sum(y)} != E={E}")
    g = np.asarray(list(g_per_unit), dtype=np.float64)
    if g.shape != (M,):
        raise ValueError("g_per_unit must have one entry per device")
    # A 0.0 g means "no table" never happens for a device with y>0 slots
    # (build_moe_arrays prices every device); guard anyway.
    g = np.where(g > 0, g, np.max(g, initial=1.0))

    order = np.argsort(-loads, kind="stable")
    assigned_load = np.zeros(M)
    slots_left = np.asarray(y, dtype=np.int64).copy()
    expert_of_device: List[List[int]] = [[] for _ in range(M)]
    for e in order:
        open_devs = np.flatnonzero(slots_left > 0)
        finish = g[open_devs] * (assigned_load[open_devs] + loads[e])
        i = int(open_devs[int(np.argmin(finish))])
        expert_of_device[i].append(int(e))
        assigned_load[i] += loads[e]
        slots_left[i] -= 1

    share = assigned_load / E  # loads are mean-1: total mass is E
    uniform = np.asarray(y, dtype=np.float64) / E
    factors = np.divide(
        share, uniform, out=np.ones(M), where=uniform > 0
    )
    for ids in expert_of_device:
        ids.sort()
    return ExpertMapping(
        expert_of_device=expert_of_device, load_share=share, factors=factors
    )


def expert_makespan(
    g_per_unit: Sequence[float], mapping: ExpertMapping
) -> float:
    """Realized expert-busy makespan of a mapping: ``max_i g_i * load_i``.

    ``load_i`` is the mean-1 load mass device i actually serves under the
    concrete expert assignment (``E * load_share_i``); with uniform routing
    it equals ``y_i``, recovering the model's ``max g_i y_i`` term. This is
    the routing-sensitive slice of the objective — the dense (w, n) costs
    do not depend on which expert ids a device hosts. ``solve_load_aware``
    selects its iterate by the full ``realized_objective`` and uses this
    slice only as the no-JAX fallback comparator.
    """
    g = np.asarray(list(g_per_unit), dtype=np.float64)
    E = float(sum(len(ids) for ids in mapping.expert_of_device))
    loads_served = mapping.load_share * E  # shares sum to 1; back to mean-1 mass
    return float(np.max(g * loads_served))


def realized_objective(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    result,
    mapping: ExpertMapping,
    kv_bits: str = "8bit",
    coeffs=None,
) -> float:
    """Exact model objective of ``result``'s placement with every device's
    expert busy priced at the loads its mapped experts ACTUALLY carry.

    Builds the instance with ``load_factors = mapping.factors`` — with the
    solve-side anti-oscillation floor DISABLED (``factor_floor=0``), so a
    device serving a genuinely cold expert tail is priced at its true cost —
    and prices the fixed ``(k, w, n, y)`` through the backend's closed-form
    pricer: dense costs, slack penalties, and the cycle term included.
    Iterates of the fixed-point loop are thereby compared end-to-end, not
    on the expert makespan slice alone (a later iterate whose expert
    makespan improves but whose dense placement regressed is correctly
    rejected).

    ``coeffs`` (the dense ``HaldaCoeffs`` of the expert-free adjusted
    profile) can be passed to skip rebuilding what a surrounding loop
    already built; only the MoE block depends on the mapping.
    """
    from ..common import kv_bits_to_factor
    from .assemble import assemble
    from .backend_jax import price_fixed_assignment, rounding_data
    from .coeffs import assign_sets, build_coeffs
    from .moe import adjust_model, build_moe_arrays

    if coeffs is None:
        coeffs = build_coeffs(
            devs, adjust_model(model), kv_bits_to_factor(kv_bits),
            assign_sets(devs),
        )
    arrays = assemble(
        coeffs,
        moe=build_moe_arrays(
            devs, model, load_factors=mapping.factors, factor_floor=0.0
        ),
    )
    rd = rounding_data(coeffs, arrays.moe)
    lin = float(
        price_fixed_assignment(
            rd, result.k, model.L // result.k, result.w, result.n, result.y
        )
    )
    return lin + float(arrays.obj_const)


def solve_load_aware(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    expert_loads: Optional[Sequence[float]] = None,
    iters: int = 2,
    **solve_kwargs,
):
    """Fixed-point loop: solve -> map experts -> re-price -> re-solve.

    Returns ``(result, mapping, realized)`` for the iterate whose REALIZED
    end-to-end objective (``realized_objective``: the full model objective
    with expert busy priced at the mapping's actual per-device loads) is
    best. Later iterates warm-start from the previous placement. With
    uniform loads (or ``expert_loads=None`` and no loads on the profile)
    this is exactly one ``halda_solve`` plus a trivial mapping.

    ``realized`` is ``None`` on installs without the JAX backend (the exact
    pricer lives there) and whenever the solves run on a non-JAX backend —
    whether requested via ``backend=`` or by ``halda_solve``'s ``'cpu'``
    default; iterates are then compared on the expert-busy makespan
    instead — a different metric in different units, which is why it is NOT
    returned in the realized slot. Pass ``backend='jax'`` for end-to-end
    selection.

    ``iters=2`` is a measured default, not a guess: on the skewed-Mixtral
    study instance (two hot experts carrying half the load over a 4-device
    fleet) the single re-pricing of iterate 2 improves the realized
    objective by ~0.11% and reshapes the expert split, while iterate 3
    reproduces iterate 2 exactly — the fixed point converges in one
    re-pricing (pinned by ``tests/test_routing.py::
    test_fixed_point_iters_study``).
    """
    from ..common import kv_bits_to_factor
    from .api import halda_solve
    from .coeffs import assign_sets, build_coeffs
    from .moe import adjust_model, build_moe_arrays

    for managed in ("moe", "warm", "load_factors"):
        if managed in solve_kwargs:
            raise TypeError(
                f"solve_load_aware manages {managed!r} itself; pass it "
                f"through halda_solve directly if you need manual control"
            )
    if solve_kwargs.get("batch_size", 1) != 1:
        # Every solve here is moe=True, where halda_solve rejects batch
        # pricing (the expert busy model is per-token batch-1); fail with
        # routing context instead of letting the first solve raise.
        raise ValueError(
            "solve_load_aware is MoE-only and batch_size pricing is "
            "dense-only; the load-aware loop always prices at batch 1"
        )

    loads = normalize_loads(
        expert_loads if expert_loads is not None else model.expert_loads,
        model.n_routed_experts,
    )
    uniform = bool(np.allclose(loads, 1.0))

    # Unweighted busy coefficients: the common metric every iterate's
    # mapping is built with. The dense coefficient block is
    # factor-independent — build it once for all realized re-pricings.
    g_base = build_moe_arrays(devs, model).g_raw
    kv_bits = solve_kwargs.get("kv_bits", "8bit")
    dense_coeffs = build_coeffs(
        devs, adjust_model(model), kv_bits_to_factor(kv_bits), assign_sets(devs)
    )

    factors = None
    best = None
    prev = None
    rounds = 1 if uniform else max(1, int(iters))
    for _ in range(rounds):
        result = halda_solve(
            devs, model, moe=True, load_factors=factors, warm=prev,
            **solve_kwargs,
        )
        if prev is not None and not result.certified:
            # The warm tick certifies against the bound at the PREVIOUS
            # iterate's duals — priced under different factors. A large
            # factor swing can leave that bound too loose; re-solve cold
            # (full ascent) instead of carrying an uncertified iterate.
            result = halda_solve(
                devs, model, moe=True, load_factors=factors, **solve_kwargs
            )
        mapping = map_experts(result.y, g_base, loads)
        if solve_kwargs.get("backend", "cpu") != "jax":  # halda_solve default
            # The exact end-to-end pricer lives in the JAX backend. The gate
            # is the EFFECTIVE backend (an absent kwarg defaults halda_solve
            # to 'cpu'): a caller whose solves run on CPU must not have this
            # comparator be the one code path that touches JAX — on a
            # machine whose JAX targets a wedged remote TPU it could hang an
            # otherwise-CPU solve. Select on the expert-makespan slice
            # instead; pass backend='jax' to get end-to-end selection.
            realized = None
            metric = expert_makespan(g_base, mapping)
        else:
            try:
                realized = realized_objective(
                    devs, model, result, mapping, kv_bits=kv_bits,
                    coeffs=dense_coeffs,
                )
                metric = realized
            except ImportError:
                # No JAX in this environment (pure-CPU backend install):
                # select on the expert-makespan slice, the routing-sensitive
                # part, and report no realized objective rather than a
                # lookalike number.
                realized = None
                metric = expert_makespan(g_base, mapping)
        if best is None or metric < best[3]:
            best = (result, mapping, realized, metric)
        if uniform:
            break
        factors = mapping.factors
        prev = result
    return best[:3]

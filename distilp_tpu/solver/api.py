"""Public solver API: ``halda_solve`` with pluggable backends.

``backend='cpu'`` — per-k scipy/HiGHS branch-and-cut (the oracle).
``backend='jax'`` — vmapped interior-point LP relaxations + batched
branch-and-bound on the accelerator (see ``backend_jax``).

Call-compatible with the reference entry point
(/root/reference/src/distilp/solver/halda_p_solver.py:369-436), with the
dead knobs wired for real: ``time_limit`` and ``k_candidates`` are honored
(the reference CLI parsed but dropped them).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

from ..axon_guard import force_cpu_if_env_requested
from ..common import DeviceProfile, ModelProfile, kv_bits_to_factor
from .assemble import assemble
from .backend_cpu import Infeasible, solve_fixed_k_cpu
from .coeffs import assign_sets, build_coeffs, valid_factors_of_L
from .moe import adjust_model, build_moe_arrays, resolve_moe
from .result import HALDAResult, ILPResult

Backend = str  # 'cpu' | 'jax'


def _warm_to_ilp(warm: Optional[HALDAResult]) -> Optional[ILPResult]:
    """A previous solve's result as the backend's warm-hint type — the ONE
    conversion every JAX solve path (sync, async, scenario) uses."""
    if warm is None:
        return None
    return ILPResult(
        k=warm.k, w=warm.w, n=warm.n, y=warm.y,
        obj_value=warm.obj_value, duals=warm.duals,
        ipm_state=warm.ipm_state,
    )


def _best_to_result(best: ILPResult, sets) -> HALDAResult:
    """Wrap a backend optimum into the public result type (shared by every
    solve path, so a new result field threads through exactly once)."""
    return HALDAResult(
        w=list(best.w),
        n=list(best.n),
        k=best.k,
        obj_value=best.obj_value,
        sets={name: list(v) for name, v in sets.items()},
        y=list(best.y) if best.y is not None else None,
        certified=best.certified,
        gap=best.gap,
        duals=best.duals,
        ipm_state=best.ipm_state,
    )


def _build_instance(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    k_candidates: Optional[Iterable[int]],
    kv_bits: str,
    moe: Optional[bool],
    load_factors: Optional[Sequence[float]],
    batch_size: int = 1,
):
    """Shared validation + instance assembly of the sync and async paths:
    (Ks, sets, coeffs, arrays). Any change here reaches both."""
    # Arm the axon-wedge guard on the LIBRARY path: every halda_solve*
    # variant funnels through here before its first backend contact, so a
    # plain `JAX_PLATFORMS=cpu halda_solve(backend='jax')` user gets the
    # same protection as the CLI entry points instead of wedging on a dead
    # tunneled-TPU plugin (VERDICT round-5 finding 2; see axon_guard).
    force_cpu_if_env_requested()
    use_moe = resolve_moe(model, moe)
    if use_moe and batch_size != 1:
        raise ValueError(
            "batch_size pricing is dense-only: the MoE expert busy model "
            "prices per-active-expert-per-token compute at batch 1, so a "
            "batch-N dense half would silently mix batches in one "
            "objective. Pass moe=False to price a MoE profile's dense "
            "slice at batch N, or keep batch_size=1."
        )
    if k_candidates:
        Ks = sorted(set(int(k) for k in k_candidates))
        bad = [k for k in Ks if k <= 0 or model.L % k != 0 or k == model.L]
        if bad:
            raise ValueError(
                f"k candidates must be proper factors of L={model.L}; invalid: {bad}"
            )
    else:
        Ks = valid_factors_of_L(model.L)

    kv_factor = kv_bits_to_factor(kv_bits)
    sets = assign_sets(devs)
    if use_moe:
        # Dense (w/n) costs come from the expert-free adjusted profile; the
        # expert block (y) carries the routed-expert bytes and compute.
        # load_factors re-prices each device's y-units at the realized load
        # of a concrete expert mapping (see solver.routing).
        coeffs = build_coeffs(
            devs, adjust_model(model), kv_factor, sets, batch_size
        )
        arrays = assemble(
            coeffs, moe=build_moe_arrays(devs, model, load_factors=load_factors)
        )
    else:
        coeffs = build_coeffs(devs, model, kv_factor, sets, batch_size)
        arrays = assemble(coeffs)
    return Ks, sets, coeffs, arrays


def halda_solve(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    k_candidates: Optional[Iterable[int]] = None,
    mip_gap: Optional[float] = 1e-4,
    plot: bool = False,
    debug: bool = False,
    kv_bits: str = "8bit",
    backend: Backend = "cpu",
    time_limit: Optional[float] = 3600.0,
    moe: Optional[bool] = None,
    warm: Optional[HALDAResult] = None,
    max_rounds: Optional[int] = None,
    beam: Optional[int] = None,
    ipm_iters: Optional[int] = None,
    ipm_warm_iters: Optional[int] = None,
    node_cap: Optional[int] = None,
    timings: Optional[dict] = None,
    load_factors: Optional[Sequence[float]] = None,
    batch_size: int = 1,
    margin_state: Optional[dict] = None,
    lp_backend: str = "auto",
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
    mesh_shards: Optional[int] = None,
    pdhg_dtype: Optional[str] = None,
    convergence: Optional[dict] = None,
) -> HALDAResult:
    """Pick the best (k, w, n[, y]) placement over all candidate segment counts.

    ``batch_size`` (opt-in, default 1 = reference parity) prices dense
    compute at the profiles' ``b_N`` throughput columns — prefill-heavy
    deployments place against their real batch instead of the decode-style
    batch-1 lookup. Requires the model profile to carry the column
    (``profile_model(batch_sizes=[N, ...])``). Dense formulation only: the
    MoE expert busy model prices per-token at batch 1, so MoE solves reject
    ``batch_size != 1`` rather than mix batches in one objective.

    ``moe=None`` (default) enables expert+layer co-assignment automatically
    when the profile carries MoE component metrics; ``moe=False`` forces the
    dense formulation; ``moe=True`` raises if the metrics are missing. In MoE
    mode the result's ``y`` lists the routed experts hosted per device (see
    ``distilp_tpu.solver.moe`` for the formulation).

    ``warm`` seeds the JAX backend with a previous solve's assignment
    (re-priced exactly under the current profiles) so streaming re-solves
    prune from round one; the CPU backend ignores it (scipy's MILP API has
    no warm-start hook).

    JAX-backend search controls (all ``None`` = problem-class defaults, see
    ``backend_jax.default_search_params``; the CPU backend ignores them):

    - ``max_rounds``: branch-and-bound round budget. Raise it when a solve
      warns that the mip-gap certificate was not met.
    - ``beam``: frontier rows that get an IPM solve per round.
    - ``ipm_iters``: interior-point iterations per LP relaxation (the cold
      root-round budget).
    - ``ipm_warm_iters``: iteration budget of every round after the root —
      those nodes warm-start from their parent's iterate, so the default is
      about half the cold budget; truncation only loosens bounds (worst
      case: more rounds), never the certificate's validity. Set equal to
      ``ipm_iters`` to disable the truncation.
    - ``node_cap``: frontier capacity (overflow floors the certificate).
    - ``lp_backend``: LP relaxation engine — ``'ipm'`` (batched
      interior-point, the small-fleet default), ``'pdhg'`` (matrix-free
      restarted Halpern PDHG, the fleet-scale engine: no factorizations,
      so M=512-4096 fleets fit where the IPM's normal matrices cannot), or
      ``'auto'`` (default; pdhg at or above
      ``backend_jax.PDHG_AUTO_M`` devices). Both engines share the
      warm-start plumbing and the f64 Lagrangian certificate; the chosen
      engine is echoed in ``timings['lp_backend']``.
    - ``pdhg_iters`` / ``pdhg_restart_tol``: first-order budget per LP
      relaxation and the Halpern restart's sufficient-decay factor
      (pdhg engine only; see ``ops/pdhg.py``).
    - ``mesh_shards``: row-partition every PDHG relaxation across this
      many devices (``ops/meshlp.py``; pdhg engine only, default 1 = no
      mesh). On a CPU host the mesh needs
      ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` set before
      the first jax import (``utils.shardcompat``).
    - ``pdhg_dtype``: first-order iterate precision, ``'f32'``/``'f64'``
      (pdhg engine only; None keeps the search default). The mip-gap
      certificate is evaluated in f64 REGARDLESS — a lower iterate
      precision can only loosen bounds or miss certification, never
      corrupt it, and an uncertified f32 solve escalates to f64 on the
      same ladder that escalates budgets.

    ``timings``: pass a dict to receive the JAX backend's wall-clock
    breakdown (build/pack/upload/solve+fetch milliseconds, see
    ``solve_sweep_jax``; ``build_ms`` is the host-side coefficient +
    instance assembly added here).

    ``convergence``: pass a dict (JAX backend) to run the solve with
    solver-interior telemetry on — the per-B&B-round search log and the
    root LP relaxations' per-chunk convergence traces are decoded into it
    (see ``solve_sweep_jax`` and ``obs.convergence.build_search_trace``),
    and a flat ``conv_*`` digest additionally lands in ``timings``. The
    default (None) runs the exact untraced device program; an escalated
    retry re-fills the dict with the final solve's telemetry.

    ``margin_state``: a dict threaded across streaming MoE ticks enabling
    the margin fast path (previous tick's decomposition bounds reused
    under a rigorous host-computed drift margin — see
    ``backend_jax.margin_bounds_from_state``). ``StreamingReplanner``
    manages one automatically; direct callers may pass their own.

    Returns the assignment minimizing the modeled per-round latency, with
    ``certified``/``gap`` reporting the optimality certificate; raises
    ``RuntimeError`` if no candidate k admits a feasible assignment.

    Certification escalation (JAX backend): a dense solve that misses the
    mip-gap certificate while EVERY search knob above is None retries once
    at the MoE-class budget (cap 256 / beam 16 / 26 IPM iterations),
    warm-seeded from the uncertified incumbent, before returning — so
    one-shot callers get the same ladder ``StreamingReplanner`` always
    had, without knowing the knobs. ``timings['escalated']`` reports it;
    passing any explicit budget disables it (the caller owns the
    trade-off). An escalated retry that still misses returns honestly
    uncertified.
    """
    import time as _time

    t0 = _time.perf_counter()
    Ks, sets, coeffs, arrays = _build_instance(
        devs, model, k_candidates, kv_bits, moe, load_factors, batch_size
    )
    if timings is not None:
        timings["build_ms"] = (_time.perf_counter() - t0) * 1e3

    per_k_objs: List[Tuple[int, Optional[float]]] = []
    best: Optional[ILPResult] = None

    if backend == "jax":
        try:
            from .backend_jax import solve_sweep_jax
        except ImportError as e:
            raise NotImplementedError(
                "The JAX backend is not available in this build "
                f"(import failed: {e}); use backend='cpu'."
            ) from e

        # One timings dict always exists internally: the escalation ladder
        # below reads the resolved lp_backend echo out of it even when the
        # caller passed None.
        tm = timings if timings is not None else {}
        results, best = solve_sweep_jax(
            arrays,
            [(k, model.L // k) for k in Ks],
            mip_gap=mip_gap if mip_gap is not None else 1e-4,
            coeffs=coeffs,
            debug=debug,
            warm=_warm_to_ilp(warm),
            max_rounds=max_rounds,
            beam=beam,
            ipm_iters=ipm_iters,
            ipm_warm_iters=ipm_warm_iters,
            node_cap=node_cap,
            timings=tm,
            margin_state=margin_state,
            lp_backend=lp_backend,
            pdhg_iters=pdhg_iters,
            pdhg_restart_tol=pdhg_restart_tol,
            mesh_shards=mesh_shards,
            pdhg_dtype=pdhg_dtype,
            convergence=convergence,
        )
        # In-solver certification escalation (the ladder one-shot callers
        # could never reach while it lived only in StreamingReplanner,
        # VERDICT r5 item 4): a DENSE solve that missed its certificate at
        # the class-default budgets retries ONCE at the MoE-class budget —
        # the largest budget the backend ships — warm-seeded from the
        # uncertified incumbent so the retry prunes from round one. Only
        # when every search knob was left at None: explicit budgets mean
        # the caller owns the trade-off, and the MoE class already runs
        # the full budget (re-running it would just double the cost).
        defaults_used = all(
            v is None
            for v in (
                max_rounds, beam, ipm_iters, ipm_warm_iters, node_cap,
                pdhg_iters,
            )
        )
        if (
            best is not None
            and not best.certified
            and defaults_used
            and arrays.moe is None
        ):
            from .backend_jax import (
                BEAM, IPM_ITERS, MAX_ROUNDS, NODE_CAP, default_pdhg_iters,
            )

            engine = tm.get("lp_backend", "ipm")
            if debug:
                print(
                    f"  escalating: gap {best.gap} uncertified at default "
                    f"budgets; retrying at cap={NODE_CAP} beam={BEAM} "
                    f"engine={engine}"
                )
            # Per-engine escalated budgets: the IPM gets the MoE-class
            # interior-point budget with the warm-iteration truncation
            # disabled (the escalated attempt is the last line of defense
            # before an honest uncertified return, so every IPM round gets
            # the full cold budget); a PDHG solve gets 4x its first-order
            # budget (its knobs are a different unit — 26 Mehrotra steps
            # is never what a first-order escalation means). The 4x is on
            # top of the RESOLVED size-aware default (which already scales
            # with fleet size, see _resolve_search_params) — a flat
            # 4·PDHG_ITERS would be a budget CUT at fleet scale — and its
            # warm rounds derive as a quarter of it (ipm_warm_iters is an
            # IPM knob the pdhg path ignores), i.e. each escalated warm
            # round runs the ORIGINAL full cold budget.
            # The precision rung rides the same ladder: an uncertified f32
            # run retries in f64 — reduced-precision iterates can stall
            # short of the tolerance on hard instances, and the escalated
            # attempt should remove BOTH suspects (budget and precision)
            # before an honest uncertified return.
            esc_kw = (
                {
                    "pdhg_iters": 4 * default_pdhg_iters(len(devs)),
                    "pdhg_dtype": "f64" if pdhg_dtype == "f32" else pdhg_dtype,
                    "mesh_shards": mesh_shards,
                }
                if engine == "pdhg"
                else {"ipm_iters": IPM_ITERS, "ipm_warm_iters": IPM_ITERS}
            )
            results2, best2 = solve_sweep_jax(
                arrays,
                [(k, model.L // k) for k in Ks],
                mip_gap=mip_gap if mip_gap is not None else 1e-4,
                coeffs=coeffs,
                debug=debug,
                warm=best,
                max_rounds=MAX_ROUNDS,
                beam=BEAM,
                node_cap=NODE_CAP,
                timings=tm,
                lp_backend=engine,
                pdhg_restart_tol=pdhg_restart_tol,
                convergence=convergence,
                **esc_kw,
            )
            if best2 is not None:
                results, best = results2, best2
            tm["escalated"] = 1
        for k, res in zip(Ks, results):
            per_k_objs.append((k, res.obj_value if res is not None else None))
            if debug:
                obj = f"{res.obj_value:.6f}" if res is not None else "infeasible"
                print(f"  k={k:<4d}  obj={obj}")
    elif backend == "cpu":
        for k in Ks:
            try:
                res = solve_fixed_k_cpu(
                    arrays, k, model.L // k, time_limit=time_limit, mip_gap=mip_gap
                )
            except Infeasible:
                per_k_objs.append((k, None))
                if debug:
                    print(f"  k={k:<4d}  obj=infeasible")
                continue
            per_k_objs.append((k, res.obj_value))
            if debug:
                print(f"  k={k:<4d}  obj={res.obj_value:.6f}")
            if best is None or res.obj_value < best.obj_value:
                best = res
    else:
        raise ValueError(f"Unknown backend {backend!r}; expected 'cpu' or 'jax'")

    if best is None:
        raise RuntimeError("No feasible MILP found for any k.")

    result = _best_to_result(best, sets)

    if plot:
        from .plotter import plot_k_curve

        plot_k_curve(per_k_objs, k_star=result.k)

    return result


class PendingHalda:
    """An in-flight ``halda_solve`` (JAX backend): dispatched, not fetched.

    ``collect()`` blocks on the device result and returns the HALDAResult.
    Produced by ``halda_solve_async``; the point is overlap — the host can
    build and dispatch the NEXT tick's instance while this one computes
    and its result rides the (slow, on tunneled TPUs) link back.
    """

    def __init__(self, pending, sets):
        self._pending = pending
        self._sets = sets

    def collect(self) -> HALDAResult:
        from .backend_jax import collect_sweep

        _, best = collect_sweep(self._pending)
        if best is None:
            raise RuntimeError("No feasible MILP found for any k.")
        return _best_to_result(best, self._sets)


def halda_solve_async(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    k_candidates: Optional[Iterable[int]] = None,
    mip_gap: Optional[float] = 1e-4,
    kv_bits: str = "8bit",
    moe: Optional[bool] = None,
    warm: Optional[HALDAResult] = None,
    max_rounds: Optional[int] = None,
    beam: Optional[int] = None,
    ipm_iters: Optional[int] = None,
    ipm_warm_iters: Optional[int] = None,
    node_cap: Optional[int] = None,
    load_factors: Optional[Sequence[float]] = None,
    batch_size: int = 1,
    margin_state: Optional[dict] = None,
    lp_backend: str = "auto",
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
    mesh_shards: Optional[int] = None,
    pdhg_dtype: Optional[str] = None,
    convergence: Optional[dict] = None,
) -> PendingHalda:
    """Dispatch a HALDA solve and return without waiting for the result.

    JAX backend only (the CPU oracle has no async substrate). Same
    semantics as ``halda_solve`` otherwise; redeem with ``.collect()``.
    Pipelining warm hints one tick behind (seed tick t+1 with tick t-1's
    collected result) is sound: hints are re-priced exactly on-device, so
    staleness only affects pruning speed, never correctness. The MoE
    margin chain (``margin_state``) works pipelined too: the bound reuse
    is decided at dispatch, the anchor refresh at collect — and so does
    ``convergence``: the telemetry is recorded in-dispatch and decoded
    into the dict when ``.collect()`` redeems the result.
    """
    try:
        from .backend_jax import PendingSweep, solve_sweep_jax
    except ImportError as e:
        raise NotImplementedError(
            "The JAX backend is not available in this build "
            f"(import failed: {e}); use halda_solve(backend='cpu')."
        ) from e

    Ks, sets, coeffs, arrays = _build_instance(
        devs, model, k_candidates, kv_bits, moe, load_factors, batch_size
    )

    pending = solve_sweep_jax(
        arrays,
        [(k, model.L // k) for k in Ks],
        mip_gap=mip_gap if mip_gap is not None else 1e-4,
        coeffs=coeffs,
        warm=_warm_to_ilp(warm),
        max_rounds=max_rounds,
        beam=beam,
        ipm_iters=ipm_iters,
        ipm_warm_iters=ipm_warm_iters,
        node_cap=node_cap,
        collect=False,
        margin_state=margin_state,
        lp_backend=lp_backend,
        pdhg_iters=pdhg_iters,
        pdhg_restart_tol=pdhg_restart_tol,
        mesh_shards=mesh_shards,
        pdhg_dtype=pdhg_dtype,
        convergence=convergence,
    )
    if not isinstance(pending, PendingSweep):
        # Plain (results, None) tuple: structurally infeasible sweep
        # (no k admits W >= M). NB PendingSweep is itself a NamedTuple,
        # so this must be a type check, not an isinstance(..., tuple).
        raise RuntimeError("No feasible MILP found for any k.")
    return PendingHalda(pending, sets)


def _scenarios_via_batchlayout(
    built,
    kWs,
    mip_gap: float,
    warm_ilps,
    *,
    max_rounds,
    beam,
    ipm_iters,
    ipm_warm_iters,
    node_cap,
    lp_backend,
    pdhg_iters,
    pdhg_restart_tol,
    pdhg_dtype=None,
    timings=None,
):
    """Row-scale-crossing fallback for ``halda_solve_scenarios``: one
    packed instance per scenario (each carries its own static half), one
    ``solve_batch`` dispatch. Same ``(per_k_results, best)``-per-scenario
    contract as ``solve_sweep_scenarios``."""
    from .batchlayout import pack_instance, solve_batch

    S = len(built)

    def _mk(warms_l):
        return [
            pack_instance(
                arrays, kWs, mip_gap=mip_gap, coeffs=coeffs,
                warm=warms_l[i], ipm_iters=ipm_iters,
                max_rounds=max_rounds, beam=beam, node_cap=node_cap,
                ipm_warm_iters=ipm_warm_iters, lp_backend=lp_backend,
                pdhg_iters=pdhg_iters, pdhg_restart_tol=pdhg_restart_tol,
                pdhg_dtype=pdhg_dtype,
            )
            for i, (_, _, coeffs, arrays) in enumerate(built)
        ]

    insts = _mk(warm_ilps if warm_ilps is not None else [None] * S)
    if any(inst is None for inst in insts):
        # No structurally feasible k — uniform across scenarios (they
        # share the fleet size and k grid), same early-out shape as the
        # shared-static path.
        return [([None] * len(kWs), None) for _ in range(S)]
    if len({inst.signature for inst in insts}) > 1:
        # Warm hints engaged unevenly across lanes (a mis-shaped or
        # partial seed): drop them everywhere — the same both-or-cold
        # rule the shared-static path applies.
        insts = _mk([None] * S)
        if len({inst.signature for inst in insts}) > 1:
            raise ValueError(
                "scenarios do not share a packed shape family (fleet "
                "size, k grid, or model shape differ across scenarios); "
                "solve them as separate sweeps"
            )
    if timings is not None:
        timings["scenario_fallback"] = 1.0
        timings["lp_backend"] = insts[0].statics["lp_backend"]
    return solve_batch(insts, timings=timings)


def halda_solve_scenarios(
    scenarios: Sequence[Sequence[DeviceProfile]],
    model: ModelProfile,
    k_candidates: Optional[Iterable[int]] = None,
    mip_gap: Optional[float] = 1e-4,
    kv_bits: str = "8bit",
    moe: Optional[bool] = None,
    warms: Optional[Sequence[Optional[HALDAResult]]] = None,
    max_rounds: Optional[int] = None,
    beam: Optional[int] = None,
    ipm_iters: Optional[int] = None,
    ipm_warm_iters: Optional[int] = None,
    node_cap: Optional[int] = None,
    load_factors_list: Optional[Sequence[Optional[Sequence[float]]]] = None,
    timings: Optional[dict] = None,
    batch_size: int = 1,
    lp_backend: str = "auto",
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
    pdhg_dtype: Optional[str] = None,
) -> List[HALDAResult]:
    """Solve S what-if variants of one fleet in a single device dispatch.

    Each scenario is the SAME fleet under different profile drift — e.g.
    candidate t_comm futures from a link forecast, or per-device expert
    load factors for alternative routing regimes. The instances share
    their device-resident static half, so the whole batch costs one
    upload + one dispatch + one fetch: on a tunneled TPU this prices S
    placements at roughly one placement's wire time (JAX backend only).

    Scenarios whose static halves diverge — out-of-class drift (device
    speeds, memory capacities) or a t_comm/load excursion large enough
    to cross a row-scaling threshold — fall back to the multi-instance
    batch layout (``solver.batchlayout``): each scenario packs its OWN
    static half and the batch still runs as one device dispatch, at the
    cost of S static uploads instead of one. Only scenarios that do not
    even share a packed shape family (different fleet size, k grid, or
    model shape) raise ValueError — solve those independently.

    ``warms``/``load_factors_list``: optional per-scenario seeds and MoE
    load factors (one entry each per scenario). Warm hints engage only
    when every scenario provides one. Raises ``RuntimeError`` if any
    scenario admits no feasible placement.
    """
    try:
        from .backend_jax import solve_sweep_scenarios
    except ImportError as e:
        raise NotImplementedError(
            "The JAX backend is not available in this build "
            f"(import failed: {e}); scenario batching needs it."
        ) from e

    S = len(scenarios)
    if S == 0:
        return []
    if load_factors_list is not None and len(load_factors_list) != S:
        raise ValueError("load_factors_list must have one entry per scenario")
    if warms is not None and len(warms) != S:
        raise ValueError("warms must have one entry per scenario")

    built = [
        _build_instance(
            devs, model, k_candidates, kv_bits, moe,
            load_factors_list[i] if load_factors_list is not None else None,
            batch_size,
        )
        for i, devs in enumerate(scenarios)
    ]
    Ks = built[0][0]
    kWs = [(k, model.L // k) for k in Ks]
    gap = mip_gap if mip_gap is not None else 1e-4

    warm_ilps: Optional[List[Optional[ILPResult]]] = None
    if warms is not None:
        warm_ilps = [_warm_to_ilp(w) for w in warms]

    try:
        outs = solve_sweep_scenarios(
            [arrays for _, _, _, arrays in built],
            kWs,
            [coeffs for _, _, coeffs, _ in built],
            mip_gap=gap,
            warms=warm_ilps,
            max_rounds=max_rounds,
            beam=beam,
            ipm_iters=ipm_iters,
            ipm_warm_iters=ipm_warm_iters,
            node_cap=node_cap,
            timings=timings,
            lp_backend=lp_backend,
            pdhg_iters=pdhg_iters,
            pdhg_restart_tol=pdhg_restart_tol,
            pdhg_dtype=pdhg_dtype,
        )
    except ValueError:
        # Static halves diverged — an excursion crossed a row-scale
        # threshold, so the scenarios can no longer share ONE uploaded
        # static blob. They still share a SIGNATURE (same fleet size,
        # k grid, blob layout), which is all the multi-instance batch
        # layout needs: pack each scenario with its OWN static half and
        # solve them as one ``_solve_batched`` dispatch. Costs S static
        # uploads instead of one; still one device dispatch, and the
        # batch serves instead of raising.
        outs = _scenarios_via_batchlayout(
            built, kWs, gap, warm_ilps,
            max_rounds=max_rounds, beam=beam, ipm_iters=ipm_iters,
            ipm_warm_iters=ipm_warm_iters, node_cap=node_cap,
            lp_backend=lp_backend, pdhg_iters=pdhg_iters,
            pdhg_restart_tol=pdhg_restart_tol, pdhg_dtype=pdhg_dtype,
            timings=timings,
        )

    results: List[HALDAResult] = []
    for i, (_, best) in enumerate(outs):
        if best is None:
            raise RuntimeError(f"No feasible MILP found for scenario {i}.")
        results.append(_best_to_result(best, built[i][1]))
    return results


def halda_solve_per_k(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    k_candidates: Optional[Iterable[int]] = None,
    mip_gap: Optional[float] = 1e-4,
    kv_bits: str = "8bit",
    backend: Backend = "jax",
    moe: Optional[bool] = None,
    max_rounds: Optional[int] = None,
    beam: Optional[int] = None,
    ipm_iters: Optional[int] = None,
    ipm_warm_iters: Optional[int] = None,
    node_cap: Optional[int] = None,
    load_factors: Optional[Sequence[float]] = None,
    batch_size: int = 1,
    time_limit: Optional[float] = 3600.0,
    debug: bool = False,
    plot: bool = False,
    timings: Optional[dict] = None,
    lp_backend: str = "auto",
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
    mesh_shards: Optional[int] = None,
    pdhg_dtype: Optional[str] = None,
) -> List[HALDAResult]:
    """Certified optimum for EVERY feasible k.

    ``halda_solve`` answers "what is THE best placement" — losing segment
    counts prune early against the global incumbent and report objectives
    only. This answers the reference CLI's original contract (one solved
    MILP per k, /root/reference/src/distilp/solver/halda_p_solver.py:
    392-412): each k runs under per-k pruning until its own mip-gap
    certificate closes, and comes back as a full ``HALDAResult`` with its
    assignment, certificate, and gap. Use it to inspect the k-curve with
    real placements (capacity planning: "what would k=8 cost me?").

    Structurally infeasible k's (fewer layers per segment than devices) and
    k's proven infeasible by the search are omitted from the returned list.

    ``backend='jax'`` solves the whole family in one device dispatch;
    ``backend='cpu'`` loops the scipy/HiGHS oracle over the k grid (exact
    per-k optima, ``time_limit`` seconds each; the search knobs are JAX
    knobs and are ignored) so ``--per-k`` works on installs without the
    JAX backend.
    """
    Ks, sets, coeffs, arrays = _build_instance(
        devs, model, k_candidates, kv_bits, moe, load_factors, batch_size
    )

    if backend == "cpu":
        out: List[HALDAResult] = []
        for k in Ks:
            try:
                res = solve_fixed_k_cpu(
                    arrays, k, model.L // k, time_limit=time_limit,
                    mip_gap=mip_gap,
                )
            except Infeasible:
                if debug:
                    print(f"  k={k:<4d}  obj=infeasible")
                continue
            if debug:
                print(f"  k={k:<4d}  obj={res.obj_value:.6f}")
            out.append(_best_to_result(res, sets))
        if plot and out:
            from .plotter import plot_k_curve

            plot_k_curve(
                [(r.k, r.obj_value) for r in out],
                k_star=min(out, key=lambda r: r.obj_value).k,
            )
        return out
    if backend != "jax":
        raise ValueError(f"Unknown backend {backend!r}; expected 'cpu' or 'jax'")

    try:
        from .backend_jax import solve_sweep_jax
    except ImportError as e:
        raise NotImplementedError(
            "The JAX backend is not available in this build "
            f"(import failed: {e}); use halda_solve_per_k(backend='cpu')."
        ) from e
    results, _ = solve_sweep_jax(
        arrays,
        [(k, model.L // k) for k in Ks],
        mip_gap=mip_gap if mip_gap is not None else 1e-4,
        coeffs=coeffs,
        max_rounds=max_rounds,
        beam=beam,
        ipm_iters=ipm_iters,
        ipm_warm_iters=ipm_warm_iters,
        node_cap=node_cap,
        debug=debug,
        timings=timings,
        per_k_optima=True,
        lp_backend=lp_backend,
        pdhg_iters=pdhg_iters,
        pdhg_restart_tol=pdhg_restart_tol,
        mesh_shards=mesh_shards,
        pdhg_dtype=pdhg_dtype,
    )
    out = [
        _best_to_result(res, sets)
        for res in results
        if res is not None and res.w is not None
    ]
    if plot and out:
        from .plotter import plot_k_curve

        plot_k_curve(
            [(r.k, r.obj_value) for r in out],
            k_star=min(out, key=lambda r: r.obj_value).k,
        )
    return out

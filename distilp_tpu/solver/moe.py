"""MoE expert+layer co-assignment: the solver extension the reference
advertises but never built.

The reference profiles per-layer expert metrics (bytes_per_expert,
flops_per_expert, router_*, flops_per_active_expert_per_token —
/root/reference/src/distilp/profiler/profiler/model.py:1059-1073, schema
/root/reference/src/distilp/common/model.py:74-85) and its package
description promises "layer/expert assignment"
(/root/reference/pyproject.toml:4), yet ``solve_fixed_k_milp`` consumes only
the dense scalars. This module supplies the missing formulation.

Formulation (new design — there is no reference implementation):

- One integer variable ``y_i`` per device: how many of the ``E`` routed
  experts device i hosts. The split is the SAME for every MoE layer
  (standard expert-parallel sharding: device i owns expert slice
  [offset_i, offset_i + y_i) of each MoE layer), so ``sum_i y_i = E``.
- Expert weights are always resident — they are needed at every MoE layer,
  so unlike pipeline windows they cannot be disk-streamed. Device i's
  primary memory row gains ``eb_i * y_i`` bytes, where
  ``eb_i = (1+rho_w) * bytes_per_expert * n_moe``.
- Compute + dispatch: with uniform routing, device i executes the share
  ``y_i / E`` of every MoE layer's routed-expert FLOPs and receives the same
  share of the all-to-all token dispatch. Per pipeline segment (1/k of the
  layers, hence ``n_moe / k`` MoE layers on average) that adds

      g_i(k) * y_i,   g_i(k) = (n_moe / (k * E)) * (f_exp / s_i + 2 t_comm_i)

  seconds to the device's busy time B_i, where ``f_exp = experts_per_token *
  flops_per_active_expert_per_token`` is the active-expert work of one MoE
  layer and ``s_i`` the device's measured FLOPS. The ``1/k`` makes the busy
  rows k-dependent — the only place the MoE MILP family loses the shared-
  constraint-matrix property (handled by ``MilpArrays.A_ub_for_k``).
- The dense layer costs must not double-count experts: ``adjust_model``
  replaces the typical-layer scalars with the expert-free average layer
  (attention + router + shared experts for MoE layers, the dense scalar for
  dense layers), so ``w`` carries the pipeline-resident part and ``y``
  carries the expert part.

Certification note: the LP root integrality gap on wide-expert instances is
structural (box branch-and-bound alone stalls several percent short of the
optimum HiGHS reaches with cutting planes). The JAX backend closes it with
per-k Lagrangian decomposition root bounds — the coupling constraints
(sum w = W, sum y = E) are dualized and each device's subproblem is solved
exactly over its integer lattice on-device — which certify mip_gap<=1e-3 on
both flagships (Mixtral 8x7B and DeepSeek-V3 E=256 over 32 devices; see
``tests/test_solver_moe.py::test_deepseek_v3_flagship_certified`` and
``backend_jax._decomp_bound_roots``).

Expert pool placement (v2): each device hosts its expert slice in the
memory pool where expert compute is fastest, decided per device at
coefficient-build time:

- split-memory accelerator (CUDA/TPU) whose measured expert throughput
  beats the CPU's: expert bytes charge the VRAM capacity row
  (``eb_vram``) and expert compute uses the accelerator table;
- unified-memory accelerator (Apple Metal): compute at the faster of the
  two tables; bytes charge the unified budget either way (``eb_ram``), and
  when GPU compute wins they additionally charge the Metal working-set row
  (``eb_metal``) — the wired budget can be smaller than the unified one;
- otherwise: CPU table, primary-RAM residency (``eb_ram``).

This is a per-device *static* choice, not a per-expert solver variable: a
fractional ``y_gpu`` split of one device's experts across its two pools is
deliberately out of scope (expert slices are few and large, so the split
granularity buys almost nothing, while the extra integer block would grow
every backend — see git history for the trade study).

Expert residency is HARD-capped: expert weights are needed at every MoE
layer and cannot ride the disk-streaming slack the way pipeline-window
layers can, so the memory rows admit no slack on the ``eb*y`` term — a
fleet that cannot physically hold E experts is reported infeasible instead
of "optimal at a disk penalty" (physically unrealizable).

Dispatch pricing (v3): when the device profile carries the measured link
shape (``comm_latency``/``comm_bandwidth``, from the profiler's timed
collectives), the all-to-all hop is priced as
``2 x (latency + dispatched_bytes / bandwidth)`` — dispatch + combine,
with ``dispatched_bytes = experts_per_token * e_embed * 2`` (each decoded
token's bf16 hidden state shipped to its top-k experts). Profiles without
link terms (hand-written fleets, reference fixtures) fall back to the v2
``2 x t_comm`` scalar, so existing fixtures price identically.

Deliberate simplifications (documented, not hidden):
- The full a2a latency is charged per expert-unit share (inside the 1/E
  factor) rather than once per layer — same structural approximation the
  v2 scalar made; it keeps g linear in y.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..common import DeviceProfile, ModelProfile
from .coeffs import RHO_W, flops_over_flops_per_s


@dataclass
class MoEArrays:
    """Per-device MoE coefficients consumed by the assembler and backends."""

    E: int  # routed experts per MoE layer
    n_moe: int  # MoE layer count
    g_raw: np.ndarray  # (M,) seconds per y-unit per segment, times k
    eb_ram: np.ndarray  # (M,) resident bytes per y-unit in the primary pool
    eb_vram: np.ndarray  # (M,) resident bytes per y-unit in discrete VRAM
    # (M,) bytes per y-unit charged to the Metal working-set row: unified
    # devices whose expert compute elects the GPU table wire their expert
    # slice, so it must fit the (possibly smaller) wired budget too — the
    # unified budget row (eb_ram) alone would miss d_avail_metal < d_avail_ram.
    eb_metal: np.ndarray


def model_has_moe_components(model: ModelProfile) -> bool:
    """True when the profile carries enough MoE detail to co-assign experts."""
    return bool(
        model.is_moe
        and model.n_routed_experts > 0
        and model.total_moe_layers > 0
        and model.bytes_per_expert
        and model.flops_per_active_expert_per_token
    )


def resolve_moe(model: ModelProfile, moe) -> bool:
    """The ONE moe-mode resolution rule: ``None`` auto-detects from the
    profile's component metrics, ``True`` requires them, ``False`` forces
    dense. Shared by the solver instance builder and the twin so a
    placement is always evaluated under the same interpretation it was
    solved with."""
    use_moe = model_has_moe_components(model) if moe is None else bool(moe)
    if use_moe and not model_has_moe_components(model):
        raise ValueError(
            "moe=True requires a profile with MoE component metrics "
            "(bytes_per_expert, flops_per_active_expert_per_token, ...)"
        )
    return use_moe


def _moe_mean(d: Optional[dict], default: float = 0.0) -> float:
    if not d:
        return default
    vals = [float(v) for v in d.values()]
    return float(np.mean(vals)) if vals else default


def adjust_model(model: ModelProfile) -> ModelProfile:
    """Expert-free copy of the profile for the dense (w/n) part of the MILP.

    Typical-layer scalars become the average over ALL real layers of the
    expert-free cost: MoE layers contribute attention + router + shared
    experts; dense layers contribute the original typical scalars. KV/
    architecture fields are untouched (attention is identical either way).
    """
    if not model_has_moe_components(model):
        return model

    L = max(1, model.L)
    n_moe = model.total_moe_layers
    n_dense = max(0, L - n_moe)

    bpe = _moe_mean(model.bytes_per_expert)
    router_b = _moe_mean(model.router_bytes)
    shared_b = _moe_mean(model.bytes_shared_experts)

    # Average attention bytes over MoE layers. moe_layer_indices are 1-based
    # layer numbers; attn_bytes/attn_flops are 0-based length-L lists.
    moe_idx = model.moe_layer_indices or []
    if model.attn_bytes and moe_idx and len(model.attn_bytes) >= max(moe_idx):
        attn_b = float(np.mean([model.attn_bytes[i - 1] for i in moe_idx]))
    else:
        # No component split recorded: subtract the expert block instead.
        attn_b = max(0.0, float(model.b_layer) - model.n_routed_experts * bpe
                     - router_b - shared_b)

    b_moe_nonexp = attn_b + router_b + shared_b
    b_layer_adj = (n_dense * float(model.b_layer) + n_moe * b_moe_nonexp) / L

    # Expert-free FLOPs per batch key: attention + router + shared.
    f_exp_act = (
        model.experts_per_token
        * _moe_mean(model.flops_per_active_expert_per_token)
    )
    f_shared = _moe_mean(model.flops_shared_experts)
    f_router = _moe_mean(model.router_flops)

    f_q_adj = {}
    for bk, f_total in model.f_q.items():
        if (
            model.attn_flops
            and bk in model.attn_flops
            and moe_idx
            and len(model.attn_flops[bk]) >= max(moe_idx)
        ):
            attn_f = float(
                np.mean([model.attn_flops[bk][i - 1] for i in moe_idx])
            )
        else:
            attn_f = max(0.0, float(f_total) - f_exp_act - f_router - f_shared)
        f_moe_nonexp = attn_f + f_router + f_shared
        f_q_adj[bk] = (n_dense * float(f_total) + n_moe * f_moe_nonexp) / L

    return model.model_copy(
        update={"b_layer": int(round(b_layer_adj)), "f_q": f_q_adj}
    )


def build_moe_arrays(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    *,
    rho_w: float = RHO_W,
    load_factors: Optional[Sequence[float]] = None,
    factor_floor: float = 0.05,
) -> MoEArrays:
    """Derive the per-device expert coefficients from an (unadjusted) profile.

    ``load_factors`` (one multiplier per device, default all-1) scales each
    device's busy coefficient ``g_i`` by the realized per-y-unit load of a
    concrete expert->device mapping — the linearization handle of
    load-weighted routing (``solver.routing``). Residency bytes are NOT
    scaled: a hot expert occupies the same memory as a cold one.

    ``factor_floor`` guards the SOLVE pricing against oscillation (see the
    inline comment); evaluation callers that need the un-floored cost of a
    fixed placement (``routing.realized_objective``) pass 0.0.
    """
    if not model_has_moe_components(model):
        raise ValueError("model profile lacks the MoE component metrics")
    if load_factors is not None and len(load_factors) != len(devs):
        raise ValueError("load_factors must have one entry per device")

    M = len(devs)
    E = model.n_routed_experts
    n_moe = model.total_moe_layers
    bpe = _moe_mean(model.bytes_per_expert)
    f_exp = (
        model.experts_per_token
        * _moe_mean(model.flops_per_active_expert_per_token)
    )
    f_dict = {"b_1": f_exp}

    bytes_per_y = (1.0 + rho_w) * bpe * n_moe
    g_raw = np.zeros(M)
    eb_ram = np.full(M, bytes_per_y)
    eb_vram = np.zeros(M)
    eb_metal = np.zeros(M)
    for i, d in enumerate(devs):
        sec_cpu = flops_over_flops_per_s(f_dict, d.scpu, model.Q)
        sec_gpu = flops_over_flops_per_s(f_dict, d.gpu_table(), model.Q)
        has_split_accel = (d.has_tpu and d.d_avail_tpu is not None) or (
            d.has_cuda and d.d_avail_cuda is not None
        )
        # Pool choice (see module docstring). A 0.0 sec means "no table" —
        # never treat it as infinitely fast on either side.
        if d.is_unified_mem and sec_gpu > 0.0:
            use_gpu = sec_cpu == 0.0 or sec_gpu < sec_cpu
            sec = sec_gpu if use_gpu else sec_cpu
            if use_gpu:
                # GPU-resident experts are wired: they must also fit the
                # Metal working-set budget, not only the unified RAM row.
                eb_metal[i] = bytes_per_y
        elif has_split_accel and sec_gpu > 0.0 and (
            sec_gpu < sec_cpu or sec_cpu == 0.0
        ):
            sec = sec_gpu
            eb_ram[i], eb_vram[i] = 0.0, bytes_per_y
        else:
            sec = sec_cpu
        if d.comm_bandwidth > 0:
            # Payload-aware all-to-all: dispatch + combine of one token's
            # top-k expert traffic over the measured link (see module
            # docstring, "Dispatch pricing (v3)").
            a2a_bytes = model.experts_per_token * model.e_embed * 2.0
            a2a = 2.0 * (d.comm_latency + a2a_bytes / d.comm_bandwidth)
        else:
            a2a = 2.0 * d.t_comm
        # Floor the factor: a device whose mapped experts saw zero traffic
        # must not become FREE to host experts (g=0 would let the next tick
        # pile experts there up to memory and oscillate); the default 0.05
        # keeps a cold device cheap without making it a black hole.
        lf = (
            1.0 if load_factors is None
            else max(factor_floor, float(load_factors[i]))
        )
        g_raw[i] = lf * (n_moe / float(E)) * (sec + a2a)
    return MoEArrays(
        E=E, n_moe=n_moe, g_raw=g_raw, eb_ram=eb_ram, eb_vram=eb_vram,
        eb_metal=eb_metal,
    )

"""Fixed-shape MILP assembly for the per-k HALDA subproblem.

Decision vector (N = 7M+1), all integer except z and C:

    x = [ w_0..w_{M-1} | n | s1 | s2 | s3 | t | z | C ]

    w_i  layers assigned to device i                 in [1, W]
    n_i  of those, layers resident on the accelerator in [0, W] (0 w/o GPU)
    s1/s2/s3_i  RAM-overflow slack layers, gated to the device's set
    t_i  VRAM-overflow slack layers, gated on GPU presence
    z_i  pipeline stall seconds (continuous)
    C    steady-state cycle time seconds (continuous)

Constraint rows are emitted at a fixed count (6M inequality + 1 equality) so
every (M, k) instance of one fleet shares a single array shape — that is what
lets the JAX backend vmap the k-sweep and batch branch-and-bound nodes. Rows
that don't apply to a device (no CUDA, no Metal) keep their structural columns
but get a huge RHS, and the variable bounds already pin their variables to 0.

Row layout of A_ub:
    [0,  M)   n_i - w_i <= 0
    [M, 2M)   RAM/unified residency cap per device (set-dependent shape)
    [2M,3M)   CUDA VRAM cap
    [3M,4M)   Metal shared-memory cap
    [4M,5M)   cycle bound:   B_i + z_i - C <= -(xi_i + t_comm_i)
    [5M,6M)   prefetch bound: B_i + F_i - z_i - C <= -(xi_i + t_comm_i)

where B_i is the device busy time (a_i w_i + b_i n_i + disk penalties on the
slacks, plus the constant xi_i + t_comm_i) and F_i = (b'/s_disk_i) w_i the
disk prefetch time for the next window.

Parity: constraint set and objective match the reference MILP
(/root/reference/src/distilp/solver/halda_p_solver.py:59-366); the golden
fixture objectives pin the numerics.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .coeffs import HaldaCoeffs

# RHS standing in for "row inactive" — far beyond any byte count in a profile.
INACTIVE_RHS = 1e30


@dataclass(frozen=True)
class VarLayout:
    """Index helpers into the decision vector."""

    M: int

    @property
    def n_vars(self) -> int:
        return 7 * self.M + 1

    def w(self, i: int) -> int:
        return i

    def n(self, i: int) -> int:
        return self.M + i

    def s1(self, i: int) -> int:
        return 2 * self.M + i

    def s2(self, i: int) -> int:
        return 3 * self.M + i

    def s3(self, i: int) -> int:
        return 4 * self.M + i

    def t(self, i: int) -> int:
        return 5 * self.M + i

    def z(self, i: int) -> int:
        return 6 * self.M + i

    @property
    def C(self) -> int:
        return 7 * self.M


@dataclass
class MilpArrays:
    """The k-independent dense arrays of one HALDA instance.

    Only ``b_eq`` (= W) and the variable upper bounds scale with k; everything
    else is shared across the whole k-sweep.
    """

    layout: VarLayout
    A_ub: np.ndarray  # (6M, N)
    b_ub: np.ndarray  # (6M,)
    A_eq: np.ndarray  # (1, N)
    c_base: np.ndarray  # (N,) objective without the k-dependent C coefficient
    integrality: np.ndarray  # (N,) 1 = integer, 0 = continuous
    # Per-variable bound templates: lb fixed; ub is ub_scale * W + ub_const,
    # with np.inf marking unbounded (z, C).
    lb: np.ndarray
    ub_scale: np.ndarray
    ub_const: np.ndarray
    obj_const: float  # additive constant: sum t_comm + sum xi + kappa

    def bounds_for_k(self, W: int) -> tuple[np.ndarray, np.ndarray]:
        ub = self.ub_scale * float(W) + self.ub_const
        return self.lb.copy(), ub

    def c_for_k(self, k: int) -> np.ndarray:
        c = self.c_base.copy()
        c[self.layout.C] = float(k - 1)
        return c


def assemble(coeffs: HaldaCoeffs) -> MilpArrays:
    """Emit the fixed-shape arrays for one (devices, model, kv_factor) instance."""
    M = coeffs.M
    lay = VarLayout(M)
    N = lay.n_vars

    A_ub = np.zeros((6 * M, N))
    b_ub = np.zeros(6 * M)
    bp = coeffs.bprime

    # Per-device slack penalty coefficients reused by busy rows and objective.
    # The slack's disk penalty depends on which slack it is, not on the device
    # set, because bounds already pin out-of-set slacks to zero.
    pen = {
        "s1": coeffs.pen_m1,
        "s2": coeffs.pen_m2,
        "s3": coeffs.pen_m3,
        "t": coeffs.pen_vram,
    }

    for i in range(M):
        # --- accelerator-count row: n_i <= w_i ---
        r = i
        A_ub[r, lay.n(i)] = 1.0
        A_ub[r, lay.w(i)] = -1.0
        b_ub[r] = 0.0

        # --- RAM residency row ---
        r = M + i
        A_ub[r, lay.w(i)] = bp
        if coeffs.ram_minus_n[i]:
            A_ub[r, lay.n(i)] = -bp
        sid = int(coeffs.set_id[i])
        slack_col = {1: lay.s1, 2: lay.s2, 3: lay.s3}[sid](i)
        A_ub[r, slack_col] = -bp
        b_ub[r] = coeffs.ram_rhs[i] if np.isfinite(coeffs.ram_rhs[i]) else INACTIVE_RHS

        # --- CUDA VRAM row ---
        r = 2 * M + i
        A_ub[r, lay.n(i)] = bp
        A_ub[r, lay.t(i)] = -bp
        b_ub[r] = coeffs.cuda_rhs[i] if coeffs.cuda_row[i] else INACTIVE_RHS

        # --- Metal shared-memory row ---
        r = 3 * M + i
        A_ub[r, lay.n(i)] = bp
        A_ub[r, lay.t(i)] = -bp
        b_ub[r] = coeffs.metal_rhs[i] if coeffs.metal_row[i] else INACTIVE_RHS

        # --- busy time B_i (shared by the two cycle rows) ---
        busy = np.zeros(N)
        busy[lay.w(i)] = coeffs.a[i]
        busy[lay.n(i)] = coeffs.b_gpu[i]
        busy[lay.s1(i)] = pen["s1"][i]
        busy[lay.s2(i)] = pen["s2"][i]
        busy[lay.s3(i)] = pen["s3"][i]
        busy[lay.t(i)] = pen["t"][i]
        busy_const = coeffs.busy_const[i]

        # --- cycle bound: B_i + const + z_i <= C ---
        r = 4 * M + i
        A_ub[r] = busy
        A_ub[r, lay.z(i)] += 1.0
        A_ub[r, lay.C] -= 1.0
        b_ub[r] = -busy_const

        # --- prefetch bound: z_i >= F_i - (C - B_i - const) ---
        r = 5 * M + i
        A_ub[r] = busy
        A_ub[r, lay.w(i)] += bp / coeffs.s_disk[i]
        A_ub[r, lay.z(i)] -= 1.0
        A_ub[r, lay.C] -= 1.0
        b_ub[r] = -busy_const

    # --- equality: sum w_i = W ---
    A_eq = np.zeros((1, N))
    A_eq[0, : M] = 1.0

    # --- objective (C coefficient filled per k) ---
    c = np.zeros(N)
    c[:M] = coeffs.a
    c[M : 2 * M] = coeffs.b_gpu
    for name, sl in (("s1", lay.s1), ("s2", lay.s2), ("s3", lay.s3), ("t", lay.t)):
        for i in range(M):
            c[sl(i)] = pen[name][i]

    integrality = np.ones(N, dtype=np.int64)
    integrality[6 * M :] = 0  # z and C continuous

    # --- bounds templates ---
    lb = np.zeros(N)
    ub_scale = np.zeros(N)
    ub_const = np.zeros(N)

    lb[:M] = 1.0  # every device gets at least one layer
    ub_scale[:M] = 1.0  # w <= W
    ub_scale[M : 2 * M] = coeffs.has_gpu.astype(float)  # n <= W or 0
    for sid, sl in ((1, lay.s1), (2, lay.s2), (3, lay.s3)):
        for i in range(M):
            ub_scale[sl(i)] = 1.0 if int(coeffs.set_id[i]) == sid else 0.0
    ub_scale[5 * M : 6 * M] = coeffs.has_gpu.astype(float)  # t
    ub_const[6 * M :] = np.inf  # z, C unbounded above

    return MilpArrays(
        layout=lay,
        A_ub=A_ub,
        b_ub=b_ub,
        A_eq=A_eq,
        c_base=c,
        integrality=integrality,
        lb=lb,
        ub_scale=ub_scale,
        ub_const=ub_const,
        obj_const=coeffs.obj_const,
    )

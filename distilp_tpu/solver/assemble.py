"""Fixed-shape MILP assembly for the per-k HALDA subproblem.

Decision vector (N = 7M+1 dense, 8M+1 with MoE co-assignment), all integer
except z and C:

    x = [ w_0..w_{M-1} | n | (y) | s1 | s2 | s3 | t | z | C ]

    w_i  layers assigned to device i                 in [1, W]
    n_i  of those, layers resident on the accelerator in [0, W] (0 w/o GPU)
    y_i  routed experts hosted per MoE layer          in [0, E] (MoE mode)
    s1/s2/s3_i  RAM-overflow slack layers, gated to the device's set
    t_i  VRAM-overflow slack layers, gated on GPU presence
    z_i  pipeline stall seconds (continuous)
    C    steady-state cycle time seconds (continuous)

Constraint rows are emitted at a fixed count (6M inequality + 1 equality
dense; 8M + 2 with MoE) so every (M, k) instance of one fleet shares a
single array shape —
that is what lets the JAX backend vmap the k-sweep and batch branch-and-bound
nodes. Rows that don't apply to a device (no CUDA, no Metal) keep their
structural columns but get a huge RHS, and the variable bounds already pin
their variables to 0.

Row layout of A_ub:
    [0,  M)   n_i - w_i <= 0
    [M, 2M)   RAM/unified residency cap per device (set-dependent shape;
              MoE mode adds eb_ram_i * y_i resident expert bytes)
    [2M,3M)   CUDA VRAM cap (MoE mode adds eb_vram_i * y_i)
    [3M,4M)   Metal shared-memory cap (MoE mode adds eb_metal_i * y_i for
              unified devices whose expert compute elects the GPU table)
    [4M,5M)   cycle bound:   B_i + z_i - C <= -(xi_i + t_comm_i)
    [5M,6M)   prefetch bound: B_i + F_i - z_i - C <= -(xi_i + t_comm_i)
    [6M,7M)   (MoE only) s_i - w_i <= 0: a device cannot stream more layers
              than it hosts. Dense mode satisfies this automatically (the
              RAM violation is at most b'*w_i), but expert bytes would
              otherwise ride the layer slack; algebraically s_i <= w_i
              forces eb_ram*y to fit in physical capacity.
    [7M,8M)   (MoE only) t_i - n_i <= 0, same for the VRAM slack: forces
              eb_vram*y to fit in VRAM.

where B_i is the device busy time (a_i w_i + b_i n_i + disk penalties on the
slacks, plus the constant xi_i + t_comm_i — and, in MoE mode, the expert
share (g_raw_i / k) y_i) and F_i = (b'/s_disk_i) w_i the disk prefetch time
for the next window. Expert weights are always resident, so they appear in
the memory rows but never in F_i.

The MoE busy coefficient g_raw_i / k is the one k-DEPENDENT entry of the
constraint matrix (a segment covers n_moe/k MoE layers); ``A_ub_for_k``
materializes the per-k matrix. The dense mode keeps A fully k-independent.

Parity: the dense constraint set and objective match the reference MILP
(/root/reference/src/distilp/solver/halda_p_solver.py:59-366); the golden
fixture objectives pin the numerics. The MoE block is new design — see
``distilp_tpu.solver.moe`` for the formulation rationale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from .coeffs import HaldaCoeffs
from .moe import MoEArrays

# RHS standing in for "row inactive" — far beyond any byte count in a profile.
INACTIVE_RHS = 1e30


@dataclass(frozen=True)
class VarLayout:
    """Index helpers into the decision vector. ``moe`` inserts the y block
    after n and shifts everything behind it by M."""

    M: int
    moe: bool = False

    @property
    def ny(self) -> int:
        return self.M if self.moe else 0

    @property
    def n_vars(self) -> int:
        return 7 * self.M + self.ny + 1

    @property
    def n_eq(self) -> int:
        return 2 if self.moe else 1

    def w(self, i: int) -> int:
        return i

    def n(self, i: int) -> int:
        return self.M + i

    def y(self, i: int) -> int:
        if not self.moe:
            raise IndexError("y block only exists in MoE mode")
        return 2 * self.M + i

    def s1(self, i: int) -> int:
        return 2 * self.M + self.ny + i

    def s2(self, i: int) -> int:
        return 3 * self.M + self.ny + i

    def s3(self, i: int) -> int:
        return 4 * self.M + self.ny + i

    def t(self, i: int) -> int:
        return 5 * self.M + self.ny + i

    def z(self, i: int) -> int:
        return 6 * self.M + self.ny + i

    @property
    def z0(self) -> int:
        return 6 * self.M + self.ny

    @property
    def C(self) -> int:
        return 7 * self.M + self.ny


@dataclass
class MilpArrays:
    """The k-independent dense arrays of one HALDA instance.

    Only ``b_eq``'s W entry, the variable upper bounds, the objective's C
    coefficient, and (MoE mode) the y busy coefficients scale with k;
    everything else is shared across the whole k-sweep.
    """

    layout: VarLayout
    A_ub: np.ndarray  # (6M, N) — y busy coefficients left at 0 (k-dependent)
    b_ub: np.ndarray  # (6M,)
    A_eq: np.ndarray  # (n_eq, N)
    c_base: np.ndarray  # (N,) objective without the k-dependent coefficients
    integrality: np.ndarray  # (N,) 1 = integer, 0 = continuous
    # Per-variable bound templates: lb fixed; ub is ub_scale * W + ub_const,
    # with np.inf marking unbounded (z, C).
    lb: np.ndarray
    ub_scale: np.ndarray
    ub_const: np.ndarray
    obj_const: float  # additive constant: sum t_comm + sum xi + kappa
    moe: Optional[MoEArrays] = None

    def bounds_for_k(self, W: int) -> tuple[np.ndarray, np.ndarray]:
        ub = self.ub_scale * float(W) + self.ub_const
        return self.lb.copy(), ub

    def c_for_k(self, k: int) -> np.ndarray:
        c = self.c_base.copy()
        c[self.layout.C] = float(k - 1)
        if self.moe is not None:
            lay = self.layout
            for i in range(lay.M):
                c[lay.y(i)] = self.moe.g_raw[i] / float(k)
        return c

    def A_ub_for_k(self, k: int) -> np.ndarray:
        """The inequality matrix at one k (fills the y busy coefficients)."""
        if self.moe is None:
            return self.A_ub
        A = self.A_ub.copy()
        lay = self.layout
        M = lay.M
        for i in range(M):
            g_k = self.moe.g_raw[i] / float(k)
            A[4 * M + i, lay.y(i)] = g_k  # cycle row
            A[5 * M + i, lay.y(i)] = g_k  # prefetch row (contains B_i too)
        return A

    def b_eq_for_k(self, W: int) -> np.ndarray:
        if self.moe is None:
            return np.array([float(W)])
        return np.array([float(W), float(self.moe.E)])


def assemble(coeffs: HaldaCoeffs, moe: Optional[MoEArrays] = None) -> MilpArrays:
    """Emit the fixed-shape arrays for one (devices, model, kv_factor) instance."""
    M = coeffs.M
    lay = VarLayout(M, moe=moe is not None)
    N = lay.n_vars

    n_rows = 8 * M if moe is not None else 6 * M
    A_ub = np.zeros((n_rows, N))
    b_ub = np.zeros(n_rows)
    bp = coeffs.bprime

    # Per-device slack penalty coefficients reused by busy rows and objective.
    # The slack's disk penalty depends on which slack it is, not on the device
    # set, because bounds already pin out-of-set slacks to zero.
    pen = {
        "s1": coeffs.pen_m1,
        "s2": coeffs.pen_m2,
        "s3": coeffs.pen_m3,
        "t": coeffs.pen_vram,
    }

    for i in range(M):
        # --- accelerator-count row: n_i <= w_i ---
        r = i
        A_ub[r, lay.n(i)] = 1.0
        A_ub[r, lay.w(i)] = -1.0
        b_ub[r] = 0.0

        # --- RAM residency row ---
        r = M + i
        A_ub[r, lay.w(i)] = bp
        if coeffs.ram_minus_n[i]:
            A_ub[r, lay.n(i)] = -bp
        if moe is not None:
            A_ub[r, lay.y(i)] = moe.eb_ram[i]  # resident expert bytes
        sid = int(coeffs.set_id[i])
        slack_col = {1: lay.s1, 2: lay.s2, 3: lay.s3}[sid](i)
        A_ub[r, slack_col] = -bp
        b_ub[r] = coeffs.ram_rhs[i] if np.isfinite(coeffs.ram_rhs[i]) else INACTIVE_RHS

        # --- CUDA VRAM row (VRAM-resident experts charge it in MoE mode) ---
        r = 2 * M + i
        A_ub[r, lay.n(i)] = bp
        if moe is not None:
            A_ub[r, lay.y(i)] = moe.eb_vram[i]
        A_ub[r, lay.t(i)] = -bp
        b_ub[r] = coeffs.cuda_rhs[i] if coeffs.cuda_row[i] else INACTIVE_RHS

        # --- Metal shared-memory row (wired expert slices charge it too) ---
        r = 3 * M + i
        A_ub[r, lay.n(i)] = bp
        if moe is not None:
            A_ub[r, lay.y(i)] = moe.eb_metal[i]
        A_ub[r, lay.t(i)] = -bp
        b_ub[r] = coeffs.metal_rhs[i] if coeffs.metal_row[i] else INACTIVE_RHS

        # --- busy time B_i (shared by the two cycle rows; y filled per k) ---
        busy = np.zeros(N)
        busy[lay.w(i)] = coeffs.a[i]
        busy[lay.n(i)] = coeffs.b_gpu[i]
        busy[lay.s1(i)] = pen["s1"][i]
        busy[lay.s2(i)] = pen["s2"][i]
        busy[lay.s3(i)] = pen["s3"][i]
        busy[lay.t(i)] = pen["t"][i]
        busy_const = coeffs.busy_const[i]

        # --- cycle bound: B_i + const + z_i <= C ---
        r = 4 * M + i
        A_ub[r] = busy
        A_ub[r, lay.z(i)] += 1.0
        A_ub[r, lay.C] -= 1.0
        b_ub[r] = -busy_const

        # --- prefetch bound: z_i >= F_i - (C - B_i - const) ---
        r = 5 * M + i
        A_ub[r] = busy
        A_ub[r, lay.w(i)] += bp / coeffs.s_disk[i]
        A_ub[r, lay.z(i)] -= 1.0
        A_ub[r, lay.C] -= 1.0
        b_ub[r] = -busy_const

        # --- MoE hard caps: s_i <= w_i and t_i <= n_i (see row layout) ---
        if moe is not None:
            r = 6 * M + i
            A_ub[r, slack_col] = 1.0
            A_ub[r, lay.w(i)] = -1.0
            r = 7 * M + i
            A_ub[r, lay.t(i)] = 1.0
            A_ub[r, lay.n(i)] = -1.0

    # --- equalities: sum w_i = W; MoE mode adds sum y_i = E ---
    A_eq = np.zeros((lay.n_eq, N))
    A_eq[0, :M] = 1.0
    if moe is not None:
        A_eq[1, 2 * M : 3 * M] = 1.0

    # --- objective (k-dependent coefficients filled per k) ---
    c = np.zeros(N)
    c[:M] = coeffs.a
    c[M : 2 * M] = coeffs.b_gpu
    for name, sl in (("s1", lay.s1), ("s2", lay.s2), ("s3", lay.s3), ("t", lay.t)):
        for i in range(M):
            c[sl(i)] = pen[name][i]

    integrality = np.ones(N, dtype=np.int64)
    integrality[lay.z0 :] = 0  # z and C continuous

    # --- bounds templates ---
    lb = np.zeros(N)
    ub_scale = np.zeros(N)
    ub_const = np.zeros(N)

    lb[:M] = 1.0  # every device gets at least one layer
    ub_scale[:M] = 1.0  # w <= W
    ub_scale[M : 2 * M] = coeffs.has_gpu.astype(float)  # n <= W or 0
    if moe is not None:
        ub_const[2 * M : 3 * M] = float(moe.E)  # y <= E (k-independent)
    for sid, sl in ((1, lay.s1), (2, lay.s2), (3, lay.s3)):
        for i in range(M):
            in_set = int(coeffs.set_id[i]) == sid
            # Slack counts disk-streamed pipeline-window LAYERS, so its cap
            # is W in MoE mode too: expert weights are needed at every MoE
            # layer and cannot stream, so eb*y gets no slack — a fleet that
            # cannot hold E experts is infeasible, not "optimal at a disk
            # penalty" it could never realize.
            ub_scale[sl(i)] = 1.0 if in_set else 0.0
    for i in range(M):
        ub_scale[lay.t(i)] = 1.0 if coeffs.has_gpu[i] else 0.0
    ub_const[lay.z0 :] = np.inf  # z, C unbounded above

    return MilpArrays(
        layout=lay,
        A_ub=A_ub,
        b_ub=b_ub,
        A_eq=A_eq,
        c_base=c,
        integrality=integrality,
        lb=lb,
        ub_scale=ub_scale,
        ub_const=ub_const,
        obj_const=coeffs.obj_const,
        moe=moe,
    )

"""Typed fleet-churn events and the JSONL trace wire format.

An event is one line of JSON with a ``kind`` discriminator; a trace is a
file of them, applied in order. Two classes matter to the scheduler:

- **structural** events (``join``/``leave``/``model_swap``) change the
  fleet or model *identity* — the placement problem's shape — and route to
  a (possibly pool-warmed) re-solve under a new warm-pool key;
- **drift** events (``degrade``/``load``) perturb coefficients of the SAME
  problem shape — t_comm, link bandwidth, memory headroom, expert loads —
  and ride warm (dense) or margin (MoE) ticks on the pooled replanner.

The split mirrors what the solver itself distinguishes: a shape change
invalidates the warm incumbent (``StreamingReplanner`` re-solves cold),
pure coefficient drift is exactly what warm re-pricing and the margin fast
path were built for (see ``solver.streaming``).
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Annotated, Dict, List, Literal, Optional, Sequence, Union

from pydantic import BaseModel, Field, TypeAdapter

from ..common import DeviceProfile, ModelProfile

STRUCTURAL_KINDS = frozenset({"join", "leave", "model_swap"})
DRIFT_KINDS = frozenset({"degrade", "load"})


class DeviceJoin(BaseModel):
    """A device enters the fleet (carries its full measured profile)."""

    kind: Literal["join"] = "join"
    t: float = 0.0  # trace time, seconds (monotone but not wall-clock)
    device: DeviceProfile


class DeviceLeave(BaseModel):
    """A device drops out of the fleet, by name."""

    kind: Literal["leave"] = "leave"
    t: float = 0.0
    name: str


class DeviceDegrade(BaseModel):
    """Coefficient drift on one device: link and/or memory degradation.

    Multiplicative, so repeated events compound — a gradual-decay scenario
    is a stream of small ``t_comm_scale > 1`` degrades. ``mem_scale``
    shrinks (or restores) every memory pool the device advertises; for a
    MoE fleet that breaks the margin fast path's exact-match gate, forcing
    a full bound re-evaluation — i.e. a re-certification — by design.
    """

    kind: Literal["degrade"] = "degrade"
    t: float = 0.0
    name: str
    t_comm_scale: float = 1.0  # multiplies t_comm (per-round link time)
    bandwidth_scale: float = 1.0  # multiplies comm_bandwidth (bytes/s)
    mem_scale: float = 1.0  # multiplies d_avail_ram / d_avail_{cuda,metal,tpu}


class ModelSwap(BaseModel):
    """The served model changes (carries the full new profile)."""

    kind: Literal["model_swap"] = "model_swap"
    t: float = 0.0
    model: ModelProfile


class LoadTick(BaseModel):
    """Periodic load refresh: router statistics and/or per-device jitter.

    ``expert_loads`` replaces the model's measured expert popularity (MoE
    profiles; ignored by dense models). ``t_comm_jitter`` multiplies the
    named devices' t_comm — the "load changed the network" channel that
    keeps dense ticks honest too.
    """

    kind: Literal["load"] = "load"
    t: float = 0.0
    expert_loads: Optional[List[float]] = None
    t_comm_jitter: Dict[str, float] = Field(default_factory=dict)


FleetEvent = Annotated[
    Union[DeviceJoin, DeviceLeave, DeviceDegrade, ModelSwap, LoadTick],
    Field(discriminator="kind"),
]

_EVENT_ADAPTER: TypeAdapter = TypeAdapter(FleetEvent)


def is_structural(event) -> bool:
    """Whether the event changes the placement problem's shape/identity."""
    return event.kind in STRUCTURAL_KINDS


def event_from_dict(data: dict):
    """Validate one wire dict into its typed event (discriminated on kind)."""
    return _EVENT_ADAPTER.validate_python(data)


def write_trace(path: str | Path, events: Sequence) -> None:
    """Write events as JSONL, one compact object per line."""
    with open(path, "w") as f:
        for ev in events:
            # exclude_defaults keeps profile-heavy events readable; the
            # discriminator must survive it (it IS a default) or the line
            # cannot be re-validated.
            data = ev.model_dump(exclude_defaults=True)
            data["kind"] = ev.kind
            f.write(json.dumps(data) + "\n")


def read_trace(path: str | Path) -> List:
    """Load a JSONL trace back into typed events (blank lines skipped)."""
    events = []
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(event_from_dict(json.loads(line)))
    return events


# -- input validation: the quarantine gate ---------------------------------
#
# Pydantic accepts float('nan')/inf in float fields, so a NaN-poisoned
# profile or a contradictory scale survives schema validation and would
# reach the solver's coefficient builders, where one non-finite entry
# poisons every bound in the sweep. The scheduler calls validate_event()
# on every event BEFORE mutating its fleet; a non-None return quarantines
# the event (counted, recorded, fleet untouched).


def non_finite_path(value, path: str = "") -> Optional[str]:
    """Dotted path of the first non-finite float inside a dumped payload.

    Walks dicts/lists/tuples of plain JSON-able values (the shape
    ``model_dump()`` produces); bools are ints in Python and always fine.
    """
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        if not math.isfinite(value):
            return path or "<value>"
        return None
    if isinstance(value, dict):
        for k, v in value.items():
            hit = non_finite_path(v, f"{path}.{k}" if path else str(k))
            if hit is not None:
                return hit
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            hit = non_finite_path(v, f"{path}[{i}]")
            if hit is not None:
                return hit
    return None


def validate_event(event) -> Optional[str]:
    """Reason this event must be quarantined, or None when it is sane.

    Catches what the pydantic schema cannot: non-finite floats anywhere in
    the payload and contradictory values (non-positive multiplicative
    scales, empty/degenerate load vectors). Structural contradictions
    against the LIVE fleet (leave of an unknown device, duplicate join)
    are ``FleetState.apply``'s job — it raises, and the scheduler treats
    that raise as a quarantine too.
    """
    if isinstance(event, DeviceDegrade):
        for fld in ("t_comm_scale", "bandwidth_scale", "mem_scale"):
            v = getattr(event, fld)
            if not math.isfinite(v):
                return f"degrade.{fld} is non-finite ({v!r})"
            if v <= 0 and fld != "mem_scale":
                return f"degrade.{fld} must be > 0 (got {v!r})"
        if event.mem_scale < 0:
            return f"degrade.mem_scale must be >= 0 (got {event.mem_scale!r})"
    elif isinstance(event, LoadTick):
        for name, f in event.t_comm_jitter.items():
            if not math.isfinite(f) or f <= 0:
                return f"load.t_comm_jitter[{name!r}] invalid ({f!r})"
        if event.expert_loads is not None:
            if not event.expert_loads:
                return "load.expert_loads is empty"
            for i, v in enumerate(event.expert_loads):
                if not math.isfinite(v) or v < 0:
                    return f"load.expert_loads[{i}] invalid ({v!r})"
            if sum(event.expert_loads) <= 0:
                return "load.expert_loads sums to zero"
    elif isinstance(event, DeviceJoin):
        if not event.device.name:
            return "join carries an unnamed device"
        hit = non_finite_path(event.device.model_dump())
        if hit is not None:
            return f"join.device.{hit} is non-finite"
    elif isinstance(event, ModelSwap):
        if event.model.L <= 0:
            return f"model_swap.model.L must be > 0 (got {event.model.L})"
        hit = non_finite_path(event.model.model_dump())
        if hit is not None:
            return f"model_swap.model.{hit} is non-finite"
    elif isinstance(event, DeviceLeave):
        if not event.name:
            return "leave names no device"
    return None

"""Mutable fleet snapshot: the state a scheduler owns and events mutate.

Devices live in an insertion-ordered dict (placement order is device
order — the solver's pipeline rings follow it), the model rides alongside,
and two digests name the CURRENT placement problem's identity:

- ``fleet_digest``  — device names in order (shape identity: who is in the
  ring, in what order). Drift events mutate coefficients, not the digest.
- ``model_digest``  — the model's architecture scalars.

The (fleet_digest, model_digest) pair is the scheduler's warm-pool key: a
fleet+model identity seen before gets its warm ``StreamingReplanner`` back
(stale warm hints are sound — they are re-priced exactly on-device), a new
identity starts cold.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List

from ..common import DeviceProfile, ModelProfile
from .events import (
    DeviceDegrade,
    DeviceJoin,
    DeviceLeave,
    LoadTick,
    ModelSwap,
    is_structural,
)


class FleetState:
    """Ordered device map + current model, with event application."""

    def __init__(self, devices: List[DeviceProfile], model: ModelProfile):
        if not devices:
            raise ValueError("a fleet needs at least one device")
        self.devices: Dict[str, DeviceProfile] = {}
        for d in devices:
            dev = d.model_copy(deep=True)
            if dev.name in self.devices:
                raise ValueError(f"duplicate device name {dev.name!r}")
            self.devices[dev.name] = dev
        self.model: ModelProfile = model.model_copy(deep=True)
        self.seq: int = 0  # events applied so far
        self._ensure_head()

    # -- identity ---------------------------------------------------------

    def device_list(self) -> List[DeviceProfile]:
        """The live device ring, in placement order."""
        return list(self.devices.values())

    def fleet_digest(self) -> str:
        """Shape identity: device names in ring order (drift-invariant)."""
        h = hashlib.sha1("|".join(self.devices).encode())
        return h.hexdigest()[:16]

    def model_digest(self) -> str:
        """Model identity: architecture scalars, not drifting loads."""
        m = self.model
        key = (
            f"{m.L}:{m.V}:{m.e_embed}:{m.hk}:{m.ek}:{m.hv}:{m.ev}:{m.n_kv}:"
            f"{m.b_layer}:{m.b_in}:{m.b_out}:{m.Q}:{m.is_moe}:"
            f"{m.n_routed_experts}:{m.experts_per_token}"
        )
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def key(self) -> tuple:
        return (self.fleet_digest(), self.model_digest())

    # -- event application ------------------------------------------------

    def apply(self, event) -> bool:
        """Mutate the snapshot under one event; True iff it was structural.

        Raises ``ValueError`` on events naming unknown devices, removing
        the last device, or re-joining a live name — a trace that does any
        of these is malformed, and silently skipping would let a replay
        diverge from the trace it claims to reproduce.
        """
        if isinstance(event, DeviceJoin):
            dev = event.device.model_copy(deep=True)
            if not dev.name:
                raise ValueError("join event carries an unnamed device")
            if dev.name in self.devices:
                raise ValueError(f"device {dev.name!r} is already in the fleet")
            dev.is_head = False  # the ring already has a head
            self.devices[dev.name] = dev
        elif isinstance(event, DeviceLeave):
            if event.name not in self.devices:
                raise ValueError(f"leave of unknown device {event.name!r}")
            if len(self.devices) == 1:
                raise ValueError("cannot remove the last device in the fleet")
            self.devices.pop(event.name)
            self._ensure_head()
        elif isinstance(event, ModelSwap):
            self.model = event.model.model_copy(deep=True)
        elif isinstance(event, DeviceDegrade):
            dev = self.devices.get(event.name)
            if dev is None:
                raise ValueError(f"degrade of unknown device {event.name!r}")
            dev.t_comm = max(0.0, dev.t_comm * event.t_comm_scale)
            if dev.comm_bandwidth:
                dev.comm_bandwidth *= event.bandwidth_scale
            if event.mem_scale != 1.0:
                s = max(0.0, event.mem_scale)
                dev.d_avail_ram = int(dev.d_avail_ram * s)
                for pool in ("d_avail_cuda", "d_avail_metal", "d_avail_tpu"):
                    cap = getattr(dev, pool)
                    if cap is not None:
                        setattr(dev, pool, int(cap * s))
        elif isinstance(event, LoadTick):
            # Check-THEN-mutate: the scheduler's quarantine path promises a
            # rejected event left the fleet untouched, so every jitter name
            # must be validated before the first t_comm (or expert_loads)
            # write — raising halfway through would leave a partially
            # applied event behind a "fleet untouched" record.
            unknown = [n for n in event.t_comm_jitter if n not in self.devices]
            if unknown:
                raise ValueError(
                    f"load jitter on unknown device {unknown[0]!r}"
                )
            if event.expert_loads is not None:
                self.model.expert_loads = list(event.expert_loads)
            for name, factor in event.t_comm_jitter.items():
                self.devices[name].t_comm = max(
                    0.0, self.devices[name].t_comm * factor
                )
        else:
            raise TypeError(f"not a fleet event: {type(event).__name__}")
        self.seq += 1
        return is_structural(event)

    # -- solvability guard (the quarantine gate's second layer) -----------

    # Scalar fields the coefficient builders consume directly: one NaN here
    # poisons every bound of the sweep. The per-tick check scans ONLY these
    # scalars (not the throughput tables — those enter via join/model_swap
    # events, whose full payloads are walked once at event validation), so
    # it is O(M) cheap enough to run unconditionally.
    _SCALAR_FIELDS = (
        "t_comm", "comm_latency", "comm_bandwidth", "T_cpu", "s_disk",
        "t_kvcpy_cpu", "t_kvcpy_gpu", "t_ram2vram", "t_vram2ram",
    )

    def non_finite_reason(self) -> str | None:
        """First non-finite solver-consumed scalar, or None when clean.

        The scheduler refuses to solve (and serves its last-known-good
        placement) when this returns a reason: a poisoned fleet state must
        never reach ``build_coeffs``.
        """
        import math

        for name, dev in self.devices.items():
            for fld in self._SCALAR_FIELDS:
                v = getattr(dev, fld)
                if v is not None and not math.isfinite(v):
                    return f"device {name!r} field {fld} is non-finite ({v!r})"
        loads = self.model.expert_loads
        if loads is not None and any(not math.isfinite(v) for v in loads):
            return "model.expert_loads contains a non-finite entry"
        return None

    def _ensure_head(self) -> None:
        """Exactly one head device, and it is the first in ring order.

        The solver requires the head (I/O-layer owner) to exist; when the
        head leaves, the first surviving device is promoted.
        """
        devs = list(self.devices.values())
        for i, d in enumerate(devs):
            d.is_head = i == 0

"""Fleet scheduler service: the solver library run as a long-lived daemon.

``distilp_tpu.solver`` answers "where do the layers/experts go, right now?"
one call at a time; nothing owns a fleet over time. This package does:

- ``events``    — typed device-churn events + the JSONL trace format;
- ``fleet``     — the mutable fleet snapshot events apply to;
- ``scheduler`` — the replanning core: one warm ``StreamingReplanner`` per
  (fleet, model) identity in a bounded LRU pool, drift events riding warm/
  margin ticks, structural events re-solving (warm when the identity was
  seen before, cold otherwise), latest certified placement always served;
- ``metrics``   — per-tick counters + latency histograms as a plain dict,
  plus the health-state vocabulary (healthy/degraded/broken);
- ``sim``       — deterministic churn scenario generator + trace replay;
- ``faults``    — seeded fault injection (solver exceptions, latency
  spikes, NaN poisoning, malformed events, dropout bursts) and the
  chaos-replay soak that certifies the degraded-serving path.

The design target is the restarted-PDHG observation (arXiv:2407.16144)
packaged as infrastructure (arXiv:2412.09734): repeated nearby solves
should keep their warm state alive across invocations, which only a
long-lived process can do.
"""

from .faults import (
    FAULT_KINDS,
    ChaosReport,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    InjectedSolverFault,
    chaos_replay,
)
from .events import (
    DRIFT_KINDS,
    STRUCTURAL_KINDS,
    DeviceDegrade,
    DeviceJoin,
    DeviceLeave,
    FleetEvent,
    LoadTick,
    ModelSwap,
    event_from_dict,
    is_structural,
    read_trace,
    write_trace,
)
from .fleet import FleetState
from .forecast import ChurnForecaster
from .metrics import (
    HEALTH_BROKEN,
    HEALTH_DEGRADED,
    HEALTH_HEALTHY,
    HEALTH_STATES,
    METRIC_FAMILIES,
    METRIC_REGISTRY,
    LatencyHist,
    SchedulerMetrics,
    registry_help,
)
from .scheduler import PlacementView, Scheduler, WarmPool, drift_warm_share
from .sim import ReplayReport, generate_trace, replay
from .speculate import (
    BankEntry,
    SpeculationBank,
    bucket_vector,
    candidate_digest,
    instance_digest,
)

__all__ = [
    "DeviceJoin",
    "DeviceLeave",
    "DeviceDegrade",
    "ModelSwap",
    "LoadTick",
    "FleetEvent",
    "STRUCTURAL_KINDS",
    "DRIFT_KINDS",
    "is_structural",
    "event_from_dict",
    "read_trace",
    "write_trace",
    "FleetState",
    "SchedulerMetrics",
    "LatencyHist",
    "METRIC_REGISTRY",
    "METRIC_FAMILIES",
    "registry_help",
    "HEALTH_HEALTHY",
    "HEALTH_DEGRADED",
    "HEALTH_BROKEN",
    "HEALTH_STATES",
    "Scheduler",
    "WarmPool",
    "drift_warm_share",
    "PlacementView",
    "ReplayReport",
    "generate_trace",
    "replay",
    "FAULT_KINDS",
    "FaultSpec",
    "FaultPlan",
    "FaultInjector",
    "InjectedSolverFault",
    "ChaosReport",
    "chaos_replay",
    "ChurnForecaster",
    "SpeculationBank",
    "BankEntry",
    "instance_digest",
    "candidate_digest",
    "bucket_vector",
]

"""Speculative replanning: pre-solved placements served at cache-hit cost.

The warm-start line (PR 3: iterate-carrying warm ticks; PR 6: shared
first-order warm entry) makes the solve on the critical path cheaper; this
module moves it OFF the critical path entirely when churn is predictable.
After each real tick, the scheduler asks the forecaster
(``sched.forecast``) for K likely near-future fleets and prices them in
ONE ``halda_solve_scenarios`` vmapped dispatch, warm-seeded from the
incumbent — the same batch-and-overlap discipline the scenario bench
measures (S placements for ~one dispatch's wire time). The certified
results land in a ``SpeculationBank`` keyed by a tolerance-bucketed
*instance digest*; when the next event's post-apply fleet digests to a
banked entry, the scheduler serves the pre-solved placement immediately
(published ``mode='spec'``) and the solve ladder never runs.

Digest semantics — the honesty contract:

- The digest covers EVERY drift channel (per-device ``t_comm``,
  ``comm_bandwidth``, memory pools, the model's ``expert_loads``) plus the
  fleet/model identity key, bucketed at ``tolerance`` relative width
  (log-space for the multiplicative channels). A hit therefore certifies
  "the live instance is within one tolerance bucket of the instance this
  placement was solved (and certified) for" — staleness bounded by
  construction, exactly the warm-hint soundness argument with an explicit
  tolerance instead of re-pricing.
- Channels the forecaster does NOT model (bandwidth, memory, loads) still
  move the digest, so unforecast drift produces an honest miss and falls
  through to the unchanged tick path.
- A structural event changes the identity key; ``invalidate()`` drops the
  stale entries (counted ``spec_stale``) rather than letting them match a
  different problem.

Bank entries carry the full ``HALDAResult`` including its Lagrangian duals
and LP iterates (``ipm_state``), so a hit can donate its scenario solve as
the next tick's warm seed; a miss donates nothing — speculative work must
never touch the replanner's warm state unless it was actually served.
"""

from __future__ import annotations

import hashlib
import math
from collections import OrderedDict
from typing import List, NamedTuple, Optional, Tuple

from ..solver.result import HALDAResult
from ..solver.streaming import _decode_state, _encode_state

DEFAULT_SPEC_K = 3
DEFAULT_SPEC_TOLERANCE = 0.05

_EPS = 1e-12


def _bucket_log(value: Optional[float], width: float) -> int:
    """Tolerance bucket of a positive multiplicative-drift scalar.

    ``None``/non-positive collapse to sentinels (absent capacity vs a
    zeroed coefficient are different instances); non-finite values get
    their own bucket so a poisoned profile can never alias a clean one.
    """
    if value is None:
        return -(10**9)
    if not math.isfinite(value):
        return 10**9
    if value <= 0:
        return -(10**9) + 1
    return round(math.log(max(float(value), _EPS)) / width)


def bucket_vector(devices, model, tolerance: float) -> Tuple[int, ...]:
    """The instance's drift coordinates as a flat tuple of tolerance
    buckets (the same channels, widths and order ``candidate_digest``
    hashes, minus the device names).

    Unlike the digest — which only answers "same bucket on every channel,
    yes or no" — the vector supports a DISTANCE: two instances of the same
    identity differ by ``max |bucket_i - bucket_j|`` tolerance steps on
    their worst channel. The degraded-mode near-match probe
    (``SpeculationBank.nearest``) ranks banked entries by exactly that.
    """
    w = math.log1p(tolerance)
    out: List[int] = []
    for dev in devices:
        out.extend(
            (
                _bucket_log(dev.t_comm, w),
                _bucket_log(dev.comm_bandwidth, w),
                _bucket_log(float(dev.d_avail_ram), w),
                _bucket_log(_accel_pool(dev), w),
            )
        )
    loads = model.expert_loads
    if loads is not None:
        out.extend(round(v / tolerance) for v in loads)
    return tuple(out)


def candidate_digest(devices, model, key, tolerance: float) -> str:
    """Tolerance-bucketed digest of one instance's DRIFT coordinates.

    Two instances with equal digests differ by at most ~``tolerance``
    (relative) on every drift channel and share fleet/model identity —
    close enough that a placement certified on one is served for the
    other under the bank's documented tolerance semantics. Used both for
    the live fleet (``instance_digest``) and for forecast candidates
    (device lists sharing the live fleet's identity key).
    """
    w = math.log1p(tolerance)
    parts: List[str] = ["|".join(key)]
    for dev in devices:
        parts.append(
            f"{dev.name}:{_bucket_log(dev.t_comm, w)}:"
            f"{_bucket_log(dev.comm_bandwidth, w)}:"
            f"{_bucket_log(float(dev.d_avail_ram), w)}:"
            f"{_bucket_log(_accel_pool(dev), w)}"
        )
    loads = model.expert_loads
    if loads is not None:
        # Linear bucketing: loads are mean-1 shares, so an absolute
        # ``tolerance`` step is the natural unit.
        parts.append(
            ",".join(str(round(v / tolerance)) for v in loads)
        )
    return hashlib.sha1("\n".join(parts).encode()).hexdigest()[:20]


def instance_digest(fleet, tolerance: float) -> str:
    """``candidate_digest`` of a live ``FleetState``."""
    return candidate_digest(
        fleet.device_list(), fleet.model, fleet.key(), tolerance
    )


def _accel_pool(dev) -> Optional[float]:
    for pool in ("d_avail_cuda", "d_avail_metal", "d_avail_tpu"):
        cap = getattr(dev, pool, None)
        if cap is not None:
            return float(cap)
    return None


class BankEntry(NamedTuple):
    """One pre-solved placement, ready to serve on a digest match."""

    result: HALDAResult
    key: Tuple[str, str]  # fleet/model identity the solve priced
    weight: float  # forecast confidence (1.0 for banked real ticks)
    solved_seq: int  # fleet seq the presolve was dispatched at
    # Bucket coordinates of the instance the entry was certified on
    # (``bucket_vector``); None on entries banked before the near-match
    # probe existed — they still serve exact hits, just never near ones.
    buckets: Optional[Tuple[int, ...]] = None


class SpeculationBank:
    """Bounded LRU of certified speculative placements, digest-keyed.

    Shard-owned by construction: each scheduler builds its own bank and
    every access happens on that shard's (single) tick path, so — like
    the warm pool — no locking is needed or taken.
    """

    def __init__(
        self,
        capacity: int = 4 * DEFAULT_SPEC_K,
        tolerance: float = DEFAULT_SPEC_TOLERANCE,
    ):
        if capacity < 1:
            raise ValueError("speculation bank capacity must be >= 1")
        if not 0.0 < tolerance < 1.0:
            raise ValueError(
                f"spec tolerance must be in (0, 1) (got {tolerance})"
            )
        self.capacity = capacity
        self.tolerance = tolerance
        self._entries: "OrderedDict[str, BankEntry]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, digest: str) -> bool:
        return digest in self._entries

    def digest(self, fleet) -> str:
        return instance_digest(fleet, self.tolerance)

    def put(self, digest: str, entry: BankEntry) -> None:
        """Insert/refresh an entry (LRU position renewed; LRU evicted)."""
        self._entries[digest] = entry
        self._entries.move_to_end(digest)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)

    def probe(self, digest: str, key: Tuple[str, str]) -> Optional[BankEntry]:
        """The banked entry for this digest under this identity, or None.

        The identity check is belt-and-braces (the digest already folds
        the key in); a hit renews recency so live futures outlast dead
        ones under the LRU bound.
        """
        entry = self._entries.get(digest)
        if entry is None or entry.key != key:
            return None
        self._entries.move_to_end(digest)
        return entry

    def nearest(
        self, devices, model, key: Tuple[str, str], max_radius: int
    ) -> Optional[Tuple[BankEntry, int]]:
        """The closest certified banked entry within ``max_radius``
        tolerance buckets of the live instance, or None.

        Degraded-mode serving's probe (``mode='spec_near'``): when a shard
        is behind, a placement certified on an instance a few tolerance
        steps away beats queueing the solve past the deadline. Distance is
        the worst channel's bucket gap (L-inf over ``bucket_vector``), so
        ``max_radius`` bounds staleness per channel: every coefficient of
        the served instance is within ~``(1 + tolerance)^max_radius`` of
        the instance the placement was certified on. Identity must match
        exactly (a near-match across fleets/models is a different problem,
        not a stale one); entries without bucket coordinates never match.
        A hit renews LRU recency, like ``probe``.
        """
        live = bucket_vector(devices, model, self.tolerance)
        best: Optional[Tuple[str, BankEntry, int]] = None
        for digest, e in self._entries.items():
            if (
                e.key != key
                or e.buckets is None
                or len(e.buckets) != len(live)
                or not e.result.certified
            ):
                continue
            dist = (
                max(abs(a - b) for a, b in zip(live, e.buckets))
                if live
                else 0
            )
            if dist > max_radius:
                continue
            if best is None or dist < best[2]:
                best = (digest, e, dist)
        if best is None:
            return None
        self._entries.move_to_end(best[0])
        return best[1], best[2]

    def invalidate(self, key: Tuple[str, str]) -> int:
        """Drop entries NOT priced under ``key``; returns how many (the
        ``spec_stale`` count after a structural identity change)."""
        stale = [d for d, e in self._entries.items() if e.key != key]
        for d in stale:
            del self._entries[d]
        return len(stale)

    def clear(self) -> int:
        n = len(self._entries)
        self._entries.clear()
        return n

    # -- snapshot/restore (rides Scheduler.dump_state) ---------------------

    def dump_state(self) -> dict:
        """JSON-able bank contents; LP iterates travel bit-exact (the same
        base64-raw-bytes encoding the warm-state blob uses), so a restored
        hit donates byte-identical warm seeds."""
        return {
            "capacity": self.capacity,
            "tolerance": self.tolerance,
            "entries": [
                {
                    "digest": digest,
                    "key": list(e.key),
                    "weight": e.weight,
                    "solved_seq": e.solved_seq,
                    "buckets": (
                        list(e.buckets) if e.buckets is not None else None
                    ),
                    "result": e.result.model_dump(),
                    "ipm_state": _encode_state(e.result.ipm_state),
                }
                for digest, e in self._entries.items()
            ],
        }

    def load_state(self, state: Optional[dict]) -> None:
        """Restore a ``dump_state`` blob (None/empty restores clean);
        configured capacity/tolerance stay the constructor's — the blob
        carries state, not config — so entries beyond a smaller restored
        capacity evict LRU-style."""
        self._entries.clear()
        if not state:
            return
        for rec in state.get("entries", []):
            result = HALDAResult.model_validate(rec["result"])
            result.ipm_state = _decode_state(rec.get("ipm_state"))
            buckets = rec.get("buckets")
            self.put(
                rec["digest"],
                BankEntry(
                    result=result,
                    key=tuple(rec["key"]),
                    weight=float(rec.get("weight", 1.0)),
                    solved_seq=int(rec.get("solved_seq", 0)),
                    buckets=(
                        tuple(int(b) for b in buckets)
                        if buckets is not None
                        else None
                    ),
                ),
            )

    def snapshot(self) -> List[dict]:
        """Compact live view (flight recorder / debug), oldest first."""
        return [
            {
                "digest": d,
                "k": e.result.k,
                "certified": e.result.certified,
                "weight": round(e.weight, 4),
                "solved_seq": e.solved_seq,
            }
            for d, e in self._entries.items()
        ]


def presolve_candidates(
    candidates,
    model,
    *,
    k_candidates=None,
    mip_gap: float,
    kv_bits: str,
    moe,
    warm: Optional[HALDAResult],
    load_factors=None,
    lp_backend: str = "auto",
    pdhg_iters: Optional[int] = None,
    pdhg_restart_tol: Optional[float] = None,
    pdhg_dtype: Optional[str] = None,
) -> List[HALDAResult]:
    """Solve the forecast candidates as ONE vmapped scenario dispatch.

    ``candidates`` is the forecaster's ``[(devices, weight), ...]``; the
    incumbent ``warm`` seeds every scenario (what-ifs ARE drifts of the
    current placement — the scenario bench measured warm seeding cutting
    the batch ~2.6x). Raises exactly what ``halda_solve_scenarios``
    raises; the scheduler treats any failure as "no speculation this
    tick", never as a serving fault.
    """
    from ..solver import halda_solve_scenarios

    fleets = [devs for devs, _w in candidates]
    return halda_solve_scenarios(
        fleets,
        model,
        k_candidates=k_candidates,
        mip_gap=mip_gap,
        kv_bits=kv_bits,
        moe=moe,
        warms=[warm] * len(fleets) if warm is not None else None,
        load_factors_list=(
            [load_factors] * len(fleets) if load_factors is not None else None
        ),
        lp_backend=lp_backend,
        pdhg_iters=pdhg_iters,
        pdhg_restart_tol=pdhg_restart_tol,
        pdhg_dtype=pdhg_dtype,
    )

"""Deterministic churn scenarios + trace replay.

``generate_trace`` turns (scenario, seed, n_events) into a reproducible
event list — same seed, same trace, bit for bit — so tests can assert
determinism and benches can compare captures. Scenarios model the churn a
real fleet sees:

- ``drift``  — pure coefficient noise: per-device t_comm jitter + load ticks;
- ``decay``  — gradual bandwidth decay on a subset of links (compounding
  small ``t_comm_scale > 1`` degrades) over a drifting background;
- ``flap``   — one non-head device repeatedly leaves and rejoins (the
  warm-pool cache's reason to exist) over a drifting background;
- ``burst``  — load spikes: occasional large skews (expert loads on MoE
  models, t_comm surges otherwise) that relax back — a surge is undone by
  the next burst event (the inverse jitter), so long replays measure
  spike-and-recover, not compounding degradation;
- ``mixed``  — all of the above plus occasional permanent joins/leaves;
- ``spec_burst`` — correlated multi-device drift spikes: one fixed cohort
  of devices spikes t_comm by per-device factors (drawn once per trace)
  and the next burst event relaxes the spike EXACTLY, over a tiny-drift
  background — the fleet alternates between two nearby states, which is
  the churn shape the speculative replanner (``sched.speculate``) banks;
- ``spec_flap`` — oscillating up/down drift on a channel subset: a fixed
  subset's t_comm multiplies by f, then 1/f, alternating per oscillation
  event (no membership churn, unlike ``flap``) — the bundled
  ``tests/traces/spec_burst.jsonl`` / ``spec_flap.jsonl`` are seeded
  captures of these two (ROADMAP item 3's burst/flap traces).

``replay`` drives a scheduler through a trace and reports event→placement
latency (p50/p99) and sustained events/sec — the numbers ``bench.py``
publishes as the scheduler section.
"""

from __future__ import annotations

import time
from typing import List, NamedTuple, Optional, Sequence

import numpy as np

from ..common import DeviceProfile
from ..utils import make_synthetic_fleet
from .events import DeviceDegrade, DeviceJoin, DeviceLeave, LoadTick, is_structural

SCENARIOS = (
    "drift", "decay", "flap", "burst", "mixed", "spec_burst", "spec_flap"
)


def _joinable_device(idx: int, seed: int) -> DeviceProfile:
    """A deterministic fresh device for join events (never the head)."""
    dev = make_synthetic_fleet(1, seed=seed * 7919 + idx)[0]
    dev.name = f"churn-{seed}-{idx}"
    dev.is_head = False
    return dev


def generate_trace(
    scenario: str,
    n_events: int,
    seed: int,
    base_fleet: Sequence[DeviceProfile],
    n_experts: int = 0,
    max_extra_devices: int = 2,
) -> List:
    """A reproducible event list for one scenario.

    ``base_fleet`` is the fleet the scheduler starts from (the trace only
    references its device NAMES — generation does not mutate profiles).
    ``n_experts > 0`` makes load ticks carry skewed expert loads (MoE
    models); otherwise load shows up as t_comm jitter. The fleet never
    shrinks below 2 devices, never grows past ``len(base_fleet) +
    max_extra_devices``, and the head device is never removed — traces are
    valid by construction (``FleetState.apply`` is strict).
    """
    if scenario not in SCENARIOS:
        raise ValueError(f"unknown scenario {scenario!r}; pick from {SCENARIOS}")
    rng = np.random.default_rng(seed)
    names = [d.name for d in base_fleet]
    if scenario in ("spec_burst", "spec_flap"):
        return _spec_trace(scenario, n_events, rng, names)
    profiles = {d.name: d.model_copy(deep=True) for d in base_fleet}
    head = names[0]
    live = list(names)  # membership tracking; order irrelevant here
    next_join = 0
    events: List = []
    t = 0.0

    def drift_event():
        """Background coefficient noise: jitter one or two live links."""
        picks = rng.choice(len(live), size=min(2, len(live)), replace=False)
        if rng.random() < 0.5:
            return LoadTick(
                t=t,
                t_comm_jitter={
                    live[int(i)]: float(rng.uniform(0.97, 1.03)) for i in picks
                },
                expert_loads=(
                    _skewed_loads(rng, n_experts, strength=0.15)
                    if n_experts
                    else None
                ),
            )
        return DeviceDegrade(
            name=live[int(picks[0])],
            t=t,
            t_comm_scale=float(rng.uniform(0.96, 1.04)),
        )

    flapper: Optional[str] = None  # name currently flapped OUT
    active_burst: Optional[dict] = None  # surge jitter awaiting its inverse
    decay_targets = [n for n in names[1:]][: max(1, len(names) // 3)]

    def decay_event():
        # Prefer the fixed decay cohort, but never name a device that has
        # left the fleet (mixed traces churn membership; apply() is strict).
        pool = [n for n in decay_targets if n in live] or [
            n for n in live if n != head
        ]
        return DeviceDegrade(
            name=str(rng.choice(pool)),
            t=t,
            t_comm_scale=float(rng.uniform(1.01, 1.05)),
            bandwidth_scale=float(rng.uniform(0.96, 0.995)),
        )

    for i in range(n_events):
        t += float(rng.exponential(1.0))
        roll = rng.random()
        ev = None
        if scenario == "decay" and roll < 0.35:
            ev = decay_event()
        elif scenario == "flap" and roll < 0.25:
            if flapper is None:
                candidates = [n for n in live if n != head]
                flapper = str(rng.choice(candidates))
                live.remove(flapper)
                ev = DeviceLeave(name=flapper, t=t)
            else:
                # Rejoin with the SAME name and profile. The rejoined
                # device lands at the END of the ring, so the first flap
                # cycle mints two new warm-pool keys ("without X" and
                # "X moved last") — every later cycle of the same device
                # hits both keys warm. That recurrence is the placement
                # cache's reason to exist.
                dev = profiles[flapper].model_copy(deep=True)
                dev.is_head = False
                live.append(flapper)
                flapper = None
                ev = DeviceJoin(device=dev, t=t)
        elif scenario == "burst" and roll < 0.3:
            if n_experts:
                ev = LoadTick(
                    t=t, expert_loads=_skewed_loads(rng, n_experts, strength=1.5)
                )
            elif active_burst is not None:
                # Relax: undo the outstanding surge exactly (inverse
                # jitter), so bursts never compound across the replay.
                ev = LoadTick(
                    t=t,
                    t_comm_jitter={
                        n: 1.0 / f
                        for n, f in active_burst.items()
                        if n in live
                    },
                )
                active_burst = None
            else:
                active_burst = {
                    n: float(rng.uniform(1.2, 1.8))
                    for n in live
                    if rng.random() < 0.5
                }
                ev = LoadTick(t=t, t_comm_jitter=dict(active_burst))
        elif scenario == "mixed" and roll < 0.2:
            grow_ok = len(live) < len(names) + max_extra_devices
            shrink_ok = len(live) > max(2, len(names) - 1)
            if grow_ok and (roll < 0.1 or not shrink_ok):
                dev = _joinable_device(next_join, seed)
                next_join += 1
                live.append(dev.name)
                ev = DeviceJoin(device=dev, t=t)
            elif shrink_ok:
                candidates = [n for n in live if n != head]
                gone = str(rng.choice(candidates))
                live.remove(gone)
                ev = DeviceLeave(name=gone, t=t)
        elif scenario == "mixed" and roll < 0.35:
            # "All of the above" includes the decay class: gradual
            # bandwidth decay events, not just t_comm jitter.
            ev = decay_event()
        if ev is None:
            ev = drift_event()
        events.append(ev)
    return events


def _spec_trace(scenario: str, n_events: int, rng, names: List[str]) -> List:
    """Speculation-friendly drift traces: predictable, t_comm-only churn.

    Both scenarios keep membership fixed and drift ONLY t_comm (the
    channel the forecaster models), so the fleet walks between a small
    number of tolerance-bucket states:

    - ``spec_burst``: a large cohort spikes together by per-device
      factors drawn ONCE for the whole trace, and the next burst event
      is the exact inverse — spike-and-recover between two states;
    - ``spec_flap``: a smaller subset oscillates up/down per event at a
      higher rate (the flapping-load shape, without ``flap``'s leaves).

    The non-cohort background drifts by ±0.1% per event — real noise, but
    small against the default 5% speculation tolerance, so background
    ticks rarely change the instance digest (occasional bucket-boundary
    crossings stay in as honest misses).
    """
    head = names[0]
    others = [n for n in names[1:]] or [head]
    if scenario == "spec_flap":
        subset = others[: max(1, (len(others) + 1) // 2)]
        factors = {n: float(rng.uniform(1.25, 1.5)) for n in subset}
        p_osc = 0.7
    else:  # spec_burst
        subset = others[: max(1, (2 * len(others) + 2) // 3)]
        factors = {n: float(rng.uniform(1.3, 1.7)) for n in subset}
        p_osc = 0.5
    background = [n for n in others if n not in subset] or [head]
    events: List = []
    t = 0.0
    up = False  # whether the subset currently sits at its spiked state
    for _ in range(n_events):
        t += float(rng.exponential(1.0))
        if rng.random() < p_osc:
            jitter = (
                {n: 1.0 / f for n, f in factors.items()}
                if up
                else dict(factors)
            )
            up = not up
            events.append(LoadTick(t=t, t_comm_jitter=jitter))
        else:
            events.append(
                DeviceDegrade(
                    name=str(rng.choice(background)),
                    t=t,
                    t_comm_scale=float(rng.uniform(0.999, 1.001)),
                )
            )
    return events


def _skewed_loads(rng, n_experts: int, strength: float) -> List[float]:
    """Mean-1 positive load vector; ``strength`` scales the skew."""
    raw = np.exp(strength * rng.standard_normal(n_experts))
    raw = raw / raw.mean()
    return [float(x) for x in raw]


class ReplayReport(NamedTuple):
    """What a trace replay measured, ready for a bench JSON line."""

    views: list  # one PlacementView per event
    latencies_ms: List[float]  # event -> placement, per event
    events_per_sec: float  # sustained over the whole replay
    p50_ms: float
    p99_ms: float
    structural_uncertified: int  # structural events whose tick missed cert
    failed_ticks: int

    def summary(self) -> dict:
        return {
            "events": len(self.latencies_ms),
            "events_per_sec": round(self.events_per_sec, 2),
            "p50_ms": round(self.p50_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "structural_uncertified": self.structural_uncertified,
            "failed_ticks": self.failed_ticks,
        }


def replay(
    scheduler, events: Sequence, warmup: int = 0, on_event=None
) -> ReplayReport:
    """Drive a scheduler through a trace, measuring per-event latency.

    ``warmup`` events at the head of the trace are handled but excluded
    from the timing stats (jit compilation of a fleet shape's solve
    program belongs to deployment, not to the steady state the p50/p99
    describe; the bench reports both by replaying with and without it).

    ``on_event(event, view, ms)`` is called after each tick — the CLI's
    event log hangs off this hook so there is exactly ONE replay loop.
    """
    lat: List[float] = []
    views = []
    uncert = 0
    failed_before = scheduler.metrics.counters["tick_failed"]
    t_start = time.perf_counter()
    for i, ev in enumerate(events):
        t0 = time.perf_counter()
        view = scheduler.handle(ev)
        ms = (time.perf_counter() - t0) * 1e3
        views.append(view)
        if i >= warmup:
            lat.append(ms)
        if (
            is_structural(ev)
            and view.events_behind == 0
            and not view.result.certified
        ):
            uncert += 1
        if on_event is not None:
            on_event(ev, view, ms)
    total_s = time.perf_counter() - t_start
    srt = sorted(lat)

    def q(p: float) -> float:
        if not srt:
            return 0.0
        return srt[min(len(srt) - 1, max(0, round(p * (len(srt) - 1))))]

    return ReplayReport(
        views=views,
        latencies_ms=lat,
        events_per_sec=len(events) / total_s if total_s > 0 else 0.0,
        p50_ms=q(0.50),
        p99_ms=q(0.99),
        structural_uncertified=uncert,
        failed_ticks=scheduler.metrics.counters["tick_failed"] - failed_before,
    )

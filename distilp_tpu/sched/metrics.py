"""Scheduler observability: counters + latency histograms, plain dicts out.

One ``SchedulerMetrics`` instance funnels everything: the scheduler counts
events, pool traffic and per-event latency; every pooled
``StreamingReplanner`` reports its tick mode (cold / warm / margin),
certification outcome and fallback-ladder escalations through the same
object (``solver.streaming`` calls ``record_tick`` when a metrics sink is
attached — duck-typed, so the solver package does not import this one).

``snapshot()`` returns nothing but plain ints/floats in dicts — safe to
``json.dumps`` straight into a bench line or a /metrics endpoint.

Thread safety: the gateway tier (``distilp_tpu.gateway``) funnels every
shard worker thread into ONE gateway-level sink, and an HTTP ``/metrics``
read can land mid-``observe`` — so ``inc``/``observe``/``snapshot`` (and
the hist's ``record``) hold a lock. Uncontended, that is one
``threading.Lock`` acquire per counter bump (tens of nanoseconds) — noise
next to a solve tick; contended, it is exactly what keeps a concurrent
snapshot from reading a half-updated hist buffer (count bumped, value not
yet appended).
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Dict, List

from ..utils.lockwatch import make_lock

TICK_MODES = ("cold", "warm", "margin")

# Service health, coarsest first. The scheduler owns the transitions
# (scheduler._note_fault / _on_clean_tick); this module owns the vocabulary
# so metrics consumers and the serve CLI agree on the strings.
#
# - ``healthy``  — recent ticks solved fresh, no outstanding faults;
# - ``degraded`` — serving, but on stale/fallback answers (quarantined
#   input, deadline miss, failed or retried solves) until a clean streak
#   clears it;
# - ``broken``   — the circuit breaker is open: solves are suspended and
#   every tick serves the last-known-good placement until the half-open
#   probe succeeds.
HEALTH_HEALTHY = "healthy"
HEALTH_DEGRADED = "degraded"
HEALTH_BROKEN = "broken"
HEALTH_STATES = (HEALTH_HEALTHY, HEALTH_DEGRADED, HEALTH_BROKEN)

# Counter names the fault-hardened serving path increments; listed here so
# dashboards (and the chaos harness's accounting pass) can enumerate them
# without grepping the scheduler. Injection-side ``fault_injected_*`` /
# ``fault_fired_*`` counters come from sched.faults with the kind appended.
FAULT_COUNTERS = (
    "events_quarantined",  # events rejected before touching the fleet
    "quarantine_fleet",  # non-finite fleet state refused a solve
    "deadline_missed",  # solve abandoned at the wall-clock deadline
    "deadline_backlog",  # tick skipped: an abandoned solve still running
    "abandoned_solves_drained",  # overrun solves that finished and were discarded
    "solve_retries",  # retry attempts after a solve exception
    "solve_retry_success",  # ticks saved by a retry
    "breaker_open",  # breaker transitions to open
    "breaker_short_circuit",  # ticks served degraded with the breaker open
    "breaker_half_open_probe",  # probe solves attempted from half-open
    "breaker_close",  # probe succeeded; breaker closed
    "breaker_reopen",  # probe failed; breaker re-opened
    "served_stale",  # views served as mode='stale'
    "served_degraded",  # views served as mode='degraded'
    "health_recovered",  # degraded/broken -> healthy transitions
)

# The ONE enumeration of every metric name the sched/gateway/obs layers
# emit, name -> Prometheus `# HELP` text. Three consumers keep each other
# honest: the Prometheus exposition (obs.export.render_prometheus) takes
# its HELP lines from here, dashboards enumerate from here instead of
# grepping call sites, and dlint DLP019 fails the gate on any
# string-literal ``metrics.inc("...")`` in those layers whose name is NOT
# an exact entry — so a new counter cannot ship without its help text.
# Dynamically composed names (f-strings over event kinds, tick modes,
# fault kinds, worker ids) resolve through METRIC_FAMILIES by longest
# prefix instead; ``registry_help`` is the lookup both exposition and
# tests use.
METRIC_REGISTRY = {
    # -- event routing (scheduler.handle) ---------------------------------
    "events_total": "Events accepted into the fleet state",
    "structural_events": "Accepted events that changed the problem identity",
    "drift_events": "Accepted events that kept the problem identity",
    "events_quarantined": "Events rejected before touching the fleet",
    "quarantine_fleet": "Ticks refused because the fleet state went non-finite",
    "init_solve": "Eventless solves at construction (solve_on_init)",
    # -- tick outcomes (SchedulerMetrics.record_tick + scheduler) ---------
    "tick_cold": "Solver ticks that solved from scratch",
    "tick_warm": "Solver ticks warm-started from the previous placement",
    "tick_margin": "Solver ticks served by the MoE margin fast path",
    "tick_certified": "Ticks whose placement carried an optimality certificate",
    "tick_uncertified": "Ticks whose placement missed its certificate",
    "tick_failed": "Ticks that produced no placement at all",
    "tick_failed_structural": "Failed ticks routed as structural events",
    "tick_failed_drift": "Failed ticks routed as drift events",
    "fallback_escalations": "Certification-ladder escalations across ticks",
    "solver_escalations": "In-solver budget escalations (timings['escalated'])",
    "structural_uncertified": "Structural events whose tick missed its certificate",
    # -- warm pool --------------------------------------------------------
    "pool_hit": "Warm-pool lookups that found a live replanner",
    "pool_miss": "Warm-pool lookups that minted a fresh replanner",
    "pool_evict": "Warm replanners dropped by the LRU bound",
    # -- fault-hardened serving (see FAULT_COUNTERS comments) -------------
    "deadline_missed": "Solves abandoned at the wall-clock deadline",
    "deadline_backlog": "Ticks skipped behind a still-running abandoned solve",
    "abandoned_solves_drained": "Overrun solves that finished and were discarded",
    "solve_retries": "Retry attempts after a failed solve attempt",
    "solve_attempt_failed": "Individual solve attempts that raised",
    "solve_retry_success": "Ticks saved by a retry",
    "breaker_open": "Circuit-breaker transitions to open",
    "breaker_short_circuit": "Ticks served degraded with the breaker open",
    "breaker_half_open_probe": "Probe solves attempted from half-open",
    "breaker_close": "Half-open probes that closed the breaker",
    "breaker_reopen": "Half-open probes that re-opened the breaker",
    "served_stale": "Views re-served as mode='stale'",
    "served_degraded": "Views re-served as mode='degraded'",
    "health_recovered": "degraded/broken -> healthy transitions",
    "faults_injected_total": "Faults injected by the chaos harness (all kinds)",
    # -- risk-aware serving ----------------------------------------------
    "risk_eval": "Ticks that ran the twin's risk-aware candidate scoring",
    "risk_candidates": "Candidates scored by the risk-aware selector",
    "risk_switch": "Ticks that served a candidate over the fresh solve",
    "risk_error": "Risk scorings that failed (fresh solve served instead)",
    "risk_per_k_failed": "Per-k candidate enumerations that failed",
    # -- speculative replanning (sched.forecast + sched.speculate) --------
    "spec_hit": "Ticks served from the speculation bank (pre-solved placement)",
    "spec_miss": "Bank probes that found no matching pre-solved placement",
    "spec_stale": "Bank entries invalidated by a problem-identity change",
    "spec_presolve": "Forecast instances pre-solved into the speculation bank",
    "spec_presolve_failed": "Speculative presolve dispatches that failed",
    # -- admission control / overload (gateway + traffic) -----------------
    "events_shed": "Events rejected at the admission gate (429 + Retry-After)",
    "events_coalesced": "Queued drift events folded into a newer tick's solve",
    "spec_near_hit": "Pressure ticks served a banked near-match (mode='spec_near')",
    "spec_near_miss": "Pressure ticks that found no banked near-match to serve",
    # -- cross-shard solve combiner (distilp_tpu.combine) -----------------
    "combine_prepared": "Ticks packed for a cross-shard batched solve",
    "combine_local": "Combine-eligible ticks solved per-shard instead "
    "(structural / MoE / probe / post-restore)",
    "combine_stale": "Combined results discarded: the fleet advanced past "
    "the packed seq before adoption",
    "combine_fallback": "Combined ticks that re-solved per-shard "
    "(uncertified lane or combiner dispatch failure)",
    "combine_batches": "Batched solve dispatches executed by the combiner",
    "combine_instances": "Shard instances solved inside combined batches",
    "combine_flush_full": "Combiner flushes triggered by a full bucket",
    "combine_flush_deadline": "Combiner flushes triggered by the max-wait deadline",
    "combine_bucket_occupancy": "Instances per combined batch (histogram)",
    "combine_padding_waste": "Phantom-device fraction of combined batches "
    "(padded lanes' pad share, histogram)",
    "combine_batch_ms": "Combined batch dispatch latency (pack to decode), ms",
    "combine_static_hit": "Fraction of a combined batch's lanes whose static "
    "half was already device-resident (histogram; 1.0 = zero static bytes "
    "re-shipped)",
    "combine_dispatch_error": "Batched solve dispatches that raised; every "
    "lane fell back to a per-shard solve",
    "drift_tick_combine": "Drift ticks served via a cross-shard batched "
    "solve (mode='combine')",
    # -- snapshot / restore ----------------------------------------------
    "state_restored": "Scheduler warm-state restores (load_state)",
    "warm_resumes": "First post-restore ticks that rode warm (the proof)",
    "cold_resumes": "First post-restore ticks that paid a cold solve",
    "resume_identity_changed": "First post-restore ticks on a changed identity",
    # -- gateway tier -----------------------------------------------------
    "shards_registered": "Shards registered with the gateway",
    "shards_restored": "Shards registered from a snapshot blob",
    "gateway_events": "Events ingested through the gateway",
    "worker_events": "Events routed, by worker (worker label)",
    "worker_queue_depth": "Commands queued on a shard worker, by worker "
    "(gauge; the admission-control input)",
    "snapshots_taken": "Gateway warm-state snapshots taken",
    "worker_exception": "Closures that raised on a shard worker thread",
    "worker_callback_error": "Completion callbacks that raised (dead loop)",
    "prom_scrape_error": "Background Prometheus scrapes that failed",
    "http_client_gone": "HTTP clients gone before request/response finished",
    "http_bad_request": "HTTP 400s (malformed request or body)",
    "http_not_found": "HTTP 404s (unknown route or fleet)",
    "http_conflict": "HTTP 409s (shard exists but nothing servable yet)",
    "http_too_many_requests": "HTTP 429s (queue full; Retry-After returned)",
    "http_internal_error": "HTTP 500s (unexpected server-side failure)",
    # -- dynamic fleet / live migration (gateway) -------------------------
    "workers_spawned": "Workers added live to a dynamic gateway",
    "workers_retired": "Workers drained and stopped live",
    "shards_migrated": "Shards moved between workers (warm, zero cold ticks)",
    "migration_parked": "Events parked during a migration flip and replayed "
    "onto the destination (none lost, none doubled)",
    "migration_failed": "Migration flips that failed (routing unchanged, "
    "source kept serving)",
    # -- crash-tolerant process tier (gateway supervision) ----------------
    "worker_crashes": "Process-worker child deaths the supervisor classified",
    "child_respawns": "Crashed children respawned (fresh socket, same worker)",
    "shards_recovered": "Shards rebuilt onto a respawned child (warm)",
    "events_replayed": "WAL-tail events replayed during crash recovery",
    "wal_appends": "Accepted events journaled to the per-shard WAL",
    "micro_snapshots": "Per-shard micro-snapshots taken (WAL truncated)",
    "micro_snapshot_failed": "Micro-snapshot attempts that hit a dead child",
    "workers_quarantined": "Workers taken out of the ring by the crash-loop "
    "breaker (slice rebalanced away; surfaced in /signals)",
    "http_worker_crashed": "HTTP 503s (child died mid-request; shard "
    "recovering, Retry-After returned)",
    # -- closed-loop autoscaler (distilp_tpu.control) ---------------------
    "control_actions": "Controller actions emitted (all kinds)",
    "control_scale_out": "Scale-out actions (spawn one worker + rebalance)",
    "control_scale_in": "Scale-in actions (retire one worker after drain)",
    "control_degrade_on": "Forced-degrade admissions switched ON",
    "control_degrade_off": "Forced-degrade admissions switched OFF",
    "control_spec_k": "spec_k adaptations applied fleet-wide",
    "control_hold": "Decisions suppressed by cooldown or band edges",
    "control_errors": "Control ticks that raised (loop survived; counted)",
    # -- observability layer ----------------------------------------------
    "flight_dumps": "Flight-recorder post-mortem dumps written",
    "health_state": "Shard health as a gauge (0 healthy, 1 degraded, 2 broken)",
    # -- compile ledger (obs.compile_ledger) ------------------------------
    "compiles": "XLA compile events attributed to this scheduler's ticks",
    "compile_cache_hits": "Compiles served by the persistent compilation cache",
    "recompile_storms": "Recompile-storm alarms (N same-entry compiles in a window)",
    # -- memory ledger (obs.memory) ---------------------------------------
    "mem_samples": "Ticks that recorded a fresh memory-ledger watermark sample",
    "mem_pressure": "Ticks marked under pressure by low memory headroom "
    "(gateway degrade-on-low-headroom)",
    # -- SLO engine / metrics timelines (obs.timeline + obs.slo) ----------
    "timeline_samples": "Timeline sampler ticks that recorded a sample",
    "timeline_sample_error": "Timeline sampler ticks that failed (counted, never fatal)",
    "slo_alert_opened": "SLO burn-rate alerts opened (multi-window AND fired)",
    "slo_alert_closed": "SLO burn-rate alerts closed (hysteresis cleared)",
    # -- latency histograms (exposed as Prometheus summaries, ms) ---------
    "event_to_placement": "Event to published placement, ms (per shard)",
    "structural_tick": "Structural-event tick latency, ms",
    "drift_tick": "Drift-event tick latency, ms",
    "ipm_iters_executed": "LP iterations the tick's solve actually executed",
    "twin_p95": "Twin p95 latency of the served placement, ms",
    "gateway_event_to_placement": "Gateway ingest to placement (queue wait included), ms",
    "spec_hit_ms": "Speculative-hit serve latency (bank probe to publish), ms",
    "recovery_mttr_ms": "Crash detection to shard(s) serving again "
    "(respawn+replay or quarantine+rebalance), ms",
    "spec_presolve_ms": "Speculative presolve batch latency (off the serving path), ms",
    "compile_ms": "XLA compile time a tick paid (ledger-attributed), ms",
    "mem_live_mb": "Live jax-array megabytes at tick end (memory-ledger "
    "watermark; gauge-like, exposed as a summary)",
    "mem_rss_mb": "Host RSS megabytes at tick end (memory-ledger "
    "watermark; gauge-like, exposed as a summary)",
}

# Longest-prefix fallback for dynamically composed names. Every f-string
# ``inc``/``observe`` site in sched/gateway/obs must be covered by one of
# these (or be an exact entry above).
METRIC_FAMILIES = (
    ("event_", "Accepted events, by event kind"),
    ("quarantine_", "Quarantined events, by event kind"),
    ("structural_tick_", "Structural-event ticks, by tick mode"),
    ("drift_tick_", "Drift-event ticks, by tick mode"),
    ("tick_", "Solver ticks, by mode or outcome"),
    ("lp_backend_", "Ticks by the LP relaxation engine that actually ran"),
    ("served_", "Degraded-mode serves, by published mode"),
    ("fault_injected_", "Chaos faults scheduled, by kind"),
    ("fault_fired_", "Solver/process-channel chaos faults that fired, by kind"),
    ("worker_", "Gateway per-worker counters (worker_<i>_events)"),
)


def registry_help(name: str):
    """``# HELP`` text for a metric name: exact entry first, then the
    longest matching family prefix; None when nothing covers it (the
    Prometheus round-trip test treats that as registry drift)."""
    if name in METRIC_REGISTRY:
        return METRIC_REGISTRY[name]
    best = None
    for prefix, help_txt in METRIC_FAMILIES:
        if name.startswith(prefix) and (
            best is None or len(prefix) > len(best[0])
        ):
            best = (prefix, help_txt)
    return best[1] if best else None


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank quantile on an already-sorted list (no numpy needed)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class LatencyHist:
    """Latency recorder with p50/p99 snapshots.

    Keeps raw samples (traces are thousands of events, not millions); the
    snapshot sorts once. ``cap`` bounds memory for genuinely long-lived
    daemons by keeping the most recent window.

    Snapshot semantics after the window overflows: ``count``/``mean_ms``
    are ALL-TIME (every sample ever recorded), while the quantiles, the
    max and the ``window_count``/``window_mean_ms`` pair describe only the
    ``cap``-bounded recent window. Both views are reported explicitly so a
    long-lived daemon's snapshot is never an incoherent mix of the two
    (the old snapshot paired an all-time mean with windowed quantiles).
    """

    def __init__(self, cap: int = 100_000):
        # deque(maxlen=...) keeps the recent-window trim O(1) per record;
        # the snapshot (rare) pays the sort.
        self._vals: "deque[float]" = deque(maxlen=cap)  # guarded-by: self._lock
        self.count = 0  # guarded-by: self._lock
        self.total = 0.0  # guarded-by: self._lock
        # record() is a three-field update; a snapshot between the count
        # bump and the append would see count != len(values) and report a
        # torn (count, mean, quantile) triple. One lock covers both.
        self._lock = make_lock("metrics.hist")

    def record(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total += ms
            self._vals.append(float(ms))

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._vals)
            count, total = self.count, self.total
        return {
            # All-time: survives the window overflowing. total_ms is the
            # exact running sum (the Prometheus summary `_sum` — derived
            # from the rounded mean it could DECREASE between scrapes).
            "count": count,
            "total_ms": round(total, 3),
            "mean_ms": round(total / count, 3) if count else 0.0,
            # Recent window (at most `cap` samples): the same population
            # the quantiles and max are computed from.
            "window_count": len(vals),
            "window_mean_ms": (
                round(sum(vals) / len(vals), 3) if vals else 0.0
            ),
            "p50_ms": round(_quantile(vals, 0.50), 3),
            "p99_ms": round(_quantile(vals, 0.99), 3),
            "max_ms": round(vals[-1], 3) if vals else 0.0,
        }


class SchedulerMetrics:
    """Counters + histograms for one scheduler (or one replanner)."""

    def __init__(self):
        self.counters: Dict[str, int] = defaultdict(int)  # guarded-by: self._lock
        self.hists: Dict[str, LatencyHist] = {}  # guarded-by: self._lock
        # Guards the counter dict and hist-map mutation; each hist guards
        # its own buffer (record/snapshot above), so observe() holds this
        # lock only for the get-or-create, never across the record.
        self._lock = make_lock("metrics.counters")

    # -- generic sinks ----------------------------------------------------

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def observe(self, name: str, ms: float) -> None:
        with self._lock:
            hist = self.hists.get(name)
            if hist is None:
                hist = self.hists[name] = LatencyHist()
        hist.record(ms)

    # -- the replanner hook (see StreamingReplanner.metrics) --------------

    def record_tick(self, mode: str, certified: bool, escalations: int = 0) -> None:
        """One solver tick: its mode, certificate, and ladder escalations."""
        if mode not in TICK_MODES:
            mode = "cold"
        self.inc(f"tick_{mode}")
        self.inc("tick_certified" if certified else "tick_uncertified")
        if escalations:
            self.inc("fallback_escalations", escalations)

    # -- derived views ----------------------------------------------------

    def tick_total(self) -> int:
        with self._lock:
            return sum(self.counters[f"tick_{m}"] for m in TICK_MODES)

    def pool_hit_rate(self) -> float:
        with self._lock:
            hits = self.counters["pool_hit"]
            total = hits + self.counters["pool_miss"]
        return hits / total if total else 0.0

    def snapshot(self) -> dict:
        """Plain-dict view: counters, derived rates, histogram quantiles."""
        with self._lock:
            counters = dict(self.counters)
            hists = list(self.hists.items())
        tick_total = sum(counters.get(f"tick_{m}", 0) for m in TICK_MODES)
        hits = counters.get("pool_hit", 0)
        pool_total = hits + counters.get("pool_miss", 0)
        return {
            "counters": counters,
            "pool_hit_rate": round(hits / pool_total, 4) if pool_total else 0.0,
            "tick_total": tick_total,
            "latency": {name: h.snapshot() for name, h in hists},
        }

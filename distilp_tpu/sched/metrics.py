"""Scheduler observability: counters + latency histograms, plain dicts out.

One ``SchedulerMetrics`` instance funnels everything: the scheduler counts
events, pool traffic and per-event latency; every pooled
``StreamingReplanner`` reports its tick mode (cold / warm / margin),
certification outcome and fallback-ladder escalations through the same
object (``solver.streaming`` calls ``record_tick`` when a metrics sink is
attached — duck-typed, so the solver package does not import this one).

``snapshot()`` returns nothing but plain ints/floats in dicts — safe to
``json.dumps`` straight into a bench line or a /metrics endpoint.

Thread safety: the gateway tier (``distilp_tpu.gateway``) funnels every
shard worker thread into ONE gateway-level sink, and an HTTP ``/metrics``
read can land mid-``observe`` — so ``inc``/``observe``/``snapshot`` (and
the hist's ``record``) hold a lock. Uncontended, that is one
``threading.Lock`` acquire per counter bump (tens of nanoseconds) — noise
next to a solve tick; contended, it is exactly what keeps a concurrent
snapshot from reading a half-updated hist buffer (count bumped, value not
yet appended).
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Dict, List

TICK_MODES = ("cold", "warm", "margin")

# Service health, coarsest first. The scheduler owns the transitions
# (scheduler._note_fault / _on_clean_tick); this module owns the vocabulary
# so metrics consumers and the serve CLI agree on the strings.
#
# - ``healthy``  — recent ticks solved fresh, no outstanding faults;
# - ``degraded`` — serving, but on stale/fallback answers (quarantined
#   input, deadline miss, failed or retried solves) until a clean streak
#   clears it;
# - ``broken``   — the circuit breaker is open: solves are suspended and
#   every tick serves the last-known-good placement until the half-open
#   probe succeeds.
HEALTH_HEALTHY = "healthy"
HEALTH_DEGRADED = "degraded"
HEALTH_BROKEN = "broken"
HEALTH_STATES = (HEALTH_HEALTHY, HEALTH_DEGRADED, HEALTH_BROKEN)

# Counter names the fault-hardened serving path increments; listed here so
# dashboards (and the chaos harness's accounting pass) can enumerate them
# without grepping the scheduler. Injection-side ``fault_injected_*`` /
# ``fault_fired_*`` counters come from sched.faults with the kind appended.
FAULT_COUNTERS = (
    "events_quarantined",  # events rejected before touching the fleet
    "quarantine_fleet",  # non-finite fleet state refused a solve
    "deadline_missed",  # solve abandoned at the wall-clock deadline
    "deadline_backlog",  # tick skipped: an abandoned solve still running
    "abandoned_solves_drained",  # overrun solves that finished and were discarded
    "solve_retries",  # retry attempts after a solve exception
    "solve_retry_success",  # ticks saved by a retry
    "breaker_open",  # breaker transitions to open
    "breaker_short_circuit",  # ticks served degraded with the breaker open
    "breaker_half_open_probe",  # probe solves attempted from half-open
    "breaker_close",  # probe succeeded; breaker closed
    "breaker_reopen",  # probe failed; breaker re-opened
    "served_stale",  # views served as mode='stale'
    "served_degraded",  # views served as mode='degraded'
    "health_recovered",  # degraded/broken -> healthy transitions
)


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank quantile on an already-sorted list (no numpy needed)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class LatencyHist:
    """Latency recorder with p50/p99 snapshots.

    Keeps raw samples (traces are thousands of events, not millions); the
    snapshot sorts once. ``cap`` bounds memory for genuinely long-lived
    daemons by keeping the most recent window.
    """

    def __init__(self, cap: int = 100_000):
        # deque(maxlen=...) keeps the recent-window trim O(1) per record;
        # the snapshot (rare) pays the sort.
        self._vals: "deque[float]" = deque(maxlen=cap)
        self.count = 0
        self.total = 0.0
        # record() is a three-field update; a snapshot between the count
        # bump and the append would see count != len(values) and report a
        # torn (count, mean, quantile) triple. One lock covers both.
        self._lock = threading.Lock()

    def record(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total += ms
            self._vals.append(float(ms))

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._vals)
            count, total = self.count, self.total
        return {
            "count": count,
            "mean_ms": round(total / count, 3) if count else 0.0,
            "p50_ms": round(_quantile(vals, 0.50), 3),
            "p99_ms": round(_quantile(vals, 0.99), 3),
            "max_ms": round(vals[-1], 3) if vals else 0.0,
        }


class SchedulerMetrics:
    """Counters + histograms for one scheduler (or one replanner)."""

    def __init__(self):
        self.counters: Dict[str, int] = defaultdict(int)
        self.hists: Dict[str, LatencyHist] = {}
        # Guards the counter dict and hist-map mutation; each hist guards
        # its own buffer (record/snapshot above), so observe() holds this
        # lock only for the get-or-create, never across the record.
        self._lock = threading.Lock()

    # -- generic sinks ----------------------------------------------------

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def observe(self, name: str, ms: float) -> None:
        with self._lock:
            hist = self.hists.get(name)
            if hist is None:
                hist = self.hists[name] = LatencyHist()
        hist.record(ms)

    # -- the replanner hook (see StreamingReplanner.metrics) --------------

    def record_tick(self, mode: str, certified: bool, escalations: int = 0) -> None:
        """One solver tick: its mode, certificate, and ladder escalations."""
        if mode not in TICK_MODES:
            mode = "cold"
        self.inc(f"tick_{mode}")
        self.inc("tick_certified" if certified else "tick_uncertified")
        if escalations:
            self.inc("fallback_escalations", escalations)

    # -- derived views ----------------------------------------------------

    def tick_total(self) -> int:
        with self._lock:
            return sum(self.counters[f"tick_{m}"] for m in TICK_MODES)

    def pool_hit_rate(self) -> float:
        with self._lock:
            hits = self.counters["pool_hit"]
            total = hits + self.counters["pool_miss"]
        return hits / total if total else 0.0

    def snapshot(self) -> dict:
        """Plain-dict view: counters, derived rates, histogram quantiles."""
        with self._lock:
            counters = dict(self.counters)
            hists = list(self.hists.items())
        tick_total = sum(counters.get(f"tick_{m}", 0) for m in TICK_MODES)
        hits = counters.get("pool_hit", 0)
        pool_total = hits + counters.get("pool_miss", 0)
        return {
            "counters": counters,
            "pool_hit_rate": round(hits / pool_total, 4) if pool_total else 0.0,
            "tick_total": tick_total,
            "latency": {name: h.snapshot() for name, h in hists},
        }

"""Churn forecasting: predict the fleet's next drift states from its past.

The serving path's latency is dominated by solve time, yet the churn that
triggers those solves is highly predictable: drift traces are smooth
multiplicative walks (gradual decay compounds small ``t_comm_scale``
degrades), bursts relax back to where they came from, and flapping load
oscillates between a handful of states. ``ChurnForecaster`` turns that
predictability into concrete candidate *futures* — full device lists the
speculative pre-solver (``sched.speculate``) prices ahead of time.

Model, deliberately tiny and deterministic: one channel per device for the
``t_comm`` scalar (the coefficient every drift event class perturbs —
``DeviceDegrade.t_comm_scale`` and ``LoadTick.t_comm_jitter`` both
multiply it), tracked in LOG space because drift is multiplicative. Each
applied event updates, per channel:

- ``last``  — the live value (what the fleet holds right now);
- ``prev``  — the value before the most recent change (the state an
  oscillation or a spike-relax cycle returns to);
- ``trend`` — an EWMA of the per-event log-steps (Holt-style smoothed
  linear trend: a decay trace's compounding 1–5% degrades average to a
  persistent positive trend; an oscillation's alternating ±d averages to
  ~0, which is exactly right — "revert" covers it instead).

``forecast()`` emits up to K candidate fleets with confidence weights:
``revert`` (every channel returns to ``prev`` — bursts and flaps), then
``trend×h`` horizons (``last·exp(h·trend)`` — decay continuation). The
whole thing is a pure function of the APPLIED event stream: same events,
same forecasts, bit for bit — quarantined events never reach ``observe``
(the scheduler only calls it after ``FleetState.apply`` succeeded), so a
NaN-poisoned event cannot corrupt the EWMA state silently, and a
defensive finite-check skips any non-finite channel value anyway.

Only ``t_comm`` is *forecast*; every other drift channel (bandwidth,
memory, expert loads) is held at its live value in the candidates. That
is not an accident: ``halda_solve_scenarios`` shares one device-resident
static half across the batch, and t_comm futures are exactly the drift
class it documents as in-class. Out-of-class drift still lands in the
speculation bank's *digest* (``sched.speculate``), so an unforecast
channel moving produces an honest miss, never a mispriced hit.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..common import DeviceProfile

# EWMA factor for the per-event log-step trend. 0.4 weighs the last few
# events heavily (churn regimes shift fast) while still averaging an
# oscillation's alternating steps toward zero within a cycle or two.
TREND_BETA = 0.4

# Guard for log(): t_comm can legitimately be driven to 0.0 by compounding
# degrades (fleet.apply clamps at max(0.0, ...)).
_EPS = 1e-12


class ChurnForecaster:
    """Per-device EWMA + linear-trend predictor over applied churn events.

    >>> fc = ChurnForecaster()
    >>> fc.observe(scheduler.fleet)          # after every APPLIED event
    >>> for devs, weight in fc.forecast(scheduler.fleet, k=3):
    ...     ...                              # candidate near-future fleets
    """

    def __init__(self, beta: float = TREND_BETA):
        if not 0.0 < beta <= 1.0:
            raise ValueError(f"trend beta must be in (0, 1] (got {beta})")
        self.beta = beta
        # name -> {"last": float, "prev": float, "trend": float}
        self._channels: Dict[str, Dict[str, float]] = {}

    def __len__(self) -> int:
        return len(self._channels)

    def observe(self, fleet) -> None:
        """Fold the fleet's post-event channel values into the predictor.

        Call ONLY after an event was applied (the quarantine gates run
        first) — the forecaster must never learn from rejected input.
        Devices that left the fleet drop their state; unchanged channels
        leave ``prev``/``trend`` alone so a no-op load tick does not decay
        the memory of the last real move.
        """
        live = set(fleet.devices)
        for dev in fleet.devices.values():
            v = dev.t_comm
            if not (isinstance(v, (int, float)) and math.isfinite(v)):
                # Defensive only: the scheduler's quarantine layers keep
                # non-finite values out of the fleet; skipping the update
                # (but keeping the channel's finite history) keeps the
                # forecaster advisory rather than raising.
                continue
            ch = self._channels.get(dev.name)
            if ch is None:
                self._channels[dev.name] = {
                    "last": float(v), "prev": float(v), "trend": 0.0,
                }
                continue
            old = ch["last"]
            if v == old:
                continue
            step = math.log(max(float(v), _EPS)) - math.log(max(old, _EPS))
            ch["prev"] = old
            ch["last"] = float(v)
            ch["trend"] = self.beta * step + (1.0 - self.beta) * ch["trend"]
        for name in list(self._channels):
            if name not in live:
                del self._channels[name]

    def forecast(
        self, fleet, k: int
    ) -> List[Tuple[List[DeviceProfile], float]]:
        """Up to ``k`` candidate near-future fleets with confidence weights.

        Candidate 0 is ``revert`` (every tracked channel back to ``prev``);
        candidates 1.. extrapolate the smoothed trend ``h`` steps. Each is
        a deep-copied device list safe to mutate/solve; weights decay
        geometrically and sum to 1 over the emitted list. Candidates whose
        channels all equal the live values are skipped (the live instance
        is banked by the real tick itself), so fewer than ``k`` may come
        back — or none, before any drift has been observed.
        """
        if k < 1 or not self._channels:
            return []
        plans: List[Tuple[Dict[str, float], float]] = []
        revert = {
            name: ch["prev"]
            for name, ch in self._channels.items()
            if ch["prev"] != ch["last"]
        }
        if revert:
            plans.append((revert, 1.0))
        h = 1
        while len(plans) < k:
            stepped = {
                name: ch["last"] * math.exp(h * ch["trend"])
                for name, ch in self._channels.items()
                if ch["trend"] != 0.0
            }
            if not stepped:
                break
            plans.append((stepped, 0.5**h))
            h += 1
        if not plans:
            return []
        total = sum(w for _, w in plans)
        out: List[Tuple[List[DeviceProfile], float]] = []
        for overrides, w in plans[:k]:
            devs = [d.model_copy(deep=True) for d in fleet.device_list()]
            for dev in devs:
                if dev.name in overrides:
                    dev.t_comm = max(0.0, float(overrides[dev.name]))
            out.append((devs, w / total))
        return out

    # -- snapshot/restore (rides Scheduler.dump_state) ---------------------

    def dump_state(self) -> dict:
        """JSON-able forecaster state; floats round-trip bit-exact."""
        return {
            "beta": self.beta,
            "channels": {
                name: dict(ch) for name, ch in self._channels.items()
            },
        }

    def load_state(self, state: Optional[dict]) -> None:
        """Restore a ``dump_state`` blob (None/empty restores clean)."""
        self._channels = {}
        if not state:
            return
        self.beta = float(state.get("beta", TREND_BETA))
        for name, ch in state.get("channels", {}).items():
            self._channels[name] = {
                "last": float(ch["last"]),
                "prev": float(ch["prev"]),
                "trend": float(ch["trend"]),
            }

    def channel(self, name: str) -> Optional[dict]:
        """Read-only view of one device's channel state (tests/debug)."""
        ch = self._channels.get(name)
        return dict(ch) if ch is not None else None

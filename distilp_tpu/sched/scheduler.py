"""The replanning core: events in, certified placements out, warm state kept.

``Scheduler`` owns a ``FleetState`` and a bounded LRU **warm pool** of
``StreamingReplanner`` instances keyed by (fleet_digest, model_digest).
Routing follows the event classes:

- **drift** events (degrade / load) keep the key, so the tick lands on the
  same warm replanner — a warm re-solve (dense) or a margin tick (MoE
  chains), exactly the solver's streaming fast paths;
- **structural** events (join / leave / model swap) change the key. A key
  seen before gets its replanner — and its warm incumbent, duals and
  margin anchor — back from the pool (a device flapping out and back in
  replans warm: this is the placement cache); a brand-new key starts cold.

Serving never blocks on solving: ``latest()`` returns the most recently
*published* placement plus staleness metadata (events behind, age). A tick
that fails (e.g. the fleet drifted infeasible) increments a counter and
leaves the last placement served; certification is the replanner's
escalation ladder's job and its outcome is recorded per tick.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import Callable, NamedTuple, Optional, Sequence, Tuple

from ..common import DeviceProfile, ModelProfile
from ..obs import compile_ledger as _compile_ledger
from ..obs import memory as _memory
from ..obs.trace import NOOP_SPAN, NOOP_TRACER
from ..solver.result import HALDAResult
from ..solver.streaming import StreamingReplanner
from .events import validate_event
from .fleet import FleetState
from .forecast import ChurnForecaster
from .metrics import (
    HEALTH_BROKEN,
    HEALTH_DEGRADED,
    HEALTH_HEALTHY,
    SchedulerMetrics,
)
from .speculate import (
    DEFAULT_SPEC_K,
    DEFAULT_SPEC_TOLERANCE,
    BankEntry,
    SpeculationBank,
    bucket_vector,
    candidate_digest,
    presolve_candidates,
)

# Default near-match radius for degraded-mode serving, in tolerance
# buckets: a banked placement may serve an instance up to this many
# tolerance steps away on its worst drift channel (~(1+tol)^radius
# relative — ~22% at the default 5% tolerance). Wide enough to cover a
# burst's excursion from the pre-burst instance the bank holds; narrow
# enough that the served placement was certified on a genuinely nearby
# problem.
DEFAULT_SPEC_NEAR_RADIUS = 4

# Solver-timings keys worth attaching to the solve span: the wall-clock
# breakdown plus the work/engine counters that attribute a slow tick, plus
# the solver-diagnostics digest (Scheduler(diagnostics=True) / `serve
# --solver-diagnostics`; present in timings only when the tick solved with
# convergence tracing on). The digest key set is imported from its one
# source of truth next to SearchTrace.digest() — a field added there
# reaches the span and the flight records without touching this module.
from ..obs.convergence import CONV_DIGEST_KEYS as _CONV_DIGEST_KEYS  # noqa: E402

_SOLVE_SPAN_KEYS = (
    "build_ms", "pack_ms", "upload_ms", "solve_ms", "static_hit",
    "lp_backend", "bnb_rounds", "ipm_iters_executed", "escalated",
) + _CONV_DIGEST_KEYS


class _DeadlineMiss(Exception):
    """Internal: the tick's solve overran its wall-clock deadline (or an
    earlier abandoned solve is still occupying the worker)."""


class CombineTicket(NamedTuple):
    """A prepared-but-unsolved combined tick (``Scheduler.prepare_combine``
    → the gateway's ``SolveCombiner`` → ``Scheduler.adopt_combine``). The
    packed instance rides ``prep.instance``; ``seq`` pins the fleet state
    the pack described, so adopt can detect (and discard) a result a
    structural barrier raced past."""

    key: tuple
    planner: StreamingReplanner
    prep: object  # solver.streaming.CombinePrep
    seq: int
    t0: float
    event: object  # last event of the coalesced run, for flight records


class _SolveWorker:
    """One DAEMON thread executing solve attempts for the deadline path.

    A deadline-abandoned solve cannot be interrupted (it is deep inside
    jit'd device code); it keeps running here and is discarded. The thread
    is a daemon precisely so an abandoned solve never blocks process exit
    (a ThreadPoolExecutor's non-daemon workers are joined at interpreter
    shutdown — a CLI would 'finish' and then hang for the rest of the
    abandoned compile). Single worker on purpose: solves on one scheduler
    are serialized, so an abandoned solve has always COMPLETED before the
    next one starts and the planner's warm state is never written by two
    solves at once.
    """

    def __init__(self) -> None:
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue()
        # The worker's thread ident, read by the compile-ledger capture:
        # a deadline-path solve compiles on THIS thread, and the tick's
        # compile attribution must include it (set in _run; reads before
        # the thread publishes it just see None and skip the filter hit).
        self.ident = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="sched-solve"
        )
        self._thread.start()

    def _run(self) -> None:
        import threading

        self.ident = threading.get_ident()
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, box, done = item
            try:
                box["result"] = fn()
            except BaseException as e:  # dlint: disable=DLP017 not swallowed: re-raised by _attempt_deadline from the box
                box["exc"] = e
            finally:
                done.set()

    def submit(self, fn):
        """-> (box, done): ``done.wait(timeout)`` then read the box."""
        import threading

        box: dict = {}
        done = threading.Event()
        self._q.put((fn, box, done))
        return box, done

    def stop(self) -> None:
        self._q.put(None)


# Serving-side perturbation model for risk-aware candidate scoring: modest
# symmetric jitter on compute/link/disk plus a straggler scenario (each
# device has a 5% chance per draw of running 8x slower — the GC-pause /
# thermal-throttle / contended-host class of event on consumer swarms).
# The straggler channel is what separates candidates: a deeper pipeline
# multiplies the straggled bottleneck cycle (k-1) times, so the twin's p95
# regularly prefers a shallower runner-up k the mean-objective ranking
# puts second; pure symmetric jitter rarely reorders close candidates.
DEFAULT_RISK_MC = {
    "sigma_compute": 0.10,
    "sigma_comm": 0.15,
    "sigma_disk": 0.10,
    "sigma_mem": 0.0,
    "dropout_p": 0.05,
    "dropout_slowdown": 8.0,
}


class PlacementView(NamedTuple):
    """One served placement + how stale it is relative to the event stream."""

    result: HALDAResult
    seq: int  # fleet seq the placement was solved at
    fleet_seq: int  # fleet seq at read time
    events_behind: int  # fleet_seq - seq (0 = fresh)
    age_s: float  # wall-clock seconds since publication
    # 'cold' | 'warm' | 'margin' tick that produced it; 'spec' when the
    # speculation bank served a PRE-solved placement (certified on a
    # forecast instance within the bank's tolerance of this one — no solve
    # ran this tick); 'spec_near' when a PRESSURE tick (gateway admission
    # control, shard behind) served the bank's nearest certified match
    # within spec_near_radius tolerance buckets — approximate by
    # construction, not merely stale; 'risk' when the risk-aware selector
    # served a candidate OTHER than that tick's fresh solve (a cached
    # incumbent or per-k alternative). Under degraded serving the field is
    # REWRITTEN on the published view: 'stale' when a deadline miss (or
    # poisoned fleet state) re-served the last-known-good placement,
    # 'degraded' while the open circuit breaker skips solves.
    mode: str
    # Problem identity at publication time. For mode == 'risk' the served
    # placement may have been SOLVED under an earlier identity/tick — the
    # twin re-priced it against this one before serving.
    key: Tuple[str, str]
    # Risk-aware mode only: the served placement's twin p95 latency and
    # whether the twin preferred a candidate over the fresh solve.
    twin_p95_s: Optional[float] = None
    risk_selected: bool = False


class WarmPool:
    """Bounded LRU of warm replanners, keyed by problem identity.

    Eviction drops the warm state (incumbent, duals, margin anchor) — the
    next solve under that key is cold but still correct; the pool trades
    re-solve speed for bounded memory, never answers.
    """

    def __init__(
        self,
        capacity: int,
        factory: Callable[[], StreamingReplanner],
        metrics: Optional[SchedulerMetrics] = None,
    ):
        if capacity < 1:
            raise ValueError("warm pool capacity must be >= 1")
        self.capacity = capacity
        self._factory = factory
        self._metrics = metrics
        self._pool: "OrderedDict[tuple, StreamingReplanner]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._pool)

    def __contains__(self, key: tuple) -> bool:
        return key in self._pool

    def get(self, key: tuple) -> Tuple[StreamingReplanner, bool]:
        """(replanner, was_a_hit) for the key, creating + evicting LRU-style."""
        planner = self._pool.get(key)
        hit = planner is not None
        if hit:
            self._pool.move_to_end(key)
        else:
            planner = self._factory()
            self._pool[key] = planner
            while len(self._pool) > self.capacity:
                self._pool.popitem(last=False)
                if self._metrics is not None:
                    self._metrics.inc("pool_evict")
        if self._metrics is not None:
            self._metrics.inc("pool_hit" if hit else "pool_miss")
        return planner, hit

    def items(self):
        """(key, replanner) pairs, LRU order — the risk-aware candidate scan
        reads cached incumbents without touching recency or hit counters."""
        return list(self._pool.items())

    def peek(self, key: tuple) -> Optional[StreamingReplanner]:
        """The key's live replanner, or None — no counters, no minting, no
        recency bump. The speculative hit path donates warm state through
        this: serving from the bank must not skew pool accounting, and it
        must never mint (or LRU-evict) a planner for a tick that solves
        nothing."""
        return self._pool.get(key)

    def adopt(self, key: tuple, planner: StreamingReplanner) -> None:
        """Install a restored replanner under its key (snapshot restore).

        Counts as neither a hit nor a miss — the pool never routed an
        event to it; capacity is still enforced (restoring onto a smaller
        pool evicts LRU-style, warm state lost but correctness kept).
        """
        self._pool[key] = planner
        self._pool.move_to_end(key)
        while len(self._pool) > self.capacity:
            self._pool.popitem(last=False)
            if self._metrics is not None:
                self._metrics.inc("pool_evict")


class Scheduler:
    """Event-driven replanning daemon over one fleet + model.

    >>> sched = Scheduler(devs, model, k_candidates=[4, 8])
    >>> view = sched.handle(DeviceDegrade(name="synth-android-3",
    ...                                   t_comm_scale=1.2))
    >>> view.result.certified, view.mode
    (True, 'warm')
    >>> sched.latest().events_behind
    0
    """

    def __init__(
        self,
        devices: Sequence[DeviceProfile],
        model: ModelProfile,
        mip_gap: float = 1e-3,
        kv_bits: str = "4bit",
        backend: str = "jax",
        moe: Optional[bool] = None,
        k_candidates: Optional[Sequence[int]] = None,
        warm_pool_size: int = 4,
        solve_on_init: bool = False,
        metrics: Optional[SchedulerMetrics] = None,
        cold_start: bool = False,
        lp_backend: str = "auto",
        pdhg_iters: Optional[int] = None,
        pdhg_restart_tol: Optional[float] = None,
        mesh_shards: Optional[int] = None,
        pdhg_dtype: Optional[str] = None,
        risk_aware: bool = False,
        risk_samples: int = 256,
        risk_seed: int = 0,
        risk_mc: Optional[dict] = None,
        solve_deadline_s: Optional[float] = None,
        max_retries: int = 0,
        retry_backoff_s: float = 0.05,
        retry_backoff_max_s: float = 1.0,
        breaker_threshold: int = 5,
        breaker_cooldown: int = 3,
        healthy_after: int = 3,
        fault_hook: Optional[Callable[[int], None]] = None,
        speculative: bool = False,
        spec_k: int = DEFAULT_SPEC_K,
        spec_tolerance: float = DEFAULT_SPEC_TOLERANCE,
        spec_bank_size: Optional[int] = None,
        spec_near_radius: int = DEFAULT_SPEC_NEAR_RADIUS,
        tracer=None,
        flight=None,
        flight_key: str = "default",
        jax_profile_dir: Optional[str] = None,
        diagnostics: bool = False,
    ):
        self.fleet = FleetState(list(devices), model)
        self.mip_gap = mip_gap
        self.kv_bits = kv_bits
        self.backend = backend
        self.moe = moe
        # A/B switch (`solver serve --cold-start`): the pool still routes
        # events, but every tick solves from scratch — the baseline against
        # which warm/margin/iterate reuse is measured.
        self.cold_start = cold_start
        # LP relaxation engine (`serve --lp-backend`): 'auto' stays on the
        # IPM for the small fleets this daemon historically served and
        # flips to matrix-free PDHG at fleet scale; every minted replanner
        # inherits it, and the engine each tick actually ran is counted
        # (`lp_backend_ipm`/`lp_backend_pdhg`) next to the tick modes.
        self.lp_backend = lp_backend
        self.pdhg_iters = pdhg_iters
        self.pdhg_restart_tol = pdhg_restart_tol
        # Row-mesh + iterate-precision knobs (`serve --mesh-shards
        # --pdhg-dtype`): inherited by every minted replanner's tick
        # solves; speculation and the per-k risk enumeration keep their
        # vmap composition (mesh_shards is a per-dispatch knob there).
        self.mesh_shards = mesh_shards
        self.pdhg_dtype = pdhg_dtype
        # Solver-interior diagnostics (`serve --solver-diagnostics`): every
        # tick solves with convergence tracing on; the conv_* digest rides
        # the timings dict onto the sched.solve span and the flight
        # recorder's tick records. Off (default) = the exact untraced
        # device program — counters and placements byte-identical.
        self.diagnostics = diagnostics
        # Risk-aware serving (`serve --risk-aware`): every tick scores the
        # fresh solve AND the warm pool's cached incumbents on the digital
        # twin (Monte-Carlo p95 + feasibility-violation penalty, seeded so
        # replays are deterministic) and publishes the lowest-risk
        # candidate — instead of serving the freshest placement on
        # staleness alone. Solver warm state is untouched: risk selection
        # changes what is SERVED, never what seeds the next solve.
        self.risk_aware = risk_aware
        self.risk_samples = risk_samples
        self.risk_seed = risk_seed
        # Perturbation-model overrides forwarded to the twin (sigma_*,
        # dropout_p, dropout_slowdown, degrade). The serving default leans
        # on the straggler channel: DEFAULT_RISK_MC's dropout scenario is
        # what separates placements that concentrate layers from ones that
        # spread them — symmetric small jitter alone rarely reorders.
        self.risk_mc = dict(DEFAULT_RISK_MC if risk_mc is None else risk_mc)
        # Per-k candidate cache: the enumeration is a COLD per-k sweep, so
        # drift ticks reuse the placements enumerated at the current
        # problem identity (the twin re-prices them against the live
        # profiles anyway); only an identity change re-enumerates.
        self._risk_per_k: list = []
        self._risk_per_k_key: Optional[tuple] = None
        self.k_candidates = list(k_candidates) if k_candidates else None
        self.metrics = metrics if metrics is not None else SchedulerMetrics()
        self.pool = WarmPool(
            warm_pool_size, self._make_replanner, metrics=self.metrics
        )
        # -- fault-hardened serving (see README "Degraded-mode semantics").
        # All knobs default OFF/neutral: with no deadline, no retries and no
        # injected faults the tick path below is bit-for-bit the old one —
        # the chaos machinery must be zero-cost when disabled.
        self.solve_deadline_s = solve_deadline_s
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_max_s = retry_backoff_max_s
        # Breaker: opens after `breaker_threshold` CONSECUTIVE solve
        # failures (exceptions after retries, or deadline misses); while
        # open, `breaker_cooldown` ticks serve degraded without solving at
        # all, then one half-open probe solve decides close vs re-open.
        # threshold <= 0 disables the breaker entirely.
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self.healthy_after = healthy_after
        # Test/chaos seam: called (with the 0-based attempt index) before
        # every solve attempt; raising injects a solve failure, sleeping
        # injects a latency spike. None in production.
        self.fault_hook = fault_hook
        # -- speculative replanning (sched.forecast + sched.speculate),
        # default OFF = byte-identical serving: no forecaster, no bank, no
        # probe, no presolve — every site below is behind `if speculative`.
        # When on: applied events feed the forecaster, each solved tick
        # pre-solves the K most likely futures as one vmapped scenario
        # batch, and the next event's bank probe runs BEFORE the solve
        # ladder — a hit serves the pre-solved placement (mode='spec') at
        # cache-hit latency, an honest miss falls through unchanged.
        self.speculative = speculative
        self.spec_k = spec_k
        self.spec_tolerance = spec_tolerance
        # Degraded-mode serving: how far (in tolerance buckets, worst
        # channel) a banked placement may be from the live instance and
        # still be served under queue pressure (gateway admission control
        # passes pressure=True; mode='spec_near'). Only consulted on
        # pressure ticks — plain serving never near-matches.
        self.spec_near_radius = spec_near_radius
        self.forecaster = ChurnForecaster() if speculative else None
        self.spec_bank = (
            SpeculationBank(
                capacity=(
                    spec_bank_size
                    if spec_bank_size is not None
                    else max(4, 4 * spec_k)
                ),
                tolerance=spec_tolerance,
            )
            if speculative
            else None
        )
        # Event->published-placement latency of the most recent tick, ms
        # (presolve excluded — it runs after publish, off the serving
        # path). The bench's speculation arms read this instead of timing
        # handle(), which would bill background presolve work to serving.
        self.last_serve_ms: float = 0.0
        self.health = HEALTH_HEALTHY
        self.quarantined: "deque[tuple]" = deque(maxlen=100)
        self._consec_failures = 0
        self._clean_streak = 0
        self._breaker_open = False
        self._breaker_cooldown_left = 0
        self._executor = None  # lazy; only a deadline needs the worker
        self._abandoned = None  # future of a deadline-abandoned solve
        self._published: Optional[PlacementView] = None
        self._published_at: float = 0.0
        # Snapshot-restore accounting: set by load_state(); the FIRST tick
        # after a restore proves whether the warm state survived the round
        # trip (counter `warm_resumes`) or the service paid a cold re-solve
        # it was promised not to (`cold_resumes`). One tick only — later
        # cold ticks are ordinary identity changes, not restore failures.
        # A first tick whose identity was NOT in the restored pool (e.g. a
        # structural event landed first) proves nothing about the restore
        # and counts as neither (`resume_identity_changed`).
        self._restore_pending = False
        self._restored_keys: frozenset = frozenset()
        # -- observability (distilp_tpu.obs), all opt-in. The tracer falls
        # back to the shared NOOP twin so every instrumentation site below
        # is a constant-cost no-op when tracing is off; the flight recorder
        # and the XLA-profile hook stay None/dormant unless configured —
        # the default tick path must remain byte-identical (pinned by the
        # smoke gates' counter assertions).
        self.tracer = NOOP_TRACER if tracer is None else tracer
        self._span = NOOP_SPAN  # the in-flight tick's span (handle())
        self._flight = flight
        self._flight_key = flight_key
        self._flight_prev_counters: dict = {}
        self._flight_pending: Optional[str] = None
        self._last_lp_backend: Optional[str] = None
        # Per-tick diagnostics for the flight record: the exception CLASS
        # behind this tick's solve_attempt_failed / spec_presolve_failed
        # counters (a bare counter bump is invisible post-mortem), and the
        # conv_* digest when solver diagnostics ran. Reset per handle().
        self._tick_exc: dict = {}
        self._tick_conv: Optional[dict] = None
        # This tick's compile-ledger delta (obs.compile_ledger): set by
        # _note_compiles when a process ledger is enabled AND the tick's
        # own threads paid at least one XLA compile; rides the flight
        # record so a slow tick's post-mortem says WHY it was slow.
        self._tick_compile: Optional[dict] = None
        # This tick's memory watermark (obs.memory): set by _note_memory
        # on ticks where the throttled ledger actually took a FRESH
        # sample (live-array walks are ~3 us/array — unthrottled per-tick
        # walks would blow the obs overhead budget); rides the tick span
        # and the flight record. _mem_prev_live is the previous fresh
        # sample's live bytes — the per-tick delta the leak gate's
        # post-mortem reads.
        self._tick_mem: Optional[dict] = None
        self._mem_prev_live: Optional[int] = None
        # Whether THIS tick applied a structural (identity-changing)
        # event — the memory ledger re-pins its leak baseline there
        # (structural re-allocation is provisioning, not a leak).
        self._tick_structural = False
        self.jax_profile_dir = jax_profile_dir
        self._jax_profiled = False
        if solve_on_init:
            self.metrics.inc("init_solve")
            self._tick(structural=None)

    def _make_replanner(self) -> StreamingReplanner:
        search = {"lp_backend": self.lp_backend}
        if self.pdhg_iters is not None:
            search["pdhg_iters"] = self.pdhg_iters
        if self.pdhg_restart_tol is not None:
            search["pdhg_restart_tol"] = self.pdhg_restart_tol
        if self.mesh_shards is not None:
            search["mesh_shards"] = self.mesh_shards
        if self.pdhg_dtype is not None:
            search["pdhg_dtype"] = self.pdhg_dtype
        planner = StreamingReplanner(
            mip_gap=self.mip_gap,
            kv_bits=self.kv_bits,
            backend=self.backend,
            moe=self.moe,
            cold_start=self.cold_start,
            search=search,
            diagnostics=self.diagnostics,
        )
        planner.metrics = self.metrics  # tick modes funnel into one snapshot
        return planner

    # -- the event loop body ----------------------------------------------

    def handle(self, event, pressure: bool = False) -> PlacementView:
        """Apply one event and replan; returns the freshly published view.

        ``pressure`` is the admission-control hint (gateway ingest sets it
        when the owning worker's queue is past its degrade threshold): a
        pressure tick whose exact speculation probe misses may serve a
        banked NEAR-match (``mode='spec_near'``, see ``_spec_near_probe``)
        instead of queueing a solve it is already late for. False (the
        default, and the only value non-gateway callers pass) leaves the
        tick path byte-identical.

        Structural events route through the warm pool under their new key;
        drift events tick the current key's replanner warm. A failed solve
        (no feasible placement for the mutated fleet) keeps the previous
        placement published and is visible as ``tick_failed`` + a growing
        ``events_behind`` on ``latest()``.

        Input quarantine: an event carrying non-finite or contradictory
        values (``events.validate_event``), or one the strict
        ``FleetState.apply`` rejects (unknown device, duplicate join, ...),
        never mutates the fleet — it is counted, recorded on
        ``self.quarantined``, and the last-known-good placement stays
        served. Before any placement exists a poisoned event is still an
        error: there is nothing safe to serve instead.

        Observability wrapper: the whole handle runs inside a ``sched.tick``
        span (the root of the trace in single-scheduler serving; a child of
        the gateway ingest span when a worker attached its context), and —
        when a flight recorder is attached — appends one tick record (mode,
        health, counter deltas, span ids) to the shard's ring on every
        exit path, raising ones included.
        """
        span = self.tracer.span(
            "sched.tick",
            attrs={"kind": getattr(event, "kind", type(event).__name__)},
        )
        with span:
            self._span = span
            self._tick_exc = {}
            self._tick_conv = None
            self._tick_compile = None
            self._tick_mem = None
            self._tick_structural = False
            led = _compile_ledger.current()
            tok = led.seq() if led is not None else 0
            view: Optional[PlacementView] = None
            try:
                view = self._handle(event, pressure=pressure)
                return view
            finally:
                if led is not None:
                    # BEFORE the flight note: the compile counters must be
                    # in this tick's counter delta, not the next one's.
                    self._note_compiles(led, tok, span)
                mled = _memory.current()
                if mled is not None:
                    # No-solve ticks (spec hits, breaker short-circuits,
                    # quarantines) still watermark at tick exit; solved
                    # ticks already sampled on the sched.solve span. Same
                    # ordering contract as the compile note: the memory
                    # counters/attrs land in THIS tick's record. A
                    # structural tick then re-pins the leak baseline —
                    # its allocation is provisioning, not a leak.
                    if self._tick_mem is None:
                        self._note_memory(mled, span)
                    if self._tick_structural:
                        mled.note_structural()
                span.set_attr("mode", view.mode if view is not None else "error")
                if self._flight is not None:
                    self._flight_note(event, view, span)
                self._span = NOOP_SPAN

    def handle_coalesced(
        self, events: Sequence, pressure: bool = False
    ) -> PlacementView:
        """Apply a run of queued events and solve ONCE, at the newest state.

        The gateway's admission-control coalescing hook: when several
        drift events for the same shard are queued behind one solve, each
        is still validated, quarantined-or-applied and counted exactly as
        ``handle`` would (fleet ``seq`` advances per applied event — the
        per-shard seq accounting the shed contract audits), but only the
        final state pays a solve; the folded events are counted
        ``events_coalesced``. All waiters are served the one resulting
        view. A single-event batch IS ``handle`` — same path, same spans.

        Callers coalesce drift runs (the gateway treats structural events
        as barriers); a structural event in the batch is still handled
        correctly — it just makes the one solve a structural tick.
        """
        events = list(events)
        if not events:
            raise ValueError("handle_coalesced needs at least one event")
        if len(events) == 1:
            return self.handle(events[0], pressure=pressure)
        last = events[-1]
        span = self.tracer.span(
            "sched.tick",
            attrs={
                "kind": getattr(last, "kind", type(last).__name__),
                "coalesced": len(events),
            },
        )
        with span:
            self._span = span
            self._tick_exc = {}
            self._tick_conv = None
            self._tick_compile = None
            self._tick_mem = None
            self._tick_structural = False
            led = _compile_ledger.current()
            tok = led.seq() if led is not None else 0
            view: Optional[PlacementView] = None
            try:
                view = self._handle_coalesced(events, pressure)
                return view
            finally:
                if led is not None:
                    self._note_compiles(led, tok, span)
                mled = _memory.current()
                if mled is not None:
                    if self._tick_mem is None:
                        self._note_memory(mled, span)
                    if self._tick_structural:
                        mled.note_structural()
                span.set_attr("mode", view.mode if view is not None else "error")
                if self._flight is not None:
                    self._flight_note(last, view, span)
                self._span = NOOP_SPAN

    # -- cross-shard combine path (distilp_tpu.combine) --------------------
    #
    # A combined tick splits handle_coalesced's one synchronous solve into
    # prepare (apply events, pack this shard's instance) and adopt (redeem
    # the shard's lane of the batched solve) so the gateway's SolveCombiner
    # can execute many shards' solves as ONE vmapped dispatch in between.
    # Everything around the solve — event validation/quarantine, the gate
    # short-circuits, breaker accounting, publish, speculation refill — is
    # the per-shard code, shared, not copied.

    def prepare_combine(self, events: Sequence, pressure: bool = False,
                        M_pad: Optional[int] = None):
        """Apply a coalesced run of drift events and PACK the resulting
        solve instead of executing it. Returns ``(ticket, view)`` — exactly
        one is non-None. A view means the tick was fully served here: a
        gate short-circuit (spec hit, breaker, quarantine) or a local
        fallback solve for ticks the combiner cannot batch (structural,
        MoE, half-open probe, first post-restore tick — counted
        ``combine_local``). A ticket means the solve is deferred: hand
        ``ticket.prep.instance`` to the combiner and redeem the lane with
        ``adopt_combine``."""
        events = list(events)
        if not events:
            raise ValueError("prepare_combine needs at least one event")
        last = events[-1]
        span = self.tracer.span(
            "sched.tick",
            attrs={
                "kind": getattr(last, "kind", type(last).__name__),
                "coalesced": len(events),
                "combine": True,
            },
        )
        with span:
            self._span = span
            self._tick_exc = {}
            self._tick_conv = None
            self._tick_compile = None
            self._tick_mem = None
            self._tick_structural = False
            led = _compile_ledger.current()
            tok = led.seq() if led is not None else 0
            view: Optional[PlacementView] = None
            ticket = None
            try:
                applied = 0
                structural = False
                for ev in events:
                    reason = validate_event(ev)
                    if reason is not None:
                        self._quarantine_note(ev, reason)
                        continue
                    try:
                        s = self.fleet.apply(ev)
                    except (ValueError, TypeError) as e:
                        self._quarantine_note(ev, f"{type(e).__name__}: {e}")
                        continue
                    self._absorbed(ev, s)
                    if applied:
                        self.metrics.inc("events_coalesced")
                    applied += 1
                    structural = structural or s
                if not applied:
                    if self._published is None:
                        raise ValueError(
                            "every coalesced event was quarantined before "
                            "any placement was published; nothing safe to "
                            "serve"
                        )
                    view = self.latest()
                    return None, view
                self._tick_structural = structural
                gview, key, planner, probing = self._tick_gate(
                    structural, pressure
                )
                if gview is not None:
                    view = gview
                    return None, view
                # Ticks the combiner cannot batch solve locally, now:
                # structural ticks re-shape the instance (and are barriers
                # at the gateway anyway), the half-open breaker probe must
                # prove recovery with a real solve it owns, and the first
                # post-restore tick IS the warm-resume proof.
                if structural or probing or self._restore_pending:
                    self.metrics.inc("combine_local")
                    view = self._tick_solve(structural, key, planner, probing)
                    return None, view
                t0 = time.perf_counter()
                try:
                    prep = planner.prepare(
                        self.fleet.device_list(), self.fleet.model, M_pad=M_pad
                    )
                except (RuntimeError, ValueError, NotImplementedError) as e:
                    self.metrics.inc("tick_failed")
                    self.metrics.inc("tick_failed_drift")
                    self._last_error = f"{type(e).__name__}: {e}"
                    self._solve_failed(probing)
                    if self._published is None:
                        raise
                    view = self.latest()
                    return None, view
                if prep is None:
                    # MoE shard (load-factor fixed point / margin ladder
                    # are iterative) or non-jax backend: per-shard path.
                    self.metrics.inc("combine_local")
                    view = self._tick_solve(structural, key, planner, probing)
                    return None, view
                self.metrics.inc("combine_prepared")
                ticket = CombineTicket(
                    key=key, planner=planner, prep=prep,
                    seq=self.fleet.seq, t0=t0, event=last,
                )
                return ticket, None
            finally:
                if led is not None:
                    self._note_compiles(led, tok, span)
                mled = _memory.current()
                if mled is not None:
                    if self._tick_mem is None:
                        self._note_memory(mled, span)
                    if self._tick_structural:
                        mled.note_structural()
                span.set_attr(
                    "mode",
                    view.mode if view is not None
                    else ("combine_pending" if ticket is not None else "error"),
                )
                if self._flight is not None and view is not None:
                    self._flight_note(last, view, span)
                self._span = NOOP_SPAN

    def adopt_combine(self, ticket, decoded=None,
                      error: Optional[BaseException] = None) -> PlacementView:
        """Redeem one lane of a batched solve — the deferred second half of
        a ``prepare_combine`` tick. ``decoded`` is this shard's
        ``(per_k_results, best)`` from ``batchlayout.solve_batch``;
        ``error`` (a combiner-level dispatch failure) falls back to a full
        local tick, counted ``combine_fallback``. A ticket whose fleet has
        advanced past the packed ``seq`` (a structural barrier raced in
        between) is discarded as ``combine_stale`` — the newer published
        view already covers this ticket's events."""
        span = self.tracer.span(
            "sched.tick",
            attrs={"kind": "combine_adopt", "combine": True},
        )
        with span:
            self._span = span
            self._tick_exc = {}
            self._tick_conv = None
            self._tick_compile = None
            self._tick_mem = None
            self._tick_structural = False
            led = _compile_ledger.current()
            tok = led.seq() if led is not None else 0
            view: Optional[PlacementView] = None
            try:
                if error is not None:
                    self.metrics.inc("combine_fallback")
                    self._span.add_event(
                        "combine_fallback",
                        error=f"{type(error).__name__}: {error}",
                    )
                    view = self._tick(structural=False)
                    return view
                if self.fleet.seq != ticket.seq:
                    # The packed instance no longer describes the live
                    # fleet; whoever advanced it published past us.
                    self.metrics.inc("combine_stale")
                    if self._published is not None:
                        view = self.latest()
                    else:
                        view = self._tick(structural=False)
                    return view
                tick_tm: dict = {}
                try:
                    result = ticket.planner.adopt(
                        ticket.prep, decoded, timings=tick_tm
                    )
                except (RuntimeError, ValueError, NotImplementedError) as e:
                    self.metrics.inc("tick_failed")
                    self.metrics.inc("tick_failed_drift")
                    self._last_error = f"{type(e).__name__}: {e}"
                    self._solve_failed(False)
                    if self._published is None:
                        raise
                    view = self.latest()
                    return view
                self._on_clean_solve(False)
                ms = (time.perf_counter() - ticket.t0) * 1e3
                self.metrics.observe("event_to_placement", ms)
                if "lp_backend" in tick_tm:
                    self.metrics.inc(f"lp_backend_{tick_tm['lp_backend']}")
                    self._last_lp_backend = tick_tm["lp_backend"]
                if ticket.planner.last_tick_escalations:
                    # An uncertified lane re-solved per-shard inside
                    # adopt(): the combined path's certification rung.
                    self.metrics.inc("solver_escalations")
                    self.metrics.inc("combine_fallback")
                self.metrics.observe("drift_tick", ms)
                self.metrics.inc("drift_tick_combine")
                view = self._publish(
                    result, "combine", ticket.key, ticket.planner,
                    ticket.prep.devs, ms,
                )
                if self.speculative and self.health == HEALTH_HEALTHY:
                    self._spec_presolve(ticket.key, ticket.planner, result)
                return view
            finally:
                if led is not None:
                    self._note_compiles(led, tok, span)
                mled = _memory.current()
                if mled is not None:
                    if self._tick_mem is None:
                        self._note_memory(mled, span)
                span.set_attr(
                    "mode", view.mode if view is not None else "error"
                )
                if self._flight is not None:
                    self._flight_note(ticket.event, view, span)
                self._span = NOOP_SPAN

    def _handle(self, event, pressure: bool = False) -> PlacementView:
        reason = validate_event(event)
        if reason is not None:
            return self._quarantine(event, reason)
        try:
            structural = self.fleet.apply(event)
        except (ValueError, TypeError) as e:
            return self._quarantine(event, f"{type(e).__name__}: {e}")
        self._tick_structural = structural
        self._absorbed(event, structural)
        return self._tick(structural=structural, pressure=pressure)

    def _handle_coalesced(self, events, pressure: bool) -> PlacementView:
        applied = 0
        structural = False
        for ev in events:
            reason = validate_event(ev)
            if reason is not None:
                self._quarantine_note(ev, reason)
                continue
            try:
                s = self.fleet.apply(ev)
            except (ValueError, TypeError) as e:
                self._quarantine_note(ev, f"{type(e).__name__}: {e}")
                continue
            self._absorbed(ev, s)
            if applied:
                # Every applied event beyond the first folds into the one
                # solve below instead of paying its own.
                self.metrics.inc("events_coalesced")
            applied += 1
            structural = structural or s
        if not applied:
            if self._published is None:
                raise ValueError(
                    "every coalesced event was quarantined before any "
                    "placement was published; nothing safe to serve"
                )
            return self.latest()
        self._tick_structural = structural
        return self._tick(structural=structural, pressure=pressure)

    def _absorbed(self, event, structural: bool) -> None:
        """Post-apply bookkeeping shared by the single and coalesced
        paths: routing counters, bank invalidation, forecaster feed."""
        self.metrics.inc("events_total")
        self.metrics.inc(f"event_{event.kind}")
        self.metrics.inc("structural_events" if structural else "drift_events")
        if self.speculative:
            if structural:
                # Identity changed: drop stale bank entries HERE, on the
                # event path — the probe may be suppressed (unhealthy,
                # half-open, post-restore) exactly when a structural
                # event lands, and stale entries must not squat the LRU.
                stale = self.spec_bank.invalidate(self.fleet.key())
                if stale:
                    self.metrics.inc("spec_stale", stale)
                    self._span.add_event("spec_stale", dropped=stale)
            # APPLIED events only: the quarantine gates already returned
            # for poisoned/contradictory input, so a NaN drift can never
            # corrupt the forecaster's EWMA state silently.
            self.forecaster.observe(self.fleet)

    def _quarantine_note(self, event, reason: str) -> None:
        """Count and record a rejected event (the fleet stays untouched)."""
        kind = getattr(event, "kind", type(event).__name__)
        self.metrics.inc("events_quarantined")
        self.metrics.inc(f"quarantine_{kind}")
        self._span.add_event("quarantined", kind=kind, reason=reason)
        self.quarantined.append((self.fleet.seq, kind, reason))
        self._last_error = f"quarantined {kind}: {reason}"
        self._note_fault()

    def _quarantine(self, event, reason: str) -> PlacementView:
        """Record a rejected event and keep serving the last-known-good."""
        self._quarantine_note(event, reason)
        if self._published is None:
            kind = getattr(event, "kind", type(event).__name__)
            raise ValueError(
                f"poisoned {kind} event before any placement was published "
                f"({reason}); nothing safe to serve"
            )
        return self.latest()

    def _tick(
        self, structural: Optional[bool], pressure: bool = False
    ) -> PlacementView:
        """One replan; ``structural=None`` marks the eventless init solve
        (it times and mode-counts like any tick but belongs to neither
        routing class, so the per-class counters keep summing to events).
        ``pressure`` widens a missed speculation probe to the bank's
        nearest certified match (degraded-mode serving under overload)."""
        view, key, planner, probing = self._tick_gate(structural, pressure)
        if view is not None:
            return view
        return self._tick_solve(structural, key, planner, probing)

    def _tick_gate(self, structural, pressure: bool):
        """The no-solve short-circuits of a tick, factored so the combine
        path (``prepare_combine``) shares them verbatim with ``_tick``:
        fleet quarantine, circuit breaker, speculation-bank probes, then
        the planner-pool fetch. Returns ``(view, key, planner, probing)``
        — a non-None view means the tick is already served."""
        # Second quarantine layer: a poisoned fleet state (however it got
        # here) must never reach build_coeffs. Cheap O(M) scalar scan.
        # Both short-circuits run BEFORE pool.get: a tick that will not
        # solve must not mint (or LRU-evict) warm planners, nor skew the
        # pool hit-rate counters.
        bad = self.fleet.non_finite_reason()
        if bad is not None:
            self.metrics.inc("quarantine_fleet")
            self._span.add_event("quarantine_fleet", reason=bad)
            self._last_error = f"fleet state quarantined: {bad}"
            self._note_fault()
            if self._published is None:
                raise ValueError(f"fleet state is poisoned: {bad}")
            return self._serve_stale("stale"), None, None, False
        # Circuit breaker: while open, cooldown ticks serve degraded with
        # no solve at all; the tick after cooldown falls through as the
        # half-open probe.
        probing = False
        if self._breaker_open:
            if self._breaker_cooldown_left > 0:
                self._breaker_cooldown_left -= 1
                self.metrics.inc("breaker_short_circuit")
                self._span.add_event("breaker_short_circuit")
                return self._serve_stale("degraded"), None, None, False
            probing = True
            self.metrics.inc("breaker_half_open_probe")
            self._span.add_event("breaker_half_open_probe")
        key = self.fleet.key()
        # Speculation bank probe, BEFORE the solve ladder (and before
        # pool.get — a hit must not skew pool hit-rate counters any more
        # than a quarantined tick does). Suppressed on the half-open
        # breaker probe and while unhealthy (a degraded service must
        # actually solve to prove recovery — a bank that kept hitting
        # would stall the clean streak forever), and on the first
        # post-restore tick (that tick IS the warm-resume proof).
        if (
            self.speculative
            and structural is not None
            and not probing
            and not self._restore_pending
            and self.health == HEALTH_HEALTHY
        ):
            view = self._spec_probe(key, structural)
            if view is not None:
                return view, None, None, False
            if pressure:
                # Behind under load: a certified placement from a NEARBY
                # instance beats queueing this solve past its deadline.
                view = self._spec_near_probe(key, structural)
                if view is not None:
                    return view, None, None, False
        planner, _hit = self.pool.get(key)
        return None, key, planner, probing

    def _tick_solve(self, structural, key, planner, probing) -> PlacementView:
        """The solve-and-publish half of a tick (everything after
        ``_tick_gate``), shared by ``_tick`` and the combine path's local
        fallback."""
        devs = self.fleet.device_list()
        t0 = time.perf_counter()
        tick_tm: dict = {}
        solve_span = self.tracer.start_span("sched.solve")
        # Outer try/finally so the solve span is ended on EVERY exit — the
        # handled failure classes below, and any exception type outside
        # them (which would otherwise leak the span right out of the trace
        # a post-mortem needs most). end() is idempotent.
        try:
            try:
                result = self._maybe_profiled_solve(planner, devs, tick_tm)
            except _DeadlineMiss:
                solve_span.add_event("deadline_missed")
                self.metrics.inc("deadline_missed")
                self._last_error = (
                    f"solve deadline ({self.solve_deadline_s:.3f}s) missed"
                )
                self._solve_failed(probing)
                return self._serve_stale("stale")
            except (RuntimeError, ValueError, NotImplementedError) as e:
                solve_span.add_event(
                    "solve_failed", error=f"{type(e).__name__}: {e}"
                )
                self.metrics.inc("tick_failed")
                if structural is not None:
                    self.metrics.inc(
                        "tick_failed_structural" if structural
                        else "tick_failed_drift"
                    )
                self._last_error = f"{type(e).__name__}: {e}"
                self._solve_failed(probing)
                if self._published is None:
                    raise
                return self.latest()
            for k in _SOLVE_SPAN_KEYS:
                if k in tick_tm:
                    solve_span.set_attr(k, tick_tm[k])
            conv = {k: tick_tm[k] for k in _CONV_DIGEST_KEYS if k in tick_tm}
            self._tick_conv = conv or None
            mled = _memory.current()
            if mled is not None:
                # The solve is where allocation happens: the watermark
                # sampled HERE rides the sched.solve span (and, via
                # _tick_mem, the flight record). No-solve ticks (spec
                # hits, short-circuits) fall back to the handle()-exit
                # note instead.
                self._note_memory(mled, solve_span)
        finally:
            solve_span.end()
        self._on_clean_solve(probing)
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.observe("event_to_placement", ms)
        # Device-program work accounting (JAX backend): how many Mehrotra
        # iterations the tick actually executed — the warm-start health
        # gauge next to the tick-mode counters (a drift tick burning the
        # cold budget means the iterate chain broke).
        if "ipm_iters_executed" in tick_tm:
            self.metrics.observe(
                "ipm_iters_executed", tick_tm["ipm_iters_executed"]
            )
        # LP engine echo: which relaxation engine the tick's solve actually
        # ran ('auto' resolves per fleet size) — the observable for the
        # ipm/pdhg crossover in production, next to the tick-mode counters.
        if "lp_backend" in tick_tm:
            self.metrics.inc(f"lp_backend_{tick_tm['lp_backend']}")
            self._last_lp_backend = tick_tm["lp_backend"]
        # The in-solver certification ladder (halda_solve retrying an
        # uncertified dense solve at the MoE-class budget) reports through
        # the timings dict; count it so escalation storms are visible.
        if tick_tm.get("escalated"):
            self.metrics.inc("solver_escalations")
        mode = getattr(planner, "last_tick_mode", None) or "cold"
        if self._restore_pending:
            self._restore_pending = False
            if key not in self._restored_keys:
                # The first post-restore tick changed identity (structural
                # event); a cold solve here is ordinary routing, not a
                # restore failure — flagging it as cold_resumes would page
                # on a perfectly healthy drain/restore cycle.
                self.metrics.inc("resume_identity_changed")
            else:
                self.metrics.inc(
                    "warm_resumes"
                    if mode in ("warm", "margin")
                    else "cold_resumes"
                )
        if structural is not None:
            self.metrics.observe(
                "structural_tick" if structural else "drift_tick", ms
            )
            # Mode per routing class: the acceptance gauge (drift should
            # ride warm/margin, structural may cold-solve) reads these.
            self.metrics.inc(
                f"{'structural' if structural else 'drift'}_tick_{mode}"
            )
        if structural and not result.certified:
            self.metrics.inc("structural_uncertified")
        view = self._publish(result, mode, key, planner, devs, ms)
        if self.speculative and self.health == HEALTH_HEALTHY:
            # AFTER publish: presolving likely futures is background work
            # and must never sit between an event and its placement. Same
            # health gate as the probe: while the service recovers, the
            # bank cannot be served from, so presolving would only delay
            # the recovery ticks it rides behind.
            self._spec_presolve(key, planner, result)
        return view

    def _publish(
        self, result: HALDAResult, mode: str, key, planner, devs, ms: float
    ) -> PlacementView:
        """Publish a tick's served placement — the ONE publication path
        (solved ticks and speculative hits both land here, so risk
        scoring, the publish span and the serve clock cannot diverge).
        ``planner`` may be None (a spec hit whose pooled planner was
        LRU-evicted): risk scoring then prices without load factors.
        """
        with self.tracer.span("sched.publish") as pspan:
            served, twin_p95, switched = result, None, False
            if self.risk_aware:
                served, twin_p95, switched = self._risk_select(
                    devs, result, planner
                )
            self._published = PlacementView(
                result=served,
                seq=self.fleet.seq,
                fleet_seq=self.fleet.seq,
                events_behind=0,
                age_s=0.0,
                # A switched tick serves a placement this tick did NOT
                # produce; 'risk' keeps the mode field honest (see
                # PlacementView).
                mode="risk" if switched else mode,
                key=key,
                twin_p95_s=twin_p95,
                risk_selected=switched,
            )
            pspan.set_attr("mode", self._published.mode)
            pspan.set_attr("certified", served.certified)
        self._published_at = time.monotonic()
        self.last_serve_ms = ms
        return self._published

    # -- speculative replanning (sched.forecast + sched.speculate) ---------

    def _spec_probe(self, key, structural) -> Optional[PlacementView]:
        """Serve a pre-solved placement if the post-event fleet digests to
        a banked entry; None = honest miss, fall through to the ladder.

        A hit donates the scenario solve (incumbent, duals, LP iterates)
        as the pooled replanner's warm seed — the next REAL tick starts
        from the future that actually happened. A miss touches nothing:
        speculative work never writes warm state it did not serve.
        """
        t0 = time.perf_counter()
        digest = self.spec_bank.digest(self.fleet)
        entry = self.spec_bank.probe(digest, key)
        if entry is None or not entry.result.certified:
            # Only certified placements are banked; the certificate guard
            # is belt-and-braces against a blob restored from elsewhere.
            self.metrics.inc("spec_miss")
            return None
        self.metrics.inc("spec_hit")
        self._span.add_event(
            "spec_hit", digest=digest, weight=round(entry.weight, 4)
        )
        devs = self.fleet.device_list()
        # Warm donation: seed the next tick from the served scenario's
        # iterates (shape recomputed the way StreamingReplanner.step
        # would, so the seed engages instead of being shape-rejected).
        # peek, not get: serving from the bank must neither skew the pool
        # hit-rate counters nor mint/evict planners — a key whose planner
        # was LRU-evicted simply forgoes the donation.
        planner = self.pool.peek(key)
        if planner is not None:
            from ..solver.moe import model_has_moe_components

            use_moe = (
                model_has_moe_components(self.fleet.model)
                if planner.moe is None
                else bool(planner.moe)
            )
            planner.last = entry.result
            planner._last_shape = (len(devs), self.fleet.model.L, use_moe)
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.observe("event_to_placement", ms)
        self.metrics.observe("spec_hit_ms", ms)
        self.metrics.observe(
            "structural_tick" if structural else "drift_tick", ms
        )
        self.metrics.inc(
            f"{'structural' if structural else 'drift'}_tick_spec"
        )
        return self._publish(entry.result, "spec", key, planner, devs, ms)

    def _spec_near_probe(self, key, structural) -> Optional[PlacementView]:
        """Degraded-mode serving: the bank's nearest certified match.

        Runs ONLY on pressure ticks whose exact probe missed. A hit serves
        a placement certified on an instance within ``spec_near_radius``
        tolerance buckets of the live one (worst channel), published as
        ``mode='spec_near'`` so readers can see the answer is approximate
        by construction, not merely stale. No warm-state donation: the
        entry's iterates belong to a nearby-but-different instance, and
        the next unpressured solve should seed from the incumbent chain
        as usual. A miss (nothing close enough banked) falls through to
        the normal solve — the queue is behind either way, and solving is
        the only remaining answer.
        """
        t0 = time.perf_counter()
        devs = self.fleet.device_list()
        found = self.spec_bank.nearest(
            devs, self.fleet.model, key, max_radius=self.spec_near_radius
        )
        if found is None:
            self.metrics.inc("spec_near_miss")
            return None
        entry, dist = found
        self.metrics.inc("spec_near_hit")
        self._span.add_event(
            "spec_near_hit", distance=dist, weight=round(entry.weight, 4)
        )
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.observe("event_to_placement", ms)
        self.metrics.observe(
            "structural_tick" if structural else "drift_tick", ms
        )
        self.metrics.inc(
            f"{'structural' if structural else 'drift'}_tick_spec_near"
        )
        return self._publish(
            entry.result, "spec_near", key, self.pool.peek(key), devs, ms
        )

    def _spec_presolve(self, key, planner, result: HALDAResult) -> None:
        """Refill the bank after a solved tick: bank the fresh solve under
        its own digest (oscillating churn returns to it), then pre-solve
        the forecaster's K candidate futures in ONE vmapped scenario
        dispatch, warm-seeded from the incumbent.

        Best-effort by design: any failure (out-of-class drift splitting
        the static half, an infeasible future, a CPU-only build) costs
        only this tick's speculation, never the serving path — and reads
        the replanner's warm state without ever writing it.
        """
        bank = self.spec_bank
        # Certified placements only, incumbents included: a banked entry
        # is served verbatim later, with no ladder to escalate it — an
        # uncertified one would silently bypass --fail-uncertified.
        if result.certified:
            bank.put(
                bank.digest(self.fleet),
                BankEntry(
                    result=result, key=key, weight=1.0,
                    solved_seq=self.fleet.seq,
                    buckets=bucket_vector(
                        self.fleet.device_list(), self.fleet.model,
                        bank.tolerance,
                    ),
                ),
            )
        if self.backend != "jax":
            return  # scenario batching is a JAX-backend path
        candidates = self.forecaster.forecast(self.fleet, self.spec_k)
        fresh = []
        for devs_c, w in candidates:
            d = candidate_digest(
                devs_c, self.fleet.model, key, bank.tolerance
            )
            if d not in bank:
                fresh.append((d, devs_c, w))
        if not fresh:
            return
        t0 = time.perf_counter()
        with self.tracer.span(
            "sched.speculate", attrs={"batch": len(fresh)}
        ) as span:
            try:
                results = presolve_candidates(
                    [(devs_c, w) for _, devs_c, w in fresh],
                    self.fleet.model,
                    k_candidates=self.k_candidates,
                    mip_gap=self.mip_gap,
                    kv_bits=self.kv_bits,
                    moe=self.moe,
                    warm=result,
                    load_factors=getattr(planner, "_load_factors", None),
                    lp_backend=self.lp_backend,
                    pdhg_iters=self.pdhg_iters,
                    pdhg_restart_tol=self.pdhg_restart_tol,
                    pdhg_dtype=self.pdhg_dtype,
                )
            except (RuntimeError, ValueError, NotImplementedError) as e:
                self.metrics.inc("spec_presolve_failed")
                # Flight-record attribution: the known row-scale-crossing
                # ValueError class of presolve failure must be visible in
                # the post-mortem, not just a counter bump.
                self._tick_exc["spec_presolve_failed"] = type(e).__name__
                span.add_event(
                    "presolve_failed", error=f"{type(e).__name__}: {e}"
                )
                return
            banked = 0
            for (d, devs_c, w), res in zip(fresh, results):
                if not res.certified:
                    continue  # never bank what --fail-uncertified rejects
                banked += 1
                bank.put(
                    d,
                    BankEntry(
                        result=res, key=key, weight=w,
                        solved_seq=self.fleet.seq,
                        buckets=bucket_vector(
                            devs_c, self.fleet.model, bank.tolerance
                        ),
                    ),
                )
            if banked:
                self.metrics.inc("spec_presolve", banked)
            span.set_attr("banked", banked)
        self.metrics.observe(
            "spec_presolve_ms", (time.perf_counter() - t0) * 1e3
        )

    def speculation_snapshot(self) -> dict:
        """Plain-dict speculation view (serve summary / tests)."""
        c = self.metrics.counters
        hits = c.get("spec_hit", 0)
        misses = c.get("spec_miss", 0)
        probes = hits + misses
        return {
            "enabled": self.speculative,
            "hits": hits,
            "misses": misses,
            "near_hits": c.get("spec_near_hit", 0),
            "presolved": c.get("spec_presolve", 0),
            "presolve_failed": c.get("spec_presolve_failed", 0),
            "stale": c.get("spec_stale", 0),
            "bank_size": len(self.spec_bank) if self.speculative else 0,
            "hit_rate": round(hits / probes, 4) if probes else 0.0,
        }

    # -- fault-hardened solve path ----------------------------------------

    def _solve_with_guards(self, planner, devs, tick_tm: dict):
        """One tick's solve under the reliability policy: optional fault
        hook, bounded exponential-backoff retries, wall-clock deadline.

        With every knob at its default (no deadline, no retries, no hook)
        this is exactly ``planner.step(...)`` — one call, no threads, no
        copies. The first-ever solve is exempt from the deadline: with
        nothing published there is no last-known-good to serve instead,
        so abandoning the solve could only turn a slow start into an
        outage.
        """
        deadline = self.solve_deadline_s if self._published is not None else None
        attempts = max(1, self.max_retries + 1)
        last_exc: Optional[Exception] = None
        for attempt in range(attempts):
            if attempt:
                self.metrics.inc("solve_retries")
                self._span.add_event("solve_retry", attempt=attempt)
                time.sleep(
                    min(
                        self.retry_backoff_s * (2 ** (attempt - 1)),
                        self.retry_backoff_max_s,
                    )
                )
            try:
                if deadline is None:
                    result = self._attempt(planner, devs, self.fleet.model,
                                           tick_tm, attempt)
                else:
                    result = self._attempt_deadline(planner, devs, tick_tm,
                                                    attempt, deadline)
            except _DeadlineMiss:
                raise  # a miss is a tick-level outcome, not retryable
            except (RuntimeError, ValueError, NotImplementedError) as e:
                self.metrics.inc("solve_attempt_failed")
                # The exception CLASS rides into the tick's flight record
                # (the counter alone is a bare bump post-mortem).
                self._tick_exc["solve_attempt_failed"] = type(e).__name__
                self._span.add_event(
                    "solve_attempt_failed",
                    attempt=attempt,
                    error=f"{type(e).__name__}: {e}",
                )
                last_exc = e
                continue
            if attempt:
                self.metrics.inc("solve_retry_success")
            return result
        raise last_exc  # every attempt failed

    def _maybe_profiled_solve(self, planner, devs, tick_tm: dict):
        """The solve, optionally wrapped in an XLA profiler trace.

        ``jax_profile_dir`` (``serve --jax-profile-dir``) captures the
        FIRST solve tick only — the cold solve whose compile+execute
        profile the TPU-reclamation work wants — then steps aside; the
        profiler is process-global, so the capture covers the solve even
        when the deadline path runs it on the worker thread.
        """
        if self.jax_profile_dir is not None and not self._jax_profiled:
            self._jax_profiled = True
            import jax  # lazy: only a profiling run pays the import here

            self._span.add_event("jax_profile", dir=str(self.jax_profile_dir))
            with jax.profiler.trace(str(self.jax_profile_dir)):
                return self._solve_with_guards(planner, devs, tick_tm)
        return self._solve_with_guards(planner, devs, tick_tm)

    def _attempt(self, planner, devs, model, tick_tm: dict, attempt: int):
        if self.fault_hook is not None:
            self.fault_hook(attempt)
        return planner.step(
            devs, model, k_candidates=self.k_candidates, timings=tick_tm
        )

    def _attempt_deadline(self, planner, devs, tick_tm, attempt, deadline):
        """Run the attempt on the daemon worker, bounded by the deadline.

        An overrun solve cannot be interrupted (it is deep inside jit'd
        device code); it is *abandoned*: the service serves stale and the
        worker finishes in the background (daemon thread — it can never
        block process exit). The next tick first drains the abandoned
        attempt (bounded by one more deadline) before dispatching fresh
        work — one solve in flight, ever, so planner warm state is never
        written by two solves at once. Device/model profiles are
        deep-copied for the worker because later events mutate them in
        place while an abandoned solve may still be reading them. Known
        skew, accepted: an abandoned solve that eventually finishes still
        reports its tick through the shared metrics sink (record_tick)
        even though its result is discarded — the drain counter
        (``abandoned_solves_drained``) bounds how many ticks that can be.
        """
        if self._executor is None:
            self._executor = _SolveWorker()
        if self._abandoned is not None:
            box, done = self._abandoned
            if not done.wait(timeout=deadline):
                self.metrics.inc("deadline_backlog")
                raise _DeadlineMiss()
            # Finished (result or failure): either way it was already
            # billed as a deadline miss; discard and move on.
            self.metrics.inc("abandoned_solves_drained")
            self._abandoned = None
        devs_snap = [d.model_copy(deep=True) for d in devs]
        model_snap = self.fleet.model.model_copy(deep=True)
        box, done = self._executor.submit(
            lambda: self._attempt(planner, devs_snap, model_snap, tick_tm,
                                  attempt)
        )
        if not done.wait(timeout=deadline):
            self._abandoned = (box, done)
            raise _DeadlineMiss()
        if "exc" in box:
            raise box["exc"]
        return box["result"]

    def _solve_failed(self, probing: bool) -> None:
        """Consecutive-failure + breaker bookkeeping after a failed tick."""
        self._consec_failures += 1
        self._note_fault()
        if probing:
            # Half-open probe failed: straight back to open, full cooldown.
            self.metrics.inc("breaker_reopen")
            self._span.add_event("breaker_reopen")
            self._breaker_cooldown_left = self.breaker_cooldown
            return
        if (
            self.breaker_threshold > 0
            and not self._breaker_open
            and self._consec_failures >= self.breaker_threshold
        ):
            self._breaker_open = True
            self._breaker_cooldown_left = self.breaker_cooldown
            self.metrics.inc("breaker_open")
            self._span.add_event("breaker_open")
            self._set_health(HEALTH_BROKEN)
            if self._flight is not None:
                # Post-mortem moment: the dump happens at the END of this
                # handle (after the tick's own record lands in the ring),
                # so the breaker-open tick is IN its own post-mortem.
                self._flight_pending = "breaker_open"

    def _on_clean_solve(self, probing: bool) -> None:
        """A solve succeeded: close the breaker (if probing) and advance
        the recovery streak toward healthy."""
        self._consec_failures = 0
        if probing:
            self._breaker_open = False
            self._breaker_cooldown_left = 0
            self.metrics.inc("breaker_close")
            self._span.add_event("breaker_close")
            self._set_health(HEALTH_DEGRADED)  # until the streak clears it
        self._clean_streak += 1
        if (
            self.health != HEALTH_HEALTHY
            and not self._breaker_open
            and self._clean_streak >= self.healthy_after
        ):
            self._set_health(HEALTH_HEALTHY)
            self.metrics.inc("health_recovered")

    def _note_fault(self) -> None:
        """Any fault (quarantine, miss, failure) degrades health and resets
        the clean streak; an open breaker pins health at broken."""
        self._clean_streak = 0
        self._set_health(
            HEALTH_BROKEN if self._breaker_open else HEALTH_DEGRADED
        )

    def _set_health(self, state: str) -> None:
        """Health assignment with the transition recorded as a span event
        (only actual CHANGES — repeated faults at the same state are
        already visible as their own events)."""
        if state != self.health:
            self.health = state
            self._span.add_event("health", state=state)

    def _serve_stale(self, mode: str) -> PlacementView:
        """Re-serve the last-known-good placement under a degraded mode.

        The published view's ``mode`` is rewritten ('stale' | 'degraded')
        so readers of ``latest()`` see HOW the current answer is being
        served, not how it was once produced; ``seq``/``events_behind``
        already carry how far behind it is.
        """
        if self._published is None:
            raise RuntimeError(
                "no placement published yet; cannot serve a stale answer"
            )
        if self._published.mode != mode:
            self._published = self._published._replace(mode=mode)
        self.metrics.inc(f"served_{mode}")
        self._span.add_event("served_stale", mode=mode)
        return self.latest()

    def _solve_threads(self) -> set:
        """Thread idents this tick's solves (and presolves) may compile
        on: the handling thread itself plus the deadline worker. The
        compile-ledger capture filters on these so concurrent shards'
        compiles are never cross-billed to this scheduler's tick."""
        import threading

        threads = {threading.get_ident()}
        if self._executor is not None and self._executor.ident is not None:
            threads.add(self._executor.ident)
        return threads

    def _note_compiles(self, led, token: int, span) -> None:
        """Attribute this tick's compile-ledger events: counters
        (``compiles``/``compile_cache_hits``/``recompile_storms`` +
        the ``compile_ms`` hist) ride the shared metrics sink — and
        therefore ``timeline_sample``'s ``c.*`` series and the gateway's
        shard aggregation — while the event detail lands on the tick
        span and the flight record. Zero events = zero work, and with no
        ledger enabled this is never called (the byte-identical pin)."""
        events = led.events_since(token, threads=self._solve_threads())
        if not events:
            return
        n = len(events)
        hits = sum(1 for e in events if e.get("cache") == "hit")
        ms = sum(e.get("compile_ms") or 0.0 for e in events)
        # Episode TRANSITIONS only (ev["storm_start"]), never the per-
        # event storm flags: the counter must agree with the ledger's
        # `storms` total and the c.recompile_storms timeline series —
        # one alarm per episode, however many compiles it contains.
        storms = sum(1 for e in events if e.get("storm_start"))
        causes: dict = {}
        for e in events:
            causes[e["cause"]] = causes.get(e["cause"], 0) + 1
        self.metrics.inc("compiles", n)
        if hits:
            self.metrics.inc("compile_cache_hits", hits)
        if storms:
            self.metrics.inc("recompile_storms", storms)
        self.metrics.observe("compile_ms", ms)
        span.set_attr("compiles", n)
        span.set_attr("compile_ms", round(ms, 3))
        span.set_attr(
            "compile_causes",
            ",".join(f"{k}:{v}" for k, v in sorted(causes.items())),
        )
        self._tick_compile = {
            "count": n,
            "ms": round(ms, 3),
            "cache_hits": hits,
            "causes": causes,
            "entries": sorted({e["entry"] for e in events}),
        }
        if storms:
            self._tick_compile["storms"] = storms
            span.add_event("recompile_storm", count=storms)
            if self._flight is not None and self._flight_pending is None:
                # A storm is a post-mortem moment of its own class: dump
                # the ring after this tick's record lands (same deferred
                # shape as breaker_open, never clobbering one).
                self._flight_pending = "recompile_storm"

    def _note_memory(self, mled, span) -> None:
        """Attribute this tick's memory watermark (obs.memory): one
        throttled ledger sample; on ticks where a FRESH sample landed the
        live/RSS bytes (+ the live-byte delta vs the previous fresh
        sample — the leak gate's per-tick view) ride the tick span, the
        ``mem_live_mb``/``mem_rss_mb`` hists and the flight record. A
        cached (throttled) sample records nothing — attaching a stale
        watermark to this tick would claim a measurement that did not
        happen. With no ledger enabled this is never called (the
        byte-identical pin)."""
        rec = mled.sample()
        if not rec.get("fresh"):
            return
        self.metrics.inc("mem_samples")
        live = rec.get("live_bytes")
        rss = rec.get("rss_bytes")
        tick_mem: dict = {}
        if live is not None:
            self.metrics.observe("mem_live_mb", live / 1e6)
            span.set_attr("mem_live_bytes", live)
            tick_mem["live_bytes"] = live
            prev = self._mem_prev_live
            if prev is not None:
                span.set_attr("mem_live_delta", live - prev)
                tick_mem["live_delta"] = live - prev
            self._mem_prev_live = live
        if rss is not None:
            self.metrics.observe("mem_rss_mb", rss / 1e6)
            span.set_attr("mem_rss_bytes", rss)
            tick_mem["rss_bytes"] = rss
        if tick_mem:
            self._tick_mem = tick_mem

    def _flight_note(self, event, view: Optional[PlacementView], span) -> None:
        """Append this tick's flight record; fire any pending post-mortem.

        Counter DELTAS, not totals: the record answers "what did THIS tick
        do" (one quarantine? a retry plus a breaker transition?) without
        the reader diffing snapshots. The span ids tie the record to the
        trace when tracing is on (None otherwise). Runs only with a
        recorder attached — the default path never builds the dicts.
        """
        counters = dict(self.metrics.counters)
        prev = self._flight_prev_counters
        delta = {
            k: v - prev.get(k, 0)
            for k, v in counters.items()
            if v != prev.get(k, 0)
        }
        self._flight_prev_counters = counters
        ctx = span.context()
        rec = {
            "seq": self.fleet.seq,
            "kind": getattr(event, "kind", type(event).__name__),
            "mode": view.mode if view is not None else "error",
            "health": self.health,
            "lp_backend": self._last_lp_backend,
            "trace_id": ctx.trace_id if ctx is not None else None,
            "span_id": ctx.span_id if ctx is not None else None,
            "counters_delta": delta,
        }
        if self._tick_exc:
            # Exception classes behind this tick's failure counters
            # (solve_attempt_failed / spec_presolve_failed): the counter
            # says a solve raised, this says WHAT raised.
            rec["exc"] = dict(self._tick_exc)
        if self._tick_conv is not None:
            # Solver-diagnostics digest (Scheduler(diagnostics=True)): the
            # tick's convergence facts next to its mode/health/deltas.
            rec["convergence"] = dict(self._tick_conv)
        if self._tick_compile is not None:
            # A tick that paid an XLA compile says so — and why (cause
            # taxonomy + which entry points): the multi-second span a
            # post-mortem would otherwise call 'unexplained'.
            rec["compile"] = dict(self._tick_compile)
        if self._tick_mem is not None:
            # The tick's memory watermark (fresh samples only): a leak's
            # post-mortem reads which tick the live bytes stepped on.
            rec["mem"] = dict(self._tick_mem)
        if self.speculative:
            # The post-mortem question speculation adds: was THIS tick a
            # hit or a miss, and how full was the bank when it happened?
            rec["spec"] = {
                "hit": delta.get("spec_hit", 0) > 0,
                "miss": delta.get("spec_miss", 0) > 0,
                "bank": len(self.spec_bank),
            }
        self._flight.record(self._flight_key, rec)
        if self._flight_pending is not None:
            reason, self._flight_pending = self._flight_pending, None
            path = self._flight.trigger(self._flight_key, reason, rec)
            if path is not None:
                self.metrics.inc("flight_dumps")

    def health_snapshot(self) -> dict:
        """Plain-dict health view for the serve CLI / metrics endpoint."""
        return {
            "state": self.health,
            "breaker_open": self._breaker_open,
            "breaker_cooldown_left": self._breaker_cooldown_left,
            "consecutive_failures": self._consec_failures,
            "clean_streak": self._clean_streak,
            "quarantined_events": len(self.quarantined),
            "last_error": self._last_error,
        }

    def close(self) -> None:
        """Release the deadline worker (no-op when never used). The worker
        is a daemon thread, so even without close() an abandoned solve
        cannot block process exit."""
        if self._executor is not None:
            self._executor.stop()
            self._executor = None
            self._abandoned = None

    def _risk_select(self, devs, fresh: HALDAResult, planner):
        """Score the fresh solve + cached pool incumbents on the twin.

        Candidates are every pooled replanner's last placement that is
        structurally executable on the CURRENT fleet (right device count,
        window sums, offload/expert cover — ``twin.placement_applicable``);
        each is priced by Monte-Carlo p95 plus a feasibility-violation
        penalty under one seeded perturbation model, so the comparison is
        paired (same draws) and deterministic per tick. Load-aware MoE
        ticks score at the replanner's realized per-device load factors —
        the same prices the fresh solve's y-units were solved at. Returns
        ``(served, twin_p95_s, switched)``; any twin failure falls back to
        the fresh placement (serving must never break on scoring).
        """
        try:
            from ..twin import (
                applicable_candidates,
                build_twin_arrays,
                twin_p95_score,
            )

            factors = getattr(planner, "_load_factors", None)
            if factors is not None and len(factors) != len(devs):
                factors = None
            arrays = build_twin_arrays(
                devs, self.fleet.model, kv_bits=self.kv_bits, moe=self.moe,
                load_factors=factors,
            )
            seen = {self._placement_key(fresh)}
            candidates = [fresh]
            for cached in self._risk_candidates(devs, factors):
                pk = self._placement_key(cached)
                if pk in seen:
                    continue
                seen.add(pk)
                candidates.append(cached)
            candidates = applicable_candidates(arrays, candidates)
            if fresh not in candidates:  # paranoia: fresh must stay eligible
                candidates.insert(0, fresh)
            self.metrics.inc("risk_eval")
            self.metrics.inc("risk_candidates", len(candidates))
            scores = [
                twin_p95_score(
                    devs,
                    self.fleet.model,
                    c,
                    samples=self.risk_samples,
                    seed=self.risk_seed,
                    kv_bits=self.kv_bits,
                    moe=self.moe,
                    arrays=arrays,
                    **self.risk_mc,
                )
                for c in candidates
            ]
            best = min(range(len(candidates)), key=lambda i: scores[i]["score"])
            served = candidates[best]
            switched = served is not fresh
            if switched:
                self.metrics.inc("risk_switch")
            self.metrics.observe("twin_p95", scores[best]["p95_s"] * 1e3)
            return served, scores[best]["p95_s"], switched
        except Exception as e:  # scoring is advisory; serving must survive
            self.metrics.inc("risk_error")
            self._last_error = f"risk_select {type(e).__name__}: {e}"
            return fresh, None, False

    def _risk_candidates(self, devs, load_factors=None):
        """Alternative placements worth scoring against the fresh solve.

        Two sources: (1) every pooled replanner's cached incumbent — a
        fleet identity seen before keeps its placement alive in the warm
        pool, and risk scoring is what justifies serving it over the
        fresh one; (2) the solver-enumerated k-candidates of the current
        problem identity (``halda_solve_per_k``): the objective ranks
        pipeline depths within mip-gap-scale margins, but their risk
        profiles differ structurally (deeper pipelines ride the bottleneck
        cycle (k-1) times; shallower ones concentrate layers), so the twin
        regularly prefers a runner-up k. The per-k sweep is COLD and
        therefore cached per identity: drift ticks reuse the enumeration
        (the twin re-prices every candidate against the live profiles;
        ``placement_applicable`` drops any that stop fitting), only a
        structural identity change re-enumerates. jax-backend only and
        best-effort: a failure costs candidates, never the tick.
        """
        out = []
        for _, planner in self.pool.items():
            if planner.last is not None:
                out.append(planner.last)
        if self.backend != "jax":
            return out
        # Cache key is the drift-invariant problem identity: load factors
        # may drift between ticks, but stale per-k placements stay valid
        # CANDIDATES (the twin re-prices them at the live factors).
        key = self.fleet.key()
        if key != self._risk_per_k_key:
            from ..solver import halda_solve_per_k

            try:
                self._risk_per_k = halda_solve_per_k(
                    devs,
                    self.fleet.model,
                    k_candidates=self.k_candidates,
                    mip_gap=self.mip_gap,
                    kv_bits=self.kv_bits,
                    moe=self.moe,
                    load_factors=load_factors,
                    # The enumeration honors the same engine pin as the
                    # tick solves — an operator who pinned away from an
                    # engine must not get candidates from it.
                    lp_backend=self.lp_backend,
                    pdhg_iters=self.pdhg_iters,
                    pdhg_restart_tol=self.pdhg_restart_tol,
                    mesh_shards=self.mesh_shards,
                    pdhg_dtype=self.pdhg_dtype,
                )
                self._risk_per_k_key = key
            except (RuntimeError, ValueError, NotImplementedError):
                self.metrics.inc("risk_per_k_failed")
                self._risk_per_k = []
                self._risk_per_k_key = None
        out.extend(self._risk_per_k)
        return out

    @staticmethod
    def _placement_key(result: HALDAResult) -> tuple:
        """Assignment identity for candidate dedup (pool keys alias)."""
        return (
            result.k,
            tuple(result.w),
            tuple(result.n),
            tuple(result.y) if result.y is not None else None,
        )

    # -- the read side -----------------------------------------------------

    def latest(self) -> PlacementView:
        """The most recent published placement, with live staleness fields.

        Never solves, never blocks: readers pay a tuple copy. Raises
        ``RuntimeError`` only when nothing has ever been published.
        """
        if self._published is None:
            raise RuntimeError(
                "no placement published yet; handle an event first (or "
                "construct with solve_on_init=True)"
            )
        return self._published._replace(
            fleet_seq=self.fleet.seq,
            events_behind=self.fleet.seq - self._published.seq,
            age_s=time.monotonic() - self._published_at,
        )

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def timeline_sample(self) -> dict:
        """One flat ``{series: value}`` sample for the metrics timeline
        (``obs.timeline.TimelineSampler``'s sample_fn on the
        single-scheduler serving path).

        Carries the SLO inputs the spec layer names: every counter
        (``c.<name>``, cumulative), latency quantiles (``lat.<hist>.*``
        — iters-to-certify rides ``lat.ipm_iters_executed``), the serve
        clock (``last_serve_ms``), the health rank, and — when solver
        diagnostics are on — the latest tick's ``conv_*`` digest
        (``conv.<key>``). Pure read; no timeline knob engaged means this
        is simply never called.
        """
        from ..obs.timeline import flatten_metrics_snapshot

        out = flatten_metrics_snapshot(self.metrics.snapshot())
        out["last_serve_ms"] = float(self.last_serve_ms)
        out["health"] = float(
            {HEALTH_HEALTHY: 0, HEALTH_DEGRADED: 1, HEALTH_BROKEN: 2}[
                self.health
            ]
        )
        if self._tick_conv:
            for k, v in self._tick_conv.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[f"conv.{k}"] = float(v)
        led = _compile_ledger.current()
        if led is not None:
            # Process-wide compile telemetry (timeline_series is the one
            # definition, shared with Gateway.timeline_sample): an SLO
            # over c.compiles / c.recompile_storms sees a storm's full
            # delta, and the feature-off sample stays byte-identical.
            out.update(led.timeline_series())
        mled = _memory.current()
        if mled is not None:
            # mem.* watermark gauges (obs.memory.timeline_series, the one
            # definition shared with Gateway.timeline_sample): absent —
            # never zeroed — when a value is unavailable, and emitted
            # only while a ledger is enabled (feature-off byte-identical).
            out.update(mled.timeline_series())
        return out

    # -- warm snapshot / restore (the gateway's drain/restore cycle) -------

    def dump_state(self) -> dict:
        """The scheduler's full warm state as one JSON-able blob.

        Everything a restored scheduler needs to resume serving mid-trace
        with warm ticks: the live fleet snapshot (devices + model + event
        seq), the published placement (so ``latest()`` serves immediately),
        the health/breaker machine, and the warm pool — every replanner's
        incumbent, duals, LP iterates and margin anchor via
        ``StreamingReplanner.dump_warm_state`` (bit-exact round trip).
        Metrics counters are NOT included: a restored process starts fresh
        observability, and the ``warm_resumes``/``cold_resumes`` counters
        are what audit the restore itself. The risk-aware per-k candidate
        cache is also dropped — it is re-enumerated on demand (a cold
        *enumeration*, never a cold serving tick).
        """
        # A deadline-abandoned solve still runs on the sched-solve daemon
        # thread and writes the planner's warm state (last/_margin_state)
        # when it finally finishes — dumping concurrently could pair an
        # incumbent and LP iterates from different ticks (or crash
        # encoding a dict mutated mid-walk). Drain it first: the solve is
        # finite jit'd work, and a snapshot's consistency outranks its
        # latency.
        if self._abandoned is not None:
            _box, done = self._abandoned
            done.wait()
            self.metrics.inc("abandoned_solves_drained")
            self._abandoned = None
        published = None
        if self._published is not None:
            v = self._published
            published = {
                "result": v.result.model_dump(),
                "seq": v.seq,
                "mode": v.mode,
                "key": list(v.key),
                "twin_p95_s": v.twin_p95_s,
                "risk_selected": v.risk_selected,
            }
        state_spec = None
        if self.speculative:
            # Speculation state rides the snapshot (additive, versioned by
            # the blob's top-level version): forecaster EWMA/trend plus the
            # bank's entries with their LP iterates bit-exact, so a
            # restored shard's first matching event still hits. Old blobs
            # without the block restore clean — an empty bank refills from
            # the first post-restore solved tick.
            state_spec = {
                "forecaster": self.forecaster.dump_state(),
                "bank": self.spec_bank.dump_state(),
            }
        return {
            "version": 1,
            "spec": state_spec,
            "devices": [d.model_dump() for d in self.fleet.device_list()],
            "model": self.fleet.model.model_dump(),
            "seq": self.fleet.seq,
            "health": self.health,
            "breaker_open": self._breaker_open,
            "breaker_cooldown_left": self._breaker_cooldown_left,
            "consec_failures": self._consec_failures,
            "clean_streak": self._clean_streak,
            "last_error": self._last_error,
            "published": published,
            # LRU order preserved oldest-first so adoption re-creates it.
            "pool": [
                {"key": list(key), "warm": planner.dump_warm_state()}
                for key, planner in self.pool.items()
            ],
        }

    def load_state(self, state: dict) -> None:
        """Restore a ``dump_state`` blob into this scheduler.

        The scheduler must have been constructed with the same solver
        configuration (gap, backend, engine pins — the blob carries state,
        not config); fleet and model are taken from the blob, so the
        constructor's devices only seeded routing. The first tick after a
        restore self-reports through ``warm_resumes``/``cold_resumes``.
        """
        if state.get("version") != 1:
            raise ValueError(
                f"unknown scheduler state version {state.get('version')!r}"
            )
        from ..common import DeviceProfile, ModelProfile

        devices = [DeviceProfile.model_validate(d) for d in state["devices"]]
        model = ModelProfile.model_validate(state["model"])
        self.fleet = FleetState(devices, model)
        self.fleet.seq = state["seq"]
        self.health = state["health"]
        self._breaker_open = state["breaker_open"]
        self._breaker_cooldown_left = state["breaker_cooldown_left"]
        self._consec_failures = state["consec_failures"]
        self._clean_streak = state["clean_streak"]
        self._last_error = state.get("last_error")
        for entry in state["pool"]:
            planner = self._make_replanner()
            planner.load_warm_state(entry["warm"])
            self.pool.adopt(tuple(entry["key"]), planner)
        self._restored_keys = frozenset(
            tuple(entry["key"]) for entry in state["pool"]
        )
        pub = state.get("published")
        if pub is not None:
            self._published = PlacementView(
                result=HALDAResult.model_validate(pub["result"]),
                seq=pub["seq"],
                fleet_seq=self.fleet.seq,
                events_behind=self.fleet.seq - pub["seq"],
                age_s=0.0,
                mode=pub["mode"],
                key=tuple(pub["key"]),
                twin_p95_s=pub.get("twin_p95_s"),
                risk_selected=bool(pub.get("risk_selected", False)),
            )
            self._published_at = time.monotonic()
        if self.speculative:
            spec = state.get("spec") or {}
            self.forecaster.load_state(spec.get("forecaster"))
            self.spec_bank.load_state(spec.get("bank"))
        self._risk_per_k = []
        self._risk_per_k_key = None
        self._restore_pending = True
        self.metrics.inc("state_restored")

    _last_error: Optional[str] = None


def drift_warm_share(metrics: SchedulerMetrics) -> float:
    """Fraction of drift events served by warm, margin or speculative ticks.

    The streaming north star's health gauge: pure coefficient drift should
    essentially never pay a cold solve (the acceptance bar is >= 0.6; in
    practice it is ~1.0 — cold drift ticks mean the pool is thrashing).
    A speculative bank hit counts as fast — it is the fastest serve there
    is — otherwise enabling --speculate would collapse the gauge exactly
    when the feature works. Failed drift ticks count against the share; a
    tick the escalation ladder restarted cold still counts by its ENTRY
    mode, since the entry mode is what the event routing chose.
    """
    c = metrics.counters
    # .get everywhere, for two reasons: a bracket read on the live
    # defaultdict would MINT a speculation counter into the default
    # (spec-off) path's summary output — breaking the byte-identical
    # contract — and a process-backed shard's counters arrive as a PLAIN
    # dict snapshotted over RPC, where a missing key is a KeyError.
    drift = c.get("drift_events", 0)
    if not drift:
        return 1.0
    fast = (
        c.get("drift_tick_warm", 0)
        + c.get("drift_tick_margin", 0)
        + c.get("drift_tick_spec", 0)
        + c.get("drift_tick_spec_near", 0)
    )
    return fast / drift

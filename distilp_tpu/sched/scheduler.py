"""The replanning core: events in, certified placements out, warm state kept.

``Scheduler`` owns a ``FleetState`` and a bounded LRU **warm pool** of
``StreamingReplanner`` instances keyed by (fleet_digest, model_digest).
Routing follows the event classes:

- **drift** events (degrade / load) keep the key, so the tick lands on the
  same warm replanner — a warm re-solve (dense) or a margin tick (MoE
  chains), exactly the solver's streaming fast paths;
- **structural** events (join / leave / model swap) change the key. A key
  seen before gets its replanner — and its warm incumbent, duals and
  margin anchor — back from the pool (a device flapping out and back in
  replans warm: this is the placement cache); a brand-new key starts cold.

Serving never blocks on solving: ``latest()`` returns the most recently
*published* placement plus staleness metadata (events behind, age). A tick
that fails (e.g. the fleet drifted infeasible) increments a counter and
leaves the last placement served; certification is the replanner's
escalation ladder's job and its outcome is recorded per tick.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, NamedTuple, Optional, Sequence, Tuple

from ..common import DeviceProfile, ModelProfile
from ..solver.result import HALDAResult
from ..solver.streaming import StreamingReplanner
from .fleet import FleetState
from .metrics import SchedulerMetrics


# Serving-side perturbation model for risk-aware candidate scoring: modest
# symmetric jitter on compute/link/disk plus a straggler scenario (each
# device has a 5% chance per draw of running 8x slower — the GC-pause /
# thermal-throttle / contended-host class of event on consumer swarms).
# The straggler channel is what separates candidates: a deeper pipeline
# multiplies the straggled bottleneck cycle (k-1) times, so the twin's p95
# regularly prefers a shallower runner-up k the mean-objective ranking
# puts second; pure symmetric jitter rarely reorders close candidates.
DEFAULT_RISK_MC = {
    "sigma_compute": 0.10,
    "sigma_comm": 0.15,
    "sigma_disk": 0.10,
    "sigma_mem": 0.0,
    "dropout_p": 0.05,
    "dropout_slowdown": 8.0,
}


class PlacementView(NamedTuple):
    """One served placement + how stale it is relative to the event stream."""

    result: HALDAResult
    seq: int  # fleet seq the placement was solved at
    fleet_seq: int  # fleet seq at read time
    events_behind: int  # fleet_seq - seq (0 = fresh)
    age_s: float  # wall-clock seconds since publication
    # 'cold' | 'warm' | 'margin' tick that produced it; 'risk' when the
    # risk-aware selector served a candidate OTHER than that tick's fresh
    # solve (a cached incumbent or per-k alternative).
    mode: str
    # Problem identity at publication time. For mode == 'risk' the served
    # placement may have been SOLVED under an earlier identity/tick — the
    # twin re-priced it against this one before serving.
    key: Tuple[str, str]
    # Risk-aware mode only: the served placement's twin p95 latency and
    # whether the twin preferred a candidate over the fresh solve.
    twin_p95_s: Optional[float] = None
    risk_selected: bool = False


class WarmPool:
    """Bounded LRU of warm replanners, keyed by problem identity.

    Eviction drops the warm state (incumbent, duals, margin anchor) — the
    next solve under that key is cold but still correct; the pool trades
    re-solve speed for bounded memory, never answers.
    """

    def __init__(
        self,
        capacity: int,
        factory: Callable[[], StreamingReplanner],
        metrics: Optional[SchedulerMetrics] = None,
    ):
        if capacity < 1:
            raise ValueError("warm pool capacity must be >= 1")
        self.capacity = capacity
        self._factory = factory
        self._metrics = metrics
        self._pool: "OrderedDict[tuple, StreamingReplanner]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._pool)

    def __contains__(self, key: tuple) -> bool:
        return key in self._pool

    def get(self, key: tuple) -> Tuple[StreamingReplanner, bool]:
        """(replanner, was_a_hit) for the key, creating + evicting LRU-style."""
        planner = self._pool.get(key)
        hit = planner is not None
        if hit:
            self._pool.move_to_end(key)
        else:
            planner = self._factory()
            self._pool[key] = planner
            while len(self._pool) > self.capacity:
                self._pool.popitem(last=False)
                if self._metrics is not None:
                    self._metrics.inc("pool_evict")
        if self._metrics is not None:
            self._metrics.inc("pool_hit" if hit else "pool_miss")
        return planner, hit

    def items(self):
        """(key, replanner) pairs, LRU order — the risk-aware candidate scan
        reads cached incumbents without touching recency or hit counters."""
        return list(self._pool.items())


class Scheduler:
    """Event-driven replanning daemon over one fleet + model.

    >>> sched = Scheduler(devs, model, k_candidates=[4, 8])
    >>> view = sched.handle(DeviceDegrade(name="synth-android-3",
    ...                                   t_comm_scale=1.2))
    >>> view.result.certified, view.mode
    (True, 'warm')
    >>> sched.latest().events_behind
    0
    """

    def __init__(
        self,
        devices: Sequence[DeviceProfile],
        model: ModelProfile,
        mip_gap: float = 1e-3,
        kv_bits: str = "4bit",
        backend: str = "jax",
        moe: Optional[bool] = None,
        k_candidates: Optional[Sequence[int]] = None,
        warm_pool_size: int = 4,
        solve_on_init: bool = False,
        metrics: Optional[SchedulerMetrics] = None,
        cold_start: bool = False,
        risk_aware: bool = False,
        risk_samples: int = 256,
        risk_seed: int = 0,
        risk_mc: Optional[dict] = None,
    ):
        self.fleet = FleetState(list(devices), model)
        self.mip_gap = mip_gap
        self.kv_bits = kv_bits
        self.backend = backend
        self.moe = moe
        # A/B switch (`solver serve --cold-start`): the pool still routes
        # events, but every tick solves from scratch — the baseline against
        # which warm/margin/iterate reuse is measured.
        self.cold_start = cold_start
        # Risk-aware serving (`serve --risk-aware`): every tick scores the
        # fresh solve AND the warm pool's cached incumbents on the digital
        # twin (Monte-Carlo p95 + feasibility-violation penalty, seeded so
        # replays are deterministic) and publishes the lowest-risk
        # candidate — instead of serving the freshest placement on
        # staleness alone. Solver warm state is untouched: risk selection
        # changes what is SERVED, never what seeds the next solve.
        self.risk_aware = risk_aware
        self.risk_samples = risk_samples
        self.risk_seed = risk_seed
        # Perturbation-model overrides forwarded to the twin (sigma_*,
        # dropout_p, dropout_slowdown, degrade). The serving default leans
        # on the straggler channel: DEFAULT_RISK_MC's dropout scenario is
        # what separates placements that concentrate layers from ones that
        # spread them — symmetric small jitter alone rarely reorders.
        self.risk_mc = dict(DEFAULT_RISK_MC if risk_mc is None else risk_mc)
        # Per-k candidate cache: the enumeration is a COLD per-k sweep, so
        # drift ticks reuse the placements enumerated at the current
        # problem identity (the twin re-prices them against the live
        # profiles anyway); only an identity change re-enumerates.
        self._risk_per_k: list = []
        self._risk_per_k_key: Optional[tuple] = None
        self.k_candidates = list(k_candidates) if k_candidates else None
        self.metrics = metrics if metrics is not None else SchedulerMetrics()
        self.pool = WarmPool(
            warm_pool_size, self._make_replanner, metrics=self.metrics
        )
        self._published: Optional[PlacementView] = None
        self._published_at: float = 0.0
        if solve_on_init:
            self.metrics.inc("init_solve")
            self._tick(structural=None)

    def _make_replanner(self) -> StreamingReplanner:
        planner = StreamingReplanner(
            mip_gap=self.mip_gap,
            kv_bits=self.kv_bits,
            backend=self.backend,
            moe=self.moe,
            cold_start=self.cold_start,
        )
        planner.metrics = self.metrics  # tick modes funnel into one snapshot
        return planner

    # -- the event loop body ----------------------------------------------

    def handle(self, event) -> PlacementView:
        """Apply one event and replan; returns the freshly published view.

        Structural events route through the warm pool under their new key;
        drift events tick the current key's replanner warm. A failed solve
        (no feasible placement for the mutated fleet) keeps the previous
        placement published and is visible as ``tick_failed`` + a growing
        ``events_behind`` on ``latest()``.
        """
        structural = self.fleet.apply(event)
        self.metrics.inc("events_total")
        self.metrics.inc(f"event_{event.kind}")
        self.metrics.inc("structural_events" if structural else "drift_events")
        return self._tick(structural=structural)

    def _tick(self, structural: Optional[bool]) -> PlacementView:
        """One replan; ``structural=None`` marks the eventless init solve
        (it times and mode-counts like any tick but belongs to neither
        routing class, so the per-class counters keep summing to events)."""
        key = self.fleet.key()
        planner, _hit = self.pool.get(key)
        devs = self.fleet.device_list()
        t0 = time.perf_counter()
        tick_tm: dict = {}
        try:
            result = planner.step(
                devs, self.fleet.model, k_candidates=self.k_candidates,
                timings=tick_tm,
            )
        except (RuntimeError, ValueError, NotImplementedError) as e:
            self.metrics.inc("tick_failed")
            if structural is not None:
                self.metrics.inc(
                    "tick_failed_structural" if structural
                    else "tick_failed_drift"
                )
            self._last_error = f"{type(e).__name__}: {e}"
            if self._published is None:
                raise
            return self.latest()
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.observe("event_to_placement", ms)
        # Device-program work accounting (JAX backend): how many Mehrotra
        # iterations the tick actually executed — the warm-start health
        # gauge next to the tick-mode counters (a drift tick burning the
        # cold budget means the iterate chain broke).
        if "ipm_iters_executed" in tick_tm:
            self.metrics.observe(
                "ipm_iters_executed", tick_tm["ipm_iters_executed"]
            )
        mode = getattr(planner, "last_tick_mode", None) or "cold"
        if structural is not None:
            self.metrics.observe(
                "structural_tick" if structural else "drift_tick", ms
            )
            # Mode per routing class: the acceptance gauge (drift should
            # ride warm/margin, structural may cold-solve) reads these.
            self.metrics.inc(
                f"{'structural' if structural else 'drift'}_tick_{mode}"
            )
        if structural and not result.certified:
            self.metrics.inc("structural_uncertified")
        served, twin_p95, switched = result, None, False
        if self.risk_aware:
            served, twin_p95, switched = self._risk_select(devs, result, planner)
        self._published = PlacementView(
            result=served,
            seq=self.fleet.seq,
            fleet_seq=self.fleet.seq,
            events_behind=0,
            age_s=0.0,
            # A switched tick serves a placement this tick did NOT produce;
            # 'risk' keeps the mode field honest (see PlacementView).
            mode="risk" if switched else mode,
            key=key,
            twin_p95_s=twin_p95,
            risk_selected=switched,
        )
        self._published_at = time.monotonic()
        return self._published

    def _risk_select(self, devs, fresh: HALDAResult, planner):
        """Score the fresh solve + cached pool incumbents on the twin.

        Candidates are every pooled replanner's last placement that is
        structurally executable on the CURRENT fleet (right device count,
        window sums, offload/expert cover — ``twin.placement_applicable``);
        each is priced by Monte-Carlo p95 plus a feasibility-violation
        penalty under one seeded perturbation model, so the comparison is
        paired (same draws) and deterministic per tick. Load-aware MoE
        ticks score at the replanner's realized per-device load factors —
        the same prices the fresh solve's y-units were solved at. Returns
        ``(served, twin_p95_s, switched)``; any twin failure falls back to
        the fresh placement (serving must never break on scoring).
        """
        try:
            from ..twin import (
                applicable_candidates,
                build_twin_arrays,
                twin_p95_score,
            )

            factors = getattr(planner, "_load_factors", None)
            if factors is not None and len(factors) != len(devs):
                factors = None
            arrays = build_twin_arrays(
                devs, self.fleet.model, kv_bits=self.kv_bits, moe=self.moe,
                load_factors=factors,
            )
            seen = {self._placement_key(fresh)}
            candidates = [fresh]
            for cached in self._risk_candidates(devs, factors):
                pk = self._placement_key(cached)
                if pk in seen:
                    continue
                seen.add(pk)
                candidates.append(cached)
            candidates = applicable_candidates(arrays, candidates)
            if fresh not in candidates:  # paranoia: fresh must stay eligible
                candidates.insert(0, fresh)
            self.metrics.inc("risk_eval")
            self.metrics.inc("risk_candidates", len(candidates))
            scores = [
                twin_p95_score(
                    devs,
                    self.fleet.model,
                    c,
                    samples=self.risk_samples,
                    seed=self.risk_seed,
                    kv_bits=self.kv_bits,
                    moe=self.moe,
                    arrays=arrays,
                    **self.risk_mc,
                )
                for c in candidates
            ]
            best = min(range(len(candidates)), key=lambda i: scores[i]["score"])
            served = candidates[best]
            switched = served is not fresh
            if switched:
                self.metrics.inc("risk_switch")
            self.metrics.observe("twin_p95", scores[best]["p95_s"] * 1e3)
            return served, scores[best]["p95_s"], switched
        except Exception as e:  # scoring is advisory; serving must survive
            self.metrics.inc("risk_error")
            self._last_error = f"risk_select {type(e).__name__}: {e}"
            return fresh, None, False

    def _risk_candidates(self, devs, load_factors=None):
        """Alternative placements worth scoring against the fresh solve.

        Two sources: (1) every pooled replanner's cached incumbent — a
        fleet identity seen before keeps its placement alive in the warm
        pool, and risk scoring is what justifies serving it over the
        fresh one; (2) the solver-enumerated k-candidates of the current
        problem identity (``halda_solve_per_k``): the objective ranks
        pipeline depths within mip-gap-scale margins, but their risk
        profiles differ structurally (deeper pipelines ride the bottleneck
        cycle (k-1) times; shallower ones concentrate layers), so the twin
        regularly prefers a runner-up k. The per-k sweep is COLD and
        therefore cached per identity: drift ticks reuse the enumeration
        (the twin re-prices every candidate against the live profiles;
        ``placement_applicable`` drops any that stop fitting), only a
        structural identity change re-enumerates. jax-backend only and
        best-effort: a failure costs candidates, never the tick.
        """
        out = []
        for _, planner in self.pool.items():
            if planner.last is not None:
                out.append(planner.last)
        if self.backend != "jax":
            return out
        # Cache key is the drift-invariant problem identity: load factors
        # may drift between ticks, but stale per-k placements stay valid
        # CANDIDATES (the twin re-prices them at the live factors).
        key = self.fleet.key()
        if key != self._risk_per_k_key:
            from ..solver import halda_solve_per_k

            try:
                self._risk_per_k = halda_solve_per_k(
                    devs,
                    self.fleet.model,
                    k_candidates=self.k_candidates,
                    mip_gap=self.mip_gap,
                    kv_bits=self.kv_bits,
                    moe=self.moe,
                    load_factors=load_factors,
                )
                self._risk_per_k_key = key
            except (RuntimeError, ValueError, NotImplementedError):
                self.metrics.inc("risk_per_k_failed")
                self._risk_per_k = []
                self._risk_per_k_key = None
        out.extend(self._risk_per_k)
        return out

    @staticmethod
    def _placement_key(result: HALDAResult) -> tuple:
        """Assignment identity for candidate dedup (pool keys alias)."""
        return (
            result.k,
            tuple(result.w),
            tuple(result.n),
            tuple(result.y) if result.y is not None else None,
        )

    # -- the read side -----------------------------------------------------

    def latest(self) -> PlacementView:
        """The most recent published placement, with live staleness fields.

        Never solves, never blocks: readers pay a tuple copy. Raises
        ``RuntimeError`` only when nothing has ever been published.
        """
        if self._published is None:
            raise RuntimeError(
                "no placement published yet; handle an event first (or "
                "construct with solve_on_init=True)"
            )
        return self._published._replace(
            fleet_seq=self.fleet.seq,
            events_behind=self.fleet.seq - self._published.seq,
            age_s=time.monotonic() - self._published_at,
        )

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    _last_error: Optional[str] = None


def drift_warm_share(metrics: SchedulerMetrics) -> float:
    """Fraction of drift events served by warm or margin ticks.

    The streaming north star's health gauge: pure coefficient drift should
    essentially never pay a cold solve (the acceptance bar is >= 0.6; in
    practice it is ~1.0 — cold drift ticks mean the pool is thrashing).
    Failed drift ticks count against the share; a tick the escalation
    ladder restarted cold still counts by its ENTRY mode, since the entry
    mode is what the event routing chose.
    """
    c = metrics.counters
    drift = c["drift_events"]
    if not drift:
        return 1.0
    fast = c["drift_tick_warm"] + c["drift_tick_margin"]
    return fast / drift

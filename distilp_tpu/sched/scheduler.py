"""The replanning core: events in, certified placements out, warm state kept.

``Scheduler`` owns a ``FleetState`` and a bounded LRU **warm pool** of
``StreamingReplanner`` instances keyed by (fleet_digest, model_digest).
Routing follows the event classes:

- **drift** events (degrade / load) keep the key, so the tick lands on the
  same warm replanner — a warm re-solve (dense) or a margin tick (MoE
  chains), exactly the solver's streaming fast paths;
- **structural** events (join / leave / model swap) change the key. A key
  seen before gets its replanner — and its warm incumbent, duals and
  margin anchor — back from the pool (a device flapping out and back in
  replans warm: this is the placement cache); a brand-new key starts cold.

Serving never blocks on solving: ``latest()`` returns the most recently
*published* placement plus staleness metadata (events behind, age). A tick
that fails (e.g. the fleet drifted infeasible) increments a counter and
leaves the last placement served; certification is the replanner's
escalation ladder's job and its outcome is recorded per tick.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, NamedTuple, Optional, Sequence, Tuple

from ..common import DeviceProfile, ModelProfile
from ..solver.result import HALDAResult
from ..solver.streaming import StreamingReplanner
from .fleet import FleetState
from .metrics import SchedulerMetrics


class PlacementView(NamedTuple):
    """One served placement + how stale it is relative to the event stream."""

    result: HALDAResult
    seq: int  # fleet seq the placement was solved at
    fleet_seq: int  # fleet seq at read time
    events_behind: int  # fleet_seq - seq (0 = fresh)
    age_s: float  # wall-clock seconds since publication
    mode: str  # 'cold' | 'warm' | 'margin' tick that produced it
    key: Tuple[str, str]  # (fleet_digest, model_digest) it was solved under


class WarmPool:
    """Bounded LRU of warm replanners, keyed by problem identity.

    Eviction drops the warm state (incumbent, duals, margin anchor) — the
    next solve under that key is cold but still correct; the pool trades
    re-solve speed for bounded memory, never answers.
    """

    def __init__(
        self,
        capacity: int,
        factory: Callable[[], StreamingReplanner],
        metrics: Optional[SchedulerMetrics] = None,
    ):
        if capacity < 1:
            raise ValueError("warm pool capacity must be >= 1")
        self.capacity = capacity
        self._factory = factory
        self._metrics = metrics
        self._pool: "OrderedDict[tuple, StreamingReplanner]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._pool)

    def __contains__(self, key: tuple) -> bool:
        return key in self._pool

    def get(self, key: tuple) -> Tuple[StreamingReplanner, bool]:
        """(replanner, was_a_hit) for the key, creating + evicting LRU-style."""
        planner = self._pool.get(key)
        hit = planner is not None
        if hit:
            self._pool.move_to_end(key)
        else:
            planner = self._factory()
            self._pool[key] = planner
            while len(self._pool) > self.capacity:
                self._pool.popitem(last=False)
                if self._metrics is not None:
                    self._metrics.inc("pool_evict")
        if self._metrics is not None:
            self._metrics.inc("pool_hit" if hit else "pool_miss")
        return planner, hit


class Scheduler:
    """Event-driven replanning daemon over one fleet + model.

    >>> sched = Scheduler(devs, model, k_candidates=[4, 8])
    >>> view = sched.handle(DeviceDegrade(name="synth-android-3",
    ...                                   t_comm_scale=1.2))
    >>> view.result.certified, view.mode
    (True, 'warm')
    >>> sched.latest().events_behind
    0
    """

    def __init__(
        self,
        devices: Sequence[DeviceProfile],
        model: ModelProfile,
        mip_gap: float = 1e-3,
        kv_bits: str = "4bit",
        backend: str = "jax",
        moe: Optional[bool] = None,
        k_candidates: Optional[Sequence[int]] = None,
        warm_pool_size: int = 4,
        solve_on_init: bool = False,
        metrics: Optional[SchedulerMetrics] = None,
        cold_start: bool = False,
    ):
        self.fleet = FleetState(list(devices), model)
        self.mip_gap = mip_gap
        self.kv_bits = kv_bits
        self.backend = backend
        self.moe = moe
        # A/B switch (`solver serve --cold-start`): the pool still routes
        # events, but every tick solves from scratch — the baseline against
        # which warm/margin/iterate reuse is measured.
        self.cold_start = cold_start
        self.k_candidates = list(k_candidates) if k_candidates else None
        self.metrics = metrics if metrics is not None else SchedulerMetrics()
        self.pool = WarmPool(
            warm_pool_size, self._make_replanner, metrics=self.metrics
        )
        self._published: Optional[PlacementView] = None
        self._published_at: float = 0.0
        if solve_on_init:
            self.metrics.inc("init_solve")
            self._tick(structural=None)

    def _make_replanner(self) -> StreamingReplanner:
        planner = StreamingReplanner(
            mip_gap=self.mip_gap,
            kv_bits=self.kv_bits,
            backend=self.backend,
            moe=self.moe,
            cold_start=self.cold_start,
        )
        planner.metrics = self.metrics  # tick modes funnel into one snapshot
        return planner

    # -- the event loop body ----------------------------------------------

    def handle(self, event) -> PlacementView:
        """Apply one event and replan; returns the freshly published view.

        Structural events route through the warm pool under their new key;
        drift events tick the current key's replanner warm. A failed solve
        (no feasible placement for the mutated fleet) keeps the previous
        placement published and is visible as ``tick_failed`` + a growing
        ``events_behind`` on ``latest()``.
        """
        structural = self.fleet.apply(event)
        self.metrics.inc("events_total")
        self.metrics.inc(f"event_{event.kind}")
        self.metrics.inc("structural_events" if structural else "drift_events")
        return self._tick(structural=structural)

    def _tick(self, structural: Optional[bool]) -> PlacementView:
        """One replan; ``structural=None`` marks the eventless init solve
        (it times and mode-counts like any tick but belongs to neither
        routing class, so the per-class counters keep summing to events)."""
        key = self.fleet.key()
        planner, _hit = self.pool.get(key)
        devs = self.fleet.device_list()
        t0 = time.perf_counter()
        tick_tm: dict = {}
        try:
            result = planner.step(
                devs, self.fleet.model, k_candidates=self.k_candidates,
                timings=tick_tm,
            )
        except (RuntimeError, ValueError, NotImplementedError) as e:
            self.metrics.inc("tick_failed")
            if structural is not None:
                self.metrics.inc(
                    "tick_failed_structural" if structural
                    else "tick_failed_drift"
                )
            self._last_error = f"{type(e).__name__}: {e}"
            if self._published is None:
                raise
            return self.latest()
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.observe("event_to_placement", ms)
        # Device-program work accounting (JAX backend): how many Mehrotra
        # iterations the tick actually executed — the warm-start health
        # gauge next to the tick-mode counters (a drift tick burning the
        # cold budget means the iterate chain broke).
        if "ipm_iters_executed" in tick_tm:
            self.metrics.observe(
                "ipm_iters_executed", tick_tm["ipm_iters_executed"]
            )
        mode = getattr(planner, "last_tick_mode", None) or "cold"
        if structural is not None:
            self.metrics.observe(
                "structural_tick" if structural else "drift_tick", ms
            )
            # Mode per routing class: the acceptance gauge (drift should
            # ride warm/margin, structural may cold-solve) reads these.
            self.metrics.inc(
                f"{'structural' if structural else 'drift'}_tick_{mode}"
            )
        if structural and not result.certified:
            self.metrics.inc("structural_uncertified")
        self._published = PlacementView(
            result=result,
            seq=self.fleet.seq,
            fleet_seq=self.fleet.seq,
            events_behind=0,
            age_s=0.0,
            mode=mode,
            key=key,
        )
        self._published_at = time.monotonic()
        return self._published

    # -- the read side -----------------------------------------------------

    def latest(self) -> PlacementView:
        """The most recent published placement, with live staleness fields.

        Never solves, never blocks: readers pay a tuple copy. Raises
        ``RuntimeError`` only when nothing has ever been published.
        """
        if self._published is None:
            raise RuntimeError(
                "no placement published yet; handle an event first (or "
                "construct with solve_on_init=True)"
            )
        return self._published._replace(
            fleet_seq=self.fleet.seq,
            events_behind=self.fleet.seq - self._published.seq,
            age_s=time.monotonic() - self._published_at,
        )

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    _last_error: Optional[str] = None


def drift_warm_share(metrics: SchedulerMetrics) -> float:
    """Fraction of drift events served by warm or margin ticks.

    The streaming north star's health gauge: pure coefficient drift should
    essentially never pay a cold solve (the acceptance bar is >= 0.6; in
    practice it is ~1.0 — cold drift ticks mean the pool is thrashing).
    Failed drift ticks count against the share; a tick the escalation
    ladder restarted cold still counts by its ENTRY mode, since the entry
    mode is what the event routing chose.
    """
    c = metrics.counters
    drift = c["drift_events"]
    if not drift:
        return 1.0
    fast = c["drift_tick_warm"] + c["drift_tick_margin"]
    return fast / drift

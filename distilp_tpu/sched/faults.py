"""Seeded fault injection + the chaos-replay harness for the scheduler.

A *fault plan* is a composable list of fault specs — solver exceptions,
solve-latency spikes (which overrun a configured deadline), NaN/Inf
poisoning of device/model coefficients, malformed or contradictory churn
events, and device-dropout bursts — each active on an explicit tick list or
as a seeded per-tick Bernoulli draw inside a window. Everything is
deterministic: the schedule for (plan, tick) comes from
``np.random.default_rng([seed, spec_index, tick])``, so the injected fault
sequence is a pure function of the plan, independent of call order, and two
replays of the same trace under the same plan inject — and, faults being
the only nondeterminism, serve — exactly the same things.

``chaos_replay`` drives a (fault-hardened) ``Scheduler`` through a trace
under a plan: solver-channel faults fire inside the solve attempt via the
scheduler's ``fault_hook`` seam, event-channel faults are injected as extra
churn events the quarantine gate must reject, and dropout bursts
leave/rejoin real devices through the normal event path. After the trace it
keeps ticking clean events until the scheduler reports healthy (bounded),
then ``ChaosReport.violations()`` checks the soak contract:

- every tick (faulted or not) served a structurally valid placement;
- every poisoned/malformed injected event was quarantined — the fleet
  state never absorbed a poison, and the counters account for each one;
- the service returned to ``healthy`` within the recovery budget.

``make smoke-chaos`` runs exactly this over the bundled churn trace.
"""

from __future__ import annotations

import json
import time
from collections import defaultdict
from pathlib import Path
from typing import Dict, List, Literal, NamedTuple, Optional, Sequence, Tuple

import numpy as np
from pydantic import BaseModel, Field

from .events import DeviceDegrade, DeviceJoin, DeviceLeave, LoadTick
from .metrics import HEALTH_HEALTHY

FAULT_KINDS = (
    "solver_exception",
    "latency_spike",
    "nan_poison",
    "malformed_event",
    "dropout_burst",
    "child_kill",
    "rpc_torn",
    "rpc_delay",
)

# Fault channels that fire inside the solve attempt (via fault_hook) vs
# ones injected as churn events ahead of the trace event vs ones aimed at
# the PROCESS boundary (SIGKILL, torn RPC frames, RPC delay) through the
# gateway's ``chaos_process_hook`` — only meaningful against a
# process-backed worker tier, rejected otherwise.
SOLVER_CHANNEL = frozenset({"solver_exception", "latency_spike"})
EVENT_CHANNEL = frozenset({"nan_poison", "malformed_event", "dropout_burst"})
PROCESS_CHANNEL = frozenset({"child_kill", "rpc_torn", "rpc_delay"})


class InjectedSolverFault(RuntimeError):
    """The exception the injector raises inside a solve attempt."""


class FaultSpec(BaseModel):
    """One composable fault source.

    Active on ``at_ticks`` when given, else as a Bernoulli(``p``) draw per
    tick inside ``[start, end)`` (``end=None`` = unbounded). The remaining
    fields parameterize individual kinds and are ignored by the others.
    """

    kind: Literal[
        "solver_exception",
        "latency_spike",
        "nan_poison",
        "malformed_event",
        "dropout_burst",
        "child_kill",
        "rpc_torn",
        "rpc_delay",
    ]
    at_ticks: Optional[List[int]] = None
    p: float = 0.0
    start: int = 0
    end: Optional[int] = None
    # latency_spike: seconds slept inside the solve attempt.
    spike_s: float = 0.05
    # solver_exception / latency_spike: fire on the first attempt only, so
    # a retry ladder can save the tick (False = every attempt fails).
    transient: bool = False
    # dropout_burst: devices dropped at once, and ticks until they rejoin.
    burst_size: int = 1
    rejoin_after: int = 2
    # rpc_delay: seconds the owning worker stalls its next RPC dispatch.
    delay_s: float = 0.05


class FaultPlan(BaseModel):
    """A seed plus the fault specs composed over one replay."""

    seed: int = 0
    faults: List[FaultSpec] = Field(default_factory=list)

    @classmethod
    def from_json(cls, path) -> "FaultPlan":
        return cls.model_validate(json.loads(Path(path).read_text()))

    def empty(self) -> bool:
        return not self.faults


class FaultInjector:
    """Deterministic executor of one ``FaultPlan`` over one replay.

    ``metrics`` (a ``SchedulerMetrics``) receives ``fault_injected_<kind>``
    on every scheduled fault and ``fault_fired_<kind>`` each time a
    solver-channel fault actually fires inside an attempt (a non-transient
    exception fires once per retry attempt; an armed fault on a
    breaker-skipped tick never fires at all). ``self.counters`` mirrors
    both without needing a metrics sink.
    """

    def __init__(self, plan: FaultPlan, metrics=None):
        self.plan = plan
        self.metrics = metrics
        self.counters: Dict[str, int] = defaultdict(int)
        self._armed: List[FaultSpec] = []
        self._tick = -1
        # tick -> device profiles due to rejoin (dropout bursts).
        self._rejoins: Dict[int, list] = {}

    # -- the deterministic schedule ---------------------------------------

    def _rng(self, spec_idx: int, tick: int) -> np.random.Generator:
        return np.random.default_rng([self.plan.seed, spec_idx, tick])

    def faults_at(self, tick: int) -> List[Tuple[int, FaultSpec]]:
        """(spec_index, spec) pairs active at this tick — pure in (plan,
        tick), so any replay of the plan sees the identical schedule."""
        out: List[Tuple[int, FaultSpec]] = []
        for i, spec in enumerate(self.plan.faults):
            if spec.at_ticks is not None:
                if tick in spec.at_ticks:
                    out.append((i, spec))
            elif (
                spec.p > 0.0
                and tick >= spec.start
                and (spec.end is None or tick < spec.end)
                and self._rng(i, tick).random() < spec.p
            ):
                out.append((i, spec))
        return out

    def schedule(self, n_ticks: int) -> List[Tuple[int, str]]:
        """The full (tick, kind) schedule over a replay of ``n_ticks`` —
        the object the determinism tests compare across injectors."""
        return [
            (t, spec.kind)
            for t in range(n_ticks)
            for _, spec in self.faults_at(t)
        ]

    # -- solver channel (scheduler.fault_hook) ----------------------------

    def arm(self, tick: int, specs: Sequence[Tuple[int, FaultSpec]]) -> None:
        """Install this tick's solver-channel faults; event-channel specs
        are ignored here (they go through ``event_faults``)."""
        self._tick = tick
        self._armed = [s for _, s in specs if s.kind in SOLVER_CHANNEL]
        for spec in self._armed:
            self._count("injected", spec.kind)

    def disarm(self) -> None:
        self._armed = []

    def solver_hook(self, attempt: int) -> None:
        """The scheduler's pre-attempt seam: sleep spikes, raise exceptions."""
        for spec in self._armed:
            if spec.transient and attempt > 0:
                continue
            if spec.kind == "latency_spike":
                self._count("fired", spec.kind)
                time.sleep(spec.spike_s)
            else:
                self._count("fired", spec.kind)
                raise InjectedSolverFault(
                    f"injected solver exception (tick {self._tick}, "
                    f"attempt {attempt})"
                )

    # -- event channel ----------------------------------------------------

    def event_faults(self, tick: int, specs, fleet) -> List[Tuple[str, object]]:
        """(label, event) pairs to push through ``scheduler.handle`` ahead
        of the trace event: poisoned profiles, malformed/contradictory
        events, and dropout-burst leaves. ``fleet`` is the scheduler's live
        ``FleetState`` (read-only here: victims must exist *now*)."""
        out: List[Tuple[str, object]] = []
        for idx, spec in specs:
            if spec.kind not in EVENT_CHANNEL:
                continue
            rng = self._rng(idx, tick)
            if spec.kind == "nan_poison":
                out.append(("nan_poison", self._poison_event(rng, tick, fleet)))
                self._count("injected", spec.kind)
            elif spec.kind == "malformed_event":
                out.append(
                    ("malformed_event", self._malformed_event(rng, tick, fleet))
                )
                self._count("injected", spec.kind)
            elif spec.kind == "dropout_burst":
                leaves = self._burst_events(rng, tick, spec, fleet)
                out.extend(("dropout_burst", ev) for ev in leaves)
                if leaves:
                    self._count("injected", spec.kind)
        return out

    # -- process channel (gateway.chaos_process_hook) ---------------------

    def process_faults(self, tick: int, specs) -> List[Tuple[int, FaultSpec]]:
        """The process-channel specs active this tick, counted as
        injected; ``chaos_replay`` fires each through the gateway's
        process hook (SIGKILL, torn frame, RPC delay)."""
        out = [(i, s) for i, s in specs if s.kind in PROCESS_CHANNEL]
        for _, spec in out:
            self._count("injected", spec.kind)
        return out

    def count_fired(self, kind: str) -> None:
        """Record that a process-channel fault actually fired (the hook
        returned — the kill/torn-frame/delay landed on the child)."""
        self._count("fired", kind)

    def pop_rejoins(self, tick: int) -> list:
        """Device profiles due to rejoin at (or before) this tick."""
        due = []
        for t in sorted(self._rejoins):
            if t <= tick:
                due.extend(self._rejoins.pop(t))
        return due

    def pending_rejoins(self) -> int:
        return sum(len(v) for v in self._rejoins.values())

    def _victims(self, fleet, rng, count: int = 1) -> List[str]:
        """Non-head live devices, never shrinking the fleet below 2."""
        names = list(fleet.devices)
        pool = names[1:]  # head is names[0] by the _ensure_head invariant
        count = min(count, len(pool), max(0, len(names) - 2))
        if count <= 0 or not pool:
            return []
        picks = rng.choice(len(pool), size=count, replace=False)
        return [pool[int(i)] for i in picks]

    def _poison_event(self, rng, tick: int, fleet):
        """A NaN/Inf-poisoned churn event the quarantine gate must reject."""
        victims = self._victims(fleet, rng)
        flavor = int(rng.integers(0, 2)) if victims else 1
        if flavor == 0 and victims:
            # Coefficient poisoning of a live device: NaN would flow
            # straight into build_coeffs' t_comm channel if accepted.
            return DeviceDegrade(name=victims[0], t_comm_scale=float("nan"))
        # A joining device advertising an infinite throughput scalar.
        src = next(iter(fleet.devices.values()))
        dev = src.model_copy(deep=True)
        dev.name = f"poison-{self.plan.seed}-{tick}"
        dev.is_head = False
        dev.T_cpu = float("inf")
        return DeviceJoin(device=dev)

    def _malformed_event(self, rng, tick: int, fleet):
        """A structurally contradictory event (strict apply must reject)."""
        flavor = int(rng.integers(0, 3))
        if flavor == 0:
            return DeviceLeave(name=f"ghost-{self.plan.seed}-{tick}")
        if flavor == 1:
            # Duplicate join: a name already live in the fleet.
            src = next(iter(fleet.devices.values()))
            return DeviceJoin(device=src.model_copy(deep=True))
        victims = self._victims(fleet, rng)
        name = victims[0] if victims else next(iter(fleet.devices))
        return DeviceDegrade(name=name, t_comm_scale=-1.0)  # contradictory

    def _burst_events(self, rng, tick: int, spec: FaultSpec, fleet) -> list:
        """Leave events for a dropout burst; victims rejoin (same profile)
        ``rejoin_after`` ticks later via ``pop_rejoins``."""
        victims = self._victims(fleet, rng, count=spec.burst_size)
        if not victims:
            return []
        saved = []
        for name in victims:
            dev = fleet.devices[name].model_copy(deep=True)
            dev.is_head = False
            saved.append(dev)
        self._rejoins.setdefault(tick + spec.rejoin_after, []).extend(saved)
        return [DeviceLeave(name=n) for n in victims]

    def _count(self, phase: str, kind: str) -> None:
        self.counters[f"{phase}_{kind}"] += 1
        if phase == "injected":
            self.counters["injected_total"] += 1
        # hasattr, not None-check: a process-backed shard's facade hands
        # a read-only _MetricsView (child-side counters over RPC) with no
        # inc(); the injector's own self.counters still mirror everything.
        if self.metrics is not None and hasattr(self.metrics, "inc"):
            self.metrics.inc(f"fault_{phase}_{kind}")
            if phase == "injected":
                self.metrics.inc("faults_injected_total")


# -- the chaos soak --------------------------------------------------------


class ChaosRecord(NamedTuple):
    """One handled event during a chaos replay."""

    tick: int  # trace tick the event belongs to (recovery ticks continue)
    source: str  # 'trace' | 'injected:<kind>' | 'recovery'
    kind: str  # event kind handled
    quarantined: bool  # the event did not advance the fleet seq
    view: object  # the PlacementView served after the event
    ms: float
    L: int = 0  # the model's layer count in force when the view was served
    # Fleet-seq advance across the handle: 1 = applied exactly once, 0 =
    # quarantined, >1 = DOUBLE-APPLIED (a crash-recovery replay applied
    # the event on top of the dead child's application — the exactly-once
    # contract's per-record reconciliation key).
    seq_delta: int = 1


class ChaosReport(NamedTuple):
    """What a chaos replay did, plus the soak-contract checker."""

    records: List[ChaosRecord]
    views: list  # one served view per TRACE event (the replay contract)
    injected: Dict[str, int]  # injector counters (injected_*/fired_*)
    ticks_to_healthy: Optional[int]  # clean ticks until healthy (0 = already)
    final_health: str
    metrics: dict  # scheduler metrics snapshot at the end
    # The supervision tier's audit at soak end (Gateway.recovery_status,
    # via chaos_replay's recovery_probe) — None on a soak that injected
    # no process faults / ran without a supervised gateway. Feeds the
    # crash-contract section of violations().
    recovery: Optional[dict] = None

    def summary(self) -> dict:
        out = {
            "events": len(self.views),
            "handled": len(self.records),
            "injected": {
                k: v for k, v in sorted(self.injected.items())
                if k.startswith("injected_")
            },
            "quarantined": sum(1 for r in self.records if r.quarantined),
            "ticks_to_healthy": self.ticks_to_healthy,
            "final_health": self.final_health,
        }
        if self.recovery is not None:
            out["recovery"] = {
                k: self.recovery.get(k)
                for k in (
                    "worker_crashes",
                    "child_respawns",
                    "workers_quarantined",
                    "shards_recovered",
                    "events_replayed",
                    "events_lost",
                    "warm_resumes",
                    "cold_resumes",
                    "identity_resumes",
                    "mttr_p50_ms",
                    "mttr_p99_ms",
                )
                if k in self.recovery
            }
        return out

    def violations(self, L: Optional[int] = None) -> List[str]:
        """Soak-contract violations (empty = the chaos soak passed).

        ``L`` is a fallback for records captured before the per-record
        layer count existed; each record carries the model L in force when
        its view was served, so a trace with a ``model_swap`` checks every
        placement against the right architecture. A STALE view (a served
        last-known-good from before a swap) is checked for internal
        consistency only — it was checked against its own L when fresh.
        """
        out: List[str] = []
        for r in self.records:
            res = r.view.result
            want_L = r.L or L
            bad = (
                res.k < 1
                or len(res.w) != len(res.n)
                or any(w < 0 for w in res.w)
            )
            if (
                not bad
                and want_L
                and r.view.events_behind == 0
                and sum(res.w) * res.k != want_L
            ):
                bad = True
            if bad:
                out.append(
                    f"tick {r.tick} ({r.source}): structurally invalid "
                    f"placement k={res.k} w={res.w}"
                )
        must_quarantine = ("injected:nan_poison", "injected:malformed_event")
        for r in self.records:
            if r.source in must_quarantine and not r.quarantined:
                out.append(
                    f"tick {r.tick}: {r.source} event was ACCEPTED into the "
                    "fleet state instead of quarantined"
                )
        # Quarantine accounting: every quarantined record (injected
        # poison/malformed, plus collateral — e.g. a trace event naming a
        # device a dropout burst currently has out of the fleet) must be
        # counted, and nothing counted that the records cannot explain.
        counters = self.metrics.get("counters", {})
        expect_q = sum(1 for r in self.records if r.quarantined)
        got_q = counters.get("events_quarantined", 0)
        if got_q != expect_q:
            out.append(
                f"quarantine accounting: {expect_q} handled events were "
                f"quarantined but events_quarantined={got_q}"
            )
        injected_q = self.injected.get("injected_nan_poison", 0) + (
            self.injected.get("injected_malformed_event", 0)
        )
        if got_q < injected_q:
            out.append(
                f"quarantine accounting: {injected_q} poisoned/malformed "
                f"events injected but only {got_q} quarantined"
            )
        # Speculation accounting (active only when the scheduler ran with
        # --speculate): every bank probe was counted exactly once (hit or
        # miss) on a non-quarantined handle, every mode='spec' serve in
        # the records maps to a counted hit, and no hit exists without a
        # banked entry to have come from (a presolved instance or a real
        # solved tick). A drifting reconciliation here means served
        # placements and counters disagree about what speculation did.
        spec_hits = counters.get("spec_hit", 0)
        spec_probes = spec_hits + counters.get("spec_miss", 0)
        if spec_probes or counters.get("spec_presolve", 0):
            non_q = sum(1 for r in self.records if not r.quarantined)
            if spec_probes > non_q:
                out.append(
                    f"speculation accounting: {spec_probes} bank probes "
                    f"counted but only {non_q} non-quarantined events "
                    "were handled"
                )
            spec_served = sum(
                1
                for r in self.records
                # Re-serves of an older spec-published view must not
                # count: a quarantined event re-serves latest() with the
                # mode it was published under, and a FAILED solve does
                # the same with the fleet seq already advanced — only a
                # fresh serve (events_behind == 0, event accepted) is a
                # hit the counter should match.
                if not r.quarantined
                and getattr(r.view, "mode", None) == "spec"
                and getattr(r.view, "events_behind", 1) == 0
            )
            if counters.get("risk_eval", 0) == 0 and spec_served != spec_hits:
                out.append(
                    f"speculation accounting: {spec_served} mode='spec' "
                    f"serves in the records but spec_hit={spec_hits}"
                )
            solved = sum(
                counters.get(f"tick_{m}", 0)
                for m in ("cold", "warm", "margin")
            )
            # NOT `hits <= presolves + solved`: one banked entry serves
            # arbitrarily many hits (an oscillating trace re-hits the same
            # entry every cycle — the probe never consumes it). The sound
            # invariant is existential: a hit needs the bank to have been
            # populated by SOMETHING, a presolve or a banked solved tick.
            if spec_hits and not (
                counters.get("spec_presolve", 0) or solved
            ):
                out.append(
                    f"speculation accounting: spec_hit={spec_hits} but "
                    "nothing was ever banked (no presolves, no solved "
                    "ticks)"
                )
        # Admission-control accounting: a sequential soak drives one event
        # at a time, so the bounded-queue gate can never legitimately fire
        # (depth is always 0 at ingest) and coalescing can never fold
        # events (each tick completes before the next is submitted). A
        # nonzero shed or coalesce counter here means the serving path
        # rejected or folded trace events it had no overload reason to —
        # the same "counters must be explained by records" contract the
        # quarantine accounting enforces. The record-by-record shed
        # reconciliation under REAL overload (counter vs per-fleet flight
        # records) lives in traffic.shed_violations, which the overload
        # smoke and bench run.
        for c_name in ("events_shed", "events_coalesced"):
            stray = counters.get(c_name, 0)
            if stray:
                out.append(
                    f"admission accounting: {c_name}={stray} in a "
                    "sequential chaos soak (nothing was concurrently "
                    "queued, so nothing could be shed or coalesced)"
                )
        # Crash contract (process-level chaos against a supervised
        # gateway): every accepted event applied exactly once or shed,
        # respawns restore WARM, every crash actually recovered, and the
        # reconciliation the recovery tier reports agrees with the
        # record-by-record seq deltas above.
        if self.recovery is not None:
            rec = self.recovery
            lost = rec.get("events_lost", 0)
            if lost:
                out.append(
                    f"crash recovery: events_lost={lost} (every accepted "
                    "event must be applied exactly once or shed; positive "
                    "= lost, negative = double-applied)"
                )
            dbl = sum(
                1 for r in self.records
                if getattr(r, "seq_delta", 1) > 1
            )
            if dbl:
                out.append(
                    f"crash recovery: {dbl} record(s) advanced the fleet "
                    "seq more than once (a recovery replay re-applied an "
                    "event the dead child had already applied)"
                )
            cold = rec.get("cold_resumes", 0)
            if cold:
                out.append(
                    f"crash recovery: cold_resumes={cold} (a respawned "
                    "shard must restore WARM from its micro-snapshot — "
                    "zero post-recovery cold ticks)"
                )
            crashes = rec.get("worker_crashes", 0)
            recovered = rec.get("child_respawns", 0) + rec.get(
                "workers_quarantined", 0
            )
            if crashes and not recovered:
                out.append(
                    f"crash recovery: {crashes} worker crash(es) but "
                    "nothing respawned or quarantined"
                )
            shards = rec.get("shards_recovered", 0)
            warm = rec.get("warm_resumes", 0)
            # A first post-restore tick that changed identity (structural
            # event replayed first) proves nothing about warmth and
            # legitimately counts as neither warm nor cold. The resume
            # tally is checked one-sided: resume classifications from an
            # epoch BETWEEN two crashes are lost whenever no micro-
            # snapshot captured them before the next kill (the fold
            # carries the last snapshot's counters; events still
            # reconcile because WAL replay re-applies the tail), so
            # equality would flake on kill timing. More resumes than
            # recoveries, or none at all, cannot be explained that way.
            ident = rec.get("identity_resumes", 0)
            if warm + ident > shards:
                out.append(
                    f"crash recovery: warm_resumes={warm} + "
                    f"identity_resumes={ident} exceeds "
                    f"{shards} shard recover(ies) "
                    "(a restored shard resumed more than once)"
                )
            if shards and warm + ident == 0:
                out.append(
                    f"crash recovery: {shards} shard recover(ies) but no "
                    "resume was ever observed (restored shards never "
                    "proved warm — warm_resumes and "
                    "resume_identity_changed both zero)"
                )
        if self.ticks_to_healthy is None:
            out.append(
                f"service did not return to healthy (final state: "
                f"{self.final_health})"
            )
        elif self.final_health != HEALTH_HEALTHY:
            # Recovered mid-replay but re-degraded before the end (e.g. a
            # rejoin flushed during recovery failed its solve): 'returned
            # to healthy' means ENDED healthy, not touched it once.
            out.append(
                f"service re-degraded after recovering (final state: "
                f"{self.final_health})"
            )
        return out


def chaos_replay(
    scheduler,
    events: Sequence,
    plan: FaultPlan,
    recovery_tick_budget: int = 25,
    on_event=None,
    process_hook=None,
    recovery_probe=None,
) -> ChaosReport:
    """Drive a scheduler through a trace under a fault plan, then recover.

    Trace events are handled in order; each tick first fires the tick's
    PROCESS-channel faults through ``process_hook`` (SIGKILL the owning
    child, tear an RPC frame, delay the next RPC — only meaningful when
    the scheduler fronts a supervised process-backed gateway, see
    ``Gateway.chaos_process_hook``), then flushes due dropout-burst
    rejoins, injects the tick's event-channel faults (which the
    quarantine gate must reject), arms the solver-channel faults on the
    scheduler's ``fault_hook``, and finally handles the real trace event.
    After the trace, clean no-op load ticks run until the scheduler
    reports healthy, bounded by ``recovery_tick_budget``.

    ``recovery_probe`` (e.g. ``Gateway.recovery_status``) is called once
    at soak end; its dict rides the report as ``.recovery`` and arms the
    crash-contract section of ``violations()``.

    ``on_event(event, view, ms)`` fires for every handled event (the serve
    CLI's log hook). The scheduler's ``fault_hook`` is overwritten for the
    duration and cleared afterwards.
    """
    injector = FaultInjector(plan, metrics=scheduler.metrics)
    scheduler.fault_hook = injector.solver_hook
    records: List[ChaosRecord] = []
    trace_views = []

    def _fleet_seq() -> int:
        # getattr with a default also absorbs an AttributeError raised
        # INSIDE a facade's ``fleet`` property (factory-built schedulers
        # may expose no fleet); seq 0 then disables seq reconciliation
        # for that record rather than killing the soak.
        fleet = getattr(scheduler, "fleet", None)
        return getattr(fleet, "seq", 0) if fleet is not None else 0

    def _fleet_L() -> int:
        # Defensive: a factory-built scheduler (stub harnesses) may carry
        # no model; the per-record L then falls back to violations(L=...).
        fleet = getattr(scheduler, "fleet", None)
        return getattr(getattr(fleet, "model", None), "L", 0) or 0

    def _handle(ev, tick: int, source: str):
        seq_before = _fleet_seq()
        t0 = time.perf_counter()
        view = scheduler.handle(ev)
        ms = (time.perf_counter() - t0) * 1e3
        delta = _fleet_seq() - seq_before
        records.append(
            ChaosRecord(
                tick=tick,
                source=source,
                kind=getattr(ev, "kind", type(ev).__name__),
                quarantined=delta == 0,
                view=view,
                ms=ms,
                L=_fleet_L(),
                seq_delta=delta,
            )
        )
        if on_event is not None:
            on_event(ev, view, ms)
        return view

    try:
        for tick, ev in enumerate(events):
            # Arm FIRST: everything handled during this tick — rejoins,
            # injected events, the trace event — runs under this tick's
            # solver-channel faults, and nothing leaks from the previous
            # tick's arming.
            specs = injector.faults_at(tick)
            injector.arm(tick, specs)
            procs = injector.process_faults(tick, specs)
            if procs and process_hook is None:
                raise ValueError(
                    f"fault plan schedules process fault "
                    f"{procs[0][1].kind!r} at tick {tick} but no "
                    "process_hook was provided (process faults need a "
                    "supervised process-backed gateway)"
                )
            for _idx, spec in procs:
                # Fire BEFORE the tick's handles: the kill/torn frame
                # lands mid-stream and the very next dispatch walks into
                # the dead child — the recovery path under test.
                process_hook(spec.kind, spec)
                injector.count_fired(spec.kind)
            for dev in injector.pop_rejoins(tick):
                _handle(DeviceJoin(device=dev), tick, "injected:rejoin")
            for label, bad in injector.event_faults(tick, specs, scheduler.fleet):
                _handle(bad, tick, f"injected:{label}")
            trace_views.append(_handle(ev, tick, "trace"))
        injector.disarm()

        # Recovery: clean ticks (rejoins first, then a no-op drift tick)
        # until the health state machine closes the loop. The exit test is
        # the LIVE health, not the first-healthy marker: a rejoin flushed
        # here can re-degrade the service, and 'returned to healthy' means
        # ENDED healthy within the budget.
        ticks_to_healthy: Optional[int] = (
            0 if scheduler.health == HEALTH_HEALTHY else None
        )
        tick = len(events)
        for i in range(recovery_tick_budget):
            if (
                scheduler.health == HEALTH_HEALTHY
                and not injector.pending_rejoins()
            ):
                break
            for dev in injector.pop_rejoins(tick + i):
                _handle(DeviceJoin(device=dev), tick + i, "injected:rejoin")
            _handle(LoadTick(t_comm_jitter={}), tick + i, "recovery")
            if (
                ticks_to_healthy is None
                and scheduler.health == HEALTH_HEALTHY
            ):
                ticks_to_healthy = i + 1
    finally:
        scheduler.fault_hook = None

    return ChaosReport(
        records=records,
        views=trace_views,
        injected=dict(injector.counters),
        ticks_to_healthy=ticks_to_healthy,
        final_health=scheduler.health,
        metrics=scheduler.metrics_snapshot(),
        recovery=dict(recovery_probe()) if recovery_probe is not None else None,
    )

"""Solver-interior convergence reports: typed views of the in-jit telemetry.

The fused solve can record two things about its own interior (see
``ops/ipm.py`` TRACE_COLS and ``backend_jax`` RL_COLS): a per-chunk LP
convergence trace (residual norms, duality gap, Halpern restarts) and a
per-branch-and-bound-round search log (nodes expanded, incumbent, proven
bound, LP iterations spent). ``solve_sweep_jax(convergence={})`` decodes
both into one plain-lists dict; this module turns that dict into pydantic
reports:

- :class:`ConvergenceTrace` — one LP element's (one k's root relaxation)
  chunk-by-chunk trajectory: how the residuals decayed, where the restarts
  fired, how many iterations it actually ran;
- :class:`SearchTrace` — the whole branch-and-bound search: one
  :class:`RoundRecord` per executed round plus the root traces, with the
  derived facts the bench and the scheduler gate on (``rounds_to_certify``,
  ``iters_to_certify``, total restarts, the final certified gap);
- :func:`SearchTrace.digest` — the flat ``conv_*`` scalar dict that rides
  ``timings`` onto the ``sched.solve`` span and the flight recorder's tick
  records;
- a JSONL round trip (:func:`search_trace_to_jsonl` /
  :func:`search_trace_from_jsonl`) for ``solver diagnose --out`` exports.

Like the rest of the obs layer this module imports neither jax nor numpy
nor the solver — the convergence dict carries plain nested lists, so a
box with no backend can still load and render an exported report.
"""

from __future__ import annotations

import json
import math
from typing import List, Optional

from pydantic import BaseModel

__all__ = [
    "CONV_DIGEST_KEYS",
    "LPChunkSample",
    "ConvergenceTrace",
    "RoundRecord",
    "SearchTrace",
    "build_search_trace",
    "search_trace_to_jsonl",
    "search_trace_from_jsonl",
]

# Every key SearchTrace.digest() can emit — the ONE enumeration the
# scheduler's span/flight plumbing filters timings by (sched.scheduler
# builds its _CONV_DIGEST_KEYS from this, so a digest field added here
# reaches the sched.solve span and the flight records without a second
# edit; pinned by tests/test_convergence.py).
CONV_DIGEST_KEYS = (
    "conv_rounds",
    "conv_lp_iters",
    "conv_restarts",
    "conv_certified",
    "conv_final_gap",
    "conv_rounds_to_certify",
    "conv_iters_to_certify",
    "conv_final_rp",
    "conv_final_rd",
)


def _clean(v) -> Optional[float]:
    """A JSON-safe float or None: non-finite sentinel values (±inf from
    'no incumbent yet' / 'subtree exhausted', NaN artifacts) decode to
    None rather than leak into reports that get json.dumps'd."""
    if v is None:
        return None
    v = float(v)
    return v if math.isfinite(v) else None


def _rel_gap(inc, bound) -> Optional[float]:
    """Relative optimality gap of an (incumbent, bound) pair; None when it
    is undefined (no incumbent, or an unexplored -inf bound). A +inf bound
    means every subtree was exhausted or pruned — the gap is closed."""
    if inc is None or not math.isfinite(inc):
        return None
    if bound is not None and math.isinf(bound) and bound > 0:
        return 0.0
    if bound is None or not math.isfinite(bound):
        return None
    if inc == 0.0:
        return max(0.0, inc - bound)
    return max(0.0, (inc - bound) / abs(inc))


class LPChunkSample(BaseModel):
    """One chunk-boundary row of an LP kernel's convergence trace."""

    iters: int  # cumulative iterations executed at this boundary
    rp_norm: float  # primal residual inf-norm (scaled system)
    rd_norm: float  # dual residual inf-norm (scaled system)
    gap: float  # engine gauge: complementarity mu (ipm) / norm. gap (pdhg)
    restarts: int  # cumulative Halpern restart chunks (0 for ipm)


class ConvergenceTrace(BaseModel):
    """One LP element's chunk-by-chunk convergence trajectory."""

    engine: str  # 'ipm' | 'pdhg'
    element: int  # batch row (root traces: the k-grid index)
    k: Optional[int] = None  # segment count, when the element maps to one
    samples: List[LPChunkSample]

    @property
    def iters(self) -> int:
        return self.samples[-1].iters if self.samples else 0

    @property
    def restarts(self) -> int:
        return self.samples[-1].restarts if self.samples else 0

    @property
    def final_rp(self) -> Optional[float]:
        return self.samples[-1].rp_norm if self.samples else None

    @property
    def final_rd(self) -> Optional[float]:
        return self.samples[-1].rd_norm if self.samples else None

    @property
    def final_gap(self) -> Optional[float]:
        return self.samples[-1].gap if self.samples else None


class RoundRecord(BaseModel):
    """One executed branch-and-bound round."""

    round: int  # 0 = the root round
    nodes_expanded: int  # frontier rows given an LP solve this round
    nodes_live: int  # live nodes after pruning/branching
    incumbent: Optional[float] = None  # best integer objective so far
    bound: Optional[float] = None  # proven lower bound after the round
    gap: Optional[float] = None  # relative (incumbent, bound) gap
    lp_iters: int = 0  # LP iterations this round actually executed


class SearchTrace(BaseModel):
    """The whole search: per-round records + root LP traces + the facts
    derived from them. Built by :func:`build_search_trace`."""

    lp_backend: str
    mip_gap: float
    incumbent: Optional[float] = None
    best_bound: Optional[float] = None
    certified: bool = False
    final_gap: Optional[float] = None
    lp_iters_executed: int = 0  # the header counter (sums the rounds)
    rounds: List[RoundRecord]
    root_traces: List[ConvergenceTrace]
    rounds_to_certify: Optional[int] = None  # executed rounds until the
    #                                          gap first closed; None = never
    iters_to_certify: Optional[int] = None  # cumulative LP iters to there
    restarts: int = 0  # total Halpern restarts across the root traces

    def digest(self) -> dict:
        """Flat ``conv_*`` scalars for ``timings`` / span attrs / flight
        records; None-valued facts are omitted so the default timings dict
        never grows null keys."""
        final_rps = [
            t.final_rp for t in self.root_traces if t.final_rp is not None
        ]
        final_rds = [
            t.final_rd for t in self.root_traces if t.final_rd is not None
        ]
        out = {
            "conv_rounds": len(self.rounds),
            "conv_lp_iters": self.lp_iters_executed,
            "conv_restarts": self.restarts,
            "conv_certified": bool(self.certified),
        }
        if self.final_gap is not None:
            out["conv_final_gap"] = self.final_gap
        if self.rounds_to_certify is not None:
            out["conv_rounds_to_certify"] = self.rounds_to_certify
        if self.iters_to_certify is not None:
            out["conv_iters_to_certify"] = self.iters_to_certify
        if final_rps:
            out["conv_final_rp"] = max(final_rps)
        if final_rds:
            out["conv_final_rd"] = max(final_rds)
        return out

    def render_text(self, max_lp_rows: int = 12) -> str:
        """The ``solver diagnose`` tables: a per-round search table, then
        each root LP trace (up to ``max_lp_rows`` chunk rows per element,
        tail-biased — the end of a trajectory is where convergence or a
        stall shows)."""

        def f(v, spec="14.6f"):
            return format(v, spec) if v is not None else " " * 10 + "n/a "

        def g(v):
            return f"{v:10.3e}" if v is not None else "       n/a"

        lines = [
            f"search: engine={self.lp_backend} certified={self.certified} "
            f"final_gap={g(self.final_gap).strip()} (mip_gap {self.mip_gap:g})",
            f"rounds={len(self.rounds)} lp_iters={self.lp_iters_executed} "
            f"restarts={self.restarts} "
            f"rounds_to_certify={self.rounds_to_certify} "
            f"iters_to_certify={self.iters_to_certify}",
            f"{'round':>5s} {'expanded':>8s} {'live':>5s} "
            f"{'incumbent':>14s} {'bound':>14s} {'gap':>10s} {'lp_iters':>8s}",
        ]
        for r in self.rounds:
            lines.append(
                f"{r.round:5d} {r.nodes_expanded:8d} {r.nodes_live:5d} "
                f"{f(r.incumbent)} {f(r.bound)} {g(r.gap)} {r.lp_iters:8d}"
            )
        for t in self.root_traces:
            if not t.samples:
                continue
            k_txt = f" k={t.k}" if t.k is not None else ""
            lines.append(
                f"root LP trace [{t.engine}] element {t.element}{k_txt}: "
                f"{t.iters} iters, {t.restarts} restarts"
            )
            shown = t.samples[-max_lp_rows:]
            skipped = len(t.samples) - len(shown)
            if skipped:
                lines.append(f"  ... {skipped} earlier chunk row(s) elided")
            for s in shown:
                lines.append(
                    f"  it={s.iters:6d} rp={s.rp_norm:9.3e} "
                    f"rd={s.rd_norm:9.3e} gap={s.gap:9.3e} "
                    f"restarts={s.restarts}"
                )
        return "\n".join(lines)


def build_search_trace(conv: dict) -> SearchTrace:
    """A :class:`SearchTrace` from the raw convergence dict
    ``solve_sweep_jax(convergence=...)`` fills (plain nested lists; see
    ``backend_jax._decode_convergence`` for the layout)."""
    engine = str(conv.get("lp_backend", "ipm"))
    mip_gap = float(conv.get("mip_gap", 0.0))
    ks = list(conv.get("ks", []))

    rounds: List[RoundRecord] = []
    for row in conv.get("round_log", []):
        idx, expanded, live, inc, bound, lp_iters = row
        inc_c, bound_c = _clean(inc), _clean(bound)
        rounds.append(
            RoundRecord(
                round=int(idx),
                nodes_expanded=int(round(expanded)),
                nodes_live=int(round(live)),
                incumbent=inc_c,
                bound=bound_c,
                gap=_rel_gap(inc_c, float(bound)),
                lp_iters=int(round(lp_iters)),
            )
        )

    traces: List[ConvergenceTrace] = []
    for e, rows in enumerate(conv.get("root_trace", [])):
        samples = [
            LPChunkSample(
                iters=int(round(r[0])),
                rp_norm=float(r[1]),
                rd_norm=float(r[2]),
                gap=float(r[3]),
                restarts=int(round(r[4])),
            )
            for r in rows
            if r[5] > 0.5  # live rows are the element's valid samples
        ]
        traces.append(
            ConvergenceTrace(
                engine=engine,
                element=e,
                k=int(ks[e]) if e < len(ks) else None,
                samples=samples,
            )
        )

    inc = _clean(conv.get("incumbent"))
    bound_raw = conv.get("best_bound")
    final_gap = _rel_gap(
        inc, float(bound_raw) if bound_raw is not None else None
    )
    certified = final_gap is not None and final_gap <= mip_gap + 1e-12

    rounds_to_certify = None
    iters_to_certify = None
    seen_iters = 0
    for n, r in enumerate(rounds, start=1):
        seen_iters += r.lp_iters
        if r.gap is not None and r.gap <= mip_gap + 1e-12:
            rounds_to_certify = n
            iters_to_certify = seen_iters
            break

    return SearchTrace(
        lp_backend=engine,
        mip_gap=mip_gap,
        incumbent=inc,
        best_bound=_clean(bound_raw),
        certified=certified,
        final_gap=final_gap,
        lp_iters_executed=int(round(conv.get("ipm_iters_executed", 0.0))),
        rounds=rounds,
        root_traces=traces,
        rounds_to_certify=rounds_to_certify,
        iters_to_certify=iters_to_certify,
        restarts=sum(t.restarts for t in traces),
    )


# -- JSONL round trip (solver diagnose --out / --load) ----------------------

_HEADER_FIELDS = (
    "lp_backend", "mip_gap", "incumbent", "best_bound", "certified",
    "final_gap", "lp_iters_executed", "rounds_to_certify",
    "iters_to_certify", "restarts",
)


def search_trace_to_jsonl(trace: SearchTrace) -> str:
    """One ``search`` header line, one ``round`` line per round, one ``lp``
    line per root trace element — greppable, streamable, and loadable back
    with :func:`search_trace_from_jsonl`."""
    lines = [
        json.dumps(
            {"type": "search", **{f: getattr(trace, f) for f in _HEADER_FIELDS}}
        )
    ]
    for r in trace.rounds:
        lines.append(json.dumps({"type": "round", **r.model_dump()}))
    for t in trace.root_traces:
        lines.append(
            json.dumps(
                {
                    "type": "lp",
                    "engine": t.engine,
                    "element": t.element,
                    "k": t.k,
                    "samples": [s.model_dump() for s in t.samples],
                }
            )
        )
    return "\n".join(lines) + "\n"


def search_trace_from_jsonl(text: str) -> SearchTrace:
    """Rebuild a :class:`SearchTrace` from an exported JSONL. Malformed
    input raises ValueError — a diagnose report silently missing its
    rounds would defeat the non-empty acceptance gate."""
    header = None
    rounds: List[RoundRecord] = []
    traces: List[ConvergenceTrace] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        kind = rec.pop("type", None)
        if kind == "search":
            header = rec
        elif kind == "round":
            rounds.append(RoundRecord.model_validate(rec))
        elif kind == "lp":
            traces.append(
                ConvergenceTrace(
                    engine=rec["engine"],
                    element=rec["element"],
                    k=rec.get("k"),
                    samples=[
                        LPChunkSample.model_validate(s)
                        for s in rec.get("samples", [])
                    ],
                )
            )
        else:
            raise ValueError(f"unknown diagnose JSONL record type {kind!r}")
    if header is None:
        raise ValueError("diagnose JSONL has no 'search' header line")
    return SearchTrace(**header, rounds=rounds, root_traces=traces)

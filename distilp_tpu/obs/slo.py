"""Declarative SLOs + multi-window multi-burn-rate alerting + signals.

The Google-SRE alerting recipe, applied to the in-process timelines of
``obs.timeline``: an SLO compiles to an error budget (``1 - objective``),
the timeline supplies the error ratio over each alert window, and an
alert fires only when EVERY window of a rule burns past its threshold at
once — the long window proves the burn is sustained, the short window
proves it is still happening (so a long-resolved incident cannot page at
the tail of a 1 h window). Clearing is hysteretic: every window must
fall below ``clear_factor`` x threshold and STAY there for
``clear_hold_s`` before the alert closes, so flapping load cannot flap
alerts.

Three spec kinds cover the serving tier's objectives:

- ``ratio``      — bad-counter delta / total-counter delta per window
                   (availability = 1 − shed ratio, solver health =
                   escalations / ticks);
- ``threshold``  — fraction of gauge samples past a bound per window
                   (latency tiers on ``last_serve_ms`` / p99 series,
                   iters-to-certify ceilings);
- ``rate_above`` — a counter's per-second rate vs a bound, normalized by
                   it (failure-rate floors with no natural total).

Alert transitions are first-class observability: each open/close is
counted (``slo_alert_opened``/``slo_alert_closed``), flight-recorded
(``kind: "slo_alert"`` records on the recorder's ``slo`` ring) and
emitted as a zero-duration ``sched.alert`` span event — so the alert
trail reconciles against the same black box as every other serving
fault.

``SignalsPayload`` is the autoscaling contract (``GET /signals``):
per-worker queue depth + trend, per-SLO burn rates, and headroom vs the
capacity probe's max-sustainable-eps — versioned and pydantic-schema'd
so the federation tier (ROADMAP item 1) consumes it unchanged.

Specs are JSON-loadable (``SLOConfig.from_json``); evaluation against a
DUMPED timeline (``SLOEngine.replay`` / ``solver slo``) is a pure
function of (timeline, spec) — byte-deterministic, which is what lets
``make smoke-slo`` pin an exact expected alert sequence.
"""

from __future__ import annotations

import json
import statistics
from pathlib import Path
from typing import Dict, List, Literal, Optional

from pydantic import BaseModel, Field, model_validator

from .timeline import Timeline

__all__ = [
    "BurnWindow",
    "AlertRule",
    "SLOSpec",
    "SLOConfig",
    "SLOEngine",
    "WorkerSignal",
    "SLOBurnSignal",
    "SignalsPayload",
    "build_signals",
    "HISTORY_TREND_RULES",
    "evaluate_history",
]

# Queue-depth series convention shared by Gateway.timeline_sample and the
# signals builder (one definition so neither side can drift).
QUEUE_DEPTH_PREFIX = "queue_depth.w"

# Series a fixed-cadence sampler keeps feeding while the process lives —
# safe under a threshold-kind SLO with no staleness horizon. Event-fed
# series (openloop.*, anything per-request) stop getting points when
# traffic stops, which is exactly when a fired alert needs data to close.
_GAUGE_SERIES_PREFIXES = ("lat.", "queue_depth.", "conv.")
_GAUGE_SERIES_EXACT = frozenset({"last_serve_ms", "health", "compile_ms"})


def _looks_like_gauge(series: str) -> bool:
    return series in _GAUGE_SERIES_EXACT or any(
        series.startswith(p) for p in _GAUGE_SERIES_PREFIXES
    )
# Trend window for /signals' queue-depth slope, seconds.
SIGNAL_TREND_WINDOW_S = 30.0


class BurnWindow(BaseModel):
    """One window of a multi-window rule: alert pressure exists when the
    measured burn rate over ``window_s`` is >= ``burn_rate`` (burn rate =
    error ratio / error budget, so 1.0 burns the budget exactly at the
    objective's horizon)."""

    window_s: float = Field(gt=0)
    burn_rate: float = Field(gt=0)


class AlertRule(BaseModel):
    """A severity tier: fires when ALL windows burn at once; clears with
    hysteresis (every window below ``clear_factor`` x its threshold for
    ``clear_hold_s`` of consecutive evaluations)."""

    severity: str = "page"
    windows: List[BurnWindow] = Field(min_length=1)
    clear_factor: float = Field(default=0.9, gt=0, le=1.0)
    clear_hold_s: float = Field(default=0.0, ge=0)


def default_alert_rules() -> List[AlertRule]:
    """The Google-SRE default ladder: 14.4x over (1h AND 5m) pages —
    2% of a 30-day budget in an hour; 6x over (6h AND 30m) warns."""
    return [
        AlertRule(
            severity="page",
            windows=[
                BurnWindow(window_s=3600, burn_rate=14.4),
                BurnWindow(window_s=300, burn_rate=14.4),
            ],
        ),
        AlertRule(
            severity="warn",
            windows=[
                BurnWindow(window_s=21600, burn_rate=6.0),
                BurnWindow(window_s=1800, burn_rate=6.0),
            ],
        ),
    ]


class SLOSpec(BaseModel):
    """One declarative objective over timeline series (see module doc)."""

    name: str
    kind: Literal["ratio", "threshold", "rate_above"]
    objective: float = Field(gt=0, lt=1)
    description: str = ""
    # ratio:
    bad_series: Optional[str] = None
    total_series: Optional[str] = None
    # threshold (gauge) / rate_above (counter):
    series: Optional[str] = None
    threshold: Optional[float] = None
    # Staleness horizon for EVENT-FED series (e.g. openloop.latency_ms,
    # which only gets a point per completed event): once the newest
    # sample of the spec's series is older than this, the window is
    # treated as KNOWN-IDLE — error ratio 0.0 instead of None — so the
    # alert's hysteretic close can actually run. Without it a
    # threshold-kind alert over an event feed holds its window-slid
    # "insufficient data" state FOREVER once traffic stops (the PR 13
    # gotcha, fixed at the source). Continuously-sampled gauge series
    # (lat.*.p99_ms, queue_depth.*) never go stale while the sampler
    # lives, so they don't need this; the validator below steers
    # threshold specs toward them when no horizon is given.
    stale_after_s: Optional[float] = Field(default=None, gt=0)
    alerts: List[AlertRule] = Field(default_factory=default_alert_rules)

    @model_validator(mode="after")
    def _check_kind_fields(self) -> "SLOSpec":
        if self.kind == "ratio":
            if not (self.bad_series and self.total_series):
                raise ValueError(
                    f"SLO {self.name!r}: kind=ratio needs bad_series and "
                    "total_series"
                )
        else:
            if not self.series or self.threshold is None:
                raise ValueError(
                    f"SLO {self.name!r}: kind={self.kind} needs series "
                    "and threshold"
                )
            if (
                self.kind == "threshold"
                and self.stale_after_s is None
                and not _looks_like_gauge(self.series)
            ):
                import warnings

                warnings.warn(
                    f"SLO {self.name!r}: threshold over {self.series!r} "
                    "looks event-fed — once the alert window slides past "
                    "the last point the state machine holds (an open "
                    "alert can never close). Use a continuously-sampled "
                    "gauge series (lat.*.p99_ms, queue_depth.*) or set "
                    "stale_after_s so an idle feed reads as error 0.",
                    stacklevel=2,
                )
        return self

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def error_ratio(
        self, timeline: Timeline, window_s: float, now: Optional[float]
    ) -> Optional[float]:
        """The windowed error ratio in [0, 1]; None = insufficient data
        (which neither fires nor clears — the state machine holds)."""
        if self.kind == "ratio":
            return timeline.ratio(
                self.bad_series, self.total_series, window_s, now
            )
        if self.kind == "threshold":
            frac = timeline.frac_above(
                self.series, self.threshold, window_s, now
            )
            if frac is None:
                return self._stale_zero(timeline, now)
            return frac
        rate = timeline.rate(self.series, window_s, now)
        if rate is None:
            return self._stale_zero(timeline, now)
        # rate_above: normalize the counter's per-second rate by the
        # bound so "budget's worth of badness" keeps one meaning across
        # kinds (rate == threshold -> ratio == budget -> burn == 1).
        return min(1.0, (rate / self.threshold) * self.budget)

    def _stale_zero(
        self, timeline: Timeline, now: Optional[float]
    ) -> Optional[float]:
        """None → 0.0 when the spec's event-fed series went KNOWN-idle:
        the series has recorded at least one point, its newest point is
        older than ``stale_after_s``, and the caller gave a horizon. An
        idle event feed burns nothing (budgets are request-weighted), so
        the windowed None must become a closeable zero — otherwise the
        window slides past the last point and the alert holds open
        forever. A series that never recorded stays None: a sampler that
        never came up is missing data, not idleness."""
        if self.stale_after_s is None or now is None:
            return None
        latest = timeline.latest(self.series)
        if latest is None:
            return None
        if now - latest[0] >= self.stale_after_s:
            return 0.0
        return None

    def burn_rate(
        self, timeline: Timeline, window_s: float, now: Optional[float]
    ) -> Optional[float]:
        ratio = self.error_ratio(timeline, window_s, now)
        if ratio is None:
            return None
        return ratio / self.budget


class SLOConfig(BaseModel):
    """A JSON-loadable set of SLOs (the ``--slo <spec.json>`` payload)."""

    slos: List[SLOSpec] = Field(min_length=1)

    @classmethod
    def from_json(cls, path) -> "SLOConfig":
        return cls.model_validate(
            json.loads(Path(path).read_text(encoding="utf-8"))
        )

    def to_json(self) -> str:
        return json.dumps(self.model_dump(), indent=2, sort_keys=True) + "\n"


class _RuleState:
    """Per (slo, rule) alert state machine (engine-internal)."""

    __slots__ = ("firing", "since", "below_since")

    def __init__(self) -> None:
        self.firing = False
        self.since: Optional[float] = None
        self.below_since: Optional[float] = None


class SLOEngine:
    """Evaluates an ``SLOConfig`` against a timeline; owns alert state.

    Live mode: ``evaluate(now)`` rides the timeline sampler's
    ``on_sample`` hook (no thread of its own). Offline mode:
    ``replay(step_s)`` walks a dumped timeline's own clock — a pure
    function of (timeline, spec, step), which is what the deterministic
    smoke pins.
    """

    def __init__(
        self,
        config: SLOConfig,
        timeline: Timeline,
        metrics=None,
        tracer=None,
        flight=None,
        flight_key: str = "slo",
        events_capacity: int = 4096,
    ):
        self.config = config
        self.timeline = timeline
        self.metrics = metrics
        self.tracer = tracer
        self.flight = flight
        self.flight_key = flight_key
        self._states: Dict[tuple, _RuleState] = {
            (slo.name, rule.severity): _RuleState()
            for slo in config.slos
            for rule in slo.alerts
        }
        # Bounded like every other obs trail (timeline rings, flight
        # rings): a long-lived daemon under flapping load must not grow
        # the transition list — and every GET /slo payload — forever.
        # Oldest transitions fall off; record-by-record reconciliation
        # against counters therefore assumes the audited run fits the
        # capacity (size it to the window, same rule as the flight ring).
        from collections import deque

        self.events: "deque[dict]" = deque(maxlen=max(1, events_capacity))

    # -- evaluation --------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass; returns the transitions it caused."""
        if now is None:
            bounds = self.timeline.bounds()
            if bounds is None:
                return []
            now = bounds[1]
        out: List[dict] = []
        for slo in self.config.slos:
            burns = {
                w.window_s: slo.burn_rate(self.timeline, w.window_s, now)
                for rule in slo.alerts
                for w in rule.windows
            }
            for rule in slo.alerts:
                state = self._states[(slo.name, rule.severity)]
                rule_burns = [burns[w.window_s] for w in rule.windows]
                all_over = all(
                    b is not None and b >= w.burn_rate
                    for b, w in zip(rule_burns, rule.windows)
                )
                all_clear = all(
                    b is not None and b < w.burn_rate * rule.clear_factor
                    for b, w in zip(rule_burns, rule.windows)
                )
                if not state.firing:
                    state.below_since = None
                    if all_over:
                        state.firing = True
                        state.since = now
                        out.append(
                            self._transition(
                                "open", slo, rule, now, rule_burns
                            )
                        )
                    continue
                # Firing: hysteresis — clear only after every window sat
                # below clear_factor x threshold for clear_hold_s. A
                # window with insufficient data holds the state (neither
                # direction), so a sampler gap cannot silently close an
                # incident.
                if not all_clear:
                    state.below_since = None
                    continue
                if state.below_since is None:
                    state.below_since = now
                if now - state.below_since >= rule.clear_hold_s:
                    state.firing = False
                    state.since = None
                    state.below_since = None
                    out.append(
                        self._transition("close", slo, rule, now, rule_burns)
                    )
        return out

    def _transition(
        self, kind: str, slo: SLOSpec, rule: AlertRule, now: float, burns
    ) -> dict:
        event = {
            "kind": "slo_alert",
            "state": kind,  # "open" | "close"
            "slo": slo.name,
            "severity": rule.severity,
            "t": round(now, 6),
            "windows_s": [w.window_s for w in rule.windows],
            "burn": {
                f"{w.window_s:g}s": (None if b is None else round(b, 4))
                for w, b in zip(rule.windows, burns)
            },
        }
        self.events.append(event)
        if self.metrics is not None:
            self.metrics.inc(
                "slo_alert_opened" if kind == "open" else "slo_alert_closed"
            )
        if self.flight is not None:
            self.flight.record(self.flight_key, dict(event))
        if self.tracer is not None and getattr(self.tracer, "enabled", False):
            from .trace import now_ms

            t = now_ms()
            self.tracer.record_span(
                "sched.alert",
                t,
                t,
                attrs={
                    "slo": slo.name,
                    "severity": rule.severity,
                    "state": kind,
                },
            )
        return event

    # -- views -------------------------------------------------------------

    def status(self, now: Optional[float] = None) -> dict:
        """The ``GET /slo`` payload: per-SLO budget, per-window burn
        rates, and the live alert states."""
        if now is None:
            bounds = self.timeline.bounds()
            now = bounds[1] if bounds else None
        slos = []
        for slo in self.config.slos:
            rules = []
            for rule in slo.alerts:
                state = self._states[(slo.name, rule.severity)]
                rules.append(
                    {
                        "severity": rule.severity,
                        "firing": state.firing,
                        "since": state.since,
                        "windows": [
                            {
                                "window_s": w.window_s,
                                "threshold": w.burn_rate,
                                "burn": (
                                    None
                                    if now is None
                                    else slo.burn_rate(
                                        self.timeline, w.window_s, now
                                    )
                                ),
                            }
                            for w in rule.windows
                        ],
                    }
                )
            slos.append(
                {
                    "name": slo.name,
                    "kind": slo.kind,
                    "objective": slo.objective,
                    "budget": slo.budget,
                    "description": slo.description,
                    "alerts": rules,
                }
            )
        return {
            "now": now,
            "slos": slos,
            "alerts_open": sum(
                1 for s in self._states.values() if s.firing
            ),
            "events": list(self.events),
        }

    def firing(self) -> List[dict]:
        return [
            {"slo": name, "severity": sev, "since": st.since}
            for (name, sev), st in sorted(self._states.items())
            if st.firing
        ]

    # -- offline replay ----------------------------------------------------

    def replay(self, step_s: float) -> List[dict]:
        """Walk the timeline's own clock from oldest to newest sample in
        ``step_s`` increments, evaluating at each step. Pure function of
        (timeline, config, step_s): same inputs, same transition list —
        the property ``make smoke-slo`` gates on."""
        if step_s <= 0:
            raise ValueError("replay step must be > 0")
        bounds = self.timeline.bounds()
        if bounds is None:
            return []
        t0, t1 = bounds
        out: List[dict] = []
        steps = int((t1 - t0) / step_s) + 1
        for i in range(steps + 1):
            now = min(t0 + i * step_s, t1)
            out.extend(self.evaluate(now))
            if now >= t1:
                break
        return out


# -- the autoscaling signal surface (GET /signals) ---------------------------


class WorkerSignal(BaseModel):
    """One solve worker's admission-side state."""

    worker: int
    queue_depth: float
    # Least-squares depth slope over the trend window; None until two
    # samples exist. Positive and sustained = the worker is losing.
    queue_depth_trend_per_s: Optional[float] = None


class SLOBurnSignal(BaseModel):
    """One SLO's live burn rates (window -> burn; None = no data yet)."""

    slo: str
    budget: float
    burn: Dict[str, Optional[float]]
    firing: List[str]  # severities currently firing


class CombineSignal(BaseModel):
    """The cross-shard solve combiner's live state (distilp_tpu.combine):
    lifetime batch counters plus bucket occupancy — the signal that says
    whether combined dispatches are actually filling their buckets (a
    padding_waste_mean near 1 or occupancy_mean near 1 means the bucket
    policy is mis-sized for the traffic)."""

    batches: int = 0
    instances: int = 0
    flush_full: int = 0
    flush_deadline: int = 0
    errors: int = 0
    pending: int = 0
    buckets: int = 0
    occupancy_mean: Optional[float] = None
    padding_waste_mean: Optional[float] = None


class SignalsPayload(BaseModel):
    """The versioned autoscaling contract.

    Consumers (ROADMAP item 1's federation tier) must key on ``version``
    and validate against THIS schema; new fields are additive, breaking
    changes bump the version. ``headroom_eps`` is the one-number answer:
    how much more offered load fits before the capacity probe's
    max-sustainable rate — negative means shed territory.
    """

    version: Literal[1] = 1
    t: Optional[float] = None
    workers: List[WorkerSignal] = Field(default_factory=list)
    queue_depth_total: float = 0.0
    slos: List[SLOBurnSignal] = Field(default_factory=list)
    alerts_open: int = 0
    # Offered/served rate observed on the timeline (events/second).
    recent_eps: Optional[float] = None
    shed_eps: Optional[float] = None
    # From the PR 12 closed-loop capacity probe (bench/serve config).
    max_sustainable_eps: Optional[float] = None
    headroom_eps: Optional[float] = None
    # From the memory ledger (obs.memory, serve --memory-ledger): budget
    # minus host RSS, bytes. Additive (None without a ledger or budget),
    # so version stays 1 — old consumers ignore it, the federation tier
    # scales on it the same way it scales on headroom_eps.
    mem_headroom_bytes: Optional[float] = None
    # Cross-shard solve combiner state. Additive (None when the gateway
    # runs per-shard), same versioning argument as mem_headroom_bytes.
    combine: Optional[CombineSignal] = None
    # Crash-recovery posture (Gateway.recovery_status()): crash/respawn/
    # quarantine counters, events replayed vs lost, MTTR quantiles.
    # Additive (None unless the gateway supervises a process tier); the
    # controller's quarantine vote keys on workers_quarantined here.
    recovery: Optional[dict] = None


def build_signals(
    timeline: Timeline,
    engine: Optional[SLOEngine] = None,
    capacity_eps: Optional[float] = None,
    now: Optional[float] = None,
    rate_window_s: float = 30.0,
    combine: Optional[dict] = None,
    recovery: Optional[dict] = None,
) -> SignalsPayload:
    """Assemble the ``/signals`` payload from a timeline (+ optional SLO
    engine and capacity estimate). Pure read — safe on any thread."""
    if now is None:
        bounds = timeline.bounds()
        now = bounds[1] if bounds else None
    workers: List[WorkerSignal] = []
    total_depth = 0.0
    for name in timeline.names():
        if not name.startswith(QUEUE_DEPTH_PREFIX):
            continue
        suffix = name[len(QUEUE_DEPTH_PREFIX):]
        if not suffix.isdigit():
            continue
        if now is None:
            latest = timeline.latest(name)
            depth = latest[1] if latest else 0.0
        else:
            # Point-in-time read: live callers pass now == the newest
            # sample (same answer as latest); an offline controller
            # replay passes a historical t and must not see the future.
            at = timeline.value_at(name, now)
            depth = at if at is not None else 0.0
        total_depth += depth
        workers.append(
            WorkerSignal(
                worker=int(suffix),
                queue_depth=depth,
                queue_depth_trend_per_s=(
                    None
                    if now is None
                    else timeline.trend_per_s(
                        name, SIGNAL_TREND_WINDOW_S, now
                    )
                ),
            )
        )
    workers.sort(key=lambda w: w.worker)
    slos: List[SLOBurnSignal] = []
    alerts_open = 0
    if engine is not None:
        for slo in engine.config.slos:
            windows = sorted(
                {w.window_s for rule in slo.alerts for w in rule.windows}
            )
            firing = [
                sev
                for (name, sev), st in engine._states.items()
                if name == slo.name and st.firing
            ]
            alerts_open += len(firing)
            slos.append(
                SLOBurnSignal(
                    slo=slo.name,
                    budget=slo.budget,
                    burn={
                        f"{w:g}s": (
                            None
                            if now is None
                            else slo.burn_rate(engine.timeline, w, now)
                        )
                        for w in windows
                    },
                    firing=sorted(firing),
                )
            )
    recent = (
        None
        if now is None
        else timeline.rate("c.gateway_events", rate_window_s, now)
    )
    shed = (
        None
        if now is None
        else timeline.rate("c.events_shed", rate_window_s, now)
    )
    headroom = None
    if capacity_eps is not None and recent is not None:
        headroom = capacity_eps - recent
    # Memory headroom rides the same payload when a memory ledger is
    # live: the scale-up signal (headroom_eps says "can take more load",
    # mem_headroom_bytes says "has the memory to take it on").
    from . import memory as _memory

    mled = _memory.current()
    mem_headroom = mled.headroom_bytes() if mled is not None else None
    return SignalsPayload(
        t=now,
        workers=workers,
        queue_depth_total=total_depth,
        slos=slos,
        alerts_open=alerts_open,
        recent_eps=recent,
        shed_eps=shed,
        max_sustainable_eps=capacity_eps,
        headroom_eps=headroom,
        mem_headroom_bytes=mem_headroom,
        combine=CombineSignal(**combine) if combine is not None else None,
        recovery=dict(recovery) if recovery is not None else None,
    )


# -- bench-history trend rules (solver slo --history) ------------------------

# (key, direction, tolerance): the newest committed bench round's value
# may not regress more than `tolerance` against the MEDIAN of the prior
# rounds. Mirrors bench.py's --against gate set, but across the whole
# committed history instead of one reference capture — the machine-
# readable version of "read BENCH_HISTORY.jsonl before trusting a trend".
HISTORY_TREND_RULES = (
    ("value", "lower", 0.25),
    ("warm_tick_ms", "lower", 0.25),
    ("gateway_events_per_sec_100f_4w", "higher", 0.25),
    ("overload_max_sustainable_eps", "higher", 0.25),
    ("spec_hit_rate", "higher", 0.25),
    ("obs_overhead_pct", "lower", None),  # reported only, never gated
    ("slo_overhead_pct", "lower", None),
)


def evaluate_history(rows: List[dict], rules=HISTORY_TREND_RULES):
    """Trend verdicts over BENCH_HISTORY.jsonl rows (oldest first).

    Returns ``(table_rows, violations)``: one table row per rule with the
    prior-median and newest value, and a violation string per gated rule
    whose newest value regressed past its tolerance. Wall-clock keys are
    box-sensitive (the history spans capture machines), so tolerances
    here are looser than --against's same-box gate — this is a trend
    check, not a perf gate.
    """
    table: List[dict] = []
    violations: List[str] = []
    for key, direction, tol in rules:
        vals = [
            r[key] for r in rows if isinstance(r.get(key), (int, float))
        ]
        if len(vals) < 2:
            table.append(
                {"key": key, "n": len(vals), "median": None,
                 "latest": vals[-1] if vals else None, "change": None}
            )
            continue
        median = statistics.median(vals[:-1])
        latest = vals[-1]
        change = (latest - median) / abs(median) if median else None
        table.append(
            {"key": key, "n": len(vals), "median": median,
             "latest": latest, "change": change}
        )
        if tol is None or change is None:
            continue
        regressed = change > tol if direction == "lower" else change < -tol
        if regressed:
            violations.append(
                f"{key}: latest {latest:g} vs prior median {median:g} "
                f"({change:+.1%}, {direction}-is-better, tol {tol:.0%})"
            )
    return table, violations

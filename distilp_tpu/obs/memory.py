"""Memory ledger: per-entry XLA memory analysis + live watermarks + leak gate.

Spans (PR 8), convergence (PR 10), SLOs (PR 13) and compilation (PR 14)
left exactly one axis of the serving stack unobserved: memory — the axis
that is now the binding scaling constraint (the bench's fleet_scale
section skips the IPM's M=4096 arm on an analytic, never-validated
proxy, and ROADMAP item 3's operator sharding needs per-kernel memory
attribution before any mesh decision). The ledger makes memory
first-class:

- **Static memory model per entry point.** The ledger rides the compile
  ledger's ``instrument()`` registry (a dispatch hook, see
  ``compile_ledger.set_dispatch_hook``): the first time a registered jit
  entry point is dispatched from Python (never inside an outer trace —
  tracers cannot lower), the ledger AOT-lowers it at the call's own
  arguments and records ``lower(...).compile().memory_analysis()`` —
  temp / argument / output / generated-code bytes — plus the same
  compiled object's ``cost_analysis()`` FLOPs. The AOT pass's own
  compile events are suppressed through PR 14's ``_tls.suppress``
  machinery, exactly like the compile ledger's cost attribution; a
  backend that does not report (``memory_analysis()`` returning None, or
  the AOT path raising) records a graceful ``None`` — absent, never
  zeroed.
- **Live watermarks.** ``sample()`` records jax live-array bytes by
  backend platform plus host RSS/HWM parsed stdlib-only from
  ``/proc/self/status`` (``VmHWM`` is genuinely absent on some container
  kernels — absent fields stay ``None``). A live-array walk costs ~3 us
  per live array, so samples are throttled (``sample_min_interval_s``);
  the serving path attaches watermark attrs only on ticks where a fresh
  sample actually landed.
- **Leak gate.** ``mark_warm()`` pins the warm-serving baseline;
  ``leak_report()`` compares the newest live-array bytes against it.
  The warm path's contract is FLAT — drift/spec/spec_near ticks allocate
  nothing persistent — gated absolutely by ``bench --against`` and
  pinned by the >=100-tick regression test on both LP engines.
- **Headroom.** ``headroom_bytes()`` = budget - RSS (budget defaults to
  ``/proc/meminfo`` MemTotal; override per deployment). It feeds the
  ``mem_headroom_bytes`` field of ``GET /signals`` and the gateway's
  optional degrade-on-low-headroom admission hint.

Like every obs module: stdlib-only at import (jax loads lazily inside
the sampling/analysis paths), opt-in (no ledger enabled means the
instrumented entry points run the exact pre-ledger path — one extra
module-global read per dispatch), and JSONL persistence follows the
flight-recorder convention with a byte-stable round trip;
``render_report`` is a pure function of a dump, so ``solver memory``
renders identical bytes on every replay of the same dump.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import compile_ledger as _cl
from ..utils.lockwatch import make_lock

__all__ = [
    "MemoryLedger",
    "enable",
    "disable",
    "current",
    "parse_proc_status",
    "read_proc_status",
    "read_meminfo_total",
    "live_array_bytes",
    "memory_to_jsonl",
    "memory_from_jsonl",
    "render_report",
]

# memory_analysis() attribute -> dump key. host_* fields exist on newer
# jaxlibs; missing attributes record None (absent, never zero).
MEM_ANALYSIS_FIELDS = (
    ("temp_bytes", "temp_size_in_bytes"),
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
    ("host_temp_bytes", "host_temp_size_in_bytes"),
)

_tls = threading.local()
_LEDGER: Optional["MemoryLedger"] = None
_LEDGER_LOCK = make_lock("memory_ledger.global")
# Cached jax.core.trace_state_clean (probed once): an inner-trace
# dispatch sees tracer arguments, which cannot be AOT-lowered.
_TRACE_STATE = None


# -- stdlib probes ------------------------------------------------------------


def _kb_value(line: str) -> Optional[int]:
    """Bytes from a ``Vm...:   1234 kB`` /proc status line; None when the
    line does not parse (proc(5) promises kB, but a parser that crashes
    on a weird kernel would take the watermark sampler down with it)."""
    parts = line.split()
    if len(parts) < 2:
        return None
    try:
        kb = int(parts[1])
    except ValueError:  # dlint: disable=DLP017 the None return IS the signal (absent-not-zero contract): every consumer renders it as n/a, and the summary's sample accounting stays intact
        return None
    return kb * 1024


def parse_proc_status(text: str) -> Dict[str, Optional[int]]:
    """``{"rss_bytes", "hwm_bytes"}`` from a ``/proc/<pid>/status`` blob.

    ``VmHWM`` is missing on some container/sandbox kernels (this repo's
    own CI box among them) — a missing field is ``None``, and every
    consumer treats None as "absent", never as zero.
    """
    out: Dict[str, Optional[int]] = {"rss_bytes": None, "hwm_bytes": None}
    for line in text.splitlines():
        if line.startswith("VmRSS:"):
            out["rss_bytes"] = _kb_value(line)
        elif line.startswith("VmHWM:"):
            out["hwm_bytes"] = _kb_value(line)
    return out


def read_proc_status(path: str = "/proc/self/status") -> Dict[str, Optional[int]]:
    """Parsed RSS/HWM of this process; all-None off Linux (no /proc)."""
    try:
        with open(path, "r", encoding="ascii", errors="replace") as fh:
            text = fh.read()
    except OSError:  # dlint: disable=DLP017 no /proc on this platform: the all-None record IS the signal (absent-not-zero), rendered as n/a everywhere — not a fault to count
        return {"rss_bytes": None, "hwm_bytes": None}
    return parse_proc_status(text)


def read_meminfo_total(path: str = "/proc/meminfo") -> Optional[int]:
    """MemTotal in bytes — the default headroom budget when none is
    configured; None off Linux (headroom then reports None, not a lie)."""
    try:
        with open(path, "r", encoding="ascii", errors="replace") as fh:
            for line in fh:
                if line.startswith("MemTotal:"):
                    return _kb_value(line)
    except OSError:  # dlint: disable=DLP017 no /proc on this platform: a None budget makes headroom report None (honest absence), never a fabricated number
        return None
    return None


def live_array_bytes() -> dict:
    """Live jax-array bytes: ``{"total_bytes", "count", "by_platform"}``.

    Lazy jax import (the obs layer stays jax-free at import time); the
    walk costs ~3 us per live array, which is why the ledger throttles
    its samples. A process with no jax loaded yet reports zero live
    arrays honestly — importing jax here just to count nothing would
    drag backend init into a watermark read.
    """
    import sys

    if "jax" not in sys.modules:
        return {"total_bytes": 0, "count": 0, "by_platform": {}}
    import jax  # lazy: already loaded, this is just the name

    total = 0
    count = 0
    by_platform: Dict[str, int] = {}
    for a in jax.live_arrays():
        nbytes = getattr(a, "nbytes", None)
        if nbytes is None:
            continue
        total += int(nbytes)
        count += 1
        try:
            platform = next(iter(a.devices())).platform
        except Exception:  # dlint: disable=DLP017 per-array platform lookup is cosmetic grouping; the byte total above already counted this array and a deleted-buffer race here must not kill the sampler
            platform = "unknown"
        by_platform[platform] = by_platform.get(platform, 0) + int(nbytes)
    return {"total_bytes": total, "count": count, "by_platform": by_platform}


def _trace_clean() -> bool:
    global _TRACE_STATE
    if _TRACE_STATE is None:
        try:
            from jax.core import trace_state_clean

            _TRACE_STATE = trace_state_clean
        except Exception:  # dlint: disable=DLP017 probed once: no jax (unit-tier stand-ins) means no traces to collide with — analysis then fails gracefully on the missing .lower instead
            _TRACE_STATE = lambda: True  # noqa: E731
    try:
        return bool(_TRACE_STATE())
    except Exception:  # dlint: disable=DLP017 a trace-state probe that raises mid-teardown must read as "not clean": skipping one analysis opportunity is recoverable, crashing the dispatch is not
        return False


class MemoryLedger:
    """Process-wide memory ledger (see module docstring).

    One re-entrant lock covers all mutation: the dispatch hook fires from
    every shard-worker thread, the timeline sampler reads watermarks, and
    the AOT analysis claims entries before releasing the lock.
    """

    def __init__(
        self,
        capacity: int = 4096,
        budget_bytes: Optional[int] = None,
        sample_min_interval_s: float = 0.25,
    ):
        if capacity < 2:
            raise ValueError("memory ledger capacity must be >= 2")
        self.capacity = capacity
        # Headroom budget; enable() fills in MemTotal when left None.
        self.budget_bytes = budget_bytes
        # A live-array walk is ~3 us/array — at thousands of live arrays
        # an unthrottled per-tick walk would blow the <=5% overhead gate,
        # so sample() returns the cached record inside this window.
        self.sample_min_interval_s = sample_min_interval_s
        self._lock = make_lock("memory_ledger.entries", kind="rlock")
        self._t0 = time.monotonic()
        # entry -> analysis record (claimed at first Python-side
        # dispatch): {"memory": {...}|None, "flops": float|None,
        # "bytes_accessed": float|None, "error": str|None}.
        self.analyses: Dict[str, dict] = {}
        self.analysis_errors = 0
        self.dispatches: Dict[str, int] = {}
        self.samples: "deque[dict]" = deque(maxlen=capacity)
        self.sample_count = 0  # total ever (ring may have evicted)
        self.sample_errors = 0
        self.peak: Dict[str, Optional[int]] = {
            "live_bytes": None,
            "rss_bytes": None,
            "hwm_bytes": None,
        }
        self._last: Optional[dict] = None
        self._last_t: Optional[float] = None
        # The leak-gate baseline (mark_warm); None until marked.
        self._warm_sample: Optional[dict] = None

    # -- dispatch hook (the compile-ledger registry ride-along) ------------

    def _on_dispatch(self, wrapper, args, kwargs) -> None:
        """Per-dispatch hook: count, and AOT-analyze the entry once.

        Steady state (entry already analyzed) is one lock hold — a
        counter bump and a membership check; the <=5% bench gate measures
        exactly this path. The analysis itself runs at most once per
        entry, only on a Python-side dispatch (never inside an outer
        trace — the enclosing entry's analysis covers the executable
        that actually allocates), and never re-entrantly (the AOT lower
        re-dispatches inner instrumented kernels at trace time).
        """
        entry = wrapper.entry_point
        with self._lock:
            self.dispatches[entry] = self.dispatches.get(entry, 0) + 1
            analyzed = entry in self.analyses
        if analyzed or getattr(_tls, "in_analysis", False):
            return
        if not _trace_clean():
            return
        _tls.in_analysis = True
        try:
            self._analyze(entry, wrapper, args, kwargs)
        finally:
            _tls.in_analysis = False

    def _analyze(self, entry: str, wrapper, args, kwargs) -> None:
        """AOT memory+cost analysis of one entry at these arguments."""
        with self._lock:
            if entry in self.analyses:
                return
            rec: dict = {
                "memory": None,
                "flops": None,
                "bytes_accessed": None,
                "error": None,
            }
            self.analyses[entry] = rec  # claim before releasing the lock
        lower = getattr(wrapper, "lower", None)
        if lower is None:
            with self._lock:
                rec["error"] = "entry point has no AOT lower()"
                self.analysis_errors += 1
            return
        # PR 14's suppression machinery: the AOT re-lowering below fires
        # real backend_compile events, and counting our own analysis as a
        # recompile would poison the zero-recompile warm gate.
        _cl._tls.suppress = True
        try:
            compiled = lower(*args, **kwargs).compile()
            mem = None
            try:
                ma = compiled.memory_analysis()
            except Exception:  # dlint: disable=DLP017 counted on the ledger (analysis_errors, surfaced per entry as rec.error): a backend without buffer-assignment stats is the documented graceful-None path, not a fault to crash serving over
                ma = None
                with self._lock:
                    rec["error"] = "memory_analysis() unsupported"
                    self.analysis_errors += 1
            if ma is not None:
                mem = {
                    key: (
                        int(v)
                        if (v := getattr(ma, attr, None)) is not None
                        else None
                    )
                    for key, attr in MEM_ANALYSIS_FIELDS
                }
            flops = bytes_accessed = None
            try:
                flops, bytes_accessed = _cl.parse_cost_analysis(
                    compiled.cost_analysis()
                )
            except Exception:  # dlint: disable=DLP017 counted on the ledger (analysis_errors): FLOPs attribution is advisory — a backend that reports memory but not cost must still keep its memory record
                with self._lock:
                    self.analysis_errors += 1
            with self._lock:
                rec["memory"] = mem
                rec["flops"] = flops
                rec["bytes_accessed"] = bytes_accessed
        except Exception as e:  # dlint: disable=DLP017 counted on the ledger (analysis_errors) and surfaced per entry as rec.error — an unlowerable call (donated buffers, exotic statics) must cost one missing analysis, never the dispatch
            with self._lock:
                rec["error"] = f"{type(e).__name__}: {e}"[:200]
                self.analysis_errors += 1
        finally:
            _cl._tls.suppress = False

    # -- watermark sampling -------------------------------------------------

    def sample(self, force: bool = False) -> dict:
        """One watermark record (throttled; ``force=True`` bypasses).

        Inside the throttle window the CACHED record returns (its
        ``fresh`` key False) so per-tick callers can attach-or-skip
        without a second live-array walk. Failures are counted, never
        raised: the serving path outranks its own observability.
        """
        now = time.monotonic()
        with self._lock:
            if (
                not force
                and self._last is not None
                and now - self._last_t < self.sample_min_interval_s
            ):
                cached = dict(self._last)
                cached["fresh"] = False
                return cached
        try:
            live = live_array_bytes()
        except Exception:  # dlint: disable=DLP017 counted on the ledger (sample_errors, surfaced in summary/watermarks): a failed live-array walk mid-teardown must degrade to an RSS-only sample, not kill the sampler thread
            with self._lock:
                self.sample_errors += 1
            live = {"total_bytes": None, "count": None, "by_platform": {}}
        status = read_proc_status()
        rec: dict = {
            "t": round(now - self._t0, 6),
            "live_bytes": live["total_bytes"],
            "live_count": live["count"],
            "rss_bytes": status["rss_bytes"],
            "hwm_bytes": status["hwm_bytes"],
        }
        if live["by_platform"]:
            rec["by_platform"] = dict(sorted(live["by_platform"].items()))
        with self._lock:
            self.sample_count += 1
            self.samples.append(rec)
            self._last = rec
            self._last_t = now
            for key in ("live_bytes", "rss_bytes", "hwm_bytes"):
                v = rec[key]
                if v is not None and (
                    self.peak[key] is None or v > self.peak[key]
                ):
                    self.peak[key] = v
        out = dict(rec)
        out["fresh"] = True
        return out

    def mark_warm(self) -> dict:
        """Pin the leak-gate baseline: the warm serving path's live-array
        bytes must stay flat from here on. Returns the baseline sample."""
        rec = self.sample(force=True)
        rec.pop("fresh", None)
        with self._lock:
            self._warm_sample = rec
        return dict(rec)

    def note_structural(self) -> None:
        """A problem-identity change legitimately re-allocates (new
        layouts, new warm-state shapes): re-pin the leak baseline IF one
        was already marked. Growth ACROSS a structural boundary is
        provisioning; growth BETWEEN them is a leak — which is exactly
        the warm-path contract (drift/spec/spec_near ticks allocate
        nothing persistent). Before ``mark_warm`` this is a no-op: the
        cold warmup phase owns its own boundary."""
        with self._lock:
            marked = self._warm_sample is not None
        if marked:
            self.mark_warm()

    def leak_report(self, tolerance_bytes: int = 0) -> Optional[dict]:
        """The leak gate's verdict vs the ``mark_warm`` baseline; None
        until the baseline is marked or while live bytes are unreadable.
        ``flat`` is the contract: no net live-array growth across the
        warm serving path."""
        with self._lock:
            base = self._warm_sample
            last = self._last
        if base is None or last is None:
            return None
        b, l = base.get("live_bytes"), last.get("live_bytes")
        if b is None or l is None:
            return None
        growth = int(l) - int(b)
        return {
            "baseline_bytes": int(b),
            "last_bytes": int(l),
            "growth_bytes": growth,
            "tolerance_bytes": int(tolerance_bytes),
            "flat": growth <= tolerance_bytes,
        }

    def headroom_bytes(self, max_age_s: float = 1.0) -> Optional[float]:
        """budget - RSS; None without a budget or a readable RSS.

        Uses the cached sample when fresh enough, else ONE cheap /proc
        read (~0.1 ms, no live-array walk) — cheap enough for the
        gateway's per-ingest degrade check.
        """
        if self.budget_bytes is None:
            return None
        rss = None
        now = time.monotonic()
        with self._lock:
            if (
                self._last is not None
                and self._last_t is not None
                and now - self._last_t <= max_age_s
            ):
                rss = self._last.get("rss_bytes")
        if rss is None:
            rss = read_proc_status()["rss_bytes"]
        if rss is None:
            return None
        return float(self.budget_bytes - rss)

    # -- the read side -------------------------------------------------------

    def timeline_series(self) -> Dict[str, float]:
        """The ledger's ``mem.*`` timeline emission — ONE definition
        shared by ``Scheduler.timeline_sample`` and
        ``Gateway.timeline_sample`` (the compile ledger's convention, so
        the two serving shapes' series cannot drift). These are GAUGES:
        an unavailable value is ABSENT, never zero — a zero RSS would be
        a lie, unlike the counter-baseline case PR 13 zero-fills.
        Sampling is throttled, so a sampler outpacing the throttle
        re-emits the cached watermark (windows stay populated)."""
        rec = self.sample()
        out: Dict[str, float] = {}
        if rec.get("live_bytes") is not None:
            out["mem.live_bytes"] = float(rec["live_bytes"])
            out["mem.live_count"] = float(rec["live_count"])
        for platform, nbytes in (rec.get("by_platform") or {}).items():
            out[f"mem.live_bytes.{platform}"] = float(nbytes)
        if rec.get("rss_bytes") is not None:
            out["mem.rss_bytes"] = float(rec["rss_bytes"])
        if rec.get("hwm_bytes") is not None:
            out["mem.hwm_bytes"] = float(rec["hwm_bytes"])
        headroom = self.headroom_bytes()
        if headroom is not None:
            out["mem.headroom_bytes"] = headroom
        return out

    def counters(self) -> dict:
        with self._lock:
            return {
                "mem_entries_analyzed": len(self.analyses),
                "mem_analysis_errors": self.analysis_errors,
                "mem_samples": self.sample_count,
                "mem_sample_errors": self.sample_errors,
                "mem_dispatches": sum(self.dispatches.values()),
            }

    def summary(self) -> dict:
        """Per-entry table + watermarks + leak verdict, JSON-able."""
        with self._lock:
            entries = {}
            names = sorted(set(self.analyses) | set(self.dispatches))
            for name in names:
                rec = self.analyses.get(name)
                e: dict = {"dispatches": self.dispatches.get(name, 0)}
                if rec is not None:
                    e["memory"] = (
                        dict(rec["memory"]) if rec["memory"] else None
                    )
                    e["flops"] = rec["flops"]
                    e["bytes_accessed"] = rec["bytes_accessed"]
                    if rec["error"]:
                        e["error"] = rec["error"]
                entries[name] = e
            watermarks = {
                "peak_live_bytes": self.peak["live_bytes"],
                "peak_rss_bytes": self.peak["rss_bytes"],
                "peak_hwm_bytes": self.peak["hwm_bytes"],
                "samples": self.sample_count,
                "sample_errors": self.sample_errors,
            }
        return {
            "entries": entries,
            "watermarks": watermarks,
            "leak": self.leak_report(),
            "budget_bytes": self.budget_bytes,
            "counters": self.counters(),
        }

    def dump(self) -> dict:
        """One JSON-able blob: header + watermark sample list."""
        with self._lock:
            samples = [dict(s) for s in self.samples]
        return {
            "header": {
                "memory_ledger": 1,
                "capacity": self.capacity,
                "budget_bytes": self.budget_bytes,
                "summary": self.summary(),
            },
            "samples": samples,
        }

    def to_jsonl(self) -> str:
        return memory_to_jsonl(self.dump())

    def dump_jsonl(self, path) -> None:
        from pathlib import Path

        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(self.to_jsonl(), encoding="utf-8")


# -- process-wide enable/disable ---------------------------------------------


def _on_dispatch(wrapper, args, kwargs) -> None:
    led = _LEDGER
    if led is None:
        return
    led._on_dispatch(wrapper, args, kwargs)


def enable(ledger: Optional[MemoryLedger] = None, **kwargs) -> MemoryLedger:
    """Install ``ledger`` (or a fresh one from ``kwargs``) as THE process
    memory ledger and register the dispatch hook on the compile ledger's
    entry-point registry. The hook stays registered across
    disable/enable cycles and is dormant (one module-global read) while
    no ledger is current. A budget left None resolves to MemTotal."""
    global _LEDGER
    led = ledger if ledger is not None else MemoryLedger(**kwargs)
    if led.budget_bytes is None:
        # /proc read stays OUTSIDE the lock: enable() is rare, but the
        # ledger lock is on the dispatch path and must never wait on I/O.
        led.budget_bytes = read_meminfo_total()
    with _LEDGER_LOCK:
        _cl.set_dispatch_hook(_on_dispatch)
        _LEDGER = led
        return led


def disable() -> Optional[MemoryLedger]:
    """Detach the process memory ledger (hook goes dormant); returns it.
    Every test/CLI owner must call this in a finally — a leaked global
    ledger would AOT-analyze (and watermark) other tests' dispatches,
    exactly like a leaked compile ledger would mint counters."""
    global _LEDGER
    with _LEDGER_LOCK:
        led, _LEDGER = _LEDGER, None
        return led


def current() -> Optional[MemoryLedger]:
    return _LEDGER


# -- persistence + report (the flight-recorder JSONL convention) -------------


def memory_to_jsonl(dump: dict) -> str:
    """Header line + one watermark sample per line; pure function of the
    dump, so ``to_jsonl(from_jsonl(s)) == s`` byte-for-byte."""
    lines = [json.dumps(dump["header"], sort_keys=True)]
    for rec in dump["samples"]:
        lines.append(json.dumps(rec, sort_keys=True))
    return "\n".join(lines) + "\n"


def memory_from_jsonl(text: str) -> dict:
    """Parse a dumped memory ledger back into the ``dump()`` shape."""
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError("empty memory-ledger dump")
    header = json.loads(lines[0])
    if not isinstance(header, dict) or "memory_ledger" not in header:
        raise ValueError("memory-ledger dump missing its header line")
    if header["memory_ledger"] != 1:
        raise ValueError(
            f"unknown memory-ledger dump version {header['memory_ledger']!r}"
        )
    return {
        "header": header,
        "samples": [json.loads(ln) for ln in lines[1:]],
    }


def _fmt_bytes(v: Optional[int]) -> str:
    """Deterministic human-scale bytes: exact value, MB alongside."""
    if v is None:
        return "n/a"
    return f"{v} ({v / 1e6:.2f} MB)"


def render_report(dump: dict) -> str:
    """Deterministic text over a ``dump()``/``memory_from_jsonl`` blob:
    watermarks, leak verdict, per-entry static model (bytes + FLOPs per
    dispatch). No clocks, no live reads — byte-identical on every replay
    of the same dump (the ``solver memory --check`` contract)."""
    summary = dump["header"].get("summary", {})
    entries = summary.get("entries", {})
    marks = summary.get("watermarks", {})
    leak = summary.get("leak")
    out: List[str] = []
    out.append("memory ledger")
    budget = summary.get("budget_bytes")
    out.append(f"  headroom budget: {_fmt_bytes(budget)}")
    out.append(
        "  watermarks: peak_live={} peak_rss={} peak_hwm={} "
        "(samples={}, errors={})".format(
            _fmt_bytes(marks.get("peak_live_bytes")),
            _fmt_bytes(marks.get("peak_rss_bytes")),
            _fmt_bytes(marks.get("peak_hwm_bytes")),
            marks.get("samples", 0),
            marks.get("sample_errors", 0),
        )
    )
    if leak is None:
        out.append("  leak gate: not marked (no warm baseline)")
    else:
        out.append(
            "  leak gate: {} — baseline={} last={} growth={:+d} B".format(
                "FLAT" if leak["flat"] else "GREW",
                _fmt_bytes(leak["baseline_bytes"]),
                _fmt_bytes(leak["last_bytes"]),
                leak["growth_bytes"],
            )
        )
    out.append("")
    out.append(
        f"  {'entry point':<34s} {'disp':>7s} {'temp MB':>9s} "
        f"{'args MB':>9s} {'out MB':>8s} {'code MB':>8s} {'flops':>12s}"
    )
    for name in sorted(entries):
        e = entries[name]
        mem = e.get("memory")

        def _mb(key: str) -> str:
            if not mem or mem.get(key) is None:
                return "n/a"
            return f"{mem[key] / 1e6:.2f}"

        flops = e.get("flops")
        out.append(
            f"  {name:<34s} {e.get('dispatches', 0):>7d} "
            f"{_mb('temp_bytes'):>9s} {_mb('argument_bytes'):>9s} "
            f"{_mb('output_bytes'):>8s} {_mb('generated_code_bytes'):>8s} "
            f"{(f'{flops:.3g}' if flops is not None else 'n/a'):>12s}"
        )
        if e.get("error"):
            out.append(f"  {'':<34s} ! {e['error']}")
    unanalyzed = [
        n for n, e in sorted(entries.items())
        if "memory" in e and e["memory"] is None and not e.get("error")
    ]
    if unanalyzed:
        out.append("")
        out.append(
            "  no static model (backend reported none): "
            + ", ".join(unanalyzed)
        )
    return "\n".join(out) + "\n"

"""In-process metrics timelines: bounded time series over the live sinks.

Counters answer "how many, ever"; window histograms answer "how slow,
recently"; neither answers the SLO question "is the burn rate over the
last five minutes 14x the budget?" — that needs HISTORY. This module is
the history: a fixed-cadence sampler (one daemon thread) snapshots the
serving tier's own sinks (``Scheduler.timeline_sample`` /
``Gateway.timeline_sample`` — counters, latency quantiles, per-worker
queue depths, conv_* digests) into bounded per-series rings of
``(t, value)`` points, and rates / ratios / window fractions derive from
the deltas between points — no external scrape infrastructure, no second
metrics system, exactly the flight-recorder philosophy applied to time
series.

Semantics worth knowing:

- **Counters vs gauges are a read-side decision.** The timeline stores
  raw samples; ``delta``/``rate``/``ratio`` treat a series as cumulative
  (first-to-last difference over the window), ``frac_above``/``latest``
  treat it as a gauge. The SLO layer (``obs.slo``) picks per spec.
- **Windows are measured, not assumed.** ``rate()`` divides by the
  actual elapsed time between the two samples it used, so a late sampler
  tick degrades resolution, never correctness.
- **Dump/load is JSONL** like the flight recorder: a header line, then
  one ``{"t", "s", "v"}`` object per point, oldest first per series —
  ``solver slo --timeline`` replays a dumped run's alert evaluation
  offline, byte-deterministically.

Everything here is stdlib-only and opt-in: a gateway or scheduler with
no sampler attached runs the exact pre-timeline code path (pinned by the
no-knobs counter test in tests/test_slo.py).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.lockwatch import make_lock

__all__ = [
    "Timeline",
    "TimelineSampler",
    "flatten_metrics_snapshot",
    "synthesize_overload_timeline",
]


class Timeline:
    """Bounded per-series rings of ``(t, value)`` samples.

    ``capacity`` bounds EACH series (oldest falls off); timestamps are
    caller-supplied seconds on one monotonic clock (the sampler uses
    ``time.monotonic``). All reads/writes hold one lock — points land
    from the sampler thread and the open-loop executor while the SLO
    engine reads windows.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 2:
            # One point cannot form a delta; a timeline that can never
            # answer rate() is a misconfiguration, not a small buffer.
            raise ValueError("timeline capacity must be >= 2")
        self.capacity = capacity
        self._series: Dict[str, deque] = {}  # guarded-by: self._lock
        self._lock = make_lock("timeline.series")

    # -- the write side ----------------------------------------------------

    def record(self, name: str, t: float, value: float) -> None:
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                ring = self._series[name] = deque(maxlen=self.capacity)
            ring.append((float(t), float(value)))

    def record_many(self, t: float, values: Dict[str, float]) -> None:
        """One sampler tick: every series gets a point at the same t."""
        with self._lock:
            for name, value in values.items():
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = deque(maxlen=self.capacity)
                ring.append((float(t), float(value)))

    # -- the read side -----------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str) -> List[Tuple[float, float]]:
        with self._lock:
            ring = self._series.get(name)
            return list(ring) if ring is not None else []

    def latest(self, name: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            ring = self._series.get(name)
            return ring[-1] if ring else None

    def value_at(self, name: str, t: float) -> Optional[float]:
        """The last recorded value at or before ``t`` (None when no
        sample that old exists). On a live timeline ``value_at(now)`` IS
        ``latest``; on a dumped capture it is the point-in-time read that
        keeps an offline replay (SLO engine, controller) honest — a
        replayed decision at t must not see a sample from t+30."""
        with self._lock:
            ring = self._series.get(name)
            if not ring:
                return None
            out = None
            for pt, pv in ring:
                if pt > t:
                    break
                out = pv
            return out

    def bounds(self) -> Optional[Tuple[float, float]]:
        """(oldest, newest) timestamp across every series; None if empty.

        The offline replay clock (``SLOEngine.replay``) walks this range.
        """
        lo = hi = None
        with self._lock:
            for ring in self._series.values():
                if not ring:
                    continue
                if lo is None or ring[0][0] < lo:
                    lo = ring[0][0]
                if hi is None or ring[-1][0] > hi:
                    hi = ring[-1][0]
        return None if lo is None else (lo, hi)

    def window(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> List[Tuple[float, float]]:
        """Samples of ``name`` with t in ``[now - window_s, now]``."""
        pts = self.series(name)
        if not pts:
            return []
        if now is None:
            now = pts[-1][0]
        t0 = now - window_s
        return [p for p in pts if t0 <= p[0] <= now]

    def _window_with_baseline(
        self, name: str, window_s: float, now: Optional[float]
    ):
        """(baseline_point, in-window points) for counter reads.

        The baseline is the last sample AT OR BEFORE the window start
        (Prometheus increase() semantics): a counter jump that landed
        between a stale pre-window sample and the first in-window one is
        attributed to the window — the only honest choice when the
        sampler itself was delayed by the very overload it is measuring
        (a blocked sampler tick behind a cold solve must not blind the
        alert to the burst it missed the edge of)."""
        pts = self.series(name)
        if not pts:
            return None, []
        if now is None:
            now = pts[-1][0]
        t0 = now - window_s
        baseline = None
        inside = []
        for p in pts:
            if p[0] < t0:
                baseline = p
            elif p[0] <= now:
                inside.append(p)
        return baseline, inside

    def delta(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Counter delta over the window: newest in-window value minus
        the baseline (last sample at or before the window start; falls
        back to the oldest in-window sample). None without a baseline
        pair — no baseline means no honest delta, and the SLO layer
        treats that as "insufficient data", never as zero."""
        baseline, inside = self._window_with_baseline(name, window_s, now)
        if not inside:
            return None
        if baseline is not None:
            return inside[-1][1] - baseline[1]
        if len(inside) < 2:
            return None
        return inside[-1][1] - inside[0][1]

    def rate(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Counter rate per second over the window, using the MEASURED
        elapsed time between the samples the delta came from (a stale
        baseline spreads the jump over the real gap, so a late sampler
        degrades resolution, never inflates the rate)."""
        baseline, inside = self._window_with_baseline(name, window_s, now)
        if not inside:
            return None
        first = baseline if baseline is not None else (
            inside[0] if len(inside) >= 2 else None
        )
        if first is None:
            return None
        elapsed = inside[-1][0] - first[0]
        if elapsed <= 0:
            return None
        return (inside[-1][1] - first[1]) / elapsed

    def ratio(
        self,
        bad: str,
        total: str,
        window_s: float,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """bad-delta / total-delta over one shared window (the SLO error
        ratio). None when either delta is unknown (insufficient samples
        — the alert state machine holds). A window with samples but NO
        events is ratio 0.0: the budget is request-weighted, so an idle
        recovery window burns nothing — which is exactly what lets a
        flood's alert clear once the burst slides out of the window.
        (A fully-shedding service is NOT idle here: sheds are events,
        so size ``total`` as offered = accepted + shed.)"""
        db = self.delta(bad, window_s, now)
        dt = self.delta(total, window_s, now)
        if db is None or dt is None:
            return None
        if dt <= 0:
            return 0.0 if db <= 0 else 1.0
        return max(0.0, min(1.0, db / dt))

    def frac_above(
        self,
        name: str,
        threshold: float,
        window_s: float,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Gauge view: the fraction of in-window samples exceeding
        ``threshold`` (the latency-tier SLO's error ratio)."""
        pts = self.window(name, window_s, now)
        if not pts:
            return None
        return sum(1 for _, v in pts if v > threshold) / len(pts)

    def trend_per_s(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Least-squares slope (units/second) over the window — the
        queue-depth trend the ``/signals`` autoscaling payload carries."""
        pts = self.window(name, window_s, now)
        if len(pts) < 2:
            return None
        n = len(pts)
        mt = sum(t for t, _ in pts) / n
        mv = sum(v for _, v in pts) / n
        den = sum((t - mt) ** 2 for t, _ in pts)
        if den <= 0:
            return None
        num = sum((t - mt) * (v - mv) for t, v in pts)
        return num / den

    # -- persistence (the flight-recorder JSONL convention) ----------------

    def to_jsonl(self) -> str:
        """Header line + one point per line, series in sorted order and
        points oldest-first — byte-stable for a given timeline state, so
        the committed fixture pins regeneration exactly."""
        with self._lock:
            header = {
                "timeline": 1,
                "capacity": self.capacity,
                "series": len(self._series),
            }
            lines = [json.dumps(header, sort_keys=True)]
            # Full float precision on purpose: JSON floats round-trip
            # bit-exactly, so a loaded timeline replays IDENTICALLY to
            # the one that was dumped (rounding t would shift window
            # membership at boundaries and break replay determinism).
            for name in sorted(self._series):
                for t, v in self._series[name]:
                    lines.append(
                        json.dumps({"t": t, "s": name, "v": v}, sort_keys=True)
                    )
        return "\n".join(lines) + "\n"

    def dump(self, path) -> Path:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(self.to_jsonl(), encoding="utf-8")
        return path

    @classmethod
    def from_jsonl(cls, text: str) -> "Timeline":
        lines = [ln for ln in text.splitlines() if ln.strip()]
        if not lines:
            raise ValueError("empty timeline dump")
        header = json.loads(lines[0])
        if not isinstance(header, dict) or "timeline" not in header:
            raise ValueError("timeline dump missing its header line")
        if header["timeline"] != 1:
            raise ValueError(
                f"unknown timeline dump version {header['timeline']!r}"
            )
        tl = cls(capacity=int(header.get("capacity", 4096)))
        for ln in lines[1:]:
            rec = json.loads(ln)
            tl.record(rec["s"], rec["t"], rec["v"])
        return tl

    @classmethod
    def load(cls, path) -> "Timeline":
        return cls.from_jsonl(Path(path).read_text(encoding="utf-8"))


def flatten_metrics_snapshot(snap: dict, prefix: str = "") -> Dict[str, float]:
    """A ``SchedulerMetrics.snapshot()``-shaped dict as flat timeline
    series: counters as ``c.<name>`` (cumulative), each latency hist as
    ``lat.<name>.{p50_ms,p99_ms,count}`` (quantiles are window gauges,
    count is cumulative). Shared by the scheduler- and gateway-level
    ``timeline_sample`` hooks so series names cannot drift between the
    two serving shapes."""
    out: Dict[str, float] = {}
    for name, value in snap.get("counters", {}).items():
        if isinstance(value, (int, float)):
            out[f"{prefix}c.{name}"] = float(value)
    for name, hist in snap.get("latency", {}).items():
        for key in ("p50_ms", "p99_ms", "count"):
            v = hist.get(key)
            if isinstance(v, (int, float)):
                out[f"{prefix}lat.{name}.{key}"] = float(v)
    return out


def synthesize_overload_timeline(
    duration_s: float = 60.0,
    period_s: float = 0.1,
    burst_start_s: float = 20.0,
    burst_end_s: float = 30.0,
    offered_eps: float = 300.0,
    shed_frac: float = 0.8,
) -> Timeline:
    """A deterministic timeline shaped like the measured PR 12 overload
    run: steady offered load, a correlated shed burst in the middle
    (queue pinned at the admission depth, p99 blown, escalations
    climbing), then recovery.

    This is the committed-fixture generator behind
    ``tests/traces/slo_timeline_overload.jsonl`` — a pure function of
    its arguments (no clocks, no RNG), pinned byte-exact by
    tests/test_slo.py the same way the traffic captures are, so
    ``make smoke-slo``'s offline alert replay is reproducible on any
    box. Series names follow ``Gateway.timeline_sample``'s conventions
    so a spec written against this fixture evaluates unchanged against
    a live gateway's timeline.
    """
    tl = Timeline(capacity=max(2, int(duration_s / period_s) + 1))
    steps = int(duration_s / period_s)
    offered = shed = escal = 0.0
    for i in range(steps + 1):
        t = i * period_s
        in_burst = burst_start_s <= t < burst_end_s
        if i > 0:
            offered += offered_eps * period_s
            shed += (offered_eps * shed_frac * period_s) if in_burst else 0.0
            escal += 2.0 * period_s if in_burst else 0.0
        # p99 spikes during the burst and decays linearly over 5 s after.
        if in_burst:
            p99 = 900.0
        elif burst_end_s <= t < burst_end_s + 5.0:
            p99 = 900.0 - (900.0 - 40.0) * (t - burst_end_s) / 5.0
        else:
            p99 = 40.0
        depth = 8.0 if in_burst else 0.0
        tl.record_many(
            t,
            {
                "c.events_offered": offered,
                "c.events_shed": shed,
                "c.gateway_events": offered - shed,
                "shards.solver_escalations": escal,
                "lat.gateway_event_to_placement.p99_ms": round(p99, 3),
                "queue_depth.w0": depth,
                "queue_depth.w1": depth,
            },
        )
    return tl


class TimelineSampler:
    """Fixed-cadence daemon thread: sample_fn() -> timeline, every tick.

    ``sample_fn`` returns one flat ``{series: value}`` dict (the
    ``timeline_sample`` hooks); ``on_sample(timeline, now)`` runs after
    each recorded tick — the SLO engine's evaluation rides here, so
    alerting needs no thread of its own. Every tick is accounted
    (``timeline_samples`` / ``timeline_sample_error`` on the metrics
    sink) and a failing sample NEVER kills the thread: observability
    outage must be a counted signal, not a silent one.

    ``stop()`` is idempotent and joins the thread — ``Gateway.close()``
    calls it for every attached sampler BEFORE stopping the workers, so
    a sampler mid-probe can never race the teardown (the PR 8 bench
    gotcha, fixed at the source).
    """

    def __init__(
        self,
        timeline: Timeline,
        sample_fn: Callable[[], Dict[str, float]],
        period_s: float = 0.1,
        metrics=None,
        on_sample: Optional[Callable[[Timeline, float], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if period_s <= 0:
            raise ValueError("sampler period must be > 0")
        self.timeline = timeline
        self.period_s = period_s
        self._sample_fn = sample_fn
        self._metrics = metrics
        self._on_sample = on_sample
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0
        self.errors = 0

    def sample_once(self, now: Optional[float] = None) -> bool:
        """One sampler tick (also the deterministic-test entry point).
        Returns True when a sample landed, False when it failed."""
        if now is None:
            now = self._clock()
        try:
            values = self._sample_fn()
            self.timeline.record_many(now, values)
        except Exception:
            # Counted, never fatal: the serving path outranks its own
            # observability, and a dead sampler thread would silence the
            # very alerts this layer exists to raise.
            self.errors += 1
            if self._metrics is not None:
                self._metrics.inc("timeline_sample_error")
            return False
        self.samples += 1
        if self._metrics is not None:
            self._metrics.inc("timeline_samples")
        if self._on_sample is not None:
            try:
                self._on_sample(self.timeline, now)
            except Exception:
                self.errors += 1
                if self._metrics is not None:
                    self._metrics.inc("timeline_sample_error")
                return False
        return True

    def start(self) -> "TimelineSampler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="timeline-sampler"
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            self.sample_once()

    def stop(self, join: bool = True, timeout: float = 2.0) -> None:
        """Signal and (by default) join; safe to call any number of
        times, from ``Gateway.close()`` or a CLI finally block or both."""
        self._stop.set()
        thread = self._thread
        if join and thread is not None and thread.is_alive():
            thread.join(timeout=timeout)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

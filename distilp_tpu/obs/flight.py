"""Flight recorder: a black box for the serving path's last N ticks.

Counters say HOW OFTEN things happen; the chaos soak says WHETHER the
contract held; neither answers the post-mortem question "what exactly were
the last thirty ticks doing when the breaker opened?". The flight recorder
does: every handled event appends one compact record — fleet seq, event
kind, served mode, health, LP engine, the tick's span ids (when tracing is
on) and the COUNTER DELTAS that tick caused — to a per-shard bounded ring.

Two ways out of the ring:

- **live**: ``GET /debug/flight/<fleet>`` on the gateway HTTP API returns
  the shard's current ring (``FlightRecorder.snapshot``), no dump needed;
- **post-mortem**: ``trigger()`` — fired by the scheduler on breaker-open,
  and by the serve CLI on a chaos-contract violation — writes the ring to
  a JSONL file in ``dump_dir`` (header line naming the trigger reason and
  the triggering record, then one line per ring record, oldest first).
  With no ``dump_dir`` the trigger still lands in the ring as a marker
  record, so the live view shows it.

Recording is append-one-dict-per-tick under a lock: workers record
concurrently, HTTP reads land mid-soak, and dumps must see a consistent
ring. Like tracing, the whole thing is opt-in — a scheduler without a
recorder attached runs the exact pre-obs code path.
"""

from __future__ import annotations

import json
import re
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional

from ..utils.lockwatch import make_lock

__all__ = ["FlightRecorder"]

_SAFE_KEY = re.compile(r"[^A-Za-z0-9._-]+")


class FlightRecorder:
    """Bounded per-shard tick-record rings with post-mortem dumps."""

    def __init__(self, capacity: int = 128, dump_dir=None):
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        self.capacity = capacity
        self.dump_dir = Path(dump_dir) if dump_dir is not None else None
        self._rings: Dict[str, deque] = {}  # guarded-by: self._lock
        self._lock = make_lock("flight.ring")
        self._dump_seq = 0  # guarded-by: self._lock
        # Post-mortems written, oldest first.
        self.dumps: List[Path] = []  # guarded-by: self._lock

    def record(self, key: str, rec: dict) -> None:
        """Append one tick record to ``key``'s ring (oldest falls off)."""
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = deque(maxlen=self.capacity)
            ring.append(rec)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._rings)

    def snapshot(self, key: str) -> List[dict]:
        """The ring's current contents, oldest first (copy; JSON-able)."""
        with self._lock:
            ring = self._rings.get(key)
            return list(ring) if ring is not None else []

    def trigger(
        self, key: str, reason: str, record: Optional[dict] = None
    ) -> Optional[Path]:
        """A post-mortem moment: dump ``key``'s ring (when a ``dump_dir``
        is configured) and mark the trigger in the ring either way.

        ``record`` is the tick record that tripped the trigger (it carries
        the span ids a post-mortem starts from). Returns the dump path, or
        None when no dump directory is configured.
        """
        with self._lock:
            ring = self._rings.get(key)
            records = list(ring) if ring is not None else []
            marker = {
                "flight_trigger": reason,
                "at": time.time(),
                "record": record,
            }
            if ring is None:
                ring = self._rings[key] = deque(maxlen=self.capacity)
            ring.append(marker)
            if self.dump_dir is None:
                return None
            self._dump_seq += 1
            seq = self._dump_seq
        self.dump_dir.mkdir(parents=True, exist_ok=True)
        safe = _SAFE_KEY.sub("_", key) or "shard"
        path = self.dump_dir / f"postmortem-{safe}-{seq:03d}.jsonl"
        header = {
            "flight": key,
            "reason": reason,
            "dumped_at": time.time(),
            "records": len(records),
            "trigger": record,
        }
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(header, default=str) + "\n")
            for rec in records:
                fh.write(json.dumps(rec, default=str) + "\n")
        with self._lock:
            self.dumps.append(path)
        return path
